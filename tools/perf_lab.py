#!/usr/bin/env python
"""Perf lab: pinned microbenchmark suite with regression gating.

Every ROADMAP perf-backlog item must land "with before/after
breakdowns" — this is the harness that produces them.  The suite pins
the hot primitives the node's latency decomposes into (the same
decomposition the flight recorder attributes per height):

  * ``batch_verify_cpu_pad*``  — CPU ed25519 batch verification at the
    kernel pad-bucket batch shapes (crypto/batch.py PAD_BUCKETS);
  * ``merkle_root_1024``       — the block-hash primitive;
  * ``vote_sign_bytes``        — canonical vote encoding (every sign
    and every verify path builds these bytes);
  * ``signature_cache_hit``    — the verification fast path;
  * ``metrics_observe``        — histogram+labeled-counter cost per
    observation (the metrics-v2 overhead budget);
  * ``tracing_disabled_span``  — the flight-recorder disabled path
    (tier-1 separately guards < 1µs);
  * ``tracing_overhead``       — the ENABLED path: a peer-attributed
    arrival instant with the clock-anchor refresh firing every event
    (the fleet-observatory per-receive cost ceiling);
  * ``p2p_loopback_send``      — MConnection framing/scheduling cost
    per message over an in-memory pipe (no sockets, no crypto);
  * ``multiproof_build`` / ``multiproof_verify`` /
    ``proofs_verify_256`` — lightserve compact multiproofs: build and
    verify 256 of 1024 leaves vs the same leaves as 256 individual
    Proofs (the committed numbers demonstrate the >= 4x size / >= 3x
    verify win; tests/test_lightserve.py pins the claim against this
    baseline);
  * ``rpc_cache_hit``          — lightserve response-cache lookup
    (the path thousands of light clients ride per request);
  * ``statetree_commit`` / ``statetree_proof_build`` /
    ``statetree_proof_verify`` — the committed state tree behind the
    kvstore's app_hash (docs/state_tree.md): a 1k-key write+commit,
    and building/verifying a 256-key proof envelope (224 existence +
    32 non-inclusion arms under one multiproof);
  * ``bftlint_selfcheck``      — the full-package bftlint run that
    gates tier-1 (tests/test_bftlint.py), including the ISSUE 20
    whole-package call graph + effect summaries (built once per run,
    shared by every checker); a pathological checker (an accidental
    O(n^2) walk) or a diverging fixed point must not blow the tier-1
    budget, so this is pinned < ~8s via an explicit tolerance.

Modes:
  run                 run the suite, print a JSON report
  check               run + diff against the committed baseline;
                      exit 1 when any benchmark regresses beyond its
                      tolerance (per-benchmark ``tolerance`` in the
                      baseline, else ``default_tolerance``)
  rebaseline          run + rewrite the baseline file

``--fast`` runs the tier-1 subset (seconds, not minutes); the full
suite is what perf PRs attach before/after reports from.  The gate
compares per-op ``min_ms`` (the most noise-robust statistic on a
shared CI box; p50/mean ride along in reports for humans) with
generous multiplier tolerances — it catches order-of-magnitude
regressions (an accidental O(n^2), a dropped cache), not 10% drift.

Usage for a perf PR: ``python tools/perf_lab.py run > before.json``,
apply the change, run again, put both numbers in the PR description,
and ``rebaseline`` if the improvement should become the new floor.
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

DEFAULT_BASELINE = os.path.join(_REPO_ROOT, "perf_baseline.json")
SCHEMA = 1
DEFAULT_TOLERANCE = 6.0

# comparison-arm statistics carried into the committed baseline so
# claim tests (e.g. tests/test_verify_pipeline.py's pipelined >=
# 1.25x monolithic / stall >= 5x gates) can check them statically
CLAIM_KEYS = ("monolithic_min_ms", "sync_stall_ms",
              "speedup_vs_monolithic", "stall_drop",
              "host_prep_ms", "kernel_execute_ms")


# ---------------------------------------------------------------------
# measurement core

def measure(fn, reps: int, inner: int = 1,
            setup=None, warmup: int = 1) -> dict:
    """Time ``fn`` (called with the value returned by ``setup``, if
    any) ``reps`` times, ``inner`` calls per rep; returns per-op
    millisecond stats.  ``warmup`` leading reps are discarded — on a
    throttled shared box the first iterations of a native-heavy loop
    run several times slower than steady state (cold caches, branch
    predictors, CPU frequency ramp)."""
    arg = setup() if setup is not None else None
    call = (lambda: fn(arg)) if setup is not None else fn
    durations = []
    for rep in range(reps + warmup):
        t0 = time.perf_counter()
        for _ in range(inner):
            call()
        dt = (time.perf_counter() - t0) / inner
        if rep >= warmup:
            durations.append(dt)
    durations.sort()
    return {
        "p50_ms": round(statistics.median(durations) * 1e3, 6),
        "min_ms": round(durations[0] * 1e3, 6),
        "mean_ms": round(statistics.fmean(durations) * 1e3, 6),
        "reps": reps,
        "inner": inner,
    }


# ---------------------------------------------------------------------
# benchmarks.  Each entry: name -> (fn(fast: bool) -> stats dict,
# in_fast_subset).  tests/test_perf_lab.py monkeypatches this table to
# prove the regression gate trips.

def _make_sigs(n: int):
    from cometbft_tpu.crypto import ed25519
    sk = ed25519.gen_priv_key()
    pk = sk.pub_key()
    msgs = [b"perf-lab-msg-%d" % i for i in range(n)]
    return [(pk, m, sk.sign(m)) for pk, m in
            ((pk, m) for m in msgs)]


def bench_batch_verify_cpu(batch: int, reps: int):
    from cometbft_tpu.crypto import ed25519

    def setup():
        return _make_sigs(batch)

    def run(items):
        bv = ed25519.CpuBatchVerifier()
        for pk, m, s in items:
            bv.add(pk, m, s)
        ok, _ = bv.verify()
        if not ok:
            raise RuntimeError("benchmark signatures failed to verify")

    stats = measure(run, reps=reps, setup=setup, warmup=4)
    stats["batch"] = batch
    return stats


def bench_batch_verify_pad64(fast: bool):
    return bench_batch_verify_cpu(batch=64, reps=4 if fast else 6)


def bench_batch_verify_pad1024(fast: bool):
    # 256 signatures dispatch at the 1024 pad bucket
    return bench_batch_verify_cpu(batch=256, reps=3)


def bench_merkle_root(fast: bool):
    from cometbft_tpu.crypto.merkle import hash_from_byte_slices
    leaves = [(b"%08d" % i) * 32 for i in range(1024)]
    return measure(lambda: hash_from_byte_slices(leaves),
                   reps=10 if fast else 30, inner=3)


def bench_vote_sign_bytes(fast: bool):
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types.block_id import BlockID
    from cometbft_tpu.types.part_set import PartSetHeader
    from cometbft_tpu.types.timestamp import Timestamp
    bid = BlockID(hash=b"\xab" * 32,
                  part_set_header=PartSetHeader(total=1,
                                                hash=b"\xcd" * 32))
    ts = Timestamp(1700000000, 123456789)
    return measure(
        lambda: canonical.vote_sign_bytes(
            "perf-lab-chain", canonical.PRECOMMIT_TYPE, 12345, 2,
            bid, ts),
        reps=5 if fast else 15, inner=500)


def bench_signature_cache_hit(fast: bool):
    from cometbft_tpu.types.signature_cache import (
        SignatureCache, SignatureCacheValue,
    )
    cache = SignatureCache(capacity=4096)
    sigs = [os.urandom(64) for _ in range(512)]
    for s in sigs:
        cache.add(s, SignatureCacheValue(s[:20], s[:32]))

    def run():
        for s in sigs:
            if cache.get(s) is None:
                raise RuntimeError("expected a cache hit")

    stats = measure(run, reps=5 if fast else 15, inner=4)
    # per-op: each run() call does len(sigs) lookups
    for k in ("p50_ms", "min_ms", "mean_ms"):
        stats[k] = round(stats[k] / len(sigs), 6)
    return stats


def bench_metrics_observe(fast: bool):
    from cometbft_tpu.libs.metrics import Registry
    reg = Registry()
    hist = reg.histogram("perf", "lat", "perf-lab latency histogram",
                         labels=("backend",))
    ctr = reg.counter("perf", "ops", "perf-lab labeled counter",
                      labels=("kind",))

    def run():
        hist.with_labels("cpu").observe(0.0123)
        ctr.with_labels("bench").add()

    return measure(run, reps=5 if fast else 15, inner=5000)


def bench_tracing_disabled_span(fast: bool):
    from cometbft_tpu.libs import tracing
    old = tracing.set_recorder(tracing.Recorder(enabled=False))
    try:
        def run():
            with tracing.span(tracing.CRYPTO, "bench"):
                pass
        return measure(run, reps=5 if fast else 15, inner=5000)
    finally:
        tracing.set_recorder(old)


def bench_tracing_overhead(fast: bool):
    """Enabled-path flight-recorder cost: one peer-attributed arrival
    instant (the fleet-observatory hot path on every p2p/consensus
    receive) with the passive clock-anchor refresh armed to fire on
    every event — the worst case including the wall-clock sample."""
    from cometbft_tpu.libs import tracing
    rec = tracing.Recorder(buffer_size=4096, anchor_interval_s=1e-9)
    old = tracing.set_recorder(rec)
    try:
        def run():
            tracing.instant(tracing.P2P, "recv", height=7,
                            peer="perfpeer1234", chan=32, bytes=512)
        return measure(run, reps=5 if fast else 15, inner=5000)
    finally:
        tracing.set_recorder(old)


def bench_p2p_loopback_send(fast: bool):
    import asyncio

    from cometbft_tpu.p2p.conn import ChannelDescriptor, MConnection

    n_msgs = 100 if fast else 400
    payload = b"\x5a" * 1024

    class _Pipe:
        def __init__(self):
            self._q: asyncio.Queue = asyncio.Queue()
            self.peer: "_Pipe" = None          # type: ignore

        async def write_msg(self, data: bytes) -> None:
            await self.peer._q.put(bytes(data))

        async def read_msg(self) -> bytes:
            return await self._q.get()

        def close(self) -> None:
            pass

    async def run_once() -> float:
        a, b = _Pipe(), _Pipe()
        a.peer, b.peer = b, a
        got = asyncio.Event()
        count = 0

        async def on_recv(chan, msg):
            nonlocal count
            count += 1
            if count >= n_msgs:
                got.set()

        async def nop_recv(chan, msg):
            pass

        descs = [ChannelDescriptor(id=0x30,
                                   send_queue_capacity=n_msgs + 8)]
        # rate 0 = unlimited: measure framing + scheduling, not the
        # token bucket
        tx = MConnection(a, descs, nop_recv, lambda e: None,
                         send_rate=0, recv_rate=0, peer_id="tx")
        rx = MConnection(b, descs, on_recv, lambda e: None,
                         send_rate=0, recv_rate=0, peer_id="rx")
        tx.start()
        rx.start()
        try:
            t0 = time.perf_counter()
            for _ in range(n_msgs):
                await tx.send_blocking(0x30, payload)
            await asyncio.wait_for(got.wait(), 30)
            return (time.perf_counter() - t0) / n_msgs
        finally:
            tx.close()
            rx.close()

    reps = 3 if fast else 5
    durations = sorted(asyncio.run(run_once())
                       for _ in range(reps + 1))[: reps]
    return {
        "p50_ms": round(statistics.median(durations) * 1e3, 6),
        "min_ms": round(durations[0] * 1e3, 6),
        "mean_ms": round(statistics.fmean(durations) * 1e3, 6),
        "reps": reps,
        "inner": n_msgs,
    }


# lightserve: multiproof build/verify and the RPC response-cache hit
# path (docs/light_proofs.md).  Fixed geometry — 1024-leaf tree, 256
# seeded-random keys — so the committed numbers demonstrate the
# compactness claims: tests/test_lightserve.py statically checks the
# baseline shows multiproof_verify >= 3x faster than
# proofs_verify_256, and the tight tolerance on multiproof_verify
# makes a regression that would void the claim fail `check`.

_MULTIPROOF_LEAVES = 1024
_MULTIPROOF_KEYS = 256


def _multiproof_fixture():
    import random
    items = [b"perf-leaf-%05d" % i for i in range(_MULTIPROOF_LEAVES)]
    sel = sorted(random.Random(7).sample(
        range(_MULTIPROOF_LEAVES), _MULTIPROOF_KEYS))
    return items, sel


def bench_multiproof_build(fast: bool):
    from cometbft_tpu.crypto import merkle
    items, sel = _multiproof_fixture()
    stats = measure(lambda: merkle.multiproof_from_byte_slices(
        items, sel), reps=5 if fast else 15, inner=3)
    # 1/16-key builds ride along for the scaling picture (ungated)
    for k in (1, 16):
        sub = measure(lambda: merkle.multiproof_from_byte_slices(
            items, sel[:k]), reps=3, inner=3)
        stats[f"keys{k}_min_ms"] = sub["min_ms"]
    stats["keys"] = _MULTIPROOF_KEYS
    return stats


def bench_multiproof_verify(fast: bool):
    import json as _json

    from cometbft_tpu.crypto import merkle
    items, sel = _multiproof_fixture()
    root, mp = merkle.multiproof_from_byte_slices(items, sel)
    leaves = [items[i] for i in sel]
    stats = measure(lambda: mp.verify(root, leaves),
                    reps=5 if fast else 15, inner=3, warmup=2)
    # serialized-size comparison vs 256 individual Proofs (the
    # deterministic half of the compactness claim; also asserted in
    # tests/test_lightserve.py)
    _, proofs = merkle.proofs_from_byte_slices(items)
    stats["bytes"] = len(_json.dumps(mp.to_dict()))
    stats["per_key_bytes"] = sum(
        len(_json.dumps(proofs[i].to_dict())) for i in sel)
    stats["size_ratio"] = round(
        stats["per_key_bytes"] / stats["bytes"], 2)
    stats["keys"] = _MULTIPROOF_KEYS
    return stats


def bench_proofs_verify_256(fast: bool):
    """The per-key comparison: verifying the same 256 leaves with 256
    individual Proof objects."""
    from cometbft_tpu.crypto import merkle
    items, sel = _multiproof_fixture()
    root, proofs = merkle.proofs_from_byte_slices(items)

    def run():
        for i in sel:
            proofs[i].verify(root, items[i])

    stats = measure(run, reps=5 if fast else 15, inner=3, warmup=2)
    stats["keys"] = _MULTIPROOF_KEYS
    return stats


def bench_rpc_cache_hit(fast: bool):
    from cometbft_tpu.lightserve.cache import ResponseCache
    cache = ResponseCache(max_bytes=1 << 24)
    payload = {"block": {"data": "x" * 512}}
    for h in range(1, 513):
        cache.put("block", h, (), payload, latest_height=1024)

    def run():
        for h in range(1, 513):
            if cache.get("block", h) is None:
                raise RuntimeError("expected a cache hit")

    stats = measure(run, reps=5 if fast else 15, inner=4)
    # per-op: each run() does 512 lookups
    for k in ("p50_ms", "min_ms", "mean_ms"):
        stats[k] = round(stats[k] / 512, 6)
    return stats


# statetree: the committed state tree that IS the kvstore's app_hash
# (docs/state_tree.md).  Pinned geometry: 1024 committed keys, and a
# 256-key request batch of which 32 are absent — so the verify number
# includes the non-inclusion adjacency arms, not just membership.

_STATETREE_KEYS = 1024
_STATETREE_REQ_PRESENT = 224
_STATETREE_REQ_ABSENT = 32


def _statetree_fixture():
    from cometbft_tpu.db import MemDB
    from cometbft_tpu.statetree import StateTree
    t = StateTree(MemDB())
    for i in range(_STATETREE_KEYS):
        t.set(b"st-key-%05d" % (2 * i), b"st-val-%d" % i)
    root = t.commit(1)
    # even keys exist; odd keys fall in the gaps between them
    req = [b"st-key-%05d" % (2 * i)
           for i in range(_STATETREE_REQ_PRESENT)] + \
          [b"st-key-%05d" % (2 * i + 1)
           for i in range(_STATETREE_REQ_ABSENT)]
    return t, req, root


def bench_statetree_commit(fast: bool):
    """1k-key write + version commit — the per-block ceiling for a
    block that rewrites every key of a 1k-key app (the ISSUE 17
    gate shape)."""
    from cometbft_tpu.db import MemDB
    from cometbft_tpu.statetree import StateTree

    def setup():
        t = StateTree(MemDB())
        for i in range(_STATETREE_KEYS):
            t.set(b"st-key-%05d" % (2 * i), b"v0")
        t.commit(1)
        return {"tree": t, "version": 1}

    def run(state):
        state["version"] += 1
        v = state["version"]
        t = state["tree"]
        for i in range(_STATETREE_KEYS):
            t.set(b"st-key-%05d" % (2 * i), b"v%d" % v)
        t.commit(v)

    stats = measure(run, reps=5 if fast else 15, setup=setup,
                    warmup=1)
    stats["keys"] = _STATETREE_KEYS
    return stats


def bench_statetree_proof_build(fast: bool):
    t, req, _ = _statetree_fixture()
    stats = measure(lambda: t.prove(req, 1),
                    reps=5 if fast else 15, inner=3, warmup=1)
    stats["keys"] = len(req)
    stats["absent_keys"] = _STATETREE_REQ_ABSENT
    return stats


def bench_statetree_proof_verify(fast: bool):
    from cometbft_tpu.statetree import verify_proof_envelope
    t, req, root = _statetree_fixture()
    env = t.prove(req, 1)
    present = [(b"st-key-%05d" % (2 * i), b"st-val-%d" % i)
               for i in range(_STATETREE_REQ_PRESENT)]
    absent = req[_STATETREE_REQ_PRESENT:]
    stats = measure(
        lambda: verify_proof_envelope(env, present=present,
                                      absent=absent,
                                      expected_root=root),
        reps=5 if fast else 15, inner=3, warmup=2)
    stats["keys"] = len(req)
    stats["absent_keys"] = _STATETREE_REQ_ABSENT
    return stats


def bench_mempool_incremental_recheck(fast: bool):
    """ISSUE 10: a 512-tx pool absorbing a commit that touched 16
    keys.  Gates the incremental ``update()`` pass (remove + slice +
    batched recheck); the full-pool recheck of the same commit rides
    along as ``full_min_ms`` — the before/after of the 10 tx/s wall
    (QA_r05's collapse was recheck-bound: every commit re-ran CheckTx
    for thousands of pooled txs)."""
    import asyncio

    from cometbft_tpu.abci import types as abci_t
    from cometbft_tpu.abci.client import AppConns
    from cometbft_tpu.abci.kvstore import (
        DEFAULT_LANES, KVStoreApplication, tx_recheck_keys,
    )
    from cometbft_tpu.config import MempoolConfig
    from cometbft_tpu.mempool import CListMempool

    n_pool, n_touch = 512, 16

    async def run_once(incremental: bool) -> float:
        app = KVStoreApplication()
        conns = AppConns(app)
        mp = CListMempool(
            MempoolConfig(size=2 * n_pool,
                          recheck_incremental=incremental),
            conns.mempool, lanes=DEFAULT_LANES,
            default_lane="default")
        for i in range(n_pool):
            await mp.check_tx(b"pk%04dx=v" % i)
        committed = [b"pk%04dx=z" % i for i in range(n_touch)]
        results = [abci_t.ExecTxResult(
            code=abci_t.CODE_TYPE_OK,
            recheck_keys=tx_recheck_keys(t)) for t in committed]
        t0 = time.perf_counter()
        await mp.update(1, committed, results)
        return time.perf_counter() - t0

    reps = 3 if fast else 6
    inc = sorted(asyncio.run(run_once(True))
                 for _ in range(reps + 1))[:reps]
    full = sorted(asyncio.run(run_once(False))
                  for _ in range(max(2, reps - 1) + 1))[
                      :max(2, reps - 1)]
    return {
        "p50_ms": round(statistics.median(inc) * 1e3, 6),
        "min_ms": round(inc[0] * 1e3, 6),
        "mean_ms": round(statistics.fmean(inc) * 1e3, 6),
        "full_min_ms": round(full[0] * 1e3, 6),
        "pool": n_pool,
        "touched": n_touch,
        "reps": reps,
        "inner": 1,
    }


def bench_height_pipeline_overlap(fast: bool):
    """ISSUE 10: wall-clock for a wired 2-validator in-process net to
    commit 4 heights with a 10 ms-FinalizeBlock app and a loaded
    mempool.  Gates the pipelined path (commit/propose overlap +
    incremental recheck); the serial path (pipeline_commit=False,
    full recheck) rides along as ``serial_min_ms``."""
    import asyncio

    from cometbft_tpu.abci.client import AppConns
    from cometbft_tpu.abci.kvstore import (
        DEFAULT_LANES, KVStoreApplication,
    )
    from cometbft_tpu.config import MempoolConfig
    from cometbft_tpu.config import test_config as _test_config
    from cometbft_tpu.consensus.messages import (
        BlockPartMessage, ProposalMessage, VoteMessage,
    )
    from cometbft_tpu.consensus.state import ConsensusState
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.db import MemDB
    from cometbft_tpu.mempool import CListMempool
    from cometbft_tpu.state import make_genesis_state
    from cometbft_tpu.state.execution import BlockExecutor
    from cometbft_tpu.state.store import Store
    from cometbft_tpu.store import BlockStore
    from cometbft_tpu.types.genesis import (
        GenesisDoc, GenesisValidator,
    )
    from cometbft_tpu.types.priv_validator import new_mock_pv
    from cometbft_tpu.types.timestamp import Timestamp

    gossip = (ProposalMessage, BlockPartMessage, VoteMessage)
    heights = 4

    async def run_once(pipeline: bool) -> float:
        crypto_batch.set_backend("cpu")
        pvs = [new_mock_pv() for _ in range(2)]
        doc = GenesisDoc(
            chain_id="perf-pipeline",
            genesis_time=Timestamp(1700000000, 0),
            validators=[GenesisValidator(
                address=b"", pub_key=pv.get_pub_key(), power=10)
                for pv in pvs])
        # small blocks so the preloaded pool stays occupied across
        # every height — the serial path then pays its full-pool
        # recheck inside the commit critical path each block, which
        # is exactly the cost the pipeline + incremental recheck
        # take off it
        doc.consensus_params.block.max_bytes = 2048
        doc.consensus_params.evidence.max_bytes = 1024
        nodes, pools = [], []
        for pv in pvs:
            state = make_genesis_state(doc)
            app = KVStoreApplication()
            app.abci_delays = {"finalize_block": 0.01}
            conns = AppConns(app)
            ss, bs = Store(MemDB()), BlockStore(MemDB())
            ss.save(state)
            mp = CListMempool(
                MempoolConfig(size=4096,
                              recheck_incremental=pipeline),
                conns.mempool, lanes=DEFAULT_LANES,
                default_lane="default")
            ex = BlockExecutor(ss, conns.consensus, mempool=mp,
                               block_store=bs)
            cfg = _test_config().consensus
            cfg.pipeline_commit = pipeline
            nodes.append(ConsensusState(cfg, state, ex, bs,
                                        priv_validator=pv))
            pools.append(mp)
        for i, cs in enumerate(nodes):
            def mk(idx):
                def hook(msg):
                    if isinstance(msg, gossip):
                        for j, other in enumerate(nodes):
                            if j != idx:
                                other.send_peer(msg, f"n{idx}")
                return hook
            cs.broadcast_hooks.append(mk(i))
        for mp in pools:
            for i in range(768):
                await mp.check_tx(b"ld%04dx=v" % i)
        t0 = time.perf_counter()
        for cs in nodes:
            await cs.start()
        try:
            while min(cs.block_store.height for cs in nodes) \
                    < heights:
                if time.perf_counter() - t0 > 60:
                    raise RuntimeError("pipeline bench net stuck")
                await asyncio.sleep(0.005)
            return time.perf_counter() - t0
        finally:
            for cs in nodes:
                await cs.stop()
            crypto_batch.set_backend("auto")

    reps = 2 if fast else 4
    piped = sorted(asyncio.run(run_once(True))
                   for _ in range(reps + 1))[:reps]
    serial = sorted(asyncio.run(run_once(False))
                    for _ in range(2 + 1))[:2]
    return {
        "p50_ms": round(statistics.median(piped) * 1e3, 6),
        "min_ms": round(piped[0] * 1e3, 6),
        "mean_ms": round(statistics.fmean(piped) * 1e3, 6),
        "serial_min_ms": round(serial[0] * 1e3, 6),
        "heights": heights,
        "reps": reps,
        "inner": 1,
    }


def bench_gossip_reconcile_roundtrip(fast: bool):
    """ISSUE 12: one reconciliation round at a 5k-tx pool — build the
    short-id summary for a 256-tx advert batch, encode + decode the
    TxHave, and diff it against a receiver pool missing 32 of the
    txs (the receiver-side cost every advert pays).  The short-id
    hashing of the full 5k pool rides along as ``pool_hash_min_ms``
    (the per-salt map build, amortized across adverts)."""
    from cometbft_tpu.mempool.messages import (
        TxHaveMessage, decode_mempool, encode_mempool, short_ids,
    )
    from cometbft_tpu.types.tx import tx_key

    n_pool, n_advert, n_missing = 5000, 256, 32
    keys = [tx_key(b"sum%05d=" % i + b"v" * 248)
            for i in range(n_pool)]
    salt = b"perf-salt"
    # receiver's short map: the pool minus the missing txs
    have = dict(zip(short_ids(salt, keys[n_missing:]),
                    keys[n_missing:]))
    advert_keys = keys[:n_advert]

    def run():
        sids = short_ids(salt, advert_keys)
        raw = encode_mempool(TxHaveMessage(salt=salt, ids=sids))
        msg = decode_mempool(raw)
        wants = [sid for sid in msg.ids if sid not in have]
        if len(wants) != n_missing:
            raise RuntimeError(f"diff found {len(wants)} missing")

    stats = measure(run, reps=5 if fast else 15, inner=5, warmup=2)
    sub = measure(lambda: short_ids(salt, keys), reps=3, inner=1,
                  warmup=1)
    stats["pool_hash_min_ms"] = sub["min_ms"]
    stats["pool"] = n_pool
    stats["advert"] = n_advert
    return stats


def bench_compact_block_reconstruct(fast: bool):
    """ISSUE 12: rebuild a 900-tx / 256 KiB proposal from the mempool
    given its compact form (skeleton + tx hashes) — resolve, splice,
    re-encode, re-split, verify the part-set header.  The full-part
    path this replaces shipped ~233 KB per peer; the compact form is
    ~29 KB (``compact_bytes``/``full_bytes`` ride along)."""
    from cometbft_tpu.consensus.messages import (
        make_compact_block, reconstruct_block_bytes,
    )
    from cometbft_tpu.types.block import Block, Data, Header
    from cometbft_tpu.types.part_set import PartSet
    from cometbft_tpu.types.timestamp import Timestamp
    from cometbft_tpu.types.tx import tx_key

    txs = [(b"cb%04d=" % i) + b"v" * 249 for i in range(900)]
    block = Block(header=Header(chain_id="perf", height=7,
                                time=Timestamp(1700000000, 0),
                                proposer_address=b"p" * 20),
                  data=Data(txs=list(txs)))
    block.fill_header()
    parts = block.make_part_set()
    msg = make_compact_block(7, 0, block, parts.header())
    pool = {tx_key(tx): tx for tx in txs}

    def run():
        resolved = [pool[h] for h in msg.tx_hashes]
        rebuilt = PartSet.from_data(
            reconstruct_block_bytes(msg.skeleton, resolved))
        if rebuilt.header() != parts.header():
            raise RuntimeError("part-set header mismatch")

    stats = measure(run, reps=5 if fast else 15, inner=2, warmup=2)
    stats["txs"] = len(txs)
    stats["compact_bytes"] = len(msg.skeleton) + \
        32 * len(msg.tx_hashes)
    stats["full_bytes"] = parts.byte_size
    return stats


def bench_bftlint_selfcheck(fast: bool):
    from tools.bftlint import lint_paths
    from tools.bftlint.checkers import ALL_CHECKERS
    pkg = os.path.join(_REPO_ROOT, "cometbft_tpu")

    def run():
        result = lint_paths([pkg], ALL_CHECKERS)
        if result.parse_errors:
            raise RuntimeError(
                f"bftlint parse errors: {result.parse_errors}")

    return measure(run, reps=2 if fast else 4, warmup=1)


def _pipeline_workload(n: int = 10000):
    """n (pub, msg, sig) triples with DISTINCT keys — the shape of a
    10k-validator commit burst (tpu_probe's disk-cached workload, so
    the ~90 s keygen is paid once per checkout, not per run)."""
    from cometbft_tpu.tools import tpu_probe
    return tpu_probe.load_or_make_workload(n)


def _cpu_bv(items, monolithic: bool):
    from cometbft_tpu.crypto import ed25519
    bv = ed25519.CpuBatchVerifier(monolithic=monolithic)
    for pub, msg, sig in items:
        bv.add(ed25519.Ed25519PubKey(pub), msg, sig)
    return bv


def bench_ed25519_pipelined_dispatch(fast: bool):
    """ISSUE 14 tentpole gate: the tiled+overlapped verification
    pipeline (native tile kernel: packed blobs, staged pubkey
    decompression, signed-digit MSM with cached-form bucket adds,
    fe_sqr decompression — KERNEL_NOTES round 6) at the 10k-signature
    commit-burst shape, vs the pre-pipeline monolithic dispatch
    riding along as ``monolithic_min_ms``.  The committed baseline
    pins pipelined >= 1.25x faster (tests/test_verify_pipeline.py
    statically checks the claim); the host_prep/kernel_execute
    histogram split rides along as evidence the phases are
    separately instrumented (``host_prep_ms``/``kernel_execute_ms``).
    """
    from cometbft_tpu.crypto import pipeline as cpipe
    from cometbft_tpu.libs import metrics as libmetrics

    items = _pipeline_workload()
    piped = _cpu_bv(items, monolithic=False)
    mono = _cpu_bv(items, monolithic=True)

    hist = cpipe._dispatch_histogram()
    tile = str(cpipe.tile_size())
    prep = hist.with_labels("host_prep", "native", tile, "1")
    execu = hist.with_labels("kernel_execute", "native", tile, "1")
    prep0, exec0 = prep._sum, execu._sum

    def run_piped():
        ok, _ = piped.verify()
        if not ok:
            raise RuntimeError("workload must verify")

    def run_mono():
        ok, _ = mono.verify()
        if not ok:
            raise RuntimeError("workload must verify")

    stats = measure(run_piped, reps=3 if fast else 5, warmup=1)
    mono_stats = measure(run_mono, reps=2 if fast else 4, warmup=1)
    stats["monolithic_min_ms"] = mono_stats["min_ms"]
    stats["speedup_vs_monolithic"] = round(
        mono_stats["min_ms"] / stats["min_ms"], 3)
    stats["host_prep_ms"] = round((prep._sum - prep0) * 1e3, 3)
    stats["kernel_execute_ms"] = round((execu._sum - exec0) * 1e3, 3)
    stats["sigs"] = len(items)
    return stats


def bench_verify_event_loop_stall(fast: bool):
    """ISSUE 14 gate: maximum event-loop stall while a 10k-signature
    burst verifies.  The async arm awaits ``verify_async()`` (the
    whole tiled pipeline on the verification staging worker;
    GIL-free kernels), the sync arm calls ``verify()`` on the loop —
    the pre-pipeline behavior, riding along as ``sync_stall_ms``.
    A ticker coroutine measures the largest gap between 1 ms ticks;
    the committed baseline pins the async stall >= 5x smaller
    (tests/test_verify_pipeline.py checks the claim statically)."""
    import asyncio

    items = _pipeline_workload()

    async def run_arm(use_async: bool) -> float:
        bv = _cpu_bv(items, monolithic=not use_async)
        max_gap = 0.0
        done = asyncio.Event()

        async def ticker():
            nonlocal max_gap
            last = time.perf_counter()
            while not done.is_set():
                await asyncio.sleep(0.001)
                now = time.perf_counter()
                if now - last > max_gap:
                    max_gap = now - last
                last = now

        t = asyncio.ensure_future(ticker())
        await asyncio.sleep(0.05)       # ticker cadence settles
        max_gap = 0.0
        if use_async:
            ok, _ = await bv.verify_async()
        else:
            ok, _ = bv.verify()
        if not ok:
            raise RuntimeError("workload must verify")
        done.set()
        await t
        return max_gap

    reps = 3 if fast else 5
    asyncio.run(run_arm(True))          # warm (kernel, cache, worker)
    gaps = sorted(asyncio.run(run_arm(True)) for _ in range(reps))
    sync_gaps = sorted(asyncio.run(run_arm(False))
                       for _ in range(2))
    return {
        "p50_ms": round(gaps[len(gaps) // 2] * 1e3, 6),
        "min_ms": round(gaps[0] * 1e3, 6),
        "mean_ms": round(sum(gaps) / len(gaps) * 1e3, 6),
        "sync_stall_ms": round(sync_gaps[0] * 1e3, 6),
        "stall_drop": round(sync_gaps[0] / gaps[0], 2)
        if gaps[0] > 0 else 0.0,
        "sigs": len(items),
        "reps": reps,
        "inner": 1,
    }


# name -> (fn, in_fast_subset)
def _agg_commit_fixture(n: int):
    """An n-validator BLS valset + verified-shape aggregate commit.

    Tiny secret scalars keep fixture construction fast at 10k
    validators; verification cost is independent of scalar size (the
    pairing and the G1 point sum see full-width field elements)."""
    from cometbft_tpu.crypto import bls12381 as bls
    from cometbft_tpu.crypto import _bls12381_math as m
    from cometbft_tpu.libs.bits import BitArray
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types.block_id import BlockID
    from cometbft_tpu.types.commit import AggregateCommit
    from cometbft_tpu.types.part_set import PartSetHeader
    from cometbft_tpu.types.timestamp import Timestamp
    from cometbft_tpu.types.validator_set import (
        Validator, ValidatorSet,
    )

    bid = BlockID(hash=b"\x0b" * 32,
                  part_set_header=PartSetHeader(1, b"\x0c" * 32))
    height = 9
    sks = list(range(2, n + 2))
    vals_list = []
    pk_by_addr = {}
    for sk in sks:
        pk = bls.Bls12381PubKey._from_point_unchecked(
            m.pt_mul(m.G1_OPS, m.G1_GEN, sk))
        vals_list.append(Validator(address=pk.address(), pub_key=pk,
                                   voting_power=10))
        pk_by_addr[pk.address()] = sk
    vals = ValidatorSet(vals_list)
    sb = canonical.vote_sign_bytes(
        "perf-chain", canonical.PRECOMMIT_TYPE, height, 0, bid,
        Timestamp.zero())
    # aggregate signature = [sum sk]H(m): one G2 mul instead of n
    # signs + n adds — same point the real aggregation produces
    agg_sk = sum(pk_by_addr[v.address] for v in vals.validators) \
        % m.R_ORDER
    hm = m.hash_to_g2(sb, bls.DST)
    agg_sig = m.g2_compress(m.pt_mul(m.G2_OPS, hm, agg_sk))
    signers = BitArray(n)
    for i in range(n):
        signers.set_index(i, True)
    commit = AggregateCommit(height=height, round=0, block_id=bid,
                             signers=signers, signature=agg_sig)
    vals.hash()   # memoize: the valset hash is not what we measure
    return vals, commit, bid, height


def bench_bls_aggregate_commit_verify(n: int, reps: int,
                                      warm: bool):
    """O(1) aggregate-commit verification (docs/aggregate_commits.md):
    cold pays the G1 pubkey point-sum + one pairing; warm hits the
    aggregate-pubkey cache and pays the pairing alone.  The ISSUE 13
    acceptance gate lives at the 10k shape."""
    from cometbft_tpu.crypto import bls12381 as bls
    from cometbft_tpu.types import validation

    def setup():
        return _agg_commit_fixture(n)

    def run(fixture):
        vals, commit, bid, height = fixture
        if not warm:
            bls._AGG_PK_CACHE = None     # force the G1 point-sum
        validation.verify_commit_light("perf-chain", vals, bid,
                                       height, commit)

    if warm:
        fixture = _agg_commit_fixture(n)
        run(fixture)                     # prime the pubkey cache
        stats = measure(lambda _: run(fixture), reps=reps,
                        setup=lambda: None, warmup=1)
    else:
        stats = measure(run, reps=reps, setup=setup, warmup=1)
    stats["validators"] = n
    stats["warm_pubkey_cache"] = warm
    return stats


def bench_bls_agg_verify_100_cold(fast: bool):
    return bench_bls_aggregate_commit_verify(
        100, reps=4 if fast else 6, warm=False)


def bench_bls_agg_verify_1k_cold(fast: bool):
    return bench_bls_aggregate_commit_verify(1000, reps=4, warm=False)


def bench_bls_agg_verify_10k_cold(fast: bool):
    return bench_bls_aggregate_commit_verify(10000, reps=4,
                                             warm=False)


def bench_bls_agg_verify_10k_warm(fast: bool):
    return bench_bls_aggregate_commit_verify(10000, reps=4, warm=True)


BENCHMARKS = {
    "batch_verify_cpu_pad64": (bench_batch_verify_pad64, True),
    "batch_verify_cpu_pad1024": (bench_batch_verify_pad1024, False),
    "merkle_root_1024": (bench_merkle_root, True),
    "vote_sign_bytes": (bench_vote_sign_bytes, True),
    "signature_cache_hit": (bench_signature_cache_hit, True),
    "metrics_observe": (bench_metrics_observe, True),
    "tracing_disabled_span": (bench_tracing_disabled_span, True),
    "tracing_overhead": (bench_tracing_overhead, True),
    "p2p_loopback_send": (bench_p2p_loopback_send, True),
    "multiproof_build": (bench_multiproof_build, True),
    "multiproof_verify": (bench_multiproof_verify, True),
    "proofs_verify_256": (bench_proofs_verify_256, True),
    "rpc_cache_hit": (bench_rpc_cache_hit, True),
    "statetree_commit": (bench_statetree_commit, True),
    "statetree_proof_build": (bench_statetree_proof_build, True),
    "statetree_proof_verify": (bench_statetree_proof_verify, True),
    "mempool_incremental_recheck": (
        bench_mempool_incremental_recheck, True),
    "height_pipeline_overlap": (bench_height_pipeline_overlap, True),
    "gossip_reconcile_roundtrip": (
        bench_gossip_reconcile_roundtrip, True),
    "compact_block_reconstruct": (
        bench_compact_block_reconstruct, True),
    "bftlint_selfcheck": (bench_bftlint_selfcheck, True),
    "ed25519_pipelined_dispatch": (
        bench_ed25519_pipelined_dispatch, True),
    "verify_event_loop_stall": (
        bench_verify_event_loop_stall, True),
    "bls_aggregate_commit_verify_100_cold": (
        bench_bls_agg_verify_100_cold, True),
    "bls_aggregate_commit_verify_1k_cold": (
        bench_bls_agg_verify_1k_cold, False),
    "bls_aggregate_commit_verify_10k_cold": (
        bench_bls_agg_verify_10k_cold, False),
    "bls_aggregate_commit_verify_10k_warm": (
        bench_bls_agg_verify_10k_warm, False),
}


# ---------------------------------------------------------------------
# modes

def run_suite(fast: bool = False, only=None) -> dict:
    results = {}
    for name, (fn, in_fast) in BENCHMARKS.items():
        if only and name not in only:
            continue
        if fast and not in_fast:
            continue
        results[name] = fn(fast)
    return {
        "schema": SCHEMA,
        "mode": "fast" if fast else "full",
        **({"only": sorted(only)} if only else {}),
        "env": {
            "python": sys.version.split()[0],
            "platform": sys.platform,
            "cpus": os.cpu_count(),
        },
        "benchmarks": results,
    }


def load_baseline(path: str) -> dict:
    with open(path) as f:
        base = json.load(f)
    if base.get("schema") != SCHEMA:
        raise ValueError(
            f"baseline schema {base.get('schema')} != {SCHEMA}; "
            f"rerun `perf_lab.py rebaseline`")
    return base


def check_report(report: dict, baseline: dict) -> tuple[bool, list]:
    """Diff a run report against the baseline.  Returns (ok, lines).
    A benchmark regresses when its current min_ms exceeds the
    baseline min_ms times its tolerance; a benchmark in the baseline
    but missing from the (non-fast-filtered) report fails too."""
    default_tol = float(baseline.get("default_tolerance",
                                     DEFAULT_TOLERANCE))
    base_benches = baseline.get("benchmarks", {})
    ok = True
    lines = []
    for name, stats in sorted(report["benchmarks"].items()):
        base = base_benches.get(name)
        if base is None:
            lines.append(f"NEW   {name}: min {stats['min_ms']}ms "
                         f"(not in baseline — rebaseline to gate it)")
            continue
        tol = float(base.get("tolerance", default_tol))
        limit = base["min_ms"] * tol
        cur = stats["min_ms"]
        ratio = cur / base["min_ms"] if base["min_ms"] > 0 else 0.0
        verdict = "ok   " if cur <= limit else "REGRESSED"
        if cur > limit:
            ok = False
        lines.append(
            f"{verdict} {name}: min {cur}ms vs baseline "
            f"{base['min_ms']}ms (x{ratio:.2f}, limit x{tol:g})")
    wanted = {n for n, (fn, in_fast) in BENCHMARKS.items()
              if report["mode"] == "full" or in_fast}
    if report.get("only"):
        # an explicit --only subset only gates what it ran
        wanted &= set(report["only"])
    for name in sorted(set(base_benches) & wanted
                       - set(report["benchmarks"])):
        ok = False
        lines.append(f"MISSING {name}: in baseline but did not run")
    return ok, lines


def rebaseline(report: dict, path: str,
               default_tolerance: float = DEFAULT_TOLERANCE) -> dict:
    prev_tols = {}
    if os.path.exists(path):
        try:
            prev = load_baseline(path)
            prev_tols = {n: b["tolerance"]
                         for n, b in prev.get("benchmarks", {}).items()
                         if "tolerance" in b}
        except Exception:
            pass
    base = {
        "schema": SCHEMA,
        "default_tolerance": default_tolerance,
        "generated_by": "tools/perf_lab.py rebaseline",
        "env": report["env"],
        "benchmarks": {
            name: {"min_ms": stats["min_ms"],
                   "p50_ms": stats["p50_ms"],
                   **{k: stats[k] for k in CLAIM_KEYS if k in stats},
                   **({"tolerance": prev_tols[name]}
                      if name in prev_tols else {})}
            for name, stats in sorted(report["benchmarks"].items())
        },
    }
    with open(path, "w") as f:
        json.dump(base, f, indent=2, sort_keys=True)
        f.write("\n")
    return base


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("mode", choices=("run", "check", "rebaseline"),
                    nargs="?", default="run")
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 subset (seconds, not minutes)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--out", default="",
                    help="also write the JSON report here")
    ap.add_argument("--only", default="",
                    help="comma-separated benchmark subset")
    args = ap.parse_args(argv)

    only = {s.strip() for s in args.only.split(",") if s.strip()} \
        or None
    report = run_suite(fast=args.fast, only=only)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")

    if args.mode == "run":
        print(json.dumps(report, indent=2, sort_keys=True))
        return 0
    if args.mode == "rebaseline":
        base = rebaseline(report, args.baseline)
        print(f"baseline written: {args.baseline} "
              f"({len(base['benchmarks'])} benchmarks)")
        return 0
    # check
    baseline = load_baseline(args.baseline)
    ok, lines = check_report(report, baseline)
    print("\n".join(lines))
    print("PASS" if ok else "FAIL: perf regression beyond tolerance")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
