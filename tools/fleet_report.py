#!/usr/bin/env python3
"""Cluster critical-path report from N nodes' flight records.

Input: any mix of per-node flight dumps (supervisor give-up, nemesis
archive, ``/debug/pprof/trace?dump=1``), saved ``/trace`` RPC bodies,
and the QA fleet collector's ``fleet_<run>.json`` — each carries the
``(monotonic_ns, wall_ns)`` clock-anchor pairs the recorder refreshes
(cometbft_tpu/libs/tracing.py).  Per node, offset + drift are fitted
from the anchors by least squares and every monotonic timestamp is
mapped onto one shared wall timeline; with NTP-disciplined hosts the
residual alignment error is the wall-clock sync error (ones of ms),
far below the propagation latencies being measured.

Output, per height — the decomposition the committee-consensus
measurement line of work (PAPERS.md) applies to BFT latency:

  * the proposer (the node that recorded ``proposal_broadcast``) and
    its propose span;
  * per-node first-proposal-seen (``proposal_recv``) deltas from the
    proposer's first-sent instant;
  * the vote-arrival waterfall: per node, ``vote_recv`` arrivals
    accumulated by voting power → time-to-1/3 and time-to-2/3 for
    prevotes and time-to-2/3 for precommits;
  * per-node ``commit`` instants and the inter-node commit skew;

plus gossip hop-latency distributions (each vote/proposal's arrival
delta vs its earliest sighting anywhere in the fleet) and a straggler
table.  Text by default, ``--json`` for machines — the CLI mirrors
``tools/trace_report.py``.

    python tools/fleet_report.py dump-a.json dump-b.json ... \
        [--height H] [--powers 10,1,1,1] [--json]
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

_MS = 1e6  # ns per ms

PREVOTE = 1
PRECOMMIT = 2


def _to_int(v) -> int:
    try:
        return int(v)
    except (TypeError, ValueError):
        return 0


# ---------------------------------------------------------------------
# clock alignment

def fit_clock(anchors: list) -> tuple[float, float]:
    """Fit ``wall = mono + offset + drift*mono`` by least squares over
    ``(monotonic_ns, wall_ns)`` anchor pairs.  One pair pins the
    offset only (drift 0); the recorder keeps its first anchor
    forever, so long-lived nodes give the fit a long drift baseline.
    Returns ``(offset_ns, drift)``."""
    pairs = [(_to_int(m), _to_int(w)) for m, w in anchors]
    if not pairs:
        return 0.0, 0.0
    if len(pairs) == 1:
        return float(pairs[0][1] - pairs[0][0]), 0.0
    # regress y = wall - mono against x = mono (numerically safer
    # than wall against mono: y is small, x is huge)
    n = len(pairs)
    xbar = sum(m for m, _ in pairs) / n
    ybar = sum(w - m for m, w in pairs) / n
    sxx = sum((m - xbar) ** 2 for m, _ in pairs)
    if sxx == 0:
        return ybar, 0.0
    sxy = sum((m - xbar) * ((w - m) - ybar) for m, w in pairs)
    drift = sxy / sxx
    offset = ybar - drift * xbar
    return offset, drift


def to_wall(ts_ns: int, fit: tuple[float, float]) -> float:
    offset, drift = fit
    return ts_ns + offset + drift * ts_ns


# ---------------------------------------------------------------------
# input loading

def _norm_events(evs: list) -> list[dict]:
    out = []
    for e in evs:
        out.append({
            "ts_ns": _to_int(e.get("ts_ns")),
            "dur_ns": _to_int(e.get("dur_ns")),
            "category": e.get("category", ""),
            "name": e.get("name", ""),
            "height": _to_int(e.get("height")),
            "attrs": e.get("attrs") or {},
        })
    out.sort(key=lambda e: e["ts_ns"])
    return out


def node_record(obj: dict, fallback_name: str) -> dict:
    """Normalize one node's record — a flight dump or a saved /trace
    body — to ``{"node", "anchors", "events"}``."""
    name = obj.get("node") or fallback_name
    return {"node": name,
            "anchors": [(_to_int(m), _to_int(w))
                        for m, w in obj.get("anchors") or []],
            "events": _norm_events(obj.get("events") or [])}


def load_inputs(paths: list[str]) -> list[dict]:
    """Each path is a per-node record, or a fleet collection file
    (``{"nodes": {name: record, ...}}``) contributing one record per
    node."""
    nodes = []
    for path in paths:
        with open(path) as f:
            obj = json.load(f)
        stem = path.rsplit("/", 1)[-1]
        if stem.endswith(".json"):
            stem = stem[:-5]
        if isinstance(obj, dict) and isinstance(obj.get("nodes"),
                                                dict):
            for name, rec in sorted(obj["nodes"].items()):
                nodes.append(node_record(rec, name))
        else:
            nodes.append(node_record(obj, stem))
    return nodes


# ---------------------------------------------------------------------
# analysis

def _pct(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            max(0, int(q * (len(sorted_vals) - 1) + 0.5)))
    return sorted_vals[i]


def _waterfall(arrivals: list[tuple[float, int]],
               powers: list[float],
               total_power: float) -> dict:
    """``arrivals`` is [(wall_ts, validator_index)] on ONE node;
    returns the cumulative-power crossing times.  Each validator
    counts once (first arrival wins — regossip duplicates carry no
    new power)."""
    seen: set[int] = set()
    acc = 0.0
    t13 = t23 = None
    for ts, idx in sorted(arrivals):
        if idx in seen:
            continue
        seen.add(idx)
        acc += powers[idx] if 0 <= idx < len(powers) else 1.0
        if t13 is None and acc * 3 > total_power:
            t13 = ts
        if t23 is None and acc * 3 > 2 * total_power:
            t23 = ts
            break
    return {"t13": t13, "t23": t23}


def analyze(nodes: list[dict], height: Optional[int] = None,
            powers: Optional[list[float]] = None) -> dict:
    """Merge the fleet onto one wall timeline and decompose each
    height's critical path.  Returns the full report as a dict (the
    ``--json`` body); times inside are wall-clock ns floats."""
    fits = {n["node"]: fit_clock(n["anchors"]) for n in nodes}
    # per node, per height, the named instants we chart
    heights: set[int] = set()
    per_node: dict[str, dict[int, dict]] = {}
    max_vindex = -1
    for n in nodes:
        fit = fits[n["node"]]
        hmap: dict[int, dict] = {}
        per_node[n["node"]] = hmap
        for e in n["events"]:
            h = e["height"]
            if h <= 0 or e["category"] != "consensus":
                continue
            if height is not None and h != height:
                continue
            name, a = e["name"], e["attrs"]
            w = to_wall(e["ts_ns"], fit)
            rec = hmap.setdefault(h, {"first_seen": None,
                                      "broadcast": None,
                                      "propose_span_ns": 0,
                                      "commit": None,
                                      "votes": {PREVOTE: [],
                                                PRECOMMIT: []}})
            heights.add(h)
            if name in ("proposal_recv", "proposal_received"):
                if rec["first_seen"] is None or w < rec["first_seen"]:
                    rec["first_seen"] = w
            elif name == "proposal_broadcast":
                rec["broadcast"] = w
            elif name == "step:Propose":
                rec["propose_span_ns"] = max(rec["propose_span_ns"],
                                             e["dur_ns"])
            elif name == "commit":
                if rec["commit"] is None or w < rec["commit"]:
                    rec["commit"] = w
            elif name == "vote_recv":
                idx = _to_int(a.get("index", -1))
                max_vindex = max(max_vindex, idx)
                t = _to_int(a.get("type"))
                if t in (PREVOTE, PRECOMMIT):
                    rec["votes"][t].append((w, idx))
    if powers is None:
        powers = [1.0] * max(1, max_vindex + 1)
    total_power = sum(powers)

    out_heights: dict[int, dict] = {}
    proposal_hops: list[float] = []
    vote_hops: list[float] = []
    commit_delays: dict[str, list[float]] = {k: []
                                             for k in per_node}
    seen_delays: dict[str, list[float]] = {k: [] for k in per_node}

    for h in sorted(heights):
        rows = {name: hmap[h] for name, hmap in per_node.items()
                if h in hmap}
        proposer = None
        bcast = None
        for name, rec in rows.items():
            if rec["broadcast"] is not None and \
                    (bcast is None or rec["broadcast"] < bcast):
                proposer, bcast = name, rec["broadcast"]
        # t0: proposer's first-sent instant, else the fleet's first
        # sighting of the proposal, else the earliest commit
        t0 = bcast
        if t0 is None:
            seen = [r["first_seen"] for r in rows.values()
                    if r["first_seen"] is not None]
            t0 = min(seen) if seen else min(
                (r["commit"] for r in rows.values()
                 if r["commit"] is not None), default=None)
        if t0 is None:
            continue
        node_rows = {}
        commits = []
        for name in sorted(rows):
            rec = rows[name]
            pv = _waterfall(rec["votes"][PREVOTE], powers,
                            total_power)
            pc = _waterfall(rec["votes"][PRECOMMIT], powers,
                            total_power)
            fs = rec["first_seen"]
            cm = rec["commit"]
            node_rows[name] = {
                "proposal_seen_ms":
                    (fs - t0) / _MS if fs is not None else None,
                "prevote_t13_ms":
                    (pv["t13"] - t0) / _MS
                    if pv["t13"] is not None else None,
                "prevote_t23_ms":
                    (pv["t23"] - t0) / _MS
                    if pv["t23"] is not None else None,
                "precommit_t23_ms":
                    (pc["t23"] - t0) / _MS
                    if pc["t23"] is not None else None,
                "commit_ms":
                    (cm - t0) / _MS if cm is not None else None,
            }
            if cm is not None:
                commits.append((cm, name))
            if bcast is not None and fs is not None and \
                    name != proposer:
                proposal_hops.append((fs - bcast) / _MS)
                seen_delays[name].append((fs - bcast) / _MS)
        skew = ((max(c for c, _ in commits) -
                 min(c for c, _ in commits)) / _MS
                if len(commits) > 1 else 0.0)
        if commits:
            first_commit = min(c for c, _ in commits)
            for cm, name in commits:
                commit_delays[name].append((cm - first_commit) / _MS)
        # vote hop latency: arrival delta vs the earliest sighting of
        # the same (type, index) vote anywhere in the fleet
        firsts: dict[tuple, float] = {}
        for rec in rows.values():
            for t, arr in rec["votes"].items():
                for w, idx in arr:
                    k = (t, idx)
                    if k not in firsts or w < firsts[k]:
                        firsts[k] = w
        for rec in rows.values():
            for t, arr in rec["votes"].items():
                for w, idx in arr:
                    d = (w - firsts[(t, idx)]) / _MS
                    if d > 0:
                        vote_hops.append(d)
        out_heights[h] = {
            "proposer": proposer,
            "propose_span_ms":
                (rows[proposer]["propose_span_ns"] / _MS)
                if proposer else 0.0,
            "commit_skew_ms": skew,
            "nodes": node_rows,
        }

    proposal_hops.sort()
    vote_hops.sort()
    stragglers = {}
    for name in sorted(per_node):
        sd, cd = seen_delays[name], commit_delays[name]
        stragglers[name] = {
            "mean_proposal_delay_ms":
                sum(sd) / len(sd) if sd else 0.0,
            "mean_commit_delay_ms":
                sum(cd) / len(cd) if cd else 0.0,
            "heights_seen": len(per_node[name]),
        }
    return {
        "nodes": sorted(per_node),
        "clock_fits": {k: {"offset_ns": v[0], "drift": v[1]}
                       for k, v in fits.items()},
        "heights": out_heights,
        "hop_latency_ms": {
            "proposal": {"p50": _pct(proposal_hops, 0.5),
                         "p90": _pct(proposal_hops, 0.9),
                         "max": proposal_hops[-1]
                         if proposal_hops else 0.0,
                         "n": len(proposal_hops)},
            "vote": {"p50": _pct(vote_hops, 0.5),
                     "p90": _pct(vote_hops, 0.9),
                     "max": vote_hops[-1] if vote_hops else 0.0,
                     "n": len(vote_hops)},
        },
        "stragglers": stragglers,
    }


# ---------------------------------------------------------------------
# rendering

def _fmt(v: Optional[float]) -> str:
    return f"{v:8.2f}" if v is not None else "       -"


def render_report(report: dict) -> str:
    lines = [f"fleet: {len(report['nodes'])} nodes "
             f"({', '.join(report['nodes'])})"]
    for name, fit in sorted(report["clock_fits"].items()):
        lines.append(f"  clock {name}: offset "
                     f"{fit['offset_ns'] / _MS:.2f}ms drift "
                     f"{fit['drift']:+.2e}")
    if not report["heights"]:
        lines.append("no height-stamped consensus events in these "
                     "records")
        return "\n".join(lines) + "\n"
    for h, row in sorted(report["heights"].items()):
        lines.append("")
        lines.append(
            f"height {h}  proposer={row['proposer'] or '?'}  "
            f"propose_span={row['propose_span_ms']:.2f}ms  "
            f"commit_skew={row['commit_skew_ms']:.2f}ms")
        hdr = (f"  {'node':<14} {'seen_ms':>8} {'pv_1/3':>8} "
               f"{'pv_2/3':>8} {'pc_2/3':>8} {'commit':>8}")
        lines.append(hdr)
        lines.append("  " + "-" * (len(hdr) - 2))
        for name, r in sorted(row["nodes"].items()):
            lines.append(
                f"  {name:<14} {_fmt(r['proposal_seen_ms'])} "
                f"{_fmt(r['prevote_t13_ms'])} "
                f"{_fmt(r['prevote_t23_ms'])} "
                f"{_fmt(r['precommit_t23_ms'])} "
                f"{_fmt(r['commit_ms'])}")
    hops = report["hop_latency_ms"]
    lines.append("")
    lines.append(
        f"hop latency (ms): proposal p50={hops['proposal']['p50']:.2f}"
        f" p90={hops['proposal']['p90']:.2f}"
        f" max={hops['proposal']['max']:.2f}"
        f" n={hops['proposal']['n']};"
        f" vote p50={hops['vote']['p50']:.2f}"
        f" p90={hops['vote']['p90']:.2f}"
        f" max={hops['vote']['max']:.2f} n={hops['vote']['n']}")
    lines.append("stragglers (mean delay vs fleet-first, ms):")
    for name, s in sorted(report["stragglers"].items()):
        lines.append(
            f"  {name:<14} proposal={s['mean_proposal_delay_ms']:.2f}"
            f" commit={s['mean_commit_delay_ms']:.2f}"
            f" heights={s['heights_seen']}")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Cluster critical-path report from N flight "
                    "records")
    p.add_argument("dumps", nargs="+",
                   help="flight dumps, /trace bodies, or "
                        "fleet_<run>.json collections")
    p.add_argument("--height", type=int, default=None,
                   help="restrict to one height")
    p.add_argument("--powers", default="",
                   help="comma list of voting powers by validator "
                        "index (default: equal)")
    p.add_argument("--json", action="store_true",
                   help="JSON instead of text")
    args = p.parse_args(argv)
    powers = None
    if args.powers:
        powers = [float(x) for x in args.powers.split(",") if x]
    nodes = load_inputs(args.dumps)
    report = analyze(nodes, height=args.height, powers=powers)
    if args.json:
        json.dump(report, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render_report(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
