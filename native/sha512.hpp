// Self-contained SHA-512 (FIPS 180-4) + the ed25519 "k scalar"
// helper: SHA-512(R || A || msg) reduced mod the ed25519 group order
// L.  Used to batch the host-side prep of the TPU batch verifier.
#pragma once

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace sha512 {

struct Ctx {
    uint64_t state[8];
    uint64_t bitlen_lo;      // messages here are far below 2^64 bits
    uint8_t buf[128];
    size_t buflen;
};

static const uint64_t K[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL,
    0xb5c0fbcfec4d3b2fULL, 0xe9b5dba58189dbbcULL,
    0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL,
    0xd807aa98a3030242ULL, 0x12835b0145706fbeULL,
    0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL,
    0x9bdc06a725c71235ULL, 0xc19bf174cf692694ULL,
    0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL,
    0x2de92c6f592b0275ULL, 0x4a7484aa6ea6e483ULL,
    0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL,
    0xb00327c898fb213fULL, 0xbf597fc7beef0ee4ULL,
    0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL,
    0x27b70a8546d22ffcULL, 0x2e1b21385c26c926ULL,
    0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL,
    0x81c2c92e47edaee6ULL, 0x92722c851482353bULL,
    0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL,
    0xd192e819d6ef5218ULL, 0xd69906245565a910ULL,
    0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL,
    0x2748774cdf8eeb99ULL, 0x34b0bcb5e19b48a8ULL,
    0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL,
    0x748f82ee5defb2fcULL, 0x78a5636f43172f60ULL,
    0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL,
    0xbef9a3f7b2c67915ULL, 0xc67178f2e372532bULL,
    0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL,
    0x06f067aa72176fbaULL, 0x0a637dc5a2c898a6ULL,
    0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL,
    0x3c9ebe0a15c9bebcULL, 0x431d67c49c100d4cULL,
    0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL,
};

static inline uint64_t rotr(uint64_t x, int n) {
    return (x >> n) | (x << (64 - n));
}

inline void init(Ctx* c) {
    c->state[0] = 0x6a09e667f3bcc908ULL;
    c->state[1] = 0xbb67ae8584caa73bULL;
    c->state[2] = 0x3c6ef372fe94f82bULL;
    c->state[3] = 0xa54ff53a5f1d36f1ULL;
    c->state[4] = 0x510e527fade682d1ULL;
    c->state[5] = 0x9b05688c2b3e6c1fULL;
    c->state[6] = 0x1f83d9abfb41bd6bULL;
    c->state[7] = 0x5be0cd19137e2179ULL;
    c->bitlen_lo = 0;
    c->buflen = 0;
}

inline void compress(Ctx* c, const uint8_t* p) {
    uint64_t w[80];
    for (int i = 0; i < 16; i++) {
        uint64_t v = 0;
        for (int j = 0; j < 8; j++) v = (v << 8) | p[i * 8 + j];
        w[i] = v;
    }
    for (int i = 16; i < 80; i++) {
        uint64_t s0 = rotr(w[i - 15], 1) ^ rotr(w[i - 15], 8) ^
                      (w[i - 15] >> 7);
        uint64_t s1 = rotr(w[i - 2], 19) ^ rotr(w[i - 2], 61) ^
                      (w[i - 2] >> 6);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint64_t a = c->state[0], b = c->state[1], cc = c->state[2],
             d = c->state[3], e = c->state[4], f = c->state[5],
             g = c->state[6], h = c->state[7];
    for (int i = 0; i < 80; i++) {
        uint64_t S1 = rotr(e, 14) ^ rotr(e, 18) ^ rotr(e, 41);
        uint64_t ch = (e & f) ^ (~e & g);
        uint64_t t1 = h + S1 + ch + K[i] + w[i];
        uint64_t S0 = rotr(a, 28) ^ rotr(a, 34) ^ rotr(a, 39);
        uint64_t maj = (a & b) ^ (a & cc) ^ (b & cc);
        uint64_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = cc; cc = b; b = a; a = t1 + t2;
    }
    c->state[0] += a; c->state[1] += b; c->state[2] += cc;
    c->state[3] += d; c->state[4] += e; c->state[5] += f;
    c->state[6] += g; c->state[7] += h;
}

inline void update(Ctx* c, const uint8_t* data, size_t len) {
    c->bitlen_lo += uint64_t(len) * 8;
    if (c->buflen) {
        size_t need = 128 - c->buflen;
        size_t take = len < need ? len : need;
        std::memcpy(c->buf + c->buflen, data, take);
        c->buflen += take;
        data += take;
        len -= take;
        if (c->buflen == 128) {
            compress(c, c->buf);
            c->buflen = 0;
        }
    }
    while (len >= 128) {
        compress(c, data);
        data += 128;
        len -= 128;
    }
    if (len) {
        std::memcpy(c->buf, data, len);
        c->buflen = len;
    }
}

inline void final(Ctx* c, uint8_t out[64]) {
    uint64_t bitlen = c->bitlen_lo;
    uint8_t pad = 0x80;
    update(c, &pad, 1);
    uint8_t zero = 0;
    while (c->buflen != 112)
        update(c, &zero, 1);
    // 128-bit length; high 8 bytes are zero for our input sizes
    std::memset(c->buf + 112, 0, 8);
    for (int i = 0; i < 8; i++)
        c->buf[120 + i] = uint8_t(bitlen >> (56 - 8 * i));
    compress(c, c->buf);
    for (int i = 0; i < 8; i++)
        for (int j = 0; j < 8; j++)
            out[i * 8 + j] = uint8_t(c->state[i] >> (56 - 8 * j));
}

inline void hash(const uint8_t* data, size_t len, uint8_t out[64]) {
    Ctx c;
    init(&c);
    update(&c, data, len);
    final(&c, out);
}

// ---------------------------------------------------------------------------
// reduce a 512-bit little-endian value mod the ed25519 group order
// L = 2^252 + 27742317777372353535851937790883648493, via Barrett
// reduction (HAC 14.42) with b = 2^64, k = 4:
//   mu = floor(b^8 / L)            (5 limbs, precomputed)
//   q  = ((x >> 64*(k-1)) * mu) >> 64*(k+1)
//   r  = (x - q*L) mod b^(k+1); then at most a few subtractions of L.

static const uint64_t L_LIMBS[4] = {
    0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL,
    0x0000000000000000ULL, 0x1000000000000000ULL,
};
static const uint64_t MU_LIMBS[5] = {   // floor(2^512 / L)
    0xed9ce5a30a2c131bULL, 0x2106215d086329a7ULL,
    0xffffffffffffffebULL, 0xffffffffffffffffULL,
    0x000000000000000fULL,
};

// out[no] = a[na] * b[nb] (schoolbook, truncated to no limbs)
inline void mul_trunc(const uint64_t* a, int na, const uint64_t* b,
                      int nb, uint64_t* out, int no) {
    for (int i = 0; i < no; i++) out[i] = 0;
    for (int i = 0; i < na; i++) {
        unsigned __int128 carry = 0;
        for (int j = 0; j < nb && i + j < no; j++) {
            unsigned __int128 cur = (unsigned __int128)a[i] * b[j] +
                                    out[i + j] + (uint64_t)carry;
            out[i + j] = uint64_t(cur);
            carry = cur >> 64;
        }
        if (i + nb < no) {
            int k = i + nb;
            while (carry && k < no) {
                unsigned __int128 cur = (unsigned __int128)out[k] +
                                        (uint64_t)carry;
                out[k] = uint64_t(cur);
                carry = cur >> 64;
                k++;
            }
        }
    }
}

inline bool geq_l(const uint64_t x[4]) {
    for (int i = 3; i >= 0; i--) {
        if (x[i] > L_LIMBS[i]) return true;
        if (x[i] < L_LIMBS[i]) return false;
    }
    return true;
}

inline void sub_l(uint64_t x[4]) {
    unsigned __int128 borrow = 0;
    for (int i = 0; i < 4; i++) {
        unsigned __int128 d = (unsigned __int128)x[i] - L_LIMBS[i] -
                              (uint64_t)borrow;
        x[i] = uint64_t(d);
        borrow = (d >> 64) ? 1 : 0;
    }
}

// digest: 64 bytes little-endian; out: 32 bytes little-endian (mod L)
inline void reduce_mod_l(const uint8_t digest[64], uint8_t out[32]) {
    uint64_t x[8];
    for (int i = 0; i < 8; i++) {
        uint64_t v = 0;
        for (int j = 7; j >= 0; j--) v = (v << 8) | digest[i * 8 + j];
        x[i] = v;
    }
    // q1 = x >> 64*3 (5 limbs); q2 = q1 * mu (10 limbs);
    // q3 = q2 >> 64*5 (5 limbs)
    uint64_t q2[10];
    mul_trunc(x + 3, 5, MU_LIMBS, 5, q2, 10);
    const uint64_t* q3 = q2 + 5;
    // r = (x - q3*L) mod 2^(64*5): 5-limb truncated arithmetic
    uint64_t q3l[5];
    mul_trunc(q3, 5, L_LIMBS, 4, q3l, 5);
    uint64_t r5[5];
    unsigned __int128 borrow = 0;
    for (int i = 0; i < 5; i++) {
        unsigned __int128 d = (unsigned __int128)x[i] - q3l[i] -
                              (uint64_t)borrow;
        r5[i] = uint64_t(d);
        borrow = (d >> 64) ? 1 : 0;
    }
    // Barrett guarantees 0 <= r < 3L < 2^254, so limb 4 is zero after
    // the subtractions below and r fits 4 limbs
    uint64_t r[4] = {r5[0], r5[1], r5[2], r5[3]};
    while (r5[4] || geq_l(r)) {
        unsigned __int128 b2 = 0;
        for (int i = 0; i < 4; i++) {
            unsigned __int128 d = (unsigned __int128)r[i] -
                                  L_LIMBS[i] - (uint64_t)b2;
            r[i] = uint64_t(d);
            b2 = (d >> 64) ? 1 : 0;
        }
        if (b2)
            r5[4] -= 1;     // borrow consumed the limb-4 excess
    }
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            out[i * 8 + j] = uint8_t(r[i] >> (8 * j));
}

}  // namespace sha512
