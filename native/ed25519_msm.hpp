// ed25519 batch verification on the host CPU: random-linear-combination
// batch equation + Pippenger multi-scalar multiplication.
//
// This is the CPU analog of the reference's batch verifier
// (crypto/ed25519/ed25519.go:189-222 — curve25519-voi accumulates
// (pk, msg, sig) triples and verifies them in one multi-exponentiation
// with per-signature randomizers).  The engine's TPU kernel carries the
// same equation on-device; this path serves CPU-only hosts and the
// per-signature OpenSSL loop becomes the fallback that names invalid
// entries after a batch reject.
//
//   accept  iff  [8]( -(sum z_i s_i mod L)·B + sum z_i·R_i
//                      + sum (z_i k_i mod L)·A_i ) == identity
//
// with fresh odd 128-bit z_i, k_i = SHA-512(R||A||msg) mod L, and
// ZIP-215 semantics throughout: permissive A/R decoding (y >= p
// accepted, x=0 with sign=1 accepted), canonical-S required, cofactor
// cleared by the trailing three doublings.  Differentially tested
// against the pure-Python golden model (crypto/_ed25519_ref.py
// batch_verify) in tests/test_native.py.
//
// Field arithmetic: 5x51-bit limbs in uint64, products via unsigned
// __int128 (the standard radix-51 representation).  The unified
// twisted-Edwards addition is COMPLETE for ed25519 (a = -1 is a square
// mod p, d is non-square), so bucket accumulation never needs case
// analysis even for torsion or small-order inputs.
#pragma once

#include <array>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

#include "sha512.hpp"
#include "sha512_mb.hpp"

namespace ed25519_msm {

typedef unsigned __int128 u128;

// ---------------------------------------------------------------- fe

struct fe {
    uint64_t v[5];      // radix 2^51
};

static const uint64_t MASK51 = (uint64_t(1) << 51) - 1;

inline fe fe_zero() { return fe{{0, 0, 0, 0, 0}}; }
inline fe fe_one() { return fe{{1, 0, 0, 0, 0}}; }

inline fe fe_add(const fe& a, const fe& b) {
    fe r;
    for (int i = 0; i < 5; i++) r.v[i] = a.v[i] + b.v[i];
    return r;
}

// a - b + 8p, so the result is nonnegative for any b with limbs
// < 2^54 - 152 (the laziest value the point formulas produce is
// ~2^53.1).  8p limb-wise: limb0 = 2^54 - 152, others 2^54 - 8.
inline fe fe_sub(const fe& a, const fe& b) {
    fe r;
    r.v[0] = a.v[0] + 0x3FFFFFFFFFFF68ull - b.v[0];
    r.v[1] = a.v[1] + 0x3FFFFFFFFFFFF8ull - b.v[1];
    r.v[2] = a.v[2] + 0x3FFFFFFFFFFFF8ull - b.v[2];
    r.v[3] = a.v[3] + 0x3FFFFFFFFFFFF8ull - b.v[3];
    r.v[4] = a.v[4] + 0x3FFFFFFFFFFFF8ull - b.v[4];
    return r;
}

// one carry sweep: limbs -> < 2^52 (top folds at 19)
inline void fe_carry(fe& a) {
    uint64_t c;
    for (int i = 0; i < 4; i++) {
        c = a.v[i] >> 51;
        a.v[i] &= MASK51;
        a.v[i + 1] += c;
    }
    c = a.v[4] >> 51;
    a.v[4] &= MASK51;
    a.v[0] += c * 19;
}

inline fe fe_mul(const fe& a, const fe& b) {
    u128 t0, t1, t2, t3, t4;
    uint64_t a0 = a.v[0], a1 = a.v[1], a2 = a.v[2], a3 = a.v[3],
             a4 = a.v[4];
    uint64_t b0 = b.v[0], b1 = b.v[1], b2 = b.v[2], b3 = b.v[3],
             b4 = b.v[4];
    uint64_t b1_19 = b1 * 19, b2_19 = b2 * 19, b3_19 = b3 * 19,
             b4_19 = b4 * 19;
    t0 = (u128)a0 * b0 + (u128)a1 * b4_19 + (u128)a2 * b3_19 +
         (u128)a3 * b2_19 + (u128)a4 * b1_19;
    t1 = (u128)a0 * b1 + (u128)a1 * b0 + (u128)a2 * b4_19 +
         (u128)a3 * b3_19 + (u128)a4 * b2_19;
    t2 = (u128)a0 * b2 + (u128)a1 * b1 + (u128)a2 * b0 +
         (u128)a3 * b4_19 + (u128)a4 * b3_19;
    t3 = (u128)a0 * b3 + (u128)a1 * b2 + (u128)a2 * b1 +
         (u128)a3 * b0 + (u128)a4 * b4_19;
    t4 = (u128)a0 * b4 + (u128)a1 * b3 + (u128)a2 * b2 +
         (u128)a3 * b1 + (u128)a4 * b0;
    // u128 carry chain: lazy inputs reach ~2^55.3 per limb, so the
    // column sums stay under 5 * 2^55.3 * (19 * 2^55.3) ~ 2^117.2 and
    // the top carry times 19 can exceed 2^64 — keep it wide
    fe r;
    u128 c;
    r.v[0] = (uint64_t)t0 & MASK51; c = t0 >> 51;
    t1 += c;
    r.v[1] = (uint64_t)t1 & MASK51; c = t1 >> 51;
    t2 += c;
    r.v[2] = (uint64_t)t2 & MASK51; c = t2 >> 51;
    t3 += c;
    r.v[3] = (uint64_t)t3 & MASK51; c = t3 >> 51;
    t4 += c;
    r.v[4] = (uint64_t)t4 & MASK51; c = t4 >> 51;
    u128 f = c * 19 + r.v[0];
    r.v[0] = (uint64_t)f & MASK51;
    r.v[1] += (uint64_t)(f >> 51);
    return r;
}

inline fe fe_sq(const fe& a) { return fe_mul(a, a); }

// canonical little-endian bytes (fully reduced mod p)
inline void fe_tobytes(const fe& a, uint8_t out[32]) {
    fe t = a;
    fe_carry(t);
    fe_carry(t);
    // now t < 2^52 + eps per limb and the value is < 2*p + small;
    // subtract p while >= p (at most twice)
    for (int pass = 0; pass < 2; pass++) {
        // compare against p = 2^255 - 19 top-down
        static const uint64_t P[5] = {
            MASK51 - 18, MASK51, MASK51, MASK51, MASK51};
        bool ge = true;
        for (int i = 4; i >= 0; i--) {
            if (t.v[i] > P[i]) { ge = true; break; }
            if (t.v[i] < P[i]) { ge = false; break; }
        }
        if (!ge) break;
        // t -= p  (borrow-propagating)
        uint64_t borrow = 0;
        for (int i = 0; i < 5; i++) {
            uint64_t sub = P[i] + borrow;
            if (t.v[i] >= sub) {
                t.v[i] -= sub;
                borrow = 0;
            } else {
                t.v[i] = t.v[i] + (uint64_t(1) << 51) - sub;
                borrow = 1;
            }
        }
    }
    uint64_t buf[4];
    buf[0] = t.v[0] | (t.v[1] << 51);
    buf[1] = (t.v[1] >> 13) | (t.v[2] << 38);
    buf[2] = (t.v[2] >> 26) | (t.v[3] << 25);
    buf[3] = (t.v[3] >> 39) | (t.v[4] << 12);
    std::memcpy(out, buf, 32);
}

// 255-bit little-endian load (bit 255 must be masked by the caller)
inline fe fe_frombytes(const uint8_t in[32]) {
    uint64_t buf[4];
    std::memcpy(buf, in, 32);
    fe r;
    r.v[0] = buf[0] & MASK51;
    r.v[1] = ((buf[0] >> 51) | (buf[1] << 13)) & MASK51;
    r.v[2] = ((buf[1] >> 38) | (buf[2] << 26)) & MASK51;
    r.v[3] = ((buf[2] >> 25) | (buf[3] << 39)) & MASK51;
    r.v[4] = (buf[3] >> 12) & MASK51;
    return r;
}

inline bool fe_is_zero(const fe& a) {
    uint8_t b[32];
    fe_tobytes(a, b);
    uint8_t acc = 0;
    for (int i = 0; i < 32; i++) acc |= b[i];
    return acc == 0;
}

inline bool fe_eq(const fe& a, const fe& b) {
    return fe_is_zero(fe_sub(a, b));
}

inline fe fe_neg(const fe& a) { return fe_sub(fe_zero(), a); }

inline bool fe_parity(const fe& a) {
    uint8_t b[32];
    fe_tobytes(a, b);
    return b[0] & 1;
}

// a^(2^k) in place
inline fe fe_pow2k(fe a, int k) {
    while (k--) a = fe_sq(a);
    return a;
}

// a^((p-5)/8) = a^(2^252 - 3): the sqrt-chain core
inline fe fe_pow22523(const fe& a) {
    fe x2 = fe_sq(a);                       // 2
    fe x4 = fe_sq(x2);                      // 4
    fe x8 = fe_sq(x4);                      // 8
    fe z9 = fe_mul(a, x8);                  // 9
    fe z11 = fe_mul(x2, z9);                // 11
    fe z22 = fe_sq(z11);                    // 22
    fe z_5_0 = fe_mul(z9, z22);             // 2^5 - 2^0
    fe z_10_0 = fe_mul(fe_pow2k(z_5_0, 5), z_5_0);
    fe z_20_0 = fe_mul(fe_pow2k(z_10_0, 10), z_10_0);
    fe z_40_0 = fe_mul(fe_pow2k(z_20_0, 20), z_20_0);
    fe z_50_0 = fe_mul(fe_pow2k(z_40_0, 10), z_10_0);
    fe z_100_0 = fe_mul(fe_pow2k(z_50_0, 50), z_50_0);
    fe z_200_0 = fe_mul(fe_pow2k(z_100_0, 100), z_100_0);
    fe z_250_0 = fe_mul(fe_pow2k(z_200_0, 50), z_50_0);
    return fe_mul(fe_pow2k(z_250_0, 2), a); // 2^252 - 3
}

// ---------------------------------------------------------------- ge

struct ge {              // extended twisted Edwards (a = -1)
    fe X, Y, Z, T;
};

// d and sqrt(-1) constants (little-endian canonical byte form)
static const uint8_t D_BYTES[32] = {
    0xa3, 0x78, 0x59, 0x13, 0xca, 0x4d, 0xeb, 0x75,
    0xab, 0xd8, 0x41, 0x41, 0x4d, 0x0a, 0x70, 0x00,
    0x98, 0xe8, 0x79, 0x77, 0x79, 0x40, 0xc7, 0x8c,
    0x73, 0xfe, 0x6f, 0x2b, 0xee, 0x6c, 0x03, 0x52};
static const uint8_t SQRTM1_BYTES[32] = {
    0xb0, 0xa0, 0x0e, 0x4a, 0x27, 0x1b, 0xee, 0xc4,
    0x78, 0xe4, 0x2f, 0xad, 0x06, 0x18, 0x43, 0x2f,
    0xa7, 0xd7, 0xfb, 0x3d, 0x99, 0x00, 0x4d, 0x2b,
    0x0b, 0xdf, 0xc1, 0x4f, 0x80, 0x24, 0x83, 0x2b};
// compressed basepoint: y = 4/5, sign 0
static const uint8_t B_BYTES[32] = {
    0x58, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
    0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
    0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
    0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66};

inline ge ge_identity() {
    return ge{fe_zero(), fe_one(), fe_one(), fe_zero()};
}

inline const fe& fe_d2() {
    static const fe d2 = fe_add(fe_frombytes(D_BYTES),
                                fe_frombytes(D_BYTES));
    return d2;
}

// unified extended addition (complete for a = -1, d non-square)
inline ge ge_add(const ge& p, const ge& q) {
    const fe& d2 = fe_d2();
    fe a = fe_mul(fe_sub(p.Y, p.X), fe_sub(q.Y, q.X));
    fe b = fe_mul(fe_add(p.Y, p.X), fe_add(q.Y, q.X));
    fe c = fe_mul(fe_mul(p.T, q.T), d2);
    fe dd = fe_add(fe_mul(p.Z, q.Z), fe_mul(p.Z, q.Z));
    fe e = fe_sub(b, a);
    fe f = fe_sub(dd, c);
    fe g = fe_add(dd, c);
    fe h = fe_add(b, a);
    return ge{fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h)};
}

inline ge ge_double(const ge& p) {
    fe a = fe_sq(p.X);
    fe b = fe_sq(p.Y);
    fe zz = fe_sq(p.Z);
    fe c = fe_add(zz, zz);
    fe e = fe_sub(fe_sub(fe_sq(fe_add(p.X, p.Y)), a), b);
    fe g = fe_sub(b, a);
    fe f = fe_sub(g, c);
    fe h = fe_neg(fe_add(a, b));
    return ge{fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h)};
}

// ZIP-215 permissive decompression: accepts y >= p and x = 0 with
// sign = 1; rejects only encodings with no curve point.
inline bool ge_decompress(const uint8_t s[32], ge* out) {
    uint8_t yb[32];
    std::memcpy(yb, s, 32);
    int sign = yb[31] >> 7;
    yb[31] &= 0x7F;
    fe y = fe_frombytes(yb);
    fe yy = fe_sq(y);
    fe u = fe_sub(yy, fe_one());
    fe v = fe_add(fe_mul(yy, fe_frombytes(D_BYTES)), fe_one());
    // x = u v^3 (u v^7)^((p-5)/8)
    fe v3 = fe_mul(fe_sq(v), v);
    fe v7 = fe_mul(fe_sq(v3), v);
    fe x = fe_mul(fe_mul(u, v3), fe_pow22523(fe_mul(u, v7)));
    fe vxx = fe_mul(v, fe_sq(x));
    // comparison operand: u is a lazy sub result whose limbs
    // (~2^54 + 2^51) exceed BOTH fe_neg's and fe_eq's fe_sub
    // subtrahend bound (2^54 - 152) — one carry sweep brings the
    // limbs under 2^52, inside the proven precondition, so neither
    // the u == vxx test nor the vxx + u == 0 test relies on uint64
    // wrap cancellation.  (u itself stays lazy for the fe_mul calls
    // above: fe_mul's documented input bound is ~2^55.)
    fe un = u;
    fe_carry(un);
    if (!fe_eq(vxx, un)) {
        if (fe_is_zero(fe_add(vxx, un))) {
            x = fe_mul(x, fe_frombytes(SQRTM1_BYTES));
        } else {
            return false;
        }
    }
    if ((int)fe_parity(x) != sign) x = fe_neg(x);
    out->X = x;
    out->Y = y;
    out->Z = fe_one();
    out->T = fe_mul(x, y);
    return true;
}

// [8]p == identity?  (three doublings, then X == 0 && Y == Z)
inline bool ge_is_identity_cofactored(ge p) {
    p = ge_double(ge_double(ge_double(p)));
    return fe_is_zero(p.X) && fe_eq(p.Y, p.Z);
}

// ------------------------------------------------------------ scalars

// 256x256 -> 512-bit product (little-endian bytes), then mod L via the
// existing sha512::reduce_mod_l 512-bit reducer
inline void sc_mul(const uint8_t a[32], const uint8_t b[32],
                   uint8_t out[32]) {
    uint64_t al[4], bl[4];
    std::memcpy(al, a, 32);
    std::memcpy(bl, b, 32);
    uint64_t prod[8] = {0};
    for (int i = 0; i < 4; i++) {
        u128 carry = 0;
        for (int j = 0; j < 4; j++) {
            u128 t = (u128)al[i] * bl[j] + prod[i + j] + carry;
            prod[i + j] = (uint64_t)t;
            carry = t >> 64;
        }
        prod[i + 4] = (uint64_t)carry;
    }
    uint8_t wide[64];
    std::memcpy(wide, prod, 64);
    sha512::reduce_mod_l(wide, out);
}

// L little-endian
static const uint8_t L_BYTES[32] = {
    0xed, 0xd3, 0xf5, 0x5c, 0x1a, 0x63, 0x12, 0x58,
    0xd6, 0x9c, 0xf7, 0xa2, 0xde, 0xf9, 0xde, 0x14,
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x10};

// out = (a + b) mod L  (a, b < L)
inline void sc_add(const uint8_t a[32], const uint8_t b[32],
                   uint8_t out[32]) {
    uint64_t al[4], bl[4], ll[4], r[4];
    std::memcpy(al, a, 32);
    std::memcpy(bl, b, 32);
    std::memcpy(ll, L_BYTES, 32);
    unsigned char carry = 0;
    for (int i = 0; i < 4; i++) {
        u128 t = (u128)al[i] + bl[i] + carry;
        r[i] = (uint64_t)t;
        carry = (unsigned char)(t >> 64);
    }
    // subtract L if >= L
    bool ge = carry != 0;
    if (!ge) {
        ge = true;
        for (int i = 3; i >= 0; i--) {
            if (r[i] > ll[i]) { ge = true; break; }
            if (r[i] < ll[i]) { ge = false; break; }
        }
    }
    if (ge) {
        unsigned char borrow = 0;
        for (int i = 0; i < 4; i++) {
            u128 t = (u128)r[i] - ll[i] - borrow;
            r[i] = (uint64_t)t;
            borrow = (unsigned char)((t >> 64) & 1);
        }
    }
    std::memcpy(out, r, 32);
}

// out = (L - a) mod L   (a < L)
inline void sc_neg(const uint8_t a[32], uint8_t out[32]) {
    bool zero = true;
    for (int i = 0; i < 32; i++)
        if (a[i]) { zero = false; break; }
    if (zero) {
        std::memset(out, 0, 32);
        return;
    }
    uint64_t al[4], ll[4], r[4];
    std::memcpy(al, a, 32);
    std::memcpy(ll, L_BYTES, 32);
    unsigned char borrow = 0;
    for (int i = 0; i < 4; i++) {
        u128 t = (u128)ll[i] - al[i] - borrow;
        r[i] = (uint64_t)t;
        borrow = (unsigned char)((t >> 64) & 1);
    }
    std::memcpy(out, r, 32);
}

// s < L check (canonical S, ZIP-215 requirement)
inline bool sc_is_canonical(const uint8_t s[32]) {
    for (int i = 31; i >= 0; i--) {
        if (s[i] < L_BYTES[i]) return true;
        if (s[i] > L_BYTES[i]) return false;
    }
    return false;   // s == L
}

// ------------------------------------------------------------ msm

// c-bit digit w of a 256-bit little-endian scalar
inline uint32_t sc_digit(const uint8_t s[32], int c, int w) {
    int bit = c * w;
    int byte = bit >> 3, off = bit & 7;
    uint64_t chunk = 0;
    int avail = 32 - byte;
    std::memcpy(&chunk, s + byte, avail >= 8 ? 8 : avail);
    return (uint32_t)((chunk >> off) & ((uint64_t(1) << c) - 1));
}

// Pippenger bucket MSM over (points, 256-bit scalars); window width
// adapts to n so small batches skip the bucket-sweep fixed cost.
inline ge msm(const ge* pts, const uint8_t (*scalars)[32], size_t n) {
    int c = n < 8 ? 4 : n < 64 ? 6 : n < 512 ? 8 : n < 4096 ? 10 : 12;
    int windows = (256 + c - 1) / c;
    size_t nbuckets = size_t(1) << c;
    std::vector<ge> bucket(nbuckets);
    std::vector<uint8_t> used(nbuckets);
    ge acc = ge_identity();
    for (int w = windows - 1; w >= 0; w--) {
        if (w != windows - 1)
            for (int k = 0; k < c; k++) acc = ge_double(acc);
        std::memset(used.data(), 0, nbuckets);
        for (size_t i = 0; i < n; i++) {
            uint32_t d = sc_digit(scalars[i], c, w);
            if (!d) continue;
            if (used[d]) {
                bucket[d] = ge_add(bucket[d], pts[i]);
            } else {
                bucket[d] = pts[i];
                used[d] = 1;
            }
        }
        // bucket sweep: sum_b b * bucket[b] via the running-sum trick;
        // the adds before the first occupied bucket are skipped (the
        // running sum is still the identity there)
        ge running = ge_identity();
        ge sum = ge_identity();
        bool run_any = false, sum_any = false;
        for (size_t b = nbuckets - 1; b >= 1; b--) {
            if (used[b]) {
                running = run_any ? ge_add(running, bucket[b])
                                  : bucket[b];
                run_any = true;
            }
            if (run_any) {
                sum = sum_any ? ge_add(sum, running) : running;
                sum_any = true;
            }
        }
        if (sum_any) acc = ge_add(acc, sum);
    }
    return acc;
}

// ------------------------------------------------------- batch verify

struct BatchItem {
    const uint8_t* pub;      // 32
    const uint8_t* msg;
    size_t msglen;
    const uint8_t* sig;      // 64
};

// ------------------------------------------------- pubkey decompress cache
//
// Validators repeat across blocks, so the A-point decompression (the
// sqrt chain, ~265 field muls) is the same work every height — the
// reference keeps an LRU of expanded pubkeys for exactly this reason
// (crypto/ed25519/ed25519.go:62-68, size 4096).  Here: a sharded
// direct-mapped cache of decompressed A points (32768 slots, ~6 MB —
// sized so the north-star 10k-validator set fits with headroom);
// R points are per-signature nonces and never repeat.  Purely a
// speed memo: entries are only ever (pub -> its unique decompressed
// point), so a stale or evicted entry just costs a recompute.

struct PubCacheSlot {
    bool used = false;
    uint8_t pub[32];
    fe x, y;            // affine (Z = 1; T = x*y rebuilt on get —
                        // one mul instead of 80 more bytes per slot,
                        // so a hit touches 2 cachelines, not 4)
};

struct PubCache {
    // 32k slots (~4 MB): covers the north-star 10k-validator set with
    // headroom, so steady-state heights re-verify every validator
    // from the cache; typical sets (hundreds) always fit
    static const size_t SLOTS = 32768;
    static const size_t SHARDS = 16;
    std::vector<PubCacheSlot> slots;
    std::mutex mu[SHARDS];

    PubCache() : slots(SLOTS) {}

    static size_t slot_of(const uint8_t pub[32]) {
        uint64_t h;
        std::memcpy(&h, pub, 8);
        h *= 0x9E3779B97F4A7C15ull;
        return size_t(h >> 49) & (SLOTS - 1);   // 15 bits
    }

    bool get(const uint8_t pub[32], ge* out) {
        size_t s = slot_of(pub);
        std::lock_guard<std::mutex> g(mu[s % SHARDS]);
        PubCacheSlot& sl = slots[s];
        if (!sl.used || std::memcmp(sl.pub, pub, 32) != 0)
            return false;
        out->X = sl.x;
        out->Y = sl.y;
        out->Z = fe_one();
        out->T = fe_mul(sl.x, sl.y);
        return true;
    }

    void put(const uint8_t pub[32], const ge& pt) {
        // decompressed points are affine (Z = 1) by construction
        size_t s = slot_of(pub);
        std::lock_guard<std::mutex> g(mu[s % SHARDS]);
        PubCacheSlot& sl = slots[s];
        std::memcpy(sl.pub, pub, 32);
        sl.x = pt.X;
        sl.y = pt.Y;
        sl.used = true;
    }
};

inline PubCache& pub_cache() {
    static PubCache c;
    return c;
}

inline bool decompress_pub_cached(const uint8_t pub[32], ge* out) {
    PubCache& c = pub_cache();
    if (c.get(pub, out)) return true;
    if (!ge_decompress(pub, out)) return false;
    c.put(pub, *out);
    return true;
}

// thread-count default shared with the binding: hardware concurrency
// clamped to 8 (the same clamp the prep pipeline uses)
inline int default_threads() {
    unsigned hw = std::thread::hardware_concurrency();
    return hw > 8 ? 8 : (hw ? int(hw) : 1);
}

// Fan a [0, n) range out over up to nt threads (>= min_per items
// each).  Worker exceptions are caught and reported via the return
// value (false = some worker failed); a failed thread SPAWN runs that
// chunk inline instead.  fn(tid, lo, hi) must only write state that
// is disjoint per index range (or per tid).
template <typename F>
inline bool fan_out(size_t n, size_t min_per, int nt, const F& fn) {
    if (nt > 1 && n / size_t(nt) < min_per)
        nt = int(n / min_per ? n / min_per : 1);
    if (nt > 16) nt = 16;
    if (nt <= 1) {
        fn(0, size_t(0), n);
        return true;
    }
    std::atomic<bool> failed(false);
    auto body = [&](int tid, size_t lo, size_t hi) {
        try {
            fn(tid, lo, hi);
        } catch (...) {
            failed.store(true);
        }
    };
    std::vector<std::thread> ts;
    size_t chunk = (n + size_t(nt) - 1) / size_t(nt);
    for (int t = 0; t < nt; t++) {
        size_t lo = size_t(t) * chunk;
        size_t hi = lo + chunk < n ? lo + chunk : n;
        if (lo >= hi) break;
        try {
            ts.emplace_back(body, t, lo, hi);
        } catch (...) {
            body(t, lo, hi);    // spawn failed: run inline
        }
    }
    for (auto& th : ts) th.join();
    return !failed.load();
}

// 1 = batch equation holds (all signatures valid with overwhelming
// probability); 0 = reject or malformed input (caller falls back
// per-signature).  z: 16 bytes per item (random; bit 0 forced odd).
// nthreads <= 1 runs serial; otherwise the per-item preparation
// (decompress + SHA-512 + scalar muls) and the MSM both fan out over
// range chunks, each MSM thread computing a partial result that is
// combined with plain group additions — the GIL is already released
// by the binding, so worker threads scale on multi-core hosts.
// Never throws: any internal failure (allocation, worker exception)
// retries serially, and a top-level failure rejects the batch, which
// just routes the caller to the per-signature path.
inline int batch_verify_inner(const std::vector<BatchItem>& items,
                              const uint8_t* z, int nthreads) {
    size_t n = items.size();
    if (n == 0) return 1;
    size_t total = 2 * n + 1;
    std::vector<ge> pts(total);
    std::vector<uint8_t> scal(total * 32);      // 32 bytes per point
    std::vector<std::array<uint8_t, 32>> zs(n); // z_i * s_i
    std::vector<uint8_t> bad(n, 0);

    auto prepare = [&](int, size_t lo, size_t hi) {
        uint8_t digest[64], k[32], zk[32], si[32];
        for (size_t i = lo; i < hi; i++) {
            const BatchItem& it = items[i];
            ge A, R;
            if (!sc_is_canonical(it.sig + 32) ||
                !decompress_pub_cached(it.pub, &A) ||
                !ge_decompress(it.sig, &R)) {
                bad[i] = 1;
                continue;
            }
            uint8_t zi[32] = {0};
            std::memcpy(zi, z + 16 * i, 16);
            zi[0] |= 1;
            // k_i = SHA-512(R || A || msg) mod L
            sha512::Ctx c;
            sha512::init(&c);
            sha512::update(&c, it.sig, 32);
            sha512::update(&c, it.pub, 32);
            sha512::update(&c, it.msg, it.msglen);
            sha512::final(&c, digest);
            sha512::reduce_mod_l(digest, k);
            std::memcpy(si, it.sig + 32, 32);
            sc_mul(zi, si, zs[i].data());
            sc_mul(zi, k, zk);
            pts[2 * i] = R;
            std::memcpy(&scal[(2 * i) * 32], zi, 32);
            pts[2 * i + 1] = A;
            std::memcpy(&scal[(2 * i + 1) * 32], zk, 32);
        }
    };
    if (!fan_out(n, 32, nthreads, prepare)) {
        if (nthreads > 1)
            return batch_verify_inner(items, z, 1);
        return 0;
    }
    for (size_t i = 0; i < n; i++)
        if (bad[i]) return 0;

    uint8_t s_sum[32] = {0};
    for (size_t i = 0; i < n; i++)
        sc_add(s_sum, zs[i].data(), s_sum);
    ge Bp;
    ge_decompress(B_BYTES, &Bp);
    uint8_t neg_s[32];
    sc_neg(s_sum, neg_s);
    pts[2 * n] = Bp;
    std::memcpy(&scal[(2 * n) * 32], neg_s, 32);

    auto scal_at = [&](size_t i) {
        return reinterpret_cast<const uint8_t(*)[32]>(&scal[i * 32]);
    };
    int nt = nthreads;
    if (nt > 1 && total / size_t(nt) < 128) nt = 1;
    if (nt <= 1)
        return ge_is_identity_cofactored(
                   msm(pts.data(), scal_at(0), total))
                   ? 1
                   : 0;
    size_t npart = size_t(nt);
    std::vector<ge> part(npart, ge_identity());
    bool ok = fan_out(total, 128, nt,
                      [&](int tid, size_t lo, size_t hi) {
        part[size_t(tid)] = msm(pts.data() + lo, scal_at(lo), hi - lo);
    });
    if (!ok)
        return batch_verify_inner(items, z, 1);
    ge r = part[0];
    for (size_t t = 1; t < npart; t++) r = ge_add(r, part[t]);
    return ge_is_identity_cofactored(r) ? 1 : 0;
}

inline int batch_verify(const std::vector<BatchItem>& items,
                        const uint8_t* z, int nthreads = 1) {
    try {
        return batch_verify_inner(items, z, nthreads);
    } catch (...) {
        return 0;       // reject -> caller's per-signature fallback
    }
}

// ===================================================================
// Tile kernel (KERNEL_NOTES round 6): the per-tile entry behind the
// overlapped verification pipeline (crypto/pipeline.py).  The legacy
// batch_verify above is preserved byte-for-byte as the monolithic
// comparison arm (perf_lab ed25519_pipelined_dispatch) and the
// fallback for modules built before the tile entries existed; the
// kernel-geometry improvements below are tile-path only until the
// round-7 unification pass:
//
//   * dedicated squaring (fe_sqr: 15 wide products vs fe_mul's 25)
//     through the decompression sqrt chain — the chain is ~95%
//     squarings, and R-point decompression is ~1/3 of the e2e path;
//   * signed-digit Pippenger windows (digits in (-2^(c-1), 2^(c-1)]):
//     half the buckets, so the per-window sweep — the cost tiling
//     MULTIPLIES, one sweep per tile instead of one per batch — is
//     halved, which is what makes a tiled pass cheaper than the
//     monolithic MSM instead of ~10% dearer;
//   * mixed addition for bucket accumulation (decompressed inputs are
//     affine, Z = 1: one field mul saved per point add);
//   * a packed-blob calling convention (pubs/msgs/lens/sigs as four
//     contiguous buffers) so a 10k-sig burst does not pay 30k
//     PyObject extractions per dispatch.

inline fe fe_sqr(const fe& a) {
    uint64_t a0 = a.v[0], a1 = a.v[1], a2 = a.v[2], a3 = a.v[3],
             a4 = a.v[4];
    uint64_t a1_2 = a1 * 2, a3_19 = a3 * 19, a4_19 = a4 * 19;
    u128 t0 = (u128)a0 * a0 + (u128)a1_2 * a4_19 +
              (u128)(a2 * 2) * a3_19;
    u128 t1 = (u128)a0 * (a1 * 2) + (u128)(a2 * 2) * a4_19 +
              (u128)a3 * a3_19;
    u128 t2 = (u128)a0 * (a2 * 2) + (u128)a1 * a1 +
              (u128)(a3 * 2) * a4_19;
    u128 t3 = (u128)a0 * (a3 * 2) + (u128)a1_2 * a2 +
              (u128)a4 * a4_19;
    u128 t4 = (u128)a0 * (a4 * 2) + (u128)a1_2 * a3 +
              (u128)a2 * a2;
    fe r;
    u128 c;
    r.v[0] = (uint64_t)t0 & MASK51; c = t0 >> 51;
    t1 += c;
    r.v[1] = (uint64_t)t1 & MASK51; c = t1 >> 51;
    t2 += c;
    r.v[2] = (uint64_t)t2 & MASK51; c = t2 >> 51;
    t3 += c;
    r.v[3] = (uint64_t)t3 & MASK51; c = t3 >> 51;
    t4 += c;
    r.v[4] = (uint64_t)t4 & MASK51; c = t4 >> 51;
    u128 f = c * 19 + r.v[0];
    r.v[0] = (uint64_t)f & MASK51;
    r.v[1] += (uint64_t)(f >> 51);
    return r;
}

inline fe fe_pow2k_sqr(fe a, int k) {
    while (k--) a = fe_sqr(a);
    return a;
}

inline fe fe_pow22523_sqr(const fe& a) {
    fe x2 = fe_sqr(a);
    fe x4 = fe_sqr(x2);
    fe x8 = fe_sqr(x4);
    fe z9 = fe_mul(a, x8);
    fe z11 = fe_mul(x2, z9);
    fe z22 = fe_sqr(z11);
    fe z_5_0 = fe_mul(z9, z22);
    fe z_10_0 = fe_mul(fe_pow2k_sqr(z_5_0, 5), z_5_0);
    fe z_20_0 = fe_mul(fe_pow2k_sqr(z_10_0, 10), z_10_0);
    fe z_40_0 = fe_mul(fe_pow2k_sqr(z_20_0, 20), z_20_0);
    fe z_50_0 = fe_mul(fe_pow2k_sqr(z_40_0, 10), z_10_0);
    fe z_100_0 = fe_mul(fe_pow2k_sqr(z_50_0, 50), z_50_0);
    fe z_200_0 = fe_mul(fe_pow2k_sqr(z_100_0, 100), z_100_0);
    fe z_250_0 = fe_mul(fe_pow2k_sqr(z_200_0, 50), z_50_0);
    return fe_mul(fe_pow2k_sqr(z_250_0, 2), a);
}

// ZIP-215 permissive decompression through the fe_sqr chain —
// identical acceptance set to ge_decompress (differentially tested
// in tests/test_verify_pipeline.py), ~17% faster.
inline bool ge_decompress_fast(const uint8_t s[32], ge* out) {
    uint8_t yb[32];
    std::memcpy(yb, s, 32);
    int sign = yb[31] >> 7;
    yb[31] &= 0x7F;
    fe y = fe_frombytes(yb);
    fe yy = fe_sqr(y);
    fe u = fe_sub(yy, fe_one());
    fe v = fe_add(fe_mul(yy, fe_frombytes(D_BYTES)), fe_one());
    fe v3 = fe_mul(fe_sqr(v), v);
    fe v7 = fe_mul(fe_sqr(v3), v);
    fe x = fe_mul(fe_mul(u, v3), fe_pow22523_sqr(fe_mul(u, v7)));
    fe vxx = fe_mul(v, fe_sqr(x));
    fe un = u;                  // same carry rationale as ge_decompress
    fe_carry(un);
    if (!fe_eq(vxx, un)) {
        if (fe_is_zero(fe_add(vxx, un))) {
            x = fe_mul(x, fe_frombytes(SQRTM1_BYTES));
        } else {
            return false;
        }
    }
    if ((int)fe_parity(x) != sign) x = fe_neg(x);
    out->X = x;
    out->Y = y;
    out->Z = fe_one();
    out->T = fe_mul(x, y);
    return true;
}

inline bool decompress_pub_cached_fast(const uint8_t pub[32],
                                       ge* out) {
    PubCache& c = pub_cache();
    if (c.get(pub, out)) return true;
    if (!ge_decompress_fast(pub, out)) return false;
    c.put(pub, *out);
    return true;
}

// Staged A-point record: affine x || y as raw limb structs (80
// bytes, process-internal representation — the blob never leaves the
// process) + 1 validity byte.  Invalid encodings mark 0 and the
// verify pass rejects them itself.
static const size_t STAGED_REC = 2 * sizeof(fe) + 1;

// Resolve a blob of pubkeys to decompressed A points — the
// pipeline's staging phase runs this for tile i+1 while tile i's MSM
// executes on the kernel worker.  Each key is resolved exactly once
// per tile (cache hit, or decompress + cache fill) and the points
// travel to the verify pass in the staged blob, so a direct-mapped
// collision never costs a second decompression in the kernel.
inline void stage_pubs(const uint8_t* pubs, size_t n, uint8_t* out) {
    PubCache& c = pub_cache();
    ge pt;
    for (size_t i = 0; i < n; i++) {
        const uint8_t* pub = pubs + i * 32;
        uint8_t* rec = out + i * STAGED_REC;
        bool ok = c.get(pub, &pt);
        if (!ok) {
            ok = ge_decompress_fast(pub, &pt);
            if (ok) c.put(pub, pt);
        }
        if (ok) {
            std::memcpy(rec, &pt.X, sizeof(fe));
            std::memcpy(rec + sizeof(fe), &pt.Y, sizeof(fe));
            rec[2 * sizeof(fe)] = 1;
        } else {
            rec[2 * sizeof(fe)] = 0;
        }
    }
}

// cached ("niels") form of an affine point: (Y-X, Y+X, 2d*T).  The
// mixed addition below consumes it with 7 field muls — one fewer
// than the unified extended add (the 2d*T product is precomputed
// once per point instead of once per bucket add), and negation is an
// index swap plus one cheap limb negation.
struct nge {
    fe ymx, ypx, t2d;
};

inline nge ge_to_niels(const ge& p) {        // p affine (Z = 1)
    return nge{fe_sub(p.Y, p.X), fe_add(p.Y, p.X),
               fe_mul(p.T, fe_d2())};
}

// unified mixed addition p + q with q in cached affine form; sign<0
// adds -q (swap the Y±X products, negate 2dT).  Complete for a = -1.
inline ge ge_madd(const ge& p, const nge& q, int sign) {
    fe a, b, c;
    if (sign > 0) {
        a = fe_mul(fe_sub(p.Y, p.X), q.ymx);
        b = fe_mul(fe_add(p.Y, p.X), q.ypx);
        c = fe_mul(p.T, q.t2d);
    } else {
        a = fe_mul(fe_sub(p.Y, p.X), q.ypx);
        b = fe_mul(fe_add(p.Y, p.X), q.ymx);
        c = fe_neg(fe_mul(p.T, q.t2d));
    }
    fe dd = fe_add(p.Z, p.Z);
    fe e = fe_sub(b, a);
    fe f = fe_sub(dd, c);
    fe g = fe_add(dd, c);
    fe h = fe_add(b, a);
    return ge{fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h)};
}

inline ge ge_neg_affine(const ge& p) {
    return ge{fe_neg(p.X), p.Y, p.Z, fe_neg(p.T)};
}

// signed-digit window width for an npts-point tile MSM (measured on
// the 1-vCPU rig: c=11 wins for full tiles >= ~4k points, c=10 for
// balanced ~3.3k-signature tiles and the partial tail; the ladder
// tracks the legacy msm() shape at small n where signed/unsigned
// behave alike)
inline int tile_window_c(size_t npts) {
    return npts < 8 ? 4 : npts < 64 ? 6 : npts < 512 ? 8
         : npts < 2048 ? 9 : npts < 8192 ? 10 : npts < 24576 ? 11
         : 12;
}

// Pippenger MSM with signed c-bit digits over AFFINE points
// (Z = 1 — decompressed inputs).  Digits lie in
// (-2^(c-1), 2^(c-1)]: half the buckets of the unsigned form, so the
// per-window bucket sweep — the fixed cost a tiled pass pays once
// per tile — is halved; bucket accumulation runs on the cached
// (niels) form at 7 muls per add.  Scalars must be < 2^253
// (everything mod L is), which keeps the top window's carry in
// range.
inline ge msm_signed(const ge* pts, const uint8_t (*scalars)[32],
                     size_t n, int c) {
    int windows = (256 + c - 1) / c;
    size_t nbuckets = size_t(1) << (c - 1);
    std::vector<ge> bucket(nbuckets);
    std::vector<uint8_t> used(nbuckets);
    std::vector<int16_t> dig(n * size_t(windows));
    std::vector<nge> npts(n);
    for (size_t i = 0; i < n; i++) {
        npts[i] = ge_to_niels(pts[i]);
        int carry = 0;
        for (int w = 0; w < windows; w++) {
            int v = int(sc_digit(scalars[i], c, w)) + carry;
            if (v > (1 << (c - 1))) {
                v -= (1 << c);
                carry = 1;
            } else {
                carry = 0;
            }
            dig[i * size_t(windows) + w] = int16_t(v);
        }
    }
    ge acc = ge_identity();
    for (int w = windows - 1; w >= 0; w--) {
        if (w != windows - 1)
            for (int k = 0; k < c; k++) acc = ge_double(acc);
        std::memset(used.data(), 0, nbuckets);
        for (size_t i = 0; i < n; i++) {
            int d = dig[i * size_t(windows) + w];
            if (!d) continue;
            size_t b = size_t(d > 0 ? d : -d) - 1;
            if (used[b]) {
                bucket[b] = ge_madd(bucket[b], npts[i], d);
            } else {
                bucket[b] = d > 0 ? pts[i] : ge_neg_affine(pts[i]);
                used[b] = 1;
            }
        }
        ge running = ge_identity();
        ge sum = ge_identity();
        bool run_any = false, sum_any = false;
        for (size_t b = nbuckets; b >= 1; b--) {
            if (used[b - 1]) {
                running = run_any ? ge_add(running, bucket[b - 1])
                                  : bucket[b - 1];
                run_any = true;
            }
            if (run_any) {
                sum = sum_any ? ge_add(sum, running) : running;
                sum_any = true;
            }
        }
        if (sum_any) acc = ge_add(acc, sum);
    }
    return acc;
}

struct TileView {            // one signature in the packed-blob layout
    const uint8_t* pub;      // 32
    const uint8_t* msg;
    size_t msglen;
    const uint8_t* sig;      // 64
};

// k_i = SHA-512(R || A || msg) mod L for every item, through the
// 8-way multi-buffer hasher where the CPU has it (vote sign-bytes in
// a tile are uniform-length, so grouping stays trivial); scalar
// SHA-512 otherwise.
inline void tile_k_scalars(const std::vector<TileView>& items,
                           uint8_t (*ks)[32]) {
    size_t n = items.size();
    size_t i = 0;
#if COMETBFT_SHA512MB_X86
    if (sha512mb::available()) {
        std::vector<uint8_t> scratch;
        uint8_t digests[8][64];
        while (i + 8 <= n) {
            size_t nb = sha512mb::block_count(64 + items[i].msglen);
            bool uniform = nb <= 128;
            for (size_t l = 1; uniform && l < 8; l++)
                uniform = sha512mb::block_count(
                    64 + items[i + l].msglen) == nb;
            if (!uniform) break;    // ragged tail: scalar below
            size_t slot = nb * 128;
            scratch.assign(slot * 8, 0);
            const uint8_t* base[8];
            for (size_t l = 0; l < 8; l++) {
                uint8_t* buf = scratch.data() + l * slot;
                const TileView& it = items[i + l];
                std::memcpy(buf, it.sig, 32);
                std::memcpy(buf + 32, it.pub, 32);
                std::memcpy(buf + 64, it.msg, it.msglen);
                sha512mb::write_padding(buf, 64 + it.msglen, nb);
                base[l] = buf;
            }
            sha512mb::hash8(base, nb, digests);
            for (size_t l = 0; l < 8; l++)
                sha512::reduce_mod_l(digests[l], ks[i + l]);
            i += 8;
        }
    }
#endif
    uint8_t digest[64];
    for (; i < n; i++) {
        const TileView& it = items[i];
        sha512::Ctx c;
        sha512::init(&c);
        sha512::update(&c, it.sig, 32);
        sha512::update(&c, it.pub, 32);
        sha512::update(&c, it.msg, it.msglen);
        sha512::final(&c, digest);
        sha512::reduce_mod_l(digest, ks[i]);
    }
}

// One pipeline tile: same RLC batch equation and ZIP-215 semantics as
// batch_verify_inner, through the tile-kernel geometry (cached
// fe_sqr decompression, signed-digit MSM, cached-form bucket adds).
// 1 = the tile's batch equation holds; 0 = reject or malformed input
// (the caller bisects WITHIN the tile).  Single-threaded by design:
// tile-level concurrency belongs to the pipeline's worker threads,
// not nested fan-out.
inline int batch_verify_tile_inner(const std::vector<TileView>& items,
                                   const uint8_t* z,
                                   const uint8_t* staged) {
    size_t n = items.size();
    if (n == 0) return 1;
    size_t total = 2 * n + 1;
    std::vector<ge> pts(total);
    std::vector<uint8_t> scal(total * 32);
    std::vector<std::array<uint8_t, 32>> ks(n);
    tile_k_scalars(items,
                   reinterpret_cast<uint8_t(*)[32]>(ks[0].data()));
    uint8_t s_sum[32] = {0};
    uint8_t zk[32], si[32], zs[32];
    for (size_t i = 0; i < n; i++) {
        const TileView& it = items[i];
        ge A, R;
        bool a_ok;
        if (staged != nullptr) {
            // staging resolved this A point already (valid byte 0 =
            // undecompressable pubkey)
            const uint8_t* rec = staged + i * STAGED_REC;
            a_ok = rec[2 * sizeof(fe)] != 0;
            if (a_ok) {
                std::memcpy(&A.X, rec, sizeof(fe));
                std::memcpy(&A.Y, rec + sizeof(fe), sizeof(fe));
                A.Z = fe_one();
                A.T = fe_mul(A.X, A.Y);
            }
        } else {
            a_ok = decompress_pub_cached_fast(it.pub, &A);
        }
        if (!sc_is_canonical(it.sig + 32) || !a_ok ||
            !ge_decompress_fast(it.sig, &R))
            return 0;
        uint8_t zi[32] = {0};
        std::memcpy(zi, z + 16 * i, 16);
        zi[0] |= 1;
        std::memcpy(si, it.sig + 32, 32);
        sc_mul(zi, si, zs);
        sc_add(s_sum, zs, s_sum);
        sc_mul(zi, ks[i].data(), zk);
        pts[2 * i] = R;
        std::memcpy(&scal[(2 * i) * 32], zi, 32);
        pts[2 * i + 1] = A;
        std::memcpy(&scal[(2 * i + 1) * 32], zk, 32);
    }
    ge Bp;
    ge_decompress_fast(B_BYTES, &Bp);
    uint8_t neg_s[32];
    sc_neg(s_sum, neg_s);
    pts[2 * n] = Bp;
    std::memcpy(&scal[(2 * n) * 32], neg_s, 32);
    const uint8_t(*sc)[32] =
        reinterpret_cast<const uint8_t(*)[32]>(scal.data());
    ge r = msm_signed(pts.data(), sc, total, tile_window_c(total));
    return ge_is_identity_cofactored(r) ? 1 : 0;
}

inline int batch_verify_tile(const std::vector<TileView>& items,
                             const uint8_t* z,
                             const uint8_t* staged = nullptr) {
    try {
        return batch_verify_tile_inner(items, z, staged);
    } catch (...) {
        return 0;       // reject -> caller's per-signature fallback
    }
}

}  // namespace ed25519_msm
