// Self-contained SHA-256 (FIPS 180-4).  Written from the spec for the
// merkle/native module — no external crypto dependency (the build
// image ships no OpenSSL headers).
#pragma once

#include <cstdint>
#include <cstring>
#include <cstddef>

#include "sha256_ni.hpp"

namespace sha256 {

struct Ctx {
    uint32_t state[8];
    uint64_t bitlen;
    uint8_t buf[64];
    size_t buflen;
};

static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
    0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
    0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
    0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
    0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
    0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
    0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
    0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
    0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
};

static inline uint32_t rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
}

inline void init(Ctx* c) {
    c->state[0] = 0x6a09e667; c->state[1] = 0xbb67ae85;
    c->state[2] = 0x3c6ef372; c->state[3] = 0xa54ff53a;
    c->state[4] = 0x510e527f; c->state[5] = 0x9b05688c;
    c->state[6] = 0x1f83d9ab; c->state[7] = 0x5be0cd19;
    c->bitlen = 0;
    c->buflen = 0;
}

inline void compress_scalar(Ctx* c, const uint8_t* p) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
        w[i] = (uint32_t(p[i * 4]) << 24) | (uint32_t(p[i * 4 + 1]) << 16) |
               (uint32_t(p[i * 4 + 2]) << 8) | uint32_t(p[i * 4 + 3]);
    for (int i = 16; i < 64; i++) {
        uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^
                      (w[i - 15] >> 3);
        uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^
                      (w[i - 2] >> 10);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = c->state[0], b = c->state[1], cc = c->state[2],
             d = c->state[3], e = c->state[4], f = c->state[5],
             g = c->state[6], h = c->state[7];
    for (int i = 0; i < 64; i++) {
        uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
        uint32_t ch = (e & f) ^ (~e & g);
        uint32_t t1 = h + S1 + ch + K[i] + w[i];
        uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
        uint32_t maj = (a & b) ^ (a & cc) ^ (b & cc);
        uint32_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = cc; cc = b; b = a; a = t1 + t2;
    }
    c->state[0] += a; c->state[1] += b; c->state[2] += cc;
    c->state[3] += d; c->state[4] += e; c->state[5] += f;
    c->state[6] += g; c->state[7] += h;
}

inline void compress(Ctx* c, const uint8_t* p) {
#if COMETBFT_SHA_NI_POSSIBLE
    static const bool ni = sha256ni::supported();
    if (ni) {
        sha256ni::compress(c->state, p);
        return;
    }
#endif
    compress_scalar(c, p);
}

inline void update(Ctx* c, const uint8_t* data, size_t len) {
    c->bitlen += uint64_t(len) * 8;
    if (c->buflen) {
        size_t need = 64 - c->buflen;
        size_t take = len < need ? len : need;
        std::memcpy(c->buf + c->buflen, data, take);
        c->buflen += take;
        data += take;
        len -= take;
        if (c->buflen == 64) {
            compress(c, c->buf);
            c->buflen = 0;
        }
    }
    while (len >= 64) {
        compress(c, data);
        data += 64;
        len -= 64;
    }
    if (len) {
        std::memcpy(c->buf, data, len);
        c->buflen = len;
    }
}

inline void final(Ctx* c, uint8_t out[32]) {
    uint64_t bitlen = c->bitlen;
    uint8_t pad = 0x80;
    update(c, &pad, 1);
    uint8_t zero = 0;
    while (c->buflen != 56)
        update(c, &zero, 1);  // bitlen counter is advanced but unused
    uint8_t lenbuf[8];
    for (int i = 0; i < 8; i++)
        lenbuf[i] = uint8_t(bitlen >> (56 - 8 * i));
    // write the length block directly (update would change bitlen)
    std::memcpy(c->buf + 56, lenbuf, 8);
    compress(c, c->buf);
    for (int i = 0; i < 8; i++) {
        out[i * 4] = uint8_t(c->state[i] >> 24);
        out[i * 4 + 1] = uint8_t(c->state[i] >> 16);
        out[i * 4 + 2] = uint8_t(c->state[i] >> 8);
        out[i * 4 + 3] = uint8_t(c->state[i]);
    }
}

inline void hash(const uint8_t* data, size_t len, uint8_t out[32]) {
    Ctx c;
    init(&c);
    update(&c, data, len);
    final(&c, out);
}

// hash of prefix-byte + payload (merkle leaf) without copying
inline void hash_prefixed(uint8_t prefix, const uint8_t* data,
                          size_t len, uint8_t out[32]) {
    Ctx c;
    init(&c);
    update(&c, &prefix, 1);
    update(&c, data, len);
    final(&c, out);
}

}  // namespace sha256
