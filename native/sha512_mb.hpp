// 8-way multi-buffer SHA-512 (AVX-512): eight independent messages
// hashed in the 64-bit lanes of ZMM registers.  This is the standard
// wide-lane construction (one logical SHA-512 round executed on 8
// lanes at once) — the batch verifier's k = SHA-512(R||A||msg) prep
// is embarrassingly parallel across signatures, and the scalar loop
// alone (~9 ms at 10k sigs) blows the < 5 ms end-to-end budget.
// Runtime-gated on AVX-512F; callers fall back to sha512::hash.
#pragma once

#include <cstddef>
#include <cstdint>
#include <cstring>

#include "sha512.hpp"

#if defined(__x86_64__) || defined(_M_X64)
#define COMETBFT_SHA512MB_X86 1
#include <immintrin.h>
#endif

namespace sha512mb {

inline bool available() {
#if COMETBFT_SHA512MB_X86
    return __builtin_cpu_supports("avx512f");
#else
    return false;
#endif
}

// number of 128-byte blocks for a total message length (bytes)
inline size_t block_count(size_t total_len) {
    return (total_len + 17 + 127) / 128;
}

// write the FIPS-180-4 padding for a message already copied at buf
// (buf must be zeroed, nblocks*128 bytes)
inline void write_padding(uint8_t* buf, size_t total_len,
                          size_t nblocks) {
    buf[total_len] = 0x80;
    uint64_t bitlen = uint64_t(total_len) * 8;
    uint8_t* p = buf + nblocks * 128 - 8;
    for (int i = 0; i < 8; i++)
        p[i] = uint8_t(bitlen >> (56 - 8 * i));
}

#if COMETBFT_SHA512MB_X86

#define MB_TARGET __attribute__((target("avx512f")))

MB_TARGET static inline __m512i mb_ror(__m512i x, int n) {
    return _mm512_or_si512(_mm512_srli_epi64(x, n),
                           _mm512_slli_epi64(x, 64 - n));
}

MB_TARGET static inline __m512i mb_shr(__m512i x, int n) {
    return _mm512_srli_epi64(x, n);
}

MB_TARGET static inline __m512i mb_add(__m512i a, __m512i b) {
    return _mm512_add_epi64(a, b);
}

MB_TARGET static inline __m512i mb_xor3(__m512i a, __m512i b,
                                        __m512i c) {
    return _mm512_xor_si512(_mm512_xor_si512(a, b), c);
}

// hash 8 equal-block-count messages: lane l's padded message starts
// at base[l] (nblocks * 128 bytes, padding already written).  Digests
// out as 64 big-endian bytes per lane.
MB_TARGET inline void hash8(const uint8_t* const base[8],
                            size_t nblocks, uint8_t out[8][64]) {
    static const uint64_t H0[8] = {
        0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL,
        0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
        0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
        0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL,
    };
    __m512i h[8];
    for (int i = 0; i < 8; i++) h[i] = _mm512_set1_epi64(int64_t(H0[i]));

    alignas(64) uint64_t lanes[8];
    for (size_t blk = 0; blk < nblocks; blk++) {
        __m512i w[16];
        for (int t = 0; t < 16; t++) {
            for (int l = 0; l < 8; l++) {
                uint64_t v;
                std::memcpy(&v, base[l] + blk * 128 + t * 8, 8);
                lanes[l] = __builtin_bswap64(v);
            }
            w[t] = _mm512_load_si512(
                reinterpret_cast<const void*>(lanes));
        }
        __m512i a = h[0], b = h[1], c = h[2], d = h[3];
        __m512i e = h[4], f = h[5], g = h[6], hh = h[7];
        for (int t = 0; t < 80; t++) {
            if (t >= 16) {
                __m512i w15 = w[(t - 15) & 15], w2 = w[(t - 2) & 15];
                __m512i s0 = mb_xor3(mb_ror(w15, 1), mb_ror(w15, 8),
                                     mb_shr(w15, 7));
                __m512i s1 = mb_xor3(mb_ror(w2, 19), mb_ror(w2, 61),
                                     mb_shr(w2, 6));
                w[t & 15] = mb_add(mb_add(w[t & 15], s0),
                                   mb_add(w[(t - 7) & 15], s1));
            }
            __m512i S1 = mb_xor3(mb_ror(e, 14), mb_ror(e, 18),
                                 mb_ror(e, 41));
            __m512i ch = _mm512_xor_si512(
                _mm512_and_si512(e, f),
                _mm512_andnot_si512(e, g));
            __m512i t1 = mb_add(
                mb_add(hh, S1),
                mb_add(mb_add(ch, _mm512_set1_epi64(
                    int64_t(sha512::K[t]))), w[t & 15]));
            __m512i S0 = mb_xor3(mb_ror(a, 28), mb_ror(a, 34),
                                 mb_ror(a, 39));
            __m512i maj = mb_xor3(_mm512_and_si512(a, b),
                                  _mm512_and_si512(a, c),
                                  _mm512_and_si512(b, c));
            __m512i t2 = mb_add(S0, maj);
            hh = g; g = f; f = e; e = mb_add(d, t1);
            d = c; c = b; b = a; a = mb_add(t1, t2);
        }
        h[0] = mb_add(h[0], a); h[1] = mb_add(h[1], b);
        h[2] = mb_add(h[2], c); h[3] = mb_add(h[3], d);
        h[4] = mb_add(h[4], e); h[5] = mb_add(h[5], f);
        h[6] = mb_add(h[6], g); h[7] = mb_add(h[7], hh);
    }
    for (int i = 0; i < 8; i++) {
        _mm512_store_si512(reinterpret_cast<void*>(lanes), h[i]);
        for (int l = 0; l < 8; l++)
            for (int j = 0; j < 8; j++)
                out[l][i * 8 + j] = uint8_t(lanes[l] >> (56 - 8 * j));
    }
}

#undef MB_TARGET

#endif  // COMETBFT_SHA512MB_X86

}  // namespace sha512mb
