// cometbft_tpu._native — C++ fast paths for the host runtime.
//
// Reference parity note: the reference engine is Go with one native
// dep (blst); this build keeps the hot host-side hashing in C++
// instead.  Implements the RFC-6962-style merkle tree of
// crypto/merkle/tree.go (leaf prefix 0x00, inner prefix 0x01,
// getSplitPoint recursion) and batch SHA-256 for tx/part hashing —
// the (f) hot loop in the survey's hot-path list.
//
// Built by cometbft_tpu/crypto/_native_loader.py (g++ -O3); the
// Python implementations remain the fallback when no compiler is
// available.
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <functional>
#include <thread>
#include <vector>

#include "sha256.hpp"
#include "sha512.hpp"
#include "sha512_mb.hpp"
#include "ed25519_msm.hpp"
#include "bls12381.hpp"
#include "chacha20poly1305.hpp"

namespace {

constexpr uint8_t kLeafPrefix = 0x00;
constexpr uint8_t kInnerPrefix = 0x01;

struct Slice {
    const uint8_t* p;
    Py_ssize_t n;
};

size_t split_point(size_t n) {
    // largest power of two strictly less than n (tree.go:89)
    size_t b = 1;
    while (b * 2 < n) b *= 2;
    return b;
}

void inner_hash(const uint8_t l[32], const uint8_t r[32],
                uint8_t out[32]) {
    sha256::Ctx c;
    sha256::init(&c);
    sha256::update(&c, &kInnerPrefix, 1);
    sha256::update(&c, l, 32);
    sha256::update(&c, r, 32);
    sha256::final(&c, out);
}

void tree_hash(const std::vector<Slice>& items, size_t lo, size_t hi,
               uint8_t out[32]) {
    size_t n = hi - lo;
    if (n == 1) {
        sha256::hash_prefixed(kLeafPrefix, items[lo].p,
                              size_t(items[lo].n), out);
        return;
    }
    size_t k = split_point(n);
    uint8_t left[32], right[32];
    tree_hash(items, lo, lo + k, left);
    tree_hash(items, lo + k, hi, right);
    inner_hash(left, right, out);
}

bool collect(PyObject* seq_in, std::vector<Slice>* items,
             PyObject** fast_out) {
    PyObject* fast = PySequence_Fast(seq_in, "expected a sequence");
    if (!fast) return false;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    items->reserve(size_t(n));
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* it = PySequence_Fast_GET_ITEM(fast, i);
        char* buf;
        Py_ssize_t len;
        if (PyBytes_AsStringAndSize(it, &buf, &len) < 0) {
            Py_DECREF(fast);
            return false;
        }
        items->push_back(
            {reinterpret_cast<const uint8_t*>(buf), len});
    }
    *fast_out = fast;
    return true;
}

PyObject* merkle_root(PyObject*, PyObject* arg) {
    std::vector<Slice> items;
    PyObject* fast;
    if (!collect(arg, &items, &fast)) return nullptr;
    uint8_t out[32];
    if (items.empty()) {
        sha256::hash(nullptr, 0, out);
    } else {
        tree_hash(items, 0, items.size(), out);
    }
    Py_DECREF(fast);
    return PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(out), 32);
}

PyObject* leaf_hashes(PyObject*, PyObject* arg) {
    // concatenated 32-byte RFC-6962 leaf hashes
    std::vector<Slice> items;
    PyObject* fast;
    if (!collect(arg, &items, &fast)) return nullptr;
    PyObject* out =
        PyBytes_FromStringAndSize(nullptr, Py_ssize_t(items.size()) * 32);
    if (!out) {
        Py_DECREF(fast);
        return nullptr;
    }
    uint8_t* p =
        reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out));
    for (size_t i = 0; i < items.size(); i++)
        sha256::hash_prefixed(kLeafPrefix, items[i].p,
                              size_t(items[i].n), p + i * 32);
    Py_DECREF(fast);
    return out;
}

PyObject* sha256_many(PyObject*, PyObject* arg) {
    // concatenated plain SHA-256 digests (tx hashing)
    std::vector<Slice> items;
    PyObject* fast;
    if (!collect(arg, &items, &fast)) return nullptr;
    PyObject* out =
        PyBytes_FromStringAndSize(nullptr, Py_ssize_t(items.size()) * 32);
    if (!out) {
        Py_DECREF(fast);
        return nullptr;
    }
    uint8_t* p =
        reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out));
    for (size_t i = 0; i < items.size(); i++)
        sha256::hash(items[i].p, size_t(items[i].n), p + i * 32);
    Py_DECREF(fast);
    return out;
}

PyObject* sha512_many(PyObject*, PyObject* arg) {
    // concatenated 64-byte SHA-512 digests
    std::vector<Slice> items;
    PyObject* fast;
    if (!collect(arg, &items, &fast)) return nullptr;
    PyObject* out =
        PyBytes_FromStringAndSize(nullptr, Py_ssize_t(items.size()) * 64);
    if (!out) {
        Py_DECREF(fast);
        return nullptr;
    }
    uint8_t* p = reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out));
    for (size_t i = 0; i < items.size(); i++)
        sha512::hash(items[i].p, size_t(items[i].n), p + i * 64);
    Py_DECREF(fast);
    return out;
}

PyObject* ed25519_kscalars(PyObject*, PyObject* arg) {
    // per item: SHA-512(item) reduced mod the ed25519 group order L,
    // as concatenated 32-byte little-endian scalars (the batch
    // verifier's k = H(R || A || msg) host-prep hot loop)
    std::vector<Slice> items;
    PyObject* fast;
    if (!collect(arg, &items, &fast)) return nullptr;
    PyObject* out =
        PyBytes_FromStringAndSize(nullptr, Py_ssize_t(items.size()) * 32);
    if (!out) {
        Py_DECREF(fast);
        return nullptr;
    }
    uint8_t* p = reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out));
    uint8_t digest[64];
    for (size_t i = 0; i < items.size(); i++) {
        sha512::hash(items[i].p, size_t(items[i].n), digest);
        sha512::reduce_mod_l(digest, p + i * 32);
    }
    Py_DECREF(fast);
    return out;
}

// ed25519_prep(items, m, b_bytes, identity_bytes) ->
//   (a_b, r_b, s_w8, k_w8, pre_bad)
// items: sequence of (pub, msg, sig) byte tuples; m: padded lane
// count (>= len(items)).  Outputs are numpy-ready buffers in the
// packed uint8 WIRE layout (1 byte per element — the host->device
// transfer is the e2e bottleneck on a tunneled TPU, and the int32
// transpose/cast now runs on-device):
//   a_b, r_b: [m, 32] uint8 (padding lanes = B / identity)
//   s_w8, k_w8: [m, 64] uint8 4-bit windows, lane-major
//   pre_bad: [m] uint8 (1 = malformed or non-canonical S)
// This is the batch verifier's entire host prep: pointers are
// extracted under the GIL (cheap), then the SHA-512 / window loop
// runs GIL-free across hardware threads — the budget (BASELINE:
// < 5 ms e2e at 10k sigs) leaves < 3 ms for all host work, and
// single-threaded SHA-512 alone is ~9 ms at 10k.
namespace prep {

struct ItemRef {
    const uint8_t* pub;
    const uint8_t* msg;
    size_t msglen;
    const uint8_t* sig;
    bool bad;
};

// L little-endian, for the canonical-S check
static const uint8_t L_LE[32] = {
    0xed, 0xd3, 0xf5, 0x5c, 0x1a, 0x63, 0x12, 0x58,
    0xd6, 0x9c, 0xf7, 0xa2, 0xde, 0xf9, 0xde, 0x14,
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x10,
};

inline void write_windows(uint8_t* row, const uint8_t le[32]) {
    for (int b = 0; b < 32; b++) {
        row[2 * b] = le[b] & 0x0F;
        row[2 * b + 1] = le[b] >> 4;
    }
}

inline void k_windows_from_digest(const uint8_t digest[64],
                                  uint8_t* kw8, Py_ssize_t lane) {
    uint8_t k_le[32];
    sha512::reduce_mod_l(digest, k_le);
    write_windows(kw8 + lane * 64, k_le);
}

#if COMETBFT_SHA512MB_X86
// pending 8-lane group of equal-block-count messages for the
// multi-buffer hasher
struct KGroup {
    size_t nblocks = 0;
    int n = 0;
    Py_ssize_t lane[8];
    const ItemRef* item[8];
};

inline void flush_group(KGroup& g, std::vector<uint8_t>& scratch,
                        uint8_t* kw8) {
    if (g.n == 0) return;
    size_t slot = g.nblocks * 128;
    scratch.assign(slot * 8, 0);
    const uint8_t* base[8];
    for (int l = 0; l < 8; l++) {
        int src = l < g.n ? l : 0;      // pad group with lane 0
        if (l < g.n) {
            uint8_t* buf = scratch.data() + size_t(l) * slot;
            const ItemRef* it = g.item[l];
            std::memcpy(buf, it->sig, 32);
            std::memcpy(buf + 32, it->pub, 32);
            std::memcpy(buf + 64, it->msg, it->msglen);
            sha512mb::write_padding(buf, 64 + it->msglen,
                                    g.nblocks);
            base[l] = buf;
        } else {
            base[l] = scratch.data() + size_t(src) * slot;
        }
    }
    uint8_t digests[8][64];
    sha512mb::hash8(base, g.nblocks, digests);
    for (int l = 0; l < g.n; l++)
        k_windows_from_digest(digests[l], kw8, g.lane[l]);
    g.n = 0;
}
#endif

// phase 2 worker: lanes [lo, hi) — canonical-S, row copies, SHA-512
// (8-way multi-buffer where AVX-512 is present), item-major windows
void lanes(const ItemRef* refs, Py_ssize_t lo, Py_ssize_t hi,
           uint8_t* a_p, uint8_t* r_p, uint8_t* sw8, uint8_t* kw8,
           uint8_t* bad_p) {
#if COMETBFT_SHA512MB_X86
    const bool use_mb = sha512mb::available();
    // groups keyed by block count (messages in one batch are nearly
    // always uniform-length vote sign-bytes, so this stays tiny)
    std::vector<KGroup> groups;
    std::vector<uint8_t> scratch;
#endif
    for (Py_ssize_t i = lo; i < hi; i++) {
        const ItemRef& it = refs[i];
        if (it.bad) {
            bad_p[i] = 1;
            continue;
        }
        const uint8_t* s_le = it.sig + 32;
        bool lt = false, gt = false;
        for (int b = 31; b >= 0; b--) {
            if (s_le[b] < L_LE[b]) { lt = true; break; }
            if (s_le[b] > L_LE[b]) { gt = true; break; }
        }
        if (!lt || gt) {     // s >= L: non-canonical
            bad_p[i] = 1;
            continue;
        }
        std::memcpy(a_p + i * 32, it.pub, 32);
        std::memcpy(r_p + i * 32, it.sig, 32);
        write_windows(sw8 + i * 64, s_le);
#if COMETBFT_SHA512MB_X86
        if (use_mb) {
            size_t nb = sha512mb::block_count(64 + it.msglen);
            if (nb <= 128) {            // > 16 KiB msgs go scalar
                KGroup* g = nullptr;
                for (auto& cand : groups)
                    if (cand.nblocks == nb) { g = &cand; break; }
                if (!g) {
                    groups.emplace_back();
                    g = &groups.back();
                    g->nblocks = nb;
                }
                g->lane[g->n] = i;
                g->item[g->n] = &it;
                if (++g->n == 8) flush_group(*g, scratch, kw8);
                continue;
            }
        }
#endif
        // scalar fallback: k = SHA-512(R || A || msg) mod L
        sha512::Ctx c;
        sha512::init(&c);
        sha512::update(&c, it.sig, 32);
        sha512::update(&c, it.pub, 32);
        sha512::update(&c, it.msg, it.msglen);
        uint8_t digest[64];
        sha512::final(&c, digest);
        k_windows_from_digest(digest, kw8, i);
    }
#if COMETBFT_SHA512MB_X86
    for (auto& g : groups) flush_group(g, scratch, kw8);
#endif
}

void run_threads(Py_ssize_t n,
                 const std::function<void(Py_ssize_t, Py_ssize_t)>& fn) {
    unsigned hw = std::thread::hardware_concurrency();
    unsigned nt = hw > 8 ? 8 : (hw ? hw : 1);
    if (nt <= 1 || n < 2048) {
        fn(0, n);
        return;
    }
    std::vector<std::thread> ts;
    Py_ssize_t chunk = (n + nt - 1) / nt;
    for (unsigned t = 0; t < nt; t++) {
        Py_ssize_t lo = Py_ssize_t(t) * chunk;
        Py_ssize_t hi = lo + chunk < n ? lo + chunk : n;
        if (lo >= hi) break;
        ts.emplace_back(fn, lo, hi);
    }
    for (auto& th : ts) th.join();
}

}  // namespace prep

PyObject* ed25519_prep(PyObject*, PyObject* args) {
    PyObject* seq_in;
    Py_ssize_t m;
    const char* b_bytes;
    Py_ssize_t b_len;
    const char* id_bytes;
    Py_ssize_t id_len;
    if (!PyArg_ParseTuple(args, "Ony#y#", &seq_in, &m, &b_bytes,
                          &b_len, &id_bytes, &id_len))
        return nullptr;
    if (b_len != 32 || id_len != 32) {
        PyErr_SetString(PyExc_ValueError, "constants must be 32 bytes");
        return nullptr;
    }
    PyObject* fast = PySequence_Fast(seq_in, "expected a sequence");
    if (!fast) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    if (n > m) {
        Py_DECREF(fast);
        PyErr_SetString(PyExc_ValueError, "m < len(items)");
        return nullptr;
    }
    PyObject* a_out = PyBytes_FromStringAndSize(nullptr, m * 32);
    PyObject* r_out = PyBytes_FromStringAndSize(nullptr, m * 32);
    PyObject* sw_out = PyBytes_FromStringAndSize(
        nullptr, Py_ssize_t(64) * m);
    PyObject* kw_out = PyBytes_FromStringAndSize(
        nullptr, Py_ssize_t(64) * m);
    PyObject* bad_out = PyBytes_FromStringAndSize(nullptr, m);
    if (!a_out || !r_out || !sw_out || !kw_out || !bad_out) {
        Py_XDECREF(a_out); Py_XDECREF(r_out); Py_XDECREF(sw_out);
        Py_XDECREF(kw_out); Py_XDECREF(bad_out); Py_DECREF(fast);
        return nullptr;
    }
    uint8_t* a_p = reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(a_out));
    uint8_t* r_p = reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(r_out));
    uint8_t* sw_p =
        reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(sw_out));
    uint8_t* kw_p =
        reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(kw_out));
    uint8_t* bad_p =
        reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(bad_out));

    // phase 1 (GIL held): borrow data pointers out of the Python
    // objects; kept alive by `fast` + `fits` until the workers join
    std::vector<prep::ItemRef> refs;
    refs.resize(static_cast<size_t>(n));
    std::vector<PyObject*> fits;
    fits.reserve(size_t(n));
    for (Py_ssize_t i = 0; i < n; i++) {
        prep::ItemRef& ref = refs[size_t(i)];
        ref.bad = true;
        PyObject* it = PySequence_Fast_GET_ITEM(fast, i);
        PyObject* fit = PySequence_Fast(it, "item must be a tuple");
        if (!fit || PySequence_Fast_GET_SIZE(fit) != 3) {
            PyErr_Clear();
            Py_XDECREF(fit);
            continue;
        }
        fits.push_back(fit);
        char *pub, *msg, *sig;
        Py_ssize_t publen, msglen, siglen;
        if (PyBytes_AsStringAndSize(PySequence_Fast_GET_ITEM(fit, 0),
                                    &pub, &publen) < 0 ||
            PyBytes_AsStringAndSize(PySequence_Fast_GET_ITEM(fit, 1),
                                    &msg, &msglen) < 0 ||
            PyBytes_AsStringAndSize(PySequence_Fast_GET_ITEM(fit, 2),
                                    &sig, &siglen) < 0) {
            PyErr_Clear();
            continue;
        }
        if (publen != 32 || siglen != 64) continue;
        ref.pub = reinterpret_cast<uint8_t*>(pub);
        ref.msg = reinterpret_cast<uint8_t*>(msg);
        ref.msglen = size_t(msglen);
        ref.sig = reinterpret_cast<uint8_t*>(sig);
        ref.bad = false;
    }

    // phase 2 (GIL released): hash/window lanes, straight into the
    // lane-major uint8 output buffers
    {
        const prep::ItemRef* refp = refs.data();
        Py_BEGIN_ALLOW_THREADS
        // padding defaults (windows of unwritten lanes must be zero)
        std::memset(sw_p, 0, size_t(64) * size_t(m));
        std::memset(kw_p, 0, size_t(64) * size_t(m));
        for (Py_ssize_t i = 0; i < m; i++) {
            std::memcpy(a_p + i * 32, b_bytes, 32);
            std::memcpy(r_p + i * 32, id_bytes, 32);
            bad_p[i] = 0;
        }
        prep::run_threads(n, [&](Py_ssize_t lo, Py_ssize_t hi) {
            prep::lanes(refp, lo, hi, a_p, r_p, sw_p, kw_p, bad_p);
        });
        Py_END_ALLOW_THREADS
    }
    for (PyObject* fit : fits) Py_DECREF(fit);
    Py_DECREF(fast);
    PyObject* out = PyTuple_Pack(5, a_out, r_out, sw_out, kw_out,
                                 bad_out);
    Py_DECREF(a_out); Py_DECREF(r_out); Py_DECREF(sw_out);
    Py_DECREF(kw_out); Py_DECREF(bad_out);
    return out;
}

// --- BLS12-381 (see native/bls12381.hpp) -----------------------------------
// Point wire format between python and C: raw affine coordinates,
// big-endian —  G1: 96B x||y;  G2: 192B x0||x1||y0||y1;  b"" = infinity.

bool parse_g1(PyObject* obj, bls::G1* out) {
    char* buf;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(obj, &buf, &len) < 0) return false;
    const uint8_t* b = reinterpret_cast<uint8_t*>(buf);
    if (len == 0) {
        out->inf = true;
        return true;
    }
    if (len != 96) {
        PyErr_SetString(PyExc_ValueError, "bad G1 length");
        return false;
    }
    out->inf = false;
    if (!bls::fp_from_be48(b, &out->x) ||
        !bls::fp_from_be48(b + 48, &out->y)) {
        PyErr_SetString(PyExc_ValueError, "G1 coordinate >= p");
        return false;
    }
    return true;
}

bool parse_g2(PyObject* obj, bls::G2* out) {
    char* buf;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(obj, &buf, &len) < 0) return false;
    const uint8_t* b = reinterpret_cast<uint8_t*>(buf);
    if (len == 0) {
        out->inf = true;
        return true;
    }
    if (len != 192) {
        PyErr_SetString(PyExc_ValueError, "bad G2 length");
        return false;
    }
    out->inf = false;
    if (!bls::fp_from_be48(b, &out->x.c0) ||
        !bls::fp_from_be48(b + 48, &out->x.c1) ||
        !bls::fp_from_be48(b + 96, &out->y.c0) ||
        !bls::fp_from_be48(b + 144, &out->y.c1)) {
        PyErr_SetString(PyExc_ValueError, "G2 coordinate >= p");
        return false;
    }
    return true;
}

PyObject* g1_bytes(const bls::G1& p) {
    if (p.inf) return PyBytes_FromStringAndSize("", 0);
    uint8_t out[96];
    bls::fp_to_be48(p.x, out);
    bls::fp_to_be48(p.y, out + 48);
    return PyBytes_FromStringAndSize(
        reinterpret_cast<char*>(out), 96);
}

PyObject* g2_bytes(const bls::G2& p) {
    if (p.inf) return PyBytes_FromStringAndSize("", 0);
    uint8_t out[192];
    bls::fp_to_be48(p.x.c0, out);
    bls::fp_to_be48(p.x.c1, out + 48);
    bls::fp_to_be48(p.y.c0, out + 96);
    bls::fp_to_be48(p.y.c1, out + 144);
    return PyBytes_FromStringAndSize(
        reinterpret_cast<char*>(out), 192);
}

PyObject* bls_pairings_product_is_one(PyObject*, PyObject* arg) {
    PyObject* fast = PySequence_Fast(arg, "expected a sequence");
    if (!fast) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    std::vector<bls::Pair> pairs;
    pairs.reserve(size_t(n));
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* it = PySequence_Fast_GET_ITEM(fast, i);
        PyObject* fit = PySequence_Fast(it, "pair must be a tuple");
        if (!fit || PySequence_Fast_GET_SIZE(fit) != 2) {
            Py_XDECREF(fit);
            Py_DECREF(fast);
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_ValueError, "pair must have 2 items");
            return nullptr;
        }
        bls::Pair pr;
        if (!parse_g1(PySequence_Fast_GET_ITEM(fit, 0), &pr.p) ||
            !parse_g2(PySequence_Fast_GET_ITEM(fit, 1), &pr.q)) {
            Py_DECREF(fit);
            Py_DECREF(fast);
            return nullptr;
        }
        pairs.push_back(pr);
        Py_DECREF(fit);
    }
    Py_DECREF(fast);
    bool ok;
    Py_BEGIN_ALLOW_THREADS
    ok = bls::pairings_product_is_one(pairs);
    Py_END_ALLOW_THREADS
    return PyBool_FromLong(ok);
}

PyObject* bls_selftest(PyObject*, PyObject*) {
    bool ok;
    Py_BEGIN_ALLOW_THREADS
    ok = bls::selftest() && bls::selftest_psi();
    Py_END_ALLOW_THREADS
    return PyBool_FromLong(ok);
}

PyObject* bls_g1_in_subgroup(PyObject*, PyObject* arg) {
    bls::G1 p;
    if (!parse_g1(arg, &p)) return nullptr;
    bool ok;
    Py_BEGIN_ALLOW_THREADS
    ok = bls::g1_in_subgroup(p);
    Py_END_ALLOW_THREADS
    return PyBool_FromLong(ok);
}

PyObject* bls_g2_in_subgroup(PyObject*, PyObject* arg) {
    bls::G2 p;
    if (!parse_g2(arg, &p)) return nullptr;
    bool ok;
    Py_BEGIN_ALLOW_THREADS
    ok = bls::g2_in_subgroup(p);
    Py_END_ALLOW_THREADS
    return PyBool_FromLong(ok);
}

PyObject* bls_hash_to_g2(PyObject*, PyObject* args) {
    const char* msg;
    Py_ssize_t msg_len;
    const char* dst;
    Py_ssize_t dst_len;
    if (!PyArg_ParseTuple(args, "y#y#", &msg, &msg_len, &dst,
                          &dst_len))
        return nullptr;
    if (dst_len > 255) {
        PyErr_SetString(PyExc_ValueError, "DST too long");
        return nullptr;
    }
    bls::G2 r;
    Py_BEGIN_ALLOW_THREADS
    r = bls::hash_to_g2(reinterpret_cast<const uint8_t*>(msg),
                        size_t(msg_len),
                        reinterpret_cast<const uint8_t*>(dst),
                        size_t(dst_len));
    Py_END_ALLOW_THREADS
    return g2_bytes(r);
}

PyObject* bls_g1_uncompress(PyObject*, PyObject* arg) {
    char* buf;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(arg, &buf, &len) < 0) return nullptr;
    if (len != 48) {
        PyErr_SetString(PyExc_ValueError, "bad G1 compressed length");
        return nullptr;
    }
    bls::G1 p;
    int rc;
    Py_BEGIN_ALLOW_THREADS
    rc = bls::g1_uncompress(reinterpret_cast<uint8_t*>(buf), &p);
    Py_END_ALLOW_THREADS
    if (rc < 0) {
        PyErr_SetString(PyExc_ValueError, "invalid compressed G1");
        return nullptr;
    }
    if (rc == 1) Py_RETURN_NONE;
    return g1_bytes(p);
}

PyObject* bls_g2_uncompress(PyObject*, PyObject* arg) {
    char* buf;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(arg, &buf, &len) < 0) return nullptr;
    if (len != 96) {
        PyErr_SetString(PyExc_ValueError, "bad G2 compressed length");
        return nullptr;
    }
    bls::G2 p;
    int rc;
    Py_BEGIN_ALLOW_THREADS
    rc = bls::g2_uncompress(reinterpret_cast<uint8_t*>(buf), &p);
    Py_END_ALLOW_THREADS
    if (rc < 0) {
        PyErr_SetString(PyExc_ValueError, "invalid compressed G2");
        return nullptr;
    }
    if (rc == 1) Py_RETURN_NONE;
    return g2_bytes(p);
}

PyObject* bls_g1_mul(PyObject*, PyObject* args) {
    PyObject* pt_obj;
    const char* k;
    Py_ssize_t klen;
    if (!PyArg_ParseTuple(args, "Oy#", &pt_obj, &k, &klen))
        return nullptr;
    bls::G1 p;
    if (!parse_g1(pt_obj, &p)) return nullptr;
    bls::G1 r;
    Py_BEGIN_ALLOW_THREADS
    r = p.inf ? p : bls::G1_mul_be_fast(
        p, reinterpret_cast<const uint8_t*>(k), size_t(klen));
    Py_END_ALLOW_THREADS
    return g1_bytes(r);
}

PyObject* bls_g2_mul(PyObject*, PyObject* args) {
    PyObject* pt_obj;
    const char* k;
    Py_ssize_t klen;
    if (!PyArg_ParseTuple(args, "Oy#", &pt_obj, &k, &klen))
        return nullptr;
    bls::G2 p;
    if (!parse_g2(pt_obj, &p)) return nullptr;
    bls::G2 r;
    Py_BEGIN_ALLOW_THREADS
    r = p.inf ? p : bls::G2_mul_be_fast(
        p, reinterpret_cast<const uint8_t*>(k), size_t(klen));
    Py_END_ALLOW_THREADS
    return g2_bytes(r);
}

// bls_g1_sum(blob) / bls_g2_sum(blob): sum of concatenated raw affine
// points (96B / 192B each; the python side filters infinities out of
// the blob).  Jacobian accumulation — one field inversion total
// instead of one per addition — is what makes the aggregate-pubkey
// assembly O(n) *cheap* adds: ~0.5 us/point vs ~50 us for the
// python affine loop (the only O(n) residue of aggregate-commit
// verification; docs/aggregate_commits.md).
PyObject* bls_g1_sum(PyObject*, PyObject* arg) {
    char* buf;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(arg, &buf, &len) < 0) return nullptr;
    if (len % 96 != 0) {
        PyErr_SetString(PyExc_ValueError, "blob not a multiple of 96");
        return nullptr;
    }
    const uint8_t* b = reinterpret_cast<uint8_t*>(buf);
    Py_ssize_t n = len / 96;
    bls::G1 out;
    bool coord_ok = true;
    Py_BEGIN_ALLOW_THREADS
    std::vector<bls::G1> pts(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; i++) {
        pts[size_t(i)].inf = false;
        if (!bls::fp_from_be48(b + i * 96, &pts[size_t(i)].x) ||
            !bls::fp_from_be48(b + i * 96 + 48, &pts[size_t(i)].y)) {
            coord_ok = false;
            break;
        }
    }
    if (coord_ok) {
        std::vector<bls::Fp> sa(static_cast<size_t>(n) / 2 + 1);
        std::vector<bls::Fp> sb(static_cast<size_t>(n) / 2 + 1);
        out = bls::sum_affine<bls::G1, bls::Fp>(
            pts.data(), size_t(n), sa.data(), sb.data());
    }
    Py_END_ALLOW_THREADS
    if (!coord_ok) {
        PyErr_SetString(PyExc_ValueError, "G1 coordinate >= p");
        return nullptr;
    }
    return g1_bytes(out);
}

PyObject* bls_g2_sum(PyObject*, PyObject* arg) {
    char* buf;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(arg, &buf, &len) < 0) return nullptr;
    if (len % 192 != 0) {
        PyErr_SetString(PyExc_ValueError, "blob not a multiple of 192");
        return nullptr;
    }
    const uint8_t* b = reinterpret_cast<uint8_t*>(buf);
    Py_ssize_t n = len / 192;
    bls::G2 out;
    bool coord_ok = true;
    Py_BEGIN_ALLOW_THREADS
    std::vector<bls::G2> pts(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; i++) {
        bls::G2& p = pts[size_t(i)];
        p.inf = false;
        if (!bls::fp_from_be48(b + i * 192, &p.x.c0) ||
            !bls::fp_from_be48(b + i * 192 + 48, &p.x.c1) ||
            !bls::fp_from_be48(b + i * 192 + 96, &p.y.c0) ||
            !bls::fp_from_be48(b + i * 192 + 144, &p.y.c1)) {
            coord_ok = false;
            break;
        }
    }
    if (coord_ok) {
        std::vector<bls::Fp2> sa(static_cast<size_t>(n) / 2 + 1);
        std::vector<bls::Fp2> sb(static_cast<size_t>(n) / 2 + 1);
        out = bls::sum_affine<bls::G2, bls::Fp2>(
            pts.data(), size_t(n), sa.data(), sb.data());
    }
    Py_END_ALLOW_THREADS
    if (!coord_ok) {
        PyErr_SetString(PyExc_ValueError, "G2 coordinate >= p");
        return nullptr;
    }
    return g2_bytes(out);
}

PyObject* sha256_one(PyObject*, PyObject* arg) {
    char* buf;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(arg, &buf, &len) < 0) return nullptr;
    uint8_t out[32];
    sha256::hash(reinterpret_cast<const uint8_t*>(buf), size_t(len),
                 out);
    return PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(out), 32);
}

// ed25519_batch_verify(items, z) -> int
// items: sequence of (pub, msg, sig) byte tuples; z: 16*len(items)
// random bytes (one 128-bit randomizer per item, bit 0 forced odd in
// C).  Returns 1 iff the RLC batch equation holds for every item
// (ZIP-215 semantics); 0 on any malformed input or batch reject —
// the caller falls back to the per-signature path for the mask.
// The CPU analog of the reference's voi batch verifier
// (crypto/ed25519/ed25519.go:189-222); see ed25519_msm.hpp.
PyObject* ed25519_batch_verify(PyObject*, PyObject* args) {
    PyObject* seq_in;
    const char* z_bytes;
    Py_ssize_t z_len;
    if (!PyArg_ParseTuple(args, "Oy#", &seq_in, &z_bytes, &z_len))
        return nullptr;
    PyObject* fast = PySequence_Fast(seq_in, "expected a sequence");
    if (!fast) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    if (z_len != n * 16) {
        Py_DECREF(fast);
        PyErr_SetString(PyExc_ValueError,
                        "need 16 randomizer bytes per item");
        return nullptr;
    }
    std::vector<ed25519_msm::BatchItem> items;
    items.reserve(size_t(n));
    std::vector<PyObject*> fits;
    fits.reserve(size_t(n));
    bool shape_ok = true;
    for (Py_ssize_t i = 0; i < n && shape_ok; i++) {
        PyObject* it = PySequence_Fast_GET_ITEM(fast, i);
        PyObject* fit = PySequence_Fast(it, "item must be a tuple");
        if (!fit || PySequence_Fast_GET_SIZE(fit) != 3) {
            PyErr_Clear();
            Py_XDECREF(fit);
            shape_ok = false;
            break;
        }
        fits.push_back(fit);
        char *pub, *msg, *sig;
        Py_ssize_t publen, msglen, siglen;
        if (PyBytes_AsStringAndSize(PySequence_Fast_GET_ITEM(fit, 0),
                                    &pub, &publen) < 0 ||
            PyBytes_AsStringAndSize(PySequence_Fast_GET_ITEM(fit, 1),
                                    &msg, &msglen) < 0 ||
            PyBytes_AsStringAndSize(PySequence_Fast_GET_ITEM(fit, 2),
                                    &sig, &siglen) < 0) {
            PyErr_Clear();
            shape_ok = false;
            break;
        }
        if (publen != 32 || siglen != 64) {
            shape_ok = false;
            break;
        }
        items.push_back(ed25519_msm::BatchItem{
            reinterpret_cast<uint8_t*>(pub),
            reinterpret_cast<uint8_t*>(msg), size_t(msglen),
            reinterpret_cast<uint8_t*>(sig)});
    }
    int ok = 0;
    if (shape_ok) {
        const uint8_t* z = reinterpret_cast<const uint8_t*>(z_bytes);
        int nt = 0;
        const char* env = getenv("COMETBFT_TPU_MSM_THREADS");
        if (env && *env) nt = atoi(env);
        if (nt <= 0) nt = ed25519_msm::default_threads();
        if (nt > 16) nt = 16;       // fan_out's clamp; part[] sizing
        Py_BEGIN_ALLOW_THREADS
        ok = ed25519_msm::batch_verify(items, z, nt);
        Py_END_ALLOW_THREADS
    }
    for (PyObject* fit : fits) Py_DECREF(fit);
    Py_DECREF(fast);
    return PyLong_FromLong(ok);
}

// ed25519_batch_verify_tile(pubs, msgs, lens, sigs, z) -> int
// The pipeline's per-tile entry (KERNEL_NOTES round 6): packed-blob
// calling convention — pubs 32n, sigs 64n, z 16n, msgs concatenated
// with lens as n little-endian uint32 — so a tile dispatch costs four
// buffer borrows instead of 3n PyObject extractions.  Returns 1 iff
// the tile's RLC batch equation holds (ZIP-215), 0 on malformed
// input or batch reject (caller bisects within the tile).  The
// signed-digit MSM + cached fe_sqr decompression run with the GIL
// released on the pipeline's kernel worker thread.
PyObject* ed25519_batch_verify_tile(PyObject*, PyObject* args) {
    const char *pubs, *msgs, *lens, *sigs, *z_bytes;
    const char* staged = nullptr;
    Py_ssize_t pubs_len, msgs_len, lens_len, sigs_len, z_len;
    Py_ssize_t staged_len = 0;
    if (!PyArg_ParseTuple(args, "y#y#y#y#y#|y#", &pubs, &pubs_len,
                          &msgs, &msgs_len, &lens, &lens_len,
                          &sigs, &sigs_len, &z_bytes, &z_len,
                          &staged, &staged_len))
        return nullptr;
    if (lens_len % 4 != 0) {
        PyErr_SetString(PyExc_ValueError,
                        "lens must be 4 bytes per item");
        return nullptr;
    }
    Py_ssize_t n = lens_len / 4;
    if (pubs_len != n * 32 || sigs_len != n * 64 || z_len != n * 16) {
        PyErr_SetString(PyExc_ValueError,
                        "need 32 pub / 64 sig / 16 z bytes per item");
        return nullptr;
    }
    if (staged != nullptr && staged_len !=
            n * Py_ssize_t(ed25519_msm::STAGED_REC)) {
        // a mismatched staged blob is ignored, not an error: it is a
        // pure speed memo and the verify pass decompresses itself
        staged = nullptr;
    }
    std::vector<ed25519_msm::TileView> items;
    items.reserve(size_t(n));
    const uint8_t* lp = reinterpret_cast<const uint8_t*>(lens);
    size_t off = 0;
    bool shape_ok = true;
    for (Py_ssize_t i = 0; i < n; i++) {
        uint32_t ml;
        std::memcpy(&ml, lp + i * 4, 4);
        if (off + ml > size_t(msgs_len)) {
            shape_ok = false;
            break;
        }
        items.push_back(ed25519_msm::TileView{
            reinterpret_cast<const uint8_t*>(pubs) + i * 32,
            reinterpret_cast<const uint8_t*>(msgs) + off, size_t(ml),
            reinterpret_cast<const uint8_t*>(sigs) + i * 64});
        off += ml;
    }
    if (!shape_ok || off != size_t(msgs_len)) {
        PyErr_SetString(PyExc_ValueError,
                        "msgs blob does not match lens");
        return nullptr;
    }
    int ok = 0;
    const uint8_t* z = reinterpret_cast<const uint8_t*>(z_bytes);
    Py_BEGIN_ALLOW_THREADS
    ok = ed25519_msm::batch_verify_tile(
        items, z, reinterpret_cast<const uint8_t*>(staged));
    Py_END_ALLOW_THREADS
    return PyLong_FromLong(ok);
}

// ed25519_stage_pubs(pubs_blob) -> staged points blob
// Resolve a blob of 32-byte pubkeys to decompressed A points,
// GIL-free — the pipeline's staging phase runs this for tile i+1
// while tile i's MSM executes on the kernel worker.  Cache hits copy
// out; misses decompress once and fill the shared cache.  The
// returned blob (81 bytes per key: raw affine x || y limbs +
// validity byte, process-internal representation) feeds the same
// tile's ed25519_batch_verify_tile call.
PyObject* ed25519_stage_pubs(PyObject*, PyObject* arg) {
    char* buf;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(arg, &buf, &len) < 0) return nullptr;
    if (len % 32 != 0) {
        PyErr_SetString(PyExc_ValueError,
                        "blob not a multiple of 32");
        return nullptr;
    }
    Py_ssize_t n = len / 32;
    PyObject* out = PyBytes_FromStringAndSize(
        nullptr, n * Py_ssize_t(ed25519_msm::STAGED_REC));
    if (!out) return nullptr;
    uint8_t* op = reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out));
    Py_BEGIN_ALLOW_THREADS
    ed25519_msm::stage_pubs(reinterpret_cast<const uint8_t*>(buf),
                            size_t(n), op);
    Py_END_ALLOW_THREADS
    return out;
}

// chacha20poly1305_seal(key, nonce, aad, plaintext) -> ct||tag
// The p2p secret-connection frame hot path when the python
// `cryptography` package is absent (see crypto/_aead_fallback.py).
PyObject* chacha20poly1305_seal(PyObject*, PyObject* args) {
    const char *key, *nonce, *aad, *pt;
    Py_ssize_t keyl, noncel, aadl, ptl;
    if (!PyArg_ParseTuple(args, "y#y#y#y#", &key, &keyl, &nonce,
                          &noncel, &aad, &aadl, &pt, &ptl))
        return nullptr;
    if (keyl != 32 || noncel != 12) {
        PyErr_SetString(PyExc_ValueError,
                        "key must be 32 bytes, nonce 12");
        return nullptr;
    }
    PyObject* out = PyBytes_FromStringAndSize(nullptr, ptl + 16);
    if (!out) return nullptr;
    ccp::seal(reinterpret_cast<const uint8_t*>(key),
              reinterpret_cast<const uint8_t*>(nonce),
              reinterpret_cast<const uint8_t*>(aad), size_t(aadl),
              reinterpret_cast<const uint8_t*>(pt), size_t(ptl),
              reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out)));
    return out;
}

// chacha20poly1305_open(key, nonce, aad, ct_and_tag) -> plaintext
// or None on tag mismatch.
PyObject* chacha20poly1305_open(PyObject*, PyObject* args) {
    const char *key, *nonce, *aad, *ct;
    Py_ssize_t keyl, noncel, aadl, ctl;
    if (!PyArg_ParseTuple(args, "y#y#y#y#", &key, &keyl, &nonce,
                          &noncel, &aad, &aadl, &ct, &ctl))
        return nullptr;
    if (keyl != 32 || noncel != 12 || ctl < 16) {
        PyErr_SetString(PyExc_ValueError,
                        "key must be 32 bytes, nonce 12, ct >= 16");
        return nullptr;
    }
    PyObject* out = PyBytes_FromStringAndSize(nullptr, ctl - 16);
    if (!out) return nullptr;
    bool ok = ccp::open(
        reinterpret_cast<const uint8_t*>(key),
        reinterpret_cast<const uint8_t*>(nonce),
        reinterpret_cast<const uint8_t*>(aad), size_t(aadl),
        reinterpret_cast<const uint8_t*>(ct), size_t(ctl),
        reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out)));
    if (!ok) {
        Py_DECREF(out);
        Py_RETURN_NONE;
    }
    return out;
}

PyMethodDef kMethods[] = {
    {"chacha20poly1305_seal", chacha20poly1305_seal, METH_VARARGS,
     "RFC 8439 AEAD seal: (key, nonce, aad, pt) -> ct||tag"},
    {"chacha20poly1305_open", chacha20poly1305_open, METH_VARARGS,
     "RFC 8439 AEAD open: (key, nonce, aad, ct||tag) -> pt | None"},
    {"merkle_root", merkle_root, METH_O,
     "RFC-6962/CometBFT merkle root of a sequence of bytes"},
    {"leaf_hashes", leaf_hashes, METH_O,
     "concatenated 32-byte leaf hashes"},
    {"sha256_many", sha256_many, METH_O,
     "concatenated SHA-256 digests of a sequence of bytes"},
    {"sha512_many", sha512_many, METH_O,
     "concatenated SHA-512 digests of a sequence of bytes"},
    {"ed25519_kscalars", ed25519_kscalars, METH_O,
     "concatenated SHA-512(item) mod L scalars (32B LE each)"},
    {"ed25519_batch_verify", ed25519_batch_verify, METH_VARARGS,
     "RLC batch verification of (pub, msg, sig) items (ZIP-215)"},
    {"ed25519_prep", ed25519_prep, METH_VARARGS,
     "full batch-verify host prep: (items, m, B, identity) -> "
     "(a_b, r_b, s_win, k_win, pre_bad)"},
    {"ed25519_batch_verify_tile", ed25519_batch_verify_tile,
     METH_VARARGS,
     "per-tile RLC batch verification over packed blobs "
     "(pubs, msgs, lens, sigs, z[, staged]) -> 1/0"},
    {"ed25519_stage_pubs", ed25519_stage_pubs, METH_O,
     "resolve a 32n pubkey blob to a staged A-point blob "
     "(cache-backed decompression)"},
    {"bls_pairings_product_is_one", bls_pairings_product_is_one,
     METH_O, "prod e(P_i, Q_i) == 1 over raw affine pairs"},
    {"bls_selftest", bls_selftest, METH_NOARGS,
     "Frobenius + fast-final-exponentiation consistency check"},
    {"bls_g1_in_subgroup", bls_g1_in_subgroup, METH_O,
     "curve + r-order check for a raw affine G1 point"},
    {"bls_g2_in_subgroup", bls_g2_in_subgroup, METH_O,
     "curve + r-order check for a raw affine G2 point"},
    {"bls_hash_to_g2", bls_hash_to_g2, METH_VARARGS,
     "hash_to_g2(msg, dst) -> raw affine G2"},
    {"bls_g1_uncompress", bls_g1_uncompress, METH_O,
     "ZCash-flag compressed 48B -> raw affine G1 | None (infinity)"},
    {"bls_g2_uncompress", bls_g2_uncompress, METH_O,
     "ZCash-flag compressed 96B -> raw affine G2 | None (infinity)"},
    {"bls_g1_sum", bls_g1_sum, METH_O,
     "sum of concatenated raw affine G1 points"},
    {"bls_g2_sum", bls_g2_sum, METH_O,
     "sum of concatenated raw affine G2 points"},
    {"bls_g1_mul", bls_g1_mul, METH_VARARGS,
     "scalar multiple of a raw affine G1 point (k big-endian)"},
    {"bls_g2_mul", bls_g2_mul, METH_VARARGS,
     "scalar multiple of a raw affine G2 point (k big-endian)"},
    {"sha256", sha256_one, METH_O, "SHA-256 of one bytes object"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "_native",
    "C++ fast paths: merkle tree + batch SHA-256", -1, kMethods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__native(void) {
    return PyModule_Create(&kModule);
}
