// cometbft_tpu._native — C++ fast paths for the host runtime.
//
// Reference parity note: the reference engine is Go with one native
// dep (blst); this build keeps the hot host-side hashing in C++
// instead.  Implements the RFC-6962-style merkle tree of
// crypto/merkle/tree.go (leaf prefix 0x00, inner prefix 0x01,
// getSplitPoint recursion) and batch SHA-256 for tx/part hashing —
// the (f) hot loop in the survey's hot-path list.
//
// Built by cometbft_tpu/crypto/_native_loader.py (g++ -O3); the
// Python implementations remain the fallback when no compiler is
// available.
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <vector>

#include "sha256.hpp"

namespace {

constexpr uint8_t kLeafPrefix = 0x00;
constexpr uint8_t kInnerPrefix = 0x01;

struct Slice {
    const uint8_t* p;
    Py_ssize_t n;
};

size_t split_point(size_t n) {
    // largest power of two strictly less than n (tree.go:89)
    size_t b = 1;
    while (b * 2 < n) b *= 2;
    return b;
}

void inner_hash(const uint8_t l[32], const uint8_t r[32],
                uint8_t out[32]) {
    sha256::Ctx c;
    sha256::init(&c);
    sha256::update(&c, &kInnerPrefix, 1);
    sha256::update(&c, l, 32);
    sha256::update(&c, r, 32);
    sha256::final(&c, out);
}

void tree_hash(const std::vector<Slice>& items, size_t lo, size_t hi,
               uint8_t out[32]) {
    size_t n = hi - lo;
    if (n == 1) {
        sha256::hash_prefixed(kLeafPrefix, items[lo].p,
                              size_t(items[lo].n), out);
        return;
    }
    size_t k = split_point(n);
    uint8_t left[32], right[32];
    tree_hash(items, lo, lo + k, left);
    tree_hash(items, lo + k, hi, right);
    inner_hash(left, right, out);
}

bool collect(PyObject* seq_in, std::vector<Slice>* items,
             PyObject** fast_out) {
    PyObject* fast = PySequence_Fast(seq_in, "expected a sequence");
    if (!fast) return false;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    items->reserve(size_t(n));
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* it = PySequence_Fast_GET_ITEM(fast, i);
        char* buf;
        Py_ssize_t len;
        if (PyBytes_AsStringAndSize(it, &buf, &len) < 0) {
            Py_DECREF(fast);
            return false;
        }
        items->push_back(
            {reinterpret_cast<const uint8_t*>(buf), len});
    }
    *fast_out = fast;
    return true;
}

PyObject* merkle_root(PyObject*, PyObject* arg) {
    std::vector<Slice> items;
    PyObject* fast;
    if (!collect(arg, &items, &fast)) return nullptr;
    uint8_t out[32];
    if (items.empty()) {
        sha256::hash(nullptr, 0, out);
    } else {
        tree_hash(items, 0, items.size(), out);
    }
    Py_DECREF(fast);
    return PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(out), 32);
}

PyObject* leaf_hashes(PyObject*, PyObject* arg) {
    // concatenated 32-byte RFC-6962 leaf hashes
    std::vector<Slice> items;
    PyObject* fast;
    if (!collect(arg, &items, &fast)) return nullptr;
    PyObject* out =
        PyBytes_FromStringAndSize(nullptr, Py_ssize_t(items.size()) * 32);
    if (!out) {
        Py_DECREF(fast);
        return nullptr;
    }
    uint8_t* p =
        reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out));
    for (size_t i = 0; i < items.size(); i++)
        sha256::hash_prefixed(kLeafPrefix, items[i].p,
                              size_t(items[i].n), p + i * 32);
    Py_DECREF(fast);
    return out;
}

PyObject* sha256_many(PyObject*, PyObject* arg) {
    // concatenated plain SHA-256 digests (tx hashing)
    std::vector<Slice> items;
    PyObject* fast;
    if (!collect(arg, &items, &fast)) return nullptr;
    PyObject* out =
        PyBytes_FromStringAndSize(nullptr, Py_ssize_t(items.size()) * 32);
    if (!out) {
        Py_DECREF(fast);
        return nullptr;
    }
    uint8_t* p =
        reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out));
    for (size_t i = 0; i < items.size(); i++)
        sha256::hash(items[i].p, size_t(items[i].n), p + i * 32);
    Py_DECREF(fast);
    return out;
}

PyObject* sha256_one(PyObject*, PyObject* arg) {
    char* buf;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(arg, &buf, &len) < 0) return nullptr;
    uint8_t out[32];
    sha256::hash(reinterpret_cast<const uint8_t*>(buf), size_t(len),
                 out);
    return PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(out), 32);
}

PyMethodDef kMethods[] = {
    {"merkle_root", merkle_root, METH_O,
     "RFC-6962/CometBFT merkle root of a sequence of bytes"},
    {"leaf_hashes", leaf_hashes, METH_O,
     "concatenated 32-byte leaf hashes"},
    {"sha256_many", sha256_many, METH_O,
     "concatenated SHA-256 digests of a sequence of bytes"},
    {"sha256", sha256_one, METH_O, "SHA-256 of one bytes object"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "_native",
    "C++ fast paths: merkle tree + batch SHA-256", -1, kMethods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__native(void) {
    return PyModule_Create(&kModule);
}
