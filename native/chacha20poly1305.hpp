// ChaCha20-Poly1305 AEAD (RFC 8439), self-contained.
//
// Backs p2p/secret_connection.py when the python `cryptography`
// package is absent: every 1044-byte p2p frame is sealed/opened
// through here, so this is the link-layer hot path (~1 µs/frame vs
// ~2 ms for the numpy fallback).
#pragma once

#include <cstdint>
#include <cstring>
#include <vector>

namespace ccp {

static inline uint32_t rotl32(uint32_t x, int n) {
    return (x << n) | (x >> (32 - n));
}

static inline uint32_t le32(const uint8_t* p) {
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
           ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
}

static inline void st32(uint8_t* p, uint32_t v) {
    p[0] = v & 0xff; p[1] = (v >> 8) & 0xff;
    p[2] = (v >> 16) & 0xff; p[3] = (v >> 24) & 0xff;
}

#define CCP_QR(a, b, c, d)                                    \
    a += b; d ^= a; d = rotl32(d, 16);                        \
    c += d; b ^= c; b = rotl32(b, 12);                        \
    a += b; d ^= a; d = rotl32(d, 8);                         \
    c += d; b ^= c; b = rotl32(b, 7);

inline void chacha20_block(const uint8_t key[32], uint32_t counter,
                           const uint8_t nonce[12], uint8_t out[64]) {
    uint32_t s[16] = {0x61707865u, 0x3320646eu, 0x79622d32u,
                      0x6b206574u};
    for (int i = 0; i < 8; i++) s[4 + i] = le32(key + 4 * i);
    s[12] = counter;
    for (int i = 0; i < 3; i++) s[13 + i] = le32(nonce + 4 * i);
    uint32_t x[16];
    std::memcpy(x, s, sizeof(x));
    for (int r = 0; r < 10; r++) {
        CCP_QR(x[0], x[4], x[8], x[12]);
        CCP_QR(x[1], x[5], x[9], x[13]);
        CCP_QR(x[2], x[6], x[10], x[14]);
        CCP_QR(x[3], x[7], x[11], x[15]);
        CCP_QR(x[0], x[5], x[10], x[15]);
        CCP_QR(x[1], x[6], x[11], x[12]);
        CCP_QR(x[2], x[7], x[8], x[13]);
        CCP_QR(x[3], x[4], x[9], x[14]);
    }
    for (int i = 0; i < 16; i++) st32(out + 4 * i, x[i] + s[i]);
}

inline void chacha20_xor(const uint8_t key[32], uint32_t counter,
                         const uint8_t nonce[12], const uint8_t* in,
                         uint8_t* out, size_t n) {
    uint8_t block[64];
    size_t off = 0;
    while (off < n) {
        chacha20_block(key, counter++, nonce, block);
        size_t take = (n - off < 64) ? n - off : 64;
        for (size_t i = 0; i < take; i++)
            out[off + i] = in[off + i] ^ block[i];
        off += take;
    }
}

// ---- Poly1305 over 26-bit limbs (portable, no __int128 needed) ------
struct Poly1305 {
    uint32_t r[5], h[5] = {0, 0, 0, 0, 0}, pad[4];

    explicit Poly1305(const uint8_t key[32]) {
        r[0] = (le32(key + 0)) & 0x3ffffff;
        r[1] = (le32(key + 3) >> 2) & 0x3ffff03;
        r[2] = (le32(key + 6) >> 4) & 0x3ffc0ff;
        r[3] = (le32(key + 9) >> 6) & 0x3f03fff;
        r[4] = (le32(key + 12) >> 8) & 0x00fffff;
        for (int i = 0; i < 4; i++) pad[i] = le32(key + 16 + 4 * i);
    }

    void blocks(const uint8_t* m, size_t n, uint32_t hibit) {
        const uint32_t s1 = r[1] * 5, s2 = r[2] * 5, s3 = r[3] * 5,
                       s4 = r[4] * 5;
        uint32_t h0 = h[0], h1 = h[1], h2 = h[2], h3 = h[3],
                 h4 = h[4];
        while (n >= 16) {
            h0 += (le32(m + 0)) & 0x3ffffff;
            h1 += (le32(m + 3) >> 2) & 0x3ffffff;
            h2 += (le32(m + 6) >> 4) & 0x3ffffff;
            h3 += (le32(m + 9) >> 6) & 0x3ffffff;
            h4 += (le32(m + 12) >> 8) | hibit;
            uint64_t d0 = (uint64_t)h0 * r[0] + (uint64_t)h1 * s4 +
                          (uint64_t)h2 * s3 + (uint64_t)h3 * s2 +
                          (uint64_t)h4 * s1;
            uint64_t d1 = (uint64_t)h0 * r[1] + (uint64_t)h1 * r[0] +
                          (uint64_t)h2 * s4 + (uint64_t)h3 * s3 +
                          (uint64_t)h4 * s2;
            uint64_t d2 = (uint64_t)h0 * r[2] + (uint64_t)h1 * r[1] +
                          (uint64_t)h2 * r[0] + (uint64_t)h3 * s4 +
                          (uint64_t)h4 * s3;
            uint64_t d3 = (uint64_t)h0 * r[3] + (uint64_t)h1 * r[2] +
                          (uint64_t)h2 * r[1] + (uint64_t)h3 * r[0] +
                          (uint64_t)h4 * s4;
            uint64_t d4 = (uint64_t)h0 * r[4] + (uint64_t)h1 * r[3] +
                          (uint64_t)h2 * r[2] + (uint64_t)h3 * r[1] +
                          (uint64_t)h4 * r[0];
            uint32_t c = (uint32_t)(d0 >> 26); h0 = d0 & 0x3ffffff;
            d1 += c; c = (uint32_t)(d1 >> 26); h1 = d1 & 0x3ffffff;
            d2 += c; c = (uint32_t)(d2 >> 26); h2 = d2 & 0x3ffffff;
            d3 += c; c = (uint32_t)(d3 >> 26); h3 = d3 & 0x3ffffff;
            d4 += c; c = (uint32_t)(d4 >> 26); h4 = d4 & 0x3ffffff;
            h0 += c * 5; c = h0 >> 26; h0 &= 0x3ffffff;
            h1 += c;
            m += 16;
            n -= 16;
        }
        h[0] = h0; h[1] = h1; h[2] = h2; h[3] = h3; h[4] = h4;
    }

    void finish(uint8_t tag[16]) {
        uint32_t h0 = h[0], h1 = h[1], h2 = h[2], h3 = h[3],
                 h4 = h[4];
        uint32_t c = h1 >> 26; h1 &= 0x3ffffff;
        h2 += c; c = h2 >> 26; h2 &= 0x3ffffff;
        h3 += c; c = h3 >> 26; h3 &= 0x3ffffff;
        h4 += c; c = h4 >> 26; h4 &= 0x3ffffff;
        h0 += c * 5; c = h0 >> 26; h0 &= 0x3ffffff;
        h1 += c;
        // compute h + -p
        uint32_t g0 = h0 + 5; c = g0 >> 26; g0 &= 0x3ffffff;
        uint32_t g1 = h1 + c; c = g1 >> 26; g1 &= 0x3ffffff;
        uint32_t g2 = h2 + c; c = g2 >> 26; g2 &= 0x3ffffff;
        uint32_t g3 = h3 + c; c = g3 >> 26; g3 &= 0x3ffffff;
        uint32_t g4 = h4 + c - (1u << 26);
        uint32_t mask = (g4 >> 31) - 1;   // all-ones when h >= p
        h0 = (h0 & ~mask) | (g0 & mask);
        h1 = (h1 & ~mask) | (g1 & mask);
        h2 = (h2 & ~mask) | (g2 & mask);
        h3 = (h3 & ~mask) | (g3 & mask);
        h4 = (h4 & ~mask) | (g4 & mask);
        h0 = (h0 | (h1 << 26)) & 0xffffffff;
        h1 = ((h1 >> 6) | (h2 << 20)) & 0xffffffff;
        h2 = ((h2 >> 12) | (h3 << 14)) & 0xffffffff;
        h3 = ((h3 >> 18) | (h4 << 8)) & 0xffffffff;
        uint64_t f;
        f = (uint64_t)h0 + pad[0]; h0 = (uint32_t)f;
        f = (uint64_t)h1 + pad[1] + (f >> 32); h1 = (uint32_t)f;
        f = (uint64_t)h2 + pad[2] + (f >> 32); h2 = (uint32_t)f;
        f = (uint64_t)h3 + pad[3] + (f >> 32); h3 = (uint32_t)f;
        st32(tag + 0, h0); st32(tag + 4, h1);
        st32(tag + 8, h2); st32(tag + 12, h3);
    }
};

inline void aead_tag(const uint8_t key[32], const uint8_t nonce[12],
                     const uint8_t* aad, size_t aad_len,
                     const uint8_t* ct, size_t ct_len,
                     uint8_t tag[16]) {
    uint8_t block0[64];
    chacha20_block(key, 0, nonce, block0);
    Poly1305 mac(block0);
    // AEAD mac input: aad || pad16 || ct || pad16 || le64 lens
    mac.blocks(aad, aad_len & ~(size_t)15, 1u << 24);
    if (aad_len & 15) {
        uint8_t last[16] = {0};
        std::memcpy(last, aad + (aad_len & ~(size_t)15), aad_len & 15);
        mac.blocks(last, 16, 1u << 24);
    }
    mac.blocks(ct, ct_len & ~(size_t)15, 1u << 24);
    if (ct_len & 15) {
        uint8_t last[16] = {0};
        std::memcpy(last, ct + (ct_len & ~(size_t)15), ct_len & 15);
        mac.blocks(last, 16, 1u << 24);
    }
    uint8_t lens[16];
    for (int i = 0; i < 8; i++) {
        lens[i] = (uint8_t)(((uint64_t)aad_len) >> (8 * i));
        lens[8 + i] = (uint8_t)(((uint64_t)ct_len) >> (8 * i));
    }
    mac.blocks(lens, 16, 1u << 24);
    mac.finish(tag);
}

// seal: out must hold pt_len + 16
inline void seal(const uint8_t key[32], const uint8_t nonce[12],
                 const uint8_t* aad, size_t aad_len,
                 const uint8_t* pt, size_t pt_len, uint8_t* out) {
    chacha20_xor(key, 1, nonce, pt, out, pt_len);
    aead_tag(key, nonce, aad, aad_len, out, pt_len, out + pt_len);
}

// open: returns false on tag mismatch; out must hold ct_len - 16
inline bool open(const uint8_t key[32], const uint8_t nonce[12],
                 const uint8_t* aad, size_t aad_len,
                 const uint8_t* ct, size_t ct_len, uint8_t* out) {
    if (ct_len < 16) return false;
    size_t pt_len = ct_len - 16;
    uint8_t tag[16];
    aead_tag(key, nonce, aad, aad_len, ct, pt_len, tag);
    uint8_t diff = 0;
    for (int i = 0; i < 16; i++) diff |= tag[i] ^ ct[pt_len + i];
    if (diff) return false;
    chacha20_xor(key, 1, nonce, ct, out, pt_len);
    return true;
}

}  // namespace ccp
