// BLS12-381 pairing arithmetic in C++ — the native fast path for the
// engine's verify-side BLS (reference parity note: the reference's one
// native dependency is the blst C library; this is the analogous
// native component, built against OUR pure-python golden model in
// cometbft_tpu/crypto/_bls12381_math.py).
//
// The structure mirrors the python module one-to-one — same tower
// (Fq2 = Fq[u]/(u^2+1), Fq6 = Fq2[v]/(v^3-(1+u)), Fq12 = Fq6[w]/
// (w^2-v)) and the same RFC-9380 SSWU hash-to-curve as the python
// golden model, so every function is differentially tested against
// it.  Where this port diverges for speed — projective Fq2 Miller
// loop with sparse lines, Frobenius-decomposed final exponentiation
// with Granger-Scott cyclotomic squaring, psi-endomorphism subgroup
// checks and cofactor clearing — each fast path is proven equivalent
// to the plain formulation by the runtime selftest.  Fq uses 6x64
// Montgomery arithmetic (CIOS).
#pragma once

#include <cstdint>
#include <cstring>
#include <vector>

#include "sha256.hpp"

namespace bls {

// --- Fq: 6x64-limb Montgomery ----------------------------------------------

struct Fp {
    uint64_t v[6];
};

static const uint64_t P_LIMBS[6] = {
    0xb9feffffffffaaabULL, 0x1eabfffeb153ffffULL,
    0x6730d2a0f6b0f624ULL, 0x64774b84f38512bfULL,
    0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL};
static const uint64_t N0 = 0x89f3fffcfffcfffdULL;
static const uint64_t R1_LIMBS[6] = {
    0x760900000002fffdULL, 0xebf4000bc40c0002ULL,
    0x5f48985753c758baULL, 0x77ce585370525745ULL,
    0x5c071a97a256ec6dULL, 0x15f65ec3fa80e493ULL};
static const uint64_t R2_LIMBS[6] = {
    0xf4df1f341c341746ULL, 0x0a76e6a609d104f1ULL,
    0x8de5476c4c95b6d5ULL, 0x67eb88a9939d83c0ULL,
    0x9a793e85b519952dULL, 0x11988fe592cae3aaULL};

inline Fp fp_zero() { Fp r{}; return r; }
inline Fp fp_one() {
    Fp r;
    std::memcpy(r.v, R1_LIMBS, sizeof r.v);
    return r;
}

inline bool fp_is_zero(const Fp& a) {
    uint64_t acc = 0;
    for (int i = 0; i < 6; i++) acc |= a.v[i];
    return acc == 0;
}

inline bool fp_eq(const Fp& a, const Fp& b) {
    uint64_t acc = 0;
    for (int i = 0; i < 6; i++) acc |= a.v[i] ^ b.v[i];
    return acc == 0;
}

inline int fp_cmp_raw(const uint64_t a[6], const uint64_t b[6]) {
    for (int i = 5; i >= 0; i--) {
        if (a[i] < b[i]) return -1;
        if (a[i] > b[i]) return 1;
    }
    return 0;
}

inline void raw_sub_p(uint64_t a[6]) {
    unsigned __int128 borrow = 0;
    for (int i = 0; i < 6; i++) {
        unsigned __int128 d =
            (unsigned __int128)a[i] - P_LIMBS[i] - (uint64_t)borrow;
        a[i] = uint64_t(d);
        borrow = (d >> 64) ? 1 : 0;
    }
}

inline Fp fp_add(const Fp& a, const Fp& b) {
    Fp r;
    unsigned __int128 carry = 0;
    for (int i = 0; i < 6; i++) {
        unsigned __int128 s =
            (unsigned __int128)a.v[i] + b.v[i] + (uint64_t)carry;
        r.v[i] = uint64_t(s);
        carry = s >> 64;
    }
    if (carry || fp_cmp_raw(r.v, P_LIMBS) >= 0) raw_sub_p(r.v);
    return r;
}

inline Fp fp_sub(const Fp& a, const Fp& b) {
    Fp r;
    unsigned __int128 borrow = 0;
    for (int i = 0; i < 6; i++) {
        unsigned __int128 d =
            (unsigned __int128)a.v[i] - b.v[i] - (uint64_t)borrow;
        r.v[i] = uint64_t(d);
        borrow = (d >> 64) ? 1 : 0;
    }
    if (borrow) {
        unsigned __int128 carry = 0;
        for (int i = 0; i < 6; i++) {
            unsigned __int128 s =
                (unsigned __int128)r.v[i] + P_LIMBS[i] +
                (uint64_t)carry;
            r.v[i] = uint64_t(s);
            carry = s >> 64;
        }
    }
    return r;
}

inline Fp fp_neg(const Fp& a) {
    if (fp_is_zero(a)) return a;
    Fp p;
    std::memcpy(p.v, P_LIMBS, sizeof p.v);
    return fp_sub(p, a);
}

// CIOS Montgomery multiplication (portable; also the differential
// reference for the ADX path below)
inline Fp fp_mul_generic(const Fp& a, const Fp& b) {
    uint64_t t[8] = {0};
    for (int i = 0; i < 6; i++) {
        unsigned __int128 carry = 0;
        for (int j = 0; j < 6; j++) {
            unsigned __int128 cur =
                (unsigned __int128)a.v[i] * b.v[j] + t[j] +
                (uint64_t)carry;
            t[j] = uint64_t(cur);
            carry = cur >> 64;
        }
        unsigned __int128 s =
            (unsigned __int128)t[6] + (uint64_t)carry;
        t[6] = uint64_t(s);
        t[7] = uint64_t(s >> 64);

        uint64_t m = t[0] * N0;
        carry = 0;
        {
            unsigned __int128 cur =
                (unsigned __int128)m * P_LIMBS[0] + t[0];
            carry = cur >> 64;
        }
        for (int j = 1; j < 6; j++) {
            unsigned __int128 cur =
                (unsigned __int128)m * P_LIMBS[j] + t[j] +
                (uint64_t)carry;
            t[j - 1] = uint64_t(cur);
            carry = cur >> 64;
        }
        s = (unsigned __int128)t[6] + (uint64_t)carry;
        t[5] = uint64_t(s);
        t[6] = t[7] + uint64_t(s >> 64);
        t[7] = 0;
    }
    Fp r;
    std::memcpy(r.v, t, sizeof r.v);
    if (t[6] || fp_cmp_raw(r.v, P_LIMBS) >= 0) raw_sub_p(r.v);
    return r;
}

#if defined(__ADX__) && defined(__BMI2__)
// MULX/ADCX/ADOX interleaved-CIOS Montgomery multiply.  Two carry
// chains ride CF (adcx) and OF (adox) as the ISA intends — the
// compiler cannot be coaxed into this from __int128 code (it folds
// both chains onto CF), so the two per-round blocks are hand-written.
// Window analysis: t stays < 2p per round (standard CIOS bound), so
// seven limbs t0..t6 suffice and the chain-fold adds into t6 cannot
// overflow.  ~2x the generic CIOS on this class of core; the loader
// compiles -march=native so the gate matches the running machine.
// Differentially checked against fp_mul_generic in selftest().
inline Fp fp_mul(const Fp& a, const Fp& b) {
    uint64_t t0 = 0, t1 = 0, t2 = 0, t3 = 0, t4 = 0, t5 = 0, t6 = 0;
    const uint64_t* p = P_LIMBS;
    for (int i = 0; i < 6; i++) {
        asm volatile(
            "xorq %%r11, %%r11\n\t"          // clear CF+OF
            "mulxq 0(%[b]), %%r8, %%r9\n\t"  // rdx = a[i]
            "adcxq %%r8, %[t0]\n\t"
            "adoxq %%r9, %[t1]\n\t"
            "mulxq 8(%[b]), %%r8, %%r9\n\t"
            "adcxq %%r8, %[t1]\n\t"
            "adoxq %%r9, %[t2]\n\t"
            "mulxq 16(%[b]), %%r8, %%r9\n\t"
            "adcxq %%r8, %[t2]\n\t"
            "adoxq %%r9, %[t3]\n\t"
            "mulxq 24(%[b]), %%r8, %%r9\n\t"
            "adcxq %%r8, %[t3]\n\t"
            "adoxq %%r9, %[t4]\n\t"
            "mulxq 32(%[b]), %%r8, %%r9\n\t"
            "adcxq %%r8, %[t4]\n\t"
            "adoxq %%r9, %[t5]\n\t"
            "mulxq 40(%[b]), %%r8, %%r9\n\t"
            "adcxq %%r8, %[t5]\n\t"
            "adoxq %%r9, %[t6]\n\t"
            "movq $0, %%r8\n\t"
            "adcxq %%r8, %[t6]\n\t"
            : [t0] "+r"(t0), [t1] "+r"(t1), [t2] "+r"(t2),
              [t3] "+r"(t3), [t4] "+r"(t4), [t5] "+r"(t5),
              [t6] "+r"(t6)
            : [b] "r"(b.v), "d"(a.v[i]),
              "m"(*(const uint64_t(*)[6])b.v)  // asm READS *b.v: the
              // operand forces the stores to land before the block
            : "r8", "r9", "r11", "cc");
        uint64_t m = t0 * N0;
        asm volatile(
            "xorq %%r11, %%r11\n\t"
            "mulxq 0(%[p]), %%r8, %%r9\n\t"  // rdx = m; kills t0
            "adcxq %%r8, %[t0]\n\t"
            "adoxq %%r9, %[t1]\n\t"
            "mulxq 8(%[p]), %%r8, %%r9\n\t"
            "adcxq %%r8, %[t1]\n\t"
            "adoxq %%r9, %[t2]\n\t"
            "mulxq 16(%[p]), %%r8, %%r9\n\t"
            "adcxq %%r8, %[t2]\n\t"
            "adoxq %%r9, %[t3]\n\t"
            "mulxq 24(%[p]), %%r8, %%r9\n\t"
            "adcxq %%r8, %[t3]\n\t"
            "adoxq %%r9, %[t4]\n\t"
            "mulxq 32(%[p]), %%r8, %%r9\n\t"
            "adcxq %%r8, %[t4]\n\t"
            "adoxq %%r9, %[t5]\n\t"
            "mulxq 40(%[p]), %%r8, %%r9\n\t"
            "adcxq %%r8, %[t5]\n\t"
            "adoxq %%r9, %[t6]\n\t"
            "movq $0, %%r8\n\t"
            "adcxq %%r8, %[t6]\n\t"
            : [t0] "+r"(t0), [t1] "+r"(t1), [t2] "+r"(t2),
              [t3] "+r"(t3), [t4] "+r"(t4), [t5] "+r"(t5),
              [t6] "+r"(t6)
            : [p] "r"(p), "d"(m),
              "m"(*(const uint64_t(*)[6])p)
            : "r8", "r9", "r11", "cc");
        t0 = t1; t1 = t2; t2 = t3; t3 = t4; t4 = t5; t5 = t6; t6 = 0;
    }
    Fp r;
    r.v[0] = t0; r.v[1] = t1; r.v[2] = t2;
    r.v[3] = t3; r.v[4] = t4; r.v[5] = t5;
    if (fp_cmp_raw(r.v, P_LIMBS) >= 0) raw_sub_p(r.v);
    return r;
}
#else
inline Fp fp_mul(const Fp& a, const Fp& b) {
    return fp_mul_generic(a, b);
}
#endif

inline Fp fp_sqr(const Fp& a) { return fp_mul(a, a); }

inline Fp fp_muli(const Fp& a, int k) {
    // double-and-add: the Miller loop multiplies by 8/16/18/27/36
    // per iteration — a linear add chain would burn ~200 adds/step
    Fp out = fp_zero();
    Fp base = a;
    while (k) {
        if (k & 1) out = fp_add(out, base);
        k >>= 1;
        if (k) base = fp_add(base, base);
    }
    return out;
}

// generic pow over a big-endian exponent byte string
inline Fp fp_pow_be(const Fp& a, const uint8_t* e, size_t elen) {
    Fp out = fp_one();
    bool started = false;
    for (size_t i = 0; i < elen; i++) {
        for (int b = 7; b >= 0; b--) {
            if (started) out = fp_sqr(out);
            if ((e[i] >> b) & 1) {
                if (started) out = fp_mul(out, a);
                else { out = a; started = true; }
            }
        }
    }
    return started ? out : fp_one();
}

static const uint8_t PM2_BE[48] = {
    0x1a,0x01,0x11,0xea,0x39,0x7f,0xe6,0x9a,0x4b,0x1b,0xa7,0xb6,
    0x43,0x4b,0xac,0xd7,0x64,0x77,0x4b,0x84,0xf3,0x85,0x12,0xbf,
    0x67,0x30,0xd2,0xa0,0xf6,0xb0,0xf6,0x24,0x1e,0xab,0xff,0xfe,
    0xb1,0x53,0xff,0xff,0xb9,0xfe,0xff,0xff,0xff,0xff,0xaa,0xa9};
static const uint8_t PP14_BE[48] = {
    0x06,0x80,0x44,0x7a,0x8e,0x5f,0xf9,0xa6,0x92,0xc6,0xe9,0xed,
    0x90,0xd2,0xeb,0x35,0xd9,0x1d,0xd2,0xe1,0x3c,0xe1,0x44,0xaf,
    0xd9,0xcc,0x34,0xa8,0x3d,0xac,0x3d,0x89,0x07,0xaa,0xff,0xff,
    0xac,0x54,0xff,0xff,0xee,0x7f,0xbf,0xff,0xff,0xff,0xea,0xab};
inline Fp fp_inv(const Fp& a) { return fp_pow_be(a, PM2_BE, 48); }

// from/to big-endian 48-byte standard form
inline bool fp_from_be48(const uint8_t* b, Fp* out) {
    uint64_t raw[6];
    for (int i = 0; i < 6; i++) {
        uint64_t v = 0;
        for (int j = 0; j < 8; j++)
            v = (v << 8) | b[(5 - i) * 8 + j];
        raw[i] = v;
    }
    if (fp_cmp_raw(raw, P_LIMBS) >= 0) return false;
    Fp t, r2;
    std::memcpy(t.v, raw, sizeof t.v);
    std::memcpy(r2.v, R2_LIMBS, sizeof r2.v);
    *out = fp_mul(t, r2);      // to Montgomery
    return true;
}

inline void fp_to_be48(const Fp& a, uint8_t* out) {
    // from Montgomery: multiply by 1
    Fp one{};
    one.v[0] = 1;
    Fp std_form = fp_mul(a, one);
    for (int i = 0; i < 6; i++)
        for (int j = 0; j < 8; j++)
            out[(5 - i) * 8 + j] =
                uint8_t(std_form.v[i] >> (56 - 8 * j));
}

inline Fp fp_from_u64(uint64_t x) {
    Fp t{}, r2;
    t.v[0] = x;
    std::memcpy(r2.v, R2_LIMBS, sizeof r2.v);
    return fp_mul(t, r2);
}

inline bool fp_is_odd(const Fp& a) {
    uint8_t be[48];
    fp_to_be48(a, be);
    return be[47] & 1;
}

// sqrt via (p+1)/4 (p % 4 == 3); false if non-square
inline bool fp_sqrt(const Fp& a, Fp* out) {
    Fp r = fp_pow_be(a, PP14_BE, 48);
    if (!fp_eq(fp_sqr(r), a)) return false;
    *out = r;
    return true;
}

// --- Fq2 --------------------------------------------------------------------

struct Fp2 {
    Fp c0, c1;
};

inline Fp2 f2_zero() { return {fp_zero(), fp_zero()}; }
inline Fp2 f2_one() { return {fp_one(), fp_zero()}; }
inline bool f2_is_zero(const Fp2& a) {
    return fp_is_zero(a.c0) && fp_is_zero(a.c1);
}
inline bool f2_eq(const Fp2& a, const Fp2& b) {
    return fp_eq(a.c0, b.c0) && fp_eq(a.c1, b.c1);
}
inline Fp2 f2_add(const Fp2& a, const Fp2& b) {
    return {fp_add(a.c0, b.c0), fp_add(a.c1, b.c1)};
}
inline Fp2 f2_sub(const Fp2& a, const Fp2& b) {
    return {fp_sub(a.c0, b.c0), fp_sub(a.c1, b.c1)};
}
inline Fp2 f2_neg(const Fp2& a) {
    return {fp_neg(a.c0), fp_neg(a.c1)};
}
inline Fp2 f2_mul(const Fp2& a, const Fp2& b) {
    Fp t0 = fp_mul(a.c0, b.c0);
    Fp t1 = fp_mul(a.c1, b.c1);
    Fp s = fp_mul(fp_add(a.c0, a.c1), fp_add(b.c0, b.c1));
    return {fp_sub(t0, t1), fp_sub(fp_sub(s, t0), t1)};
}
inline Fp2 f2_sqr(const Fp2& a) {
    Fp s = fp_mul(fp_add(a.c0, a.c1), fp_sub(a.c0, a.c1));
    Fp d = fp_mul(a.c0, a.c1);
    return {s, fp_add(d, d)};
}
inline Fp2 f2_muli(const Fp2& a, int k) {
    return {fp_muli(a.c0, k), fp_muli(a.c1, k)};
}
inline Fp2 f2_inv(const Fp2& a) {
    Fp d = fp_inv(fp_add(fp_sqr(a.c0), fp_sqr(a.c1)));
    return {fp_mul(a.c0, d), fp_neg(fp_mul(a.c1, d))};
}
inline Fp2 f2_mul_xi(const Fp2& a) {
    // * (1 + u)
    return {fp_sub(a.c0, a.c1), fp_add(a.c0, a.c1)};
}

// sqrt in Fq2, mirroring the python norm-trick implementation
inline bool f2_sqrt(const Fp2& a, Fp2* out) {
    if (fp_is_zero(a.c1)) {
        Fp r;
        if (fp_sqrt(a.c0, &r)) {
            *out = {r, fp_zero()};
            return true;
        }
        if (fp_sqrt(fp_neg(a.c0), &r)) {
            *out = {fp_zero(), r};
            return true;
        }
        return false;
    }
    Fp alpha;
    if (!fp_sqrt(fp_add(fp_sqr(a.c0), fp_sqr(a.c1)), &alpha))
        return false;
    static const Fp inv2 = fp_inv(fp_from_u64(2));
    Fp delta = fp_mul(fp_add(a.c0, alpha), inv2);
    Fp x0;
    if (!fp_sqrt(delta, &x0)) {
        delta = fp_mul(fp_sub(a.c0, alpha), inv2);
        if (!fp_sqrt(delta, &x0)) return false;
    }
    Fp x1 = fp_mul(a.c1, fp_inv(fp_add(x0, x0)));
    Fp2 cand = {x0, x1};
    if (!f2_eq(f2_sqr(cand), a)) return false;
    *out = cand;
    return true;
}

// --- Fq6, Fq12 --------------------------------------------------------------

struct Fp6 {
    Fp2 a0, a1, a2;
};
struct Fp12 {
    Fp6 b0, b1;
};

inline Fp6 f6_zero() { return {f2_zero(), f2_zero(), f2_zero()}; }
inline Fp6 f6_one() { return {f2_one(), f2_zero(), f2_zero()}; }
inline bool f6_eq(const Fp6& a, const Fp6& b) {
    return f2_eq(a.a0, b.a0) && f2_eq(a.a1, b.a1) &&
           f2_eq(a.a2, b.a2);
}
inline Fp6 f6_add(const Fp6& a, const Fp6& b) {
    return {f2_add(a.a0, b.a0), f2_add(a.a1, b.a1),
            f2_add(a.a2, b.a2)};
}
inline Fp6 f6_sub(const Fp6& a, const Fp6& b) {
    return {f2_sub(a.a0, b.a0), f2_sub(a.a1, b.a1),
            f2_sub(a.a2, b.a2)};
}
inline Fp6 f6_neg(const Fp6& a) {
    return {f2_neg(a.a0), f2_neg(a.a1), f2_neg(a.a2)};
}
inline Fp6 f6_mul(const Fp6& a, const Fp6& b) {
    Fp2 t0 = f2_mul(a.a0, b.a0);
    Fp2 t1 = f2_mul(a.a1, b.a1);
    Fp2 t2 = f2_mul(a.a2, b.a2);
    Fp2 c0 = f2_add(t0, f2_mul_xi(f2_sub(
        f2_mul(f2_add(a.a1, a.a2), f2_add(b.a1, b.a2)),
        f2_add(t1, t2))));
    Fp2 c1 = f2_add(f2_sub(
        f2_mul(f2_add(a.a0, a.a1), f2_add(b.a0, b.a1)),
        f2_add(t0, t1)), f2_mul_xi(t2));
    Fp2 c2 = f2_add(f2_sub(
        f2_mul(f2_add(a.a0, a.a2), f2_add(b.a0, b.a2)),
        f2_add(t0, t2)), t1);
    return {c0, c1, c2};
}
inline Fp6 f6_sqr(const Fp6& a) { return f6_mul(a, a); }
inline Fp6 f6_mul_v(const Fp6& a) {
    return {f2_mul_xi(a.a2), a.a0, a.a1};
}
inline Fp6 f6_inv(const Fp6& a) {
    Fp2 c0 = f2_sub(f2_sqr(a.a0), f2_mul_xi(f2_mul(a.a1, a.a2)));
    Fp2 c1 = f2_sub(f2_mul_xi(f2_sqr(a.a2)), f2_mul(a.a0, a.a1));
    Fp2 c2 = f2_sub(f2_sqr(a.a1), f2_mul(a.a0, a.a2));
    Fp2 t = f2_inv(f2_add(
        f2_mul(a.a0, c0),
        f2_mul_xi(f2_add(f2_mul(a.a2, c1), f2_mul(a.a1, c2)))));
    return {f2_mul(c0, t), f2_mul(c1, t), f2_mul(c2, t)};
}

inline Fp12 f12_zero() { return {f6_zero(), f6_zero()}; }
inline Fp12 f12_one() { return {f6_one(), f6_zero()}; }
inline bool f12_eq(const Fp12& a, const Fp12& b) {
    return f6_eq(a.b0, b.b0) && f6_eq(a.b1, b.b1);
}
inline Fp12 f12_add(const Fp12& a, const Fp12& b) {
    return {f6_add(a.b0, b.b0), f6_add(a.b1, b.b1)};
}
inline Fp12 f12_sub(const Fp12& a, const Fp12& b) {
    return {f6_sub(a.b0, b.b0), f6_sub(a.b1, b.b1)};
}
inline Fp12 f12_neg(const Fp12& a) {
    return {f6_neg(a.b0), f6_neg(a.b1)};
}
inline Fp12 f12_mul(const Fp12& a, const Fp12& b) {
    Fp6 t0 = f6_mul(a.b0, b.b0);
    Fp6 t1 = f6_mul(a.b1, b.b1);
    Fp6 c0 = f6_add(t0, f6_mul_v(t1));
    Fp6 c1 = f6_sub(f6_mul(f6_add(a.b0, a.b1), f6_add(b.b0, b.b1)),
                    f6_add(t0, t1));
    return {c0, c1};
}
inline Fp12 f12_sqr(const Fp12& a) {
    // (b0 + b1 w)^2 with w^2 = v: 2 Fq6 muls (complex squaring)
    Fp6 t = f6_mul(a.b0, a.b1);
    Fp6 tv = f6_mul_v(t);
    Fp6 c0 = f6_sub(f6_sub(
        f6_mul(f6_add(a.b0, a.b1), f6_add(a.b0, f6_mul_v(a.b1))),
        t), tv);
    return {c0, f6_add(t, t)};
}

// f12 multiply by a sparse Miller line {b0.a0 = c0; b1.a1 = c3,
// b1.a2 = c4}: 12 Fq2 muls vs f12_mul's 18
inline Fp12 f12_mul_sparse(const Fp12& f, const Fp2& c0,
                           const Fp2& c3, const Fp2& c4) {
    // L = c0 + L1 w, L1 = (0, c3, c4):
    //   result = (f.b0 c0 + v·(f.b1 L1)) + (f.b0 L1 + f.b1 c0) w
    const Fp6& a = f.b0;
    const Fp6& b = f.b1;
    Fp6 ac0 = {f2_mul(a.a0, c0), f2_mul(a.a1, c0),
               f2_mul(a.a2, c0)};
    Fp6 bc0 = {f2_mul(b.a0, c0), f2_mul(b.a1, c0),
               f2_mul(b.a2, c0)};
    // x·L1 for x = (x0, x1, x2):  (xi(x1 c4 + x2 c3),
    //                              x0 c3 + xi(x2 c4),
    //                              x0 c4 + x1 c3)
    auto mul_l1 = [&](const Fp6& x) -> Fp6 {
        return {f2_mul_xi(f2_add(f2_mul(x.a1, c4),
                                 f2_mul(x.a2, c3))),
                f2_add(f2_mul(x.a0, c3),
                       f2_mul_xi(f2_mul(x.a2, c4))),
                f2_add(f2_mul(x.a0, c4), f2_mul(x.a1, c3))};
    };
    Fp6 bl1 = mul_l1(b);
    Fp6 al1 = mul_l1(a);
    return {f6_add(ac0, f6_mul_v(bl1)), f6_add(al1, bc0)};
}
inline Fp12 f12_inv(const Fp12& a) {
    Fp6 t = f6_inv(f6_sub(f6_sqr(a.b0), f6_mul_v(f6_sqr(a.b1))));
    return {f6_mul(a.b0, t), f6_neg(f6_mul(a.b1, t))};
}
inline Fp12 f12_conj(const Fp12& a) { return {a.b0, f6_neg(a.b1)}; }

inline Fp12 f12_pow_be(const Fp12& a, const uint8_t* e, size_t elen) {
    Fp12 out = f12_one();
    bool started = false;
    for (size_t i = 0; i < elen; i++) {
        for (int b = 7; b >= 0; b--) {
            if (started) out = f12_sqr(out);
            if ((e[i] >> b) & 1) {
                if (started) out = f12_mul(out, a);
                else { out = a; started = true; }
            }
        }
    }
    return started ? out : f12_one();
}

// --- affine points ----------------------------------------------------------

struct G1 {
    Fp x, y;
    bool inf;
};
struct G2 {
    Fp2 x, y;
    bool inf;
};
// one affine implementation per field, mirroring the python formulas

#define DEFINE_PT_OPS(PT, F, fadd, fsub, fmul, fsqr, fneg, finv,      \
                      fiszero, feq, fmuli)                            \
    inline PT PT##_neg(const PT& p) {                                 \
        if (p.inf) return p;                                          \
        return {p.x, fneg(p.y), false};                               \
    }                                                                 \
    inline PT PT##_double(const PT& p) {                              \
        if (p.inf) return p;                                          \
        if (fiszero(p.y)) return {p.x, p.y, true};                    \
        F m = fmul(fmuli(fsqr(p.x), 3),                               \
                   finv(fmuli(p.y, 2)));                              \
        F nx = fsub(fsqr(m), fmuli(p.x, 2));                          \
        F ny = fsub(fmul(m, fsub(p.x, nx)), p.y);                     \
        return {nx, ny, false};                                       \
    }                                                                 \
    inline PT PT##_add(const PT& a, const PT& b) {                    \
        if (a.inf) return b;                                          \
        if (b.inf) return a;                                          \
        if (feq(a.x, b.x)) {                                          \
            if (feq(a.y, b.y)) return PT##_double(a);                 \
            return {a.x, a.y, true};                                  \
        }                                                             \
        F m = fmul(fsub(b.y, a.y), finv(fsub(b.x, a.x)));             \
        F nx = fsub(fsub(fsqr(m), a.x), b.x);                         \
        F ny = fsub(fmul(m, fsub(a.x, nx)), a.y);                     \
        return {nx, ny, false};                                       \
    }                                                                 \

inline Fp fp_muli_(const Fp& a, int k) { return fp_muli(a, k); }
DEFINE_PT_OPS(G1, Fp, fp_add, fp_sub, fp_mul, fp_sqr, fp_neg, fp_inv,
              fp_is_zero, fp_eq, fp_muli_)
DEFINE_PT_OPS(G2, Fp2, f2_add, f2_sub, f2_mul, f2_sqr, f2_neg,
              f2_inv, f2_is_zero, f2_eq, f2_muli)

// Jacobian scalar multiplication (one inversion at the end instead of
// one per step): (X, Y, Z) with x = X/Z^2, y = Y/Z^3.  Used for the
// long multiplications (subgroup checks, cofactor clearing, signing);
// the result is normalized back to affine, so outputs are
// byte-identical to the affine ladder and the python golden model.
#define DEFINE_JAC_MUL(PT, F, fadd, fsub, fmul, fsqr, fneg, finv,     \
                       fiszero, feq, fone)                            \
    struct PT##Jac { F X, Y, Z; };                                    \
    inline PT##Jac PT##_jac_double(const PT##Jac& p) {                \
        if (fiszero(p.Z) || fiszero(p.Y)) return {p.X, p.Y,           \
                                                  F{} /*zero*/};      \
        F A = fsqr(p.X);                                              \
        F B = fsqr(p.Y);                                              \
        F C = fsqr(B);                                                \
        F D0 = fsub(fsqr(fadd(p.X, B)), fadd(A, C));                  \
        F D = fadd(D0, D0);                                           \
        F E = fadd(fadd(A, A), A);                                    \
        F X3 = fsub(fsqr(E), fadd(D, D));                             \
        F C8 = fadd(C, C);                                            \
        C8 = fadd(C8, C8);                                            \
        C8 = fadd(C8, C8);                                            \
        F Y3 = fsub(fmul(E, fsub(D, X3)), C8);                        \
        F Z3 = fmul(fadd(p.Y, p.Y), p.Z);                             \
        return {X3, Y3, Z3};                                          \
    }                                                                 \
    inline PT##Jac PT##_jac_add_affine(const PT##Jac& p,              \
                                       const PT& q) {                 \
        if (fiszero(p.Z)) {                                           \
            /* p = inf: lift q */                                     \
            return {q.x, q.y, fone()};                                \
        }                                                             \
        F Z2 = fsqr(p.Z);                                             \
        F U2 = fmul(q.x, Z2);                                         \
        F S2 = fmul(fmul(q.y, Z2), p.Z);                              \
        if (feq(p.X, U2)) {                                           \
            if (feq(p.Y, S2)) return PT##_jac_double(p);              \
            return {p.X, p.Y, F{}};        /* p + (-p) = inf */       \
        }                                                             \
        F H = fsub(U2, p.X);                                          \
        F HH = fsqr(H);                                               \
        F I = fadd(HH, HH);                                           \
        I = fadd(I, I);                                               \
        F J = fmul(H, I);                                             \
        F rr = fsub(S2, p.Y);                                         \
        rr = fadd(rr, rr);                                            \
        F V = fmul(p.X, I);                                           \
        F X3 = fsub(fsub(fsqr(rr), J), fadd(V, V));                   \
        F Y2J = fmul(p.Y, J);                                         \
        F Y3 = fsub(fmul(rr, fsub(V, X3)), fadd(Y2J, Y2J));           \
        F Z3 = fmul(fadd(p.Z, p.Z), H);                               \
        return {X3, Y3, Z3};                                          \
    }                                                                 \
    inline PT PT##_jac_to_affine(const PT##Jac& p) {                  \
        if (fiszero(p.Z)) return {F{}, F{}, true};                    \
        F zi = finv(p.Z);                                             \
        F zi2 = fsqr(zi);                                             \
        return {fmul(p.X, zi2), fmul(fmul(p.Y, zi2), zi), false};     \
    }                                                                 \
    inline PT PT##_mul_be_fast(const PT& p, const uint8_t* k,         \
                               size_t klen) {                         \
        if (p.inf) return p;                                          \
        PT##Jac acc = {F{}, F{}, F{}};      /* infinity (Z = 0) */    \
        bool started = false;                                         \
        for (size_t i = 0; i < klen; i++) {                           \
            for (int b = 7; b >= 0; b--) {                            \
                if (started) acc = PT##_jac_double(acc);              \
                if ((k[i] >> b) & 1) {                                \
                    acc = PT##_jac_add_affine(acc, p);                \
                    started = true;                                   \
                }                                                     \
            }                                                         \
        }                                                             \
        return PT##_jac_to_affine(acc);                               \
    }

DEFINE_JAC_MUL(G1, Fp, fp_add, fp_sub, fp_mul, fp_sqr, fp_neg,
               fp_inv, fp_is_zero, fp_eq, fp_one)
DEFINE_JAC_MUL(G2, Fp2, f2_add, f2_sub, f2_mul, f2_sqr, f2_neg,
               f2_inv, f2_is_zero, f2_eq, f2_one)
inline bool f12_is_zero(const Fp12& a) { return f12_eq(a, f12_zero()); }
// curve equations
inline bool g1_on_curve(const G1& p) {
    if (p.inf) return true;
    Fp b4 = fp_from_u64(4);
    return fp_eq(fp_sqr(p.y),
                 fp_add(fp_mul(fp_sqr(p.x), p.x), b4));
}
inline Fp2 g2_b() {
    // 4 * (1 + u)
    Fp f4 = fp_from_u64(4);
    return {f4, f4};
}
inline bool g2_on_curve(const G2& p) {
    if (p.inf) return true;
    return f2_eq(f2_sqr(p.y),
                 f2_add(f2_mul(f2_sqr(p.x), p.x), g2_b()));
}

static const uint8_t R_BE[32] = {
    0x73,0xed,0xa7,0x53,0x29,0x9d,0x7d,0x48,0x33,0x39,0xd8,0x08,
    0x09,0xa1,0xd8,0x05,0x53,0xbd,0xa4,0x02,0xff,0xfe,0x5b,0xfe,
    0xff,0xff,0xff,0xff,0x00,0x00,0x00,0x01};

inline bool g1_in_subgroup(const G1& p) {
    if (!g1_on_curve(p)) return false;
    if (p.inf) return true;
    return G1_mul_be_fast(p, R_BE, 32).inf;
}
inline bool g2_in_subgroup(const G2& p);

// --- pairing ----------------------------------------------------------------

// |x| = 0xD201000000010000; loop over bits below the leading one
static const uint64_t ATE_LOOP = 0xD201000000010000ULL;

inline Fp2 f2_scale(const Fp2& a, const Fp& s) {
    return {fp_mul(a.c0, s), fp_mul(a.c1, s)};
}

// A Miller line as a sparse Fp12.  With the untwist (x, y) ->
// (x w^-2, y w^-3) the line through points of E'(Fq2) evaluated at
// P in G1 is  c0 + c4·w^-1 + c3·w^-3;  w^-1 = xi^-1 v^2 w and
// w^-3 = xi^-1 v w, so multiplying the whole line by xi (an Fq2
// constant, annihilated by the final exponentiation's p^6-1 easy
// part) gives the sparse element below.
// Projective Miller loop: R in homogeneous (X, Y, Z) over Fq2 —
// NO inversions anywhere (the round-2 affine-Fq12 loop paid one Fq12
// inversion per step; that was the 26 ms).  Every line is scaled by
// an Fq2 factor (2YZ^2 for tangents, D for chords), which the final
// exponentiation kills, so verdicts are unchanged.  The projective
// doubling/addition formulas are derived directly from the affine
// chord-tangent law by clearing denominators (Z3 = 8Y^3Z^3 resp.
// D^3 Z); the python golden model remains the affine reference.
inline Fp12 miller_loop(const G2& q, const G1& p) {
    if (q.inf || p.inf) return f12_one();
    Fp2 X = q.x, Y = q.y, Z = f2_one();
    Fp12 f = f12_one();
    Fp neg_yp = fp_neg(p.y);
    Fp xp3 = fp_muli(p.x, 3);
    int top = 63;
    while (!((ATE_LOOP >> top) & 1)) top--;
    for (int i = top - 1; i >= 0; i--) {
        // tangent at R, scaled by 2YZ^2:
        //   -2YZ^2·yP + 3X^2·Z·xP·w^-1 + (2Y^2·Z - 3X^3)·w^-3
        Fp2 X2 = f2_sqr(X), Y2 = f2_sqr(Y), Z2 = f2_sqr(Z);
        Fp2 Xc = f2_mul(X2, X);                       // X^3
        Fp2 YZ2 = f2_mul(Y, Z2);
        Fp2 c0 = f2_scale(f2_add(YZ2, YZ2), neg_yp);
        Fp2 c4 = f2_scale(f2_mul(X2, Z), xp3);
        Fp2 c3 = f2_sub(f2_muli(f2_mul(Y2, Z), 2), f2_muli(Xc, 3));
        f = f12_mul_sparse(f12_sqr(f), f2_mul_xi(c0), c3, c4);
        // R = 2R:  X' = 18X^4·YZ - 16X·Y^3·Z^2,
        //          Y' = 36X^3·Y^2·Z - 27X^6 - 8Y^4·Z^2,
        //          Z' = 8Y^3·Z^3
        Fp2 X4 = f2_sqr(X2);
        Fp2 Yc = f2_mul(Y2, Y);                       // Y^3
        Fp2 nX = f2_sub(f2_muli(f2_mul(f2_mul(X4, Y), Z), 18),
                        f2_muli(f2_mul(f2_mul(X, Yc), Z2), 16));
        Fp2 nY = f2_sub(
            f2_sub(f2_muli(f2_mul(f2_mul(Xc, Y2), Z), 36),
                   f2_muli(f2_sqr(Xc), 27)),
            f2_muli(f2_mul(f2_sqr(Y2), Z2), 8));
        Fp2 nZ = f2_muli(f2_mul(Yc, f2_mul(Z2, Z)), 8);
        X = nX; Y = nY; Z = nZ;
        if ((ATE_LOOP >> i) & 1) {
            // chord through R and affine Q, scaled by D = Z·xQ - X:
            //   -D·yP + N·xP·w^-1 + (D·yQ - N·xQ)·w^-3
            Fp2 N = f2_sub(f2_mul(Z, q.y), Y);
            Fp2 D = f2_sub(f2_mul(Z, q.x), X);
            Fp2 c0a = f2_scale(D, neg_yp);
            Fp2 c4a = f2_scale(N, p.x);
            Fp2 c3a = f2_sub(f2_mul(D, q.y), f2_mul(N, q.x));
            f = f12_mul_sparse(f, f2_mul_xi(c0a), c3a, c4a);
            // R = R + Q:  W = N^2·Z - D^2·(X + xQ·Z),
            //   X' = D·W,  Y' = N·(X·D^2 - W) - Y·D^3,  Z' = D^3·Z
            Fp2 D2 = f2_sqr(D), D3 = f2_mul(D2, D);
            Fp2 W = f2_sub(f2_mul(f2_sqr(N), Z),
                           f2_mul(D2, f2_add(X, f2_mul(q.x, Z))));
            Fp2 aX = f2_mul(D, W);
            Fp2 aY = f2_sub(f2_mul(N, f2_sub(f2_mul(X, D2), W)),
                            f2_mul(Y, D3));
            Fp2 aZ = f2_mul(D3, Z);
            X = aX; Y = aY; Z = aZ;
        }
    }
    return f12_conj(f);        // x < 0 adjustment
}

// (p^6 + 1) / r, big-endian (the python module's folded exponent)
static const uint8_t FINAL_E_BE[254] = {
    0x28,0xb3,0x14,0x87,0x75,0x03,0x7b,0x6f,0x23,0x5c,0x55,0xca,
    0x75,0x66,0xdb,0xf8,0x5a,0xe6,0x64,0xcf,0x5b,0xb3,0x65,0x79,
    0xae,0xa8,0x3c,0x48,0xc1,0xda,0xe0,0xec,0x90,0x31,0x17,0x9b,
    0xde,0xcc,0xad,0x73,0x75,0xa3,0x76,0x3b,0xdf,0x7c,0xcf,0x56,
    0xfb,0x15,0x73,0xbe,0xaa,0x8c,0x54,0x8c,0xe0,0x80,0x9b,0xc5,
    0xf6,0x1a,0xfb,0x46,0xe1,0x97,0xbd,0x2f,0xa4,0x89,0x9f,0x0c,
    0x50,0x12,0x6c,0x80,0x2e,0xec,0x85,0xa2,0xe7,0x07,0xf0,0x84,
    0x18,0x55,0x47,0x44,0x49,0x7f,0x8b,0x2f,0x29,0x22,0x96,0x78,
    0x78,0xfe,0xbc,0xb9,0x5d,0x1f,0x13,0x04,0x27,0x5e,0xf4,0x99,
    0xdf,0xfb,0x12,0xd6,0xa8,0x74,0xd2,0x1b,0x73,0xda,0x2b,0x82,
    0x2f,0x51,0x4a,0x9c,0x4f,0x6f,0xee,0x6a,0x95,0xdb,0x11,0xe6,
    0x3f,0x56,0x5e,0x88,0x6c,0x94,0xc4,0xf8,0x23,0x84,0xc3,0xb5,
    0xe2,0xf5,0x57,0xc0,0xb1,0x5f,0x27,0xd7,0xbd,0x90,0x93,0x50,
    0x21,0xc3,0xf0,0x07,0xc0,0x1e,0x7e,0xbe,0x3a,0xfc,0x81,0x61,
    0x01,0xdd,0xd0,0x76,0x11,0x7d,0x1d,0x61,0x5d,0x49,0xe2,0x76,
    0x4d,0x7b,0xc3,0xb5,0xef,0x4b,0x18,0x8a,0x20,0xb0,0x38,0xee,
    0x1c,0xd4,0x77,0x8e,0x0d,0xe7,0x33,0x82,0x59,0xc2,0x2a,0x12,
    0xbd,0x40,0x22,0x47,0x41,0xb3,0x6f,0xec,0x77,0x60,0x2d,0x72,
    0x71,0x56,0x38,0x90,0xf1,0x33,0x3a,0x09,0xc4,0x49,0x79,0x03,
    0xf7,0x6e,0x9c,0xf0,0xf7,0x0a,0x61,0xc7,0x91,0xe2,0x09,0xa5,
    0x25,0x6d,0xe0,0x38,0x1a,0x16,0x87,0x39,0xe1,0xcd,0xc0,0x70,
    0x5d,0x6a};

inline Fp12 final_exponentiation_naive(const Fp12& f) {
    // easy part f^(p^6-1) = conj(f) * f^-1, then the folded pow
    Fp12 g = f12_mul(f12_conj(f), f12_inv(f));
    return f12_pow_be(g, FINAL_E_BE, sizeof FINAL_E_BE);
}

// --- Frobenius + fast final exponentiation ---------------------------------

// generic Fq2 pow over a big-endian exponent
inline Fp2 f2_pow_be(const Fp2& a, const uint8_t* e, size_t elen) {
    Fp2 out = f2_one();
    bool started = false;
    for (size_t i = 0; i < elen; i++) {
        for (int b = 7; b >= 0; b--) {
            if (started) out = f2_sqr(out);
            if ((e[i] >> b) & 1) {
                if (started) out = f2_mul(out, a);
                else { out = a; started = true; }
            }
        }
    }
    return started ? out : f2_one();
}

// (p - 1) / 6, big-endian — the Frobenius gamma exponent
static const uint8_t PM16_BE[48] = {
    0x04,0x55,0x82,0xfc,0x5e,0xea,0xa6,0x6f,0x0c,0x84,0x9b,0xf3,
    0xb5,0xe1,0xf2,0x23,0xe6,0x13,0xe1,0xeb,0x7d,0xeb,0x83,0x1f,
    0xe6,0x88,0x23,0x1a,0xd3,0xc8,0x29,0x06,0x05,0x1c,0xaa,0xaa,
    0x72,0xe3,0x55,0x55,0x49,0xaa,0x7f,0xff,0xff,0xff,0xf1,0xc7};

struct FrobConsts {
    Fp2 gamma[6];      // gamma[i] = xi^(i*(p-1)/6); gamma[0] = 1
};

inline const FrobConsts& frob_consts() {
    static FrobConsts k = [] {
        FrobConsts c;
        Fp2 xi = {fp_one(), fp_one()};            // 1 + u
        c.gamma[0] = f2_one();
        c.gamma[1] = f2_pow_be(xi, PM16_BE, 48);
        for (int i = 2; i < 6; i++)
            c.gamma[i] = f2_mul(c.gamma[i - 1], c.gamma[1]);
        return c;
    }();
    return k;
}

// f^p: conjugate each Fq2 coefficient, multiply the w^i coefficient
// by gamma[i].  Coefficient i of w^i:  [b0.a0, b1.a0, b0.a1, b1.a1,
// b0.a2, b1.a2]  (w^2 = v).
inline Fp12 f12_frobenius(const Fp12& f) {
    const FrobConsts& k = frob_consts();
    auto cm = [&](const Fp2& c, int i) {
        return f2_mul(Fp2{c.c0, fp_neg(c.c1)}, k.gamma[i]);
    };
    Fp12 r;
    r.b0.a0 = cm(f.b0.a0, 0);
    r.b1.a0 = cm(f.b1.a0, 1);
    r.b0.a1 = cm(f.b0.a1, 2);
    r.b1.a1 = cm(f.b1.a1, 3);
    r.b0.a2 = cm(f.b0.a2, 4);
    r.b1.a2 = cm(f.b1.a2, 5);
    return r;
}

// --- the psi endomorphism on E'(Fq2) ---------------------------------------
// psi = twist ∘ Frobenius ∘ untwist:  (x, y) -> (x̄·γ^-2, ȳ·γ^-3),
// γ = ξ^((p-1)/6) (= frob_consts().gamma[1]).  On G2 its eigenvalue
// is z (the BLS parameter), which gives the Scott subgroup check and
// the Budroni–Pintore cofactor clearing below; both are validated
// against the plain scalar-multiplication paths by the differential
// tests (the python golden model clears with h_eff and checks the
// subgroup with [r]P).

static const uint8_t Z_ABS_BE[8] = {
    0xd2,0x01,0x00,0x00,0x00,0x01,0x00,0x00};

struct PsiConsts {
    Fp2 c2, c3;
};

inline const PsiConsts& psi_consts() {
    static const PsiConsts k = [] {
        const FrobConsts& f = frob_consts();
        PsiConsts c;
        c.c2 = f2_inv(f.gamma[2]);
        c.c3 = f2_inv(f.gamma[3]);
        return c;
    }();
    return k;
}

inline G2 g2_psi(const G2& p) {
    if (p.inf) return p;
    const PsiConsts& k = psi_consts();
    return {f2_mul(Fp2{p.x.c0, fp_neg(p.x.c1)}, k.c2),
            f2_mul(Fp2{p.y.c0, fp_neg(p.y.c1)}, k.c3), false};
}

inline G2 g2_neg_pt(const G2& p) {
    return {p.x, f2_neg(p.y), p.inf};
}

// [z]P with z < 0: negate the |z| multiple
inline G2 g2_mul_z(const G2& p) {
    return g2_neg_pt(G2_mul_be_fast(p, Z_ABS_BE, sizeof Z_ABS_BE));
}

// Budroni–Pintore efficient cofactor clearing for BLS12 G2:
//   [z^2 - z - 1]P + [z - 1]ψ(P) + ψ^2(2P)   ( = [h_eff]P )
inline G2 g2_clear_cofactor(const G2& p) {
    if (p.inf) return p;
    G2 zp = g2_mul_z(p);                       // [z]P
    G2 z2p = g2_mul_z(zp);                     // [z^2]P
    G2 acc = G2_add(z2p, g2_neg_pt(zp));       // [z^2 - z]P
    acc = G2_add(acc, g2_neg_pt(p));           // [z^2 - z - 1]P
    G2 pp = g2_psi(p);
    G2 zpp = g2_mul_z(pp);                     // [z]ψ(P)
    acc = G2_add(acc, G2_add(zpp, g2_neg_pt(pp)));
    return G2_add(acc, g2_psi(g2_psi(G2_double(p))));
}

// Scott fast subgroup membership: P in G2 iff ψ(P) = [z]P (the ψ
// eigenvalue on G2 is z) — a 64-bit ladder instead of the 255-bit
// [r]P == O check
inline bool g2_in_subgroup(const G2& p) {
    if (!g2_on_curve(p)) return false;
    if (p.inf) return true;
    G2 zp = g2_mul_z(p);
    G2 ps = g2_psi(p);
    if (ps.inf || zp.inf) return ps.inf == zp.inf;
    return f2_eq(ps.x, zp.x) && f2_eq(ps.y, zp.y);
}

// Granger–Scott cyclotomic squaring — valid ONLY for unitary
// elements (the final exponentiation's post-easy-part values): 9 Fq2
// squarings instead of f12_sqr's 12 Fq2 muls.  The component mapping
// was derived numerically against the python golden model
// (cyc_sqr(g) == g^2 for g = f^((p^6-1)(p^2+1))) and is re-asserted
// by the runtime selftest.
inline Fp12 f12_sqr_cyc(const Fp12& x) {
    Fp2 t0 = f2_sqr(x.b1.a1), t1 = f2_sqr(x.b0.a0);
    Fp2 t6 = f2_sub(f2_sub(f2_sqr(f2_add(x.b1.a1, x.b0.a0)), t0),
                    t1);
    Fp2 t2 = f2_sqr(x.b0.a2), t3 = f2_sqr(x.b1.a0);
    Fp2 t7 = f2_sub(f2_sub(f2_sqr(f2_add(x.b0.a2, x.b1.a0)), t2),
                    t3);
    Fp2 t4 = f2_sqr(x.b1.a2), t5 = f2_sqr(x.b0.a1);
    Fp2 t8 = f2_mul_xi(f2_sub(
        f2_sub(f2_sqr(f2_add(x.b1.a2, x.b0.a1)), t4), t5));
    t0 = f2_add(f2_mul_xi(t0), t1);
    t2 = f2_add(f2_mul_xi(t2), t3);
    t4 = f2_add(f2_mul_xi(t4), t5);
    Fp12 z;
    z.b0.a0 = f2_sub(f2_muli(t0, 3), f2_muli(x.b0.a0, 2));
    z.b0.a1 = f2_sub(f2_muli(t2, 3), f2_muli(x.b0.a1, 2));
    z.b0.a2 = f2_sub(f2_muli(t4, 3), f2_muli(x.b0.a2, 2));
    z.b1.a0 = f2_add(f2_muli(t8, 3), f2_muli(x.b1.a0, 2));
    z.b1.a1 = f2_add(f2_muli(t6, 3), f2_muli(x.b1.a1, 2));
    z.b1.a2 = f2_add(f2_muli(t7, 3), f2_muli(x.b1.a2, 2));
    return z;
}

// m^u with u = |x| = 0xD201000000010000; m must be unitary (only the
// final exponentiation's hard part calls this)
inline Fp12 f12_pow_u(const Fp12& m) {
    Fp12 out = m;                     // leading bit
    for (int i = 62; i >= 0; i--) {
        out = f12_sqr_cyc(out);
        if ((ATE_LOOP >> i) & 1) out = f12_mul(out, m);
    }
    return out;
}

inline Fp12 final_exponentiation(const Fp12& f) {
    // easy part: g = f^((p^6-1)(p^2+1)) — in the cyclotomic subgroup,
    // where inverse == conjugate
    Fp12 g = f12_mul(f12_conj(f), f12_inv(f));          // ^(p^6-1)
    g = f12_mul(f12_frobenius(f12_frobenius(g)), g);    // ^(p^2+1)
    // hard part cubed (Hayashida-style decomposition; exact identity
    // verified offline:  3*((p^4-p^2+1)/r) =
    //   (x-1)^2 (x+p) (x^2+p^2-1) + 3,  x = -u):
    // the result is naive^3, and since gcd(3, r) = 1 the ==1 verdict
    // is unchanged (the module's only consumer).
    Fp12 t1 = f12_conj(f12_mul(f12_pow_u(g), g));       // g^(x-1)
    Fp12 t2 = f12_conj(f12_mul(f12_pow_u(t1), t1));     // ^(x-1)
    Fp12 t3 = f12_mul(f12_conj(f12_pow_u(t2)),          // ^(x+p)
                      f12_frobenius(t2));
    Fp12 t4 = f12_mul(
        f12_mul(f12_pow_u(f12_pow_u(t3)),               // ^(x^2)
                f12_frobenius(f12_frobenius(t3))),      // ^(p^2)
        f12_conj(t3));                                  // ^(-1)
    Fp12 g3 = f12_mul(f12_sqr(g), g);
    return f12_mul(t4, g3);
}

// startup self-check: Frobenius vs a plain ^p pow, and the fast final
// exponentiation (naive^3) vs the naive one, on a derived element —
// any algebra slip fails loudly before a verdict is ever produced
inline bool selftest() {
    // the ADX multiplier must agree with the generic CIOS on a
    // pseudo-random walk (covers carry/edge behavior cheaply; any
    // miscompiled or mis-scheduled asm fails before first use)
    {
        uint64_t s = 0x243f6a8885a308d3ULL;
        Fp x = fp_one(), y;
        for (int i = 0; i < 6; i++) {
            s = s * 6364136223846793005ULL + 1442695040888963407ULL;
            y.v[i] = s;
        }
        y.v[5] &= 0x0fffffffffffffffULL;
        for (int i = 0; i < 64; i++) {
            Fp fast = fp_mul(x, y);
            if (!fp_eq(fast, fp_mul_generic(x, y))) return false;
            x = fast;
            y = fp_add(y, fp_one());
        }
        Fp pm1;
        std::memcpy(pm1.v, P_LIMBS, sizeof pm1.v);
        pm1.v[0] -= 1;        // p-1 in raw form exercises top carries
        if (!fp_eq(fp_mul(pm1, pm1), fp_mul_generic(pm1, pm1)))
            return false;
    }
    // a "random" fp12 from small constants
    Fp12 f = f12_zero();
    uint64_t seed = 0x9e3779b97f4a7c15ULL;
    Fp2* coeffs[6] = {&f.b0.a0, &f.b1.a0, &f.b0.a1,
                      &f.b1.a1, &f.b0.a2, &f.b1.a2};
    for (int i = 0; i < 6; i++) {
        seed = seed * 6364136223846793005ULL + 1442695040888963407ULL;
        coeffs[i]->c0 = fp_from_u64(seed >> 8);
        seed = seed * 6364136223846793005ULL + 1442695040888963407ULL;
        coeffs[i]->c1 = fp_from_u64(seed >> 8);
    }
    // P big-endian = PM2 + 2
    uint8_t p_be[48];
    std::memcpy(p_be, PM2_BE, 48);
    p_be[47] = uint8_t(p_be[47] + 2);
    if (!f12_eq(f12_frobenius(f), f12_pow_be(f, p_be, 48)))
        return false;
    // cyclotomic squaring must agree with the generic squaring on a
    // unitary element (the easy-part image of f)
    Fp12 g = f12_mul(f12_conj(f), f12_inv(f));
    g = f12_mul(f12_frobenius(f12_frobenius(g)), g);
    if (!f12_eq(f12_sqr_cyc(g), f12_sqr(g))) return false;
    Fp12 naive = final_exponentiation_naive(f);
    Fp12 naive3 = f12_mul(f12_sqr(naive), naive);
    return f12_eq(final_exponentiation(f), naive3);
}

inline bool selftest_psi();   // defined after the hash-to-G2 block

struct Pair {
    G1 p;
    G2 q;
};

inline bool pairings_product_is_one(const std::vector<Pair>& pairs) {
    Fp12 f = f12_one();
    for (const Pair& pr : pairs) {
        if (pr.p.inf || pr.q.inf) continue;
        f = f12_mul(f, miller_loop(pr.q, pr.p));
    }
    return f12_eq(final_exponentiation(f), f12_one());
}

// --- hash to G2 (mirrors the python module's custom map) --------------------

inline void sha256_digest(const uint8_t* d, size_t n, uint8_t out[32]) {
    sha256::hash(d, n, out);
}

inline void expand_message_xmd(const uint8_t* msg, size_t msg_len,
                               const uint8_t* dst, size_t dst_len,
                               size_t out_len, uint8_t* out) {
    // RFC 9380 §5.3.1 with SHA-256 (lengths validated by the caller)
    size_t ell = (out_len + 31) / 32;
    std::vector<uint8_t> buf;
    buf.assign(64, 0);                         // z_pad
    buf.insert(buf.end(), msg, msg + msg_len);
    buf.push_back(uint8_t(out_len >> 8));
    buf.push_back(uint8_t(out_len));
    buf.push_back(0);
    buf.insert(buf.end(), dst, dst + dst_len);
    buf.push_back(uint8_t(dst_len));
    uint8_t b0[32];
    sha256_digest(buf.data(), buf.size(), b0);

    std::vector<uint8_t> round;
    round.assign(b0, b0 + 32);
    round.push_back(1);
    round.insert(round.end(), dst, dst + dst_len);
    round.push_back(uint8_t(dst_len));
    uint8_t prev[32];
    sha256_digest(round.data(), round.size(), prev);
    size_t written = 0;
    for (size_t i = 1; i <= ell && written < out_len; i++) {
        size_t take = out_len - written < 32 ? out_len - written : 32;
        std::memcpy(out + written, prev, take);
        written += take;
        if (i == ell) break;
        round.clear();
        for (int j = 0; j < 32; j++)
            round.push_back(b0[j] ^ prev[j]);
        round.push_back(uint8_t(i + 1));
        round.insert(round.end(), dst, dst + dst_len);
        round.push_back(uint8_t(dst_len));
        sha256_digest(round.data(), round.size(), prev);
    }
}

// 64-byte big-endian -> Fp (mod p), for hash_to_field
inline Fp fp_from_be64_mod(const uint8_t* b) {
    // incremental: r = r*256 + byte (in standard form via Montgomery)
    Fp r = fp_zero();
    Fp c256 = fp_from_u64(256);
    for (int i = 0; i < 64; i++) {
        r = fp_add(fp_mul(r, c256), fp_from_u64(b[i]));
    }
    return r;
}

inline int sgn0_fq2(const Fp2& a) {
    bool s0 = fp_is_odd(a.c0);
    bool z0 = fp_is_zero(a.c0);
    return s0 || (z0 && fp_is_odd(a.c1));
}

// h_eff = h2 * (3z^2 - 3) (RFC 9380 §8.8.2 cofactor clearing; the
// closed form is asserted against the curve's z parameter in the
// python golden model's tests)
static const uint8_t H_EFF_BE[80] = {
    0x0b,0xc6,0x9f,0x08,0xf2,0xee,0x75,0xb3,0x58,0x4c,0x6a,0x0e,
    0xa9,0x1b,0x35,0x28,0x88,0xe2,0xa8,0xe9,0x14,0x5a,0xd7,0x68,
    0x99,0x86,0xff,0x03,0x15,0x08,0xff,0xe1,0x32,0x9c,0x2f,0x17,
    0x87,0x31,0xdb,0x95,0x6d,0x82,0xbf,0x01,0x5d,0x12,0x12,0xb0,
    0x2e,0xc0,0xec,0x69,0xd7,0x47,0x7c,0x1a,0xe9,0x54,0xcb,0xc0,
    0x66,0x89,0xf6,0xa3,0x59,0x89,0x4c,0x0a,0xde,0xbb,0xf6,0xb4,
    0xe8,0x02,0x00,0x05,0xaa,0xa9,0x55,0x51};

// RFC 9380 §6.6.2 simplified SWU onto the 3-isogenous curve
//   E': y^2 = x^3 + A'x + B',  A' = 240i, B' = 1012(1+i), Z = -(2+i)
// then the Vélu-derived 3-isogeny to E (kernel x0 = (-6, 6); see the
// python golden model _bls12381_math.py for the offline derivation
// and its re-derivation test).
struct SswuConsts {
    Fp2 A, B, Z, x0, iso_t, iso_u, inv9, inv27;
};

inline const SswuConsts& sswu_consts() {
    static const SswuConsts c = [] {
        SswuConsts s;
        s.A = {fp_zero(), fp_from_u64(240)};
        s.B = {fp_from_u64(1012), fp_from_u64(1012)};
        s.Z = f2_neg({fp_from_u64(2), fp_one()});
        s.x0 = {fp_neg(fp_from_u64(6)), fp_from_u64(6)};
        // Vélu: t = 2(3 x0^2 + A'), u = 4(x0^3 + A' x0 + B')
        Fp2 x0sq = f2_sqr(s.x0);
        s.iso_t = f2_muli(f2_add(f2_muli(x0sq, 3), s.A), 2);
        s.iso_u = f2_muli(
            f2_add(f2_mul(x0sq, s.x0),
                   f2_add(f2_mul(s.A, s.x0), s.B)), 4);
        s.inv9 = {fp_inv(fp_from_u64(9)), fp_zero()};
        s.inv27 = {fp_inv(fp_from_u64(27)), fp_zero()};
        return s;
    }();
    return c;
}

inline G2 map_to_curve_g2(const Fp2& u) {
    const SswuConsts& cs = sswu_consts();
    Fp2 u2 = f2_sqr(u);
    Fp2 zu2 = f2_mul(cs.Z, u2);
    Fp2 tv1 = f2_add(f2_sqr(zu2), zu2);       // Z^2 u^4 + Z u^2
    Fp2 x1;
    if (f2_is_zero(tv1)) {
        x1 = f2_mul(cs.B, f2_inv(f2_mul(cs.Z, cs.A)));
    } else {
        x1 = f2_mul(f2_mul(f2_neg(cs.B), f2_inv(cs.A)),
                    f2_add(f2_one(), f2_inv(tv1)));
    }
    Fp2 gx1 = f2_add(f2_mul(f2_sqr(x1), x1),
                     f2_add(f2_mul(cs.A, x1), cs.B));
    Fp2 x = x1, y;
    if (!f2_sqrt(gx1, &y)) {
        x = f2_mul(zu2, x1);
        Fp2 gx2 = f2_add(f2_mul(f2_sqr(x), x),
                         f2_add(f2_mul(cs.A, x), cs.B));
        if (!f2_sqrt(gx2, &y))
            return {f2_zero(), f2_zero(), true};  // unreachable
    }
    if (sgn0_fq2(y) != sgn0_fq2(u)) y = f2_neg(y);
    // 3-isogeny: x_E = (x + t/d + u/d^2)/9,
    //            y_E = y (1 - t/d^2 - 2u/d^3)/27,  d = x - x0
    Fp2 d = f2_sub(x, cs.x0);
    if (f2_is_zero(d))
        return {f2_zero(), f2_zero(), true};      // kernel -> infinity
    Fp2 d2 = f2_sqr(d);
    Fp2 inv_d3 = f2_inv(f2_mul(d2, d));
    Fp2 inv_d2 = f2_mul(inv_d3, d);
    Fp2 inv_d = f2_mul(inv_d2, d);
    Fp2 xn = f2_add(x, f2_add(f2_mul(cs.iso_t, inv_d),
                              f2_mul(cs.iso_u, inv_d2)));
    Fp2 yn = f2_mul(y, f2_sub(
        f2_one(), f2_add(f2_mul(cs.iso_t, inv_d2),
                         f2_mul(f2_muli(cs.iso_u, 2), inv_d3))));
    // z = -3 isomorphism branch (y -> -y/27): RFC 9380's iso_map sign
    // convention, pinned by the J.10.1 vectors in the python golden
    // model's tests (the +3 branch yields -P for every message).
    return {f2_mul(xn, cs.inv9), f2_neg(f2_mul(yn, cs.inv27)), false};
}

inline G2 hash_to_g2(const uint8_t* msg, size_t msg_len,
                     const uint8_t* dst, size_t dst_len) {
    uint8_t data[256];
    expand_message_xmd(msg, msg_len, dst, dst_len, 256, data);
    Fp2 u0 = {fp_from_be64_mod(data), fp_from_be64_mod(data + 64)};
    Fp2 u1 = {fp_from_be64_mod(data + 128),
              fp_from_be64_mod(data + 192)};
    G2 q = G2_add(map_to_curve_g2(u0), map_to_curve_g2(u1));
    return g2_clear_cofactor(q);
}

// ψ machinery self-check: Budroni–Pintore cofactor clearing must
// equal the plain [h_eff]P on a non-subgroup curve point (an
// endomorphism identity — any slip in γ/ψ or the formula fails
// here), and the Scott subgroup check must agree with [r]P == O on
// --- ZCash-flag compressed-point parsing ------------------------------------
// (python golden model: _bls12381_math.py g1_uncompress/g2_uncompress;
// reference behavior: blst's Uncompress behind key_bls12381.go)

inline bool fp_y_larger(const Fp& y) {
    // y > (p-1)/2  ⟺  y > p - y in standard form (y = 0 -> false)
    uint8_t a[48], b[48];
    fp_to_be48(y, a);
    fp_to_be48(fp_neg(y), b);
    return std::memcmp(a, b, 48) > 0;
}

inline bool f2_y_larger(const Fp2& y) {
    if (!fp_is_zero(y.c1)) return fp_y_larger(y.c1);
    return fp_y_larger(y.c0);
}

// compressed 48B -> G1; 0 = point, 1 = infinity, -1 = invalid
inline int g1_uncompress(const uint8_t* in, G1* out) {
    uint8_t flags = in[0];
    if (!(flags & 0x80)) return -1;
    if (flags & 0x40) {
        if (flags & 0x3f) return -1;
        for (int i = 1; i < 48; i++)
            if (in[i]) return -1;
        return 1;
    }
    uint8_t xbe[48];
    std::memcpy(xbe, in, 48);
    xbe[0] &= 0x1f;
    Fp x;
    if (!fp_from_be48(xbe, &x)) return -1;
    Fp gx = fp_add(fp_mul(fp_sqr(x), x), fp_from_u64(4));
    Fp y;
    if (!fp_sqrt(gx, &y)) return -1;
    if (fp_y_larger(y) != bool(flags & 0x20)) y = fp_neg(y);
    out->x = x;
    out->y = y;
    out->inf = false;
    return 0;
}

// compressed 96B -> G2; 0 = point, 1 = infinity, -1 = invalid
inline int g2_uncompress(const uint8_t* in, G2* out) {
    uint8_t flags = in[0];
    if (!(flags & 0x80)) return -1;
    if (flags & 0x40) {
        if (flags & 0x3f) return -1;
        for (int i = 1; i < 96; i++)
            if (in[i]) return -1;
        return 1;
    }
    uint8_t x1be[48];
    std::memcpy(x1be, in, 48);
    x1be[0] &= 0x1f;
    Fp2 x;
    if (!fp_from_be48(x1be, &x.c1)) return -1;
    if (!fp_from_be48(in + 48, &x.c0)) return -1;
    Fp f4 = fp_from_u64(4);
    Fp2 b2 = {f4, f4};                      // 4(1+i)
    Fp2 gx = f2_add(f2_mul(f2_sqr(x), x), b2);
    Fp2 y;
    if (!f2_sqrt(gx, &y)) return -1;
    if (f2_y_larger(y) != bool(flags & 0x20)) y = f2_neg(y);
    out->x = x;
    out->y = y;
    out->inf = false;
    return 0;
}

// both a G2 point and a non-subgroup point.
inline bool selftest_psi() {
    Fp2 u = {fp_from_u64(0x1234567), fp_from_u64(0x89abcd)};
    G2 h = map_to_curve_g2(u);
    G2 want = G2_mul_be_fast(h, H_EFF_BE, sizeof H_EFF_BE);
    G2 got = g2_clear_cofactor(h);
    if (want.inf != got.inf) return false;
    if (!want.inf &&
        (!f2_eq(want.x, got.x) || !f2_eq(want.y, got.y)))
        return false;
    if (!g2_in_subgroup(got)) return false;
    if (!G2_mul_be_fast(got, R_BE, 32).inf) return false;
    if (g2_in_subgroup(h) != G2_mul_be_fast(h, R_BE, 32).inf)
        return false;
    return true;
}

// --- many-point affine sum (aggregate-commit assembly/verify) ---------------
// Pairwise tree reduction with Montgomery-batched inversions: each
// round halves the point count, sharing ONE field inversion across
// every pairwise addition (~6 field muls per add vs ~16 for the
// Jacobian ladder).  This is the O(n) residue of aggregate-commit
// verification — the G1 pubkey sum — so constant factors matter.
// Field-overloaded helpers let one template serve G1 (Fp) and G2
// (Fp2).

inline Fp fld_add(const Fp& a, const Fp& b) { return fp_add(a, b); }
inline Fp fld_sub(const Fp& a, const Fp& b) { return fp_sub(a, b); }
inline Fp fld_mul(const Fp& a, const Fp& b) { return fp_mul(a, b); }
inline Fp fld_sqr(const Fp& a) { return fp_sqr(a); }
inline Fp fld_inv(const Fp& a) { return fp_inv(a); }
inline Fp fld_muli(const Fp& a, int k) { return fp_muli(a, k); }
inline bool fld_is_zero(const Fp& a) { return fp_is_zero(a); }
inline bool fld_eq(const Fp& a, const Fp& b) { return fp_eq(a, b); }
inline Fp2 fld_add(const Fp2& a, const Fp2& b) { return f2_add(a, b); }
inline Fp2 fld_sub(const Fp2& a, const Fp2& b) { return f2_sub(a, b); }
inline Fp2 fld_mul(const Fp2& a, const Fp2& b) { return f2_mul(a, b); }
inline Fp2 fld_sqr(const Fp2& a) { return f2_sqr(a); }
inline Fp2 fld_inv(const Fp2& a) { return f2_inv(a); }
inline Fp2 fld_muli(const Fp2& a, int k) { return f2_muli(a, k); }
inline bool fld_is_zero(const Fp2& a) { return f2_is_zero(a); }
inline bool fld_eq(const Fp2& a, const Fp2& b) { return f2_eq(a, b); }
inline void fld_set_one(Fp* out) { *out = fp_one(); }
inline void fld_set_one(Fp2* out) { *out = f2_one(); }

// one batched-inversion round: pts[0..n) -> pts[0..ceil(n/2)).
// Pairs with x1 == x2 take the doubling (denominator 2y) or cancel
// to infinity; infinities are compacted out between rounds.
template <typename PT, typename F>
inline size_t sum_affine_round(PT* pts, size_t n, F* den, F* pre) {
    size_t pairs = n / 2;
    // denominators: x2 - x1, or 2y for the doubling case; zero
    // denominators (cancellation) are replaced by 1 and the pair is
    // resolved without the inverse.
    for (size_t i = 0; i < pairs; i++) {
        const PT& a = pts[2 * i];
        const PT& b = pts[2 * i + 1];
        if (fld_eq(a.x, b.x)) {
            den[i] = fld_muli(a.y, 2);       // doubling: 2y
        } else {
            den[i] = fld_sub(b.x, a.x);      // chord: x2 - x1
        }
        // cancelling pairs (y2 = -y1, incl. the y = 0 order-2 case on
        // adversarial off-curve input) resolve to infinity without an
        // inverse; a 1 keeps the batched product invertible
        if (fld_is_zero(den[i])) fld_set_one(&den[i]);
    }
    // Montgomery batch inversion over den[0..pairs)
    if (pairs) {
        pre[0] = den[0];
        for (size_t i = 1; i < pairs; i++)
            pre[i] = fld_mul(pre[i - 1], den[i]);
        F inv_all = fld_inv(pre[pairs - 1]);
        for (size_t i = pairs; i-- > 1;) {
            F inv_i = fld_mul(inv_all, pre[i - 1]);
            inv_all = fld_mul(inv_all, den[i]);
            den[i] = inv_i;
        }
        den[0] = inv_all;
    }
    size_t out = 0;
    for (size_t i = 0; i < pairs; i++) {
        const PT& a = pts[2 * i];
        const PT& b = pts[2 * i + 1];
        F m;
        if (fld_eq(a.x, b.x)) {
            if (!fld_eq(a.y, b.y) || fld_is_zero(a.y))
                continue;                    // a + (-a) = infinity
            m = fld_mul(fld_muli(fld_sqr(a.x), 3), den[i]);  // 3x^2/2y
        } else {
            m = fld_mul(fld_sub(b.y, a.y), den[i]);
        }
        PT r;
        r.x = fld_sub(fld_sub(fld_sqr(m), a.x), b.x);
        r.y = fld_sub(fld_mul(m, fld_sub(a.x, r.x)), a.y);
        r.inf = false;
        pts[out++] = r;
    }
    if (n & 1) pts[out++] = pts[n - 1];      // odd leftover rides along
    return out;
}

template <typename PT, typename F>
inline PT sum_affine(PT* pts, size_t n, F* scratch_a, F* scratch_b) {
    while (n > 1)
        n = sum_affine_round<PT, F>(pts, n, scratch_a, scratch_b);
    if (n == 0) { PT r{}; r.inf = true; return r; }
    return pts[0];
}

}  // namespace bls
