// BLS12-381 pairing arithmetic in C++ — the native fast path for the
// engine's verify-side BLS (reference parity note: the reference's one
// native dependency is the blst C library; this is the analogous
// native component, built against OUR pure-python golden model in
// cometbft_tpu/crypto/_bls12381_math.py).
//
// The structure mirrors the python module one-to-one — same tower
// (Fq2 = Fq[u]/(u^2+1), Fq6 = Fq2[v]/(v^3-(1+u)), Fq12 = Fq6[w]/
// (w^2-v)), same affine point formulas, same optimal-ate Miller loop
// in E(Fq12), same naive final exponentiation, same custom
// hash-to-curve (expand_message_xmd + try-and-increment; see the
// python module docstring) — so every function can be differentially
// tested against the golden model.  Fq uses 6x64 Montgomery
// arithmetic (CIOS) for speed; everything above it is formula-
// identical.
#pragma once

#include <cstdint>
#include <cstring>
#include <vector>

#include "sha256.hpp"

namespace bls {

// --- Fq: 6x64-limb Montgomery ----------------------------------------------

struct Fp {
    uint64_t v[6];
};

static const uint64_t P_LIMBS[6] = {
    0xb9feffffffffaaabULL, 0x1eabfffeb153ffffULL,
    0x6730d2a0f6b0f624ULL, 0x64774b84f38512bfULL,
    0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL};
static const uint64_t N0 = 0x89f3fffcfffcfffdULL;
static const uint64_t R1_LIMBS[6] = {
    0x760900000002fffdULL, 0xebf4000bc40c0002ULL,
    0x5f48985753c758baULL, 0x77ce585370525745ULL,
    0x5c071a97a256ec6dULL, 0x15f65ec3fa80e493ULL};
static const uint64_t R2_LIMBS[6] = {
    0xf4df1f341c341746ULL, 0x0a76e6a609d104f1ULL,
    0x8de5476c4c95b6d5ULL, 0x67eb88a9939d83c0ULL,
    0x9a793e85b519952dULL, 0x11988fe592cae3aaULL};

inline Fp fp_zero() { Fp r{}; return r; }
inline Fp fp_one() {
    Fp r;
    std::memcpy(r.v, R1_LIMBS, sizeof r.v);
    return r;
}

inline bool fp_is_zero(const Fp& a) {
    uint64_t acc = 0;
    for (int i = 0; i < 6; i++) acc |= a.v[i];
    return acc == 0;
}

inline bool fp_eq(const Fp& a, const Fp& b) {
    uint64_t acc = 0;
    for (int i = 0; i < 6; i++) acc |= a.v[i] ^ b.v[i];
    return acc == 0;
}

inline int fp_cmp_raw(const uint64_t a[6], const uint64_t b[6]) {
    for (int i = 5; i >= 0; i--) {
        if (a[i] < b[i]) return -1;
        if (a[i] > b[i]) return 1;
    }
    return 0;
}

inline void raw_sub_p(uint64_t a[6]) {
    unsigned __int128 borrow = 0;
    for (int i = 0; i < 6; i++) {
        unsigned __int128 d =
            (unsigned __int128)a[i] - P_LIMBS[i] - (uint64_t)borrow;
        a[i] = uint64_t(d);
        borrow = (d >> 64) ? 1 : 0;
    }
}

inline Fp fp_add(const Fp& a, const Fp& b) {
    Fp r;
    unsigned __int128 carry = 0;
    for (int i = 0; i < 6; i++) {
        unsigned __int128 s =
            (unsigned __int128)a.v[i] + b.v[i] + (uint64_t)carry;
        r.v[i] = uint64_t(s);
        carry = s >> 64;
    }
    if (carry || fp_cmp_raw(r.v, P_LIMBS) >= 0) raw_sub_p(r.v);
    return r;
}

inline Fp fp_sub(const Fp& a, const Fp& b) {
    Fp r;
    unsigned __int128 borrow = 0;
    for (int i = 0; i < 6; i++) {
        unsigned __int128 d =
            (unsigned __int128)a.v[i] - b.v[i] - (uint64_t)borrow;
        r.v[i] = uint64_t(d);
        borrow = (d >> 64) ? 1 : 0;
    }
    if (borrow) {
        unsigned __int128 carry = 0;
        for (int i = 0; i < 6; i++) {
            unsigned __int128 s =
                (unsigned __int128)r.v[i] + P_LIMBS[i] +
                (uint64_t)carry;
            r.v[i] = uint64_t(s);
            carry = s >> 64;
        }
    }
    return r;
}

inline Fp fp_neg(const Fp& a) {
    if (fp_is_zero(a)) return a;
    Fp p;
    std::memcpy(p.v, P_LIMBS, sizeof p.v);
    return fp_sub(p, a);
}

// CIOS Montgomery multiplication
inline Fp fp_mul(const Fp& a, const Fp& b) {
    uint64_t t[8] = {0};
    for (int i = 0; i < 6; i++) {
        unsigned __int128 carry = 0;
        for (int j = 0; j < 6; j++) {
            unsigned __int128 cur =
                (unsigned __int128)a.v[i] * b.v[j] + t[j] +
                (uint64_t)carry;
            t[j] = uint64_t(cur);
            carry = cur >> 64;
        }
        unsigned __int128 s =
            (unsigned __int128)t[6] + (uint64_t)carry;
        t[6] = uint64_t(s);
        t[7] = uint64_t(s >> 64);

        uint64_t m = t[0] * N0;
        carry = 0;
        {
            unsigned __int128 cur =
                (unsigned __int128)m * P_LIMBS[0] + t[0];
            carry = cur >> 64;
        }
        for (int j = 1; j < 6; j++) {
            unsigned __int128 cur =
                (unsigned __int128)m * P_LIMBS[j] + t[j] +
                (uint64_t)carry;
            t[j - 1] = uint64_t(cur);
            carry = cur >> 64;
        }
        s = (unsigned __int128)t[6] + (uint64_t)carry;
        t[5] = uint64_t(s);
        t[6] = t[7] + uint64_t(s >> 64);
        t[7] = 0;
    }
    Fp r;
    std::memcpy(r.v, t, sizeof r.v);
    if (t[6] || fp_cmp_raw(r.v, P_LIMBS) >= 0) raw_sub_p(r.v);
    return r;
}

inline Fp fp_sqr(const Fp& a) { return fp_mul(a, a); }

inline Fp fp_muli(const Fp& a, int k) {
    Fp out = a;
    for (int i = 1; i < k; i++) out = fp_add(out, a);
    return out;
}

// generic pow over a big-endian exponent byte string
inline Fp fp_pow_be(const Fp& a, const uint8_t* e, size_t elen) {
    Fp out = fp_one();
    bool started = false;
    for (size_t i = 0; i < elen; i++) {
        for (int b = 7; b >= 0; b--) {
            if (started) out = fp_sqr(out);
            if ((e[i] >> b) & 1) {
                if (started) out = fp_mul(out, a);
                else { out = a; started = true; }
            }
        }
    }
    return started ? out : fp_one();
}

static const uint8_t PM2_BE[48] = {
    0x1a,0x01,0x11,0xea,0x39,0x7f,0xe6,0x9a,0x4b,0x1b,0xa7,0xb6,
    0x43,0x4b,0xac,0xd7,0x64,0x77,0x4b,0x84,0xf3,0x85,0x12,0xbf,
    0x67,0x30,0xd2,0xa0,0xf6,0xb0,0xf6,0x24,0x1e,0xab,0xff,0xfe,
    0xb1,0x53,0xff,0xff,0xb9,0xfe,0xff,0xff,0xff,0xff,0xaa,0xa9};
static const uint8_t PP14_BE[48] = {
    0x06,0x80,0x44,0x7a,0x8e,0x5f,0xf9,0xa6,0x92,0xc6,0xe9,0xed,
    0x90,0xd2,0xeb,0x35,0xd9,0x1d,0xd2,0xe1,0x3c,0xe1,0x44,0xaf,
    0xd9,0xcc,0x34,0xa8,0x3d,0xac,0x3d,0x89,0x07,0xaa,0xff,0xff,
    0xac,0x54,0xff,0xff,0xee,0x7f,0xbf,0xff,0xff,0xff,0xea,0xab};
inline Fp fp_inv(const Fp& a) { return fp_pow_be(a, PM2_BE, 48); }

// from/to big-endian 48-byte standard form
inline bool fp_from_be48(const uint8_t* b, Fp* out) {
    uint64_t raw[6];
    for (int i = 0; i < 6; i++) {
        uint64_t v = 0;
        for (int j = 0; j < 8; j++)
            v = (v << 8) | b[(5 - i) * 8 + j];
        raw[i] = v;
    }
    if (fp_cmp_raw(raw, P_LIMBS) >= 0) return false;
    Fp t, r2;
    std::memcpy(t.v, raw, sizeof t.v);
    std::memcpy(r2.v, R2_LIMBS, sizeof r2.v);
    *out = fp_mul(t, r2);      // to Montgomery
    return true;
}

inline void fp_to_be48(const Fp& a, uint8_t* out) {
    // from Montgomery: multiply by 1
    Fp one{};
    one.v[0] = 1;
    Fp std_form = fp_mul(a, one);
    for (int i = 0; i < 6; i++)
        for (int j = 0; j < 8; j++)
            out[(5 - i) * 8 + j] =
                uint8_t(std_form.v[i] >> (56 - 8 * j));
}

inline Fp fp_from_u64(uint64_t x) {
    Fp t{}, r2;
    t.v[0] = x;
    std::memcpy(r2.v, R2_LIMBS, sizeof r2.v);
    return fp_mul(t, r2);
}

inline bool fp_is_odd(const Fp& a) {
    uint8_t be[48];
    fp_to_be48(a, be);
    return be[47] & 1;
}

// sqrt via (p+1)/4 (p % 4 == 3); false if non-square
inline bool fp_sqrt(const Fp& a, Fp* out) {
    Fp r = fp_pow_be(a, PP14_BE, 48);
    if (!fp_eq(fp_sqr(r), a)) return false;
    *out = r;
    return true;
}

// --- Fq2 --------------------------------------------------------------------

struct Fp2 {
    Fp c0, c1;
};

inline Fp2 f2_zero() { return {fp_zero(), fp_zero()}; }
inline Fp2 f2_one() { return {fp_one(), fp_zero()}; }
inline bool f2_is_zero(const Fp2& a) {
    return fp_is_zero(a.c0) && fp_is_zero(a.c1);
}
inline bool f2_eq(const Fp2& a, const Fp2& b) {
    return fp_eq(a.c0, b.c0) && fp_eq(a.c1, b.c1);
}
inline Fp2 f2_add(const Fp2& a, const Fp2& b) {
    return {fp_add(a.c0, b.c0), fp_add(a.c1, b.c1)};
}
inline Fp2 f2_sub(const Fp2& a, const Fp2& b) {
    return {fp_sub(a.c0, b.c0), fp_sub(a.c1, b.c1)};
}
inline Fp2 f2_neg(const Fp2& a) {
    return {fp_neg(a.c0), fp_neg(a.c1)};
}
inline Fp2 f2_mul(const Fp2& a, const Fp2& b) {
    Fp t0 = fp_mul(a.c0, b.c0);
    Fp t1 = fp_mul(a.c1, b.c1);
    Fp s = fp_mul(fp_add(a.c0, a.c1), fp_add(b.c0, b.c1));
    return {fp_sub(t0, t1), fp_sub(fp_sub(s, t0), t1)};
}
inline Fp2 f2_sqr(const Fp2& a) {
    Fp s = fp_mul(fp_add(a.c0, a.c1), fp_sub(a.c0, a.c1));
    Fp d = fp_mul(a.c0, a.c1);
    return {s, fp_add(d, d)};
}
inline Fp2 f2_muli(const Fp2& a, int k) {
    return {fp_muli(a.c0, k), fp_muli(a.c1, k)};
}
inline Fp2 f2_inv(const Fp2& a) {
    Fp d = fp_inv(fp_add(fp_sqr(a.c0), fp_sqr(a.c1)));
    return {fp_mul(a.c0, d), fp_neg(fp_mul(a.c1, d))};
}
inline Fp2 f2_mul_xi(const Fp2& a) {
    // * (1 + u)
    return {fp_sub(a.c0, a.c1), fp_add(a.c0, a.c1)};
}

// sqrt in Fq2, mirroring the python norm-trick implementation
inline bool f2_sqrt(const Fp2& a, Fp2* out) {
    if (fp_is_zero(a.c1)) {
        Fp r;
        if (fp_sqrt(a.c0, &r)) {
            *out = {r, fp_zero()};
            return true;
        }
        if (fp_sqrt(fp_neg(a.c0), &r)) {
            *out = {fp_zero(), r};
            return true;
        }
        return false;
    }
    Fp alpha;
    if (!fp_sqrt(fp_add(fp_sqr(a.c0), fp_sqr(a.c1)), &alpha))
        return false;
    static const Fp inv2 = fp_inv(fp_from_u64(2));
    Fp delta = fp_mul(fp_add(a.c0, alpha), inv2);
    Fp x0;
    if (!fp_sqrt(delta, &x0)) {
        delta = fp_mul(fp_sub(a.c0, alpha), inv2);
        if (!fp_sqrt(delta, &x0)) return false;
    }
    Fp x1 = fp_mul(a.c1, fp_inv(fp_add(x0, x0)));
    Fp2 cand = {x0, x1};
    if (!f2_eq(f2_sqr(cand), a)) return false;
    *out = cand;
    return true;
}

// --- Fq6, Fq12 --------------------------------------------------------------

struct Fp6 {
    Fp2 a0, a1, a2;
};
struct Fp12 {
    Fp6 b0, b1;
};

inline Fp6 f6_zero() { return {f2_zero(), f2_zero(), f2_zero()}; }
inline Fp6 f6_one() { return {f2_one(), f2_zero(), f2_zero()}; }
inline bool f6_eq(const Fp6& a, const Fp6& b) {
    return f2_eq(a.a0, b.a0) && f2_eq(a.a1, b.a1) &&
           f2_eq(a.a2, b.a2);
}
inline Fp6 f6_add(const Fp6& a, const Fp6& b) {
    return {f2_add(a.a0, b.a0), f2_add(a.a1, b.a1),
            f2_add(a.a2, b.a2)};
}
inline Fp6 f6_sub(const Fp6& a, const Fp6& b) {
    return {f2_sub(a.a0, b.a0), f2_sub(a.a1, b.a1),
            f2_sub(a.a2, b.a2)};
}
inline Fp6 f6_neg(const Fp6& a) {
    return {f2_neg(a.a0), f2_neg(a.a1), f2_neg(a.a2)};
}
inline Fp6 f6_mul(const Fp6& a, const Fp6& b) {
    Fp2 t0 = f2_mul(a.a0, b.a0);
    Fp2 t1 = f2_mul(a.a1, b.a1);
    Fp2 t2 = f2_mul(a.a2, b.a2);
    Fp2 c0 = f2_add(t0, f2_mul_xi(f2_sub(
        f2_mul(f2_add(a.a1, a.a2), f2_add(b.a1, b.a2)),
        f2_add(t1, t2))));
    Fp2 c1 = f2_add(f2_sub(
        f2_mul(f2_add(a.a0, a.a1), f2_add(b.a0, b.a1)),
        f2_add(t0, t1)), f2_mul_xi(t2));
    Fp2 c2 = f2_add(f2_sub(
        f2_mul(f2_add(a.a0, a.a2), f2_add(b.a0, b.a2)),
        f2_add(t0, t2)), t1);
    return {c0, c1, c2};
}
inline Fp6 f6_sqr(const Fp6& a) { return f6_mul(a, a); }
inline Fp6 f6_mul_v(const Fp6& a) {
    return {f2_mul_xi(a.a2), a.a0, a.a1};
}
inline Fp6 f6_inv(const Fp6& a) {
    Fp2 c0 = f2_sub(f2_sqr(a.a0), f2_mul_xi(f2_mul(a.a1, a.a2)));
    Fp2 c1 = f2_sub(f2_mul_xi(f2_sqr(a.a2)), f2_mul(a.a0, a.a1));
    Fp2 c2 = f2_sub(f2_sqr(a.a1), f2_mul(a.a0, a.a2));
    Fp2 t = f2_inv(f2_add(
        f2_mul(a.a0, c0),
        f2_mul_xi(f2_add(f2_mul(a.a2, c1), f2_mul(a.a1, c2)))));
    return {f2_mul(c0, t), f2_mul(c1, t), f2_mul(c2, t)};
}

inline Fp12 f12_zero() { return {f6_zero(), f6_zero()}; }
inline Fp12 f12_one() { return {f6_one(), f6_zero()}; }
inline bool f12_eq(const Fp12& a, const Fp12& b) {
    return f6_eq(a.b0, b.b0) && f6_eq(a.b1, b.b1);
}
inline Fp12 f12_add(const Fp12& a, const Fp12& b) {
    return {f6_add(a.b0, b.b0), f6_add(a.b1, b.b1)};
}
inline Fp12 f12_sub(const Fp12& a, const Fp12& b) {
    return {f6_sub(a.b0, b.b0), f6_sub(a.b1, b.b1)};
}
inline Fp12 f12_neg(const Fp12& a) {
    return {f6_neg(a.b0), f6_neg(a.b1)};
}
inline Fp12 f12_mul(const Fp12& a, const Fp12& b) {
    Fp6 t0 = f6_mul(a.b0, b.b0);
    Fp6 t1 = f6_mul(a.b1, b.b1);
    Fp6 c0 = f6_add(t0, f6_mul_v(t1));
    Fp6 c1 = f6_sub(f6_mul(f6_add(a.b0, a.b1), f6_add(b.b0, b.b1)),
                    f6_add(t0, t1));
    return {c0, c1};
}
inline Fp12 f12_sqr(const Fp12& a) { return f12_mul(a, a); }
inline Fp12 f12_inv(const Fp12& a) {
    Fp6 t = f6_inv(f6_sub(f6_sqr(a.b0), f6_mul_v(f6_sqr(a.b1))));
    return {f6_mul(a.b0, t), f6_neg(f6_mul(a.b1, t))};
}
inline Fp12 f12_conj(const Fp12& a) { return {a.b0, f6_neg(a.b1)}; }

inline Fp12 f12_pow_be(const Fp12& a, const uint8_t* e, size_t elen) {
    Fp12 out = f12_one();
    bool started = false;
    for (size_t i = 0; i < elen; i++) {
        for (int b = 7; b >= 0; b--) {
            if (started) out = f12_sqr(out);
            if ((e[i] >> b) & 1) {
                if (started) out = f12_mul(out, a);
                else { out = a; started = true; }
            }
        }
    }
    return started ? out : f12_one();
}

// --- affine points ----------------------------------------------------------

struct G1 {
    Fp x, y;
    bool inf;
};
struct G2 {
    Fp2 x, y;
    bool inf;
};
struct G12 {
    Fp12 x, y;
    bool inf;
};

// one affine implementation per field, mirroring the python formulas

#define DEFINE_PT_OPS(PT, F, fadd, fsub, fmul, fsqr, fneg, finv,      \
                      fiszero, feq, fmuli)                            \
    inline PT PT##_neg(const PT& p) {                                 \
        if (p.inf) return p;                                          \
        return {p.x, fneg(p.y), false};                               \
    }                                                                 \
    inline PT PT##_double(const PT& p) {                              \
        if (p.inf) return p;                                          \
        if (fiszero(p.y)) return {p.x, p.y, true};                    \
        F m = fmul(fmuli(fsqr(p.x), 3),                               \
                   finv(fmuli(p.y, 2)));                              \
        F nx = fsub(fsqr(m), fmuli(p.x, 2));                          \
        F ny = fsub(fmul(m, fsub(p.x, nx)), p.y);                     \
        return {nx, ny, false};                                       \
    }                                                                 \
    inline PT PT##_add(const PT& a, const PT& b) {                    \
        if (a.inf) return b;                                          \
        if (b.inf) return a;                                          \
        if (feq(a.x, b.x)) {                                          \
            if (feq(a.y, b.y)) return PT##_double(a);                 \
            return {a.x, a.y, true};                                  \
        }                                                             \
        F m = fmul(fsub(b.y, a.y), finv(fsub(b.x, a.x)));             \
        F nx = fsub(fsub(fsqr(m), a.x), b.x);                         \
        F ny = fsub(fmul(m, fsub(a.x, nx)), a.y);                     \
        return {nx, ny, false};                                       \
    }                                                                 \

inline Fp fp_muli_(const Fp& a, int k) { return fp_muli(a, k); }
DEFINE_PT_OPS(G1, Fp, fp_add, fp_sub, fp_mul, fp_sqr, fp_neg, fp_inv,
              fp_is_zero, fp_eq, fp_muli_)
DEFINE_PT_OPS(G2, Fp2, f2_add, f2_sub, f2_mul, f2_sqr, f2_neg,
              f2_inv, f2_is_zero, f2_eq, f2_muli)

// Jacobian scalar multiplication (one inversion at the end instead of
// one per step): (X, Y, Z) with x = X/Z^2, y = Y/Z^3.  Used for the
// long multiplications (subgroup checks, cofactor clearing, signing);
// the result is normalized back to affine, so outputs are
// byte-identical to the affine ladder and the python golden model.
#define DEFINE_JAC_MUL(PT, F, fadd, fsub, fmul, fsqr, fneg, finv,     \
                       fiszero, feq, fone)                            \
    struct PT##Jac { F X, Y, Z; };                                    \
    inline PT##Jac PT##_jac_double(const PT##Jac& p) {                \
        if (fiszero(p.Z) || fiszero(p.Y)) return {p.X, p.Y,           \
                                                  F{} /*zero*/};      \
        F A = fsqr(p.X);                                              \
        F B = fsqr(p.Y);                                              \
        F C = fsqr(B);                                                \
        F D0 = fsub(fsqr(fadd(p.X, B)), fadd(A, C));                  \
        F D = fadd(D0, D0);                                           \
        F E = fadd(fadd(A, A), A);                                    \
        F X3 = fsub(fsqr(E), fadd(D, D));                             \
        F C8 = fadd(C, C);                                            \
        C8 = fadd(C8, C8);                                            \
        C8 = fadd(C8, C8);                                            \
        F Y3 = fsub(fmul(E, fsub(D, X3)), C8);                        \
        F Z3 = fmul(fadd(p.Y, p.Y), p.Z);                             \
        return {X3, Y3, Z3};                                          \
    }                                                                 \
    inline PT##Jac PT##_jac_add_affine(const PT##Jac& p,              \
                                       const PT& q) {                 \
        if (fiszero(p.Z)) {                                           \
            /* p = inf: lift q */                                     \
            return {q.x, q.y, fone()};                                \
        }                                                             \
        F Z2 = fsqr(p.Z);                                             \
        F U2 = fmul(q.x, Z2);                                         \
        F S2 = fmul(fmul(q.y, Z2), p.Z);                              \
        if (feq(p.X, U2)) {                                           \
            if (feq(p.Y, S2)) return PT##_jac_double(p);              \
            return {p.X, p.Y, F{}};        /* p + (-p) = inf */       \
        }                                                             \
        F H = fsub(U2, p.X);                                          \
        F HH = fsqr(H);                                               \
        F I = fadd(HH, HH);                                           \
        I = fadd(I, I);                                               \
        F J = fmul(H, I);                                             \
        F rr = fsub(S2, p.Y);                                         \
        rr = fadd(rr, rr);                                            \
        F V = fmul(p.X, I);                                           \
        F X3 = fsub(fsub(fsqr(rr), J), fadd(V, V));                   \
        F Y2J = fmul(p.Y, J);                                         \
        F Y3 = fsub(fmul(rr, fsub(V, X3)), fadd(Y2J, Y2J));           \
        F Z3 = fmul(fadd(p.Z, p.Z), H);                               \
        return {X3, Y3, Z3};                                          \
    }                                                                 \
    inline PT PT##_jac_to_affine(const PT##Jac& p) {                  \
        if (fiszero(p.Z)) return {F{}, F{}, true};                    \
        F zi = finv(p.Z);                                             \
        F zi2 = fsqr(zi);                                             \
        return {fmul(p.X, zi2), fmul(fmul(p.Y, zi2), zi), false};     \
    }                                                                 \
    inline PT PT##_mul_be_fast(const PT& p, const uint8_t* k,         \
                               size_t klen) {                         \
        if (p.inf) return p;                                          \
        PT##Jac acc = {F{}, F{}, F{}};      /* infinity (Z = 0) */    \
        bool started = false;                                         \
        for (size_t i = 0; i < klen; i++) {                           \
            for (int b = 7; b >= 0; b--) {                            \
                if (started) acc = PT##_jac_double(acc);              \
                if ((k[i] >> b) & 1) {                                \
                    acc = PT##_jac_add_affine(acc, p);                \
                    started = true;                                   \
                }                                                     \
            }                                                         \
        }                                                             \
        return PT##_jac_to_affine(acc);                               \
    }

DEFINE_JAC_MUL(G1, Fp, fp_add, fp_sub, fp_mul, fp_sqr, fp_neg,
               fp_inv, fp_is_zero, fp_eq, fp_one)
DEFINE_JAC_MUL(G2, Fp2, f2_add, f2_sub, f2_mul, f2_sqr, f2_neg,
               f2_inv, f2_is_zero, f2_eq, f2_one)
inline bool f12_is_zero(const Fp12& a) { return f12_eq(a, f12_zero()); }
inline Fp12 f12_muli(const Fp12& a, int k) {
    Fp12 out = a;
    for (int i = 1; i < k; i++) out = f12_add(out, a);
    return out;
}
DEFINE_PT_OPS(G12, Fp12, f12_add, f12_sub, f12_mul, f12_sqr,
              f12_neg, f12_inv, f12_is_zero, f12_eq, f12_muli)

// curve equations
inline bool g1_on_curve(const G1& p) {
    if (p.inf) return true;
    Fp b4 = fp_from_u64(4);
    return fp_eq(fp_sqr(p.y),
                 fp_add(fp_mul(fp_sqr(p.x), p.x), b4));
}
inline Fp2 g2_b() {
    // 4 * (1 + u)
    Fp f4 = fp_from_u64(4);
    return {f4, f4};
}
inline bool g2_on_curve(const G2& p) {
    if (p.inf) return true;
    return f2_eq(f2_sqr(p.y),
                 f2_add(f2_mul(f2_sqr(p.x), p.x), g2_b()));
}

static const uint8_t R_BE[32] = {
    0x73,0xed,0xa7,0x53,0x29,0x9d,0x7d,0x48,0x33,0x39,0xd8,0x08,
    0x09,0xa1,0xd8,0x05,0x53,0xbd,0xa4,0x02,0xff,0xfe,0x5b,0xfe,
    0xff,0xff,0xff,0xff,0x00,0x00,0x00,0x01};

inline bool g1_in_subgroup(const G1& p) {
    if (!g1_on_curve(p)) return false;
    if (p.inf) return true;
    return G1_mul_be_fast(p, R_BE, 32).inf;
}
inline bool g2_in_subgroup(const G2& p) {
    if (!g2_on_curve(p)) return false;
    if (p.inf) return true;
    return G2_mul_be_fast(p, R_BE, 32).inf;
}

// --- pairing ----------------------------------------------------------------

inline Fp12 f12_from_f2(const Fp2& c) {
    Fp12 r = f12_zero();
    r.b0.a0 = c;
    return r;
}

struct Consts {
    Fp12 w2_inv, w3_inv;
};

inline const Consts& consts() {
    static Consts c = [] {
        Consts k;
        Fp12 w = f12_zero();
        w.b1.a0 = f2_one();             // the generator w
        Fp12 w2 = f12_mul(w, w);
        Fp12 w3 = f12_mul(w2, w);
        k.w2_inv = f12_inv(w2);
        k.w3_inv = f12_inv(w3);
        return k;
    }();
    return c;
}

inline G12 untwist(const G2& p) {
    if (p.inf) return {f12_zero(), f12_zero(), true};
    return {f12_mul(f12_from_f2(p.x), consts().w2_inv),
            f12_mul(f12_from_f2(p.y), consts().w3_inv), false};
}

inline G12 g1_to_fq12(const G1& p) {
    if (p.inf) return {f12_zero(), f12_zero(), true};
    Fp12 x = f12_zero(), y = f12_zero();
    x.b0.a0 = {p.x, fp_zero()};
    y.b0.a0 = {p.y, fp_zero()};
    return {x, y, false};
}

inline Fp12 line(const G12& p1, const G12& p2, const G12& t) {
    Fp12 m;
    if (!f12_eq(p1.x, p2.x)) {
        m = f12_mul(f12_sub(p2.y, p1.y),
                    f12_inv(f12_sub(p2.x, p1.x)));
    } else if (f12_eq(p1.y, p2.y)) {
        Fp12 three = f12_zero();
        three.b0.a0 = {fp_from_u64(3), fp_zero()};
        m = f12_mul(f12_mul(f12_sqr(p1.x), three),
                    f12_inv(f12_add(p1.y, p1.y)));
    } else {
        return f12_sub(t.x, p1.x);
    }
    return f12_sub(f12_mul(m, f12_sub(t.x, p1.x)),
                   f12_sub(t.y, p1.y));
}

// |x| = 0xD201000000010000; loop over bits below the leading one
static const uint64_t ATE_LOOP = 0xD201000000010000ULL;

// fused line-evaluation + point-step: the tangent/chord slope is
// computed once and reused for both the line value and the next R —
// identical math to line()+G12_double/G12_add with half the (very
// expensive) Fq12 inversions
inline Fp12 line_dbl_step(G12* r, const G12& p) {
    Fp12 three = f12_zero();
    three.b0.a0 = {fp_from_u64(3), fp_zero()};
    Fp12 m = f12_mul(f12_mul(f12_sqr(r->x), three),
                     f12_inv(f12_add(r->y, r->y)));
    Fp12 l = f12_sub(f12_mul(m, f12_sub(p.x, r->x)),
                     f12_sub(p.y, r->y));
    Fp12 nx = f12_sub(f12_sqr(m), f12_add(r->x, r->x));
    Fp12 ny = f12_sub(f12_mul(m, f12_sub(r->x, nx)), r->y);
    r->x = nx;
    r->y = ny;
    return l;
}

inline Fp12 line_add_step(G12* r, const G12& q, const G12& p) {
    if (f12_eq(r->x, q.x)) {
        // same x: tangent (equal) or vertical (opposite) — fall back
        // to the unfused forms for these never-hit-in-practice cases
        Fp12 l = line(*r, q, p);
        *r = G12_add(*r, q);
        return l;
    }
    Fp12 m = f12_mul(f12_sub(q.y, r->y),
                     f12_inv(f12_sub(q.x, r->x)));
    Fp12 l = f12_sub(f12_mul(m, f12_sub(p.x, r->x)),
                     f12_sub(p.y, r->y));
    Fp12 nx = f12_sub(f12_sub(f12_sqr(m), r->x), q.x);
    Fp12 ny = f12_sub(f12_mul(m, f12_sub(r->x, nx)), r->y);
    r->x = nx;
    r->y = ny;
    return l;
}

inline Fp12 miller_loop(const G12& q, const G12& p) {
    if (q.inf || p.inf) return f12_one();
    G12 r = q;
    Fp12 f = f12_one();
    int top = 63;
    while (!((ATE_LOOP >> top) & 1)) top--;
    for (int i = top - 1; i >= 0; i--) {
        f = f12_mul(f12_sqr(f), line_dbl_step(&r, p));
        if ((ATE_LOOP >> i) & 1)
            f = f12_mul(f, line_add_step(&r, q, p));
    }
    return f12_conj(f);        // x < 0 adjustment
}

// (p^6 + 1) / r, big-endian (the python module's folded exponent)
static const uint8_t FINAL_E_BE[254] = {
    0x28,0xb3,0x14,0x87,0x75,0x03,0x7b,0x6f,0x23,0x5c,0x55,0xca,
    0x75,0x66,0xdb,0xf8,0x5a,0xe6,0x64,0xcf,0x5b,0xb3,0x65,0x79,
    0xae,0xa8,0x3c,0x48,0xc1,0xda,0xe0,0xec,0x90,0x31,0x17,0x9b,
    0xde,0xcc,0xad,0x73,0x75,0xa3,0x76,0x3b,0xdf,0x7c,0xcf,0x56,
    0xfb,0x15,0x73,0xbe,0xaa,0x8c,0x54,0x8c,0xe0,0x80,0x9b,0xc5,
    0xf6,0x1a,0xfb,0x46,0xe1,0x97,0xbd,0x2f,0xa4,0x89,0x9f,0x0c,
    0x50,0x12,0x6c,0x80,0x2e,0xec,0x85,0xa2,0xe7,0x07,0xf0,0x84,
    0x18,0x55,0x47,0x44,0x49,0x7f,0x8b,0x2f,0x29,0x22,0x96,0x78,
    0x78,0xfe,0xbc,0xb9,0x5d,0x1f,0x13,0x04,0x27,0x5e,0xf4,0x99,
    0xdf,0xfb,0x12,0xd6,0xa8,0x74,0xd2,0x1b,0x73,0xda,0x2b,0x82,
    0x2f,0x51,0x4a,0x9c,0x4f,0x6f,0xee,0x6a,0x95,0xdb,0x11,0xe6,
    0x3f,0x56,0x5e,0x88,0x6c,0x94,0xc4,0xf8,0x23,0x84,0xc3,0xb5,
    0xe2,0xf5,0x57,0xc0,0xb1,0x5f,0x27,0xd7,0xbd,0x90,0x93,0x50,
    0x21,0xc3,0xf0,0x07,0xc0,0x1e,0x7e,0xbe,0x3a,0xfc,0x81,0x61,
    0x01,0xdd,0xd0,0x76,0x11,0x7d,0x1d,0x61,0x5d,0x49,0xe2,0x76,
    0x4d,0x7b,0xc3,0xb5,0xef,0x4b,0x18,0x8a,0x20,0xb0,0x38,0xee,
    0x1c,0xd4,0x77,0x8e,0x0d,0xe7,0x33,0x82,0x59,0xc2,0x2a,0x12,
    0xbd,0x40,0x22,0x47,0x41,0xb3,0x6f,0xec,0x77,0x60,0x2d,0x72,
    0x71,0x56,0x38,0x90,0xf1,0x33,0x3a,0x09,0xc4,0x49,0x79,0x03,
    0xf7,0x6e,0x9c,0xf0,0xf7,0x0a,0x61,0xc7,0x91,0xe2,0x09,0xa5,
    0x25,0x6d,0xe0,0x38,0x1a,0x16,0x87,0x39,0xe1,0xcd,0xc0,0x70,
    0x5d,0x6a};

inline Fp12 final_exponentiation_naive(const Fp12& f) {
    // easy part f^(p^6-1) = conj(f) * f^-1, then the folded pow
    Fp12 g = f12_mul(f12_conj(f), f12_inv(f));
    return f12_pow_be(g, FINAL_E_BE, sizeof FINAL_E_BE);
}

// --- Frobenius + fast final exponentiation ---------------------------------

// generic Fq2 pow over a big-endian exponent
inline Fp2 f2_pow_be(const Fp2& a, const uint8_t* e, size_t elen) {
    Fp2 out = f2_one();
    bool started = false;
    for (size_t i = 0; i < elen; i++) {
        for (int b = 7; b >= 0; b--) {
            if (started) out = f2_sqr(out);
            if ((e[i] >> b) & 1) {
                if (started) out = f2_mul(out, a);
                else { out = a; started = true; }
            }
        }
    }
    return started ? out : f2_one();
}

// (p - 1) / 6, big-endian — the Frobenius gamma exponent
static const uint8_t PM16_BE[48] = {
    0x04,0x55,0x82,0xfc,0x5e,0xea,0xa6,0x6f,0x0c,0x84,0x9b,0xf3,
    0xb5,0xe1,0xf2,0x23,0xe6,0x13,0xe1,0xeb,0x7d,0xeb,0x83,0x1f,
    0xe6,0x88,0x23,0x1a,0xd3,0xc8,0x29,0x06,0x05,0x1c,0xaa,0xaa,
    0x72,0xe3,0x55,0x55,0x49,0xaa,0x7f,0xff,0xff,0xff,0xf1,0xc7};

struct FrobConsts {
    Fp2 gamma[6];      // gamma[i] = xi^(i*(p-1)/6); gamma[0] = 1
};

inline const FrobConsts& frob_consts() {
    static FrobConsts k = [] {
        FrobConsts c;
        Fp2 xi = {fp_one(), fp_one()};            // 1 + u
        c.gamma[0] = f2_one();
        c.gamma[1] = f2_pow_be(xi, PM16_BE, 48);
        for (int i = 2; i < 6; i++)
            c.gamma[i] = f2_mul(c.gamma[i - 1], c.gamma[1]);
        return c;
    }();
    return k;
}

// f^p: conjugate each Fq2 coefficient, multiply the w^i coefficient
// by gamma[i].  Coefficient i of w^i:  [b0.a0, b1.a0, b0.a1, b1.a1,
// b0.a2, b1.a2]  (w^2 = v).
inline Fp12 f12_frobenius(const Fp12& f) {
    const FrobConsts& k = frob_consts();
    auto cm = [&](const Fp2& c, int i) {
        return f2_mul(Fp2{c.c0, fp_neg(c.c1)}, k.gamma[i]);
    };
    Fp12 r;
    r.b0.a0 = cm(f.b0.a0, 0);
    r.b1.a0 = cm(f.b1.a0, 1);
    r.b0.a1 = cm(f.b0.a1, 2);
    r.b1.a1 = cm(f.b1.a1, 3);
    r.b0.a2 = cm(f.b0.a2, 4);
    r.b1.a2 = cm(f.b1.a2, 5);
    return r;
}

// m^u with u = |x| = 0xD201000000010000 (64-bit square-and-multiply)
inline Fp12 f12_pow_u(const Fp12& m) {
    Fp12 out = m;                     // leading bit
    for (int i = 62; i >= 0; i--) {
        out = f12_sqr(out);
        if ((ATE_LOOP >> i) & 1) out = f12_mul(out, m);
    }
    return out;
}

inline Fp12 final_exponentiation(const Fp12& f) {
    // easy part: g = f^((p^6-1)(p^2+1)) — in the cyclotomic subgroup,
    // where inverse == conjugate
    Fp12 g = f12_mul(f12_conj(f), f12_inv(f));          // ^(p^6-1)
    g = f12_mul(f12_frobenius(f12_frobenius(g)), g);    // ^(p^2+1)
    // hard part cubed (Hayashida-style decomposition; exact identity
    // verified offline:  3*((p^4-p^2+1)/r) =
    //   (x-1)^2 (x+p) (x^2+p^2-1) + 3,  x = -u):
    // the result is naive^3, and since gcd(3, r) = 1 the ==1 verdict
    // is unchanged (the module's only consumer).
    Fp12 t1 = f12_conj(f12_mul(f12_pow_u(g), g));       // g^(x-1)
    Fp12 t2 = f12_conj(f12_mul(f12_pow_u(t1), t1));     // ^(x-1)
    Fp12 t3 = f12_mul(f12_conj(f12_pow_u(t2)),          // ^(x+p)
                      f12_frobenius(t2));
    Fp12 t4 = f12_mul(
        f12_mul(f12_pow_u(f12_pow_u(t3)),               // ^(x^2)
                f12_frobenius(f12_frobenius(t3))),      // ^(p^2)
        f12_conj(t3));                                  // ^(-1)
    Fp12 g3 = f12_mul(f12_sqr(g), g);
    return f12_mul(t4, g3);
}

// startup self-check: Frobenius vs a plain ^p pow, and the fast final
// exponentiation (naive^3) vs the naive one, on a derived element —
// any algebra slip fails loudly before a verdict is ever produced
inline bool selftest() {
    // a "random" fp12 from small constants
    Fp12 f = f12_zero();
    uint64_t seed = 0x9e3779b97f4a7c15ULL;
    Fp2* coeffs[6] = {&f.b0.a0, &f.b1.a0, &f.b0.a1,
                      &f.b1.a1, &f.b0.a2, &f.b1.a2};
    for (int i = 0; i < 6; i++) {
        seed = seed * 6364136223846793005ULL + 1442695040888963407ULL;
        coeffs[i]->c0 = fp_from_u64(seed >> 8);
        seed = seed * 6364136223846793005ULL + 1442695040888963407ULL;
        coeffs[i]->c1 = fp_from_u64(seed >> 8);
    }
    // P big-endian = PM2 + 2
    uint8_t p_be[48];
    std::memcpy(p_be, PM2_BE, 48);
    p_be[47] = uint8_t(p_be[47] + 2);
    if (!f12_eq(f12_frobenius(f), f12_pow_be(f, p_be, 48)))
        return false;
    Fp12 naive = final_exponentiation_naive(f);
    Fp12 naive3 = f12_mul(f12_sqr(naive), naive);
    return f12_eq(final_exponentiation(f), naive3);
}

struct Pair {
    G1 p;
    G2 q;
};

inline bool pairings_product_is_one(const std::vector<Pair>& pairs) {
    Fp12 f = f12_one();
    for (const Pair& pr : pairs) {
        if (pr.p.inf || pr.q.inf) continue;
        f = f12_mul(f, miller_loop(untwist(pr.q), g1_to_fq12(pr.p)));
    }
    return f12_eq(final_exponentiation(f), f12_one());
}

// --- hash to G2 (mirrors the python module's custom map) --------------------

inline void sha256_digest(const uint8_t* d, size_t n, uint8_t out[32]) {
    sha256::hash(d, n, out);
}

inline void expand_message_xmd(const uint8_t* msg, size_t msg_len,
                               const uint8_t* dst, size_t dst_len,
                               size_t out_len, uint8_t* out) {
    // RFC 9380 §5.3.1 with SHA-256 (lengths validated by the caller)
    size_t ell = (out_len + 31) / 32;
    std::vector<uint8_t> buf;
    buf.assign(64, 0);                         // z_pad
    buf.insert(buf.end(), msg, msg + msg_len);
    buf.push_back(uint8_t(out_len >> 8));
    buf.push_back(uint8_t(out_len));
    buf.push_back(0);
    buf.insert(buf.end(), dst, dst + dst_len);
    buf.push_back(uint8_t(dst_len));
    uint8_t b0[32];
    sha256_digest(buf.data(), buf.size(), b0);

    std::vector<uint8_t> round;
    round.assign(b0, b0 + 32);
    round.push_back(1);
    round.insert(round.end(), dst, dst + dst_len);
    round.push_back(uint8_t(dst_len));
    uint8_t prev[32];
    sha256_digest(round.data(), round.size(), prev);
    size_t written = 0;
    for (size_t i = 1; i <= ell && written < out_len; i++) {
        size_t take = out_len - written < 32 ? out_len - written : 32;
        std::memcpy(out + written, prev, take);
        written += take;
        if (i == ell) break;
        round.clear();
        for (int j = 0; j < 32; j++)
            round.push_back(b0[j] ^ prev[j]);
        round.push_back(uint8_t(i + 1));
        round.insert(round.end(), dst, dst + dst_len);
        round.push_back(uint8_t(dst_len));
        sha256_digest(round.data(), round.size(), prev);
    }
}

// 64-byte big-endian -> Fp (mod p), for hash_to_field
inline Fp fp_from_be64_mod(const uint8_t* b) {
    // incremental: r = r*256 + byte (in standard form via Montgomery)
    Fp r = fp_zero();
    Fp c256 = fp_from_u64(256);
    for (int i = 0; i < 64; i++) {
        r = fp_add(fp_mul(r, c256), fp_from_u64(b[i]));
    }
    return r;
}

inline int sgn0_fq2(const Fp2& a) {
    bool s0 = fp_is_odd(a.c0);
    bool z0 = fp_is_zero(a.c0);
    return s0 || (z0 && fp_is_odd(a.c1));
}

static const uint8_t H2_BE[64] = {
    0x05,0xd5,0x43,0xa9,0x54,0x14,0xe7,0xf1,0x09,0x1d,0x50,0x79,
    0x28,0x76,0xa2,0x02,0xcd,0x91,0xde,0x45,0x47,0x08,0x5a,0xba,
    0xa6,0x8a,0x20,0x5b,0x2e,0x5a,0x7d,0xdf,0xa6,0x28,0xf1,0xcb,
    0x4d,0x9e,0x82,0xef,0x21,0x53,0x7e,0x29,0x3a,0x66,0x91,0xae,
    0x16,0x16,0xec,0x6e,0x78,0x6f,0x0c,0x70,0xcf,0x1c,0x38,0xe3,
    0x1c,0x72,0x38,0xe5};

inline G2 map_to_curve_g2(const Fp2& u) {
    // deterministic try-and-increment: x = (u.c0 + ctr, u.c1)
    Fp2 x = u;
    Fp one = fp_one();
    for (int ctr = 0; ctr < 256; ctr++) {
        Fp2 rhs = f2_add(f2_mul(f2_sqr(x), x), g2_b());
        Fp2 y;
        if (f2_sqrt(rhs, &y)) {
            if (sgn0_fq2(y) != sgn0_fq2(u)) y = f2_neg(y);
            return {x, y, false};
        }
        x.c0 = fp_add(x.c0, one);
    }
    return {f2_zero(), f2_zero(), true};      // unreachable in practice
}

inline G2 hash_to_g2(const uint8_t* msg, size_t msg_len,
                     const uint8_t* dst, size_t dst_len) {
    uint8_t data[256];
    expand_message_xmd(msg, msg_len, dst, dst_len, 256, data);
    Fp2 u0 = {fp_from_be64_mod(data), fp_from_be64_mod(data + 64)};
    Fp2 u1 = {fp_from_be64_mod(data + 128),
              fp_from_be64_mod(data + 192)};
    G2 q = G2_add(map_to_curve_g2(u0), map_to_curve_g2(u1));
    return G2_mul_be_fast(q, H2_BE, sizeof H2_BE);
}

}  // namespace bls
