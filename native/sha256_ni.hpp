// SHA-256 compress using x86 SHA-NI intrinsics (runtime-detected).
// One 64-byte block per call; drop-in replacement for the scalar
// compress in sha256.hpp when the CPU supports it.  Written against
// the Intel SHA extensions programming reference round structure.
#pragma once

#if defined(__x86_64__) || defined(_M_X64)
#define COMETBFT_SHA_NI_POSSIBLE 1
#include <immintrin.h>
#if defined(__GNUC__)
#include <cpuid.h>
#endif

namespace sha256ni {

__attribute__((target("sha,sse4.1")))
inline void compress(uint32_t state[8], const uint8_t* data) {
    const __m128i MASK = _mm_set_epi64x(0x0c0d0e0f08090a0bULL,
                                        0x0405060700010203ULL);

    __m128i TMP =
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(&state[0]));
    __m128i STATE1 =
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(&state[4]));
    TMP = _mm_shuffle_epi32(TMP, 0xB1);                   // CDAB
    STATE1 = _mm_shuffle_epi32(STATE1, 0x1B);             // EFGH
    __m128i STATE0 = _mm_alignr_epi8(TMP, STATE1, 8);     // ABEF
    STATE1 = _mm_blend_epi16(STATE1, TMP, 0xF0);          // CDGH

    const __m128i ABEF_SAVE = STATE0;
    const __m128i CDGH_SAVE = STATE1;
    __m128i MSG, MSG0, MSG1, MSG2, MSG3;

    // rounds 0-3
    MSG0 = _mm_shuffle_epi8(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(data)), MASK);
    MSG = _mm_add_epi32(MSG0,
        _mm_set_epi64x(0xE9B5DBA5B5C0FBCFULL, 0x71374491428A2F98ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

    // rounds 4-7
    MSG1 = _mm_shuffle_epi8(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(data + 16)),
        MASK);
    MSG = _mm_add_epi32(MSG1,
        _mm_set_epi64x(0xAB1C5ED5923F82A4ULL, 0x59F111F13956C25BULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    MSG0 = _mm_sha256msg1_epu32(MSG0, MSG1);

    // rounds 8-11
    MSG2 = _mm_shuffle_epi8(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(data + 32)),
        MASK);
    MSG = _mm_add_epi32(MSG2,
        _mm_set_epi64x(0x550C7DC3243185BEULL, 0x12835B01D807AA98ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    MSG1 = _mm_sha256msg1_epu32(MSG1, MSG2);

    // rounds 12-15
    MSG3 = _mm_shuffle_epi8(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(data + 48)),
        MASK);
    MSG = _mm_add_epi32(MSG3,
        _mm_set_epi64x(0xC19BF1749BDC06A7ULL, 0x80DEB1FE72BE5D74ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    TMP = _mm_alignr_epi8(MSG3, MSG2, 4);
    MSG0 = _mm_add_epi32(MSG0, TMP);
    MSG0 = _mm_sha256msg2_epu32(MSG0, MSG3);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    MSG2 = _mm_sha256msg1_epu32(MSG2, MSG3);

    // rounds 16-51: the steady-state schedule, rotating MSG0..MSG3
#define QROUND(MA, MB, MC, MD, K1, K0)                                 \
    MSG = _mm_add_epi32(MA, _mm_set_epi64x(K1, K0));                   \
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);               \
    TMP = _mm_alignr_epi8(MA, MD, 4);                                  \
    MB = _mm_add_epi32(MB, TMP);                                       \
    MB = _mm_sha256msg2_epu32(MB, MA);                                 \
    MSG = _mm_shuffle_epi32(MSG, 0x0E);                                \
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);               \
    MD = _mm_sha256msg1_epu32(MD, MA);

    QROUND(MSG0, MSG1, MSG2, MSG3,
           0x240CA1CC0FC19DC6ULL, 0xEFBE4786E49B69C1ULL)  // 16-19
    QROUND(MSG1, MSG2, MSG3, MSG0,
           0x76F988DA5CB0A9DCULL, 0x4A7484AA2DE92C6FULL)  // 20-23
    QROUND(MSG2, MSG3, MSG0, MSG1,
           0xBF597FC7B00327C8ULL, 0xA831C66D983E5152ULL)  // 24-27
    QROUND(MSG3, MSG0, MSG1, MSG2,
           0x1429296706CA6351ULL, 0xD5A79147C6E00BF3ULL)  // 28-31
    QROUND(MSG0, MSG1, MSG2, MSG3,
           0x53380D134D2C6DFCULL, 0x2E1B213827B70A85ULL)  // 32-35
    QROUND(MSG1, MSG2, MSG3, MSG0,
           0x92722C8581C2C92EULL, 0x766A0ABB650A7354ULL)  // 36-39
    QROUND(MSG2, MSG3, MSG0, MSG1,
           0xC76C51A3C24B8B70ULL, 0xA81A664BA2BFE8A1ULL)  // 40-43
    QROUND(MSG3, MSG0, MSG1, MSG2,
           0x106AA070F40E3585ULL, 0xD6990624D192E819ULL)  // 44-47
    QROUND(MSG0, MSG1, MSG2, MSG3,
           0x34B0BCB52748774CULL, 0x1E376C0819A4C116ULL)  // 48-51
#undef QROUND

    // rounds 52-55 (last msg2 for MSG2; no more msg1)
    MSG = _mm_add_epi32(MSG1,
        _mm_set_epi64x(0x682E6FF35B9CCA4FULL, 0x4ED8AA4A391C0CB3ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    TMP = _mm_alignr_epi8(MSG1, MSG0, 4);
    MSG2 = _mm_add_epi32(MSG2, TMP);
    MSG2 = _mm_sha256msg2_epu32(MSG2, MSG1);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

    // rounds 56-59
    MSG = _mm_add_epi32(MSG2,
        _mm_set_epi64x(0x8CC7020884C87814ULL, 0x78A5636F748F82EEULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    TMP = _mm_alignr_epi8(MSG2, MSG1, 4);
    MSG3 = _mm_add_epi32(MSG3, TMP);
    MSG3 = _mm_sha256msg2_epu32(MSG3, MSG2);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

    // rounds 60-63
    MSG = _mm_add_epi32(MSG3,
        _mm_set_epi64x(0xC67178F2BEF9A3F7ULL, 0xA4506CEB90BEFFFAULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

    STATE0 = _mm_add_epi32(STATE0, ABEF_SAVE);
    STATE1 = _mm_add_epi32(STATE1, CDGH_SAVE);

    TMP = _mm_shuffle_epi32(STATE0, 0x1B);                // FEBA
    STATE1 = _mm_shuffle_epi32(STATE1, 0xB1);             // DCHG
    STATE0 = _mm_blend_epi16(TMP, STATE1, 0xF0);          // DCBA
    STATE1 = _mm_alignr_epi8(STATE1, TMP, 8);             // ABEF->HGFE

    _mm_storeu_si128(reinterpret_cast<__m128i*>(&state[0]), STATE0);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(&state[4]), STATE1);
}

inline bool supported() {
#if defined(__GNUC__) && !defined(__clang__) && __GNUC__ < 11
    // GCC 10's __builtin_cpu_supports rejects the "sha" feature
    // string at compile time (added in GCC 11) — the whole native
    // build died on it.  Probe cpuid directly instead.
    unsigned eax, ebx, ecx, edx;
    if (!__get_cpuid_count(7, 0, &eax, &ebx, &ecx, &edx))
        return false;
    const bool sha = (ebx >> 29) & 1u;          // leaf 7.0 EBX[29]
    if (!__get_cpuid(1, &eax, &ebx, &ecx, &edx))
        return false;
    const bool sse41 = (ecx >> 19) & 1u;        // leaf 1 ECX[19]
    return sha && sse41;
#else
    return __builtin_cpu_supports("sha") &&
           __builtin_cpu_supports("sse4.1");
#endif
}

}  // namespace sha256ni
#else
#define COMETBFT_SHA_NI_POSSIBLE 0
#endif
