"""Load generation + latency report (reference: test/loadtime,
test/e2e/runner/benchmark.go)."""
import asyncio
import os
import tempfile


def _mk_node_cfg(d):
    from cometbft_tpu.config import Config
    from cometbft_tpu.p2p.key import NodeKey
    from cometbft_tpu.privval import FilePV
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.timestamp import Timestamp

    home = os.path.join(d, "node")
    cfg = Config()
    cfg.base.home = home
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.consensus.timeout_commit_ns = 50_000_000
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    pv = FilePV.generate(
        cfg.base.path(cfg.base.priv_validator_key_file),
        cfg.base.path(cfg.base.priv_validator_state_file))
    NodeKey.load_or_gen(cfg.base.path(cfg.base.node_key_file))
    gen = GenesisDoc(
        chain_id="load-chain", genesis_time=Timestamp.now(),
        validators=[GenesisValidator(
            address=b"", pub_key=pv.get_pub_key(), power=10)],
    )
    # PBTS: block time is the proposer's clock at proposal, so tx
    # latency (block time - send time) is non-negative; without it
    # BFT time lags by up to one commit interval (the reference QA
    # baseline, CometBFT-QA-v1, also runs with PBTS)
    gen.consensus_params.feature.pbts_enable_height = 1
    gen.save_as(cfg.base.path(cfg.base.genesis_file))
    return cfg


class TestPayload:
    def test_roundtrip_and_padding(self):
        from cometbft_tpu.tools.loadtime import (
            payload_bytes, payload_from_tx,
        )

        tx = payload_bytes("exp1", size=300, rate=50, connections=2)
        assert len(tx) >= 300
        assert tx.startswith(b"a=")        # kvstore single-key form
        p = payload_from_tx(tx)
        assert p["id"] == "exp1" and p["rate"] == 50
        assert p["time_ns"] > 0
        assert payload_from_tx(b"other=tx") is None
        assert payload_from_tx(b"a=nothex!") is None

    def test_stats(self):
        from cometbft_tpu.tools.loadtime import Stats

        s = Stats.from_samples([1.0, 2.0, 3.0, 4.0])
        assert s.count == 4 and s.min_s == 1.0 and s.max_s == 4.0
        assert abs(s.avg_s - 2.5) < 1e-9
        assert s.p50_s in (2.0, 3.0)
        assert Stats.from_samples([]).count == 0


class TestLoadAgainstLiveNode:
    def test_generate_and_report(self):
        from cometbft_tpu.node.node import Node
        from cometbft_tpu.tools import loadtime

        async def run():
            with tempfile.TemporaryDirectory() as d:
                node = Node(_mk_node_cfg(d))
                await node.start()
                try:
                    ep = f"http://{node._rpc_server.listen_addr}"
                    # block 1 carries the genesis time (reference:
                    # state.go MakeBlock at initial height), so load
                    # must start after it or its txs get negative
                    # latencies
                    for _ in range(200):
                        if node.height >= 1:
                            break
                        await asyncio.sleep(0.02)
                    else:
                        raise AssertionError(
                            "node never reached height 1")
                    res = await loadtime.generate(
                        [ep], rate=40, connections=2,
                        duration_s=2.0, size=200)
                    assert res.accepted > 10, \
                        f"only {res.accepted}/{res.sent} accepted"
                    assert res.errors == 0
                    # let the tail commit
                    h = node.height
                    for _ in range(200):
                        if node.height > h + 1:
                            break
                        await asyncio.sleep(0.02)
                    rep = await loadtime.report(
                        ep, experiment_id=res.experiment_id)
                    assert rep.latency.count > 10
                    assert rep.negative_latencies == 0
                    assert 0 < rep.latency.p50_s < 10
                    assert rep.block_interval.count > 1
                    assert rep.block_interval.avg_s > 0
                finally:
                    await node.stop()
        asyncio.run(run())


class TestBaselineBenchmarks:
    def test_configs_run_at_tiny_sizes(self):
        """The BASELINE benchmark configs (#2-#5) execute and emit
        sane timings (tools/benchmarks.py)."""
        from cometbft_tpu.crypto import batch as crypto_batch
        from cometbft_tpu.tools import benchmarks as b

        crypto_batch.set_backend("cpu")
        try:
            r2 = b.config2_batch_verify(sizes=(16,))
            assert r2["results_ms"]["16"] > 0
            r3 = b.config3_light_client(n_vals=8, hops=2)
            assert r3["value_ms"] > 0
            r4 = b.config4_replay_tally(n_vals=8, heights=2)
            assert r4["tally_ms_p50"] > 0
            r5 = b.config5_mixed_stress(n_vals=12, n_bls=4)
            assert r5["mixed_commit_verify_ms"] > 0
        finally:
            crypto_batch.set_backend("auto")
