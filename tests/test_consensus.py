"""Consensus state machine tests.

Mirrors the reference's in-process multi-validator approach
(internal/consensus/common_test.go): N real ConsensusState machines wired
over in-memory queues, no sockets.
"""
import asyncio

import pytest

from cometbft_tpu.abci.client import AppConns
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config import test_config as _test_config
from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.consensus.messages import (
    BlockPartMessage, ProposalMessage, VoteMessage,
)
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.consensus.wal import WAL
from cometbft_tpu.db import MemDB
from cometbft_tpu.state import make_genesis_state
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.store import Store
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types.events import EventBus
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.priv_validator import new_mock_pv
from cometbft_tpu.types.timestamp import Timestamp


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


def _make_genesis(n_vals):
    pvs = [new_mock_pv() for _ in range(n_vals)]
    doc = GenesisDoc(
        chain_id="cs-test",
        genesis_time=Timestamp(1700000000, 0),
        validators=[GenesisValidator(address=b"",
                                     pub_key=pv.get_pub_key(), power=10)
                    for pv in pvs],
    )
    return doc, pvs


def _make_node(doc, pv, wal=None):
    state = make_genesis_state(doc)
    app = KVStoreApplication()
    conns = AppConns(app)
    state_store = Store(MemDB())
    block_store = BlockStore(MemDB())
    state_store.save(state)
    exec_ = BlockExecutor(state_store, conns.consensus,
                          block_store=block_store)
    cfg = _test_config().consensus
    bus = EventBus()
    cs = ConsensusState(cfg, state, exec_, block_store,
                        priv_validator=pv, event_bus=bus, wal=wal)
    return cs, app, block_store


GOSSIP_TYPES = (ProposalMessage, BlockPartMessage, VoteMessage)


def _wire(nodes):
    """Full-mesh in-process gossip."""
    for i, cs in enumerate(nodes):
        def mk_hook(sender_idx):
            def hook(msg):
                if not isinstance(msg, GOSSIP_TYPES):
                    return
                for j, other in enumerate(nodes):
                    if j != sender_idx:
                        other.send_peer(msg, f"node{sender_idx}")
            return hook
        cs.broadcast_hooks.append(mk_hook(i))


async def _wait_for_height(nodes, height, timeout=20.0):
    async def waiter():
        while True:
            if all(cs.block_store.height >= height for cs in nodes):
                return
            await asyncio.sleep(0.01)
    await asyncio.wait_for(waiter(), timeout)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


class TestSingleValidator:
    def test_produces_blocks(self):
        async def go():
            doc, pvs = _make_genesis(1)
            cs, app, bs = _make_node(doc, pvs[0])
            await cs.start()
            try:
                await _wait_for_height([cs], 3)
            finally:
                await cs.stop()
            assert bs.height >= 3
            b1 = bs.load_block(1)
            assert b1.header.chain_id == "cs-test"
            b2 = bs.load_block(2)
            assert b2.last_commit.size() == 1
            assert b2.last_commit.signatures[0].for_block()
            # LastCommit of b2 verifies against validator set
            assert cs.sm_state.last_block_height >= 3
        run(go())

    def test_wal_written(self, tmp_path):
        async def go():
            doc, pvs = _make_genesis(1)
            wal = WAL(str(tmp_path / "wal"))
            cs, app, bs = _make_node(doc, pvs[0], wal=wal)
            await cs.start()
            try:
                await _wait_for_height([cs], 2)
            finally:
                await cs.stop()
            msgs = list(WAL.iter_messages(str(tmp_path / "wal")))
            types = [m.get("type") for m in msgs]
            assert "proposal" in types
            assert "vote" in types
            assert "end_height" in types
            # EndHeight markers present for produced heights
            ends = [m["height"] for m in msgs
                    if m.get("type") == "end_height"]
            assert 1 in ends
            # messages after end of height 1 exist (height 2 activity)
            tail = WAL.search_for_end_height(str(tmp_path / "wal"), 1)
            assert tail is not None
        run(go())


class TestFourValidators:
    def test_network_produces_blocks(self):
        async def go():
            doc, pvs = _make_genesis(4)
            nodes = [_make_node(doc, pv)[0] for pv in pvs]
            _wire(nodes)
            for cs in nodes:
                await cs.start()
            try:
                await _wait_for_height(nodes, 3)
            finally:
                for cs in nodes:
                    await cs.stop()
            # all nodes agree on all blocks
            h1 = {cs.block_store.load_block(1).hash() for cs in nodes}
            assert len(h1) == 1
            h3 = {cs.block_store.load_block(3).hash() for cs in nodes}
            assert len(h3) == 1
            # commits carry 4 slots
            b3 = nodes[0].block_store.load_block(3)
            assert b3.last_commit.size() == 4
        run(go())

    def test_one_node_down_still_commits(self):
        # 3 of 4 validators (>2/3) are enough to make progress
        async def go():
            doc, pvs = _make_genesis(4)
            nodes = [_make_node(doc, pv)[0] for pv in pvs[:3]]
            # the 4th validator never starts; wire only the live ones
            _wire(nodes)
            for cs in nodes:
                await cs.start()
            try:
                await _wait_for_height(nodes, 2, timeout=30.0)
            finally:
                for cs in nodes:
                    await cs.stop()
            b2 = nodes[0].block_store.load_block(2)
            flags = [s.for_block() for s in b2.last_commit.signatures]
            assert flags.count(True) >= 3
        run(go())


class TestBurstPreverification:
    def test_preverify_burst_fills_memo_from_real_votes(self):
        """Drive _preverify_burst through a real ConsensusState with
        votes signed for its current height: the verified-triple memo
        must fill (regression: a bad attribute lookup once made the
        whole pre-verification a silently-swallowed no-op)."""
        from cometbft_tpu.types import vote as vote_mod
        from cometbft_tpu.types.vote import Vote
        from cometbft_tpu.types import canonical
        from cometbft_tpu.types.block_id import BlockID

        doc, pvs = _make_genesis(4)
        cs, app, _ = _make_node(doc, pvs[0])
        vote_mod._VERIFIED.clear()
        vals = cs.rs.validators
        burst = []
        for i, pv in enumerate(pvs):
            val_idx, val = vals.get_by_address(
                pv.get_pub_key().address())
            v = Vote(type=canonical.PREVOTE_TYPE, height=cs.rs.height,
                     round=0, block_id=BlockID(),
                     timestamp=Timestamp(1700000001 + i, 0),
                     validator_address=val.address,
                     validator_index=val_idx)
            sig_bytes = v.sign_bytes(cs.sm_state.chain_id)
            v.signature = pv.priv_key.sign(sig_bytes)
            burst.append(("peer", VoteMessage(vote=v), f"n{i}"))
        # the burst barrier is awaited (verification runs on the
        # staging worker; the loop keeps draining while it does)
        run(cs._preverify_burst(burst))
        assert len(vote_mod._VERIFIED) == len(pvs), \
            "burst pre-verification produced no memo entries"
        for _, msg, _ in burst:
            v = msg.vote
            val = vals.validators[v.validator_index]
            key = vote_mod._memo_key(
                val.pub_key, v.sign_bytes(cs.sm_state.chain_id),
                v.signature)
            assert key in vote_mod._VERIFIED

    def test_append_vote_entries_covers_extension_signatures(self):
        """The shared entry builder must emit all three signature
        triples for a non-nil precommit with extensions, and exactly
        one for a plain prevote."""
        from cometbft_tpu.consensus.state import ConsensusState
        from cometbft_tpu.types.vote import Vote
        from cometbft_tpu.types import canonical
        from cometbft_tpu.types.block_id import BlockID
        from cometbft_tpu.types.part_set import PartSetHeader
        from cometbft_tpu.crypto import ed25519

        pk = ed25519.gen_priv_key().pub_key()
        bid = BlockID(hash=b"\x21" * 32,
                      part_set_header=PartSetHeader(1, b"\x43" * 32))
        v = Vote(type=canonical.PRECOMMIT_TYPE, height=9, round=0,
                 block_id=bid, timestamp=Timestamp(1700000900, 0),
                 validator_address=pk.address(), validator_index=0,
                 signature=b"\x01" * 64,
                 extension=b"ext", extension_signature=b"\x02" * 64,
                 non_rp_extension=b"nrp",
                 non_rp_extension_signature=b"\x03" * 64)
        # _append_vote_entries is an instance method (it logs
        # skipped malformed votes); a stub self with a logger is
        # enough for the entry-building contract under test
        from types import SimpleNamespace
        from cometbft_tpu.libs.log import new_logger
        cs = SimpleNamespace(logger=new_logger("test"))
        entries = []
        ConsensusState._append_vote_entries(cs, entries, v, pk,
                                            "x-chain")
        assert len(entries) == 3
        assert entries[0][2] == b"\x01" * 64
        assert entries[1][2] == b"\x02" * 64
        assert entries[2][2] == b"\x03" * 64
        prevote = Vote(type=canonical.PREVOTE_TYPE, height=9, round=0,
                       block_id=bid,
                       timestamp=Timestamp(1700000901, 0),
                       validator_address=pk.address(),
                       validator_index=0, signature=b"\x04" * 64)
        entries = []
        ConsensusState._append_vote_entries(cs, entries, prevote, pk,
                                            "x-chain")
        assert len(entries) == 1
