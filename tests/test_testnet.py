"""4-validator testnet over real TCP sockets — the full stack:
SecretConnection + MConnection + Switch + ConsensusReactor +
ConsensusState + BlockExecutor + kvstore (baseline config #1 shape).
"""
import asyncio

import pytest

from cometbft_tpu.abci.client import AppConns
from cometbft_tpu.abci.kvstore import DEFAULT_LANES, KVStoreApplication
from cometbft_tpu.config import MempoolConfig
from cometbft_tpu.config import test_config as _test_config
from cometbft_tpu.consensus.reactor import ConsensusReactor
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.db import MemDB
from cometbft_tpu.mempool import CListMempool
from cometbft_tpu.p2p.key import NodeKey
from cometbft_tpu.p2p.switch import Switch
from cometbft_tpu.state import make_genesis_state
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.store import Store
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.priv_validator import new_mock_pv
from cometbft_tpu.types.timestamp import Timestamp


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


class Node:
    def __init__(self, doc, pv):
        self.doc = doc
        self.pv = pv
        self.app = KVStoreApplication()
        self.conns = AppConns(self.app)
        self.state_store = Store(MemDB())
        self.block_store = BlockStore(MemDB())
        state = make_genesis_state(doc)
        self.state_store.save(state)
        cfg = _test_config().consensus
        self.mempool = CListMempool(
            MempoolConfig(), self.conns.mempool, lanes=DEFAULT_LANES,
            default_lane="default")
        self.exec = BlockExecutor(self.state_store, self.conns.consensus,
                                  mempool=self.mempool,
                                  block_store=self.block_store)
        self.cs = ConsensusState(cfg, state, self.exec,
                                 self.block_store, priv_validator=pv)
        self.node_key = NodeKey.generate()
        self.switch = Switch(self.node_key, doc.chain_id,
                             listen_addr="127.0.0.1:0")
        self.reactor = ConsensusReactor(self.cs)
        self.switch.add_reactor(self.reactor)

    async def start(self):
        await self.switch.start()
        await self.cs.start()

    async def stop(self):
        await self.cs.stop()
        await self.switch.stop()


async def _make_net(n):
    pvs = [new_mock_pv() for _ in range(n)]
    doc = GenesisDoc(
        chain_id="testnet", genesis_time=Timestamp(1700000000, 0),
        validators=[GenesisValidator(address=b"",
                                     pub_key=pv.get_pub_key(), power=10)
                    for pv in pvs])
    nodes = [Node(doc, pv) for pv in pvs]
    for node in nodes:
        await node.start()
    # full mesh dialing
    for i, node in enumerate(nodes):
        for j, other in enumerate(nodes):
            if j > i:
                await node.switch.dial_peer(other.switch.listen_addr)
    return nodes


async def _wait_all_height(nodes, h, timeout=30.0):
    async def waiter():
        while not all(n.block_store.height >= h for n in nodes):
            await asyncio.sleep(0.02)
    await asyncio.wait_for(waiter(), timeout)


class TestSocketTestnet:
    def test_four_validators_commit_blocks(self):
        async def go():
            nodes = await _make_net(4)
            try:
                # inbound upgrades finish asynchronously — poll
                async def all_connected():
                    while not all(n.switch.num_peers() == 3
                                  for n in nodes):
                        await asyncio.sleep(0.01)
                await asyncio.wait_for(all_connected(), 10)
                await _wait_all_height(nodes, 3)
                hashes = {n.block_store.load_block(3).hash()
                          for n in nodes}
                assert len(hashes) == 1
                b3 = nodes[0].block_store.load_block(3)
                assert b3.last_commit.size() == 4
                signed = sum(1 for s in b3.last_commit.signatures
                             if s.for_block())
                assert signed >= 3
            finally:
                for n in nodes:
                    await n.stop()
        run(go())

    def test_txs_flow_through_mempool_to_blocks(self):
        async def go():
            nodes = await _make_net(4)
            try:
                await _wait_all_height(nodes, 1)
                # submit txs to different nodes' mempools; without a
                # mempool reactor yet, submit to all (gossip arrives
                # in a later round)
                for n in nodes:
                    await n.mempool.check_tx(b"alpha=1")
                    await n.mempool.check_tx(b"beta=2")
                await _wait_all_height(
                    nodes, nodes[0].block_store.height + 2)
                # txs landed in some block on every node
                found = set()
                for h in range(1, nodes[0].block_store.height + 1):
                    b = nodes[0].block_store.load_block(h)
                    if b:
                        found.update(b.data.txs)
                assert b"alpha=1" in found
                assert b"beta=2" in found
                # and were committed to app state
                from cometbft_tpu.abci import types as abci
                q = await nodes[2].app.query(
                    abci.QueryRequest(data=b"alpha"))
                assert q.value == b"1"
            finally:
                for n in nodes:
                    await n.stop()
        run(go())

    def test_late_joiner_catches_up(self):
        async def go():
            pvs = [new_mock_pv() for _ in range(4)]
            doc = GenesisDoc(
                chain_id="testnet",
                genesis_time=Timestamp(1700000000, 0),
                validators=[GenesisValidator(
                    address=b"", pub_key=pv.get_pub_key(), power=10)
                    for pv in pvs])
            nodes = [Node(doc, pv) for pv in pvs[:3]]
            for n in nodes:
                await n.start()
            for i, n in enumerate(nodes):
                for j, o in enumerate(nodes):
                    if j > i:
                        await n.switch.dial_peer(o.switch.listen_addr)
            try:
                await _wait_all_height(nodes, 3)
                # 4th validator joins late and must catch up via gossip
                late = Node(doc, pvs[3])
                await late.start()
                for o in nodes:
                    await late.switch.dial_peer(o.switch.listen_addr)
                nodes.append(late)
                target = nodes[0].block_store.height + 2
                await _wait_all_height([late], target, timeout=45.0)
                assert late.block_store.height >= target
                b = late.block_store.load_block(2)
                assert b.hash() == nodes[0].block_store.load_block(
                    2).hash()
            finally:
                for n in nodes:
                    await n.stop()
        run(go())
