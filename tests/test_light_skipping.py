"""Skipping-verification light-client sync (verify_to_height): batch
verifier routing, shared-signature-cache reuse across hops, and
bisection under validator-set rotation (docs/light_proofs.md;
"Practical Light Clients for Committee-Based Blockchains" in
PAPERS.md).
"""
import asyncio

import pytest

from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.db.db import MemDB
from cometbft_tpu.light.client import Client, TrustOptions
from cometbft_tpu.light.provider import (
    LightBlockNotFoundError, Provider,
)
from cometbft_tpu.light.store import TrustedStore
from cometbft_tpu.types import canonical
from cometbft_tpu.types.block import Header, LightBlock, SignedHeader
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.commit import Commit, CommitSig
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.priv_validator import new_mock_pv
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validator_set import Validator, ValidatorSet
from cometbft_tpu.types.vote import BLOCK_ID_FLAG_COMMIT, Vote
from cometbft_tpu.version import BLOCK_PROTOCOL

CHAIN_ID = "skip-chain"
T0 = 1_700_000_000
HOUR_NS = 3600 * 10**9


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


def _valset(pvs) -> ValidatorSet:
    return ValidatorSet([
        Validator(address=pv.get_pub_key().address(),
                  pub_key=pv.get_pub_key(), voting_power=10)
        for pv in pvs])


def make_chain(n_heights: int, pvs_by_height) -> dict[int, LightBlock]:
    """Synthetic header chain 1..n signed by per-height validator
    sets; pvs_by_height(h) returns the priv validators of height h
    (and h+1's set is committed as next_validators_hash)."""
    blocks: dict[int, LightBlock] = {}
    prev_id = BlockID()
    for h in range(1, n_heights + 1):
        pvs = pvs_by_height(h)
        vals = _valset(pvs)
        next_vals = _valset(pvs_by_height(h + 1))
        header = Header(
            chain_id=CHAIN_ID, height=h,
            time=Timestamp(T0 + h, 0),
            last_block_id=prev_id,
            validators_hash=vals.hash(),
            next_validators_hash=next_vals.hash(),
            proposer_address=vals.validators[0].address)
        assert header.version.block == BLOCK_PROTOCOL
        bid = BlockID(hash=header.hash(),
                      part_set_header=PartSetHeader(1, b"\xAA" * 32))
        sigs = []
        by_addr = {pv.get_pub_key().address(): pv for pv in pvs}
        for i, val in enumerate(vals.validators):
            ts = Timestamp(T0 + h, i + 1)
            v = Vote(type=canonical.PRECOMMIT_TYPE, height=h, round=0,
                     block_id=bid, timestamp=ts,
                     validator_address=val.address, validator_index=i)
            v.signature = by_addr[val.address].priv_key.sign(
                v.sign_bytes(CHAIN_ID))
            sigs.append(CommitSig(
                block_id_flag=BLOCK_ID_FLAG_COMMIT,
                validator_address=val.address, timestamp=ts,
                signature=v.signature))
        commit = Commit(height=h, round=0, block_id=bid,
                        signatures=sigs)
        blocks[h] = LightBlock(
            signed_header=SignedHeader(header=header, commit=commit),
            validator_set=vals)
        blocks[h].validate_basic(CHAIN_ID)
        prev_id = bid
    return blocks


class DictProvider(Provider):
    def __init__(self, blocks: dict[int, LightBlock]):
        self.blocks = blocks
        self.requests: list[int] = []

    async def light_block(self, height: int) -> LightBlock:
        self.requests.append(height)
        if height == 0:
            height = max(self.blocks)
        lb = self.blocks.get(height)
        if lb is None:
            raise LightBlockNotFoundError(f"no block {height}")
        return lb

    async def report_evidence(self, ev) -> None:
        pass


def _client(blocks, witnesses=()) -> tuple[Client, DictProvider]:
    primary = DictProvider(blocks)
    c = Client(CHAIN_ID,
               TrustOptions(period_ns=24 * HOUR_NS, height=1,
                            header_hash=blocks[1].hash()),
               primary, list(witnesses), TrustedStore(MemDB()))
    return c, primary


def _now() -> Timestamp:
    return Timestamp(T0 + 1000, 0)


class _CountingVerifier:
    """Wraps a BatchVerifier, mirroring adds/verifies into counters."""

    def __init__(self, inner, counts):
        self._inner = inner
        self._counts = counts

    def add(self, pub_key, msg, sig):
        self._counts["added"] += 1
        self._inner.add(pub_key, msg, sig)

    def verify(self):
        self._counts["batches"] += 1
        return self._inner.verify()

    def __len__(self):
        return len(self._inner)


@pytest.fixture
def batch_counts(monkeypatch):
    counts = {"created": 0, "added": 0, "batches": 0}
    orig = crypto_batch.create_batch_verifier

    def counting(pub_key):
        counts["created"] += 1
        return _CountingVerifier(orig(pub_key), counts)

    monkeypatch.setattr(crypto_batch, "create_batch_verifier",
                        counting)
    return counts


class TestVerifyToHeight:
    def test_single_hop_uses_batch_verifier(self, batch_counts):
        """Stable valset: the target is one non-adjacent hop, and its
        commit checks dispatch through the crypto.batch seam, not the
        per-signature loop."""
        pvs = [new_mock_pv() for _ in range(4)]
        blocks = make_chain(20, lambda h: pvs)

        async def run():
            c, primary = _client(blocks)
            await c.initialize(now=_now())
            lb = await c.verify_to_height(20, now=_now())
            assert lb.height == 20
            # skipping: straight jump, no intermediate fetches
            assert set(primary.requests) <= {1, 20}
            return c
        c = asyncio.run(run())
        assert batch_counts["created"] >= 1
        assert batch_counts["batches"] >= 1
        assert batch_counts["added"] >= 2
        assert c.store.light_block(20) is not None

    def test_shared_cache_skips_overlap(self, batch_counts):
        """The 1/3-trust check and the 2/3 check of one hop examine
        the same commit; with the sync-wide cache the 2/3 check only
        adds what the trusting pass has not already proved.  4 equal
        validators: trusting stops after 2 sigs (early 1/3 exit), the
        2/3 check cache-hits those and adds exactly 1 more — 3 batch
        entries, not 5."""
        pvs = [new_mock_pv() for _ in range(4)]
        blocks = make_chain(10, lambda h: pvs)

        async def run():
            c, _ = _client(blocks)
            await c.initialize(now=_now())
            await c.verify_to_height(10, now=_now())
        asyncio.run(run())
        assert batch_counts["added"] == 3, batch_counts

    def test_bisection_under_valset_rotation(self, batch_counts):
        """Rotate 1 of 4 validators per height: a straight jump from
        the trust root to the tip has < 1/3 overlap, so the client
        must bisect — and every hop's checks stay on the batch
        seam."""
        pool = [new_mock_pv() for _ in range(16)]

        def pvs_at(h):
            # window of 4 shifting one validator per height
            return [pool[(h + i) % len(pool)] for i in range(4)]

        blocks = make_chain(12, pvs_at)

        async def run():
            c, primary = _client(blocks)
            await c.initialize(now=_now())
            lb = await c.verify_to_height(12, now=_now())
            assert lb.height == 12
            # bisection fetched intermediate pivots
            assert len([r for r in primary.requests
                        if r not in (1, 12)]) > 0
            return c
        c = asyncio.run(run())
        assert batch_counts["batches"] >= 2
        # the trace of verified hops landed in the trusted store
        assert len(c.store.heights()) >= 3

    def test_verify_to_height_equals_verify_light_block(self):
        """Same verdict + stored trace as the unshared-cache path."""
        pvs = [new_mock_pv() for _ in range(4)]
        blocks = make_chain(8, lambda h: pvs)

        async def run():
            c1, _ = _client(blocks)
            await c1.initialize(now=_now())
            a = await c1.verify_to_height(8, now=_now())
            c2, _ = _client(blocks)
            await c2.initialize(now=_now())
            b = await c2.verify_light_block_at_height(8, now=_now())
            assert a.hash() == b.hash()
        asyncio.run(run())

    def test_tampered_target_rejected(self):
        """A structurally consistent forgery (header re-hashed into
        the commit's block id, signatures NOT re-made) must die in
        signature verification — the batch path's verdict."""
        pvs = [new_mock_pv() for _ in range(4)]
        blocks = make_chain(6, lambda h: pvs)
        import dataclasses
        lb = blocks[6]
        hdr = dataclasses.replace(lb.signed_header.header,
                                  app_hash=b"\xEE" * 32)
        old_commit = lb.signed_header.commit
        forged_commit = Commit(
            height=6, round=0,
            block_id=BlockID(hash=hdr.hash(),
                             part_set_header=old_commit
                             .block_id.part_set_header),
            signatures=list(old_commit.signatures))
        blocks[6] = LightBlock(
            signed_header=SignedHeader(header=hdr,
                                       commit=forged_commit),
            validator_set=lb.validator_set)

        from cometbft_tpu.light.verifier import LightClientError

        async def run():
            c, _ = _client(blocks)
            await c.initialize(now=_now())
            with pytest.raises(LightClientError):
                await c.verify_to_height(6, now=_now())
        asyncio.run(run())
