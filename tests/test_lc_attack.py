"""Light-client attack: forged witness header -> trace bisection ->
attributed evidence -> peer evidence pool verifies and admits it.

Reference: light/detector.go (examineConflictingHeaderAgainstTrace,
newLightClientAttackEvidence), internal/evidence/verify.go
(VerifyLightClientAttack, validateABCIEvidence), types/evidence.go
GetByzantineValidators.
"""
import asyncio
import dataclasses

import pytest

from cometbft_tpu.abci.client import AppConns
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config import Config
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.db.db import MemDB
from cometbft_tpu.evidence import EvidencePool
from cometbft_tpu.evidence.pool import EvidenceError
from cometbft_tpu.light.client import (
    Client, DivergenceError, TrustOptions,
)
from cometbft_tpu.light.provider import NodeProvider
from cometbft_tpu.light.store import TrustedStore
from cometbft_tpu.state import make_genesis_state
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.store import Store
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types import canonical
from cometbft_tpu.types.block import LightBlock, SignedHeader
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.commit import Commit, CommitSig
from cometbft_tpu.types.evidence import LightClientAttackEvidence
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.priv_validator import new_mock_pv
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.vote import BLOCK_ID_FLAG_COMMIT, Vote


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


def _test_config():
    cfg = Config()
    cfg.consensus.timeout_commit_ns = 10_000_000
    cfg.consensus.timeout_propose_ns = 400_000_000
    return cfg


async def _grow_chain(n_blocks, n_vals=3):
    pvs = [new_mock_pv() for _ in range(n_vals)]
    doc = GenesisDoc(
        chain_id="attack-chain",
        genesis_time=Timestamp(1700000000, 0),
        validators=[GenesisValidator(address=b"",
                                     pub_key=pv.get_pub_key(),
                                     power=10) for pv in pvs])
    from cometbft_tpu.consensus.messages import (
        BlockPartMessage, ProposalMessage, VoteMessage,
    )
    nodes = []
    for pv in pvs:
        app = KVStoreApplication()
        conns = AppConns(app)
        ss, bs = Store(MemDB()), BlockStore(MemDB())
        state = make_genesis_state(doc)
        ss.save(state)
        ex = BlockExecutor(ss, conns.consensus, block_store=bs)
        cs = ConsensusState(_test_config().consensus, state, ex, bs,
                            priv_validator=pv)
        nodes.append((cs, ss, bs))
    gossip = (ProposalMessage, BlockPartMessage, VoteMessage)
    for i, (cs, _, _) in enumerate(nodes):
        def mk(sender):
            def hook(msg):
                if isinstance(msg, gossip):
                    for j, (other, _, _) in enumerate(nodes):
                        if j != sender:
                            other.send_peer(msg, f"n{sender}")
            return hook
        cs.broadcast_hooks.append(mk(i))
    for cs, _, _ in nodes:
        await cs.start()
    while nodes[0][2].height < n_blocks:
        await asyncio.sleep(0.01)
    for cs, _, _ in nodes:
        await cs.stop()
    return doc, pvs, nodes[0][1], nodes[0][2]


def _forge_lunatic_block(doc, pvs, ss, bs, height) -> LightBlock:
    """A lunatic header at `height`: real header with a forged app hash,
    re-committed by ALL validators (they are all byzantine)."""
    meta = bs.load_block_meta(height)
    header = dataclasses.replace(meta.header, app_hash=b"\xEE" * 32)
    header = dataclasses.replace(header, _hash=None) \
        if hasattr(header, "_hash") else header
    try:
        header.__dict__.pop("_hash", None)
    except Exception:
        pass
    vals = ss.load_validators(height)
    forged_id = BlockID(hash=header.hash(),
                        part_set_header=PartSetHeader(1, b"\xAB" * 32))
    sigs = []
    by_addr = {pv.get_pub_key().address(): pv for pv in pvs}
    for i, val in enumerate(vals.validators):
        ts = Timestamp(1700000100 + i, 0)
        v = Vote(type=canonical.PRECOMMIT_TYPE, height=height, round=0,
                 block_id=forged_id, timestamp=ts,
                 validator_address=val.address, validator_index=i)
        pv = by_addr[val.address]
        v.signature = pv.priv_key.sign(v.sign_bytes(doc.chain_id))
        sigs.append(CommitSig(block_id_flag=BLOCK_ID_FLAG_COMMIT,
                              validator_address=val.address,
                              timestamp=ts, signature=v.signature))
    commit = Commit(height=height, round=0, block_id=forged_id,
                    signatures=sigs)
    return LightBlock(signed_header=SignedHeader(header=header,
                                                commit=commit),
                      validator_set=vals)


class _ForgingWitness(NodeProvider):
    """Honest below `forge_height`, lunatic at and above it."""

    def __init__(self, block_store, state_store, chain_id, doc, pvs,
                 forge_height):
        super().__init__(block_store, state_store, chain_id)
        self.doc = doc
        self.pvs = pvs
        self.forge_height = forge_height

    async def light_block(self, height):
        if height == 0:
            height = self.block_store.height
        if height >= self.forge_height:
            return _forge_lunatic_block(self.doc, self.pvs,
                                        self.state_store,
                                        self.block_store, height)
        return await super().light_block(height)


class TestLightClientAttack:
    def test_forged_witness_evidence_accepted_by_peer_pool(self):
        async def run():
            doc, pvs, ss, bs = await _grow_chain(8)
            forge_h = 6
            primary = NodeProvider(bs, ss, doc.chain_id)
            witness = _ForgingWitness(bs, ss, doc.chain_id, doc, pvs,
                                      forge_h)
            root = await primary.light_block(1)
            client = Client(
                doc.chain_id,
                TrustOptions(period_ns=10 * 365 * 24 * 3600 * 10**9,
                             height=1,
                             header_hash=root.signed_header.header
                             .hash()),
                primary, [witness], TrustedStore(MemDB()))
            await client.initialize()

            with pytest.raises(DivergenceError):
                await client.verify_light_block_at_height(forge_h)

            # both sides got the evidence (reference sends to primary
            # AND witness)
            assert primary.evidence and witness.evidence
            ev = primary.evidence[0]
            assert isinstance(ev, LightClientAttackEvidence)
            # lunatic attack: every signer of the forged commit is
            # attributed
            assert len(ev.byzantine_validators) == 3
            assert ev.conflicting_block.height == forge_h
            assert ev.common_height < forge_h

            # a PEER full node verifies the evidence against ITS chain
            # and admits it to the pool — i.e. it would commit it
            pool = EvidencePool(MemDB(), ss, bs)
            pool.add_evidence(ev)
            pending, _ = pool.pending_evidence(1 << 20)
            assert any(p.hash() == ev.hash() for p in pending)
            # the block-validation path a peer runs on a proposed block
            # carrying this evidence passes too
            pool.check_evidence([ev])
        asyncio.run(run())

    def test_tampered_attribution_rejected(self):
        """Evidence whose byzantine set doesn't match what the peer
        derives itself is rejected (validateABCIEvidence)."""
        async def run():
            doc, pvs, ss, bs = await _grow_chain(8)
            forge_h = 6
            primary = NodeProvider(bs, ss, doc.chain_id)
            witness = _ForgingWitness(bs, ss, doc.chain_id, doc, pvs,
                                      forge_h)
            root = await primary.light_block(1)
            client = Client(
                doc.chain_id,
                TrustOptions(period_ns=10 * 365 * 24 * 3600 * 10**9,
                             height=1,
                             header_hash=root.signed_header.header
                             .hash()),
                primary, [witness], TrustedStore(MemDB()))
            await client.initialize()
            with pytest.raises(DivergenceError):
                await client.verify_light_block_at_height(forge_h)
            ev = primary.evidence[0]
            ev.byzantine_validators = ev.byzantine_validators[:1]
            pool = EvidencePool(MemDB(), ss, bs)
            with pytest.raises(EvidenceError,
                               match="byzantine"):
                pool.add_evidence(ev)
        asyncio.run(run())
