"""ABCI over gRPC (reference: proto/cometbft/abci/v2/service.proto,
abci/client/grpc_client.go, abci/server/grpc_server.go)."""
import asyncio
import os
import subprocess
import sys
import tempfile

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.grpc import GRPCClient, GRPCServer
from cometbft_tpu.abci.kvstore import KVStoreApplication


class TestGRPCClientServer:
    def test_echo_info_checktx_commit(self):
        async def run():
            app = KVStoreApplication()
            srv = GRPCServer("127.0.0.1:0", app)
            await srv.start()
            cli = GRPCClient(f"127.0.0.1:{srv.port}")
            await cli.connect()
            try:
                assert (await cli.echo("hello")).message == "hello"
                info = await cli.info(abci.InfoRequest())
                assert info.last_block_height == 0
                res = await cli.check_tx(abci.CheckTxRequest(
                    tx=b"k=v", type=abci.CHECK_TX_TYPE_CHECK))
                assert res.code == 0
                bad = await cli.check_tx(abci.CheckTxRequest(
                    tx=b"notatx", type=abci.CHECK_TX_TYPE_CHECK))
                assert bad.code != 0
                await cli.flush()
            finally:
                await cli.close()
                await srv.stop()
        asyncio.run(run())

    def test_concurrent_calls_one_channel(self):
        """The gRPC client is connection-concurrent — many in-flight
        calls share one channel (reference: grpc_client.go)."""
        async def run():
            app = KVStoreApplication()
            srv = GRPCServer("127.0.0.1:0", app)
            await srv.start()
            cli = GRPCClient(f"127.0.0.1:{srv.port}")
            await cli.connect()
            try:
                results = await asyncio.gather(*(
                    cli.check_tx(abci.CheckTxRequest(
                        tx=f"k{i}=v{i}".encode(),
                        type=abci.CHECK_TX_TYPE_CHECK))
                    for i in range(50)))
                assert all(r.code == 0 for r in results)
            finally:
                await cli.close()
                await srv.stop()
        asyncio.run(run())


class TestNodeWithGRPCApp:
    def test_node_over_external_grpc_kvstore(self):
        """Full node drives a kvstore in a separate process over gRPC
        (reference: e2e 'grpc' ABCI protocol mode)."""
        from cometbft_tpu.config import Config
        from cometbft_tpu.node.node import Node
        from cometbft_tpu.p2p.key import NodeKey
        from cometbft_tpu.privval import FilePV
        from cometbft_tpu.types.genesis import (
            GenesisDoc, GenesisValidator,
        )
        from cometbft_tpu.types.timestamp import Timestamp

        async def run():
            with tempfile.TemporaryDirectory() as d:
                import socket as pysocket
                s = pysocket.socket()
                s.bind(("127.0.0.1", 0))
                port = s.getsockname()[1]
                s.close()
                proc = subprocess.Popen(
                    [sys.executable, "-m", "cometbft_tpu.abci.server",
                     "--address", f"127.0.0.1:{port}",
                     "--app", "kvstore", "--transport", "grpc"],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.DEVNULL,
                    env={**os.environ, "JAX_PLATFORMS": ""})
                try:
                    # wait for the child's ready line before booting the
                    # node — on a loaded 1-vCPU box the import alone can
                    # take seconds (reference: WaitForReady dial)
                    line = await asyncio.wait_for(
                        asyncio.get_event_loop().run_in_executor(
                            None, proc.stdout.readline), timeout=60)
                    assert b"listening" in line, (
                        f"abci server never became ready: {line!r}")
                    home = os.path.join(d, "node")
                    cfg = Config()
                    cfg.base.home = home
                    cfg.base.abci = "grpc"
                    cfg.base.proxy_app = f"127.0.0.1:{port}"
                    cfg.p2p.laddr = "tcp://127.0.0.1:0"
                    cfg.rpc.laddr = ""
                    cfg.consensus.timeout_commit_ns = 50_000_000
                    os.makedirs(os.path.join(home, "config"),
                                exist_ok=True)
                    os.makedirs(os.path.join(home, "data"),
                                exist_ok=True)
                    pv = FilePV.generate(
                        cfg.base.path(cfg.base.priv_validator_key_file),
                        cfg.base.path(
                            cfg.base.priv_validator_state_file))
                    NodeKey.load_or_gen(
                        cfg.base.path(cfg.base.node_key_file))
                    GenesisDoc(
                        chain_id="grpc-abci-chain",
                        genesis_time=Timestamp.now(),
                        validators=[GenesisValidator(
                            address=b"", pub_key=pv.get_pub_key(),
                            power=10)],
                    ).save_as(cfg.base.path(cfg.base.genesis_file))
                    node = Node(cfg)
                    await node.start()
                    for _ in range(200):
                        if node.height >= 2:
                            break
                        await asyncio.sleep(0.05)
                    assert node.height >= 2, "no blocks produced"
                    await node.mempool.check_tx(b"grpc=abci")
                    value = b""
                    for _ in range(200):
                        res = await node.app_conns.query.query(
                            abci.QueryRequest(path="/store",
                                              data=b"grpc"))
                        value = res.value
                        if value:
                            break
                        await asyncio.sleep(0.05)
                    assert value == b"abci"
                    await node.stop()
                finally:
                    proc.terminate()
                    proc.wait(timeout=10)
        asyncio.run(run())
