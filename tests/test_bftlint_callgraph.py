"""ISSUE 20: the interprocedural layer — call-graph resolution, the
fixed-point effect engine, the upgraded checkers' transitive fixtures,
the wire-tag manifest, and ``check --diff``.

Covers, per the ISSUE's test satellite:
  * resolution unit tests: module function vs method (incl. same-
    package base classes) vs imported name vs deliberately-unresolved;
  * cycle convergence of the fixed point (mutual-await cycles settle
    at False; a chain ending in a real await settles at True);
  * bad/good fixture pairs for each upgraded rule (blocking two calls
    deep, await-through-helper straddle, spawn-via-wrapper,
    yield-credited-helper), each bad one exiting 1 via the CLI;
  * regression pinning that the retired false-positive shapes stay
    clean;
  * wire-tag drift against a scratch manifest + the wire-manifest
    regeneration subcommand being idempotent against the committed
    one;
  * ``check --diff`` judging only changed files (scratch git repo).
"""
import ast
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from tools.bftlint.callgraph import (  # noqa: E402
    UNKNOWN,
    build_program,
    module_name_for,
)
from tools.bftlint.checkers import ALL_CHECKERS  # noqa: E402
from tools.bftlint.checkers.wire_tag import (  # noqa: E402
    WireTagChecker,
    extract_messages,
)
from tools.bftlint.core import FileContext, lint_paths  # noqa: E402

FIXTURES = os.path.join(os.path.dirname(__file__), "bftlint_fixtures")


def _ctx(logical_path, source):
    src = f"# bftlint: path={logical_path}\n" + textwrap.dedent(source)
    return FileContext(logical_path, src)


def _program(files):
    """files: {logical_path: source} -> (Program, {path: ctx})."""
    ctxs = {lp: _ctx(lp, src) for lp, src in files.items()}
    return build_program(ctxs.values()), ctxs


def _fn(program, logical_path, qualname):
    mod = program.modules[module_name_for(logical_path)]
    if "." in qualname:
        cname, mname = qualname.split(".", 1)
        return mod.classes[cname].methods[mname]
    return mod.functions[qualname]


def _calls_in(fi):
    return [n for n in ast.walk(fi.node) if isinstance(n, ast.Call)]


def _lint_file(path):
    return lint_paths([path], ALL_CHECKERS).findings


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.bftlint", *args],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)


# ---------------------------------------------------------------------
# resolution

class TestResolution:
    def test_module_function_bare_name(self):
        prog, ctxs = _program({"cometbft_tpu/x/a.py": """
            def helper():
                pass

            def caller():
                helper()
        """})
        ctx = ctxs["cometbft_tpu/x/a.py"]
        call = _calls_in(_fn(prog, "cometbft_tpu/x/a.py", "caller"))[0]
        fi = prog.resolve_call(ctx, call)
        assert fi is not None and fi.qualname == "helper"

    def test_from_import_across_modules(self):
        prog, ctxs = _program({
            "cometbft_tpu/x/util.py": """
                def shared():
                    pass
            """,
            "cometbft_tpu/x/a.py": """
                from cometbft_tpu.x.util import shared

                def caller():
                    shared()
            """})
        ctx = ctxs["cometbft_tpu/x/a.py"]
        call = _calls_in(_fn(prog, "cometbft_tpu/x/a.py", "caller"))[0]
        fi = prog.resolve_call(ctx, call)
        assert fi is not None
        assert fi.module == "cometbft_tpu.x.util"
        assert fi.qualname == "shared"

    def test_relative_import(self):
        prog, ctxs = _program({
            "cometbft_tpu/x/util.py": """
                def shared():
                    pass
            """,
            "cometbft_tpu/x/a.py": """
                from .util import shared

                def caller():
                    shared()
            """})
        ctx = ctxs["cometbft_tpu/x/a.py"]
        call = _calls_in(_fn(prog, "cometbft_tpu/x/a.py", "caller"))[0]
        fi = prog.resolve_call(ctx, call)
        assert fi is not None and fi.module == "cometbft_tpu.x.util"

    def test_self_method_and_base_class(self):
        prog, ctxs = _program({
            "cometbft_tpu/x/base.py": """
                class Base:
                    def inherited(self):
                        pass
            """,
            "cometbft_tpu/x/a.py": """
                from cometbft_tpu.x.base import Base

                class Impl(Base):
                    def own(self):
                        pass

                    def caller(self):
                        self.own()
                        self.inherited()
            """})
        ctx = ctxs["cometbft_tpu/x/a.py"]
        calls = _calls_in(_fn(prog, "cometbft_tpu/x/a.py",
                              "Impl.caller"))
        own = prog.resolve_call(ctx, calls[0])
        inh = prog.resolve_call(ctx, calls[1])
        assert own is not None and own.qualname == "Impl.own"
        assert inh is not None and inh.qualname == "Base.inherited"
        assert inh.module == "cometbft_tpu.x.base"

    def test_unresolved_is_explicit_unknown(self):
        prog, ctxs = _program({"cometbft_tpu/x/a.py": """
            def caller(peer):
                peer.transport.poke()
        """})
        ctx = ctxs["cometbft_tpu/x/a.py"]
        call = _calls_in(_fn(prog, "cometbft_tpu/x/a.py", "caller"))[0]
        assert prog.resolve_call(ctx, call) is None
        s = prog.summary_for_call(ctx, call)
        assert s is UNKNOWN
        # the sound defaults every rule leans on
        assert s.may_await and not s.may_block
        assert not s.always_awaits and not s.spawns_directly

    def test_inheritance_cycle_does_not_hang(self):
        prog, ctxs = _program({"cometbft_tpu/x/a.py": """
            class A(B):
                def caller(self):
                    self.nowhere()

            class B(A):
                pass
        """})
        ctx = ctxs["cometbft_tpu/x/a.py"]
        call = _calls_in(_fn(prog, "cometbft_tpu/x/a.py",
                             "A.caller"))[0]
        assert prog.resolve_call(ctx, call) is None


# ---------------------------------------------------------------------
# effect summaries + fixed point

class TestEffects:
    def test_transitive_may_block_with_chain(self):
        prog, _ = _program({"cometbft_tpu/x/a.py": """
            import time

            def leaf():
                time.sleep(1)

            def mid():
                leaf()

            def top():
                mid()
        """})
        top = _fn(prog, "cometbft_tpu/x/a.py", "top")
        assert prog.summary(top).may_block
        chain = " -> ".join(prog.blocking_chain(top))
        assert "mid" in chain and "leaf" in chain
        assert "time.sleep()" in chain

    def test_suppressed_blocking_site_does_not_propagate(self):
        prog, _ = _program({"cometbft_tpu/x/a.py": """
            import time

            def leaf():
                # bftlint: disable=blocking-in-async
                time.sleep(1)

            def top():
                leaf()
        """})
        top = _fn(prog, "cometbft_tpu/x/a.py", "top")
        assert not prog.summary(top).may_block

    def test_mutual_await_cycle_converges_false(self):
        """Two coroutines that only await each other never actually
        suspend — the least fixed point must settle at False, not
        hang or oscillate."""
        prog, _ = _program({"cometbft_tpu/x/a.py": """
            async def ping():
                await pong()

            async def pong():
                await ping()
        """})
        ping = _fn(prog, "cometbft_tpu/x/a.py", "ping")
        s = prog.summary(ping)
        assert not s.may_await and not s.always_awaits

    def test_three_node_chain_with_real_await(self):
        prog, _ = _program({"cometbft_tpu/x/a.py": """
            import asyncio

            async def c():
                await asyncio.sleep(0)

            async def b():
                await c()

            async def a():
                await b()
        """})
        for name in ("a", "b", "c"):
            s = prog.summary(_fn(prog, "cometbft_tpu/x/a.py", name))
            assert s.may_await and s.always_awaits, name

    def test_conditional_await_is_may_not_always(self):
        prog, _ = _program({"cometbft_tpu/x/a.py": """
            import asyncio

            async def maybe(flag):
                if flag:
                    await asyncio.sleep(0)
        """})
        s = prog.summary(_fn(prog, "cometbft_tpu/x/a.py", "maybe"))
        assert s.may_await and not s.always_awaits

    def test_spawns_directly_not_transitive(self):
        prog, _ = _program({"cometbft_tpu/x/a.py": """
            import asyncio

            def wrapper(coro):
                return asyncio.create_task(coro)

            def outer(coro):
                return wrapper(coro)
        """})
        w = _fn(prog, "cometbft_tpu/x/a.py", "wrapper")
        o = _fn(prog, "cometbft_tpu/x/a.py", "outer")
        assert prog.summary(w).spawns_directly
        # one-level-only by design: the summary records direct spawns
        assert not prog.summary(o).spawns_directly


# ---------------------------------------------------------------------
# the upgraded rules' transitive fixtures

_TRANSITIVE_BAD = {
    "bad_blocking_transitive.py": "blocking-in-async",
    "bad_await_helper.py": "await-atomicity",
    "bad_spawn_wrapper.py": "supervised-spawn",
    "bad_yield_helper.py": "yield-in-loop",
}
_TRANSITIVE_GOOD = (
    "good_blocking_transitive.py",
    "good_await_helper.py",
    "good_spawn_wrapper.py",
    "good_yield_helper.py",
)


@pytest.mark.parametrize("name,rule",
                         sorted(_TRANSITIVE_BAD.items()))
def test_transitive_bad_fixture_fires(name, rule):
    findings = _lint_file(os.path.join(FIXTURES, name))
    assert any(f.rule == rule for f in findings), \
        f"{rule} missing on {name}: {findings}"


def test_blocking_chain_in_finding_message():
    findings = _lint_file(
        os.path.join(FIXTURES, "bad_blocking_transitive.py"))
    two_deep = [f for f in findings
                if "_retry_with_backoff" in f.message]
    assert two_deep, findings
    msg = two_deep[0].message
    # the full witness chain, hop by hop, down to the blocking call
    assert "_backoff" in msg and "time.sleep()" in msg
    assert "cometbft_tpu/consensus/fixture.py:8" in msg


def test_wrapper_spawn_names_the_wrapper():
    findings = _lint_file(
        os.path.join(FIXTURES, "bad_spawn_wrapper.py"))
    wrapper = [f for f in findings if "one level down" in f.message]
    assert wrapper and "_spawn_bg" in wrapper[0].message


@pytest.mark.parametrize("name", _TRANSITIVE_GOOD)
def test_retired_false_positives_stay_clean(name):
    """Regression pin: the shapes the interprocedural pass un-flags
    (never-awaiting helper await before a store, supervisor-routed
    wrapper, credited awaiting helper, suppressed durability point)
    must stay clean."""
    findings = _lint_file(os.path.join(FIXTURES, name))
    assert not findings, f"{name} flagged: {findings}"


def test_cli_exits_nonzero_on_each_transitive_bad_fixture():
    for name in _TRANSITIVE_BAD:
        rel = os.path.join("tests", "bftlint_fixtures", name)
        proc = _cli("check", rel, "--no-baseline")
        assert proc.returncode == 1, \
            (f"check on {rel} exited {proc.returncode}:\n"
             f"{proc.stdout}\n{proc.stderr}")


def test_bare_filecontext_falls_back_intraprocedural():
    """Checkers must keep working on a FileContext with no program
    attached (ctx.program is None): the pre-ISSUE 20 behavior."""
    path = os.path.join(FIXTURES, "bad_blocking_transitive.py")
    with open(path, encoding="utf-8") as f:
        ctx = FileContext(path, f.read())
    assert ctx.program is None
    for checker in ALL_CHECKERS:
        if checker.in_scope(ctx.logical_path):
            list(checker.check(ctx))    # must not raise
    # and the direct-blocking fixture still fires without a program
    bad = os.path.join(FIXTURES, "bad_blocking_in_async.py")
    with open(bad, encoding="utf-8") as f:
        bctx = FileContext(bad, f.read())
    blocking = [c for c in ALL_CHECKERS
                if c.rule == "blocking-in-async"][0]
    assert any(f.rule == "blocking-in-async"
               for f in blocking.check(bctx))


# ---------------------------------------------------------------------
# wire-tag

class TestWireTag:
    def _manifest_for(self, ctx, tmp_path):
        per_path = {ctx.logical_path: extract_messages(ctx)}
        from tools.bftlint.checkers.wire_tag import manifest_payload
        p = tmp_path / "wire_manifest.json"
        p.write_text(json.dumps(manifest_payload(per_path)))
        return str(p)

    def test_extraction_reads_tags_kinds_repeated(self):
        ctx = _ctx("cometbft_tpu/wire/fixture.py", """
            V = Msg(
                "test.V",
                F(1, "height", "int64"),
                F(2, "sigs", "bytes", repeated=True),
            )
        """)
        (decl,) = extract_messages(ctx)
        assert decl.name == "test.V"
        assert decl.fields == {1: "height int64",
                               2: "sigs bytes repeated"}
        assert not decl.duplicates and not decl.unreadable

    def test_drift_changed_tag_flagged(self, tmp_path):
        base = _ctx("cometbft_tpu/wire/fixture.py", """
            V = Msg("test.V", F(1, "height", "int64"))
        """)
        manifest = self._manifest_for(base, tmp_path)
        drifted = _ctx("cometbft_tpu/wire/fixture.py", """
            V = Msg("test.V", F(2, "height", "int64"))
        """)
        findings = list(WireTagChecker(manifest).check(drifted))
        assert findings and "drifted" in findings[0].message

    def test_new_message_flagged_until_regenerated(self, tmp_path):
        base = _ctx("cometbft_tpu/wire/fixture.py", """
            V = Msg("test.V", F(1, "height", "int64"))
        """)
        manifest = self._manifest_for(base, tmp_path)
        grown = _ctx("cometbft_tpu/wire/fixture.py", """
            V = Msg("test.V", F(1, "height", "int64"))
            W = Msg("test.W", F(1, "round", "int32"))
        """)
        findings = list(WireTagChecker(manifest).check(grown))
        assert any("not in wire_manifest" in f.message
                   for f in findings)

    def test_deleted_message_flagged_as_drift(self, tmp_path):
        base = _ctx("cometbft_tpu/wire/fixture.py", """
            V = Msg("test.V", F(1, "height", "int64"))
            W = Msg("test.W", F(1, "round", "int32"))
        """)
        manifest = self._manifest_for(base, tmp_path)
        shrunk = _ctx("cometbft_tpu/wire/fixture.py", """
            V = Msg("test.V", F(1, "height", "int64"))
        """)
        findings = list(WireTagChecker(manifest).check(shrunk))
        assert any("no longer declared" in f.message
                   for f in findings)

    def test_fixture_paths_skip_drift(self, tmp_path):
        """Non-cometbft_tpu paths get duplicate checking only — a
        scratch descriptor must not demand a manifest entry."""
        ctx = _ctx("tests/scratch.py", """
            V = Msg("test.OnlyLocal", F(1, "x", "int64"))
        """)
        base = _ctx("cometbft_tpu/wire/fixture.py", """
            V = Msg("test.V", F(1, "height", "int64"))
        """)
        manifest = self._manifest_for(base, tmp_path)
        assert not list(WireTagChecker(manifest).check(ctx))

    def test_committed_manifest_is_current(self, tmp_path):
        """Regenerating into a scratch path must reproduce the
        committed manifest byte-for-byte (modulo nothing): drift in
        either direction means someone skipped the subcommand."""
        out = tmp_path / "regen.json"
        proc = _cli("wire-manifest",
                    "--wire-manifest-path", str(out))
        assert proc.returncode == 0, proc.stderr
        committed = os.path.join(REPO_ROOT, "tools", "bftlint",
                                 "wire_manifest.json")
        with open(committed, encoding="utf-8") as f:
            want = json.load(f)
        assert json.loads(out.read_text()) == want

    def test_regeneration_refuses_duplicates(self, tmp_path):
        d = tmp_path / "pkg"
        d.mkdir()
        (d / "w.py").write_text(
            'M = Msg("t.M", F(1, "a", "int64"), F(1, "b", "int64"))\n')
        out = tmp_path / "m.json"
        proc = _cli("wire-manifest", str(d),
                    "--wire-manifest-path", str(out))
        assert proc.returncode == 2
        assert "duplicate field number" in proc.stderr
        assert not out.exists()


# ---------------------------------------------------------------------
# check --diff

class TestDiffMode:
    def _git(self, root, *args):
        return subprocess.run(
            ["git", "-C", str(root), *args], check=True,
            capture_output=True, text=True,
            env={**os.environ,
                 "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                 "GIT_COMMITTER_NAME": "t",
                 "GIT_COMMITTER_EMAIL": "t@t"})

    def test_diff_judges_only_changed_files(self, tmp_path):
        """Two files with findings; only the one changed since the
        ref is judged."""
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        swallow = ("def f():\n"
                   "    try:\n"
                   "        g()\n"
                   "    except Exception:\n"
                   "        pass\n")
        (pkg / "changed.py").write_text("def f():\n    pass\n")
        (pkg / "untouched.py").write_text(swallow)
        self._git(tmp_path, "init", "-q")
        self._git(tmp_path, "add", "-A")
        self._git(tmp_path, "commit", "-qm", "seed")
        # now introduce a finding in changed.py only
        (pkg / "changed.py").write_text(swallow)
        proc = _cli("check", str(pkg), "--no-baseline",
                    "--diff", "HEAD", "--git-root", str(tmp_path),
                    "--format", "json")
        assert proc.returncode == 1, proc.stderr
        report = json.loads(proc.stdout)
        assert report["files_scanned"] == 1
        new = [f for f in report["findings"] if not f["baselined"]]
        paths = {f["path"] for f in new}
        assert paths and all(p.endswith("changed.py")
                             for p in paths), paths
        # untouched.py's identical finding was NOT judged
        assert not any(p.endswith("untouched.py") for p in paths)

    def test_diff_clean_when_no_changes(self, tmp_path):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "a.py").write_text("def f():\n    pass\n")
        self._git(tmp_path, "init", "-q")
        self._git(tmp_path, "add", "-A")
        self._git(tmp_path, "commit", "-qm", "seed")
        proc = _cli("check", str(pkg), "--no-baseline",
                    "--diff", "HEAD", "--git-root", str(tmp_path))
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "no changed Python files" in proc.stdout

    def test_diff_bad_ref_fails_loud(self):
        proc = _cli("check", "--diff", "no-such-ref-xyz")
        assert proc.returncode == 2
        assert "failed" in proc.stderr

    def test_diff_summaries_stay_whole_package(self, tmp_path):
        """The corpus for summaries is the whole lint root even when
        only one file is judged: a changed async caller of an
        UNCHANGED blocking helper must still be flagged."""
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "helper.py").write_text(
            "# bftlint: path=cometbft_tpu/consensus/h.py\n"
            "import time\n\n"
            "def pause():\n"
            "    time.sleep(1)\n")
        (pkg / "caller.py").write_text(
            "# bftlint: path=cometbft_tpu/consensus/c.py\n"
            "async def ok():\n"
            "    pass\n")
        self._git(tmp_path, "init", "-q")
        self._git(tmp_path, "add", "-A")
        self._git(tmp_path, "commit", "-qm", "seed")
        (pkg / "caller.py").write_text(
            "# bftlint: path=cometbft_tpu/consensus/c.py\n"
            "from cometbft_tpu.consensus.h import pause\n\n"
            "async def ok():\n"
            "    pause()\n")
        proc = _cli("check", str(pkg), "--no-baseline",
                    "--diff", "HEAD", "--git-root", str(tmp_path),
                    "--format", "json")
        assert proc.returncode == 1, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert report["files_scanned"] == 1
        new = [f for f in report["findings"] if not f["baselined"]]
        assert any(f["rule"] == "blocking-in-async"
                   and "transitively" in f["message"]
                   for f in new), new
