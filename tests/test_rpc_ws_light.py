"""HTTP RPC client, WebSocket subscriptions, and the HTTP light provider
against a LIVE node.

Reference: rpc/client/http, rpc/jsonrpc/server/ws_handler.go,
light/provider/http/http.go.
"""
import asyncio
import os
import tempfile

from cometbft_tpu.abci import types as abci  # noqa: F401 (parity imports)
from cometbft_tpu.config import Config
from cometbft_tpu.light.client import Client as LightClient, TrustOptions
from cometbft_tpu.light.provider import HttpProvider
from cometbft_tpu.db.db import MemDB
from cometbft_tpu.light.store import TrustedStore
from cometbft_tpu.node.node import Node
from cometbft_tpu.p2p.key import NodeKey
from cometbft_tpu.privval import FilePV
from cometbft_tpu.rpc.client import HTTPClient, WSClient
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.timestamp import Timestamp


async def _start_node(d: str) -> Node:
    home = os.path.join(d, "node")
    cfg = Config()
    cfg.base.home = home
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.consensus.timeout_commit_ns = 50_000_000
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    pv = FilePV.generate(
        cfg.base.path(cfg.base.priv_validator_key_file),
        cfg.base.path(cfg.base.priv_validator_state_file))
    NodeKey.load_or_gen(cfg.base.path(cfg.base.node_key_file))
    doc = GenesisDoc(
        chain_id="rpc-chain", genesis_time=Timestamp.now(),
        validators=[GenesisValidator(address=b"",
                                     pub_key=pv.get_pub_key(),
                                     power=10)])
    doc.save_as(cfg.base.path(cfg.base.genesis_file))
    node = Node(cfg)
    await node.start()
    for _ in range(400):
        if node.height >= 3:
            return node
        await asyncio.sleep(0.02)
    raise AssertionError("node produced no blocks")


class TestHTTPClient:
    def test_status_commit_validators_broadcast(self):
        async def run():
            with tempfile.TemporaryDirectory() as d:
                node = await _start_node(d)
                try:
                    addr = f"http://{node._rpc_server.listen_addr}"
                    cli = HTTPClient(addr)
                    st = await cli.status()
                    assert int(st["sync_info"]
                               ["latest_block_height"]) >= 3
                    sh, canonical = await cli.commit(2)
                    assert sh.header.height == 2
                    assert sh.commit.height == 2
                    # reconstructed header must re-hash to the block id the
                    # next header points at
                    sh3, _ = await cli.commit(3)
                    assert sh3.header.last_block_id.hash == \
                        sh.header.hash()
                    vals = await cli.validators(2)
                    assert vals.size() == 1
                    assert vals.validators[0].pub_key is not None
                    res = await cli.broadcast_tx_sync(b"rpc=client")
                    assert res["code"] == 0
                finally:
                    await node.stop()
        asyncio.run(run())

    def test_broadcast_tx_commit_via_events(self):
        async def run():
            with tempfile.TemporaryDirectory() as d:
                node = await _start_node(d)
                try:
                    addr = f"http://{node._rpc_server.listen_addr}"
                    cli = HTTPClient(addr, timeout=30.0)
                    res = await cli.broadcast_tx_commit(b"committed=yes")
                    assert res["check_tx"]["code"] == 0
                    assert res["tx_result"]["code"] == 0
                    assert int(res["height"]) > 0
                finally:
                    await node.stop()
        asyncio.run(run())


class TestWebSocket:
    def test_subscribe_new_block_and_tx(self):
        async def run():
            with tempfile.TemporaryDirectory() as d:
                node = await _start_node(d)
                try:
                    addr = f"http://{node._rpc_server.listen_addr}"
                    ws = WSClient(addr)
                    await ws.connect()
                    sub = await ws.subscribe("tm.event = 'NewBlock'")
                    ev = await sub.next(timeout=10)
                    assert ev["query"] == "tm.event = 'NewBlock'"
                    assert ev["data"]["type"].endswith("NewBlock")
                    h = int(ev["data"]["value"]["block"]["header"]
                            ["height"])
                    assert h >= 1
                    # tx events flow end-to-end: submit via http, hear via ws
                    txsub = await ws.subscribe("tm.event = 'Tx'")
                    cli = HTTPClient(addr)
                    await cli.broadcast_tx_sync(b"ws=event")
                    txev = await txsub.next(timeout=10)
                    import base64 as b64
                    assert b64.b64decode(
                        txev["data"]["value"]["tx"]) == b"ws=event"
                    # normal RPC also works over the same ws conn
                    st = await ws.call("status")
                    assert "sync_info" in st
                    await ws.unsubscribe("tm.event = 'Tx'")
                    await ws.close()
                finally:
                    await node.stop()
        asyncio.run(run())


class TestHttpLightProvider:
    def test_light_client_syncs_over_http(self):
        """A light client bootstraps and verifies headers from a LIVE
        node over HTTP (reference: light/provider/http + statesync's
        stateprovider pattern)."""
        async def run():
            with tempfile.TemporaryDirectory() as d:
                node = await _start_node(d)
                try:
                    addr = f"http://{node._rpc_server.listen_addr}"
                    provider = HttpProvider(addr, chain_id="rpc-chain")
                    root = await provider.light_block(1)
                    client = LightClient(
                        chain_id="rpc-chain",
                        trust_options=TrustOptions(
                            period_ns=3600 * 10**9, height=1,
                            header_hash=root.signed_header.header.hash()),
                        primary=provider, witnesses=[],
                        trusted_store=TrustedStore(MemDB()))
                    await client.initialize()
                    target = node.height
                    lb = await client.verify_light_block_at_height(target)
                    assert lb.signed_header.header.height == target
                finally:
                    await node.stop()
        asyncio.run(run())


class TestRpcStateProvider:
    def test_state_provider_over_http(self):
        """statesync's StateProvider reconstructs trusted sm.State from a
        live node over real RPC (reference: stateprovider.go:29)."""
        async def run():
            with tempfile.TemporaryDirectory() as d:
                node = await _start_node(d)
                try:
                    for _ in range(200):
                        if node.height >= 6:
                            break
                        await asyncio.sleep(0.05)
                    addr = f"http://{node._rpc_server.listen_addr}"
                    provider = HttpProvider(addr, chain_id="rpc-chain")
                    root = await provider.light_block(1)
                    from cometbft_tpu.statesync.syncer import (
                        new_rpc_state_provider,
                    )
                    sp = await new_rpc_state_provider(
                        "rpc-chain", node.genesis_doc, [addr], 1,
                        root.signed_header.header.hash())
                    h = node.height - 3
                    state = await sp.state(h)
                    assert state.last_block_height == h
                    assert state.app_hash
                    commit = await sp.commit(h)
                    assert commit.height == h
                    local = node.state_store.load_validators(h + 1)
                    assert state.validators.hash() == local.hash()
                finally:
                    await node.stop()
        asyncio.run(run())


class TestLightProxy:
    def test_verifying_proxy_serves_checked_rpc(self):
        """`cometbft light` equivalent: a proxy serves commit/validators
        /block RPC only after light verification (reference:
        light/rpc/client.go + light/proxy)."""
        from cometbft_tpu.light.proxy import LightProxy

        async def run():
            with tempfile.TemporaryDirectory() as d:
                node = await _start_node(d)
                proxy = None
                try:
                    addr = f"http://{node._rpc_server.listen_addr}"
                    provider = HttpProvider(addr, chain_id="rpc-chain")
                    root = await provider.light_block(1)
                    proxy = LightProxy(
                        "rpc-chain", addr, [], 1,
                        root.signed_header.header.hash(),
                        "tcp://127.0.0.1:0")
                    await proxy.start()
                    cli = HTTPClient(
                        f"http://{proxy.rpc_listen_addr}")
                    # verified commit round-trips
                    sh, _ = await cli.commit(2)
                    assert sh.header.height == 2
                    direct, _ = await HTTPClient(addr).commit(2)
                    assert sh.header.hash() == direct.header.hash()
                    # verified validators
                    vals = await cli.validators(2)
                    assert vals.size() == 1
                    # block passthrough with header check
                    res = await cli.block(2)
                    assert int(res["block"]["header"]["height"]) == 2
                    # broadcast passthrough works
                    r = await cli.broadcast_tx_sync(b"via=proxy")
                    assert r["code"] == 0
                finally:
                    if proxy is not None:
                        await proxy.stop()
                    await node.stop()
        asyncio.run(run())
