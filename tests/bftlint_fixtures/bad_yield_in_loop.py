class Reactor:
    async def _gossip_routine(self, peer):
        while True:
            # the PR 1 livelock shape: a persistently-true branch
            # continues without ever yielding to the event loop
            if peer.send_queue_full():
                continue
            await peer.send(self.next_part())
