# bftlint: path=cometbft_tpu/consensus/fixture.py
# the retired false positive: awaiting a helper that provably never
# suspends cannot interleave another task, so the store after it
# needs no re-validation
class Machine:
    def _bump(self):
        self.counter += 1

    async def _note(self):
        # async for interface symmetry, but no suspension point
        self._bump()

    async def on_proposal(self, h):
        if self.rs.height != h:
            return
        await self._note()
        self.rs.height = h
