# bftlint: path=cometbft_tpu/consensus/fixture.py
# the straddle hides behind an extracted helper: the await point
# moved into _flush, the unguarded store stayed behind
class Machine:
    async def _flush(self):
        # unresolved operand: may suspend
        await self.wal.write_sync_marker()

    async def on_proposal(self, h):
        if self.rs.height != h:
            return
        await self._flush()
        # await-atomicity: the round state may have advanced during
        # _flush's suspension; no re-check between await and store
        self.rs.height = h
