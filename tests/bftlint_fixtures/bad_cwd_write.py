# bftlint: path=cometbft_tpu/libs/fixture.py
from pathlib import Path


def dump(record, height):
    # relative paths land in whatever CWD the node started from
    with open(f"flight-{height}.json", "w") as f:
        f.write(record)
    Path("crash-report.txt").write_text(record)


def patch(record):
    # update mode writes too — "r+" has no w/a/x but lands in CWD
    with open("state.json", "r+") as f:
        f.write(record)
