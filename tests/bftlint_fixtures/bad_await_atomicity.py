# bftlint: path=cometbft_tpu/consensus/fixture_state.py
class ConsensusState:
    async def enter_round(self, height, round_):
        committed = self.rs.height
        # the await is a suspension point: the ticker or a stop-peer
        # one-shot may advance the round state before we resume
        await self.signer.sign(committed)
        self.rs.height = committed + 1

    async def enter_step_aliased(self, round_):
        rs = self.rs
        proposal = rs.step
        await self.signer.sign(proposal)
        rs.step = proposal + 1
