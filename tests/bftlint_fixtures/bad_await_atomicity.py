# bftlint: path=cometbft_tpu/consensus/fixture_state.py
class ConsensusState:
    async def enter_round(self, height, round_):
        committed = self.rs.height
        # the await is a suspension point: the ticker or a stop-peer
        # one-shot may advance the round state before we resume
        await self.signer.sign(committed)
        self.rs.height = committed + 1

    async def enter_step_aliased(self, round_):
        rs = self.rs
        proposal = rs.step
        await self.signer.sign(proposal)
        rs.step = proposal + 1

    async def enter_step_blind_store(self, round_):
        # strengthened rule: the store after the await is flagged even
        # WITHOUT a load of the same attribute before it — with the
        # commit pipeline two heights are in flight, so any
        # post-suspension write needs re-validation (or the seam)
        rs = self.rs
        await self.signer.sign(round_)
        rs.round = round_

    async def stale_guard_before_await(self, height, round_):
        # a guard BEFORE the suspension is stale by the time the store
        # runs — re-validation must happen after the last await
        rs = self.rs
        if rs.round != round_:
            return
        await self.signer.sign(round_)
        rs.round = round_ + 1
