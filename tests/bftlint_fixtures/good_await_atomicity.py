# bftlint: path=cometbft_tpu/consensus/fixture_state.py
class ConsensusState:
    async def enter_round(self, height, round_):
        committed = self.rs.height
        await self.signer.sign(committed)
        # re-validation after the suspension point: the write only
        # lands if the state is still the one we computed against
        if self.rs.height != committed:
            return
        self.rs.height = committed + 1

    async def enter_step_suppressed(self, round_):
        step = self.rs.step
        await self.signer.sign(step)
        # single-writer architecture; see the baseline rationale
        # bftlint: disable=await-atomicity
        self.rs.step = step + 1
