# bftlint: path=cometbft_tpu/consensus/fixture_state.py
class ConsensusState:
    async def enter_round(self, height, round_):
        committed = self.rs.height
        await self.signer.sign(committed)
        # re-validation after the suspension point: the write only
        # lands if the state is still the one we computed against
        if self.rs.height != committed:
            return
        self.rs.height = committed + 1

    async def enter_step_suppressed(self, round_):
        step = self.rs.step
        await self.signer.sign(step)
        # single-writer architecture; see the baseline rationale
        # bftlint: disable=await-atomicity
        self.rs.step = step + 1

    async def enter_prevote_via_seam(self, height, round_):
        # the sanctioned mutation path: the RoundState transition seam
        # re-validates monotonicity at the store, so a seam call after
        # an await is not a straddle
        rs = self.rs
        await self.signer.sign(round_)
        rs.advance(round_, 4)

    async def lock_via_seam(self, round_):
        rs = self.rs
        await self.signer.sign(round_)
        rs.lock(round_, self.block, self.parts)

    async def store_before_await(self, round_):
        # writes that precede every suspension point need no guard
        rs = self.rs
        rs.round = round_
        await self.signer.sign(round_)
