# bftlint: path=cometbft_tpu/fixture/reactor.py
import asyncio


class Reactor:
    async def start(self):
        # bare spawn in reactor scope: crashes die silently
        self._task = asyncio.create_task(self._routine())
        asyncio.ensure_future(self._other())

    async def _routine(self):
        pass

    async def _other(self):
        pass
