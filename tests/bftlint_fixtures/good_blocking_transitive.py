# bftlint: path=cometbft_tpu/consensus/fixture.py
import asyncio
import time


def _compute(x):
    # pure helper: calling it from async code is fine
    return x * 2


def _flush(tag):
    # justified synchronous durability point AT THE BLOCKING SITE:
    # the suppression keeps the blocking call out of the effect
    # summary, so async callers are not transitively flagged
    # bftlint: disable=blocking-in-async
    time.sleep(0.001)
    return tag


class Dialer:
    async def tick(self, peer):
        _compute(1)
        _flush("wal")
        # unresolved call: sound default is may_block=False — the
        # linter only claims blocking it can prove
        peer.transport.poke()
        await asyncio.sleep(0)
