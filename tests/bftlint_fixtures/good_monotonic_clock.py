# bftlint: path=cometbft_tpu/p2p/fixture.py
import time


class Tracker:
    def touch(self):
        self.last_seen = time.monotonic()

    def save(self, f):
        # persistence boundary: wall time is the point here
        # bftlint: disable=monotonic-clock
        now_w = time.time()
        f.write(str(now_w - (time.monotonic() - self.last_seen)))
