# a clean descriptor: unique tags, constant positional shape — and a
# fixture path outside cometbft_tpu/, so no manifest entry is demanded
from cometbft_tpu.wire.proto import F, Msg

PART = Msg(
    "test.wire.Part",
    F(1, "index", "uint32"),
    F(2, "bytes", "bytes"),
)

BLOCK_PART = Msg(
    "test.wire.BlockPart",
    F(1, "height", "int64"),
    F(2, "round", "int32"),
    F(3, "part", "msg", msg=PART, always=True),
    F(4, "sigs", "bytes", repeated=True),
)
