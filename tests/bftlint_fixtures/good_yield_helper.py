# bftlint: path=cometbft_tpu/consensus/fixture.py
# awaits inside an always-awaiting helper keep their credit: the
# naive "only literal awaits count" upgrade would have flagged this
import asyncio


class Gossip:
    async def _drain(self, ps):
        await ps.flush()

    async def routine(self, ps):
        while True:
            if ps.dirty:
                await self._drain(ps)
                continue
            await asyncio.sleep(0.1)
