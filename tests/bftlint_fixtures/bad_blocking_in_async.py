# bftlint: path=cometbft_tpu/p2p/fixture.py
import time


class Conn:
    async def backoff(self):
        # one blocking sleep freezes every reactor on the loop
        time.sleep(0.5)

    async def snapshot(self, path):
        with open(path, "w") as f:
            f.write("state")
