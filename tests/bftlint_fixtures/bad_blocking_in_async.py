# bftlint: path=cometbft_tpu/p2p/fixture.py
import time


class Conn:
    async def backoff(self):
        # one blocking sleep freezes every reactor on the loop
        time.sleep(0.5)

    async def snapshot(self, path):
        with open(path, "w") as f:
            f.write("state")


class Tally:
    async def on_vote_burst(self, entries, dev_future):
        # ISSUE 14: synchronous batch verification on the loop —
        # every reactor stalls for the whole kernel run
        bv = object()
        ok, mask = bv.verify()
        preverify_signatures(entries)
        self.signature_verifier.verify()
        dev_future.block_until_ready()
        return ok, mask
