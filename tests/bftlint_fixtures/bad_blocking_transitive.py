# bftlint: path=cometbft_tpu/consensus/fixture.py
# the ISSUE 20 blind spot: the blocking call is two helper calls deep
# from the async entry — invisible to the intra-procedural rule
import time


def _backoff():
    time.sleep(0.5)


def _retry_with_backoff():
    _backoff()


class Dialer:
    def _pause(self):
        time.sleep(0.1)

    async def connect(self):
        # blocking-in-async: transitively blocks via
        # _retry_with_backoff -> _backoff -> time.sleep
        _retry_with_backoff()

    async def reconnect(self):
        # one method-call deep: self._pause -> time.sleep
        self._pause()
