# bftlint: path=cometbft_tpu/p2p/fixture.py
import time


class Tracker:
    def touch(self):
        # wall clock feeding interval arithmetic: NTP slew corrupts it
        self.last_seen = time.time()

    def stale(self, now):
        return now - self.last_seen > 30.0
