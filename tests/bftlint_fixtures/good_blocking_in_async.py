# bftlint: path=cometbft_tpu/p2p/fixture.py
import asyncio


class Conn:
    async def backoff(self):
        await asyncio.sleep(0.5)

    def snapshot_sync(self, path):
        # sync context: blocking I/O is fine here
        with open(path, "w") as f:
            f.write("state")

    async def flush_wal(self, path):
        # synchronous durability point: the write-through fsync IS
        # the correctness requirement
        # bftlint: disable=blocking-in-async
        with open(path, "a") as f:
            f.write("entry")


class Tally:
    def tally_sync(self, bv):
        # sync context: the caller already owns a worker thread
        return bv.verify()

    async def on_vote_burst(self, entries, bv, proof, root, leaf):
        # the off-loop seam: awaitable verdict future, loop keeps
        # draining gossip until the barrier
        import asyncio
        await asyncio.wrap_future(preverify_signatures_async(entries))
        ok, mask = await bv.verify_async()
        # a merkle proof check is NOT a batch verifier: `verify` on
        # non-verifier receivers must not trip the rule
        proof.verify(root, leaf)
        return ok, mask
