# bftlint: path=cometbft_tpu/p2p/fixture.py
import asyncio


class Conn:
    async def backoff(self):
        await asyncio.sleep(0.5)

    def snapshot_sync(self, path):
        # sync context: blocking I/O is fine here
        with open(path, "w") as f:
            f.write("state")

    async def flush_wal(self, path):
        # synchronous durability point: the write-through fsync IS
        # the correctness requirement
        # bftlint: disable=blocking-in-async
        with open(path, "a") as f:
            f.write("entry")
