import logging

log = logging.getLogger(__name__)


def fetch(store, height, metrics):
    try:
        return store.load(height)
    except Exception:
        log.error("load failed", exc_info=True)
        raise


def tally(votes, metrics):
    for v in votes:
        try:
            v.verify()
        except Exception:
            metrics.invalid_votes.inc()


def gauge_failure(probe, metrics, family, backend):
    # set/add on a recognizable metric receiver is handling
    try:
        probe.run()
    except Exception:
        metrics.breaker_gauge.set(1)
        family.with_labels(backend=backend).add(1)


def delegate(conn, on_error):
    try:
        conn.flush()
    except Exception as e:
        on_error(e)


def probe():
    # availability probe: absence is the expected outcome
    try:
        import _missing_native_module  # noqa: F401
    except Exception:  # bftlint: disable=swallowed-exception
        return False
    return True
