def fetch(store, height):
    try:
        return store.load(height)
    except Exception:
        pass


def tally(votes):
    for v in votes:
        try:
            v.verify()
        except:  # noqa: E722
            continue


def stop(task, stopped, seen, peer_id):
    # .set()/.add() on non-metric receivers is still a swallow:
    # signalling an event or caching an id does not surface the error
    try:
        task.cancel()
    except Exception:
        stopped.set()
    try:
        task.join()
    except Exception:
        seen.add(peer_id)
