# bftlint: path=cometbft_tpu/p2p/switch.py
# the spawn hides one wrapper level down — ISSUE 20 follows exactly
# one level, so both the wrapper body and its call site are flagged
import asyncio


def _spawn_bg(coro):
    return asyncio.create_task(coro)


class Switch:
    async def start(self):
        _spawn_bg(self._accept_loop())
