# bftlint: path=cometbft_tpu/consensus/fixture_reactor.py
class ConsensusReactor:
    async def gossip_data(self, ps):
        # reactor-side peer round state: a store after an await with
        # no re-validation — the peer may have advanced height/round
        # (a NewRoundStep applied by the receive path) across the
        # suspension, so the stale header lands on the wrong round
        prs = ps.prs
        header = self.pick_header(prs)
        await self.sender.send(header)
        prs.proposal_block_parts_header = header

    async def gossip_catchup_blind(self, ps):
        # strengthened rule: flagged even without a prior load of the
        # same attribute
        prs = ps.prs
        await self.sender.send(b"part")
        prs.proposal_block_parts = None

    async def stale_guard(self, ps):
        # the guard runs BEFORE the suspension: stale by store time
        prs = ps.prs
        if prs.round != 0:
            return
        await self.sender.send(b"x")
        prs.round = 1
