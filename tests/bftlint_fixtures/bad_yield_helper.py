# bftlint: path=cometbft_tpu/consensus/fixture.py
# the retired false negative: the continue path "awaits", but the
# awaited helper never suspends — a busy-spin in disguise
import asyncio


class Gossip:
    async def _drain(self, ps):
        while ps.queue:
            ps.queue.pop()

    async def routine(self, ps):
        while True:
            if ps.dirty:
                await self._drain(ps)
                # yield-in-loop: _drain never awaits, so no
                # suspension happened on the way here
                continue
            await asyncio.sleep(0.1)
