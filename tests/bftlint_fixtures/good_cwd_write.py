# bftlint: path=cometbft_tpu/libs/fixture.py
import os
import tempfile


def dump(record, height, dump_dir):
    path = os.path.join(dump_dir or tempfile.gettempdir(),
                        f"flight-{height}.json")
    with open(path, "w") as f:
        f.write(record)


def dump_here_on_purpose(record):
    # a CLI report written to the invoker's CWD by contract
    # bftlint: disable=cwd-write
    with open("report.json", "w") as f:
        f.write(record)
