class Reactor:
    def on_recv(self, peer, msg, ok, backend):
        self.metrics.recv_msgs.with_labels("p2p").inc()
        self.metrics.recv_verdict.with_labels(
            "accepted" if ok else "rejected").inc()
        # `backend` is in the reviewed-bounded allowlist
        self.metrics.recv_backend.with_labels(backend).inc()
        # peer label: bounded by max peer count, runtime overflow
        # collapse backstops — reviewed at this call site
        # bftlint: disable=unbounded-label
        self.metrics.recv_peer.with_labels(peer.id).add(len(msg))
