# two fields sharing one wire tag: decode order silently picks a
# winner (the runtime Msg.__init__ check only fires if this arm is
# ever constructed — rarely-imported reactors may never be, in CI)
from cometbft_tpu.wire.proto import F, Msg

DUP = Msg(
    "test.wire.DupTag",
    F(1, "height", "int64"),
    F(1, "round", "int32"),
    F(2, "step", "uint32"),
)
