# bftlint: path=cometbft_tpu/fixture/reactor.py
import asyncio


class Reactor:
    async def start(self):
        self._task = self.supervisor.spawn(
            lambda: self._routine(), name="routine", kind="routine")
        # a provably supervisor-mediated spawn may be suppressed
        # inline with the reason on record:
        # bftlint: disable=supervised-spawn
        self._shim = asyncio.create_task(self._bridge())

    async def _routine(self):
        pass

    async def _bridge(self):
        pass
