# bftlint: path=cometbft_tpu/p2p/switch.py
# the sanctioned shape: the wrapper routes through the supervisor
# (self.supervisor.spawn is deliberately unresolvable — UNKNOWN
# spawns nothing), so neither the wrapper nor its callers are flagged
class Switch:
    def _launch(self, coro, name):
        return self.supervisor.spawn(coro, name=name)

    async def start(self):
        self._launch(self._accept_loop(), "accept")
