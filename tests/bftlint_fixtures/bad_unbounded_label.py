class Reactor:
    def on_recv(self, peer, msg, err):
        # error strings and raw peer input are unbounded label values
        self.metrics.recv_errors.with_labels(str(err)).inc()
        self.metrics.recv_bytes.with_labels(
            f"peer-{peer.remote_addr}").add(len(msg))
