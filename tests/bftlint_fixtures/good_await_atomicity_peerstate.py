# bftlint: path=cometbft_tpu/consensus/fixture_reactor_ok.py
class ConsensusReactor:
    async def gossip_data_revalidated(self, ps):
        # explicit re-validation: the stored attribute itself is
        # re-read between the last await and the store
        prs = ps.prs
        header = self.pick_header(prs)
        await self.sender.send(header)
        if prs.proposal_block_parts_header is not None:
            return
        prs.proposal_block_parts_header = header

    async def gossip_via_seam(self, ps):
        # the PeerState seam re-validates (height, round) at the
        # write — a seam call after an await is the sanctioned store
        await self.sender.send(b"part")
        ps.set_has_proposal_block_part(1, 0, 3)
        ps.init_catchup_parts(1, self.header)

    async def no_await_before_store(self, ps):
        # stores before the first suspension are not straddles
        prs = ps.prs
        prs.proposal_pol_round = 2
        await self.sender.send(b"x")
