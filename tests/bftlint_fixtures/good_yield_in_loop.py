import asyncio


class Reactor:
    async def _gossip_routine(self, peer):
        while True:
            await asyncio.sleep(0.01)
            if peer.send_queue_full():
                continue
            await peer.send(self.next_part())

    async def _drain_routine(self, peer):
        while True:
            if peer.closed():
                # terminal branch: the supervisor cancels us right
                # after close, spinning is impossible
                # bftlint: disable=yield-in-loop
                continue
            await peer.drain()
