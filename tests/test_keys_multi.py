"""secp256k1 + bls12381 key types and mixed-key validator sets.

Reference behaviors: crypto/secp256k1/secp256k1.go (lower-S rule, Bitcoin
addresses), crypto/bls12381/key_bls12381.go (G1 pubkeys / G2 sigs,
aggregates), types/validator_set.go:845 AllKeysHaveSameType gating the
batch path (types/validation.go:15-21).
"""
import pytest

from cometbft_tpu.crypto import _bls12381_math as blsm
from cometbft_tpu.crypto import bls12381, ed25519, encoding, secp256k1
from cometbft_tpu.types import canonical
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.commit import Commit, CommitSig
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validation import verify_commit
from cometbft_tpu.types.validator import Validator
from cometbft_tpu.types.validator_set import ValidatorSet
from cometbft_tpu.types.vote import BLOCK_ID_FLAG_COMMIT, Vote


class TestSecp256k1:
    def test_sign_verify(self):
        sk = secp256k1.gen_priv_key()
        pk = sk.pub_key()
        msg = b"hello consensus"
        sig = sk.sign(msg)
        assert len(sig) == 64
        assert pk.verify_signature(msg, sig)
        assert not pk.verify_signature(msg + b"!", sig)
        assert not pk.verify_signature(msg, sig[:-1] + bytes([sig[-1] ^ 1]))

    def test_pubkey_shape_and_address(self):
        sk = secp256k1.gen_priv_key()
        pk = sk.pub_key()
        assert len(pk.bytes()) == 33
        assert pk.bytes()[0] in (2, 3)
        assert len(pk.address()) == 20
        assert pk.type() == "secp256k1"

    def test_high_s_rejected(self):
        """Malleated (N - S) signatures must not verify
        (reference secp256k1.go:188-218)."""
        sk = secp256k1.gen_priv_key()
        msg = b"malleability"
        sig = sk.sign(msg)
        r, s = sig[:32], int.from_bytes(sig[32:], "big")
        high_s = (secp256k1._N - s).to_bytes(32, "big")
        assert not sk.pub_key().verify_signature(msg, r + high_s)

    def test_deterministic_from_secret(self):
        a = secp256k1.gen_priv_key_from_secret(b"seed")
        b = secp256k1.gen_priv_key_from_secret(b"seed")
        assert a.bytes() == b.bytes()
        assert a.pub_key().bytes() == b.pub_key().bytes()

    def test_roundtrip_via_encoding(self):
        pk = secp256k1.gen_priv_key().pub_key()
        d = encoding.pub_key_to_proto(pk)
        assert encoding.pub_key_from_proto(d) == pk


class TestBls12381:
    def test_sign_verify(self):
        sk = bls12381.gen_priv_key()
        pk = sk.pub_key()
        msg = b"bls block vote"
        sig = sk.sign(msg)
        assert len(sig) == 96
        assert len(pk.bytes()) == 96
        assert pk.verify_signature(msg, sig)
        assert not pk.verify_signature(msg + b"!", sig)

    def test_address_and_type(self):
        pk = bls12381.gen_priv_key_from_secret(b"s").pub_key()
        assert len(pk.address()) == 20
        assert pk.type() == "bls12_381"

    def test_deterministic_keygen(self):
        a = bls12381.gen_priv_key_from_secret(b"same secret")
        b = bls12381.gen_priv_key_from_secret(b"same secret")
        assert a.bytes() == b.bytes()
        assert a.pub_key().bytes() == b.pub_key().bytes()

    def test_infinite_pubkey_rejected(self):
        inf = bytes([0x40]) + bytes(95)
        with pytest.raises(ValueError):
            bls12381.Bls12381PubKey(inf)

    def test_serialization_roundtrip(self):
        sk = bls12381.gen_priv_key_from_secret(b"ser")
        pk_pt = blsm.g1_deserialize(sk.pub_key().bytes())
        assert blsm.g1_uncompress(blsm.g1_compress(pk_pt)) == pk_pt
        sig = sk.sign(b"m")
        sig_pt = blsm.g2_uncompress(sig)
        assert blsm.g2_compress(sig_pt) == sig

    def test_fast_aggregate_verify(self):
        """All validators sign ONE message (the aggregate-commit shape of
        BASELINE config #5)."""
        msg = b"canonical vote bytes at height H"
        sks = [bls12381.gen_priv_key_from_secret(bytes([i]) * 8)
               for i in range(4)]
        pks = [sk.pub_key() for sk in sks]
        agg = bls12381.aggregate_signatures([sk.sign(msg) for sk in sks])
        assert bls12381.fast_aggregate_verify(pks, msg, agg)
        assert not bls12381.fast_aggregate_verify(pks, msg + b"!", agg)
        assert not bls12381.fast_aggregate_verify(pks[:3], msg, agg)

    def test_aggregate_verify_distinct_msgs(self):
        sks = [bls12381.gen_priv_key_from_secret(bytes([40 + i]) * 4)
               for i in range(3)]
        pks = [sk.pub_key() for sk in sks]
        msgs = [b"m0", b"m1", b"m2"]
        agg = bls12381.aggregate_signatures(
            [sk.sign(m) for sk, m in zip(sks, msgs)])
        assert bls12381.aggregate_verify(pks, msgs, agg)
        assert not bls12381.aggregate_verify(pks, [b"m0", b"m1", b"mX"], agg)
        # duplicate messages rejected (rogue-message rule)
        assert not bls12381.aggregate_verify(pks, [b"m0", b"m0", b"m2"],
                                             agg)


class TestMixedKeyValidatorSet:
    def _commit_fixture(self, privs, chain_id="mixed-chain", height=3):
        vals = [Validator.new(pk.pub_key(), 10) for pk in privs]
        pairs = sorted(zip(vals, privs),
                       key=lambda vp: (-vp[0].voting_power, vp[0].address))
        vals = [p[0] for p in pairs]
        privs = [p[1] for p in pairs]
        vset = ValidatorSet(vals)
        block_id = BlockID(hash=b"\x21" * 32,
                           part_set_header=PartSetHeader(1, b"\x43" * 32))
        sigs = []
        for i, (val, priv) in enumerate(zip(vset.validators, privs)):
            ts = Timestamp(1700000000 + i, 0)
            v = Vote(type=canonical.PRECOMMIT_TYPE, height=height, round=0,
                     block_id=block_id, timestamp=ts,
                     validator_address=val.address, validator_index=i)
            sigs.append(CommitSig(block_id_flag=BLOCK_ID_FLAG_COMMIT,
                                  validator_address=val.address,
                                  timestamp=ts,
                                  signature=priv.sign(v.sign_bytes(chain_id))))
        commit = Commit(height=height, round=0, block_id=block_id,
                        signatures=sigs)
        return chain_id, vset, block_id, height, commit

    def test_mixed_keys_disable_batch_and_verify(self):
        """Mixed key types must fall back to the single-sig path
        (reference types/validation.go:15-21) and still verify."""
        privs = [ed25519.gen_priv_key(), ed25519.gen_priv_key(),
                 secp256k1.gen_priv_key(),
                 bls12381.gen_priv_key_from_secret(b"v3")]
        chain_id, vset, bid, h, commit = self._commit_fixture(privs)
        assert not vset.all_keys_have_same_type()
        verify_commit(chain_id, vset, bid, h, commit)

    def test_single_type_set_reports_same_type(self):
        privs = [secp256k1.gen_priv_key() for _ in range(3)]
        chain_id, vset, bid, h, commit = self._commit_fixture(privs)
        assert vset.all_keys_have_same_type()
        verify_commit(chain_id, vset, bid, h, commit)


class TestStressMixed10k:
    """BASELINE config #5: 10k-validator Commit, mixed key types, plus the
    bls12381 aggregate-sig path."""

    def test_10k_mixed_key_commit_verify(self):
        chain_id, height = "stress-chain", 9
        n_ed = 9990
        privs = [ed25519.gen_priv_key() for _ in range(n_ed)]
        privs += [secp256k1.gen_priv_key() for _ in range(8)]
        privs += [bls12381.gen_priv_key_from_secret(bytes([i]) * 2)
                  for i in range(2)]
        vals = [Validator.new(pk.pub_key(), 5) for pk in privs]
        pairs = sorted(zip(vals, privs),
                       key=lambda vp: (-vp[0].voting_power, vp[0].address))
        vset = ValidatorSet([p[0] for p in pairs])
        privs = [p[1] for p in pairs]
        assert not vset.all_keys_have_same_type()
        block_id = BlockID(hash=b"\x77" * 32,
                           part_set_header=PartSetHeader(1, b"\x99" * 32))
        sigs = []
        for i, (val, priv) in enumerate(zip(vset.validators, privs)):
            ts = Timestamp(1700000000, 0)
            v = Vote(type=canonical.PRECOMMIT_TYPE, height=height, round=0,
                     block_id=block_id, timestamp=ts,
                     validator_address=val.address, validator_index=i)
            sigs.append(CommitSig(
                block_id_flag=BLOCK_ID_FLAG_COMMIT,
                validator_address=val.address, timestamp=ts,
                signature=priv.sign(v.sign_bytes(chain_id))))
        commit = Commit(height=height, round=0, block_id=block_id,
                        signatures=sigs)
        # mixed keys -> single-sig fallback path, all 10k must verify
        verify_commit(chain_id, vset, block_id, height, commit)

    def test_10k_bls_aggregate(self):
        """10k G1 pubkey aggregation + one pairing check over a shared
        message (aggregate-signature commit shape)."""
        msg = b"one canonical commit message"
        # aggregate pubkey/sig pair built by scalar identity:
        # sum_i sk_i applied to G1/H(m); signer count kept real via
        # per-signer pubkey objects over distinct scalars.
        import cometbft_tpu.crypto._bls12381_math as mm
        n = 10_000
        scalars = [i + 2 for i in range(n)]
        # consecutive scalars -> derive pubkeys incrementally (one G1 add
        # per key instead of a full scalar mult; pure-python test budget)
        pks = []
        pt = mm.pt_mul(mm.G1_OPS, mm.G1_GEN, scalars[0])
        for _ in range(n):
            pks.append(bls12381.Bls12381PubKey._from_point_unchecked(pt))
            pt = mm.pt_add(mm.G1_OPS, pt, mm.G1_GEN)
        sig_scalar = sum(scalars) % mm.R_ORDER
        agg_sig = bls12381.Bls12381PrivKey(
            sig_scalar.to_bytes(32, "big")).sign(msg)
        assert bls12381.fast_aggregate_verify(pks, msg, agg_sig)
        assert not bls12381.fast_aggregate_verify(pks, msg + b"!", agg_sig)


class TestKeyRegistry:
    def test_gen_by_type_roundtrip(self):
        for kt in encoding.supported_key_types():
            sk = encoding.gen_priv_key_by_type(kt)
            assert sk.type() == kt
            sk2 = encoding.priv_key_from_type_and_bytes(kt, sk.bytes())
            assert sk2.pub_key() == sk.pub_key()
            pk = encoding.pub_key_from_type_and_bytes(
                kt, sk.pub_key().bytes())
            assert pk == sk.pub_key()
