"""Full node assembly + RPC tests: init files, run a validator, query
and broadcast through the JSON-RPC surface, mempool gossip between
nodes, restart, rollback.
"""
import asyncio
import json

import pytest

from cometbft_tpu.config import Config
from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.node import Node, init_files


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _cfg(home, p2p_port=0, rpc_port=0, peers=""):
    cfg = Config()
    cfg.base.home = str(home)
    cfg.base.db_backend = "sqlite"
    cfg.base.log_level = "error"
    cfg.p2p.laddr = f"127.0.0.1:{p2p_port}"
    cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_port}"
    cfg.p2p.persistent_peers = peers
    # fast test timeouts
    cfg.consensus.timeout_propose_ns = 100_000_000
    cfg.consensus.timeout_propose_delta_ns = 10_000_000
    cfg.consensus.timeout_vote_ns = 50_000_000
    cfg.consensus.timeout_vote_delta_ns = 10_000_000
    return cfg


async def _rpc_call(port, method, params=None):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    body = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                       "params": params or {}}).encode()
    writer.write(b"POST / HTTP/1.1\r\nHost: x\r\n"
                 b"Content-Type: application/json\r\n"
                 b"Content-Length: " + str(len(body)).encode() +
                 b"\r\nConnection: close\r\n\r\n" + body)
    await writer.drain()
    raw = await reader.read()
    writer.close()
    head, _, payload = raw.partition(b"\r\n\r\n")
    return json.loads(payload)


async def _wait(cond, timeout=30.0):
    async def waiter():
        while not cond():
            await asyncio.sleep(0.02)
    await asyncio.wait_for(waiter(), timeout)


class TestSingleNode:
    def test_init_start_rpc(self, tmp_path):
        async def go():
            cfg = _cfg(tmp_path)
            init_files(cfg, chain_id="rpc-chain")
            node = Node(cfg)
            await node.start()
            try:
                port = node._rpc_server.port
                await _wait(lambda: node.height >= 2)

                st = await _rpc_call(port, "status")
                assert st["result"]["node_info"]["network"] == \
                    "rpc-chain"
                assert int(st["result"]["sync_info"]
                           ["latest_block_height"]) >= 2

                h = await _rpc_call(port, "health")
                assert h["result"]["status"] == "ok"
                assert int(h["result"]["height"]) >= 2
                assert h["result"]["height_lag"] == "0"
                assert h["result"]["catching_up"] is False
                assert h["result"]["n_peers"] == "0"
                assert "event_loop_lag_p95_s" in h["result"]
                assert "pipeline_barrier_wait_p95_s" in h["result"]

                ai = await _rpc_call(port, "abci_info")
                assert int(ai["result"]["response"]
                           ["last_block_height"]) >= 1

                # broadcast a tx and watch it commit
                import base64
                tx = base64.b64encode(b"city=zion").decode()
                r = await _rpc_call(port, "broadcast_tx_commit",
                                    {"tx": tx})
                assert r["result"]["tx_result"]["code"] == 0
                committed_h = int(r["result"]["height"])
                assert committed_h >= 1

                q = await _rpc_call(port, "abci_query",
                                    {"data": "city"})
                assert base64.b64decode(
                    q["result"]["response"]["value"]) == b"zion"

                blk = await _rpc_call(port, "block",
                                      {"height": str(committed_h)})
                txs = blk["result"]["block"]["data"]["txs"]
                assert tx in txs

                br = await _rpc_call(port, "block_results",
                                     {"height": str(committed_h)})
                assert br["result"]["txs_results"][0]["code"] == 0

                vals = await _rpc_call(port, "validators")
                assert vals["result"]["total"] == "1"

                cm = await _rpc_call(port, "commit",
                                     {"height": "1"})
                assert cm["result"]["signed_header"]["header"][
                    "chain_id"] == "rpc-chain"

                ni = await _rpc_call(port, "net_info")
                assert ni["result"]["n_peers"] == "0"

                bad = await _rpc_call(port, "no_such_method")
                assert bad["error"]["code"] == -32601
            finally:
                await node.stop()
        run(go())

    def test_restart_continues(self, tmp_path):
        async def go():
            cfg = _cfg(tmp_path)
            init_files(cfg, chain_id="restart-chain")
            node = Node(cfg)
            await node.start()
            try:
                await _wait(lambda: node.height >= 3)
            finally:
                await node.stop()
            h1 = node.height

            node2 = Node(_cfg(tmp_path))
            await node2.start()
            try:
                await _wait(lambda: node2.height >= h1 + 2)
            finally:
                await node2.stop()
            assert node2.height >= h1 + 2
        run(go())


class TestTwoNodeNetwork:
    def test_mempool_gossip_between_nodes(self, tmp_path):
        async def go():
            from cometbft_tpu.privval import FilePV
            from cometbft_tpu.types.genesis import (
                GenesisDoc, GenesisValidator,
            )
            from cometbft_tpu.types.timestamp import Timestamp

            homes = [tmp_path / "n0", tmp_path / "n1"]
            cfgs = [_cfg(h) for h in homes]
            pvs = []
            for cfg in cfgs:
                import os
                os.makedirs(cfg.base.home + "/config", exist_ok=True)
                os.makedirs(cfg.base.home + "/data", exist_ok=True)
                pvs.append(FilePV.load_or_generate(
                    cfg.base.path(cfg.base.priv_validator_key_file),
                    cfg.base.path(
                        cfg.base.priv_validator_state_file)))
            doc = GenesisDoc(
                chain_id="two-node",
                genesis_time=Timestamp(1700000000, 0),
                validators=[GenesisValidator(
                    address=b"", pub_key=pv.get_pub_key(), power=10)
                    for pv in pvs])
            doc.validate_and_complete()
            for cfg in cfgs:
                doc.save_as(cfg.base.path(cfg.base.genesis_file))

            n0 = Node(cfgs[0])
            await n0.start()
            cfgs[1].p2p.persistent_peers = \
                f"{n0.node_key.id}@{n0.switch.listen_addr}"
            n1 = Node(cfgs[1])
            await n1.start()
            try:
                await _wait(lambda: n0.switch.num_peers() == 1)
                await _wait(lambda: n0.height >= 2 and
                            n1.height >= 2)
                # submit to n1 only; mempool gossip carries it to the
                # proposer eventually
                import base64
                port1 = n1._rpc_server.port
                tx = base64.b64encode(b"gossip=works").decode()
                r = await _rpc_call(port1, "broadcast_tx_sync",
                                    {"tx": tx})
                assert r["result"]["code"] == 0

                async def committed():
                    q = await _rpc_call(
                        n0._rpc_server.port, "abci_query",
                        {"data": "gossip"})
                    return base64.b64decode(
                        q["result"]["response"]["value"]) == b"works"

                async def waiter():
                    while not await committed():
                        await asyncio.sleep(0.05)
                await asyncio.wait_for(waiter(), 30)
            finally:
                await n1.stop()
                await n0.stop()
        run(go())


class TestCLI:
    def test_init_version_shownodeid(self, tmp_path):
        from cometbft_tpu.cmd.__main__ import main
        home = str(tmp_path / "clihome")
        assert main(["--home", home, "init",
                     "--chain-id", "cli-chain"]) == 0
        assert main(["--home", home, "show-node-id"]) == 0
        assert main(["--home", home, "show-validator"]) == 0
        assert main(["--home", home, "version"]) == 0
        import os
        assert os.path.exists(home + "/config/genesis.json")
        assert os.path.exists(home + "/config/node_key.json")
        assert os.path.exists(home + "/config/priv_validator_key.json")

    def test_testnet_generator(self, tmp_path):
        from cometbft_tpu.cmd.__main__ import main
        out = str(tmp_path / "net")
        assert main(["testnet", "--v", "3", "--o", out,
                     "--chain-id", "gen-chain"]) == 0
        import os
        for i in range(3):
            assert os.path.exists(f"{out}/node{i}/config/genesis.json")
            assert os.path.exists(f"{out}/node{i}/config/config.json")
        with open(f"{out}/node0/config/config.json") as f:
            cfg = json.load(f)
        assert cfg["p2p"]["persistent_peers"].count("@") == 2


class TestFilePV:
    def test_double_sign_protection(self, tmp_path):
        from cometbft_tpu.privval import DoubleSignError, FilePV
        from cometbft_tpu.types import canonical
        from cometbft_tpu.types.block_id import BlockID
        from cometbft_tpu.types.part_set import PartSetHeader
        from cometbft_tpu.types.timestamp import Timestamp
        from cometbft_tpu.types.vote import Vote

        pv = FilePV.generate(str(tmp_path / "key.json"),
                             str(tmp_path / "state.json"))
        bid = BlockID(hash=b"\x01" * 32,
                      part_set_header=PartSetHeader(1, b"\x02" * 32))
        bid2 = BlockID(hash=b"\x03" * 32,
                       part_set_header=PartSetHeader(1, b"\x04" * 32))
        addr = pv.get_pub_key().address()
        v1 = Vote(type=canonical.PREVOTE_TYPE, height=5, round=0,
                  block_id=bid, timestamp=Timestamp(1700000000, 0),
                  validator_address=addr, validator_index=0)
        pv.sign_vote("c", v1, sign_extension=False)
        # same HRS, same data: signature reused
        v1b = Vote(type=canonical.PREVOTE_TYPE, height=5, round=0,
                   block_id=bid, timestamp=Timestamp(1700000000, 0),
                   validator_address=addr, validator_index=0)
        pv.sign_vote("c", v1b, sign_extension=False)
        assert v1b.signature == v1.signature
        # same HRS, different timestamp: old timestamp + sig reused
        v1c = Vote(type=canonical.PREVOTE_TYPE, height=5, round=0,
                   block_id=bid, timestamp=Timestamp(1700000099, 0),
                   validator_address=addr, validator_index=0)
        pv.sign_vote("c", v1c, sign_extension=False)
        assert v1c.signature == v1.signature
        assert v1c.timestamp == Timestamp(1700000000, 0)
        # same HRS, different block: DOUBLE SIGN refused
        v2 = Vote(type=canonical.PREVOTE_TYPE, height=5, round=0,
                  block_id=bid2, timestamp=Timestamp(1700000000, 0),
                  validator_address=addr, validator_index=0)
        with pytest.raises(DoubleSignError):
            pv.sign_vote("c", v2, sign_extension=False)
        # height regression refused even across a reload
        pv2 = FilePV.load(str(tmp_path / "key.json"),
                          str(tmp_path / "state.json"))
        v0 = Vote(type=canonical.PREVOTE_TYPE, height=4, round=0,
                  block_id=bid, timestamp=Timestamp(1700000000, 0),
                  validator_address=addr, validator_index=0)
        with pytest.raises(DoubleSignError):
            pv2.sign_vote("c", v0, sign_extension=False)
