"""The committed state tree (cometbft_tpu/statetree/): versioned
reads, existence + non-inclusion proofs and their tamper matrix,
height pruning with cache pins, crash/restart root recovery, and
byte-identical statesync restore (docs/state_tree.md)."""
import json

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.kvstore import KVStoreApplication, _zigzag_varint
from cometbft_tpu.crypto import merkle
from cometbft_tpu.db import MemDB, SQLiteDB
from cometbft_tpu.statetree import (
    StateTree, build_proof_envelope, verify_proof_envelope,
)

from tests.test_abci import _drive_blocks, run


def _tree(db=None) -> StateTree:
    return StateTree(db if db is not None else MemDB())


def _commit_pairs(tree, version, pairs):
    for k, v in pairs:
        tree.set(k, v)
    return tree.commit(version)


# ---------------------------------------------------------------------------
# versioned reads / commit discipline


class TestVersionedTree:
    def test_versioned_reads_and_roots(self):
        t = _tree()
        r1 = _commit_pairs(t, 1, [(b"a", b"1"), (b"c", b"3")])
        t.set(b"a", b"1.1")
        t.set(b"b", b"2")
        r2 = t.commit(2)
        t.delete(b"c")
        r3 = t.commit(3)
        assert len({r1, r2, r3}) == 3
        # point reads at each version
        assert t.get(b"a", 1) == b"1"
        assert t.get(b"a", 2) == b"1.1"
        assert t.get(b"b", 1) is None
        assert t.get(b"b", 2) == b"2"
        assert t.get(b"c", 2) == b"3"
        assert t.get(b"c", 3) is None
        assert t.get(b"a") == b"1.1"          # latest
        # materialized views agree with point reads
        assert t.pairs(1) == [(b"a", b"1"), (b"c", b"3")]
        assert t.pairs(3) == [(b"a", b"1.1"), (b"b", b"2")]
        assert t.total(1) == 2 and t.total(3) == 2
        assert t.root(1) == r1 and t.root(3) == r3

    def test_working_root_is_the_commit_root(self):
        t = _tree()
        _commit_pairs(t, 1, [(b"k", b"v")])
        t.set(b"k2", b"v2")
        wr = t.working_root(2)
        # working root is a preview: committed state unchanged
        assert t.get(b"k2") is None
        assert t.commit(2) == wr
        assert t.get(b"k2") == b"v2"

    def test_reset_working_drops_staged_writes(self):
        t = _tree()
        r1 = _commit_pairs(t, 1, [(b"k", b"v")])
        t.set(b"junk", b"x")
        t.reset_working()
        # nothing staged: version 2 commits the same state as 1
        assert t.commit(2) == r1
        assert t.get(b"junk") is None

    def test_commit_discipline(self):
        t = _tree()
        r1 = _commit_pairs(t, 1, [(b"k", b"v")])
        # identical re-commit of the latest version is a no-op
        # (InitChain replay after a crash before height 1)
        assert t.commit(1) == r1
        # conflicting re-commit is an error
        t.set(b"k", b"other")
        with pytest.raises(ValueError, match="conflicting"):
            t.commit(1)
        t.reset_working()
        # non-monotonic commit is an error
        t.set(b"x", b"y")
        with pytest.raises(ValueError, match="<= latest"):
            t.commit(0)

    def test_empty_key_rejected(self):
        with pytest.raises(ValueError):
            _tree().set(b"", b"v")

    def test_deterministic_across_instances(self):
        """Same pairs, any insertion order -> same root (sorted-kv
        commitment)."""
        pairs = [(b"k%02d" % i, b"v%d" % i) for i in range(40)]
        a = _commit_pairs(_tree(), 1, pairs)
        b = _commit_pairs(_tree(), 1, list(reversed(pairs)))
        assert a == b

    def test_reopen_recovers_exact_root(self, tmp_path):
        """Crash/restart: a new StateTree over the same db recovers
        the exact latest root, version, and per-version reads."""
        db = SQLiteDB(str(tmp_path / "t.db"))
        t = StateTree(db)
        _commit_pairs(t, 1, [(b"a", b"1"), (b"b", b"2")])
        t.set(b"a", b"1.1")
        t.delete(b"b")
        r2 = t.commit(2, extra={"size": 3})

        t2 = StateTree(db)
        assert t2.latest_version == 2
        assert t2.root() == r2
        assert t2.root(1) == t.root(1)
        assert t2.get(b"a") == b"1.1"
        assert t2.get(b"b", 1) == b"2" and t2.get(b"b") is None
        assert t2.version_extra() == {"size": 3}
        # proofs from the reopened tree verify against the old root
        env = t2.prove([b"a", b"b"], 2)
        verify_proof_envelope(env, present=[(b"a", b"1.1")],
                              absent=[b"b"], expected_root=r2)


# ---------------------------------------------------------------------------
# proof envelopes: existence + non-inclusion, and the tamper matrix


def _proof_tree():
    t = _tree()
    pairs = [(b"k%02d" % i, b"v%d" % i) for i in range(0, 20, 2)]
    root = _commit_pairs(t, 1, pairs)
    return t, dict(pairs), root


class TestProofEnvelope:
    def test_present_and_absent_verify(self):
        t, pairs, root = _proof_tree()
        env = t.prove([b"k04", b"k09", b"zzz", b"aaa"], 1)
        verify_proof_envelope(
            env, present=[(b"k04", pairs[b"k04"])],
            absent=[b"k09", b"zzz", b"aaa"], expected_root=root)
        assert env["header_height"] == "2"
        assert sorted(env["missing"]) == sorted(
            [b"k09".hex(), b"zzz".hex(), b"aaa".hex()])
        # envelopes are JSON-stable (the RPC wire format)
        rt = json.loads(json.dumps(env))
        verify_proof_envelope(rt, present=[(b"k04", pairs[b"k04"])],
                              absent=[b"k09"], expected_root=root)

    def test_empty_tree_absence(self):
        t = _tree()
        env = t.prove([b"anything"])
        verify_proof_envelope(env, absent=[b"anything"],
                              expected_root=merkle.empty_hash())
        # the same claim against a non-empty tree is rejected
        t2, _, root2 = _proof_tree()
        env2 = t2.prove([b"zzz"], 1)
        arm = env2["absent"][0]
        arm["left"] = arm["right"] = None
        with pytest.raises(ValueError, match="empty-tree"):
            verify_proof_envelope(env2, absent=[b"zzz"],
                                  expected_root=root2)

    def test_stale_version_proof_rejected(self):
        """A proof from version 1 — internally consistent — must not
        verify against version 2's root (the newer header's
        app_hash)."""
        t, pairs, root1 = _proof_tree()
        t.set(b"k04", b"mutated")
        root2 = t.commit(2)
        env_old = t.prove([b"k04"], 1)
        verify_proof_envelope(env_old,
                              present=[(b"k04", pairs[b"k04"])],
                              expected_root=root1)
        with pytest.raises(ValueError, match="stale version|forged"):
            verify_proof_envelope(env_old,
                                  present=[(b"k04", pairs[b"k04"])],
                                  expected_root=root2)

    def test_neighbor_swap_forgery_rejected(self):
        """Rewriting an absence arm onto a DIFFERENT adjacent proven
        pair (which does not straddle the key) must fail."""
        t, pairs, root = _proof_tree()
        # k05 is absent between k04 (idx 2) and k06 (idx 3); also
        # prove k00/k02 so the forged arm can reference proven leaves
        env = t.prove([b"k00", b"k02", b"k05"], 1)
        arm = next(a for a in env["absent"])
        assert (arm["left"], arm["right"]) == (2, 3)
        arm["left"], arm["right"] = 0, 1       # adjacent, wrong gap
        with pytest.raises(ValueError, match="neighbor-swap"):
            verify_proof_envelope(env, absent=[b"k05"],
                                  expected_root=root)

    def test_range_gap_forgery_rejected(self):
        """An arm claiming two NON-adjacent leaves as neighbors would
        hide every key committed between them."""
        t, pairs, root = _proof_tree()
        env = t.prove([b"k00", b"k05"], 1)
        arm = env["absent"][0]
        arm["left"], arm["right"] = 0, 3       # skips leaves 1,2
        with pytest.raises(ValueError, match="range-gap"):
            verify_proof_envelope(env, absent=[b"k05"],
                                  expected_root=root)

    def test_arm_referencing_unproven_leaf_rejected(self):
        t, pairs, root = _proof_tree()
        env = t.prove([b"k05"], 1)
        env["absent"][0]["left"], env["absent"][0]["right"] = 5, 6
        with pytest.raises(ValueError, match="unproven leaf"):
            verify_proof_envelope(env, absent=[b"k05"],
                                  expected_root=root)

    def test_edge_absences(self):
        t, pairs, root = _proof_tree()
        env = t.prove([b"a-first", b"zzz"], 1)
        verify_proof_envelope(env, absent=[b"a-first", b"zzz"],
                              expected_root=root)
        # left-edge arm must anchor at leaf 0
        bad = t.prove([b"a-first", b"k02"], 1)
        bad["absent"][0]["right"] = 1
        with pytest.raises(ValueError, match="left-edge"):
            verify_proof_envelope(bad, absent=[b"a-first"],
                                  expected_root=root)
        # right-edge arm must anchor at the last leaf
        bad2 = t.prove([b"zzz", b"k16"], 1)
        bad2["absent"][0]["left"] = 8
        with pytest.raises(ValueError, match="right-edge"):
            verify_proof_envelope(bad2, absent=[b"zzz"],
                                  expected_root=root)

    def test_value_and_root_tamper_rejected(self):
        t, pairs, root = _proof_tree()
        env = t.prove([b"k04"], 1)
        forged = json.loads(json.dumps(env))
        forged["values"][0] = b"forged".hex()
        with pytest.raises(ValueError):
            verify_proof_envelope(forged,
                                  present=[(b"k04", b"forged")],
                                  expected_root=root)
        forged2 = json.loads(json.dumps(env))
        forged2["root"] = "00" * 32
        with pytest.raises(ValueError):
            verify_proof_envelope(forged2,
                                  present=[(b"k04", pairs[b"k04"])],
                                  expected_root=root)

    def test_claims_must_be_covered(self):
        t, pairs, root = _proof_tree()
        env = t.prove([b"k04"], 1)
        with pytest.raises(ValueError, match="not covered"):
            verify_proof_envelope(env, present=[(b"k06", b"v6")],
                                  expected_root=root)
        with pytest.raises(ValueError, match="no non-inclusion arm"):
            verify_proof_envelope(env, absent=[b"k05"],
                                  expected_root=root)
        with pytest.raises(ValueError, match="value mismatch"):
            verify_proof_envelope(env, present=[(b"k04", b"wrong")],
                                  expected_root=root)
        # a key proven present cannot be claimed absent
        env2 = t.prove([b"k04", b"k05"], 1)
        with pytest.raises(ValueError, match="claimed absent"):
            verify_proof_envelope(env2, absent=[b"k04"],
                                  expected_root=root)

    def test_unsorted_leaves_rejected(self):
        """A forged envelope whose proven keys are out of order cannot
        make adjacency claims."""
        keys = [b"a", b"b"]
        values = [b"1", b"2"]
        # swap the leaves but keep a consistent multiproof over them
        leaves = [merkle.value_op_leaf(k, v)
                  for k, v in zip(keys, values)]
        hashes = [merkle.leaf_hash(item) for item in leaves]
        env = build_proof_envelope(
            [b"a", b"b"], keys, values, hashes,
            {b"a": 0, b"b": 1}, 1)
        env["keys"] = [b"b".hex(), b"a".hex()]
        env["values"] = [b"2".hex(), b"1".hex()]
        with pytest.raises(ValueError):
            verify_proof_envelope(
                env, present=[(b"a", b"1")],
                expected_root=bytes.fromhex(env["root"]))


# ---------------------------------------------------------------------------
# pruning: retention + cache pins


class TestPruning:
    def _tree_5_versions(self):
        t = _tree()
        for v in range(1, 6):
            t.set(b"hot", b"v%d" % v)
            t.set(b"k%d" % v, b"x")
            t.commit(v)
        return t

    def test_prune_keeps_retained_and_pinned(self):
        t = self._tree_5_versions()
        roots = {v: t.root(v) for v in range(1, 6)}
        pins = {2}
        dropped = t.prune(4, pinned=pins)
        assert dropped == 2                       # versions 1 and 3
        assert t.base_version == 2
        assert sorted(t.versions()) == [2, 4, 5]
        # retained + pinned versions materialize the exact same state
        assert t.get(b"hot", 2) == b"v2"
        assert t.get(b"hot", 4) == b"v4"
        assert t.pairs(2) == [(b"hot", b"v2"), (b"k1", b"x"),
                              (b"k2", b"x")]
        # ... and still prove against their original roots: pruning
        # never breaks a cached-height proof (the ISSUE invariant)
        for v in (2, 4, 5):
            env = t.prove([b"hot", b"absent"], v)
            verify_proof_envelope(env, present=[(b"hot", b"v%d" % v)],
                                  absent=[b"absent"],
                                  expected_root=roots[v])
        # dropped versions are gone
        with pytest.raises(KeyError):
            t.prove([b"hot"], 3)
        assert t.get(b"hot", 1) is None

    def test_prune_survives_reopen(self, tmp_path):
        db = SQLiteDB(str(tmp_path / "t.db"))
        t = StateTree(db)
        for v in range(1, 4):
            t.set(b"k", b"v%d" % v)
            t.commit(v)
        r3 = t.root(3)
        t.prune(3)
        t2 = StateTree(db)
        assert t2.base_version == 3 and t2.root() == r3
        assert t2.get(b"k") == b"v3"

    def test_prune_everything_below_tip(self):
        t = self._tree_5_versions()
        r5 = t.root(5)
        assert t.prune(10) == 4                   # clamped to latest
        assert t.versions() == [5] and t.root() == r5
        env = t.prove([b"hot"], 5)
        verify_proof_envelope(env, present=[(b"hot", b"v5")],
                              expected_root=r5)

    def test_kvstore_retain_blocks_pins_cached_heights(self):
        """The app prunes on retain_blocks but must keep any version
        the lightserve ResponseCache still serves (node.py wires
        version_pin = cache.heights)."""
        from cometbft_tpu.lightserve.cache import ResponseCache
        app = KVStoreApplication()
        app.retain_blocks = 2
        cache = ResponseCache(max_bytes=1 << 20)
        app.version_pin = cache.heights

        async def go():
            await _drive_blocks(app, [[b"a=1"]])
            root1 = app.tree.root(1)
            cache.put("abci_query_batch", 1, (), {"cached": True},
                      latest_height=99)
            await _drive_blocks(
                app, [[b"b=2"], [b"c=3"], [b"d=4"], [b"e=5"]],
                start_height=2)
            # at height 5 the horizon is retain_height=4; the app
            # keeps version 3 (the replay base) and up, plus pins
            assert sorted(app.tree.versions()) == [1, 3, 4, 5]
            # version 1 outlived the horizon only via the cache pin —
            # and is still fully provable
            env = app.tree.prove([b"a", b"zz"], 1)
            verify_proof_envelope(env, present=[(b"a", b"1")],
                                  absent=[b"zz"], expected_root=root1)
        run(go())


# ---------------------------------------------------------------------------
# kvstore integration: versioned queries, restart, statesync restore


class TestKVStoreStateTree:
    def test_historical_queries(self):
        app = KVStoreApplication()

        async def go():
            await _drive_blocks(app, [[b"a=1"], [b"a=2", b"b=9"]])
            q1 = await app.query(abci.QueryRequest(data=b"a",
                                                   height=1))
            assert q1.value == b"1" and q1.height == 1
            q2 = await app.query(abci.QueryRequest(data=b"a"))
            assert q2.value == b"2"
            qb = await app.query(abci.QueryRequest(data=b"b",
                                                   height=1))
            assert qb.log == "does not exist"
            # unservable heights answer with a coded error, not junk
            for h in (7, -3):
                qe = await app.query(abci.QueryRequest(data=b"a",
                                                       height=h))
                assert qe.code != 0 and qe.log
        run(go())

    def test_multistore_envelope_historical(self):
        app = KVStoreApplication()

        async def go():
            await _drive_blocks(app, [[b"a=1"], [b"a=2"]])
            req = json.dumps(
                {"keys": [b"a".hex(), b"gone".hex()]}).encode()
            res = await app.query(abci.QueryRequest(
                path="/multistore", data=req, height=1))
            assert res.code == 0
            env = json.loads(res.value)
            assert env["version"] == "1" and res.height == 1
            verify_proof_envelope(env, present=[(b"a", b"1")],
                                  absent=[b"gone"],
                                  expected_root=app.tree.root(1))
            bad = await app.query(abci.QueryRequest(
                path="/multistore", data=b"not json", height=0))
            assert bad.code != 0
        run(go())

    def test_restart_recovers_root_and_size(self, tmp_path):
        db = SQLiteDB(str(tmp_path / "kv.db"))
        app = KVStoreApplication(db=db)

        async def go():
            await _drive_blocks(app, [[b"k=v"], [b"k2=v2"]])
        run(go())
        expected = app.tree.root(2)
        app2 = KVStoreApplication(db=db)

        async def go2():
            info = await app2.info(abci.InfoRequest())
            assert info.last_block_height == 2
            assert info.last_block_app_hash == expected
            assert json.loads(info.data)["size"] == 2
            # historical state survives the restart
            q = await app2.query(abci.QueryRequest(data=b"k2",
                                                   height=1))
            assert q.log == "does not exist"
        run(go2())

    def test_statesync_restore_reproduces_identical_root(self):
        """The acceptance test for snapshot restore: the consumer's
        tree root is byte-identical to the producer's, so the restored
        node reports the same app_hash and serves verifying proofs."""
        producer = KVStoreApplication(snapshot_interval=2)

        async def go():
            await _drive_blocks(
                producer, [[b"a=1", b"b=2"], [b"c=3", b"a=9"]])
            snaps = await producer.list_snapshots(
                abci.ListSnapshotsRequest())
            assert [s.height for s in snaps.snapshots] == [2]
            snap = snaps.snapshots[0]

            consumer = KVStoreApplication()
            offer = await consumer.offer_snapshot(
                abci.OfferSnapshotRequest(snapshot=snap))
            assert offer.result == \
                abci.OFFER_SNAPSHOT_RESULT_ACCEPT
            chunk = await producer.load_snapshot_chunk(
                abci.LoadSnapshotChunkRequest(height=2, format=1,
                                              chunk=0))
            applied = await consumer.apply_snapshot_chunk(
                abci.ApplySnapshotChunkRequest(index=0,
                                               chunk=chunk.chunk))
            assert applied.result == \
                abci.APPLY_SNAPSHOT_CHUNK_RESULT_ACCEPT

            assert consumer.tree.root(2) == producer.tree.root(2)
            info = await consumer.info(abci.InfoRequest())
            assert info.last_block_height == 2
            assert info.last_block_app_hash == producer.tree.root(2)
            env = consumer.tree.prove([b"a", b"zz"], 2)
            verify_proof_envelope(env, present=[(b"a", b"9")],
                                  absent=[b"zz"],
                                  expected_root=producer.tree.root(2))
            # a corrupted chunk is rejected, state untouched
            bad = await consumer.apply_snapshot_chunk(
                abci.ApplySnapshotChunkRequest(index=0,
                                               chunk=b"garbage"))
            assert bad.result == \
                abci.APPLY_SNAPSHOT_CHUNK_RESULT_REJECT_SNAPSHOT
        run(go())

    def test_legacy_store_migration(self):
        """A pre-tree db (raw kvPairKey: rows + appstate JSON) imports
        into the tree at its height under the LEGACY app hash, so
        handshake replay of the already-finalized height still
        matches; the next height reports the tree root."""
        db = MemDB()
        db.set(b"kvPairKey:old", b"value")
        db.set(b"appstate",
               json.dumps({"height": 3, "size": 4}).encode())
        app = KVStoreApplication(db=db)
        assert app._height == 3 and app._size == 4
        assert app._app_hash() == _zigzag_varint(4)
        assert app.tree.get(b"old") == b"value"
        assert db.get(b"kvPairKey:old") is None    # legacy rows gone

        async def go():
            r = await _drive_blocks(app, [[b"new=1"]],
                                    start_height=4)
            # after the migrated height the app reports tree roots
            assert r[0].app_hash == app.tree.root(4)
            assert len(app._app_hash()) == 32
            assert app._app_hash() == app.tree.root(4)
            q = await app.query(abci.QueryRequest(data=b"old"))
            assert q.value == b"value"
        run(go())
