"""Manifest-driven e2e runner (reference: test/e2e/pkg/manifest.go,
test/e2e/generator, test/e2e/runner)."""
import asyncio
import os
import tempfile

from cometbft_tpu.crypto import batch as crypto_batch
import pytest

pytestmark = pytest.mark.slow


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


class TestManifest:
    def test_roundtrip_and_generate(self):
        from cometbft_tpu.tools.manifest import (
            Manifest, ManifestNode, generate,
        )

        m = Manifest(chain_id="x", nodes={
            "validator00": ManifestNode(mode="validator",
                                        perturb=["kill"]),
            "full00": ManifestNode(mode="full", start_at=3),
        }, validators={"validator00": 100})
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "manifest.json")
            m.save(p)
            m2 = Manifest.load(p)
        assert m2.nodes["full00"].start_at == 3
        assert m2.nodes["validator00"].perturb == ["kill"]

        # the generator is deterministic per seed and samples the space
        g1, g2 = generate(seed=7), generate(seed=7)
        assert g1.to_dict() == g2.to_dict()
        assert any(generate(seed=s).abci_protocol == "builtin_unsync"
                   for s in range(8))
        vals = [n for n in generate(seed=3).nodes.values()
                if n.mode == "validator"]
        assert len(vals) >= 2

    def test_run_manifest_with_perturbation_and_late_joiner(self):
        """Full e2e: 3 validators + a late-joining full node, tx load,
        one validator killed and restarted mid-run; all nodes converge
        on identical blocks (reference: runner stage order +
        tests/block_test.go invariant)."""
        from cometbft_tpu.tools.manifest import (
            Manifest, ManifestNode, run_manifest,
        )

        m = Manifest(chain_id="runner-net", load_tx_rate=20,
                     load_tx_size=128)
        for i in range(3):
            m.nodes[f"validator{i:02d}"] = ManifestNode(
                mode="validator")
            m.validators[f"validator{i:02d}"] = 100
        m.nodes["validator02"].perturb = ["kill"]
        m.nodes["full00"] = ManifestNode(mode="full", start_at=3)

        async def run():
            with tempfile.TemporaryDirectory() as d:
                rep = await run_manifest(m, d, target_height=6,
                                         timeout_s=120.0)
                assert rep.perturbed == ["validator02:kill"]
                assert rep.load_accepted > 0
                assert all(h >= 6 for h in rep.heights.values()), \
                    rep.heights
                assert rep.mismatches == [], rep.mismatches
        asyncio.run(run())


class TestLatencyAndDelays:
    def test_two_zone_latency_slows_blocks_but_net_commits(self):
        """Two zones with 120 ms one-way latency: the net still
        commits, and block intervals reflect the emulated WAN
        (reference: latency_emulation.go zones)."""
        from cometbft_tpu.tools.manifest import (
            Manifest, ManifestNode, run_manifest,
        )

        def build(latency_ms):
            m = Manifest(chain_id="zones-net", load_tx_rate=10,
                         load_tx_size=128)
            for i in range(3):
                m.nodes[f"validator{i:02d}"] = ManifestNode(
                    mode="validator",
                    zone="zone-a" if i < 2 else "zone-b")
                m.validators[f"validator{i:02d}"] = 100
            if latency_ms:
                m.latency_ms["zone-a:zone-b"] = latency_ms
            return m

        async def run():
            import time

            async def timed(latency_ms):
                with tempfile.TemporaryDirectory() as d:
                    rep = await run_manifest(build(latency_ms), d,
                                             target_height=5,
                                             timeout_s=120.0)
                    assert all(h >= 5 for h in rep.heights.values())
                    assert rep.mismatches == []
                    # boot-to-target time, not load-drain time
                    return rep.reached_target_s

            fast = await timed(0)
            slow = await timed(120)
            # votes from the zone-b validator cross the 120 ms links,
            # so each height needs at least one WAN round trip — the
            # emulated-latency run must be measurably slower than the
            # identical zero-latency net
            assert slow > fast + 1.0, \
                f"latency had no effect (fast={fast:.1f}s, " \
                f"slow={slow:.1f}s)"
        asyncio.run(run())

    def test_abci_delay_knobs_reach_the_app(self):
        from cometbft_tpu.abci.kvstore import KVStoreApplication
        from cometbft_tpu.abci import types as abci

        async def run():
            import time

            app = KVStoreApplication()
            app.abci_delays = {"check_tx": 0.05}
            t0 = time.monotonic()
            await app.check_tx(abci.CheckTxRequest(tx=b"a=b",
                                                   type=0))
            assert time.monotonic() - t0 >= 0.05
        asyncio.run(run())


class TestEvidenceInjection:
    def test_injected_duplicate_vote_evidence_commits(self):
        """Forged duplicate-vote evidence broadcast over RPC is
        verified by peers, gossiped, and committed into a block; the
        app punishes the equivocator (reference: runner/evidence.go +
        tests/evidence_test.go)."""
        from cometbft_tpu.tools.manifest import (
            Manifest, ManifestNode, run_manifest,
        )

        m = Manifest(chain_id="evidence-net", load_tx_rate=10,
                     load_tx_size=128, evidence=2)
        for i in range(3):
            m.nodes[f"validator{i:02d}"] = ManifestNode(
                mode="validator")
            m.validators[f"validator{i:02d}"] = 100

        async def run():
            with tempfile.TemporaryDirectory() as d:
                rep = await run_manifest(m, d, target_height=8,
                                         timeout_s=120.0)
                assert len(rep.evidence_injected) == 2
                assert rep.evidence_committed >= 2, \
                    f"evidence never committed: {rep}"
                assert rep.mismatches == []
        asyncio.run(run())
