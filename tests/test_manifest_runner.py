"""Manifest-driven e2e runner (reference: test/e2e/pkg/manifest.go,
test/e2e/generator, test/e2e/runner)."""
import asyncio
import os
import tempfile

from cometbft_tpu.crypto import batch as crypto_batch
import pytest


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


class TestManifest:
    def test_roundtrip_and_generate(self):
        from cometbft_tpu.tools.manifest import (
            Manifest, ManifestNode, generate,
        )

        m = Manifest(chain_id="x", nodes={
            "validator00": ManifestNode(mode="validator",
                                        perturb=["kill"]),
            "full00": ManifestNode(mode="full", start_at=3),
        }, validators={"validator00": 100})
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "manifest.json")
            m.save(p)
            m2 = Manifest.load(p)
        assert m2.nodes["full00"].start_at == 3
        assert m2.nodes["validator00"].perturb == ["kill"]

        # the generator is deterministic per seed and samples the space
        g1, g2 = generate(seed=7), generate(seed=7)
        assert g1.to_dict() == g2.to_dict()
        assert any(generate(seed=s).abci_protocol == "builtin_unsync"
                   for s in range(8))
        vals = [n for n in generate(seed=3).nodes.values()
                if n.mode == "validator"]
        assert len(vals) >= 2

    def test_run_manifest_with_perturbation_and_late_joiner(self):
        """Full e2e: 3 validators + a late-joining full node, tx load,
        one validator killed and restarted mid-run; all nodes converge
        on identical blocks (reference: runner stage order +
        tests/block_test.go invariant)."""
        from cometbft_tpu.tools.manifest import (
            Manifest, ManifestNode, run_manifest,
        )

        m = Manifest(chain_id="runner-net", load_tx_rate=20,
                     load_tx_size=128)
        for i in range(3):
            m.nodes[f"validator{i:02d}"] = ManifestNode(
                mode="validator")
            m.validators[f"validator{i:02d}"] = 100
        m.nodes["validator02"].perturb = ["kill"]
        m.nodes["full00"] = ManifestNode(mode="full", start_at=3)

        async def run():
            with tempfile.TemporaryDirectory() as d:
                rep = await run_manifest(m, d, target_height=6,
                                         timeout_s=120.0)
                assert rep.perturbed == ["validator02:kill"]
                assert rep.load_accepted > 0
                assert all(h >= 6 for h in rep.heights.values()), \
                    rep.heights
                assert rep.mismatches == [], rep.mismatches
        asyncio.run(run())
