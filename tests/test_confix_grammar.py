"""Config tooling (confix) and the ABCI grammar checker.

Reference: internal/confix; test/e2e/pkg/grammar/checker.go.
"""
import asyncio
import json
import os
import tempfile

import pytest


class TestConfix:
    def _write(self, home, overrides):
        os.makedirs(os.path.join(home, "config"), exist_ok=True)
        with open(os.path.join(home, "config", "config.json"),
                  "w") as f:
            json.dump(overrides, f)

    def test_migrate_renames_durations_and_drops(self):
        from cometbft_tpu import confix

        with tempfile.TemporaryDirectory() as home:
            self._write(home, {
                "base": {"fast_sync": False, "log_level": "debug"},
                "consensus": {"timeout_propose": "3s",
                              "timeout_prevote": "500ms"},
                "mempool": {"version": "v1", "size": 2000},
                "junk": {"x": 1},
            })
            log = confix.migrate(home)
            assert any("renamed base.fast_sync" in line
                       for line in log)
            assert any("dropped mempool.version" in line
                       for line in log)
            assert any("dropped junk.x" in line for line in log)
            cfg = confix.effective_config(home)
            assert cfg.blocksync.enable is False
            assert cfg.consensus.timeout_propose_ns == 3_000_000_000
            assert cfg.consensus.timeout_vote_ns == 500_000_000
            assert cfg.mempool.size == 2000
            # idempotent
            assert confix.migrate(home) == []

    def test_dry_run_leaves_file_untouched(self):
        from cometbft_tpu import confix

        with tempfile.TemporaryDirectory() as home:
            self._write(home, {"mempool": {"version": "v1"}})
            before = confix.load_overrides(home)
            log = confix.migrate(home, dry_run=True)
            assert log and confix.load_overrides(home) == before

    def test_get_set_diff(self):
        from cometbft_tpu import confix

        with tempfile.TemporaryDirectory() as home:
            confix.set_value(home, "mempool.size", "7000")
            confix.set_value(home, "consensus.timeout_propose_ns",
                             "2s")
            assert confix.get_value(home, "mempool.size") == 7000
            assert confix.get_value(
                home, "consensus.timeout_propose_ns") == 2_000_000_000
            d = confix.diff_from_defaults(home)
            assert d["mempool"]["size"]["status"] == "changed"
            with pytest.raises(KeyError):
                confix.set_value(home, "mempool.nope", "1")
            with pytest.raises(KeyError):
                confix.get_value(home, "nope.size")


class TestGrammarChecker:
    def _check(self, trace, **kw):
        from cometbft_tpu.abci.grammar import GrammarChecker
        return GrammarChecker().verify(trace, **kw)

    def test_valid_traces(self):
        # clean start, two heights, round calls interleaved
        assert self._check([
            "init_chain",
            "prepare_proposal", "process_proposal",
            "finalize_block", "commit",
            "process_proposal", "extend_vote",
            "verify_vote_extension",
            "finalize_block", "commit",
        ])
        # state-sync start: attempts then success
        assert self._check([
            "offer_snapshot",                       # failed attempt
            "offer_snapshot", "apply_snapshot_chunk",
            "apply_snapshot_chunk",                 # success
            "finalize_block", "commit",
        ])
        # recovery without init_chain
        assert self._check(["finalize_block", "commit"],
                           clean_start=False)
        # non-grammar calls are ignored
        assert self._check(["info", "init_chain", "check_tx",
                            "finalize_block", "echo", "commit"])

    def test_violations(self):
        from cometbft_tpu.abci.grammar import GrammarError

        cases = [
            # consensus before handshake on clean start
            ["finalize_block", "commit"],
            # commit without finalize
            ["init_chain", "commit"],
            # round call between finalize and commit
            ["init_chain", "finalize_block", "prepare_proposal",
             "commit"],
            # init_chain mid-stream
            ["init_chain", "finalize_block", "commit", "init_chain"],
            # statesync after consensus
            ["init_chain", "finalize_block", "commit",
             "offer_snapshot"],
            # last snapshot attempt applied no chunks
            ["offer_snapshot", "finalize_block", "commit"],
            # chunk without offer
            ["init_chain", "apply_snapshot_chunk"],
            # ends mid-height
            ["init_chain", "finalize_block"],
            # no height at all
            ["init_chain"],
        ]
        for trace in cases:
            with pytest.raises(GrammarError):
                self._check(trace)

    def test_live_node_trace_is_grammatical(self):
        """A real node run (handshake -> consensus heights with txs)
        produces a trace the checker accepts."""
        from cometbft_tpu.abci.grammar import GrammarChecker
        from cometbft_tpu.config import Config
        from cometbft_tpu.node.node import Node
        from cometbft_tpu.p2p.key import NodeKey
        from cometbft_tpu.privval import FilePV
        from cometbft_tpu.rpc.client import HTTPClient
        from cometbft_tpu.types.genesis import (
            GenesisDoc, GenesisValidator,
        )
        from cometbft_tpu.types.timestamp import Timestamp

        async def run():
            with tempfile.TemporaryDirectory() as d:
                home = os.path.join(d, "node")
                cfg = Config()
                cfg.base.home = home
                cfg.base.abci_grammar_trace = True
                cfg.p2p.laddr = "tcp://127.0.0.1:0"
                cfg.rpc.laddr = "tcp://127.0.0.1:0"
                cfg.consensus.timeout_commit_ns = 20_000_000
                os.makedirs(os.path.join(home, "config"),
                            exist_ok=True)
                os.makedirs(os.path.join(home, "data"), exist_ok=True)
                pv = FilePV.generate(
                    cfg.base.path(cfg.base.priv_validator_key_file),
                    cfg.base.path(cfg.base.priv_validator_state_file))
                NodeKey.load_or_gen(
                    cfg.base.path(cfg.base.node_key_file))
                GenesisDoc(
                    chain_id="grammar-chain",
                    genesis_time=Timestamp.now(),
                    validators=[GenesisValidator(
                        address=b"", pub_key=pv.get_pub_key(),
                        power=10)],
                ).save_as(cfg.base.path(cfg.base.genesis_file))
                node = Node(cfg)
                await node.start()
                try:
                    cli = HTTPClient(
                        f"http://{node._rpc_server.listen_addr}",
                        timeout=30.0)
                    res = await cli.broadcast_tx_commit(b"g=1")
                    assert res["tx_result"]["code"] == 0
                    for _ in range(200):
                        if node.height >= 4:
                            break
                        await asyncio.sleep(0.02)
                finally:
                    await node.stop()
                trace = list(node.abci_trace)
                assert "finalize_block" in trace
                assert "prepare_proposal" in trace
                GrammarChecker().verify(trace)
        asyncio.run(run())


class TestConfixConflicts:
    def test_explicit_key_beats_legacy_alias_any_order(self):
        import json

        from cometbft_tpu import confix

        for order in (("timeout_prevote", "timeout_vote_ns"),
                      ("timeout_vote_ns", "timeout_prevote")):
            with tempfile.TemporaryDirectory() as home:
                os.makedirs(os.path.join(home, "config"))
                vals = {"timeout_prevote": "500ms",
                        "timeout_vote_ns": 2_000_000_000}
                with open(os.path.join(home, "config",
                                       "config.json"), "w") as f:
                    json.dump({"consensus": {k: vals[k]
                                             for k in order}}, f)
                log = confix.migrate(home)
                assert confix.get_value(
                    home, "consensus.timeout_vote_ns") == \
                    2_000_000_000, (order, log)
                assert any("conflict" in line for line in log)


class TestConfixSetValidation:
    def test_set_rejects_values_the_node_would_refuse(self):
        from cometbft_tpu import confix

        with tempfile.TemporaryDirectory() as home:
            for key, raw in [("tx_index.indexer", "bogus"),
                             ("mempool.size", '"abc"'),
                             ("rpc.max_body_bytes", "-5")]:
                with pytest.raises(ValueError):
                    confix.set_value(home, key, raw)
            confix.set_value(home, "mempool.size", "123")
            assert confix.get_value(home, "mempool.size") == 123

    def test_null_section_tolerated(self):
        import json

        from cometbft_tpu import confix

        with tempfile.TemporaryDirectory() as home:
            os.makedirs(os.path.join(home, "config"))
            with open(os.path.join(home, "config",
                                   "config.json"), "w") as f:
                json.dump({"base": None}, f)
            cfg = confix.effective_config(home)
            assert cfg.mempool.size == 5000
