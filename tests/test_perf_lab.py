"""Perf lab: the regression-gated microbenchmark suite
(tools/perf_lab.py + the committed perf_baseline.json).

Tier-1 runs the fast subset against the committed baseline so a perf
regression in a hot primitive fails CI, and proves the gate actually
trips by injecting a slowed path.
"""
import importlib.util
import json
import os

import pytest

_ROOT = os.path.join(os.path.dirname(__file__), "..")
_BASELINE = os.path.join(_ROOT, "perf_baseline.json")


def _load_perf_lab():
    spec = importlib.util.spec_from_file_location(
        "perf_lab", os.path.join(_ROOT, "tools", "perf_lab.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestBaselineFile:
    def test_committed_baseline_is_valid(self):
        pl = _load_perf_lab()
        base = pl.load_baseline(_BASELINE)
        assert base["schema"] == pl.SCHEMA
        assert base["benchmarks"], "baseline has no benchmarks"
        for name, b in base["benchmarks"].items():
            assert name in pl.BENCHMARKS, \
                f"baseline names unknown benchmark {name!r}"
            assert b["min_ms"] > 0
        # every benchmark in the suite is gated
        missing = set(pl.BENCHMARKS) - set(base["benchmarks"])
        assert not missing, \
            f"benchmarks not in baseline (rebaseline): {missing}"

    def test_fast_subset_covers_tier1_surfaces(self):
        pl = _load_perf_lab()
        fast = {n for n, (_, in_fast) in pl.BENCHMARKS.items()
                if in_fast}
        # the tier-1 gate must cover the verify, hash, encode,
        # observability-overhead and p2p surfaces
        for want in ("batch_verify_cpu_pad64", "merkle_root_1024",
                     "vote_sign_bytes", "signature_cache_hit",
                     "metrics_observe", "tracing_disabled_span",
                     "p2p_loopback_send"):
            assert want in fast


class TestRegressionGate:
    def test_check_fast_passes_against_committed_baseline(self):
        """The tier-1 perf gate: the fast subset on this container
        must be within tolerance of the committed baseline."""
        pl = _load_perf_lab()
        report = pl.run_suite(fast=True)
        ok, lines = pl.check_report(
            report, pl.load_baseline(_BASELINE))
        assert ok, "perf regression beyond tolerance:\n" + \
            "\n".join(lines)

    def test_injected_slow_path_fails_check(self):
        """The gate demonstrably trips: slow one benchmark past its
        tolerance and check must FAIL on exactly that benchmark."""
        import time

        pl = _load_perf_lab()
        base = pl.load_baseline(_BASELINE)
        tol = float(base["benchmarks"]["merkle_root_1024"].get(
            "tolerance", base["default_tolerance"]))
        slow_s = base["benchmarks"]["merkle_root_1024"]["min_ms"] \
            * tol * 2 / 1e3

        real_fn, in_fast = pl.BENCHMARKS["merkle_root_1024"]

        def slowed(fast):
            from cometbft_tpu.crypto.merkle import (
                hash_from_byte_slices,
            )
            leaves = [(b"%08d" % i) * 32 for i in range(1024)]

            def run():
                time.sleep(slow_s)          # the injected regression
                hash_from_byte_slices(leaves)
            return pl.measure(run, reps=2, warmup=0)

        pl.BENCHMARKS["merkle_root_1024"] = (slowed, in_fast)
        try:
            report = pl.run_suite(
                fast=True, only={"merkle_root_1024",
                                 "vote_sign_bytes"})
            ok, lines = pl.check_report(report, base)
        finally:
            pl.BENCHMARKS["merkle_root_1024"] = (real_fn, in_fast)
        assert not ok
        assert any("REGRESSED merkle_root_1024" in ln
                   for ln in lines), lines

    def test_missing_benchmark_fails_full_check(self):
        pl = _load_perf_lab()
        base = pl.load_baseline(_BASELINE)
        report = pl.run_suite(fast=True,
                              only={"tracing_disabled_span"})
        report["mode"] = "full"     # claim full coverage, deliver one
        report.pop("only")          # ...without declaring a subset
        ok, lines = pl.check_report(report, base)
        assert not ok
        assert any(ln.startswith("MISSING") for ln in lines)

    def test_only_subset_gates_only_what_ran(self):
        """`check --only X` must not fail on benchmarks it was told
        not to run."""
        pl = _load_perf_lab()
        base = pl.load_baseline(_BASELINE)
        report = pl.run_suite(fast=True,
                              only={"tracing_disabled_span"})
        ok, lines = pl.check_report(report, base)
        assert ok, lines
        assert not any(ln.startswith("MISSING") for ln in lines)


class TestReportShape:
    def test_report_json_is_stable_and_complete(self, tmp_path):
        pl = _load_perf_lab()
        report = pl.run_suite(fast=True,
                              only={"metrics_observe",
                                    "tracing_disabled_span"})
        assert report["schema"] == pl.SCHEMA
        for stats in report["benchmarks"].values():
            for k in ("p50_ms", "min_ms", "mean_ms", "reps",
                      "inner"):
                assert k in stats
        # rebaseline writes a loadable baseline preserving per-bench
        # tolerances
        out = tmp_path / "base.json"
        with open(out, "w") as f:
            json.dump({"schema": pl.SCHEMA, "default_tolerance": 6.0,
                       "benchmarks": {"metrics_observe": {
                           "min_ms": 1.0, "tolerance": 2.5}}}, f)
        new = pl.rebaseline(report, str(out))
        assert new["benchmarks"]["metrics_observe"]["tolerance"] \
            == 2.5
        reread = pl.load_baseline(str(out))
        assert set(reread["benchmarks"]) == set(report["benchmarks"])


@pytest.mark.slow
class TestFullSuite:
    def test_full_check_passes(self):
        """The full suite (incl. the pad-1024 batch shape) against
        the committed baseline — what perf PRs run before/after."""
        pl = _load_perf_lab()
        report = pl.run_suite(fast=False)
        ok, lines = pl.check_report(
            report, pl.load_baseline(_BASELINE))
        assert ok, "\n".join(lines)
