"""Event-driven gossip/sync loops must park when idle (VERDICT r3 #5:
no steady-state busy-poll under zero load) and wake promptly on work.

Reference: internal/clist/clist.go:95-104 (the blocking wait the
mempool gossip routine rides) and internal/blocksync/pool.go's
channel-driven makeRequestersRoutine.
"""
import asyncio
import time

from cometbft_tpu.abci.client import AppConns
from cometbft_tpu.abci.kvstore import DEFAULT_LANES, KVStoreApplication
from cometbft_tpu.config import MempoolConfig
from cometbft_tpu.mempool.mempool import CListMempool
from cometbft_tpu.mempool.reactor import MempoolReactor


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


class _StubPeer:
    id = "aa" * 20

    def __init__(self):
        self.sent = []

    def send(self, chan_id, payload) -> bool:
        self.sent.append(payload)
        return True


class TestMempoolGossipParks:
    def test_idle_gossip_does_not_poll(self):
        async def go():
            app = KVStoreApplication()
            conns = AppConns(app)
            mp = CListMempool(MempoolConfig(), conns.mempool,
                              lanes=DEFAULT_LANES,
                              default_lane="default")
            reactor = MempoolReactor(mp, MempoolConfig())
            peer = _StubPeer()
            await reactor.add_peer(peer)
            await mp.check_tx(b"a=1")
            await asyncio.sleep(0.1)
            assert len(peer.sent) == 1

            # instrument the park point, then hold the pool idle: the
            # routine must sit in wait_for_change, not rescan on a
            # timer (the r3 code woke every 20-50 ms)
            waits = 0
            orig = mp.wait_for_change

            async def counting(last_seq, timeout=1.0):
                nonlocal waits
                waits += 1
                await orig(last_seq, timeout)

            mp.wait_for_change = counting
            await asyncio.sleep(0.6)
            assert len(peer.sent) == 1      # nothing re-sent
            assert waits <= 2, f"gossip polled {waits}x while idle"

            # and a new append wakes it promptly (no 50 ms floor)
            t0 = time.monotonic()
            await mp.check_tx(b"b=2")
            for _ in range(50):
                if len(peer.sent) == 2:
                    break
                await asyncio.sleep(0.005)
            assert len(peer.sent) == 2
            assert time.monotonic() - t0 < 0.25
            await reactor.remove_peer(peer, "done")
        run(go())


class TestBlockPoolParks:
    def test_requester_loop_parks_when_idle(self):
        from cometbft_tpu.blocksync.pool import BlockPool

        async def go():
            pool = BlockPool(start_height=1,
                             send_request=lambda p, h: True,
                             ban_peer=lambda p, r: None)
            spins = 0
            orig = pool._spawn_requesters

            def counting():
                nonlocal spins
                spins += 1
                orig()

            pool._spawn_requesters = counting
            pool.start()
            await asyncio.sleep(0.8)
            # fallback tick is 250 ms -> a handful of iterations, not
            # the r3 code's 10 ms spin (80 iterations in this window)
            assert spins <= 6, f"requester loop spun {spins}x idle"
            # a peer arriving wakes it immediately
            before = spins
            pool.set_peer_range("bb" * 20, 1, 5)
            await asyncio.sleep(0.05)
            assert spins > before
            pool.stop()
        run(go())
