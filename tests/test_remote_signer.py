"""Remote signer: socket privval protocol, retry wrapper, double-sign
protection across the wire and across signer restarts.

Reference: privval/signer_listener_endpoint.go, signer_server.go,
signer_client.go, retry_signer_client.go.
"""
import asyncio
import os
import subprocess
import sys
import tempfile

import pytest

from cometbft_tpu.privval import FilePV
from cometbft_tpu.privval.file import DoubleSignError
from cometbft_tpu.privval.signer import (
    RetrySignerClient, SignerClient, SignerListenerEndpoint, SignerServer,
)
from cometbft_tpu.types import canonical
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.vote import Vote


def _vote(height, round_=0, hash_=b"\x11" * 32):
    return Vote(type=canonical.PRECOMMIT_TYPE, height=height,
                round=round_,
                block_id=BlockID(hash=hash_,
                                 part_set_header=PartSetHeader(
                                     1, b"\x22" * 32)),
                timestamp=Timestamp(1700000000, 0),
                validator_address=b"\x01" * 20, validator_index=0)


class TestSignerProtocol:
    def test_ping_pubkey_sign_and_double_sign_refusal(self):
        async def run():
            with tempfile.TemporaryDirectory() as d:
                pv = FilePV.generate(os.path.join(d, "k.json"),
                                     os.path.join(d, "s.json"))
                ep = SignerListenerEndpoint("tcp://127.0.0.1:0")
                await ep.start()
                srv = SignerServer(ep.listen_addr, "sig-chain", pv)
                await srv.start()
                await ep.wait_for_signer(10)
                cli = SignerClient(ep, "sig-chain")
                await cli.ping()
                pub = await cli.fetch_pub_key()
                assert pub == pv.get_pub_key()

                v = _vote(5)
                await cli.sign_vote_async("sig-chain", v, False)
                assert pub.verify_signature(
                    v.sign_bytes("sig-chain"), v.signature)

                # conflicting block at the same HRS: the SIGNER refuses
                v2 = _vote(5, hash_=b"\x99" * 32)
                with pytest.raises(DoubleSignError):
                    await cli.sign_vote_async("sig-chain", v2, False)

                # height regression also refused
                v3 = _vote(4)
                with pytest.raises(DoubleSignError):
                    await cli.sign_vote_async("sig-chain", v3, False)

                await srv.stop()
                await ep.stop()
        asyncio.run(run())

    def test_retry_wrapper_never_retries_double_sign(self):
        async def run():
            with tempfile.TemporaryDirectory() as d:
                pv = FilePV.generate(os.path.join(d, "k.json"),
                                     os.path.join(d, "s.json"))
                ep = SignerListenerEndpoint("tcp://127.0.0.1:0")
                await ep.start()
                srv = SignerServer(ep.listen_addr, "c", pv)
                await srv.start()
                await ep.wait_for_signer(10)
                cli = RetrySignerClient(SignerClient(ep, "c"))
                await cli.fetch_pub_key()
                v = _vote(7)
                await cli.sign_vote_async("c", v, False)
                before = pv.last_sign_state.height
                with pytest.raises(DoubleSignError):
                    await cli.sign_vote_async(
                        "c", _vote(7, hash_=b"\x77" * 32), False)
                assert pv.last_sign_state.height == before
                await srv.stop()
                await ep.stop()
        asyncio.run(run())

    def test_hrs_protection_survives_signer_restart(self):
        """Sign at height 9, 'restart' the signer (fresh FilePV loaded
        from disk), then a request for height 8 must be refused — the
        HRS state machine is durable in the signer."""
        async def run():
            with tempfile.TemporaryDirectory() as d:
                kf, sf = os.path.join(d, "k.json"), os.path.join(
                    d, "s.json")
                pv = FilePV.generate(kf, sf)
                ep = SignerListenerEndpoint("tcp://127.0.0.1:0")
                await ep.start()
                srv = SignerServer(ep.listen_addr, "c", pv)
                await srv.start()
                await ep.wait_for_signer(10)
                cli = SignerClient(ep, "c")
                await cli.fetch_pub_key()
                await cli.sign_vote_async("c", _vote(9), False)
                await srv.stop()
                ep._drop_conn()

                pv2 = FilePV.load(kf, sf)          # restart
                srv2 = SignerServer(ep.listen_addr, "c", pv2)
                await srv2.start()
                await ep.wait_for_signer(10)
                with pytest.raises(DoubleSignError):
                    await cli.sign_vote_async("c", _vote(8), False)
                # same height, same block: signature is REUSED, not
                # re-signed (reference same-HRS rule)
                v = _vote(9)
                await cli.sign_vote_async("c", v, False)
                assert v.signature
                await srv2.stop()
                await ep.stop()
        asyncio.run(run())


class TestNodeWithRemoteSigner:
    def test_node_signs_via_external_signer_process(self):
        """A validator node produces blocks with its key held by a
        SEPARATE signer process over the privval socket protocol."""
        from cometbft_tpu.config import Config
        from cometbft_tpu.node.node import Node
        from cometbft_tpu.p2p.key import NodeKey
        from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator

        async def run():
            with tempfile.TemporaryDirectory() as d:
                home = os.path.join(d, "node")
                signer_dir = os.path.join(d, "signer")
                os.makedirs(signer_dir)
                kf = os.path.join(signer_dir, "k.json")
                sf = os.path.join(signer_dir, "s.json")
                pv = FilePV.generate(kf, sf)

                cfg = Config()
                cfg.base.home = home
                cfg.base.priv_validator_laddr = "tcp://127.0.0.1:26679"
                cfg.p2p.laddr = "tcp://127.0.0.1:0"
                cfg.rpc.laddr = ""
                cfg.consensus.timeout_commit_ns = 50_000_000
                os.makedirs(os.path.join(home, "config"), exist_ok=True)
                os.makedirs(os.path.join(home, "data"), exist_ok=True)
                NodeKey.load_or_gen(cfg.base.path(cfg.base.node_key_file))
                GenesisDoc(
                    chain_id="remote-chain",
                    genesis_time=Timestamp.now(),
                    validators=[GenesisValidator(
                        address=b"", pub_key=pv.get_pub_key(),
                        power=10)],
                ).save_as(cfg.base.path(cfg.base.genesis_file))

                proc = subprocess.Popen(
                    [sys.executable, "-m", "cometbft_tpu.privval.signer",
                     "--address", "tcp://127.0.0.1:26679",
                     "--chain-id", "remote-chain",
                     "--key-file", kf, "--state-file", sf],
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                    env={**os.environ, "JAX_PLATFORMS": ""})
                try:
                    node = Node(cfg)
                    await node.start()
                    for _ in range(400):
                        if node.height >= 3:
                            break
                        await asyncio.sleep(0.02)
                    assert node.height >= 3, \
                        "no blocks signed via remote signer"
                    assert node.priv_validator.get_pub_key() == \
                        pv.get_pub_key()
                    await node.stop()
                finally:
                    proc.terminate()
                    proc.wait(timeout=5)
        asyncio.run(run())


class TestPrivValServerCLI:
    def test_node_signs_via_external_daemon_process(self):
        """A full node with priv_validator_laddr produces blocks whose
        votes are signed by the `priv-val-server` CLI daemon in a
        SEPARATE PROCESS (reference: cmd/priv_val_server)."""
        import subprocess
        import sys

        from cometbft_tpu.config import Config
        from cometbft_tpu.node.node import Node
        from cometbft_tpu.p2p.key import NodeKey
        from cometbft_tpu.types.genesis import (
            GenesisDoc, GenesisValidator,
        )
        from cometbft_tpu.types.timestamp import Timestamp

        async def run():
            with tempfile.TemporaryDirectory() as d:
                home = os.path.join(d, "node")
                cfg = Config()
                cfg.base.home = home
                cfg.p2p.laddr = "tcp://127.0.0.1:0"
                cfg.rpc.laddr = ""
                cfg.consensus.timeout_commit_ns = 50_000_000
                import socket as pysock
                s = pysock.socket()
                s.bind(("127.0.0.1", 0))
                port = s.getsockname()[1]
                s.close()
                cfg.base.priv_validator_laddr = \
                    f"tcp://127.0.0.1:{port}"
                os.makedirs(os.path.join(home, "config"),
                            exist_ok=True)
                os.makedirs(os.path.join(home, "data"), exist_ok=True)
                # the key lives ONLY with the signer daemon
                key_file = os.path.join(d, "signer_key.json")
                state_file = os.path.join(d, "signer_state.json")
                pv = FilePV.generate(key_file, state_file)
                NodeKey.load_or_gen(
                    cfg.base.path(cfg.base.node_key_file))
                GenesisDoc(
                    chain_id="daemon-chain",
                    genesis_time=Timestamp.now(),
                    validators=[GenesisValidator(
                        address=b"", pub_key=pv.get_pub_key(),
                        power=10)],
                ).save_as(cfg.base.path(cfg.base.genesis_file))
                proc = subprocess.Popen(
                    [sys.executable, "-m", "cometbft_tpu.cmd",
                     "priv-val-server",
                     "--addr", cfg.base.priv_validator_laddr,
                     "--chain-id", "daemon-chain",
                     "--priv-key-file", key_file,
                     "--state-file", state_file],
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                    env={**os.environ, "JAX_PLATFORMS": ""})
                try:
                    node = Node(cfg)
                    await node.start()
                    for _ in range(300):
                        if node.height >= 3:
                            break
                        await asyncio.sleep(0.05)
                    assert node.height >= 3, \
                        "no blocks signed via external daemon"
                    # the commit sig must verify against the DAEMON's
                    # key — proving the node really signed remotely
                    commit = node.block_store.load_block_commit(2)
                    assert commit is not None
                    sig = commit.signatures[0]
                    assert pv.get_pub_key().verify_signature(
                        commit.vote_sign_bytes("daemon-chain", 0),
                        sig.signature), \
                        "commit not signed by the remote key"
                    await node.stop()
                finally:
                    proc.terminate()
                    proc.wait(timeout=10)
        asyncio.run(run())
