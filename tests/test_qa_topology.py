"""QA net topology (tools/qa.py _setup_net): the sig-scale stage's
bounded-degree graph must stay connected, and single-zone must drop
every latency relay."""
import pytest

from cometbft_tpu.tools import qa


def _build(tmp_path, n_validators, n_full, **kw):
    report = qa.QAReport()
    return qa._setup_net(str(tmp_path), n_validators, n_full, 4,
                         report, **kw)


def _adjacency(names, cfgs, node_ids):
    id_to_name = {v: k for k, v in node_ids.items()}
    adj = {n: set() for n in names}
    for name in names:
        for peer in filter(None,
                           cfgs[name].p2p.persistent_peers.split(",")):
            pid = peer.split("@", 1)[0]
            other = id_to_name[pid]
            adj[name].add(other)
            adj[other].add(name)      # dials are bidirectional links
    return adj


class TestTopology:
    def test_default_is_full_mesh_with_relays(self, tmp_path):
        names, zones, cfgs, _jc, node_ids, _pp, relays = _build(
            tmp_path, 5, 1, single_zone=False, peer_degree=0)
        adj = _adjacency(names, cfgs, node_ids)
        for n in names:
            assert adj[n] == set(names) - {n}
        assert relays                      # three zones -> relay links
        assert len(set(zones.values())) == 3

    def test_bounded_degree_ring_is_connected(self, tmp_path):
        names, zones, cfgs, _jc, node_ids, _pp, relays = _build(
            tmp_path, 12, 1, single_zone=True, peer_degree=4)
        assert relays == []                # one zone -> no relays
        assert set(zones.values()) == {qa.ZONES[0]}
        adj = _adjacency(names, cfgs, node_ids)
        # each node dials at most peer_degree targets
        for name in names:
            dials = [p for p in
                     cfgs[name].p2p.persistent_peers.split(",") if p]
            assert len(dials) <= 4
        # BFS: the union graph is connected
        seen, frontier = {names[0]}, [names[0]]
        while frontier:
            cur = frontier.pop()
            for nxt in adj[cur]:
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        assert seen == set(names)
