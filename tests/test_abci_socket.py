"""ABCI socket protocol: proto round-trips, server/client over unix
sockets, pipelining, and a node running against an out-of-process app.

Reference: abci/client/socket_client.go:515, abci/server/socket_server.go,
proto/cometbft/abci/v2/types.proto Request/Response oneofs.
"""
import asyncio
import os
import subprocess
import sys
import tempfile

import pytest

from cometbft_tpu.abci import pb
from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import SocketAppConns, SocketClient
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.abci.server import SocketServer
from cometbft_tpu.types.timestamp import Timestamp


def _roundtrip_request(req):
    frame = pb.encode_request_frame(req)
    from cometbft_tpu.wire.proto import decode_uvarint
    size, pos = decode_uvarint(frame, 0)
    assert size == len(frame) - pos
    return pb.decode_request(frame[pos:])


def _roundtrip_response(resp):
    frame = pb.encode_response_frame(resp)
    from cometbft_tpu.wire.proto import decode_uvarint
    size, pos = decode_uvarint(frame, 0)
    return pb.decode_response(frame[pos:])


class TestProtoRoundTrip:
    def test_all_requests(self):
        ts = Timestamp(1700000001, 500)
        val = abci.ABCIValidator(address=b"\x01" * 20, power=10)
        reqs = [
            abci.EchoRequest(message="hi"),
            abci.FlushRequest(),
            abci.InfoRequest(version="1.0", block_version=11,
                             p2p_version=9, abci_version="2.0"),
            abci.InitChainRequest(
                time=ts, chain_id="c", validators=[
                    abci.ValidatorUpdate(power=5, pub_key_bytes=b"\x02" * 32,
                                         pub_key_type="ed25519")],
                app_state_bytes=b"{}", initial_height=1),
            abci.QueryRequest(data=b"k", path="/store", height=3,
                              prove=True),
            abci.CheckTxRequest(tx=b"a=1", type=abci.CHECK_TX_TYPE_CHECK),
            abci.CommitRequest(),
            abci.ListSnapshotsRequest(),
            abci.OfferSnapshotRequest(
                snapshot=abci.Snapshot(height=5, format=1, chunks=2,
                                       hash=b"h" * 32, metadata=b"m"),
                app_hash=b"a" * 32),
            abci.LoadSnapshotChunkRequest(height=5, format=1, chunk=1),
            abci.ApplySnapshotChunkRequest(index=1, chunk=b"c",
                                           sender="n0"),
            abci.PrepareProposalRequest(
                max_tx_bytes=1000, txs=[b"t1", b"t2"],
                local_last_commit=abci.ExtendedCommitInfo(
                    round=1, votes=[abci.ExtendedVoteInfo(
                        validator=val, vote_extension=b"e",
                        extension_signature=b"s" * 64,
                        block_id_flag=2, non_rp_vote_extension=b"n",
                        non_rp_extension_signature=b"t" * 64)]),
                misbehavior=[abci.Misbehavior(
                    type=abci.MISBEHAVIOR_TYPE_DUPLICATE_VOTE,
                    validator=val, height=2, time=ts,
                    total_voting_power=10)],
                height=7, time=ts, next_validators_hash=b"v" * 32,
                proposer_address=b"\x03" * 20),
            abci.ProcessProposalRequest(
                txs=[b"t"], proposed_last_commit=abci.CommitInfo(
                    round=0, votes=[abci.VoteInfo(validator=val,
                                                  block_id_flag=2)]),
                hash=b"H" * 32, height=7, time=ts,
                next_validators_hash=b"v" * 32,
                proposer_address=b"\x03" * 20),
            abci.ExtendVoteRequest(hash=b"H" * 32, height=7, time=ts,
                                   txs=[b"t"]),
            abci.VerifyVoteExtensionRequest(
                hash=b"H" * 32, validator_address=b"\x01" * 20, height=7,
                vote_extension=b"e", non_rp_vote_extension=b"n"),
            abci.FinalizeBlockRequest(
                txs=[b"t1"], hash=b"H" * 32, height=7, time=ts,
                next_validators_hash=b"v" * 32,
                proposer_address=b"\x03" * 20, syncing_to_height=7),
        ]
        for req in reqs:
            assert _roundtrip_request(req) == req, type(req).__name__

    def test_all_responses(self):
        resps = [
            abci.ExceptionResponse(error="boom"),
            abci.EchoResponse(message="hi"),
            abci.FlushResponse(),
            abci.InfoResponse(data="kv", version="1", app_version=1,
                              last_block_height=5,
                              last_block_app_hash=b"h" * 32,
                              lane_priorities={"a": 1, "b": 3},
                              default_lane="a"),
            abci.InitChainResponse(validators=[
                abci.ValidatorUpdate(power=3, pub_key_bytes=b"\x02" * 32,
                                     pub_key_type="ed25519")],
                app_hash=b"x" * 32),
            abci.QueryResponse(code=0, value=b"v", height=3, index=1,
                               key=b"k"),
            abci.CheckTxResponse(code=0, gas_wanted=1, lane_id="fast",
                                 events=[abci.Event(
                                     type="tx", attributes=[
                                         abci.EventAttribute(
                                             key="k", value="v",
                                             index=True)])]),
            abci.CommitResponse(retain_height=2),
            abci.ListSnapshotsResponse(snapshots=[
                abci.Snapshot(height=1, format=1, chunks=1,
                              hash=b"h" * 32)]),
            abci.OfferSnapshotResponse(
                result=abci.OFFER_SNAPSHOT_RESULT_ACCEPT),
            abci.LoadSnapshotChunkResponse(chunk=b"c"),
            abci.ApplySnapshotChunkResponse(
                result=abci.APPLY_SNAPSHOT_CHUNK_RESULT_ACCEPT,
                refetch_chunks=[1, 2], reject_senders=["bad"]),
            abci.PrepareProposalResponse(txs=[b"t"]),
            abci.ProcessProposalResponse(
                status=abci.PROCESS_PROPOSAL_STATUS_ACCEPT),
            abci.ExtendVoteResponse(vote_extension=b"e",
                                    non_rp_extension=b"n"),
            abci.VerifyVoteExtensionResponse(
                status=abci.VERIFY_VOTE_EXTENSION_STATUS_ACCEPT),
            abci.FinalizeBlockResponse(
                tx_results=[abci.ExecTxResult(code=0, gas_used=1)],
                app_hash=b"a" * 32),
        ]
        for resp in resps:
            assert _roundtrip_response(resp) == resp, type(resp).__name__


class TestSocketClientServer:
    def test_echo_info_checktx(self):
        async def run():
            with tempfile.TemporaryDirectory() as d:
                sock = os.path.join(d, "app.sock")
                srv = SocketServer(f"unix://{sock}", KVStoreApplication())
                await srv.start()
                cli = SocketClient(f"unix://{sock}")
                await cli.connect()
                echo = await cli.echo("hello")
                assert echo.message == "hello"
                info = await cli.info(abci.InfoRequest())
                assert info.data
                res = await cli.check_tx(abci.CheckTxRequest(
                    tx=b"k=v", type=abci.CHECK_TX_TYPE_CHECK))
                assert res.code == abci.CODE_TYPE_OK
                await cli.flush()
                await cli.close()
                await srv.stop()
        asyncio.run(run())

    def test_pipelined_checktx(self):
        """Many in-flight CheckTx calls resolve in order (the pipelining
        contract of socket_client.go)."""
        async def run():
            with tempfile.TemporaryDirectory() as d:
                sock = os.path.join(d, "app.sock")
                srv = SocketServer(f"unix://{sock}", KVStoreApplication())
                await srv.start()
                cli = SocketClient(f"unix://{sock}")
                await cli.connect()
                futs = [
                    asyncio.ensure_future(cli.check_tx(abci.CheckTxRequest(
                        tx=f"k{i}=v{i}".encode(),
                        type=abci.CHECK_TX_TYPE_CHECK)))
                    for i in range(100)
                ]
                res = await asyncio.gather(*futs)
                assert all(r.code == abci.CODE_TYPE_OK for r in res)
                await cli.close()
                await srv.stop()
        asyncio.run(run())

    def test_exception_response_is_fatal(self):
        """An app ExceptionResponse kills the client — the app's state is
        unknown (reference socket_client StopForError semantics)."""
        class BoomApp(abci.BaseApplication):
            async def query(self, req):
                raise RuntimeError("boom")

        async def run():
            with tempfile.TemporaryDirectory() as d:
                sock = os.path.join(d, "app.sock")
                srv = SocketServer(f"unix://{sock}", BoomApp())
                await srv.start()
                cli = SocketClient(f"unix://{sock}")
                await cli.connect()
                with pytest.raises(Exception, match="boom"):
                    await cli.query(abci.QueryRequest(path="x"))
                with pytest.raises(Exception, match="dead"):
                    await cli.echo("should be dead")
                await cli.close()
                await srv.stop()
        asyncio.run(run())


class TestNodeWithSocketApp:
    def test_node_over_external_kvstore_process(self):
        """A full node drives a kvstore app living in a SEPARATE PROCESS
        over a unix socket: handshake, block production, tx commit
        (reference: e2e 'unix' ABCI protocol mode)."""
        from cometbft_tpu.config import Config
        from cometbft_tpu.node.node import Node
        from cometbft_tpu.privval import FilePV
        from cometbft_tpu.p2p.key import NodeKey
        from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
        from cometbft_tpu.types.timestamp import Timestamp

        async def run():
            with tempfile.TemporaryDirectory() as d:
                sock = os.path.join(d, "app.sock")
                proc = subprocess.Popen(
                    [sys.executable, "-m", "cometbft_tpu.abci.server",
                     "--address", f"unix://{sock}", "--app", "kvstore"],
                    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                    env={**os.environ, "JAX_PLATFORMS": ""})
                try:
                    home = os.path.join(d, "node")
                    cfg = Config()
                    cfg.base.home = home
                    cfg.base.abci = "socket"
                    cfg.base.proxy_app = f"unix://{sock}"
                    cfg.p2p.laddr = "tcp://127.0.0.1:0"
                    cfg.rpc.laddr = ""
                    cfg.consensus.timeout_commit_ns = 50_000_000
                    os.makedirs(os.path.join(home, "config"),
                                exist_ok=True)
                    os.makedirs(os.path.join(home, "data"), exist_ok=True)
                    pv = FilePV.generate(
                        cfg.base.path(cfg.base.priv_validator_key_file),
                        cfg.base.path(cfg.base.priv_validator_state_file))
                    NodeKey.load_or_gen(
                        cfg.base.path(cfg.base.node_key_file))
                    doc = GenesisDoc(
                        chain_id="socket-chain",
                        genesis_time=Timestamp.now(),
                        validators=[GenesisValidator(
                            address=b"", pub_key=pv.get_pub_key(),
                            power=10)])
                    doc.save_as(cfg.base.path(cfg.base.genesis_file))
                    node = Node(cfg)
                    await node.start()
                    # wait for a few blocks, submit a tx, see it commit
                    for _ in range(200):
                        if node.height >= 2:
                            break
                        await asyncio.sleep(0.05)
                    assert node.height >= 2, "no blocks produced"
                    await node.mempool.check_tx(b"socket=works")
                    h0 = node.height
                    for _ in range(200):
                        if node.height >= h0 + 2:
                            break
                        await asyncio.sleep(0.05)
                    # poll: block-store height leads the app commit
                    value = b""
                    for _ in range(200):
                        res = await node.app_conns.query.query(
                            abci.QueryRequest(path="/store",
                                              data=b"socket"))
                        value = res.value
                        if value:
                            break
                        await asyncio.sleep(0.05)
                    assert value == b"works"
                    await node.stop()
                finally:
                    proc.terminate()
                    proc.wait(timeout=5)
        asyncio.run(run())
