"""gRPC data-companion services.

Reference: rpc/grpc/server/services/* and proto/cometbft/services/*/v1.
A live node exposes version/block/block-results services on the public
gRPC listener and the pruning service on the privileged listener; real
grpc.aio channels drive them.
"""
import asyncio
import os
import tempfile

import pytest


def _make_node_cfg(d: str):
    from cometbft_tpu.config import Config
    from cometbft_tpu.p2p.key import NodeKey
    from cometbft_tpu.privval import FilePV
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.timestamp import Timestamp

    home = os.path.join(d, "node")
    cfg = Config()
    cfg.base.home = home
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.grpc.laddr = "tcp://127.0.0.1:0"
    cfg.grpc.privileged_laddr = "tcp://127.0.0.1:0"
    cfg.grpc.pruning_service_enabled = True
    cfg.consensus.timeout_commit_ns = 20_000_000
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    pv = FilePV.generate(
        cfg.base.path(cfg.base.priv_validator_key_file),
        cfg.base.path(cfg.base.priv_validator_state_file))
    NodeKey.load_or_gen(cfg.base.path(cfg.base.node_key_file))
    GenesisDoc(
        chain_id="grpc-chain",
        genesis_time=Timestamp.now(),
        validators=[GenesisValidator(
            address=b"", pub_key=pv.get_pub_key(), power=10)],
    ).save_as(cfg.base.path(cfg.base.genesis_file))
    return cfg


class TestGRPCCompanion:
    def test_services_against_live_node(self):
        from cometbft_tpu.node.node import Node
        from cometbft_tpu.rpc.grpc import (
            BlockResultsServiceClient, BlockServiceClient,
            PruningServiceClient, VersionServiceClient,
        )
        from cometbft_tpu import version as ver

        async def run():
            with tempfile.TemporaryDirectory() as d:
                cfg = _make_node_cfg(d)
                node = Node(cfg)
                await node.start()
                try:
                    for _ in range(400):
                        if node.height >= 6:
                            break
                        await asyncio.sleep(0.02)
                    assert node.height >= 6
                    addr = f"127.0.0.1:{node._grpc_server.port}"
                    priv = f"127.0.0.1:{node._grpc_priv_server.port}"

                    async with VersionServiceClient(addr) as vc:
                        v = await vc.get_version()
                    assert v["node"] == ver.CMT_SEM_VER
                    assert v["block"] == ver.BLOCK_PROTOCOL

                    async with BlockServiceClient(addr) as bc:
                        b3 = await bc.get_by_height(3)
                        assert b3["block"]["header"]["height"] == 3
                        assert b3["block_id"]["hash"]
                        latest = await bc.get_by_height()
                        assert latest["block"]["header"]["height"] >= 6
                        # stream: first yield is the current height,
                        # then newly committed heights
                        heights = []
                        async for h in bc.get_latest_height():
                            heights.append(h)
                            if len(heights) >= 3:
                                break
                        assert heights[0] >= 6
                        assert heights[1] >= heights[0]
                        # NOT_FOUND for pruned-or-future heights
                        import grpc as grpclib
                        with pytest.raises(grpclib.aio.AioRpcError) as ei:
                            await bc.get_by_height(10_000)
                        assert ei.value.code() == \
                            grpclib.StatusCode.NOT_FOUND

                    async with BlockResultsServiceClient(addr) as rc:
                        r = await rc.get_block_results(2)
                        assert r["height"] == 2
                        assert r.get("app_hash", b"") != b""

                    async with PruningServiceClient(priv) as pc:
                        await pc.set_block_retain_height(4)
                        got = await pc.get_block_retain_height()
                        assert got["pruning_service_retain_height"] == 4
                        await pc.set_block_results_retain_height(4)
                        assert await \
                            pc.get_block_results_retain_height() == 4
                        await pc.set_tx_indexer_retain_height(4)
                        assert await \
                            pc.get_tx_indexer_retain_height() == 4
                        await pc.set_block_indexer_retain_height(4)
                        assert await \
                            pc.get_block_indexer_retain_height() == 4
                        # backwards movement is INVALID_ARGUMENT
                        import grpc as grpclib
                        with pytest.raises(grpclib.aio.AioRpcError) as ei:
                            await pc.set_block_retain_height(2)
                        assert ei.value.code() == \
                            grpclib.StatusCode.INVALID_ARGUMENT

                    # the companion knobs prune ABCI results once the
                    # pass runs (blocks wait for the app knob)
                    node.pruner.prune_once()
                    assert node.state_store.load_finalize_block_response(
                        2) is None
                    assert node.state_store.load_finalize_block_response(
                        node.height) is not None
                finally:
                    await node.stop()
        asyncio.run(run())


class TestPrunerCompanionArtifacts:
    def test_indexer_and_results_pruning(self):
        """Unit-level: the pruner drives tx/block indexer pruning and
        ABCI-result deletion up to the companion retain heights."""
        from cometbft_tpu.abci import types as abci
        from cometbft_tpu.db.db import MemDB
        from cometbft_tpu.indexer import BlockIndexer, TxIndexer
        from cometbft_tpu.libs.pubsub import Query
        from cometbft_tpu.state.pruner import Pruner

        class _Stores:
            height = 10
            base = 1

        tx_idx = TxIndexer(MemDB())
        blk_idx = BlockIndexer(MemDB())
        for h in range(1, 11):
            ev = abci.Event(type="transfer", attributes=[
                abci.EventAttribute(key="acct", value=f"a{h}",
                                    index=True)])
            tx_idx.index(abci.TxResult(
                height=h, index=0, tx=b"tx%d" % h,
                result=abci.ExecTxResult(code=0, events=[ev])))
            blk_idx.index(h, [ev])

        class _StateStore:
            def __init__(self):
                self.deleted = []

            def prune_abci_responses(self, lo, hi):
                self.deleted.append((lo, hi))
                return hi - lo

        ss = _StateStore()
        p = Pruner(ss, _Stores(), MemDB(), companion_enabled=True,
                   tx_indexer=tx_idx, block_indexer=blk_idx)
        p.set_abci_results_retain_height(6)
        p.set_tx_indexer_retain_height(6)
        p.set_block_indexer_retain_height(6)
        p.prune_once()
        assert ss.deleted == [(1, 6)]
        # indexed txs below 6 are gone, 6+ remain
        assert tx_idx.search(Query("transfer.acct = 'a3'")) == []
        assert len(tx_idx.search(Query("transfer.acct = 'a7'"))) == 1
        assert blk_idx.search(Query("transfer.acct = 'a4'")) == []
        assert blk_idx.search(Query("transfer.acct = 'a8'")) == [8]
        # watermark: a second pass re-prunes nothing
        assert p.prune_once() == (0, 1)
        # retain heights cannot move backwards
        with pytest.raises(ValueError):
            p.set_tx_indexer_retain_height(3)
