"""Open-loop load generation (tools/loadtime.py).

VERDICT r4 weak #3: the old generator awaited each RPC round trip
inside its pacing loop, capping offered load at connections x 1/RTT.
These tests prove the rewrite decouples pacing from completion: the
offered rate must hold even against a sink that answers slowly.
"""
import asyncio

import pytest

from cometbft_tpu.tools import loadtime


def _run(coro):
    return asyncio.run(coro)


async def _slow_sink(delay_s: float):
    return await loadtime.null_sink(delay_s)


class TestOpenLoop:
    def test_selfcheck_offers_requested_rate(self):
        out = _run(loadtime.selfcheck(rate=150, duration_s=2.0))
        # offered (sent + dropped) must track the requested schedule
        assert out["offered_ratio"] >= 0.85, out
        assert out["accepted"] >= 0.7 * out["sent"], out

    def test_offered_rate_survives_slow_endpoint(self):
        """A 1 s per-response sink: the closed-loop design capped at
        connections x 1 tx/s; open-loop must still offer ~rate."""

        async def run():
            server = await _slow_sink(1.0)
            port = server.sockets[0].getsockname()[1]
            try:
                res = await loadtime.generate(
                    [f"http://127.0.0.1:{port}"], rate=50,
                    connections=2, duration_s=2.0, method="sync")
            finally:
                server.close()
                await server.wait_closed()
            return res

        res = _run(run())
        offered = res.sent + res.dropped
        # closed-loop would have sent ~2-4; the schedule asks for ~100
        assert offered >= 70, (res.sent, res.dropped, res.errors)
        assert res.sent >= 50          # the in-flight cap is generous

    def test_in_flight_cap_bounds_outstanding(self):
        async def run():
            server = await _slow_sink(3.0)
            port = server.sockets[0].getsockname()[1]
            try:
                res = await loadtime.generate(
                    [f"http://127.0.0.1:{port}"], rate=100,
                    connections=1, duration_s=1.5, method="sync",
                    max_in_flight=10)
            finally:
                server.close()
                await server.wait_closed()
            return res

        res = _run(run())
        # never more than the cap actually dispatched concurrently:
        # sent is bounded by cap (all stuck in the 3 s sink) while the
        # remaining ticks land in dropped — offered stays visible
        assert res.sent <= 10 + 1
        assert res.sent + res.dropped >= 100, (res.sent, res.dropped)
