"""RPC contract tests: every documented method is exercised against a
live node and its response validated against docs/rpc-spec.json
(reference: cmd/contract_tests — dredd against the OpenAPI spec)."""
import asyncio
import base64
import json
import os
import tempfile

SPEC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "docs", "rpc-spec.json")


def _make_node_cfg(d):
    from cometbft_tpu.config import Config
    from cometbft_tpu.p2p.key import NodeKey
    from cometbft_tpu.privval import FilePV
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.timestamp import Timestamp

    home = os.path.join(d, "node")
    cfg = Config()
    cfg.base.home = home
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.consensus.timeout_commit_ns = 20_000_000
    cfg.rpc.unsafe = True     # exercise the unsafe control routes too
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    pv = FilePV.generate(
        cfg.base.path(cfg.base.priv_validator_key_file),
        cfg.base.path(cfg.base.priv_validator_state_file))
    NodeKey.load_or_gen(cfg.base.path(cfg.base.node_key_file))
    GenesisDoc(
        chain_id="contract-chain", genesis_time=Timestamp.now(),
        validators=[GenesisValidator(
            address=b"", pub_key=pv.get_pub_key(), power=10)],
    ).save_as(cfg.base.path(cfg.base.genesis_file))
    return cfg


def _forge_evidence(node) -> str:
    """Valid duplicate-vote evidence against the node's own validator
    at height 1, base64 wire-encoded for broadcast_evidence."""
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types.block_id import BlockID
    from cometbft_tpu.types.evidence import DuplicateVoteEvidence
    from cometbft_tpu.types.part_set import PartSetHeader
    from cometbft_tpu.types.vote import Vote
    from cometbft_tpu.wire import encode as wencode, pb as wpb

    pv = node.priv_validator
    addr = pv.get_pub_key().address()
    meta = node.block_store.load_block_meta(1)
    chain_id = node.genesis_doc.chain_id
    votes = []
    for lead in (b"\x01", b"\x02"):
        bid = BlockID(hash=lead * 32,
                      part_set_header=PartSetHeader(1, lead * 32))
        v = Vote(type=canonical.PRECOMMIT_TYPE, height=1, round=0,
                 block_id=bid, timestamp=meta.header.time,
                 validator_address=addr, validator_index=0)
        v.signature = pv.priv_key.sign(v.sign_bytes(chain_id))
        votes.append(v)
    ev = DuplicateVoteEvidence(
        vote_a=votes[0], vote_b=votes[1], total_voting_power=10,
        validator_power=10, timestamp=meta.header.time)
    return base64.b64encode(
        wencode(wpb.EVIDENCE, ev.to_proto_wrapped())).decode()


def _check(spec, method, result):
    info = spec["methods"][method]
    assert isinstance(result, (dict, list)), \
        f"{method}: result must be structured, got {type(result)}"
    if isinstance(result, dict):
        for key in info["result_required"]:
            assert key in result, \
                f"{method}: missing required field {key!r} " \
                f"(got {sorted(result)})"
        for field, subkeys in info.get("nested_required", {}).items():
            sub = result.get(field)
            assert isinstance(sub, dict), \
                f"{method}: {field} must be an object"
            for key in subkeys:
                assert key in sub, \
                    f"{method}.{field}: missing {key!r}"


class TestRPCContract:
    def test_every_documented_method(self):
        from cometbft_tpu.node.node import Node
        from cometbft_tpu.rpc.client import HTTPClient

        with open(SPEC) as f:
            spec = json.load(f)

        async def run():
            with tempfile.TemporaryDirectory() as d:
                node = Node(_make_node_cfg(d))
                await node.start()
                try:
                    cli = HTTPClient(
                        f"http://{node._rpc_server.listen_addr}",
                        timeout=30.0)
                    # commit a tx so tx/tx_search/evidence paths have
                    # data to return
                    res = await cli.broadcast_tx_commit(b"spec=ok")
                    tx_hash = res["hash"]
                    for _ in range(200):
                        if node.height >= 4:
                            break
                        await asyncio.sleep(0.02)

                    tx64 = base64.b64encode(b"probe=1").decode()
                    args = {
                        "abci_query": {"path": "/store",
                                       "data": b"spec".hex()},
                        "genesis_chunked": {"chunk": "0"},
                        "header": {"height": "2"},
                        "check_tx": {"tx": base64.b64encode(
                            b"probe=ct").decode()},
                        "dial_seeds": {"seeds":
                                       "00" * 20 +
                                       "@127.0.0.1:1"},
                        "dial_peers": {"peers":
                                       "11" * 20 +
                                       "@127.0.0.1:1",
                                       "persistent": False},
                        "broadcast_tx_sync": {"tx": tx64},
                        "broadcast_tx_async": {"tx": base64.b64encode(
                            b"probe=2").decode()},
                        "broadcast_tx_commit": {"tx": base64.b64encode(
                            b"probe=3").decode()},
                        "block": {"height": "2"},
                        "light_block": {"height": "2"},
                        "multiproof": {"height": "2", "indices": ""},
                        "abci_query_batch": {
                            "data": "0x" + b"spec".hex(),
                            "prove": True},
                        "block_results": {"height": "2"},
                        "commit": {"height": "2"},
                        "blockchain": {"minHeight": "1",
                                       "maxHeight": "3"},
                        "validators": {"height": "2"},
                        "consensus_params": {"height": "2"},
                        "tx": {"hash": tx_hash},
                        "tx_search": {"query": "tx.height >= 1"},
                        "block_search": {
                            "query": "block.height >= 1"},
                        "pruning_set_block_retain_height":
                            {"height": "2"},
                    }
                    # block_by_hash / header_by_hash need a real hash
                    blk = await cli.call("block", height="2")
                    args["block_by_hash"] = {
                        "hash": "0x" + blk["block_id"]["hash"]}
                    args["header_by_hash"] = {
                        "hash": "0x" + blk["block_id"]["hash"]}
                    # broadcast_evidence: forge valid dup-vote
                    # evidence signed by the node's own validator key
                    args["broadcast_evidence"] = {
                        "evidence": _forge_evidence(node)}

                    checked = 0
                    for method in spec["methods"]:
                        if method == "unconfirmed_tx":
                            # park a tx: stub out reaping so the
                            # proposer can't commit it mid-call (the
                            # sole-validator node otherwise commits
                            # within ~10 ms of the add)
                            from cometbft_tpu.types.tx import tx_hash
                            mp = node.mempool
                            orig = mp.reap_max_bytes_max_gas
                            mp.reap_max_bytes_max_gas = \
                                lambda *a, **k: []
                            try:
                                await mp.check_tx(b"uc=tx")
                                result = await cli.call(
                                    method, hash="0x" +
                                    tx_hash(b"uc=tx").hex())
                            finally:
                                mp.reap_max_bytes_max_gas = orig
                        else:
                            result = await cli.call(
                                method, **args.get(method, {}))
                        _check(spec, method, result)
                        checked += 1
                    assert checked == len(spec["methods"])
                finally:
                    await node.stop()
        asyncio.run(run())

    def test_spec_covers_every_served_route(self):
        """The spec and the served route table must not drift."""
        from cometbft_tpu.rpc import core

        with open(SPEC) as f:
            spec = json.load(f)

        class _Env:
            def __getattr__(self, name):
                return None
        served = set(core.routes(_Env()))
        assert served == set(spec["methods"]), (
            sorted(served ^ set(spec["methods"])))

    def test_unsafe_routes_gated(self):
        """dial_seeds/dial_peers/unsafe_flush_mempool must be refused
        unless rpc.unsafe is set (reference: AddUnsafeRoutes is only
        called for unsafe configs)."""
        import pytest

        from cometbft_tpu.node.node import Node
        from cometbft_tpu.rpc.client import HTTPClient, RPCClientError

        async def run():
            with tempfile.TemporaryDirectory() as d:
                cfg = _make_node_cfg(d)
                cfg.rpc.unsafe = False
                node = Node(cfg)
                await node.start()
                try:
                    cli = HTTPClient(
                        f"http://{node._rpc_server.listen_addr}",
                        timeout=30.0)
                    for method, kw in [
                            ("dial_seeds", {"seeds": "x@h:1"}),
                            ("dial_peers", {"peers": "x@h:1"}),
                            ("unsafe_flush_mempool", {})]:
                        with pytest.raises(RPCClientError,
                                           match="unsafe"):
                            await cli.call(method, **kw)
                finally:
                    await node.stop()
        asyncio.run(run())


class TestUriParamConventions:
    """URI GET parameter decode semantics (reference:
    rpc/jsonrpc/server/http_uri_handler.go nonJSONStringToArg): a
    QUOTED value is the raw string content (`tx="name=satoshi"`
    submits the bytes `name=satoshi`), 0x-prefixed is hex, and
    JSON-RPC POST []byte params stay base64."""

    def test_quoted_uri_tx_is_raw_bytes(self):
        import base64 as b64

        from cometbft_tpu.rpc import core as rpc_core
        from cometbft_tpu.rpc.server import _parse_uri_value

        v = _parse_uri_value('"name=satoshi"')
        assert isinstance(v, rpc_core.UriString)
        assert rpc_core._decode_tx(v) == b"name=satoshi"
        # hex and base64 conventions unchanged
        assert rpc_core._decode_tx("0x6162") == b"ab"
        assert rpc_core._decode_tx(
            b64.b64encode(b"posted").decode()) == b"posted"
        # unquoted URI values are not tagged
        assert not isinstance(_parse_uri_value("5"), rpc_core.UriString)

    def test_quoted_tx_commits_over_http_get(self):
        """End-to-end: the documented curl usage
        broadcast_tx_commit?tx="k=v" commits and the value is
        queryable (reference docs: kvstore quick-start)."""
        import urllib.request

        from cometbft_tpu.node.node import Node

        async def run():
            with tempfile.TemporaryDirectory() as d:
                node = Node(_make_node_cfg(d))
                await node.start()
                try:
                    addr = node._rpc_server.listen_addr
                    loop = asyncio.get_event_loop()

                    async def fetch(path):
                        url = f"http://{addr}{path}"
                        raw = await loop.run_in_executor(
                            None, lambda: urllib.request.urlopen(
                                url, timeout=30).read())
                        return json.loads(raw)

                    res = await fetch(
                        '/broadcast_tx_commit?tx=%22uriraw=yes%22')
                    assert "error" not in res, res
                    assert res["result"]["tx_result"]["code"] == 0
                    q = await fetch('/abci_query?data=%22uriraw%22')
                    val = q["result"]["response"]["value"]
                    assert base64.b64decode(val) == b"yes"
                finally:
                    await node.stop()
        asyncio.run(run())
