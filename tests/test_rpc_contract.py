"""RPC contract tests: every documented method is exercised against a
live node and its response validated against docs/rpc-spec.json
(reference: cmd/contract_tests — dredd against the OpenAPI spec)."""
import asyncio
import base64
import json
import os
import tempfile

SPEC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "docs", "rpc-spec.json")


def _make_node_cfg(d):
    from cometbft_tpu.config import Config
    from cometbft_tpu.p2p.key import NodeKey
    from cometbft_tpu.privval import FilePV
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.timestamp import Timestamp

    home = os.path.join(d, "node")
    cfg = Config()
    cfg.base.home = home
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.consensus.timeout_commit = 0.02
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    pv = FilePV.generate(
        cfg.base.path(cfg.base.priv_validator_key_file),
        cfg.base.path(cfg.base.priv_validator_state_file))
    NodeKey.load_or_gen(cfg.base.path(cfg.base.node_key_file))
    GenesisDoc(
        chain_id="contract-chain", genesis_time=Timestamp.now(),
        validators=[GenesisValidator(
            address=b"", pub_key=pv.get_pub_key(), power=10)],
    ).save_as(cfg.base.path(cfg.base.genesis_file))
    return cfg


def _check(spec, method, result):
    info = spec["methods"][method]
    assert isinstance(result, (dict, list)), \
        f"{method}: result must be structured, got {type(result)}"
    if isinstance(result, dict):
        for key in info["result_required"]:
            assert key in result, \
                f"{method}: missing required field {key!r} " \
                f"(got {sorted(result)})"
        for field, subkeys in info.get("nested_required", {}).items():
            sub = result.get(field)
            assert isinstance(sub, dict), \
                f"{method}: {field} must be an object"
            for key in subkeys:
                assert key in sub, \
                    f"{method}.{field}: missing {key!r}"


class TestRPCContract:
    def test_every_documented_method(self):
        from cometbft_tpu.node.node import Node
        from cometbft_tpu.rpc.client import HTTPClient

        with open(SPEC) as f:
            spec = json.load(f)

        async def run():
            with tempfile.TemporaryDirectory() as d:
                node = Node(_make_node_cfg(d))
                await node.start()
                try:
                    cli = HTTPClient(
                        f"http://{node._rpc_server.listen_addr}",
                        timeout=30.0)
                    # commit a tx so tx/tx_search/evidence paths have
                    # data to return
                    res = await cli.broadcast_tx_commit(b"spec=ok")
                    tx_hash = res["hash"]
                    for _ in range(200):
                        if node.height >= 4:
                            break
                        await asyncio.sleep(0.02)

                    tx64 = base64.b64encode(b"probe=1").decode()
                    args = {
                        "abci_query": {"path": "/store",
                                       "data": b"spec".hex()},
                        "broadcast_tx_sync": {"tx": tx64},
                        "broadcast_tx_async": {"tx": base64.b64encode(
                            b"probe=2").decode()},
                        "broadcast_tx_commit": {"tx": base64.b64encode(
                            b"probe=3").decode()},
                        "block": {"height": "2"},
                        "block_results": {"height": "2"},
                        "commit": {"height": "2"},
                        "blockchain": {"minHeight": "1",
                                       "maxHeight": "3"},
                        "validators": {"height": "2"},
                        "consensus_params": {"height": "2"},
                        "tx": {"hash": tx_hash},
                        "tx_search": {"query": "tx.height >= 1"},
                        "block_search": {
                            "query": "block.height >= 1"},
                        "pruning_set_block_retain_height":
                            {"height": "2"},
                    }
                    # block_by_hash needs a real hash
                    blk = await cli.call("block", height="2")
                    args["block_by_hash"] = {
                        "hash": "0x" + blk["block_id"]["hash"]}
                    # broadcast_evidence: use forged-but-valid dup-vote
                    # evidence via the manifest helper's building blocks
                    skipped = {"broadcast_evidence"}

                    checked = 0
                    for method in spec["methods"]:
                        if method in skipped:
                            continue
                        result = await cli.call(
                            method, **args.get(method, {}))
                        _check(spec, method, result)
                        checked += 1
                    assert checked >= 24, f"only {checked} methods"
                finally:
                    await node.stop()
        asyncio.run(run())

    def test_spec_covers_every_served_route(self):
        """The spec and the served route table must not drift."""
        from cometbft_tpu.rpc import core

        with open(SPEC) as f:
            spec = json.load(f)

        class _Env:
            def __getattr__(self, name):
                return None
        routes = core.build_routes(_Env()) if hasattr(
            core, "build_routes") else None
        if routes is None:
            # route builder takes the env object
            fn = getattr(core, "routes", None) or \
                getattr(core, "make_routes", None)
            routes = fn(_Env())
        assert set(routes) == set(spec["methods"]), (
            sorted(set(routes) ^ set(spec["methods"])))
