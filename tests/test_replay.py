"""Crash/replay tests: ABCI handshake reconciliation and WAL catchup.

Reference test model: internal/consensus/replay_test.go (crash at every
boundary, restart, verify chain continues).
"""
import asyncio

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import AppConns
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config import test_config as _test_config
from cometbft_tpu.consensus.replay import Handshaker, catchup_replay
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.consensus.wal import WAL
from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.db import MemDB, SQLiteDB
from cometbft_tpu.state import make_genesis_state
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.store import Store
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.priv_validator import new_mock_pv
from cometbft_tpu.types.timestamp import Timestamp


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _genesis(n=1):
    pvs = [new_mock_pv() for _ in range(n)]
    doc = GenesisDoc(
        chain_id="replay-test",
        genesis_time=Timestamp(1700000000, 0),
        validators=[GenesisValidator(address=b"",
                                     pub_key=pv.get_pub_key(), power=10)
                    for pv in pvs],
    )
    return doc, pvs


async def _wait_height(bs, h, timeout=20.0):
    async def waiter():
        while bs.height < h:
            await asyncio.sleep(0.01)
    await asyncio.wait_for(waiter(), timeout)


class TestHandshake:
    def test_genesis_handshake_calls_init_chain(self):
        async def go():
            doc, pvs = _genesis()
            state = make_genesis_state(doc)
            app = KVStoreApplication()
            conns = AppConns(app)
            ss, bs = Store(MemDB()), BlockStore(MemDB())
            ss.save(state)
            h = Handshaker(ss, state, bs, doc)
            app_hash = await h.handshake(conns)
            # kvstore initial app hash = the version-0 state tree root
            # (genesis validators committed by InitChain)
            assert len(app_hash) == 32
            assert app_hash == app.tree.root(0)
            info = await conns.query.info(abci.InfoRequest())
            assert info.last_block_height == 0
        run(go())

    def test_app_behind_replays_blocks(self):
        async def go():
            doc, pvs = _genesis()
            state = make_genesis_state(doc)
            app_db = MemDB()
            app = KVStoreApplication(db=app_db)
            conns = AppConns(app)
            ss, bs = Store(MemDB()), BlockStore(MemDB())
            ss.save(state)
            # production flow: handshake (InitChain) before consensus —
            # genesis validators are part of the committed state tree,
            # so the replayed app must see the same InitChain
            await Handshaker(ss, state, bs, doc).handshake(conns)
            cfg = _test_config().consensus
            exec_ = BlockExecutor(ss, conns.consensus, block_store=bs)
            cs = ConsensusState(cfg, state, exec_, bs,
                                priv_validator=pvs[0])
            await cs.start()
            try:
                await _wait_height(bs, 3)
            finally:
                await cs.stop()
            final_state = ss.load()

            # "crash": restart with a FRESH app (lost all state), same
            # stores — handshake must replay blocks 1..N into the app
            app2 = KVStoreApplication(db=MemDB())
            conns2 = AppConns(app2)
            h = Handshaker(ss, final_state, bs, doc)
            app_hash = await h.handshake(conns2)
            assert h.n_blocks >= 3
            info = await conns2.query.info(abci.InfoRequest())
            assert info.last_block_height == bs.height
            assert app_hash == info.last_block_app_hash
        run(go())

    def test_app_synced_noop(self):
        async def go():
            doc, pvs = _genesis()
            state = make_genesis_state(doc)
            app_db = MemDB()
            app = KVStoreApplication(db=app_db)
            conns = AppConns(app)
            ss, bs = Store(MemDB()), BlockStore(MemDB())
            ss.save(state)
            cfg = _test_config().consensus
            exec_ = BlockExecutor(ss, conns.consensus, block_store=bs)
            cs = ConsensusState(cfg, state, exec_, bs,
                                priv_validator=pvs[0])
            await cs.start()
            try:
                await _wait_height(bs, 2)
            finally:
                await cs.stop()
            final_state = ss.load()
            # same app, already synced: no replaying
            app2 = KVStoreApplication(db=app_db)
            conns2 = AppConns(app2)
            h = Handshaker(ss, final_state, bs, doc)
            await h.handshake(conns2)
            assert h.n_blocks == 0
        run(go())


class TestWALCatchup:
    def test_restart_resumes_chain(self, tmp_path):
        async def go():
            doc, pvs = _genesis()
            wal_path = str(tmp_path / "wal")

            # run 1: produce some blocks with durable stores + WAL
            state = make_genesis_state(doc)
            app_db = SQLiteDB(str(tmp_path / "app.db"))
            sdb = SQLiteDB(str(tmp_path / "state.db"))
            bdb = SQLiteDB(str(tmp_path / "blocks.db"))
            app = KVStoreApplication(db=app_db)
            conns = AppConns(app)
            ss, bs = Store(sdb), BlockStore(bdb)
            ss.save(state)
            cfg = _test_config().consensus
            exec_ = BlockExecutor(ss, conns.consensus, block_store=bs)
            cs = ConsensusState(cfg, state, exec_, bs,
                                priv_validator=pvs[0],
                                wal=WAL(wal_path))
            await cs.start()
            try:
                await _wait_height(bs, 3)
            finally:
                await cs.stop()
            stopped_height = bs.height

            # run 2: restart from disk; handshake + WAL catchup, then
            # the chain continues past the stopped height
            state2 = ss.load()
            app2 = KVStoreApplication(db=app_db)
            conns2 = AppConns(app2)
            h = Handshaker(ss, state2, bs, doc)
            await h.handshake(conns2)
            exec2 = BlockExecutor(ss, conns2.consensus, block_store=bs)
            cs2 = ConsensusState(cfg, state2, exec2, bs,
                                 priv_validator=pvs[0],
                                 wal=WAL(wal_path))
            n = await catchup_replay(cs2, wal_path)
            assert n >= 0
            await cs2.start()
            try:
                await _wait_height(bs, stopped_height + 2)
            finally:
                await cs2.stop()
            assert bs.height >= stopped_height + 2
            # chain is continuous: every height has a block linking back
            for hh in range(2, bs.height + 1):
                b = bs.load_block(hh)
                prev = bs.load_block(hh - 1)
                assert b.header.last_block_id.hash == prev.hash()
        run(go())
