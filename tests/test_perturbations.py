"""E2E perturbations: kill / disconnect / restart validators under tx
load on a real-socket testnet.

Reference: test/e2e/runner/perturb.go (kill, pause, disconnect, restart
stages run against a live testnet while load.go injects txs) — where
consensus bugs live.
"""
import asyncio

import pytest

from cometbft_tpu.abci.client import AppConns
from cometbft_tpu.abci.kvstore import DEFAULT_LANES, KVStoreApplication
from cometbft_tpu.config import MempoolConfig
from cometbft_tpu.config import test_config as _test_config
from cometbft_tpu.consensus.reactor import ConsensusReactor
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.db import MemDB
from cometbft_tpu.mempool import CListMempool
from cometbft_tpu.p2p.key import NodeKey
from cometbft_tpu.p2p.switch import Switch
from cometbft_tpu.state import make_genesis_state
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.store import Store
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.priv_validator import new_mock_pv
from cometbft_tpu.types.timestamp import Timestamp


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


class PerturbableNode:
    """A validator whose consensus+p2p can be killed and restarted on
    its durable stores (the in-process analog of docker kill/start)."""

    def __init__(self, doc, pv):
        self.doc = doc
        self.pv = pv
        self.app = KVStoreApplication()
        self.conns = AppConns(self.app)
        self.state_store = Store(MemDB())
        self.block_store = BlockStore(MemDB())
        self.state_store.save(make_genesis_state(doc))
        self.node_key = NodeKey.generate()
        self.cs = None
        self.switch = None
        self.mempool = None
        self.running = False

    async def start(self):
        state = self.state_store.load()
        self.mempool = CListMempool(
            MempoolConfig(), self.conns.mempool, lanes=DEFAULT_LANES,
            default_lane="default",
            height=state.last_block_height)
        ex = BlockExecutor(self.state_store, self.conns.consensus,
                           mempool=self.mempool,
                           block_store=self.block_store)
        self.cs = ConsensusState(
            _test_config().consensus, state, ex, self.block_store,
            priv_validator=self.pv)
        self.switch = Switch(self.node_key, self.doc.chain_id,
                             listen_addr="127.0.0.1:0")
        self.switch.add_reactor(ConsensusReactor(self.cs))
        await self.switch.start()
        await self.cs.start()
        self.running = True

    async def kill(self):
        """Hard stop (reference: perturb.go kill)."""
        await self.cs.stop()
        await self.switch.stop()
        self.running = False

    async def disconnect(self):
        """Sever every p2p link, keep consensus running (reference:
        perturb.go disconnect)."""
        for peer in list(self.switch.peers.values()):
            await self.switch.stop_peer(peer, "perturbation")

    @property
    def height(self):
        return self.block_store.height


async def _make_net(n=4):
    pvs = [new_mock_pv() for _ in range(n)]
    doc = GenesisDoc(
        chain_id="perturb-net", genesis_time=Timestamp(1700000000, 0),
        validators=[GenesisValidator(address=b"",
                                     pub_key=pv.get_pub_key(), power=10)
                    for pv in pvs])
    nodes = [PerturbableNode(doc, pv) for pv in pvs]
    for node in nodes:
        await node.start()
    await _connect_full_mesh(nodes)
    return nodes


async def _connect_full_mesh(nodes):
    alive = [n for n in nodes if n.running]
    for i, node in enumerate(alive):
        for other in alive[i + 1:]:
            if not any(p.remote_addr == other.switch.listen_addr
                       for p in node.switch.peers.values()):
                try:
                    await node.switch.dial_peer(
                        other.switch.listen_addr)
                except Exception:
                    pass


async def _load(nodes, stop_event):
    """Background tx injection (reference: runner/load.go)."""
    i = 0
    while not stop_event.is_set():
        for n in nodes:
            if n.running and n.mempool is not None:
                try:
                    await n.mempool.check_tx(f"load{i}=v".encode())
                except Exception:
                    pass
            i += 1
        await asyncio.sleep(0.02)


async def _wait_height(nodes, h, timeout=90.0):
    async def waiter():
        while not all(n.height >= h for n in nodes):
            await asyncio.sleep(0.02)
    await asyncio.wait_for(waiter(), timeout)


class TestPerturbations:
    def test_kill_one_validator_net_stays_live(self):
        """3/4 validators (>2/3 power) keep committing after a kill."""
        async def go():
            nodes = await _make_net(4)
            stop = asyncio.Event()
            load = asyncio.ensure_future(_load(nodes, stop))
            try:
                await _wait_height(nodes, 2)
                await nodes[3].kill()
                survivors = nodes[:3]
                h0 = max(n.height for n in survivors)
                await _wait_height(survivors, h0 + 4)
                # blocks after the kill carry only 3 commit sigs
                b = survivors[0].block_store.load_block(h0 + 3)
                signed = sum(1 for s in b.last_commit.signatures
                             if s.for_block())
                assert 3 <= signed <= 4
            finally:
                stop.set()
                load.cancel()
                for n in nodes:
                    if n.running:
                        await n.kill()
        run(go())

    def test_killed_validator_restarts_and_catches_up(self):
        """Kill -> survivors advance -> restart on the same stores ->
        WAL-less in-proc node rejoins via consensus catchup gossip."""
        async def go():
            nodes = await _make_net(4)
            stop = asyncio.Event()
            load = asyncio.ensure_future(_load(nodes, stop))
            try:
                await _wait_height(nodes, 2)
                victim = nodes[3]
                await victim.kill()
                survivors = nodes[:3]
                h0 = max(n.height for n in survivors)
                await _wait_height(survivors, h0 + 3)

                await victim.start()
                await _connect_full_mesh(nodes)
                target = max(n.height for n in survivors) + 2
                await _wait_height(nodes, target)
                # the restarted node is on the SAME chain
                h = min(n.height for n in nodes)
                assert victim.block_store.load_block(h).hash() == \
                    nodes[0].block_store.load_block(h).hash()
            finally:
                stop.set()
                load.cancel()
                for n in nodes:
                    if n.running:
                        await n.kill()
        run(go())

    def test_disconnect_then_reconnect(self):
        """A disconnected validator stalls, the rest advance; after
        reconnect it catches back up (reference: perturb.go
        disconnect)."""
        async def go():
            nodes = await _make_net(4)
            stop = asyncio.Event()
            load = asyncio.ensure_future(_load(nodes, stop))
            try:
                await _wait_height(nodes, 2)
                victim = nodes[0]
                await victim.disconnect()
                # sever the other direction too
                for other in nodes[1:]:
                    for peer in list(other.switch.peers.values()):
                        if peer.remote_addr == \
                                victim.switch.listen_addr or \
                                peer.id == victim.node_key.id:
                            await other.switch.stop_peer(
                                peer, "perturbation")
                survivors = nodes[1:]
                h0 = max(n.height for n in survivors)
                await _wait_height(survivors, h0 + 3)
                assert victim.height < max(n.height
                                           for n in survivors)

                await _connect_full_mesh(nodes)
                target = max(n.height for n in survivors) + 2
                await _wait_height(nodes, target)
            finally:
                stop.set()
                load.cancel()
                for n in nodes:
                    if n.running:
                        await n.kill()
        run(go())
