"""Flight recorder (libs/tracing.py): rings, dumps, report, RPC, and
the live-testnet per-height timeline acceptance check.

Covers:
  * span/instant recording, strict monotonic ordering, height and
    category filters, ring-buffer bounding;
  * the disabled path as a true no-op (<1µs per span call — the
    always-on budget);
  * crash dumps: supervisor give-up and the nemesis safety-assertion
    failure leave parseable JSON records (the nemesis one names the
    conflicting-commit heights), rendered by tools/trace_report.py;
  * the /trace RPC handler;
  * the bounded signature cache (LRU cap + hit/evict counters);
  * live 4-validator net: /trace?height=H returns consensus step
    spans, a batch-verify dispatch span, and p2p send/recv events,
    strictly ordered.
"""
import asyncio
import importlib.util
import json
import os
import time

import pytest

from cometbft_tpu.libs import tracing
from cometbft_tpu.libs.supervisor import RestartPolicy, Supervisor
from cometbft_tpu.libs.tracing import Recorder
from cometbft_tpu.types.signature_cache import (
    SignatureCache, SignatureCacheValue,
)

_ROOT = os.path.join(os.path.dirname(__file__), "..")


def _load_trace_report():
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(_ROOT, "tools",
                                     "trace_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


@pytest.fixture
def recorder(tmp_path):
    """Fresh process-global recorder pointed at tmp; restores the old
    one afterwards."""
    old = tracing.set_recorder(
        Recorder(buffer_size=65536, dump_dir=str(tmp_path)))
    yield tracing.recorder()
    tracing.set_recorder(old)


class TestRecorder:
    def test_spans_and_instants_strictly_ordered(self, recorder):
        with tracing.span(tracing.CONSENSUS, "step:Propose",
                          height=5, round=0):
            tracing.instant(tracing.P2P, "recv", height=5, bytes=100)
        tracing.instant(tracing.CONSENSUS, "commit", height=5)
        evs = tracing.snapshot()
        # spans sort by their START time: the span opened before the
        # instant fired inside it
        assert [e["name"] for e in evs] == \
            ["step:Propose", "recv", "commit"]
        ts = [e["ts_ns"] for e in evs]
        assert ts == sorted(ts)
        span_ev = next(e for e in evs if e["name"] == "step:Propose")
        assert span_ev["dur_ns"] > 0
        assert span_ev["attrs"]["round"] == 0

    def test_height_and_category_filters(self, recorder):
        tracing.instant(tracing.CONSENSUS, "a", height=1)
        tracing.instant(tracing.CONSENSUS, "b", height=2)
        tracing.instant(tracing.CRYPTO, "c", height=2)
        assert {e["name"] for e in tracing.snapshot(height=2)} == \
            {"b", "c"}
        assert {e["name"]
                for e in tracing.snapshot(category=tracing.CRYPTO)
                } == {"c"}
        assert len(tracing.snapshot(limit=1)) == 1

    def test_height_context_inherited(self, recorder):
        tracing.set_height(7)
        tracing.instant(tracing.P2P, "send", bytes=1)
        with tracing.span(tracing.CRYPTO, "batch_verify", batch=4):
            pass
        assert all(e["height"] == 7 for e in tracing.snapshot())

    def test_ring_is_bounded(self, tmp_path):
        old = tracing.set_recorder(
            Recorder(buffer_size=16, dump_dir=str(tmp_path)))
        try:
            for i in range(100):
                tracing.instant(tracing.P2P, "send", seq=i)
            evs = tracing.snapshot()
            assert len(evs) == 16
            # the ring keeps the NEWEST events
            assert evs[-1]["attrs"]["seq"] == 99
        finally:
            tracing.set_recorder(old)

    def test_category_enable_list(self, tmp_path):
        old = tracing.set_recorder(
            Recorder(buffer_size=16, categories="consensus,crypto",
                     dump_dir=str(tmp_path)))
        try:
            tracing.instant(tracing.CONSENSUS, "a")
            tracing.instant(tracing.P2P, "b")
            with tracing.span(tracing.P2P, "c"):
                pass
            assert [e["name"] for e in tracing.snapshot()] == ["a"]
        finally:
            tracing.set_recorder(old)

    def test_span_records_error_attr(self, recorder):
        with pytest.raises(ValueError):
            with tracing.span(tracing.ABCI, "consensus/finalize"):
                raise ValueError("boom")
        (ev,) = tracing.snapshot()
        assert ev["attrs"]["error"] == "ValueError"

    def test_dump_is_parseable_and_atomic(self, recorder, tmp_path):
        tracing.instant(tracing.CONSENSUS, "commit", height=3)
        path = tracing.dump(reason="unit test!",
                            extra={"k": "v"})
        assert path and os.path.exists(path)
        assert not os.path.exists(path + ".tmp")
        with open(path) as f:
            record = json.load(f)
        assert record["reason"] == "unit test!"
        assert record["extra"] == {"k": "v"}
        assert record["events"][0]["name"] == "commit"


class TestDisabledOverhead:
    def test_noop_span_under_1us(self, tmp_path):
        """The always-on budget: with tracing disabled, a span call
        (create + enter + exit) must cost <1µs — the hot paths
        (per-packet p2p, per-vote consensus) run it unconditionally."""
        old = tracing.set_recorder(
            Recorder(enabled=False, dump_dir=str(tmp_path)))
        try:
            span = tracing.span
            n = 50_000
            best = float("inf")
            for _ in range(5):
                t0 = time.perf_counter()
                for _ in range(n):
                    with span("consensus", "x"):
                        pass
                best = min(best, (time.perf_counter() - t0) / n)
            assert best < 1e-6, f"{best * 1e9:.0f}ns per no-op span"
            assert tracing.snapshot() == []
        finally:
            tracing.set_recorder(old)

    def test_noop_instant_records_nothing(self, tmp_path):
        old = tracing.set_recorder(
            Recorder(enabled=False, dump_dir=str(tmp_path)))
        try:
            tracing.instant(tracing.P2P, "send", bytes=1)
            tracing.record_span(tracing.P2P, "x", 0, 1)
            assert tracing.snapshot() == []
        finally:
            tracing.set_recorder(old)


class TestSupervisorGiveupDump:
    def test_giveup_dumps_flight_record(self, recorder, tmp_path):
        async def go():
            sup = Supervisor("t")

            async def boom():
                raise RuntimeError("kaput")

            st = sup.spawn(boom, name="boom", kind="boom",
                           policy=RestartPolicy(max_restarts=0))
            await st.wait()
            return st

        st = run(go())
        assert st.gave_up
        path = recorder.last_dump_path
        assert path and os.path.exists(path)
        with open(path) as f:
            record = json.load(f)
        assert "supervisor_giveup" in record["reason"]
        assert record["extra"]["kind"] == "boom"
        assert any(e["name"] == "giveup"
                   for e in record["events"])


class TestNemesisSafetyDump:
    def test_conflicting_commits_dump_heights(self, recorder,
                                              tmp_path):
        from nemesis import NemesisNet

        class _Block:
            def __init__(self, h):
                self._h = h

            def hash(self):
                return self._h

        class _Store:
            def __init__(self, blocks):
                self._b = blocks

            def load_block(self, h):
                return self._b.get(h)

        class _Node:
            def __init__(self, idx, blocks):
                self.idx = idx
                self.block_store = _Store(blocks)
                self.height = max(blocks, default=0)

        net = object.__new__(NemesisNet)
        net.nodes = [
            _Node(0, {1: _Block(b"\xaa" * 32), 2: _Block(b"\xcc" * 32)}),
            _Node(1, {1: _Block(b"\xbb" * 32), 2: _Block(b"\xcc" * 32)}),
        ]
        with pytest.raises(AssertionError) as ei:
            net.assert_no_conflicting_commits()
        assert "SAFETY VIOLATION" in str(ei.value)
        path = recorder.last_dump_path
        assert path and os.path.exists(path)
        with open(path) as f:
            record = json.load(f)
        # the dump names the conflicting heights (height 2 agreed)
        assert record["extra"]["conflicting_heights"] == [1]
        assert "aa" * 4 in json.dumps(record["extra"]["conflicts"])
        # and the report renders it
        report = _load_trace_report().render_report(record)
        assert "conflicting-commit heights: [1]" in report

    def test_agreeing_commits_do_not_dump(self, recorder):
        from nemesis import NemesisNet

        class _Node:
            def __init__(self, idx):
                self.idx = idx
                self.height = 0
                self.block_store = type(
                    "S", (), {"load_block":
                              staticmethod(lambda h: None)})()

        net = object.__new__(NemesisNet)
        net.nodes = [_Node(0), _Node(1)]
        net.assert_no_conflicting_commits()
        assert recorder.last_dump_path == ""


class TestTraceReport:
    def test_per_height_breakdown(self, recorder):
        base = tracing.now_ns()
        # height 4: propose step, proposal completes, crypto batch,
        # abci finalize, save_block
        tracing.record_span(tracing.CONSENSUS, "step:Propose",
                            base, base + 10_000_000, height=4)
        recorder.record_instant(tracing.CONSENSUS,
                                "proposal_complete", 4, None)
        tracing.record_span(tracing.CRYPTO, "batch_verify",
                            base + 2_000_000, base + 5_000_000,
                            height=4, batch=128, backend="cpu")
        tracing.record_span(tracing.ABCI, "consensus/finalize_block",
                            base + 6_000_000, base + 9_000_000,
                            height=4)
        tracing.record_span(tracing.CONSENSUS, "save_block",
                            base + 9_000_000, base + 9_500_000,
                            height=4)
        mod = _load_trace_report()
        record = {"events": tracing.snapshot()}
        rows = mod.analyze(record)
        assert 4 in rows
        r = rows[4]
        assert r["verify_ms"] == pytest.approx(3.0)
        assert r["execute_ms"] == pytest.approx(3.0)
        assert r["commit_ms"] == pytest.approx(0.5)
        assert r["batches"][0]["batch"] == 128
        assert r["batches"][0]["backend"] == "cpu"
        text = mod.render_report(record)
        assert "verify_ms" in text and "batch=128" in text

    def test_heightless_events_attributed_by_window(self, recorder):
        base = tracing.now_ns()
        tracing.record_span(tracing.CONSENSUS, "step:Prevote",
                            base, base + 10_000_000, height=9)
        # a crypto span with NO height, inside height 9's window
        recorder.record(tracing.CRYPTO, "kernel_execute",
                        base + 1_000_000, base + 2_000_000, -1, None)
        mod = _load_trace_report()
        evs = tracing.snapshot()
        for e in evs:       # strip the height for the crypto event
            if e["category"] == "crypto":
                e["height"] = 0
        rows = mod.analyze({"events": evs})
        assert rows[9]["verify_ms"] == pytest.approx(1.0)


class TestTraceRPC:
    def test_trace_route(self, recorder):
        from cometbft_tpu.rpc import core
        tracing.instant(tracing.CONSENSUS, "commit", height=12)
        tracing.instant(tracing.P2P, "send", height=13)
        routes = core.routes(None)
        resp = run(routes["trace"](height="12"))
        assert resp["enabled"] is True
        assert resp["count"] == 1
        (ev,) = resp["events"]
        assert ev["name"] == "commit"
        assert ev["height"] == "12"          # int64-as-string
        resp_all = run(routes["trace"]())
        assert resp_all["count"] == 2
        resp_cat = run(routes["trace"](height="0", category="p2p"))
        assert resp_cat["count"] == 1

    def test_pprof_trace_dump(self, recorder, tmp_path):
        from cometbft_tpu.libs.pprof import _trace_dump
        tracing.instant(tracing.CONSENSUS, "commit", height=1)
        body = json.loads(_trace_dump(False))
        assert body["events"][0]["name"] == "commit"
        body = json.loads(_trace_dump(True))
        assert os.path.exists(body["dump_path"])


class TestSignatureCacheLRU:
    def test_lru_cap_and_counters(self):
        c = SignatureCache(capacity=3)
        for i in range(4):
            c.add(bytes([i]) * 64,
                  SignatureCacheValue(b"a", bytes([i])))
        assert len(c) == 3
        assert c.evictions == 1
        assert c.get(b"\x00" * 64) is None       # evicted (oldest)
        assert c.get(b"\x03" * 64) is not None
        assert c.misses == 1 and c.hits == 1

    def test_get_refreshes_recency(self):
        c = SignatureCache(capacity=2)
        c.add(b"a" * 64, SignatureCacheValue(b"a", b"1"))
        c.add(b"b" * 64, SignatureCacheValue(b"b", b"2"))
        assert c.get(b"a" * 64) is not None      # refresh a
        c.add(b"c" * 64, SignatureCacheValue(b"c", b"3"))
        assert c.get(b"b" * 64) is None          # b evicted, not a
        assert c.get(b"a" * 64) is not None

    def test_default_capacity_configurable(self):
        from cometbft_tpu.types import signature_cache as sc
        old = sc.DEFAULT_CAPACITY
        try:
            sc.set_default_capacity(5)
            assert SignatureCache().capacity == 5
        finally:
            sc.set_default_capacity(old)


# ---------------------------------------------------------------------
# acceptance: live testnet timeline

class TestLiveNetTrace:
    def test_trace_height_timeline_on_live_net(self, recorder):
        """/trace?height=H on a running 4-validator net over real
        sockets: consensus step spans, >=1 batch-verify dispatch span
        (with batch size and backend), and p2p send/recv events, all
        strictly ordered by monotonic timestamp."""
        from test_testnet import _make_net, _wait_all_height

        from cometbft_tpu.rpc import core

        async def go():
            nodes = await _make_net(4)
            try:
                await _wait_all_height(nodes, 3)
            finally:
                for n in nodes:
                    await n.stop()

        run(go())
        routes = core.routes(None)
        # pick a height that fully played out
        resp = run(routes["trace"](height="2"))
        evs = resp["events"]
        names = [(e["category"], e["name"]) for e in evs]
        assert any(n.startswith("step:") for _, n in names
                   if _ == "consensus"), names
        batch = [e for e in evs if e["category"] == "crypto"
                 and e["name"] == "batch_verify"]
        assert batch, names
        assert batch[0]["attrs"]["batch"] >= 2
        assert batch[0]["attrs"]["backend"] in (
            "cpu", "tpu", "bls_native")
        assert any(c == "p2p" and n == "send" for c, n in names)
        assert any(c == "p2p" and n == "recv" for c, n in names)
        ts = [int(e["ts_ns"]) for e in evs]
        assert ts == sorted(ts)
        # the report renders a breakdown for this height
        report = _load_trace_report().render_report(
            {"events": tracing.snapshot()}, height=2)
        assert "gossip_ms" in report
