"""State/execution tests: genesis state, BlockStore round-trips, and the
full propose → validate → apply loop against the kvstore app.
"""
import asyncio

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import AppConns
from cometbft_tpu.abci.kvstore import KVStoreApplication, \
    make_val_set_change_tx
from cometbft_tpu.crypto import batch as crypto_batch, ed25519
from cometbft_tpu.db import MemDB
from cometbft_tpu.state import State, make_genesis_state
from cometbft_tpu.state.execution import BlockExecutor, tx_results_hash
from cometbft_tpu.state.store import Store
from cometbft_tpu.state.validation import (
    BlockValidationError, validate_block,
)
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.commit import Commit, CommitSig, ExtendedCommit
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.priv_validator import MockPV, new_mock_pv
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.vote import BLOCK_ID_FLAG_COMMIT, Vote
from cometbft_tpu.types import canonical


def run(coro):
    return asyncio.get_event_loop_policy().new_event_loop() \
        .run_until_complete(coro)


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


def _genesis(n_vals=3, power=10, chain_id="exec-test"):
    pvs = [new_mock_pv() for _ in range(n_vals)]
    doc = GenesisDoc(
        chain_id=chain_id,
        genesis_time=Timestamp(1700000000, 0),
        validators=[GenesisValidator(address=b"",
                                     pub_key=pv.get_pub_key(),
                                     power=power) for pv in pvs],
    )
    state = make_genesis_state(doc)
    # order pvs to match the sorted validator set
    by_addr = {pv.get_pub_key().address(): pv for pv in pvs}
    pvs = [by_addr[v.address] for v in state.validators.validators]
    return doc, state, pvs


def _sign_commit(chain_id, valset, pv_by_addr, height, block_id,
                 time=None) -> ExtendedCommit:
    """Validators with a known key precommit block_id; others absent."""
    from cometbft_tpu.types.commit import ExtendedCommitSig
    sigs = []
    for i, v in enumerate(valset.validators):
        pv = pv_by_addr.get(v.address)
        if pv is None:
            sigs.append(ExtendedCommitSig(timestamp=Timestamp.zero()))
            continue
        ts = time or Timestamp(1700000000 + height, 0)
        vote = Vote(type=canonical.PRECOMMIT_TYPE, height=height,
                    round=0, block_id=block_id, timestamp=ts,
                    validator_address=v.address, validator_index=i)
        pv.sign_vote(chain_id, vote, sign_extension=False)
        sigs.append(ExtendedCommitSig(
            block_id_flag=BLOCK_ID_FLAG_COMMIT,
            validator_address=v.address, timestamp=ts,
            signature=vote.signature))
    return ExtendedCommit(height=height, round=0, block_id=block_id,
                          extended_signatures=sigs)


async def _run_chain(n_blocks=3, txs_fn=None, extra_pvs=()):
    doc, state, pvs = _genesis()
    pv_by_addr = {pv.get_pub_key().address(): pv
                  for pv in list(pvs) + list(extra_pvs)}
    app = KVStoreApplication()
    conns = AppConns(app)
    state_store = Store(MemDB())
    block_store = BlockStore(MemDB())
    state_store.save(state)

    exec_ = BlockExecutor(state_store, conns.consensus,
                          block_store=block_store)

    # InitChain
    await conns.consensus.init_chain(abci.InitChainRequest(
        chain_id=doc.chain_id, initial_height=doc.initial_height,
        validators=[], time=doc.genesis_time))

    last_ext_commit = ExtendedCommit(height=0, round=0)
    heights = []
    for h in range(1, n_blocks + 1):
        proposer = state.validators.get_proposer()
        txs = (txs_fn(h) if txs_fn else [f"k{h}=v{h}".encode()])
        block = await exec_.create_proposal_block(
            h, state, last_ext_commit, proposer.address)
        # nop mempool gives empty txs; splice ours in for the test
        block = state.make_block(h, txs, last_ext_commit.to_commit(),
                                 [], proposer.address,
                                 block_time=block.header.time)
        parts = block.make_part_set()
        block_id = BlockID(hash=block.hash(),
                           part_set_header=parts.header())
        assert await exec_.process_proposal(block, state)
        validate_block(state, block)
        vals_at_h = state.validators   # the set that signs height h
        state = await exec_.apply_block(state, block_id, block)
        ext = _sign_commit(doc.chain_id, vals_at_h, pv_by_addr, h,
                           block_id)
        block_store.save_block(block, parts, ext.to_commit())
        last_ext_commit = ext
        heights.append(h)
    return doc, state, app, state_store, block_store, heights


class TestChainExecution:
    def test_three_blocks(self):
        doc, state, app, ss, bs, heights = run(_run_chain(3))
        assert state.last_block_height == 3
        assert bs.height == 3
        assert bs.base == 1
        # app hash progressed
        assert state.app_hash != b""
        # query works
        async def q():
            return await app.query(abci.QueryRequest(data=b"k2"))
        assert run(q()).value == b"v2"

    def test_block_store_roundtrip(self):
        doc, state, app, ss, bs, heights = run(_run_chain(2))
        b1 = bs.load_block(1)
        assert b1 is not None
        assert b1.header.height == 1
        assert b1.data.txs == [b"k1=v1"]
        meta = bs.load_block_meta(1)
        assert meta.header.chain_id == doc.chain_id
        assert bs.load_block_by_hash(b1.hash()).header.height == 1
        # commit for height 1 was stored from block 2's LastCommit
        c1 = bs.load_block_commit(1)
        assert c1 is not None and c1.height == 1
        sc = bs.load_seen_commit(2)
        assert sc is not None and sc.height == 2

    def test_state_store_roundtrip(self):
        doc, state, app, ss, bs, heights = run(_run_chain(2))
        loaded = ss.load()
        assert loaded.last_block_height == 2
        assert loaded.chain_id == doc.chain_id
        assert loaded.validators.hash() == state.validators.hash()
        assert loaded.app_hash == state.app_hash
        # historical validators retrievable
        v1 = ss.load_validators(1)
        assert v1.size() == 3
        p1 = ss.load_consensus_params(1)
        assert p1.block.max_bytes == state.consensus_params.block.max_bytes

    def test_finalize_block_response_persisted(self):
        doc, state, app, ss, bs, heights = run(_run_chain(2))
        r = ss.load_finalize_block_response(1)
        assert r is not None
        assert len(r.tx_results) == 1
        assert r.app_hash != b""

    def test_validator_update_applies_at_h_plus_2(self):
        new_pv = new_mock_pv()
        vtx = make_val_set_change_tx(
            "ed25519", new_pv.get_pub_key().bytes(), 5)

        def txs_fn(h):
            return [vtx] if h == 1 else [f"k{h}=v{h}".encode()]

        doc, state, app, ss, bs, heights = run(
            _run_chain(3, txs_fn, extra_pvs=[new_pv]))
        # update from height 1 lands in NextValidators after block 1,
        # i.e. Validators at height 3
        assert state.validators.size() == 4
        addrs = {v.address for v in state.validators.validators}
        assert new_pv.get_pub_key().address() in addrs

    def test_wrong_app_hash_rejected(self):
        doc, state, pvs = _genesis()
        app = KVStoreApplication()
        conns = AppConns(app)
        ss = Store(MemDB())
        ss.save(state)
        exec_ = BlockExecutor(ss, conns.consensus)
        proposer = state.validators.get_proposer()
        block = state.make_block(1, [], Commit(), [], proposer.address)
        block.header.app_hash = b"\x99" * 32   # wrong
        block.fill_header()
        with pytest.raises(BlockValidationError, match="AppHash"):
            validate_block(state, block)

    def test_last_commit_verified(self):
        # block 2 with a corrupted LastCommit signature must fail
        async def go():
            doc, state, pvs = _genesis()
            app = KVStoreApplication()
            conns = AppConns(app)
            ss = Store(MemDB())
            ss.save(state)
            exec_ = BlockExecutor(ss, conns.consensus)
            proposer = state.validators.get_proposer()
            b1 = state.make_block(1, [], Commit(), [], proposer.address)
            ps1 = b1.make_part_set()
            bid1 = BlockID(hash=b1.hash(), part_set_header=ps1.header())
            vals1 = state.validators
            pv_by_addr = {pv.get_pub_key().address(): pv for pv in pvs}
            state = await exec_.apply_block(state, bid1, b1)
            ext = _sign_commit(doc.chain_id, vals1, pv_by_addr, 1, bid1)
            commit = ext.to_commit()
            commit.signatures[0].signature = bytes(64)
            proposer2 = state.validators.get_proposer()
            b2 = state.make_block(2, [], commit, [], proposer2.address)
            with pytest.raises(BlockValidationError):
                validate_block(state, b2)
        run(go())


class TestTxResultsHash:
    def test_deterministic_fields_only(self):
        r1 = [abci.ExecTxResult(code=0, data=b"x", log="nondet")]
        r2 = [abci.ExecTxResult(code=0, data=b"x", log="different")]
        assert tx_results_hash(r1) == tx_results_hash(r2)
        r3 = [abci.ExecTxResult(code=1, data=b"x")]
        assert tx_results_hash(r1) != tx_results_hash(r3)


class TestValidatorLoadCache:
    """The store's roll-forward cache must be BIT-IDENTICAL to a cold
    LoadValidators (reference: store.go LoadValidators does one
    increment_proposer_priority(height - stored) call; chained
    single-step increments re-run the rescale prologue and diverge
    when the stored priority spread exceeds the rescale window)."""

    @staticmethod
    def _store_with_pointers(vals, last_changed, upto):
        from cometbft_tpu.state.store import (
            Store, _validators_key, state_pb)
        from cometbft_tpu.wire.proto import encode
        st = Store(MemDB())
        st._db.set(_validators_key(last_changed),
                   encode(state_pb.VALIDATORS_INFO,
                          {"last_height_changed": last_changed,
                           "validator_set": vals.to_proto()}))
        for h in range(last_changed + 1, upto + 1):
            st._db.set(_validators_key(h),
                       encode(state_pb.VALIDATORS_INFO,
                              {"last_height_changed": last_changed}))
        return st

    def _check(self, powers, priorities, upto=40):
        from cometbft_tpu.types.validator_set import (
            Validator, ValidatorSet)

        keys = [ed25519.gen_priv_key().pub_key()
                for _ in powers]

        def mk():
            vs = ValidatorSet([
                Validator(address=k.address(), pub_key=k,
                          voting_power=p, proposer_priority=pr)
                for k, (p, pr) in zip(keys,
                                      zip(powers, priorities))])
            return vs

        warm = self._store_with_pointers(mk(), 1, upto)
        cold = self._store_with_pointers(mk(), 1, upto)
        for h in range(1, upto + 1):           # sequential (cached)
            got = warm.load_validators(h)
            cold._val_cache.clear()            # force the cold path
            want = cold.load_validators(h)
            assert [v.proposer_priority for v in got.validators] == \
                [v.proposer_priority for v in want.validators], \
                f"divergence at height {h}"
            assert got.get_proposer().address == \
                want.get_proposer().address

    def test_plain_priorities(self):
        self._check([100, 200, 300], [0, 0, 0])

    def test_spread_exceeding_rescale_window(self):
        # priority spread > 2x total power forces the rescale
        # prologue to matter (the adversarial case for chained
        # increments)
        self._check([10 ** 9, 10, 1000, 1000, 10 ** 9],
                    [5 * 10 ** 9, -5 * 10 ** 9, 0, 17, -3])

    def test_cache_invalidated_on_rewrite(self):
        from cometbft_tpu.types.validator_set import (
            Validator, ValidatorSet)
        k = ed25519.gen_priv_key().pub_key()
        vs = ValidatorSet([Validator(address=k.address(), pub_key=k,
                                     voting_power=5,
                                     proposer_priority=0)])
        st = self._store_with_pointers(vs, 1, 10)
        st.load_validators(7)
        assert 7 in st._val_cache
        st._save_validators(7, vs, 7)          # record rewritten
        assert 7 not in st._val_cache
