"""Types layer tests: validator set rotation (reference golden sequence),
commit construction + verification (single and batch CPU paths), header
hashing, part sets, evidence round-trips.
"""
import pytest

from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.crypto import ed25519
from cometbft_tpu.types import canonical
from cometbft_tpu.types.block import (
    Block, ConsensusVersion, Data, Header, make_block,
)
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.commit import Commit, CommitSig
from cometbft_tpu.types.evidence import DuplicateVoteEvidence
from cometbft_tpu.types.part_set import PartSet, PartSetHeader
from cometbft_tpu.types.signature_cache import SignatureCache
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validation import (
    Fraction, NotEnoughVotingPowerError, VerificationError, verify_commit,
    verify_commit_light, verify_commit_light_trusting,
)
from cometbft_tpu.types.validator import Validator
from cometbft_tpu.types.validator_set import ValidatorSet
from cometbft_tpu.types.vote import (
    BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT, BLOCK_ID_FLAG_NIL, Vote,
)


def _val(addr: bytes, power: int) -> Validator:
    return Validator(address=addr, pub_key=None, voting_power=power)


class TestProposerSelection:
    def test_golden_sequence(self):
        """Reference: validator_set_test.go TestProposerSelection1."""
        vset = ValidatorSet([
            _val(b"foo", 1000), _val(b"bar", 300), _val(b"baz", 330)])
        proposers = []
        for _ in range(99):
            proposers.append(vset.get_proposer().address.decode())
            vset.increment_proposer_priority(1)
        expected = (
            "foo baz foo bar foo foo baz foo bar foo foo baz foo foo bar "
            "foo baz foo foo bar foo foo baz foo bar foo foo baz foo bar "
            "foo foo baz foo foo bar foo baz foo foo bar foo baz foo foo "
            "bar foo baz foo foo bar foo baz foo foo foo baz bar foo foo "
            "foo baz foo bar foo foo baz foo bar foo foo baz foo bar foo "
            "foo baz foo bar foo foo baz foo foo bar foo baz foo foo bar "
            "foo baz foo foo bar foo baz foo foo").split()
        assert proposers == expected

    def test_equal_power_order_by_address(self):
        """Reference: TestProposerSelection2 — equal power goes in
        address order."""
        addrs = [bytes(19) + bytes([i]) for i in range(3)]
        vset = ValidatorSet([_val(a, 100) for a in addrs])
        for i in range(15):
            prop = vset.get_proposer()
            assert prop.address == addrs[i % 3], f"round {i}"
            vset.increment_proposer_priority(1)

    def test_priorities_centered(self):
        vset = ValidatorSet([_val(b"a" * 20, 10), _val(b"b" * 20, 20)])
        total = sum(v.proposer_priority for v in vset.validators)
        # centered: |avg| < n
        assert abs(total) < len(vset)

    def test_update_with_change_set(self):
        vset = ValidatorSet([_val(b"a" * 20, 10), _val(b"b" * 20, 20)])
        vset.update_with_change_set([_val(b"c" * 20, 30)])
        assert vset.size() == 3
        assert vset.total_voting_power() == 60
        # removal via zero power
        vset.update_with_change_set(
            [Validator(address=b"a" * 20, pub_key=None, voting_power=0)])
        assert vset.size() == 2
        assert vset.total_voting_power() == 50

    def test_sorted_by_power_desc_then_address(self):
        vset = ValidatorSet([
            _val(b"x" * 20, 10), _val(b"a" * 20, 30), _val(b"m" * 20, 30)])
        powers = [v.voting_power for v in vset.validators]
        assert powers == [30, 30, 10]
        assert vset.validators[0].address == b"a" * 20


def _make_keys(n):
    return [ed25519.gen_priv_key() for _ in range(n)]


def _make_commit_fixture(n=4, power=10, chain_id="test-chain", height=5,
                         absent=(), nil=()):
    privs = _make_keys(n)
    vals = [Validator.new(pk.pub_key(), power) for pk in privs]
    pairs = sorted(zip(vals, privs),
                   key=lambda vp: (-vp[0].voting_power, vp[0].address))
    vals = [p[0] for p in pairs]
    privs = [p[1] for p in pairs]
    vset = ValidatorSet(vals)
    block_id = BlockID(hash=b"\x12" * 32,
                       part_set_header=PartSetHeader(1, b"\x34" * 32))
    sigs = []
    for i, (val, priv) in enumerate(zip(vset.validators, privs)):
        if i in absent:
            sigs.append(CommitSig.absent())
            continue
        bid = BlockID() if i in nil else block_id
        flag = BLOCK_ID_FLAG_NIL if i in nil else BLOCK_ID_FLAG_COMMIT
        ts = Timestamp(1700000000 + i, 0)
        v = Vote(type=canonical.PRECOMMIT_TYPE, height=height, round=0,
                 block_id=bid, timestamp=ts,
                 validator_address=val.address, validator_index=i)
        sig = priv.sign(v.sign_bytes(chain_id))
        sigs.append(CommitSig(block_id_flag=flag,
                              validator_address=val.address,
                              timestamp=ts, signature=sig))
    commit = Commit(height=height, round=0, block_id=block_id,
                    signatures=sigs)
    return chain_id, vset, block_id, height, commit


@pytest.fixture(params=["cpu"])
def backend(request):
    crypto_batch.set_backend(request.param)
    yield request.param
    crypto_batch.set_backend("auto")


class TestVerifyCommit:
    def test_all_signed_ok(self, backend):
        chain_id, vset, bid, h, commit = _make_commit_fixture()
        verify_commit(chain_id, vset, bid, h, commit)

    def test_with_absent_ok(self, backend):
        chain_id, vset, bid, h, commit = _make_commit_fixture(absent=(3,))
        verify_commit(chain_id, vset, bid, h, commit)

    def test_insufficient_power(self, backend):
        chain_id, vset, bid, h, commit = _make_commit_fixture(
            absent=(1, 2, 3))
        with pytest.raises(NotEnoughVotingPowerError):
            verify_commit(chain_id, vset, bid, h, commit)

    def test_nil_votes_do_not_count(self, backend):
        chain_id, vset, bid, h, commit = _make_commit_fixture(nil=(1, 2))
        with pytest.raises(NotEnoughVotingPowerError):
            verify_commit(chain_id, vset, bid, h, commit)

    def test_bad_signature_detected(self, backend):
        chain_id, vset, bid, h, commit = _make_commit_fixture()
        commit.signatures[2].signature = bytes(64)
        with pytest.raises(VerificationError, match="wrong signature"):
            verify_commit(chain_id, vset, bid, h, commit)

    def test_wrong_height(self, backend):
        chain_id, vset, bid, h, commit = _make_commit_fixture()
        with pytest.raises(VerificationError, match="wrong height"):
            verify_commit(chain_id, vset, bid, h + 1, commit)

    def test_light_trusting(self, backend):
        chain_id, vset, bid, h, commit = _make_commit_fixture()
        verify_commit_light_trusting(chain_id, vset, commit,
                                     Fraction(1, 3))

    def test_light_with_cache(self, backend):
        chain_id, vset, bid, h, commit = _make_commit_fixture()
        cache = SignatureCache()
        verify_commit_light(chain_id, vset, bid, h, commit,
                            count_all_signatures=True, cache=cache)
        assert len(cache) == 4
        # second run is fully cached
        verify_commit_light(chain_id, vset, bid, h, commit,
                            count_all_signatures=True, cache=cache)


class TestCommit:
    def test_hash_deterministic(self):
        _, _, bid, h, commit = _make_commit_fixture()
        assert commit.hash() == Commit(
            height=h, round=0, block_id=bid,
            signatures=list(commit.signatures)).hash()

    def test_get_vote_roundtrip_sign_bytes(self):
        chain_id, vset, bid, h, commit = _make_commit_fixture()
        v = commit.get_vote(0)
        assert v.sign_bytes(chain_id) == commit.vote_sign_bytes(chain_id, 0)

    def test_median_time(self):
        chain_id, vset, bid, h, commit = _make_commit_fixture()
        mt = commit.median_time(vset)
        assert mt.seconds in range(1700000000, 1700000004)


class TestHeaderAndBlock:
    def _header(self):
        return Header(
            chain_id="test", height=3, time=Timestamp(1700000000, 0),
            last_block_id=BlockID(hash=b"\x01" * 32,
                                  part_set_header=PartSetHeader(
                                      1, b"\x02" * 32)),
            last_commit_hash=b"\x03" * 32, data_hash=b"\x04" * 32,
            validators_hash=b"\x05" * 32, next_validators_hash=b"\x06" * 32,
            consensus_hash=b"\x07" * 32, app_hash=b"\x08" * 32,
            last_results_hash=b"\x09" * 32, evidence_hash=b"\x0a" * 32,
            proposer_address=b"\x0b" * 20)

    def test_header_hash_deterministic(self):
        h1, h2 = self._header(), self._header()
        assert h1.hash() == h2.hash()
        assert len(h1.hash()) == 32
        h2.height = 4
        assert h1.hash() != h2.hash()

    def test_header_hash_empty_without_validators_hash(self):
        h = self._header()
        h.validators_hash = b""
        assert h.hash() == b""

    def test_block_roundtrip_via_parts(self):
        commit = Commit(
            height=2, round=0,
            block_id=BlockID(hash=b"\x01" * 32,
                             part_set_header=PartSetHeader(1, b"\x02" * 32)),
            signatures=[CommitSig.absent()])
        b = make_block(3, [b"tx1", b"tx2" * 1000], commit, [])
        b.header.chain_id = "test"
        b.header.validators_hash = b"\x05" * 32
        ps = b.make_part_set(1024)
        assert ps.is_complete()
        b2 = Block.from_parts(ps)
        assert b2.header.chain_id == "test"
        assert b2.data.txs == b.data.txs
        assert b2.hash() == b.hash()

    def test_part_set_add_and_verify(self):
        data = bytes(range(256)) * 40
        ps = PartSet.from_data(data, 1024)
        ps2 = PartSet(ps.header())
        for i in range(ps.total):
            assert ps2.add_part(ps.get_part(i))
            assert not ps2.add_part(ps.get_part(i))  # duplicate
        assert ps2.is_complete()
        assert ps2.assemble() == data

    def test_part_set_rejects_corrupt(self):
        from cometbft_tpu.types.part_set import Part, PartSetError
        data = b"\xaa" * 4096
        ps = PartSet.from_data(data, 1024)
        ps2 = PartSet(ps.header())
        good = ps.get_part(0)
        bad = Part(index=0, bytes_=b"\xbb" * 1024, proof=good.proof)
        with pytest.raises(PartSetError):
            ps2.add_part(bad)


class TestEvidence:
    def test_duplicate_vote_evidence(self):
        priv = ed25519.gen_priv_key()
        val = Validator.new(priv.pub_key(), 10)
        vset = ValidatorSet([val])
        bid1 = BlockID(hash=b"\x01" * 32,
                       part_set_header=PartSetHeader(1, b"\x02" * 32))
        bid2 = BlockID(hash=b"\x03" * 32,
                       part_set_header=PartSetHeader(1, b"\x04" * 32))
        votes = []
        for bid in (bid1, bid2):
            v = Vote(type=canonical.PREVOTE_TYPE, height=7, round=0,
                     block_id=bid, timestamp=Timestamp(1700000000, 0),
                     validator_address=val.address, validator_index=0)
            v.signature = priv.sign(v.sign_bytes("test"))
            votes.append(v)
        ev = DuplicateVoteEvidence.new(
            votes[0], votes[1], Timestamp(1700000001, 0), vset)
        ev.validate_basic()
        ev.validate_abci()
        assert ev.height == 7
        assert len(ev.hash()) == 32
        # round-trip
        from cometbft_tpu.types.evidence import evidence_from_proto_wrapped
        ev2 = evidence_from_proto_wrapped(ev.to_proto_wrapped())
        assert ev2.hash() == ev.hash()


class TestValidatorSetHash:
    def test_hash_changes_with_power(self):
        privs = _make_keys(3)
        vset1 = ValidatorSet(
            [Validator.new(p.pub_key(), 10) for p in privs])
        vset2 = ValidatorSet(
            [Validator.new(p.pub_key(), 11) for p in privs])
        assert vset1.hash() != vset2.hash()
        assert len(vset1.hash()) == 32

    def test_proto_roundtrip(self):
        privs = _make_keys(3)
        vset = ValidatorSet([Validator.new(p.pub_key(), 10) for p in privs])
        vset2 = ValidatorSet.from_proto(vset.to_proto())
        assert vset2.hash() == vset.hash()
        assert vset2.proposer.address == vset.proposer.address


class TestVoteSignBytesTemplate:
    def test_template_matches_full_marshal_across_flags_and_times(self):
        """commit.vote_sign_bytes's template-splice fast path must be
        byte-for-byte the canonical Vote.sign_bytes marshal for every
        flag variant and timestamp shape (incl. zero nanos / zero
        seconds edge encodings)."""
        bid = BlockID(hash=b"\x9a" * 32,
                      part_set_header=PartSetHeader(3, b"\xbc" * 32))
        times = [Timestamp(1700000000, 0), Timestamp(1700000000, 1),
                 Timestamp(0, 0), Timestamp(1, 999_999_999),
                 Timestamp(2**31, 5)]
        sigs = []
        for i, ts in enumerate(times):
            flag = (BLOCK_ID_FLAG_COMMIT if i % 3 != 1
                    else BLOCK_ID_FLAG_NIL)
            sigs.append(CommitSig(block_id_flag=flag,
                                  validator_address=bytes([i]) * 20,
                                  timestamp=ts, signature=b"\x01" * 64))
        commit = Commit(height=42, round=3, block_id=bid,
                        signatures=sigs)
        for chain in ("tmpl-chain", ""):
            for i in range(len(sigs)):
                want = commit.get_vote(i).sign_bytes(chain)
                got = commit.vote_sign_bytes(chain, i)
                assert got == want, (chain, i)
