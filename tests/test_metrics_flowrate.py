"""Metrics registry + token-bucket flow control.

Reference: libs/metrics + per-package metrics.go; internal/flowrate and
the MConnection rate caps (connection.go:27-44).
"""
import asyncio
import time

from cometbft_tpu.libs.flowrate import RateLimiter
from cometbft_tpu.libs.metrics import Registry, Timer


class TestMetrics:
    def test_counter_gauge_histogram_render(self):
        reg = Registry()
        c = reg.counter("consensus", "total_txs", "txs committed")
        c.add(5)
        c.inc()
        g = reg.gauge("mempool", "size", "pending txs")
        g.set(42)
        h = reg.histogram("consensus", "block_interval_seconds",
                          "time between blocks")
        h.observe(0.3)
        h.observe(1.7)
        out = reg.render()
        assert "cometbft_consensus_total_txs 6" in out
        assert "cometbft_mempool_size 42" in out
        assert 'cometbft_consensus_block_interval_seconds_bucket{le="0.5"} 1' \
            in out
        assert "cometbft_consensus_block_interval_seconds_count 2" in out
        assert "# TYPE cometbft_consensus_total_txs counter" in out

    def test_labels(self):
        reg = Registry()
        c = reg.counter("p2p", "message_send_bytes_total", "bytes",
                        labels=("chID",))
        c.with_labels("0x20").add(100)
        c.with_labels("0x21").add(50)
        c.with_labels("0x20").add(1)
        out = reg.render()
        assert 'cometbft_p2p_message_send_bytes_total{chID="0x20"} 101' \
            in out
        assert 'cometbft_p2p_message_send_bytes_total{chID="0x21"} 50' \
            in out

    def test_register_idempotent(self):
        reg = Registry()
        a = reg.gauge("consensus", "height", "h")
        b = reg.gauge("consensus", "height", "h")
        assert a is b

    def test_timer(self):
        reg = Registry()
        h = reg.histogram("state", "block_processing_seconds", "t")
        with Timer(h):
            time.sleep(0.01)
        assert h._count == 1
        assert h._sum >= 0.01


class TestRateLimiter:
    def test_unlimited(self):
        async def run():
            lim = RateLimiter(0)
            t0 = time.monotonic()
            for _ in range(100):
                await lim.take(10_000_000)
            assert time.monotonic() - t0 < 0.5
            assert lim.total == 100 * 10_000_000
        asyncio.run(run())

    def test_limits_throughput(self):
        """Pushing 3x the bucket through a 100kB/s limiter must take
        ~2s beyond the initial burst."""
        async def run():
            lim = RateLimiter(100_000)      # 100 kB/s, 100 kB burst
            t0 = time.monotonic()
            for _ in range(30):
                await lim.take(10_000)      # 300 kB total
            elapsed = time.monotonic() - t0
            assert elapsed >= 1.5, f"rate not enforced ({elapsed:.2f}s)"
            assert elapsed < 4.0
        asyncio.run(run())

    def test_try_take(self):
        lim = RateLimiter(1000, burst=1000)
        assert lim.try_take(800)
        assert not lim.try_take(800)       # bucket nearly empty
        time.sleep(0.3)
        assert lim.try_take(200)           # ~300 tokens refilled
