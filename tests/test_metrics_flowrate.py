"""Metrics registry + token-bucket flow control.

Reference: libs/metrics + per-package metrics.go; internal/flowrate and
the MConnection rate caps (connection.go:27-44).
"""
import pytest
import asyncio
import time

from cometbft_tpu.libs.flowrate import RateLimiter
from cometbft_tpu.libs.metrics import Registry, Timer


class TestMetrics:
    def test_counter_gauge_histogram_render(self):
        reg = Registry()
        c = reg.counter("consensus", "total_txs", "txs committed")
        c.add(5)
        c.inc()
        g = reg.gauge("mempool", "size", "pending txs")
        g.set(42)
        h = reg.histogram("consensus", "block_interval_seconds",
                          "time between blocks")
        h.observe(0.3)
        h.observe(1.7)
        out = reg.render()
        assert "cometbft_consensus_total_txs 6" in out
        assert "cometbft_mempool_size 42" in out
        assert 'cometbft_consensus_block_interval_seconds_bucket{le="0.5"} 1' \
            in out
        assert "cometbft_consensus_block_interval_seconds_count 2" in out
        assert "# TYPE cometbft_consensus_total_txs counter" in out

    def test_labels(self):
        reg = Registry()
        c = reg.counter("p2p", "message_send_bytes_total", "bytes",
                        labels=("chID",))
        c.with_labels("0x20").add(100)
        c.with_labels("0x21").add(50)
        c.with_labels("0x20").add(1)
        out = reg.render()
        assert 'cometbft_p2p_message_send_bytes_total{chID="0x20"} 101' \
            in out
        assert 'cometbft_p2p_message_send_bytes_total{chID="0x21"} 50' \
            in out

    def test_register_idempotent(self):
        reg = Registry()
        a = reg.gauge("consensus", "height", "h")
        b = reg.gauge("consensus", "height", "h")
        assert a is b

    def test_timer(self):
        reg = Registry()
        h = reg.histogram("state", "block_processing_seconds", "t")
        with Timer(h):
            time.sleep(0.01)
        assert h._count == 1
        assert h._sum >= 0.01


class TestRateLimiter:
    def test_unlimited(self):
        async def run():
            lim = RateLimiter(0)
            t0 = time.monotonic()
            for _ in range(100):
                await lim.take(10_000_000)
            assert time.monotonic() - t0 < 0.5
            assert lim.total == 100 * 10_000_000
        asyncio.run(run())

    def test_limits_throughput(self):
        """Pushing 3x the bucket through a 100kB/s limiter must take
        ~2s beyond the initial burst."""
        async def run():
            lim = RateLimiter(100_000)      # 100 kB/s, 100 kB burst
            t0 = time.monotonic()
            for _ in range(30):
                await lim.take(10_000)      # 300 kB total
            elapsed = time.monotonic() - t0
            assert elapsed >= 1.5, f"rate not enforced ({elapsed:.2f}s)"
            assert elapsed < 4.0
        asyncio.run(run())

    def test_try_take(self):
        lim = RateLimiter(1000, burst=1000)
        assert lim.try_take(800)
        assert not lim.try_take(800)       # bucket nearly empty
        time.sleep(0.3)
        assert lim.try_take(200)           # ~300 tokens refilled


class TestNodeMetricsEndpoint:
    def test_metrics_served_from_live_node(self):
        """GET /metrics on a running node exposes consensus/mempool/p2p
        series (reference: node/node.go prometheusSrv)."""
        import os
        import tempfile

        from cometbft_tpu.config import Config
        from cometbft_tpu.node.node import Node
        from cometbft_tpu.p2p.key import NodeKey
        from cometbft_tpu.privval import FilePV
        from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
        from cometbft_tpu.types.timestamp import Timestamp

        async def run():
            with tempfile.TemporaryDirectory() as d:
                home = os.path.join(d, "node")
                cfg = Config()
                cfg.base.home = home
                cfg.p2p.laddr = "tcp://127.0.0.1:0"
                cfg.rpc.laddr = "tcp://127.0.0.1:0"
                cfg.consensus.timeout_commit_ns = 50_000_000
                os.makedirs(os.path.join(home, "config"), exist_ok=True)
                os.makedirs(os.path.join(home, "data"), exist_ok=True)
                pv = FilePV.generate(
                    cfg.base.path(cfg.base.priv_validator_key_file),
                    cfg.base.path(cfg.base.priv_validator_state_file))
                NodeKey.load_or_gen(cfg.base.path(cfg.base.node_key_file))
                GenesisDoc(
                    chain_id="metrics-chain",
                    genesis_time=Timestamp.now(),
                    validators=[GenesisValidator(
                        address=b"", pub_key=pv.get_pub_key(),
                        power=10)],
                ).save_as(cfg.base.path(cfg.base.genesis_file))
                node = Node(cfg)
                await node.start()
                try:
                    for _ in range(300):
                        if node.height >= 3:
                            break
                        await asyncio.sleep(0.02)
                    await asyncio.sleep(0.1)   # let the watcher observe
                    host, port = node._rpc_server.listen_addr.rsplit(
                        ":", 1)
                    reader, writer = await asyncio.open_connection(
                        host, int(port))
                    writer.write(b"GET /metrics HTTP/1.1\r\n"
                                 b"Host: x\r\nConnection: close\r\n\r\n")
                    await writer.drain()
                    raw = await reader.read(-1)
                    writer.close()
                    body = raw.split(b"\r\n\r\n", 1)[1].decode()
                    assert "cometbft_consensus_height" in body
                    h = [ln for ln in body.splitlines()
                         if ln.startswith("cometbft_consensus_height ")]
                    assert h and float(h[0].split()[-1]) >= 3
                    assert "cometbft_consensus_block_interval_seconds_count" \
                        in body
                    assert "cometbft_mempool_size" in body
                finally:
                    await node.stop()
        asyncio.run(run())


class TestPrunerAndWALRotation:
    def test_pruner_prunes_to_min_retain(self):
        """Reference state/pruner.go: app + companion knobs, min wins,
        monotonicity enforced."""
        import tempfile

        from cometbft_tpu.db.db import MemDB
        from cometbft_tpu.state.pruner import Pruner

        class FakeBlockStore:
            def __init__(self):
                self.base = 1
                self.height = 100
            def prune_blocks(self, retain):
                pruned = retain - self.base
                self.base = retain
                return pruned, retain

        class FakeStateStore:
            def __init__(self):
                self.calls = []
            def prune_states(self, frm, to, ev):
                self.calls.append((frm, to, ev))
                return to - frm

        bs, ss = FakeBlockStore(), FakeStateStore()
        pr = Pruner(ss, bs, MemDB(), companion_enabled=True)
        pr.set_application_retain_height(50)
        # companion not set yet: nothing prunes
        assert pr.effective_retain_height() == 0
        assert pr.prune_once() == (0, 1)
        pr.set_companion_retain_height(30)
        assert pr.effective_retain_height() == 30
        pruned, base = pr.prune_once()
        assert (pruned, base) == (29, 30)
        # companion can't move backwards
        with pytest.raises(ValueError):
            pr.set_companion_retain_height(10)
        # app knob silently keeps its max
        pr.set_application_retain_height(20)
        assert pr.get_application_retain_height() == 50

    def test_wal_rotation_and_group_replay(self):
        import os
        import tempfile

        from cometbft_tpu.consensus.wal import WAL

        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "wal")
            w = WAL(path, head_size_limit=2048)
            for h in range(1, 30):
                for i in range(20):
                    w.write({"type": "vote", "height": h, "i": i,
                             "pad": "x" * 64})
                w.write_end_height(h)
            w.close()
            files = WAL.group_files(path)
            assert len(files) > 2, "no rotation happened"
            msgs = list(WAL.iter_group(path))
            ends = [m["height"] for m in msgs
                    if m.get("type") == "end_height"]
            assert ends == list(range(1, 30))
            # tail after a mid-group end-height spans files
            tail = WAL.search_for_end_height(path, 15)
            assert tail is not None
            assert tail[0]["height"] == 16
            assert WAL.search_for_end_height(path, 99) is None

    def test_repair_with_open_handle_writes_to_new_head(self):
        """Corruption in a ROTATED file makes repair rename the head
        to .corrupted; an already-open WAL must reopen so later writes
        land in the recreated head, not the renamed inode."""
        import os
        import tempfile

        from cometbft_tpu.consensus.wal import WAL, repair_wal_file

        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "wal")
            w = WAL(path, head_size_limit=1024)
            for h in range(1, 12):
                for i in range(10):
                    w.write({"type": "vote", "height": h, "i": i,
                             "pad": "x" * 48})
                w.write_end_height(h)
            w.flush_and_sync()
            rotated = WAL.group_files(path)[:-1]
            assert rotated, "needs at least one rotated file"
            # corrupt the first rotated file mid-way
            with open(rotated[0], "r+b") as f:
                f.seek(os.path.getsize(rotated[0]) // 2)
                f.write(b"\xff" * 16)
            repair_wal_file(path)
            w.reopen()                  # what node boot does
            w.write_sync({"type": "vote", "height": 99, "i": 0})
            w.close()
            msgs = list(WAL.iter_group(path))
            assert any(m.get("height") == 99 for m in msgs), \
                "post-repair write lost"
            assert os.path.getsize(path) > 0

    def test_wal_total_size_cap_drops_oldest(self):
        import os
        import tempfile

        from cometbft_tpu.consensus.wal import WAL

        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "wal")
            w = WAL(path, head_size_limit=1024,
                    total_size_limit=4096)
            for i in range(500):
                w.write({"type": "vote", "i": i, "pad": "y" * 64})
            w.close()
            rotated = WAL.group_files(path)[:-1]
            total = sum(os.path.getsize(f) for f in rotated)
            assert total <= 4096 + 1024
            # oldest file index is no longer 0
            assert int(rotated[0].rsplit(".", 1)[1]) > 0


class TestCryptoExtras:
    def test_secp256k1eth_eth_address_rule(self):
        from cometbft_tpu.crypto import secp256k1eth
        from cometbft_tpu.crypto._keccak import keccak256
        sk = secp256k1eth.gen_priv_key()
        pk = sk.pub_key()
        assert len(pk.bytes()) == 65 and pk.bytes()[0] == 0x04
        assert pk.address() == keccak256(pk.bytes()[1:])[12:]
        sig = sk.sign(b"eth msg")
        assert pk.verify_signature(b"eth msg", sig)
        assert not pk.verify_signature(b"eth msg!", sig)
        # high-S malleation rejected
        n = secp256k1eth._N
        s = int.from_bytes(sig[32:], "big")
        assert not pk.verify_signature(
            b"eth msg", sig[:32] + (n - s).to_bytes(32, "big"))

    def test_armor_roundtrip_and_tamper(self):

        from cometbft_tpu.crypto.armor import (
            ArmorError, decode_armor, encode_armor,
        )
        data = bytes(range(200))
        text = encode_armor("TENDERMINT PRIVATE KEY",
                            {"kdf": "bcrypt", "salt": "ABCD"}, data)
        btype, headers, out = decode_armor(text)
        assert btype == "TENDERMINT PRIVATE KEY"
        assert headers == {"kdf": "bcrypt", "salt": "ABCD"}
        assert out == data
        # flip a body byte -> CRC failure
        lines = text.split("\n")
        for i, ln in enumerate(lines):
            if ln and not ln.startswith(("-", "=")) and ":" not in ln:
                lines[i] = ("B" if ln[0] != "B" else "C") + ln[1:]
                break
        with pytest.raises(ArmorError):
            decode_armor("\n".join(lines))

    @pytest.mark.slow
    def test_bench_helpers(self):
        from cometbft_tpu.crypto import ed25519
        from cometbft_tpu.crypto.benchmarking import (
            bench_batch_verify, bench_sign, bench_verify,
        )
        assert bench_sign(ed25519.gen_priv_key(), iters=10) > 0
        assert bench_verify(ed25519.gen_priv_key(), iters=10) > 0
        assert bench_batch_verify(ed25519.gen_priv_key,
                                  batch_size=8, iters=1) > 0

    def test_step_duration_metrics_on_live_node(self):
        """consensus_step_duration_seconds appears with step labels."""
        import os
        import tempfile

        from cometbft_tpu.config import Config
        from cometbft_tpu.node.node import Node
        from cometbft_tpu.p2p.key import NodeKey
        from cometbft_tpu.privval import FilePV
        from cometbft_tpu.types.genesis import (
            GenesisDoc, GenesisValidator,
        )
        from cometbft_tpu.types.timestamp import Timestamp

        async def run():
            with tempfile.TemporaryDirectory() as d:
                home = os.path.join(d, "node")
                cfg = Config()
                cfg.base.home = home
                cfg.p2p.laddr = "tcp://127.0.0.1:0"
                cfg.rpc.laddr = ""
                cfg.consensus.timeout_commit_ns = 20_000_000
                os.makedirs(os.path.join(home, "config"), exist_ok=True)
                os.makedirs(os.path.join(home, "data"), exist_ok=True)
                pv = FilePV.generate(
                    cfg.base.path(cfg.base.priv_validator_key_file),
                    cfg.base.path(cfg.base.priv_validator_state_file))
                NodeKey.load_or_gen(cfg.base.path(cfg.base.node_key_file))
                GenesisDoc(
                    chain_id="step-chain",
                    genesis_time=Timestamp.now(),
                    validators=[GenesisValidator(
                        address=b"", pub_key=pv.get_pub_key(),
                        power=10)],
                ).save_as(cfg.base.path(cfg.base.genesis_file))
                node = Node(cfg)
                await node.start()
                try:
                    for _ in range(300):
                        if node.height >= 3:
                            break
                        await asyncio.sleep(0.02)
                    text = node.metrics_registry.render()
                    assert "cometbft_consensus_step_duration_seconds" \
                        in text
                    assert 'step="Propose"' in text or \
                        'step="Commit"' in text
                finally:
                    await node.stop()
        asyncio.run(run())


class TestPerSubsystemMetricsDepth:
    def test_loaded_node_exposes_50_plus_series(self):
        """VERDICT r2 #7: per-subsystem families fed at the point of
        action — a loaded 2-node net must expose >= 50 live series
        with the reference's metric names (consensus/mempool/p2p/
        blocksync/statesync/state/proxy metrics.go)."""
        import os
        import tempfile

        from cometbft_tpu.config import Config
        from cometbft_tpu.node.node import Node
        from cometbft_tpu.p2p.key import NodeKey
        from cometbft_tpu.privval import FilePV
        from cometbft_tpu.rpc.client import HTTPClient
        from cometbft_tpu.types.genesis import (
            GenesisDoc, GenesisValidator,
        )
        from cometbft_tpu.types.timestamp import Timestamp

        def mk(d, name, gen_doc=None, validators=None):
            home = os.path.join(d, name)
            cfg = Config()
            cfg.base.home = home
            cfg.p2p.laddr = "tcp://127.0.0.1:0"
            cfg.rpc.laddr = "tcp://127.0.0.1:0"
            cfg.p2p.allow_duplicate_ip = True
            cfg.consensus.timeout_commit_ns = 30_000_000
            os.makedirs(os.path.join(home, "config"), exist_ok=True)
            os.makedirs(os.path.join(home, "data"), exist_ok=True)
            pv = FilePV.generate(
                cfg.base.path(cfg.base.priv_validator_key_file),
                cfg.base.path(cfg.base.priv_validator_state_file))
            NodeKey.load_or_gen(cfg.base.path(cfg.base.node_key_file))
            return cfg, pv

        async def run():
            with tempfile.TemporaryDirectory() as d:
                cfg1, pv1 = mk(d, "n1")
                cfg2, pv2 = mk(d, "n2")
                gen = GenesisDoc(
                    chain_id="depth-chain",
                    genesis_time=Timestamp.now(),
                    validators=[
                        GenesisValidator(address=b"",
                                         pub_key=pv1.get_pub_key(),
                                         power=10),
                        GenesisValidator(address=b"",
                                         pub_key=pv2.get_pub_key(),
                                         power=10),
                    ])
                for cfg in (cfg1, cfg2):
                    gen.save_as(cfg.base.path(cfg.base.genesis_file))
                n1, n2 = Node(cfg1), Node(cfg2)
                await n1.start()
                await n2.start()
                try:
                    await n2.switch.dial_peer(n1.switch.listen_addr)
                    cli = HTTPClient(
                        f"http://{n1._rpc_server.listen_addr}",
                        timeout=30.0)
                    for i in range(5):
                        await cli.broadcast_tx_sync(b"m%d=v" % i)
                    for _ in range(400):
                        if n1.height >= 4:
                            break
                        await asyncio.sleep(0.02)
                    assert n1.height >= 4, "net did not progress"
                    body = n1.metrics_registry.render()
                    # distinct live sample lines (not HELP/TYPE)
                    samples = {
                        ln.split("{")[0].split(" ")[0]
                        for ln in body.splitlines()
                        if ln and not ln.startswith("#")}
                    lines = [ln for ln in body.splitlines()
                             if ln and not ln.startswith("#")]
                    assert len(lines) >= 50, \
                        f"only {len(lines)} live series"
                    for want in (
                            # consensus (metrics.go:190)
                            "cometbft_consensus_height",
                            "cometbft_consensus_rounds",
                            "cometbft_consensus_validators",
                            "cometbft_consensus_validators_power",
                            "cometbft_consensus_step_duration_seconds",
                            "cometbft_consensus_round_voting_power_percent",
                            "cometbft_consensus_block_parts",
                            "cometbft_consensus_proposal_create_count",
                            "cometbft_consensus_proposal_receive_count",
                            "cometbft_consensus_validator_last_signed_height",
                            # mempool
                            "cometbft_mempool_size",
                            "cometbft_mempool_size_bytes",
                            "cometbft_mempool_lane_size",
                            "cometbft_mempool_tx_size_bytes",
                            # p2p
                            "cometbft_p2p_peers",
                            "cometbft_p2p_message_send_bytes_total",
                            "cometbft_p2p_message_receive_bytes_total",
                            # syncing + state + proxy
                            "cometbft_blocksync_syncing",
                            "cometbft_statesync_syncing",
                            "cometbft_proxy_method_timing_seconds",
                    ):
                        assert any(s == want or s.startswith(
                            want + "_") for s in samples) or \
                            want in body, f"missing {want}"
                finally:
                    await n2.stop()
                    await n1.stop()
        asyncio.run(run())


class TestPprofEndpoint:
    def test_pprof_surfaces_on_live_node(self):
        """instrumentation.pprof_listen_addr serves the live
        profiling surface (reference: node.go pprofSrv,
        config.go:488-490): task dump, thread stacks, heap, and a
        short CPU profile."""
        import os
        import tempfile

        from cometbft_tpu.config import Config
        from cometbft_tpu.node.node import Node
        from cometbft_tpu.p2p.key import NodeKey
        from cometbft_tpu.privval import FilePV
        from cometbft_tpu.types.genesis import (
            GenesisDoc, GenesisValidator,
        )
        from cometbft_tpu.types.timestamp import Timestamp

        async def fetch(addr, path):
            host, port = addr.rsplit(":", 1)
            r, w = await asyncio.open_connection(host, int(port))
            w.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n"
                    .encode())
            await w.drain()
            raw = await r.read(-1)
            w.close()
            return raw.split(b"\r\n\r\n", 1)[1].decode()

        async def run():
            with tempfile.TemporaryDirectory() as d:
                home = os.path.join(d, "node")
                cfg = Config()
                cfg.base.home = home
                cfg.p2p.laddr = "tcp://127.0.0.1:0"
                cfg.rpc.laddr = "tcp://127.0.0.1:0"
                cfg.instrumentation.pprof_listen_addr = "127.0.0.1:0"
                cfg.consensus.timeout_commit_ns = 50_000_000
                os.makedirs(os.path.join(home, "config"),
                            exist_ok=True)
                os.makedirs(os.path.join(home, "data"), exist_ok=True)
                pv = FilePV.generate(
                    cfg.base.path(cfg.base.priv_validator_key_file),
                    cfg.base.path(cfg.base.priv_validator_state_file))
                NodeKey.load_or_gen(
                    cfg.base.path(cfg.base.node_key_file))
                GenesisDoc(
                    chain_id="pprof-chain",
                    genesis_time=Timestamp.now(),
                    validators=[GenesisValidator(
                        address=b"", pub_key=pv.get_pub_key(),
                        power=10)],
                ).save_as(cfg.base.path(cfg.base.genesis_file))
                node = Node(cfg)
                await node.start()
                try:
                    addr = node._pprof_server.listen_addr
                    idx = await fetch(addr, "/debug/pprof/")
                    assert "tasks" in idx and "profile" in idx
                    tasks = await fetch(addr, "/debug/pprof/tasks")
                    assert "asyncio tasks:" in tasks
                    # the consensus receive routine must be visible
                    # in the dump (the goroutine-dump analog)
                    threads = await fetch(addr,
                                          "/debug/pprof/threads")
                    assert "thread" in threads
                    heap = await fetch(addr, "/debug/pprof/heap")
                    assert "gc counts" in heap
                    prof = await fetch(
                        addr, "/debug/pprof/profile?seconds=0.3")
                    assert "cumulative" in prof
                finally:
                    await node.stop()
        asyncio.run(run())
