"""ABCI + kvstore + db tests."""
import asyncio

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import AppConns, LocalClient
from cometbft_tpu.abci.kvstore import (
    KVStoreApplication, assign_lane, is_valid_tx, make_val_set_change_tx,
    parse_validator_tx,
)
from cometbft_tpu.crypto import ed25519
from cometbft_tpu.db import MemDB, PrefixDB, SQLiteDB


def run(coro):
    return asyncio.get_event_loop_policy().new_event_loop() \
        .run_until_complete(coro)


async def _drive_blocks(app, txs_per_block, start_height=1):
    conns = AppConns(app)
    results = []
    h = start_height
    for txs in txs_per_block:
        r = await conns.consensus.finalize_block(
            abci.FinalizeBlockRequest(txs=txs, height=h))
        await conns.consensus.commit()
        results.append(r)
        h += 1
    return results


class TestKVStore:
    def test_check_tx_formats(self):
        app = KVStoreApplication()
        async def go():
            ok = await app.check_tx(abci.CheckTxRequest(tx=b"a=1"))
            assert ok.code == 0 and ok.lane_id
            ok2 = await app.check_tx(abci.CheckTxRequest(tx=b"a:1"))
            assert ok2.code == 0
            bad = await app.check_tx(abci.CheckTxRequest(tx=b"nosep"))
            assert bad.code != 0
            bad2 = await app.check_tx(abci.CheckTxRequest(tx=b"=x"))
            assert bad2.code != 0
        run(go())

    def test_lanes(self):
        assert assign_lane(b"22=1") == "foo"      # 22 % 11 == 0
        assert assign_lane(b"9=1") == "bar"       # 9 % 3 == 0
        assert assign_lane(b"5=1") == "default"
        assert assign_lane(b"abc=1") == "default"
        assert assign_lane(make_val_set_change_tx(
            "ed25519", b"\x01" * 32, 5)) == "val"

    def test_finalize_and_query(self):
        app = KVStoreApplication()
        async def go():
            await _drive_blocks(app, [[b"name=satoshi"], [b"x=1", b"y=2"]])
            q = await app.query(abci.QueryRequest(data=b"name"))
            assert q.value == b"satoshi"
            assert q.log == "exists"
            q2 = await app.query(abci.QueryRequest(data=b"missing"))
            assert q2.log == "does not exist"
            info = await app.info(abci.InfoRequest())
            assert info.last_block_height == 2
            # app hash is the committed state tree root
            assert len(info.last_block_app_hash) == 32
            assert info.last_block_app_hash == app.tree.root(2)
        run(go())

    def test_validator_updates(self):
        app = KVStoreApplication()
        pub = ed25519.gen_priv_key().pub_key()
        tx = make_val_set_change_tx("ed25519", pub.bytes(), 7)
        async def go():
            r = (await _drive_blocks(app, [[tx]]))[0]
            assert len(r.validator_updates) == 1
            assert r.validator_updates[0].power == 7
            vals = app.get_validators()
            assert len(vals) == 1 and vals[0].power == 7
            q = await app.query(abci.QueryRequest(
                path="/val", data=__import__("base64").b64encode(
                    pub.bytes())))
            assert q.value == b"7"
        run(go())

    def test_prepare_proposal_normalizes(self):
        app = KVStoreApplication()
        async def go():
            r = await app.prepare_proposal(abci.PrepareProposalRequest(
                txs=[b"a:1", b"b=2", b"bad"], max_tx_bytes=1 << 20))
            assert r.txs == [b"a=1", b"b=2"]
            p = await app.process_proposal(abci.ProcessProposalRequest(
                txs=[b"a=1"]))
            assert p.is_accepted()
            p2 = await app.process_proposal(abci.ProcessProposalRequest(
                txs=[b"a:1"]))
            assert not p2.is_accepted()
        run(go())

    def test_app_hash_changes_with_size(self):
        app = KVStoreApplication()
        async def go():
            r1 = (await _drive_blocks(app, [[b"a=1"]]))[0]
            r2 = (await _drive_blocks(app, [[b"b=2"]], 2))[0]
            assert r1.app_hash != r2.app_hash
        run(go())

    def test_persistence(self, tmp_path):
        db = SQLiteDB(str(tmp_path / "kv.db"))
        app = KVStoreApplication(db=db)
        async def go():
            await _drive_blocks(app, [[b"k=v"]])
        run(go())
        app2 = KVStoreApplication(db=db)
        async def go2():
            info = await app2.info(abci.InfoRequest())
            assert info.last_block_height == 1
            q = await app2.query(abci.QueryRequest(data=b"k"))
            assert q.value == b"v"
        run(go2())


class TestDB:
    @pytest.mark.parametrize("mk", [
        lambda p: MemDB(), lambda p: SQLiteDB(str(p / "t.db"))])
    def test_crud_and_iteration(self, tmp_path, mk):
        db = mk(tmp_path)
        db.set(b"b", b"2")
        db.set(b"a", b"1")
        db.set(b"c", b"3")
        assert db.get(b"a") == b"1"
        assert db.has(b"b")
        db.delete(b"b")
        assert not db.has(b"b")
        assert list(db.iterator()) == [(b"a", b"1"), (b"c", b"3")]
        db.set(b"b", b"2")
        assert [k for k, _ in db.iterator(b"b")] == [b"b", b"c"]
        assert [k for k, _ in db.iterator(None, b"c")] == [b"a", b"b"]
        assert [k for k, _ in db.reverse_iterator()] == [b"c", b"b", b"a"]

    def test_batch(self, tmp_path):
        db = SQLiteDB(str(tmp_path / "b.db"))
        b = db.new_batch()
        b.set(b"x", b"1")
        b.set(b"y", b"2")
        b.delete(b"x")
        b.write()
        assert db.get(b"y") == b"2"
        assert db.get(b"x") is None

    def test_prefixdb(self):
        base = MemDB()
        p = PrefixDB(base, b"pre/")
        p.set(b"k", b"v")
        assert base.get(b"pre/k") == b"v"
        assert p.get(b"k") == b"v"
        base.set(b"other", b"z")
        assert list(p.iterator()) == [(b"k", b"v")]

    def test_empty_key_rejected(self):
        from cometbft_tpu.db import DBError
        db = MemDB()
        with pytest.raises(DBError):
            db.set(b"", b"v")


class TestBaseApplication:
    def test_defaults(self):
        app = abci.BaseApplication()
        async def go():
            r = await app.prepare_proposal(abci.PrepareProposalRequest(
                txs=[b"123", b"456", b"789"], max_tx_bytes=7))
            assert r.txs == [b"123", b"456"]
            fb = await app.finalize_block(abci.FinalizeBlockRequest(
                txs=[b"a", b"b"], height=1))
            assert len(fb.tx_results) == 2
            pp = await app.process_proposal(abci.ProcessProposalRequest())
            assert pp.is_accepted()
        run(go())


class TestEquivocationPunishmentDedup:
    def test_two_offences_one_validator_single_update(self):
        """Two duplicate-vote evidences against ONE validator in one
        block must produce a single validator update (duplicate
        entries in validator_updates are a consensus failure) with
        the power reduced per offence."""
        import asyncio

        from cometbft_tpu.abci import types as abci
        from cometbft_tpu.abci.kvstore import KVStoreApplication
        from cometbft_tpu.crypto import ed25519
        from cometbft_tpu.types.timestamp import Timestamp

        async def run():
            app = KVStoreApplication()
            pub = ed25519.gen_priv_key().pub_key()
            addr = pub.address()
            await app.init_chain(abci.InitChainRequest(
                time=Timestamp.now(), chain_id="dedup",
                validators=[abci.ValidatorUpdate(
                    power=10, pub_key_type="ed25519",
                    pub_key_bytes=pub.bytes())],
                app_state_bytes=b"", initial_height=1))
            mb = [abci.Misbehavior(
                type=abci.MISBEHAVIOR_TYPE_DUPLICATE_VOTE,
                validator=abci.ABCIValidator(address=addr, power=10),
                height=1, time=Timestamp.now(),
                total_voting_power=10) for _ in range(2)]
            resp = await app.finalize_block(abci.FinalizeBlockRequest(
                txs=[], misbehavior=mb, height=2,
                time=Timestamp.now()))
            updates = [u for u in resp.validator_updates
                       if u.pub_key_bytes == pub.bytes()]
            assert len(updates) == 1, "duplicate validator updates"
            assert updates[0].power == 8     # one unit per offence
        asyncio.run(run())
