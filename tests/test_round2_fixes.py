"""Round-2 correctness fixes: non-RP extension signatures, proto-size
budgeting, replay of timeout records.

Reference behaviors: types/vote.go VerifyExtension (:280-299) requires
both extension signatures; types/tx.go ComputeProtoSizeForTxs budgets
per-tx framing; internal/consensus/replay.go:142 replays timeoutInfo.
"""
import pytest

from cometbft_tpu.crypto import ed25519
from cometbft_tpu.types import canonical
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.priv_validator import new_mock_pv
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.tx import compute_proto_size_overhead
from cometbft_tpu.types.vote import InvalidSignatureError, Vote, VoteError


def _block_vote(pv, extension=b"ext", non_rp=b"nrp"):
    addr = pv.get_pub_key().address()
    return Vote(
        type=canonical.PRECOMMIT_TYPE, height=5, round=0,
        block_id=BlockID(hash=b"\x11" * 32,
                         part_set_header=PartSetHeader(1, b"\x22" * 32)),
        timestamp=Timestamp(1_700_000_000, 0),
        validator_address=addr, validator_index=0,
        extension=extension, non_rp_extension=non_rp)


class TestNonRpExtensionSignatures:
    def test_signer_produces_both_signatures(self):
        pv = new_mock_pv()
        v = _block_vote(pv)
        pv.sign_vote("chain", v, sign_extension=True)
        assert v.extension_signature
        assert v.non_rp_extension_signature
        v.verify_extension("chain", pv.get_pub_key())
        v.verify_vote_and_extension("chain", pv.get_pub_key())

    def test_forged_non_rp_extension_rejected(self):
        pv = new_mock_pv()
        v = _block_vote(pv)
        pv.sign_vote("chain", v, sign_extension=True)
        v.non_rp_extension = b"forged"
        with pytest.raises(InvalidSignatureError):
            v.verify_extension("chain", pv.get_pub_key())

    def test_missing_non_rp_signature_rejected(self):
        pv = new_mock_pv()
        v = _block_vote(pv)
        pv.sign_vote("chain", v, sign_extension=True)
        v.non_rp_extension_signature = b""
        with pytest.raises(InvalidSignatureError):
            v.verify_extension("chain", pv.get_pub_key())

    def test_validate_basic_requires_signature_pairing(self):
        pv = new_mock_pv()
        v = _block_vote(pv)
        pv.sign_vote("chain", v, sign_extension=True)
        v.validate_basic()
        v.non_rp_extension_signature = b""
        with pytest.raises(VoteError):
            v.validate_basic()

    def test_file_pv_signs_non_rp(self, tmp_path):
        from cometbft_tpu.privval.file import FilePV
        pv = FilePV.generate(str(tmp_path / "key.json"),
                             str(tmp_path / "state.json"))
        v = _block_vote(pv)
        v.validator_address = pv.get_pub_key().address()
        pv.sign_vote("chain", v, sign_extension=True)
        assert v.non_rp_extension_signature
        v.verify_extension("chain", pv.get_pub_key())


class TestProtoSizeBudget:
    def test_overhead_formula(self):
        # 1-byte tag + varint(len)
        assert compute_proto_size_overhead(0) == 2
        assert compute_proto_size_overhead(127) == 2
        assert compute_proto_size_overhead(128) == 3
        assert compute_proto_size_overhead(20_000) == 4

    def test_reap_respects_encoded_size(self):
        import asyncio
        from cometbft_tpu.abci.client import AppConns
        from cometbft_tpu.abci.kvstore import (
            DEFAULT_LANES, KVStoreApplication,
        )
        from cometbft_tpu.config import MempoolConfig
        from cometbft_tpu.mempool.mempool import CListMempool

        async def run():
            conns = AppConns(KVStoreApplication())
            mp = CListMempool(MempoolConfig(), conns.mempool,
                              lanes=DEFAULT_LANES, default_lane="default")
            txs = [(f"k{i}=" + "v" * 100).encode() for i in range(4)]
            for tx in txs:
                await mp.check_tx(tx)
            budget = sum(len(t) for t in txs[:2]) + \
                sum(compute_proto_size_overhead(len(t)) for t in txs[:2])
            reaped = mp.reap_max_bytes_max_gas(budget, -1)
            got = sum(len(t) + compute_proto_size_overhead(len(t))
                      for t in reaped)
            assert got <= budget
            # raw-size accounting would have squeezed in a 3rd tx
            assert len(reaped) == 2
        asyncio.run(run())
