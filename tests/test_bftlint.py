"""bftlint: the AST invariant linter gates tier-1.

Three duties:

  * run ``bftlint check`` clean over cometbft_tpu/ — the tier-1 gate
    (new findings fail CI; grandfathered ones live in
    bftlint_baseline.json with justifications);
  * prove every rule fires on its known-bad fixture and stays quiet
    on its known-good (incl. suppressed) fixture;
  * carry the invariant of the retired
    tests/test_supervised_tasks_ast.py: the supervised-spawn scope
    still covers every reactor, and an injected bare ``create_task``
    still trips.
"""
import glob
import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), ".."))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.bftlint import baseline as baseline_mod  # noqa: E402
from tools.bftlint import lint_paths  # noqa: E402
from tools.bftlint.checkers import ALL_CHECKERS  # noqa: E402
from tools.bftlint.core import FileContext  # noqa: E402

FIXTURES = os.path.join(os.path.dirname(__file__),
                        "bftlint_fixtures")
PKG = os.path.join(REPO_ROOT, "cometbft_tpu")
BASELINE = os.path.join(REPO_ROOT, "bftlint_baseline.json")
RULES = sorted(c.rule for c in ALL_CHECKERS)


def _lint_file(path, rules=None):
    return lint_paths([path], ALL_CHECKERS, rules=rules).findings


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.bftlint", *args],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)


# ---------------------------------------------------------------------
# the tier-1 gate: the repo lints clean

class TestRepoGate:
    def test_all_nine_rules_registered(self):
        assert RULES == sorted((
            "supervised-spawn", "monotonic-clock",
            "swallowed-exception", "yield-in-loop",
            "await-atomicity", "blocking-in-async",
            "unbounded-label", "cwd-write", "wire-tag"))

    def test_package_check_is_clean(self):
        """`python -m tools.bftlint check` exits 0 on the repo with
        all 8 rules active — THE gate that wires bftlint into
        tier-1."""
        proc = _cli("check", "--format", "json")
        assert proc.returncode == 0, \
            f"bftlint check failed:\n{proc.stdout}\n{proc.stderr}"
        report = json.loads(proc.stdout)
        assert report["rules"] == RULES
        assert report["counts"]["new"] == 0
        assert not report["parse_errors"]
        assert report["files_scanned"] > 100

    def test_no_stale_baseline_entries(self):
        """A fixed site must shrink the baseline, not rot in it."""
        result = lint_paths([PKG], ALL_CHECKERS)
        diff = baseline_mod.diff(result.findings,
                                 baseline_mod.load(BASELINE))
        assert not diff.stale, \
            (f"stale baseline entries (rerun `python -m tools.bftlint"
             f" baseline`): {diff.stale}")

    def test_baseline_entries_all_justified(self):
        """Every grandfathered finding carries a real one-line
        justification, not the placeholder."""
        base = baseline_mod.load(BASELINE)
        assert base, "baseline unexpectedly empty"
        for fp, entry in base.items():
            assert entry["justification"] != \
                baseline_mod.DEFAULT_JUSTIFICATION, \
                f"placeholder justification for {fp}"


# ---------------------------------------------------------------------
# per-rule fixtures: every rule trips on bad, passes good

@pytest.mark.parametrize("rule", RULES)
def test_bad_fixture_triggers(rule):
    path = os.path.join(FIXTURES,
                        f"bad_{rule.replace('-', '_')}.py")
    assert os.path.exists(path), f"missing bad fixture for {rule}"
    found = {f.rule for f in _lint_file(path)}
    assert rule in found, \
        f"{rule} did not fire on its bad fixture (found: {found})"


@pytest.mark.parametrize("rule", RULES)
def test_good_fixture_passes(rule):
    path = os.path.join(FIXTURES,
                        f"good_{rule.replace('-', '_')}.py")
    assert os.path.exists(path), f"missing good fixture for {rule}"
    findings = _lint_file(path)
    assert not findings, \
        f"good fixture for {rule} flagged: {findings}"


def test_cli_exits_nonzero_on_each_bad_fixture():
    for rule in RULES:
        rel = os.path.join("tests", "bftlint_fixtures",
                           f"bad_{rule.replace('-', '_')}.py")
        proc = _cli("check", rel, "--no-baseline")
        assert proc.returncode == 1, \
            (f"check on {rel} exited {proc.returncode}; "
             f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")


# ---------------------------------------------------------------------
# await-atomicity strengthened semantics: the transition seam

class TestAwaitAtomicitySeam:
    """The pipelined-commit refactor (docs/pipeline.md) routes every
    post-await RoundState mutation through the transition seam
    (round_state.py) and strengthened the rule: a store after an
    await is a finding even without a prior load of the same
    attribute; the seam (which re-validates at the store) and
    post-await guards are the sanctioned alternatives."""

    def _lint_src(self, tmp_path, src):
        p = tmp_path / "fx.py"
        p.write_text(
            "# bftlint: path=cometbft_tpu/consensus/fx_state.py\n"
            + src)
        return [f for f in _lint_file(str(p))
                if f.rule == "await-atomicity"]

    def test_blind_store_after_await_fires(self, tmp_path):
        found = self._lint_src(tmp_path, (
            "class C:\n"
            "    async def go(self, r):\n"
            "        rs = self.rs\n"
            "        await self.sign(r)\n"
            "        rs.round = r\n"))
        assert found, "store-after-await without a load must fire"

    def test_seam_call_after_await_clean(self, tmp_path):
        found = self._lint_src(tmp_path, (
            "class C:\n"
            "    async def go(self, r):\n"
            "        rs = self.rs\n"
            "        await self.sign(r)\n"
            "        rs.advance(r, 4)\n"
            "        rs.begin_round(r, self.vals)\n"
            "        rs.reset_proposal_parts(self.psh)\n"))
        assert not found, f"seam calls flagged: {found}"

    def test_guard_must_follow_last_await(self, tmp_path):
        found = self._lint_src(tmp_path, (
            "class C:\n"
            "    async def go(self, r):\n"
            "        rs = self.rs\n"
            "        if rs.round != r:\n"
            "            return\n"
            "        await self.sign(r)\n"
            "        await self.sign(r)\n"
            "        rs.round = r\n"))
        assert found, "pre-await guard must not sanction the store"
        found = self._lint_src(tmp_path, (
            "class C:\n"
            "    async def go(self, r):\n"
            "        rs = self.rs\n"
            "        await self.sign(r)\n"
            "        if rs.round != r:\n"
            "            return\n"
            "        rs.round = r\n"))
        assert not found, "post-await guard is re-validation"

    def test_transition_table_matches_roundstate_api(self):
        """The checker's seam table must name real RoundState
        methods, and every guarded attribute must be a real
        RoundState field — the allowlist cannot silently drift from
        the live API."""
        from cometbft_tpu.consensus.round_state import RoundState
        from tools.bftlint.checkers.await_atomicity import (
            _TRANSITION_GUARDS,
        )
        rs_fields = set(RoundState.__dataclass_fields__)
        for meth, attrs in _TRANSITION_GUARDS.items():
            assert callable(getattr(RoundState, meth, None)), \
                f"seam method {meth!r} missing from RoundState"
            for a in attrs:
                assert a in rs_fields, \
                    f"{meth} guards unknown field {a!r}"

    def test_seam_call_guards_its_validated_keys(self, tmp_path):
        """A seam call counts as re-validation for exactly the keys
        the transition checks — a same-region direct store to one of
        them passes, an unrelated key still fires."""
        found = self._lint_src(tmp_path, (
            "class C:\n"
            "    async def go(self, r):\n"
            "        rs = self.rs\n"
            "        await self.sign(r)\n"
            "        rs.advance(r, 4)\n"
            "        rs.round = r\n"))     # advance re-validated round
        assert not found, f"guarded key flagged: {found}"
        found = self._lint_src(tmp_path, (
            "class C:\n"
            "    async def go(self, r):\n"
            "        rs = self.rs\n"
            "        await self.sign(r)\n"
            "        rs.advance(r, 4)\n"
            "        rs.locked_round = r\n"))
        assert found, "advance() must not sanction locked_round"

    def test_await_atomicity_baseline_ratcheted_out(self):
        """The 4 grandfathered consensus/state.py straddles are gone
        for good: the seam replaced them, and no await-atomicity
        entry may ever come back (ratchet-down-only)."""
        base = baseline_mod.load(BASELINE)
        left = [fp for fp in base
                if fp.startswith("await-atomicity::")]
        assert not left, f"await-atomicity re-baselined: {left}"

    def test_state_py_round_mutations_use_seam(self):
        """consensus/state.py itself lints clean under the
        strengthened rule with no suppressions — the tentpole's
        single-writer claim, checked structurally."""
        path = os.path.join(PKG, "consensus", "state.py")
        found = [f for f in _lint_file(path,
                                       rules={"await-atomicity"})]
        assert not found, f"state.py straddles: {found}"
        src = open(path).read()
        assert "disable=await-atomicity" not in src


# ---------------------------------------------------------------------
# await-atomicity over reactor-side PeerState (ISSUE 12): prs stores
# are tracked like rs stores, with the PeerState seam as the guard

class TestAwaitAtomicityPeerState:
    BAD = os.path.join(FIXTURES, "bad_await_atomicity_peerstate.py")
    GOOD = os.path.join(FIXTURES, "good_await_atomicity_peerstate.py")

    def test_bad_peerstate_fixture_fires(self):
        found = [f for f in _lint_file(self.BAD)
                 if f.rule == "await-atomicity"]
        assert len(found) >= 3, \
            f"prs straddles not all flagged: {found}"
        keys = "".join(f.message for f in found)
        assert "prs.proposal_block_parts_header" in keys
        assert "prs.round" in keys

    def test_good_peerstate_fixture_passes(self):
        found = _lint_file(self.GOOD)
        assert not found, f"good prs fixture flagged: {found}"

    def test_ps_prs_alias_tracked(self, tmp_path):
        """The reactor idiom ``prs = ps.prs`` (base object is NOT
        self) must alias into the tracked base."""
        p = tmp_path / "fx.py"
        p.write_text(
            "# bftlint: path=cometbft_tpu/consensus/fx_reactor.py\n"
            "class R:\n"
            "    async def go(self, ps):\n"
            "        prs = ps.prs\n"
            "        await self.send(b'x')\n"
            "        prs.step = 1\n")
        found = [f for f in _lint_file(str(p))
                 if f.rule == "await-atomicity"]
        assert found, "ps.prs alias store-after-await must fire"

    def test_peerstate_seam_table_matches_api(self):
        """Every PeerState seam method the checker trusts must exist
        on the live PeerState, and every guarded attribute must be a
        real PeerRoundState field — no silent drift."""
        from cometbft_tpu.consensus.reactor import (
            PeerRoundState, PeerState,
        )
        from tools.bftlint.checkers.await_atomicity import (
            _PEERSTATE_GUARDS,
        )
        prs_fields = set(PeerRoundState.__dataclass_fields__)
        for meth, attrs in _PEERSTATE_GUARDS.items():
            assert callable(getattr(PeerState, meth, None)), \
                f"seam method {meth!r} missing from PeerState"
            for a in attrs:
                assert a in prs_fields, \
                    f"{meth} guards unknown field {a!r}"

    def test_reactor_py_lints_clean_no_suppressions(self):
        """consensus/reactor.py lints clean under the prs-tracking
        rule with no suppressions — the PeerState owner-discipline
        claim, checked structurally."""
        path = os.path.join(PKG, "consensus", "reactor.py")
        found = [f for f in _lint_file(
            path, rules={"await-atomicity"})]
        assert not found, f"reactor.py prs straddles: {found}"
        src = open(path).read()
        assert "disable=await-atomicity" not in src


# ---------------------------------------------------------------------
# the retired AST test's invariant, carried over

class TestSupervisedSpawnCarryover:
    """tests/test_supervised_tasks_ast.py is deleted in favor of the
    supervised-spawn checker; these lock the same semantics."""

    def test_scope_is_nonempty(self):
        # the glob must keep finding the reactors — a silent empty
        # scope would make the rule vacuous
        checker = next(c for c in ALL_CHECKERS
                       if c.rule == "supervised-spawn")
        in_scope = sorted(
            os.path.relpath(p, REPO_ROOT).replace(os.sep, "/")
            for p in glob.glob(os.path.join(PKG, "*", "reactor.py"))
            + [os.path.join(PKG, "node", "node.py"),
               os.path.join(PKG, "consensus", "state.py"),
               os.path.join(PKG, "p2p", "switch.py")])
        assert len(in_scope) >= 7, in_scope
        for rel in in_scope:
            # the literal paths must exist on disk — a renamed
            # state.py/switch.py would otherwise silently leave the
            # rule's scope (the retired AST test asserted this too)
            assert os.path.exists(os.path.join(REPO_ROOT, rel)), \
                f"{rel} is in supervised-spawn scope but missing"
            assert checker.in_scope(rel), \
                f"{rel} fell out of supervised-spawn scope"

    def test_injected_bare_create_task_trips(self, tmp_path):
        src = (
            "# bftlint: path=cometbft_tpu/injected/reactor.py\n"
            "import asyncio\n"
            "class R:\n"
            "    async def start(self):\n"
            "        asyncio.create_task(self._loop())\n")
        p = tmp_path / "injected_reactor.py"
        p.write_text(src)
        found = [f for f in _lint_file(str(p))
                 if f.rule == "supervised-spawn"]
        assert len(found) == 1
        assert "supervisor.spawn" in found[0].message

    def test_no_unsupervised_tasks_in_live_scope(self):
        """Zero supervised-spawn findings over the real tree — not
        even baselined ones (the old test's allowlist was empty)."""
        result = lint_paths([PKG], ALL_CHECKERS,
                            rules={"supervised-spawn"})
        assert not result.findings, result.findings


# ---------------------------------------------------------------------
# framework semantics: suppressions and baseline accounting

class TestFrameworkSemantics:
    def test_inline_suppression_same_line_and_preceding(self, tmp_path):
        src = (
            "def f(x):\n"
            "    try:\n"
            "        x()\n"
            "    except Exception:  # bftlint: disable=swallowed-exception\n"
            "        pass\n"
            "    try:\n"
            "        x()\n"
            "    # bftlint: disable=swallowed-exception\n"
            "    except Exception:\n"
            "        pass\n")
        p = tmp_path / "supp.py"
        p.write_text(src)
        assert _lint_file(str(p)) == []

    def test_file_level_suppression(self, tmp_path):
        src = (
            "# bftlint: disable-file=swallowed-exception\n"
            "def f(x):\n"
            "    try:\n"
            "        x()\n"
            "    except Exception:\n"
            "        pass\n")
        p = tmp_path / "suppfile.py"
        p.write_text(src)
        assert _lint_file(str(p)) == []

    def test_suppression_is_rule_specific(self, tmp_path):
        src = (
            "def f(x):\n"
            "    try:\n"
            "        x()\n"
            "    except Exception:  # bftlint: disable=cwd-write\n"
            "        pass\n")
        p = tmp_path / "wrongrule.py"
        p.write_text(src)
        assert [f.rule for f in _lint_file(str(p))] == \
            ["swallowed-exception"]

    def test_baseline_count_semantics(self, tmp_path):
        """N identical findings vs a count-1 entry: one baselined,
        the rest are new; an unmatched entry reports stale."""
        src = (
            "def f(x):\n"
            "    try:\n"
            "        x()\n"
            "    except Exception:\n"
            "        pass\n"
            "    try:\n"
            "        x()\n"
            "    except Exception:\n"
            "        pass\n")
        p = tmp_path / "counts.py"
        p.write_text(src)
        findings = _lint_file(str(p))
        assert len(findings) == 2
        fp = findings[0].fingerprint
        assert fp == findings[1].fingerprint
        diff = baseline_mod.diff(
            findings, {fp: {"count": 1, "justification": "j"},
                       "ghost": {"count": 1, "justification": "j"}})
        assert len(diff.baselined) == 1
        assert len(diff.new) == 1
        assert diff.stale == ["ghost"]

    def test_fingerprint_is_line_number_free(self, tmp_path):
        a = tmp_path / "a.py"
        b = tmp_path / "b.py"
        body = ("def f(x):\n"
                "    try:\n"
                "        x()\n"
                "    except Exception:\n"
                "        pass\n")
        a.write_text("# bftlint: path=cometbft_tpu/same.py\n" + body)
        b.write_text("# bftlint: path=cometbft_tpu/same.py\n"
                     "\n\n\n" + body)
        fa = _lint_file(str(a))
        fb = _lint_file(str(b))
        assert fa and fb
        assert fa[0].fingerprint == fb[0].fingerprint
        assert fa[0].line != fb[0].line

    def test_unknown_rule_rejected(self):
        proc = _cli("run", "--rules", "no-such-rule")
        assert proc.returncode == 2
        assert "unknown rule" in proc.stderr

    def test_partial_count_use_reports_stale(self, tmp_path):
        """An entry whose count exceeds its matches must surface as
        stale — leftover slack would silently absorb a reintroduced
        finding with the same fingerprint."""
        src = ("def f(x):\n"
               "    try:\n"
               "        x()\n"
               "    except Exception:\n"
               "        pass\n")
        p = tmp_path / "slack.py"
        p.write_text(src)
        findings = _lint_file(str(p))
        assert len(findings) == 1
        fp = findings[0].fingerprint
        diff = baseline_mod.diff(
            findings, {fp: {"count": 3, "justification": "j"}})
        assert not diff.new and len(diff.baselined) == 1
        assert diff.stale == [fp]

    def test_filtered_baseline_preserves_other_rules(self, tmp_path):
        """`baseline --rules x` / path-filtered runs must not wipe
        entries they did not re-examine."""
        prev = {
            "cwd-write::cometbft_tpu/other.py::f::open('x', 'w')":
                {"count": 1, "justification": "keep me"},
            "swallowed-exception::cometbft_tpu/gone.py::g::except Exception:":
                {"count": 1, "justification": "rule was rerun"},
        }
        out = tmp_path / "base.json"
        # rerun covered only swallowed-exception and found nothing:
        # its old entry goes; the cwd-write entry must survive
        n = baseline_mod.write(str(out), [], previous=prev,
                               active_rules={"swallowed-exception"})
        assert n == 1
        kept = baseline_mod.load(str(out))
        assert list(kept.values())[0]["justification"] == "keep me"
        # unfiltered rerun with no findings shrinks to empty
        n = baseline_mod.write(str(out), [], previous=prev)
        assert n == 0


class TestReviewRegressions:
    """Bug classes found in review: each was a false negative (or a
    lost diagnostic) in the first cut of the linter."""

    def test_yield_in_loop_sibling_handler_not_a_predecessor(
            self, tmp_path):
        """An await inside an *earlier* except handler cannot have
        run on a later handler's path — the busy-spin continue there
        must still be flagged."""
        src = (
            "import asyncio\n"
            "async def routine(work):\n"
            "    while True:\n"
            "        try:\n"
            "            work()\n"
            "        except TimeoutError:\n"
            "            await asyncio.sleep(1)\n"
            "            continue\n"
            "        except Exception:\n"
            "            continue\n")
        p = tmp_path / "handlers.py"
        p.write_text(src)
        found = [f for f in _lint_file(str(p))
                 if f.rule == "yield-in-loop"]
        assert len(found) == 1
        assert found[0].line == 10

    def test_yield_in_loop_try_body_await_counts(self, tmp_path):
        """The try body may have suspended before raising into the
        handler — a continue there is not provably spin."""
        src = (
            "import asyncio\n"
            "async def routine(work):\n"
            "    while True:\n"
            "        try:\n"
            "            await work()\n"
            "        except Exception:\n"
            "            continue\n")
        p = tmp_path / "trybody.py"
        p.write_text(src)
        assert not [f for f in _lint_file(str(p))
                    if f.rule == "yield-in-loop"]

    def test_swallowed_exception_word_boundary_match(self, tmp_path):
        """`rebuild_catalog()` ends in 'log' but is not a logging
        call; `log_error()` is."""
        src = (
            "def f(self, x):\n"
            "    try:\n"
            "        x()\n"
            "    except Exception:\n"
            "        self.rebuild_catalog()\n"
            "    try:\n"
            "        x()\n"
            "    except Exception:\n"
            "        self.log_error()\n")
        p = tmp_path / "words.py"
        p.write_text(src)
        found = [f for f in _lint_file(str(p))
                 if f.rule == "swallowed-exception"]
        assert len(found) == 1
        assert found[0].line == 4

    def test_baseline_refuses_rewrite_on_parse_errors(
            self, tmp_path):
        """An unparseable file yields no findings — an unfiltered
        baseline rewrite would silently drop all its entries and
        their justifications; refuse instead."""
        good = tmp_path / "good.py"
        good.write_text("def f():\n    return 1\n")
        broken = tmp_path / "broken.py"
        broken.write_text("def f(:\n")
        bl = tmp_path / "bl.json"
        proc = _cli("baseline", str(tmp_path), "--baseline", str(bl))
        assert proc.returncode == 2
        assert "refusing to rewrite" in proc.stderr
        assert not bl.exists()

    def test_explicit_non_py_file_argument_is_an_error(
            self, tmp_path):
        """A named file that is not .py would be silently skipped by
        the scan — mixed with other paths, the gate would pass
        without ever examining it."""
        txt = tmp_path / "notes.txt"
        txt.write_text("not python\n")
        py = tmp_path / "ok.py"
        py.write_text("def f():\n    return 1\n")
        proc = _cli("check", str(py), str(txt), "--no-baseline")
        assert proc.returncode == 2
        assert "not Python file" in proc.stderr

    def test_mangled_fingerprint_surfaces_stale_not_crash(
            self, tmp_path):
        """A hand-edit/merge that mangles one fingerprint (valid
        JSON, no '::') must not traceback a filtered run — the entry
        surfaces stale, and a baseline rewrite drops it."""
        src = ("def f(x):\n"
               "    try:\n"
               "        x()\n"
               "    except Exception:\n"
               "        pass\n")
        p = tmp_path / "site.py"
        p.write_text(src)
        bl = tmp_path / "bl.json"
        bl.write_text(json.dumps({
            "schema": 1,
            "entries": [{"fingerprint": "mangled by a bad merge",
                         "rule": "swallowed-exception",
                         "path": "x.py", "count": 1,
                         "justification": "j"}]}))
        proc = _cli("check", str(p), "--rules", "swallowed-exception",
                    "--baseline", str(bl))
        assert proc.returncode == 1
        assert "Traceback" not in proc.stderr
        assert "stale" in proc.stdout
        proc = _cli("baseline", str(p), "--rules",
                    "swallowed-exception", "--baseline", str(bl))
        assert proc.returncode == 0, proc.stderr
        assert "mangled by a bad merge" not in bl.read_text()

    def test_examined_paths_repo_root_covers_everything(self):
        """`check <repo-root>` relativizes to '.' — it re-examined
        every logical path, so none may be masked from staleness."""
        from tools.bftlint.cli import _ExaminedPaths
        ex = _ExaminedPaths([REPO_ROOT], set())
        assert "cometbft_tpu/consensus/state.py" in ex
        sub = _ExaminedPaths(
            [os.path.join(REPO_ROOT, "cometbft_tpu")], set())
        assert "cometbft_tpu/consensus/state.py" in sub
        assert "tests/other.py" not in sub

    def test_deleted_file_goes_stale_under_dir_scoped_run(
            self, tmp_path):
        """A dir-scoped check/baseline re-examined everything under
        the dir — a deleted file's entry must surface stale (and
        leave the baseline on rewrite), not be masked by exact
        scanned-file membership."""
        d = tmp_path / "pkg"
        d.mkdir()
        site = d / "site.py"
        site.write_text("def f(x):\n"
                        "    try:\n"
                        "        x()\n"
                        "    except Exception:\n"
                        "        pass\n")
        # keep the dir non-empty after the delete, or the
        # zero-files-scanned guard (exit 2) fires instead
        (d / "other.py").write_text("def g():\n    return 1\n")
        bl = tmp_path / "bl.json"
        proc = _cli("baseline", str(d), "--baseline", str(bl))
        assert proc.returncode == 0
        site.unlink()
        proc = _cli("check", str(d), "--baseline", str(bl))
        assert proc.returncode == 1, proc.stdout
        assert "stale" in proc.stdout
        proc = _cli("baseline", str(d), "--baseline", str(bl))
        assert proc.returncode == 0
        assert baseline_mod.load(str(bl)) == {}

    def test_blocking_in_async_chained_path_call(self, tmp_path):
        """`Path("wal.json").read_text()` chains through a Call, so
        call_name drops the receiver — it must still be flagged; a
        bare local `read_text()` must not."""
        src = ("# bftlint: path=cometbft_tpu/consensus/wal.py\n"
               "from pathlib import Path\n"
               "async def replay(read_text):\n"
               "    data = Path('wal.json').read_text()\n"
               "    local = read_text()\n"
               "    return data, local\n")
        p = tmp_path / "chained.py"
        p.write_text(src)
        found = [f for f in _lint_file(str(p))
                 if f.rule == "blocking-in-async"]
        assert [f.line for f in found] == [4]

    def test_swallowed_exception_nested_def_log_not_handling(
            self, tmp_path):
        """A log/raise inside a nested def or lambda only runs if it
        is later invoked — at the except site the failure is still
        dropped.  A closure capturing the bound exception variable,
        though, delegates it."""
        src = (
            "def f(x, log, defer):\n"
            "    try:\n"
            "        x()\n"
            "    except Exception:\n"
            "        cb = lambda: log.error('boom')\n"
            "        defer(cb)\n"
            "    try:\n"
            "        x()\n"
            "    except Exception as e:\n"
            "        defer(lambda: log.handle(e))\n")
        p = tmp_path / "closures.py"
        p.write_text(src)
        found = [f for f in _lint_file(str(p))
                 if f.rule == "swallowed-exception"]
        assert [f.line for f in found] == [4]

    def test_overlapping_paths_lint_each_file_once(self):
        """`check pkg pkg/file.py` must not double-count findings —
        duplicates would overflow count-capped baseline entries and
        surface as new on a clean tree."""
        overlap = os.path.join("cometbft_tpu", "consensus",
                               "state.py")
        proc = _cli("check", "cometbft_tpu", overlap)
        assert proc.returncode == 0, proc.stdout

    def test_missing_path_is_an_error_not_a_clean_pass(
            self, tmp_path):
        """`check <typo>` must exit 2, not print '0 files, 0 new
        finding(s)' and exit 0 — a silent false green from the gate."""
        proc = _cli("check", "cometbft_tpu_typo", "--no-baseline")
        assert proc.returncode == 2
        assert "no such path" in proc.stderr
        # an existing dir with no Python files is just as silent
        (tmp_path / "empty").mkdir()
        proc = _cli("check", str(tmp_path / "empty"), "--no-baseline")
        assert proc.returncode == 2
        assert "no Python files" in proc.stderr

    def test_comment_pragma_before_line_pragma_code_line(
            self, tmp_path):
        """A comment-only disable pragma applies to the next code
        line even when that line carries its own trailing pragma —
        and must not leak past it to a later line."""
        src = (
            "def f(x, seen):\n"
            "    try:\n"
            "        x()\n"
            "    # bftlint: disable=swallowed-exception\n"
            "    except Exception:  # bftlint: disable=cwd-write\n"
            "        pass\n"
            "    try:\n"
            "        x()\n"
            "    except Exception:\n"
            "        pass\n")
        p = tmp_path / "pragmas.py"
        p.write_text(src)
        found = [f for f in _lint_file(str(p))
                 if f.rule == "swallowed-exception"]
        # line 5 suppressed by the comment-only pragma; line 9 is not
        # (the pending pragma must not leak onto it)
        assert [f.line for f in found] == [9]

    def test_yield_in_loop_nested_def_await_not_a_suspension(
            self, tmp_path):
        """An await inside a nested function *definition* preceding
        the continue never ran on this path — the busy-spin must
        still be flagged."""
        src = (
            "import asyncio\n"
            "async def routine(q):\n"
            "    while True:\n"
            "        async def helper():\n"
            "            await q.get()\n"
            "        if q.empty():\n"
            "            continue\n"
            "        await helper()\n")
        p = tmp_path / "nested.py"
        p.write_text(src)
        found = [f for f in _lint_file(str(p))
                 if f.rule == "yield-in-loop"]
        assert [f.line for f in found] == [7]

    def test_await_atomicity_nested_def_await_not_a_straddle(
            self, tmp_path):
        """A nested def's await belongs to its own call's flow — the
        outer function has no suspension point, so a load/store pair
        around the def is not a straddle."""
        src = (
            "# bftlint: path=cometbft_tpu/consensus/fixture.py\n"
            "class ConsensusState:\n"
            "    async def outer(self):\n"
            "        h = self.rs.height\n"
            "        async def helper():\n"
            "            await self.signer.sign(h)\n"
            "        self._cb = helper\n"
            "        self.rs.height = h + 1\n")
        p = tmp_path / "nested_atom.py"
        p.write_text(src)
        assert not [f for f in _lint_file(str(p))
                    if f.rule == "await-atomicity"]

    def test_baseline_mode_refuses_corrupt_previous(self, tmp_path):
        """`baseline` over a corrupt/mismatched file must refuse, not
        silently rewrite it with placeholder justifications."""
        src = ("def f(x):\n"
               "    try:\n"
               "        x()\n"
               "    except Exception:\n"
               "        pass\n")
        p = tmp_path / "site.py"
        p.write_text(src)
        bl = tmp_path / "bl.json"
        bl.write_text("{ truncated by a bad merge")
        proc = _cli("baseline", str(p), "--baseline", str(bl))
        assert proc.returncode == 2
        assert "refusing to rewrite" in proc.stderr
        assert bl.read_text() == "{ truncated by a bad merge"

    def test_swallowed_exception_nonmetric_set_add(self, tmp_path):
        """`event.set()` / `seen.add()` are not metric recordings —
        only a receiver that names a metric (or with_labels) counts."""
        src = (
            "def f(self, x, seen):\n"
            "    try:\n"
            "        x()\n"
            "    except Exception:\n"
            "        self._stopped.set()\n"
            "    try:\n"
            "        x()\n"
            "    except Exception:\n"
            "        seen.add(x)\n"
            "    try:\n"
            "        x()\n"
            "    except Exception:\n"
            "        self.metrics.failures.add(1)\n")
        p = tmp_path / "events.py"
        p.write_text(src)
        found = [f for f in _lint_file(str(p))
                 if f.rule == "swallowed-exception"]
        assert sorted(f.line for f in found) == [4, 8]

    def test_cwd_write_update_mode(self, tmp_path):
        """open(..., 'r+') writes without any of w/a/x — relative
        update-mode paths land in the CWD too."""
        src = ("# bftlint: path=cometbft_tpu/libs/upd.py\n"
               "def f(rec):\n"
               "    with open('state.json', 'r+') as fh:\n"
               "        fh.write(rec)\n"
               "    with open('state.json') as fh:\n"
               "        return fh.read()\n")
        p = tmp_path / "upd.py"
        p.write_text(src)
        found = [f for f in _lint_file(str(p))
                 if f.rule == "cwd-write"]
        assert [f.line for f in found] == [3]

    def test_check_exits_nonzero_on_stale_baseline(self, tmp_path):
        """`check` must fail on stale entries like the tier-1 pytest
        gate does — a false local green hides a shrinkable baseline."""
        src = ("def f(x):\n"
               "    try:\n"
               "        x()\n"
               "    except Exception:\n"
               "        pass\n")
        p = tmp_path / "site.py"
        p.write_text(src)
        bl = tmp_path / "bl.json"
        proc = _cli("baseline", str(p), "--baseline", str(bl))
        assert proc.returncode == 0
        proc = _cli("check", str(p), "--baseline", str(bl))
        assert proc.returncode == 0
        # fix the site: the entry goes stale and check must fail
        p.write_text("def f(x):\n    return x()\n")
        proc = _cli("check", str(p), "--baseline", str(bl))
        assert proc.returncode == 1
        assert "stale" in proc.stdout

    def test_filtered_check_ignores_out_of_filter_entries(
            self, tmp_path):
        """A --rules/path-filtered check only re-examined a subset —
        entries for other rules/paths must not read as stale."""
        src = ("def f(x):\n"
               "    try:\n"
               "        x()\n"
               "    except Exception:\n"
               "        pass\n")
        p = tmp_path / "site.py"
        p.write_text(src)
        bl = tmp_path / "bl.json"
        proc = _cli("baseline", str(p), "--baseline", str(bl))
        assert proc.returncode == 0
        # the swallowed-exception entry is out of this rule filter:
        # not re-examined, so not stale — check stays green
        proc = _cli("check", str(p), "--rules", "yield-in-loop",
                    "--baseline", str(bl))
        assert proc.returncode == 0, proc.stdout
        assert "1 stale" not in proc.stdout

    def test_logger_debug_renders_traceback(self, capsys):
        """exc_info=True on debug/info/warn must emit the traceback,
        not a literal 'exc_info=True' k-v pair — the new preverify
        debug logs depend on it."""
        import logging

        from cometbft_tpu.libs.log import Logger
        base = logging.getLogger("bftlint-test-log")
        base.setLevel(logging.DEBUG)
        stream = __import__("io").StringIO()
        h = logging.StreamHandler(stream)
        base.addHandler(h)
        try:
            log = Logger(base)
            try:
                raise ValueError("boom")
            except ValueError:
                log.debug("skipping malformed vote", exc_info=True)
            out = stream.getvalue()
            assert "exc_info" not in out
            assert "ValueError: boom" in out
            assert "Traceback" in out
        finally:
            base.removeHandler(h)
