"""Static analysis: reactor/node background loops are supervisor-owned.

PR 1 (failure-domain supervision) moved every reactor/switch/consensus
background loop under libs/supervisor.py so an uncaught exception
restarts the loop (bounded, metered) instead of silently killing it.
This AST check locks that invariant into tier-1: a bare
``asyncio.create_task`` / ``loop.create_task`` / ``ensure_future`` in
reactor or node code is a regression — spawn through
``self.supervisor.spawn(...)`` (or the switch's supervisor) instead.

Scope: every ``*/reactor.py`` under cometbft_tpu/, the node assembly,
the consensus state machine, and the p2p switch.  Library plumbing
that manages its own task lifecycle with in-loop error handling
(p2p/conn.py MConnection, abci/client.py SocketClient, libs/service)
is deliberately out of scope — those are transports, not
reactor/node loops.
"""
import ast
import glob
import os

import pytest

_PKG = os.path.join(os.path.dirname(__file__), "..", "cometbft_tpu")

_SCOPE = sorted(
    glob.glob(os.path.join(_PKG, "*", "reactor.py")) + [
        os.path.join(_PKG, "node", "node.py"),
        os.path.join(_PKG, "consensus", "state.py"),
        os.path.join(_PKG, "p2p", "switch.py"),
    ])

# (relative path, line) pairs exempted from the invariant.  Keep this
# EMPTY unless a spawn is provably supervisor-mediated and cannot be
# expressed through Supervisor.spawn — and document why here.
_ALLOWLIST: set[tuple[str, int]] = set()

_SPAWN_ATTRS = {"create_task", "ensure_future"}


def _spawn_calls(path: str) -> list[tuple[str, int, str]]:
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    rel = os.path.relpath(path, os.path.join(_PKG, ".."))
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = ""
        if isinstance(fn, ast.Attribute) and fn.attr in _SPAWN_ATTRS:
            name = fn.attr
        elif isinstance(fn, ast.Name) and fn.id in _SPAWN_ATTRS:
            name = fn.id
        if name and (rel, node.lineno) not in _ALLOWLIST:
            out.append((rel, node.lineno, name))
    return out


def test_scope_is_nonempty():
    # the glob must keep finding the reactors — a silent empty scope
    # would make this whole check vacuous
    assert len(_SCOPE) >= 7, _SCOPE
    assert all(os.path.exists(p) for p in _SCOPE)


@pytest.mark.parametrize("path", _SCOPE,
                         ids=[os.path.relpath(p, _PKG)
                              for p in _SCOPE])
def test_no_unsupervised_tasks(path):
    offenders = _spawn_calls(path)
    assert not offenders, (
        "unsupervised task spawn(s) in reactor/node code — use "
        "self.supervisor.spawn(...) so crashes restart (bounded) "
        f"instead of dying silently: {offenders}")
