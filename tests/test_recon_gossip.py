"""Reconciliation data plane (ISSUE 12, docs/gossip.md): have/want tx
gossip + compact-block proposals.

Unit edges: short-hash self-collision salt rotation, want-timeout
refetch from a second advertiser, compact reconstruct with missing
txs nacking into the full-part fallback, flood interop with a peer
that never negotiated the capability; plus a live 2-node pull-path
e2e over real sockets.  The fuzz/partition coverage is the
``recon-gossip`` nemesis scenario (tests/test_nemesis.py).
"""
import asyncio

import pytest

from cometbft_tpu.abci.client import AppConns
from cometbft_tpu.abci.kvstore import DEFAULT_LANES, KVStoreApplication
from cometbft_tpu.config import MempoolConfig
from cometbft_tpu.mempool import CListMempool
from cometbft_tpu.mempool import messages as mm
from cometbft_tpu.mempool.reactor import MEMPOOL_CHANNEL, MempoolReactor
from cometbft_tpu.types.tx import tx_key


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


class _NodeInfoStub:
    def __init__(self, features):
        self.features = tuple(features)


class _StubPeer:
    """Captures sends; optionally advertises capabilities."""

    def __init__(self, pid="aa" * 20, features=()):
        self.id = pid
        self.sent = []
        self.node_info = _NodeInfoStub(features)

    def has_feature(self, name):
        return name in self.node_info.features

    def send(self, chan_id, payload):
        self.sent.append((chan_id, payload))
        return True

    def decoded(self):
        return [mm.decode_mempool(p) for _, p in self.sent]


async def _mk_pool(size=5000, **cfg):
    app = KVStoreApplication()
    conns = AppConns(app)
    return CListMempool(MempoolConfig(size=size, **cfg), conns.mempool,
                        lanes=DEFAULT_LANES, default_lane="default")


async def _wait_for(pred, timeout=5.0, what="condition"):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while not pred():
        if loop.time() > deadline:
            raise AssertionError(f"timed out waiting for {what}")
        await asyncio.sleep(0.01)


RECON = (mm.FEATURE_TXRECON,)


class TestShortIds:
    def test_short_id_is_salted(self):
        k = tx_key(b"tx-1")
        a = mm.short_id(b"salt-a", k)
        b = mm.short_id(b"salt-b", k)
        assert a != b
        assert len(a) == mm.SHORT_ID_LEN

    def test_bulk_matches_single(self):
        keys = [tx_key(b"tx-%d" % i) for i in range(100)]
        bulk = mm.short_ids(b"s", keys)
        assert bulk == [mm.short_id(b"s", k) for k in keys]

    def test_have_want_wire_roundtrip_bounds_bytes(self):
        keys = [tx_key(b"t%04d" % i) for i in range(256)]
        ids = mm.short_ids(b"salty-8b", keys)
        raw = mm.encode_mempool(mm.TxHaveMessage(b"salty-8b", ids))
        # 256 ids at 8 bytes + envelope: ~1/32nd of the 256 B txs
        assert len(raw) < 256 * mm.SHORT_ID_LEN + 64
        got = mm.decode_mempool(raw)
        assert got.ids == ids and got.salt == b"salty-8b"


class TestSaltRotation:
    def test_summary_self_collision_rotates_salt(self, monkeypatch):
        """Two pool txs colliding under the current salt make the
        summary ambiguous: the sender must rotate (bump) its salt and
        re-derive until the batch's ids are unique."""
        # 1-byte ids over 64 txs guarantee a birthday collision
        monkeypatch.setattr(mm, "SHORT_ID_LEN", 1)

        async def go():
            mp = await _mk_pool()
            reactor = MempoolReactor(mp, MempoolConfig(
                recon_push_peers=0))
            peer = _StubPeer(features=RECON)
            for i in range(64):
                await mp.check_tx(b"col%03d=v" % i)
            await reactor.add_peer(peer)
            await _wait_for(lambda: peer.sent, what="advert")
            await asyncio.sleep(0.05)
            haves = [m for m in peer.decoded()
                     if isinstance(m, mm.TxHaveMessage)]
            assert haves, "no TxHave sent"
            # at 1-byte ids NO salt can make 64 keys collision-free:
            # the rotation loop must have fired (and its bound must
            # have stopped it from spinning) — shipping a residual
            # collision is safe, it only suppresses one pull and the
            # want-timeout/compact fallbacks cover it
            assert reactor.mempool.metrics \
                .recon_salt_rotations.value > 0
            assert reactor._salt_bump <= 8
            await reactor.remove_peer(peer, "done")
        run(go())

    def test_salt_follows_height_epoch(self):
        async def go():
            mp = await _mk_pool()
            r = MempoolReactor(mp, MempoolConfig(
                recon_salt_epoch_blocks=16))
            s0 = r._current_salt()
            mp.height = 15
            assert r._current_salt() == s0
            mp.height = 16
            assert r._current_salt() != s0
        run(go())


class TestWantTracker:
    def test_want_goes_to_first_advertiser_only(self):
        async def go():
            mp = await _mk_pool()
            reactor = MempoolReactor(mp, MempoolConfig())
            a = _StubPeer(pid="aa" * 20, features=RECON)
            b = _StubPeer(pid="bb" * 20, features=RECON)
            reactor._recon_peers = {a.id: a, b.id: b}
            salt = b"s" * 8
            sid = mm.short_id(salt, tx_key(b"unknown-tx"))
            reactor._receive_have(
                mm.TxHaveMessage(salt, [sid]), a)
            reactor._receive_have(
                mm.TxHaveMessage(salt, [sid]), b)
            wants_a = [m for m in a.decoded()
                       if isinstance(m, mm.TxWantMessage)]
            wants_b = [m for m in b.decoded()
                       if isinstance(m, mm.TxWantMessage)]
            assert wants_a and wants_a[0].ids == [sid]
            assert not wants_b, "duplicate pull of an in-flight id"
            w = reactor._wants.get(salt, sid)
            assert w is not None and w.advertisers == [a.id, b.id]
        run(go())

    def test_timeout_refetches_from_second_advertiser(self):
        async def go():
            mp = await _mk_pool()
            reactor = MempoolReactor(mp, MempoolConfig())
            a = _StubPeer(pid="aa" * 20, features=RECON)
            b = _StubPeer(pid="bb" * 20, features=RECON)
            reactor._recon_peers = {a.id: a, b.id: b}
            salt = b"s" * 8
            sid = mm.short_id(salt, tx_key(b"lost-tx"))
            reactor._receive_have(mm.TxHaveMessage(salt, [sid]), a)
            reactor._receive_have(mm.TxHaveMessage(salt, [sid]), b)
            now = asyncio.get_running_loop().time()
            reactor.sweep_wants(now + 2.0, timeout_s=1.0)
            wants_b = [m for m in b.decoded()
                       if isinstance(m, mm.TxWantMessage)]
            assert wants_b and wants_b[0].ids == [sid], \
                "timeout did not refetch from the second advertiser"
            assert reactor.mempool.metrics \
                .recon_want_refetches.value == 1
            # every advertiser exhausted -> the entry is dropped
            for i in range(6):
                reactor.sweep_wants(now + 10.0 + 3 * i,
                                    timeout_s=1.0)
            assert reactor._wants.get(salt, sid) is None
            assert reactor.mempool.metrics \
                .recon_want_expired.value == 1
        run(go())

    def test_arriving_tx_settles_want(self):
        async def go():
            mp = await _mk_pool()
            reactor = MempoolReactor(mp, MempoolConfig())
            a = _StubPeer(pid="aa" * 20, features=RECON)
            reactor._recon_peers = {a.id: a}
            tx = b"wanted=v"
            salt = reactor._current_salt()
            sid = mm.short_id(salt, tx_key(tx))
            reactor._receive_have(mm.TxHaveMessage(salt, [sid]), a)
            assert reactor._wants.get(salt, sid) is not None
            await reactor._receive_txs(mm.TxsMessage([tx]), a)
            assert reactor._wants.get(salt, sid) is None
            assert mp.contains(tx_key(tx))
        run(go())

    def test_want_served_from_pool(self):
        """A peer's TxWant under the salt we advertised with returns
        the full txs, batched."""
        async def go():
            mp = await _mk_pool()
            reactor = MempoolReactor(mp, MempoolConfig())
            peer = _StubPeer(features=RECON)
            txs = [b"serve%02d=v" % i for i in range(10)]
            for t in txs:
                await mp.check_tx(t)
            salt = reactor._current_salt()
            sids = [mm.short_id(salt, tx_key(t)) for t in txs]
            reactor._receive_want(
                mm.TxWantMessage(salt, sids), peer)
            got = [m for m in peer.decoded()
                   if isinstance(m, mm.TxsMessage)]
            assert got and sorted(
                t for m in got for t in m.txs) == sorted(txs)
        run(go())


class TestFloodFallbackInterop:
    def test_non_negotiating_peer_gets_full_txs(self):
        """A peer that never advertised txrecon/1 (an old build) must
        get the flood plane: full txs, never TxHave summaries."""
        async def go():
            mp = await _mk_pool()
            reactor = MempoolReactor(mp, MempoolConfig())
            old = _StubPeer(pid="cc" * 20, features=())
            new = _StubPeer(pid="dd" * 20, features=RECON)
            await reactor.add_peer(old)
            await reactor.add_peer(new)
            # a GOSSIPED tx (has a sender): the push fast path does
            # not apply, so the recon peer must see a summary
            await mp.check_tx(b"interop=1", sender="ee" * 20)
            await _wait_for(lambda: old.sent and new.sent,
                            what="both planes to send")
            old_msgs = old.decoded()
            assert any(isinstance(m, mm.TxsMessage) and
                       b"interop=1" in m.txs for m in old_msgs)
            assert not any(isinstance(m, mm.TxHaveMessage)
                           for m in old_msgs)
            new_msgs = new.decoded()
            assert any(isinstance(m, mm.TxHaveMessage)
                       for m in new_msgs)
            assert not any(isinstance(m, mm.TxsMessage)
                           for m in new_msgs)
            await reactor.remove_peer(old, "done")
            await reactor.remove_peer(new, "done")
        run(go())

    def test_local_tx_pushed_to_fast_path_peers(self):
        """Brand-new local txs (no gossip sender) are pushed in full:
        with one peer and recon_push_peers=2 the lottery always
        selects it."""
        async def go():
            mp = await _mk_pool()
            reactor = MempoolReactor(mp, MempoolConfig(
                recon_push_peers=2))
            peer = _StubPeer(features=RECON)
            await reactor.add_peer(peer)
            await mp.check_tx(b"local=1")
            await _wait_for(lambda: peer.sent, what="push")
            msgs = peer.decoded()
            assert any(isinstance(m, mm.TxsMessage) and
                       b"local=1" in m.txs for m in msgs)
            assert reactor.mempool.metrics \
                .recon_pushed_txs.value >= 1
            await reactor.remove_peer(peer, "done")
        run(go())

    def test_duplicate_delivery_ratio_gauge(self):
        async def go():
            mp = await _mk_pool()
            reactor = MempoolReactor(mp, MempoolConfig())
            peer = _StubPeer(features=RECON)
            await reactor._receive_txs(
                mm.TxsMessage([b"d=1", b"d=2"]), peer)
            await reactor._receive_txs(
                mm.TxsMessage([b"d=1"]), peer)   # duplicate
            m = mp.metrics
            assert m.gossip_txs_received.value == 3
            assert m.gossip_txs_duplicate.value == 1
            assert abs(m.duplicate_delivery_ratio.value - 1 / 3) \
                < 1e-9
        run(go())


class TestCompactBlock:
    def _mk_block(self, n_txs=32):
        from cometbft_tpu.types.block import Block, Data, Header
        from cometbft_tpu.types.timestamp import Timestamp
        txs = [(b"cb%04d=" % i) + b"v" * 120 for i in range(n_txs)]
        b = Block(header=Header(chain_id="t", height=1,
                                time=Timestamp(1700000000, 0),
                                proposer_address=b"p" * 20),
                  data=Data(txs=txs))
        b.fill_header()
        return b, b.make_part_set()

    def test_reconstruct_is_byte_exact(self):
        from cometbft_tpu.consensus.messages import (
            make_compact_block, reconstruct_block_bytes,
        )
        from cometbft_tpu.types.part_set import PartSet
        block, parts = self._mk_block(900 // 4)
        msg = make_compact_block(1, 0, block, parts.header())
        raw = reconstruct_block_bytes(msg.skeleton,
                                      list(block.data.txs))
        assert raw == parts.assemble()
        assert PartSet.from_data(raw).header() == parts.header()

    async def _mk_cs(self):
        """A wired single-validator ConsensusState (not started) with
        a real mempool behind the executor."""
        from cometbft_tpu.config import test_config as _tc
        from cometbft_tpu.consensus.state import ConsensusState
        from cometbft_tpu.crypto import ed25519
        from cometbft_tpu.db import MemDB
        from cometbft_tpu.state import make_genesis_state
        from cometbft_tpu.state.execution import BlockExecutor
        from cometbft_tpu.state.store import Store
        from cometbft_tpu.store import BlockStore
        from cometbft_tpu.types.genesis import (
            GenesisDoc, GenesisValidator,
        )
        from cometbft_tpu.types.priv_validator import MockPV
        from cometbft_tpu.types.timestamp import Timestamp
        pv = MockPV(ed25519.Ed25519PrivKey(b"\x11" * 32))
        doc = GenesisDoc(chain_id="t",
                         genesis_time=Timestamp(1700000000, 0),
                         validators=[GenesisValidator(
                             address=b"",
                             pub_key=pv.get_pub_key(), power=10)])
        state = make_genesis_state(doc)
        app = KVStoreApplication()
        conns = AppConns(app)
        ss, bs = Store(MemDB()), BlockStore(MemDB())
        ss.save(state)
        mp = CListMempool(MempoolConfig(), conns.mempool,
                          lanes=DEFAULT_LANES,
                          default_lane="default")
        ex = BlockExecutor(ss, conns.consensus, mempool=mp,
                           block_store=bs)
        return ConsensusState(_tc().consensus, state, ex, bs,
                              priv_validator=pv), mp

    def test_missing_tx_nacks_and_falls_back(self):
        """A compact proposal with an unresolvable hash must not feed
        any parts; it nacks the sender (the immediate full-part
        fallback) and counts a miss."""
        from cometbft_tpu.consensus.messages import (
            make_compact_block,
        )
        from cometbft_tpu.types.part_set import PartSet

        async def go():
            cs, mp = await self._mk_cs()
            block, parts = self._mk_block(16)
            # all but one tx in the pool
            for tx in block.data.txs[1:]:
                await mp.check_tx(tx)
            sent = []
            cs.broadcast_hooks.append(sent.append)
            cs.rs.proposal_block_parts = PartSet(parts.header())
            msg = make_compact_block(cs.rs.height, cs.rs.round,
                                     block, parts.header())
            ok = await cs._apply_compact_block(msg, "peerX")
            assert not ok
            assert cs.rs.proposal_block is None
            assert cs.metrics.compact_block_misses.value == 1
            nacks = [m for m in sent if isinstance(m, tuple) and
                     m[0] == "compact_nack"]
            assert nacks == [("compact_nack", cs.rs.height,
                              cs.rs.round, "peerX")]
            # the missing tx arrives (the want path delivered it):
            # a re-sent compact now reconstructs fully
            await mp.check_tx(block.data.txs[0])
            ok = await cs._apply_compact_block(msg, "peerX")
            assert ok
            assert cs.rs.proposal_block is not None
            assert cs.rs.proposal_block.hash() == block.hash()
            assert cs.metrics.compact_blocks_reconstructed.value == 1
            await cs.stop()
        run(go())

    def test_header_mismatch_nacks(self):
        from cometbft_tpu.consensus.messages import (
            make_compact_block,
        )
        from cometbft_tpu.types.part_set import PartSet

        async def go():
            cs, mp = await self._mk_cs()
            block, parts = self._mk_block(16)
            other_block, other_parts = self._mk_block(12)
            for tx in block.data.txs:
                await mp.check_tx(tx)
            sent = []
            cs.broadcast_hooks.append(sent.append)
            # we are collecting OTHER block's parts; the compact
            # advertises a different header -> mismatch, nack
            cs.rs.proposal_block_parts = PartSet(
                other_parts.header())
            msg = make_compact_block(cs.rs.height, cs.rs.round,
                                     block, parts.header())
            ok = await cs._apply_compact_block(msg, "peerY")
            assert not ok
            assert cs.metrics.compact_block_mismatches.value == 1
            assert any(isinstance(m, tuple) and
                       m[0] == "compact_nack" for m in sent)
            await cs.stop()
        run(go())

    def test_small_blocks_skip_compact(self):
        """Proposals under COMPACT_MIN_TXS ship as plain parts — the
        compact tuple must not be broadcast for them."""
        from cometbft_tpu.consensus.messages import COMPACT_MIN_TXS
        assert COMPACT_MIN_TXS >= 2


class TestHandshakeNegotiation:
    def test_node_info_features_roundtrip(self):
        from cometbft_tpu.p2p.switch import NodeInfo
        ni = NodeInfo(node_id="x", network="n",
                      features=("txrecon/1", "compactblocks/1"))
        got = NodeInfo.from_json(ni.to_json())
        assert got.features == ("txrecon/1", "compactblocks/1")
        # an old build's JSON has no features key
        import json
        d = json.loads(ni.to_json())
        del d["features"]
        old = NodeInfo.from_json(json.dumps(d).encode())
        assert old.features == ()

    def test_switch_aggregates_reactor_features(self):
        from cometbft_tpu.p2p.key import NodeKey
        from cometbft_tpu.p2p.switch import Switch

        async def go():
            sw = Switch(NodeKey.generate(), "net")
            mp = await _mk_pool()
            sw.add_reactor(MempoolReactor(mp, MempoolConfig()))
            assert mm.FEATURE_TXRECON in sw.node_info().features
            sw2 = Switch(NodeKey.generate(), "net")
            mp2 = await _mk_pool()
            sw2.add_reactor(MempoolReactor(mp2, MempoolConfig(
                gossip_reconciliation=False)))
            assert mm.FEATURE_TXRECON not in sw2.node_info().features
        run(go())


class TestReconE2E:
    def test_two_node_pull_path_over_sockets(self):
        """Node B learns a tx it never saw via advertise -> want ->
        pull over a real secret-connection link (push fast path
        disabled so the reconciliation round trip itself is what
        moves the tx)."""
        from cometbft_tpu.p2p.key import NodeKey
        from cometbft_tpu.p2p.switch import Switch

        async def go():
            switches, pools, reactors = [], [], []
            for _ in range(2):
                mp = await _mk_pool()
                r = MempoolReactor(mp, MempoolConfig(
                    recon_push_peers=0,
                    recon_want_timeout_ns=500_000_000))
                sw = Switch(NodeKey.generate(), "recon-e2e",
                            listen_addr="127.0.0.1:0")
                sw.add_reactor(r)
                switches.append(sw)
                pools.append(mp)
                reactors.append(r)
            for sw in switches:
                await sw.start()
            try:
                await switches[0].dial_peer(switches[1].listen_addr)
                tx = b"e2epull=" + b"v" * 64
                await pools[0].check_tx(tx)
                await _wait_for(
                    lambda: pools[1].contains(tx_key(tx)),
                    timeout=8.0, what="tx to cross via want/pull")
                m1 = pools[1].metrics
                assert m1.recon_wants_sent.value >= 1
                assert m1.gossip_txs_duplicate.value == 0
                m0 = pools[0].metrics
                assert m0.recon_wants_received.value >= 1
            finally:
                for sw in switches:
                    await sw.stop()
        run(go())


class TestAppendLog:
    """The bounded (seq, key) append log: gossip cursors and short-id
    maps read "appended since S" in O(new) instead of rescanning the
    pool per wire message (the QA_r08 profile win)."""

    def test_covers_and_orders_appends(self):
        async def go():
            mp = await _mk_pool()
            txs = [b"log%02d=v" % i for i in range(5)]
            for tx in txs:
                await mp.check_tx(tx)
            assert mp.keys_appended_after(-1) == \
                [tx_key(tx) for tx in txs]
            mid = mp._append_log[2][0]
            assert mp.keys_appended_after(mid) == \
                [tx_key(tx) for tx in txs[3:]]
            assert mp.keys_appended_after(mp._seq) == []
        run(go())

    def test_trim_forces_fallback(self):
        async def go():
            mp = await _mk_pool()
            mp._APPEND_LOG_MAX = 8
            for i in range(12):
                await mp.check_tx(b"trim%02d=v" % i)
            # the log dropped its oldest quarter at least once: a
            # cursor from before the drop cannot be served
            assert mp._log_start_seq > -1
            assert mp.keys_appended_after(-1) is None
            assert mp.keys_appended_after(
                mp._log_start_seq - 1) is None
            # at the boundary (and after) it still serves
            assert mp.keys_appended_after(
                mp._log_start_seq) is not None
        run(go())

    def test_flush_resets_log(self):
        async def go():
            mp = await _mk_pool()
            await mp.check_tx(b"fl0=v")
            mp.flush()
            assert mp.keys_appended_after(mp._seq) == []
            # pre-flush cursors fall back to the (now empty) scan
            assert mp.keys_appended_after(-1) is None
        run(go())

    def test_fresh_entries_uses_log_and_fallback(self):
        async def go():
            mp = await _mk_pool()
            r = MempoolReactor(mp, MempoolConfig())
            for i in range(6):
                await mp.check_tx(b"fe%02d=v" % i, sender="")
            keys = [e.key for e in r._fresh_entries(-1, "zz" * 20,
                                                    set())]
            assert len(keys) == 6
            # committed/evicted entries drop out of the feed
            mp.remove_tx_by_key(keys[0])
            left = [e.key for e in r._fresh_entries(-1, "zz" * 20,
                                                    set())]
            assert keys[0] not in left and len(left) == 5
            # a handled key is skipped; a sender match is skipped
            left2 = r._fresh_entries(-1, "zz" * 20, {keys[1]})
            assert keys[1] not in [e.key for e in left2]
        run(go())


class TestVoteGossipUntrackedSet:
    """Regression for the QA_r08 livelock: _pick_send_vote must not
    send into a vote set the peer-state does not track — the
    delivery can never be marked (set_has_vote drops the write), so
    the same votes re-send every gossip tick forever, and vote
    batching amplified that into 315k messages across 12 heights."""

    def _mk_reactor_and_ps(self, vote_batch_max=16):
        from types import SimpleNamespace
        from cometbft_tpu.config import ConsensusConfig
        from cometbft_tpu.consensus.metrics import Metrics
        from cometbft_tpu.consensus.reactor import (
            ConsensusReactor, PeerState,
        )
        cfg = ConsensusConfig(vote_batch_max=vote_batch_max)
        cs = SimpleNamespace(config=cfg, metrics=Metrics(),
                             broadcast_hooks=[], on_new_step=[],
                             rs=None)
        reactor = ConsensusReactor.__new__(ConsensusReactor)
        reactor.cs = cs
        peer = _StubPeer(features=("votebatch/1",))
        return reactor, PeerState(peer), peer

    def _mk_vote_set(self, height=5, round_=0, n=4):
        from types import SimpleNamespace
        from cometbft_tpu.libs.bits import BitArray
        from cometbft_tpu.types import canonical
        from cometbft_tpu.types.block_id import BlockID
        from cometbft_tpu.types.timestamp import Timestamp
        from cometbft_tpu.types.vote import Vote
        ours = BitArray(n)
        ours.set_index(0, True)
        votes = {0: Vote(type=canonical.PREVOTE_TYPE, height=height,
                         round=round_, block_id=BlockID(),
                         timestamp=Timestamp(1700000000, 0),
                         validator_address=b"v" * 20,
                         validator_index=0, signature=b"s" * 64)}
        return SimpleNamespace(
            height=height, round=round_,
            signed_msg_type=canonical.PREVOTE_TYPE,
            bit_array=lambda: ours,
            get_by_index=lambda i: votes.get(i))

    def test_untracked_set_sends_nothing(self):
        reactor, ps, peer = self._mk_reactor_and_ps()
        vs = self._mk_vote_set(height=5)
        # default PeerRoundState: height 0 — the peer tracks nothing
        # for height 5, so there is NO send (reference PickSendVote:
        # nil bitarray -> no pick)
        assert reactor._pick_send_vote(ps, vs) is False
        assert peer.sent == []

    def test_tracked_set_sends_and_marks(self):
        from cometbft_tpu.libs.bits import BitArray
        reactor, ps, peer = self._mk_reactor_and_ps()
        vs = self._mk_vote_set(height=5)
        ps.prs.height = 5
        ps.prs.round = 0
        ps.prs.prevotes = BitArray(4)
        assert reactor._pick_send_vote(ps, vs) is True
        assert len(peer.sent) == 1
        # delivery marked: a second pick finds nothing missing
        assert ps.prs.prevotes.get_index(0)
        assert reactor._pick_send_vote(ps, vs) is False
        assert len(peer.sent) == 1
