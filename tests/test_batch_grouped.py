"""BLS batch verification + per-key-type grouped commit verification.

The reference batches only ed25519 and only when ALL validators share
one key type (crypto/batch/batch.go:21, types/validation.go:15-21).
This framework adds (a) a bls12381 batch verifier — one random-linear-
combination pairings product, n+1 Miller loops sharing a single final
exponentiation (crypto/bls12381.py Bls12381BatchVerifier) — and (b) a
grouped commit path that batches each key-type group of a MIXED
validator set (types/validation.py _verify_commit_grouped).  Verdict
parity with the per-signature path is what these tests pin.
"""
import pytest

from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.crypto import bls12381, ed25519, secp256k1
from cometbft_tpu.types import canonical
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.commit import (
    BLOCK_ID_FLAG_COMMIT, Commit, CommitSig)
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.signature_cache import SignatureCache
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validation import (
    VerificationError, _should_group_verify, verify_commit,
    verify_commit_light_trusting, Fraction)
from cometbft_tpu.types.validator import Validator
from cometbft_tpu.types.validator_set import ValidatorSet
from cometbft_tpu.types.vote import Vote


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


def _bls_keys(n):
    return [bls12381.gen_priv_key_from_secret(b"grouped-%d" % i)
            for i in range(n)]


class TestBlsBatchVerifier:
    def test_all_valid(self):
        privs = _bls_keys(3)
        bv = bls12381.Bls12381BatchVerifier()
        for i, p in enumerate(privs):
            msg = b"vote %d" % i
            bv.add(p.pub_key(), msg, p.sign(msg))
        ok, mask = bv.verify()
        assert ok and mask == [True, True, True]

    def test_flags_exactly_the_bad_signature(self):
        privs = _bls_keys(3)
        bv = bls12381.Bls12381BatchVerifier()
        for i, p in enumerate(privs):
            msg = b"vote %d" % i
            sig = p.sign(msg)
            if i == 1:
                msg = b"forged"      # signature over a different msg
            bv.add(p.pub_key(), msg, sig)
        ok, mask = bv.verify()
        assert not ok and mask == [True, False, True]

    def test_garbage_signature_bytes(self):
        privs = _bls_keys(2)
        bv = bls12381.Bls12381BatchVerifier()
        bv.add(privs[0].pub_key(), b"m0", privs[0].sign(b"m0"))
        bv.add(privs[1].pub_key(), b"m1", b"\xff" * 96)
        ok, mask = bv.verify()
        assert not ok and mask == [True, False]

    def test_single_item_and_empty(self):
        bv = bls12381.Bls12381BatchVerifier()
        assert bv.verify() == (False, [])
        p = _bls_keys(1)[0]
        bv.add(p.pub_key(), b"solo", p.sign(b"solo"))
        assert bv.verify() == (True, [True])

    def test_dispatch_creates_bls_verifier(self):
        pk = _bls_keys(1)[0].pub_key()
        assert crypto_batch.supports_batch_verifier(pk)
        bv = crypto_batch.create_batch_verifier(pk)
        # dispatch wraps every verifier in the flight-recorder shim;
        # the BLS engine sits inside it
        assert isinstance(bv, crypto_batch.TracedBatchVerifier)
        assert isinstance(bv._inner, bls12381.Bls12381BatchVerifier)
        # the locally spelled tag must track the real one
        assert crypto_batch._BLS_KEY_TYPE == bls12381.KEY_TYPE


def _mixed_commit(n_ed=3, n_bls=2, n_secp=1, chain_id="grouped-chain",
                  height=7, corrupt=None):
    privs = ([ed25519.gen_priv_key() for _ in range(n_ed)] +
             _bls_keys(n_bls) +
             [secp256k1.gen_priv_key() for _ in range(n_secp)])
    vals = [Validator.new(p.pub_key(), 10) for p in privs]
    pairs = sorted(zip(vals, privs),
                   key=lambda vp: (-vp[0].voting_power, vp[0].address))
    vset = ValidatorSet([p[0] for p in pairs])
    privs = [p[1] for p in pairs]
    block_id = BlockID(hash=b"\x77" * 32,
                       part_set_header=PartSetHeader(1, b"\x88" * 32))
    sigs = []
    for i, (val, priv) in enumerate(zip(vset.validators, privs)):
        ts = Timestamp(1700000100 + i, 0)
        v = Vote(type=canonical.PRECOMMIT_TYPE, height=height, round=0,
                 block_id=block_id, timestamp=ts,
                 validator_address=val.address, validator_index=i)
        sig = priv.sign(v.sign_bytes(chain_id))
        if corrupt is not None and i == corrupt:
            sig = bytes([sig[0] ^ 0x01]) + sig[1:]
        sigs.append(CommitSig(block_id_flag=BLOCK_ID_FLAG_COMMIT,
                              validator_address=val.address,
                              timestamp=ts, signature=sig))
    commit = Commit(height=height, round=0, block_id=block_id,
                    signatures=sigs)
    return chain_id, vset, block_id, height, commit


class TestGroupedCommitVerify:
    def test_gate_engages_only_for_mixed_with_batchable_pair(self):
        chain_id, vset, bid, h, commit = _mixed_commit()
        assert not vset.all_keys_have_same_type()
        assert _should_group_verify(vset, commit)
        # all-secp set: nothing batchable
        _, vset2, _, _, commit2 = _mixed_commit(n_ed=0, n_bls=0, n_secp=4)
        assert not _should_group_verify(vset2, commit2)

    def test_mixed_commit_verifies(self):
        chain_id, vset, bid, h, commit = _mixed_commit()
        verify_commit(chain_id, vset, bid, h, commit)

    @pytest.mark.parametrize("corrupt", [0, 2, 4, 5])
    def test_corrupt_signature_rejected_with_exact_index(self, corrupt):
        chain_id, vset, bid, h, commit = _mixed_commit(corrupt=corrupt)
        with pytest.raises(VerificationError) as ei:
            verify_commit(chain_id, vset, bid, h, commit)
        assert f"#{corrupt}" in str(ei.value)

    def test_lowest_failing_index_across_inline_and_deferred(self):
        # verdict parity: a deferred (batchable) bad signature at a
        # lower index must win over an inline (secp) failure at a
        # higher one, and vice versa — the single path reports the
        # first failure in walk order
        chain_id, vset, bid, h, commit = _mixed_commit()
        types = [v.pub_key.type() for v in vset.validators]
        deferred_idx = min(i for i, t in enumerate(types)
                           if t != "secp256k1")
        inline_idx = types.index("secp256k1")
        sigs = list(commit.signatures)
        for i in (deferred_idx, inline_idx):
            s = sigs[i]
            sigs[i] = CommitSig(
                block_id_flag=s.block_id_flag,
                validator_address=s.validator_address,
                timestamp=s.timestamp,
                signature=bytes([s.signature[0] ^ 1]) + s.signature[1:])
        bad_commit = Commit(height=h, round=0, block_id=bid,
                            signatures=sigs)
        with pytest.raises(VerificationError) as ei:
            verify_commit(chain_id, vset, bid, h, bad_commit)
        assert f"#{min(deferred_idx, inline_idx)}" in str(ei.value)

    def test_cache_populated_and_reused(self):
        chain_id, vset, bid, h, commit = _mixed_commit()
        cache = SignatureCache()
        verify_commit(chain_id, vset, bid, h, commit, cache=cache)
        assert len(cache) == len(commit.signatures)
        # second run: everything cached, still verifies
        verify_commit(chain_id, vset, bid, h, commit, cache=cache)

    def test_light_trusting_mixed(self):
        chain_id, vset, bid, h, commit = _mixed_commit()
        verify_commit_light_trusting(
            chain_id, vset, commit, Fraction(1, 3))

    def test_cache_records_verified_key_address_not_commit_field(self):
        # regression (review finding): in by-index mode the commit's
        # validator_address field is attacker-controlled; caching it
        # would let validator A's signature populate a cache entry
        # under validator B's address (sign bytes exclude address, so
        # a later by-index check in B's slot would hit and tally B's
        # power without B signing).
        chain_id, vset, bid, h, commit = _mixed_commit()
        spoof_to = vset.validators[3].address
        s = commit.signatures[0]
        commit.signatures[0] = CommitSig(
            block_id_flag=s.block_id_flag,
            validator_address=spoof_to,       # lie about the signer
            timestamp=s.timestamp, signature=s.signature)
        cache = SignatureCache()
        verify_commit(chain_id, vset, bid, h, commit, cache=cache)
        cv = cache.get(s.signature)
        assert cv is not None
        # cached under the key that actually verified (validator 0)
        assert cv.validator_address == \
            vset.validators[0].pub_key.address()
        assert cv.validator_address != spoof_to

    def test_forged_sig_reported_even_without_quorum(self):
        # regression (review finding): verdict parity with the single
        # path requires wrong-signature to surface before the
        # voting-power threshold is judged
        chain_id, vset, bid, h, commit = _mixed_commit(corrupt=1)
        # drop most signatures to absent so power is insufficient too
        for i in range(3, len(commit.signatures)):
            commit.signatures[i] = CommitSig.absent()
        with pytest.raises(VerificationError) as ei:
            verify_commit(chain_id, vset, bid, h, commit)
        assert "wrong signature" in str(ei.value)

    def test_wrong_length_signature_is_verification_error(self):
        # regression (review finding): a 32-byte "signature" passes
        # CommitSig.validate_basic (<= max size) but BatchVerifier.add
        # raises ValueError; that must surface as the usual
        # wrong-signature VerificationError, not escape as ValueError
        chain_id, vset, bid, h, commit = _mixed_commit()
        types = [v.pub_key.type() for v in vset.validators]
        idx = types.index("ed25519")
        s = commit.signatures[idx]
        commit.signatures[idx] = CommitSig(
            block_id_flag=s.block_id_flag,
            validator_address=s.validator_address,
            timestamp=s.timestamp, signature=b"\x01" * 32)
        with pytest.raises(VerificationError) as ei:
            verify_commit(chain_id, vset, bid, h, commit)
        assert f"#{idx}" in str(ei.value)

    def test_nil_pubkey_rejected_not_crash(self):
        # regression (review finding): the same-type gate skips
        # nil-pubkey validators, so a nil key reaches the batch path;
        # it must reject with VerificationError, not escape as
        # TypeError from BatchVerifier.add
        chain_id, vset, bid, h, commit = _mixed_commit(
            n_ed=4, n_bls=0, n_secp=0)
        assert vset.all_keys_have_same_type()
        vset.validators[2].pub_key = None
        assert vset.all_keys_have_same_type()   # gate still passes
        with pytest.raises(VerificationError) as ei:
            verify_commit(chain_id, vset, bid, h, commit)
        assert "nil PubKey" in str(ei.value)

    def test_all_bls_set_routes_through_plain_batch(self):
        # same-type BLS sets now pass the _should_batch_verify gate
        chain_id, vset, bid, h, commit = _mixed_commit(
            n_ed=0, n_bls=4, n_secp=0)
        assert vset.all_keys_have_same_type()
        verify_commit(chain_id, vset, bid, h, commit)
        _, vset2, bid2, h2, commit2 = _mixed_commit(
            n_ed=0, n_bls=4, n_secp=0, corrupt=1)
        with pytest.raises(VerificationError):
            verify_commit("grouped-chain", vset2, bid2, h2, commit2)


class TestVotePreverification:
    """Verified-triple memo + burst pre-verification (types/vote.py):
    the tally-path batching of SURVEY §2.1 (vote_set.go:219-236 is
    per-vote in the reference)."""

    def test_checked_verify_memoizes_both_verdicts(self, monkeypatch):
        from cometbft_tpu.types import vote as vote_mod
        vote_mod._VERIFIED.clear()
        vote_mod._REJECTED.clear()
        priv = ed25519.gen_priv_key()
        pub = priv.pub_key()
        sig = priv.sign(b"memo-me")
        calls = {"n": 0}
        real = type(pub).verify_signature

        def counting(self, msg, s):
            calls["n"] += 1
            return real(self, msg, s)

        monkeypatch.setattr(type(pub), "verify_signature", counting)
        assert vote_mod.checked_verify(pub, b"memo-me", sig)
        assert vote_mod.checked_verify(pub, b"memo-me", sig)
        assert calls["n"] == 1          # second hit served by the memo
        assert not vote_mod.checked_verify(pub, b"other", sig)
        assert not vote_mod.checked_verify(pub, b"other", sig)
        # a deterministic failure is invalid forever: the repeat is
        # served by the negative memo (byzantine re-send amplification
        # fix, ADVICE r4)
        assert calls["n"] == 2

    def test_preverify_fills_memo_by_key_type_groups(self, monkeypatch):
        from cometbft_tpu.types import vote as vote_mod
        vote_mod._VERIFIED.clear()
        vote_mod._REJECTED.clear()
        eds = [ed25519.gen_priv_key() for _ in range(3)]
        bls = _bls_keys(2)
        entries = []
        for i, p in enumerate(eds + bls):
            msg = b"pre-%d" % i
            sig = p.sign(msg)
            if i == 1:
                sig = bytes([sig[0] ^ 2]) + sig[1:]     # corrupt one
            entries.append((p.pub_key(), msg, sig))
        vote_mod.preverify_signatures(entries)
        # valid entries memoized positive; the corrupted one negative
        # (the batch mask is exact per signature, even on reject)
        for i, (pk, msg, sig) in enumerate(entries):
            key = vote_mod._memo_key(pk, msg, sig)
            assert (key in vote_mod._VERIFIED) == (i != 1)
            assert (key in vote_mod._REJECTED) == (i == 1)
        # a subsequent vote-style verify of ANY judged triple does not
        # call verify_signature again — including the invalid one
        def boom(self, *a):
            raise AssertionError("memo miss")
        for i in (0, 1):
            pk, msg, sig = entries[i]
            monkeypatch.setattr(type(pk), "verify_signature", boom)
            assert vote_mod.checked_verify(pk, msg, sig) == (i != 1)

    def test_memo_is_bounded(self):
        from cometbft_tpu.types import vote as vote_mod
        vote_mod._VERIFIED.clear()
        vote_mod._REJECTED.clear()
        for i in range(vote_mod._VERIFIED_MAX + 50):
            vote_mod._memo_add((b"p%d" % i, b"m", b"s"))
        assert len(vote_mod._VERIFIED) == vote_mod._VERIFIED_MAX
        for i in range(vote_mod._REJECTED_MAX + 50):
            vote_mod._memo_reject((b"p%d" % i, b"m", b"s"))
        assert len(vote_mod._REJECTED) == vote_mod._REJECTED_MAX

    def test_sign_bytes_memo_tracks_timestamp_rewrite(self):
        # regression: privval's double-sign protection rewrites
        # vote.timestamp on the same-HRS re-sign path AFTER sign bytes
        # may have been marshaled; the memo must not serve stale bytes
        from cometbft_tpu.types import canonical as canon
        from cometbft_tpu.types.block_id import BlockID as BID
        v = Vote(type=canonical.PREVOTE_TYPE, height=3, round=0,
                 block_id=BID(), timestamp=Timestamp(1700000500, 0),
                 validator_address=b"\x01" * 20, validator_index=0)
        sb1 = v.sign_bytes("memo-chain")
        assert v.sign_bytes("memo-chain") == sb1     # memo hit
        v.timestamp = Timestamp(1700000777, 5)       # privval rewrite
        sb2 = v.sign_bytes("memo-chain")
        assert sb2 != sb1
        assert sb2 == canon.vote_sign_bytes(
            "memo-chain", v.type, v.height, v.round, v.block_id,
            v.timestamp)
        # the memo keys EVERY signed field (ADVICE r4): mutating any
        # of them — not just the timestamp — must miss the memo
        v.round = 7
        sb3 = v.sign_bytes("memo-chain")
        assert sb3 != sb2
        assert sb3 == canon.vote_sign_bytes(
            "memo-chain", v.type, v.height, 7, v.block_id, v.timestamp)
        v.height = 4
        sb4 = v.sign_bytes("memo-chain")
        assert sb4 != sb3
        assert sb4 == canon.vote_sign_bytes(
            "memo-chain", v.type, 4, 7, v.block_id, v.timestamp)
