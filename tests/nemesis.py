"""Deterministic seeded nemesis: fault schedules against in-proc testnets.

The runner composes fault schedules — asymmetric partitions (directed
link cuts), heal, hard node crash/restart, and seeded link faults
(drop / delay / reorder / duplicate via p2p.fuzz.FuzzedConnection) —
against an in-process validator net built on real sockets (the same
substrate as tests/test_perturbations.py), then asserts the two
properties that define BFT consensus:

  * safety   — no two honest nodes ever commit conflicting blocks at
               the same height (checked over the FULL chain history;
               block stores are append-only, so a violation at any
               point survives to the final check);
  * liveness — after every fault heals, the chain commits
               ``recovery_blocks`` more blocks within a bounded time.

Determinism: the fault schedule is a literal list of steps; every
random choice (link-fuzz schedules, validator keys) derives from the
scenario seed.  asyncio interleaving is not bit-reproducible, but the
*injected* fault pattern is.

Link faults ride the Switch.conn_wrapper seam: each node wraps every
authenticated connection in (optionally) a FuzzedConnection and a
_NemesisConn that drops outbound frames on blocked directed links —
so "A cannot reach B" composes with "B can still reach A".
"""
from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass
from typing import Optional

from cometbft_tpu.abci.client import AppConns
from cometbft_tpu.abci.kvstore import DEFAULT_LANES, KVStoreApplication
from cometbft_tpu.config import MempoolConfig
from cometbft_tpu.config import test_config as _test_config
from cometbft_tpu.consensus.reactor import ConsensusReactor
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.crypto import ed25519
from cometbft_tpu.db import MemDB
from cometbft_tpu.mempool import CListMempool
from cometbft_tpu.mempool.reactor import MempoolReactor
from cometbft_tpu.p2p.fuzz import FuzzConfig, FuzzedConnection
from cometbft_tpu.p2p.key import NodeKey
from cometbft_tpu.p2p.switch import Switch
from cometbft_tpu.state import make_genesis_state
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.store import Store
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.priv_validator import MockPV
from cometbft_tpu.types.timestamp import Timestamp


class LinkTable:
    """Directed link cuts shared by every node of one net."""

    def __init__(self):
        self.blocked: set[tuple[int, int]] = set()   # (src, dst) idx
        self.dropped = 0

    def block(self, src: int, dst: int) -> None:
        self.blocked.add((src, dst))

    def heal(self) -> None:
        self.blocked.clear()

    def is_blocked(self, src: int, dst: int) -> bool:
        return (src, dst) in self.blocked


class _NemesisConn:
    """Write-side frame drop on blocked directed links, slotted under
    the MConnection (reads always pass: blocking A→B must not stop
    B→A)."""

    def __init__(self, conn, table: LinkTable, src: int, dst: int):
        self._conn = conn
        self._table = table
        self._src = src
        self._dst = dst

    async def write_msg(self, data: bytes) -> None:
        if self._table.is_blocked(self._src, self._dst):
            self._table.dropped += 1
            return
        await self._conn.write_msg(data)

    async def read_msg(self) -> bytes:
        return await self._conn.read_msg()

    def close(self) -> None:
        self._conn.close()

    def __getattr__(self, name):
        return getattr(self._conn, name)


class NemesisNode:
    """A validator whose consensus+p2p can be hard-killed and
    restarted on its durable stores, with every link wrapped in the
    net's fault injectors.

    With ``wal_path`` set the node boots like the real node: ABCI
    handshake reconciling app height vs store height (re-applying a
    block a crash left between the crash-consistency barriers — the
    window the pipelined commit widens on purpose), then WAL catchup
    replay of the in-flight height."""

    def __init__(self, net: "NemesisNet", idx: int, doc: GenesisDoc,
                 pv: MockPV, node_key: NodeKey,
                 wal_path: Optional[str] = None):
        self.net = net
        self.idx = idx
        self.doc = doc
        self.pv = pv
        self.node_key = node_key
        self.wal_path = wal_path
        self.app = KVStoreApplication()
        self.conns = AppConns(self.app)
        self.state_store = Store(MemDB())
        self.block_store = BlockStore(MemDB())
        self.state_store.save(make_genesis_state(doc))
        self.cs: Optional[ConsensusState] = None
        self.switch: Optional[Switch] = None
        self.mempool: Optional[CListMempool] = None
        self.running = False

    async def start(self) -> None:
        from cometbft_tpu.consensus.replay import (
            Handshaker, catchup_replay,
        )
        from cometbft_tpu.consensus.wal import WAL
        if self.cs is not None:
            # restart after a crash: a real process death loses the
            # app's in-memory staging — rebuild the app from its
            # durable db (committed state only), then let the
            # handshake below re-apply whatever the crash left
            # between the commit barriers (block saved / responses
            # saved / app committed / state saved)
            self.app = KVStoreApplication(db=self.app.db)
            self.conns = AppConns(self.app)
        state = self.state_store.load()
        hs = Handshaker(self.state_store, state, self.block_store,
                        self.doc)
        await hs.handshake(self.conns)
        state = self.state_store.load()
        self.mempool = CListMempool(
            MempoolConfig(), self.conns.mempool, lanes=DEFAULT_LANES,
            default_lane="default", height=state.last_block_height)
        ex = BlockExecutor(self.state_store, self.conns.consensus,
                           mempool=self.mempool,
                           block_store=self.block_store)
        wal = WAL(self.wal_path) if self.wal_path is not None else None
        self.cs = ConsensusState(
            _test_config().consensus, state, ex, self.block_store,
            priv_validator=self.pv, wal=wal)
        if self.wal_path is not None:
            await catchup_replay(self.cs, self.wal_path)
        self.switch = Switch(self.node_key, self.doc.chain_id,
                             listen_addr="127.0.0.1:0")
        self.switch.conn_wrapper = self._wrap_conn
        self.switch.add_reactor(ConsensusReactor(self.cs))
        if self.net.mempool_gossip:
            # reconciliation tx gossip rides the same fault injectors
            # as consensus (docs/gossip.md): tier-1 runs it under
            # reorder/duplicate/partition fuzz
            self.switch.add_reactor(
                MempoolReactor(self.mempool, MempoolConfig()))
        await self.switch.start()
        await self.cs.start()
        self.running = True

    def _wrap_conn(self, sconn, their_id: str, outbound: bool):
        dst = self.net.idx_of(their_id)
        conn = sconn
        fuzz = self.net.fuzz_config(self.idx, dst)
        if fuzz is not None:
            conn = FuzzedConnection(conn, fuzz)
            self.net.fuzzed_conns.append(conn)
        return _NemesisConn(conn, self.net.links, self.idx, dst)

    async def crash(self) -> None:
        """Hard stop: no flush, no goodbye, and an in-flight
        pipelined apply is ABORTED, not drained (in-proc analog of
        docker kill; the stores survive at whatever crash-consistency
        barrier the commit reached)."""
        await self.cs.stop(drain_pipeline=False)
        await self.switch.stop()
        self.running = False

    @property
    def height(self) -> int:
        return self.block_store.height


class NemesisNet:
    def __init__(self, n: int = 4, seed: int = 0,
                 fuzz_profile: Optional[dict] = None,
                 wal_dir: Optional[str] = None,
                 mempool_gossip: bool = False,
                 key_type: str = "ed25519",
                 consensus_params=None):
        self.seed = seed
        self.rng = random.Random(seed)
        self.links = LinkTable()
        self.fuzz_profile = fuzz_profile
        self.mempool_gossip = mempool_gossip
        self.fuzzed_conns: list[FuzzedConnection] = []
        # every random artifact (keys included) derives from the seed
        if key_type == "bls12_381":
            from cometbft_tpu.crypto import bls12381
            pvs = [MockPV(bls12381.gen_priv_key_from_secret(
                self.rng.getrandbits(256).to_bytes(32, "big")))
                for _ in range(n)]
        else:
            pvs = [MockPV(ed25519.Ed25519PrivKey(
                self.rng.getrandbits(256).to_bytes(32, "big")))
                for _ in range(n)]
        doc = GenesisDoc(
            chain_id=f"nemesis-{seed}",
            genesis_time=Timestamp(1700000000, 0),
            consensus_params=consensus_params,
            validators=[GenesisValidator(
                address=b"", pub_key=pv.get_pub_key(), power=10)
                for pv in pvs])
        keys = [NodeKey.generate() for _ in range(n)]
        self._id_to_idx = {k.id: i for i, k in enumerate(keys)}
        import os as _os
        self.nodes = [NemesisNode(
            self, i, doc, pvs[i], keys[i],
            wal_path=(_os.path.join(wal_dir, f"wal{i}")
                      if wal_dir else None))
            for i in range(n)]
        self._load_task: Optional[asyncio.Task] = None
        self._load_stop = asyncio.Event()
        self._tx_seq = 0

    # ------------------------------------------------------------------
    def idx_of(self, node_id: str) -> int:
        return self._id_to_idx.get(node_id, -1)

    def fuzz_config(self, src: int, dst: int) -> Optional[FuzzConfig]:
        if self.fuzz_profile is None:
            return None
        # deterministic per ordered link, derived from the net seed
        link_seed = self.seed * 1_000_003 + src * 101 + dst * 13 + 1
        return FuzzConfig(seed=link_seed, **self.fuzz_profile)

    # ------------------------------------------------------------------
    async def start(self) -> None:
        for node in self.nodes:
            await node.start()
        await self.connect_full_mesh()
        self._load_task = asyncio.ensure_future(self._load())

    async def stop(self) -> None:
        self._load_stop.set()
        if self._load_task is not None:
            self._load_task.cancel()
        for node in self.nodes:
            if node.running:
                await node.crash()

    async def connect_full_mesh(self) -> None:
        alive = [n for n in self.nodes if n.running]
        for i, node in enumerate(alive):
            for other in alive[i + 1:]:
                if any(p.id == other.node_key.id
                       for p in node.switch.peers.values()):
                    continue
                try:
                    # bounded: a saturated peer must not wedge the
                    # waiter inside an unbounded handshake read
                    await asyncio.wait_for(node.switch.dial_peer(
                        other.switch.listen_addr), 5.0)
                except Exception:
                    pass   # retried by the next mesh pass

    async def _load(self) -> None:
        """Background tx injection (reference: runner/load.go)."""
        while not self._load_stop.is_set():
            for n in self.nodes:
                if n.running and n.mempool is not None:
                    try:
                        await n.mempool.check_tx(
                            f"load{self._tx_seq}=v".encode())
                    except Exception:
                        pass
                self._tx_seq += 1
            await asyncio.sleep(0.02)

    async def reset_all_links(self) -> None:
        """Drop every connection (fresh PeerState on both sides) and
        re-mesh — the runner's model of 'the faulty links were
        replaced'."""
        for node in self.nodes:
            if node.running and node.switch is not None:
                for peer in list(node.switch.peers.values()):
                    await node.switch.stop_peer(
                        peer, "nemesis: link replaced")
        await self.connect_full_mesh()

    async def heal_links(self) -> None:
        """Unblock every link AND reset the connections that carried a
        blocked direction.  On real TCP a one-way cut ends in
        backpressure → keepalive timeout → reconnect, which resets the
        peers' delivery bookkeeping; frames silently dropped by the
        nemesis wrapper were marked delivered by the gossip routines,
        so the reconnect (fresh PeerState) is part of the fault model,
        not a cheat."""
        pairs = set(self.links.blocked)
        self.links.heal()
        reset: set[tuple[int, int]] = set()
        for s, d in pairs:
            reset.add((min(s, d), max(s, d)))
        for a, b in reset:
            for src, dst in ((a, b), (b, a)):
                node = self.nodes[src]
                if node.running and node.switch is not None:
                    peer = node.switch.peers.get(
                        self.nodes[dst].node_key.id)
                    if peer is not None:
                        await node.switch.stop_peer(
                            peer, "nemesis heal: link reset")
        await self.connect_full_mesh()

    # ------------------------------------------------------------------
    def max_height(self) -> int:
        return max(n.height for n in self.nodes)

    async def wait_all_height(self, h: int, timeout: float,
                              nodes: Optional[list] = None) -> None:
        """All (running) target nodes reach height h; the mesh is
        re-dialed periodically since fault injection can kill
        connections."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        last_mesh = 0.0
        while True:
            targets = [n for n in (nodes or self.nodes) if n.running]
            if targets and all(n.height >= h for n in targets):
                return
            if loop.time() > deadline:
                raise AssertionError(
                    f"liveness: heights "
                    f"{[n.height for n in self.nodes]} never reached "
                    f"{h} within {timeout}s")
            if loop.time() - last_mesh > 0.5:
                await self.connect_full_mesh()
                last_mesh = loop.time()
            await asyncio.sleep(0.05)

    def assert_no_conflicting_commits(self) -> None:
        """Safety: at every height, every node that committed a block
        committed the SAME block.  A violation dumps the flight
        recorder (libs/tracing.py) before failing — the black box a
        post-mortem renders with tools/trace_report.py."""
        conflicts: dict[int, dict[str, list[int]]] = {}
        for h in range(1, self.max_height() + 1):
            seen: dict[bytes, list[int]] = {}
            for n in self.nodes:
                b = n.block_store.load_block(h)
                if b is not None:
                    seen.setdefault(b.hash(), []).append(n.idx)
            if len(seen) > 1:
                conflicts[h] = {h_.hex(): idxs
                                for h_, idxs in seen.items()}
        if not conflicts:
            return
        from cometbft_tpu.libs import tracing
        for h in sorted(conflicts):
            tracing.instant(tracing.NEMESIS, "safety_violation",
                            height=h, commits=conflicts[h])
        dump_path = tracing.dump(
            reason="nemesis_safety_violation",
            extra={"conflicting_heights": sorted(conflicts),
                   "conflicts": {str(h): c
                                 for h, c in conflicts.items()}})
        detail = ", ".join(
            f"h{h}: {{{', '.join(hh[:12] + ': ' + str(i) for hh, i in c.items())}}}"
            for h, c in sorted(conflicts.items()))
        raise AssertionError(
            f"SAFETY VIOLATION: conflicting commits — {detail}; "
            f"flight record: {dump_path or '(dump failed)'}")

    # ------------------------------------------------------------------
    async def apply(self, step: tuple) -> None:
        kind, *args = step
        if kind == "wait_blocks":
            target = self.max_height() + args[0]
            await self.wait_all_height(target, timeout=60.0)
        elif kind == "partition":
            srcs, dsts = args
            for s in srcs:
                for d in dsts:
                    self.links.block(s, d)
        elif kind == "heal":
            await self.heal_links()
        elif kind == "crash":
            await self.nodes[args[0]].crash()
        elif kind == "restart":
            await self.nodes[args[0]].start()
            await self.connect_full_mesh()
        elif kind == "sleep":
            await asyncio.sleep(args[0])
        elif kind == "expect_stall":
            window_s, slack = args
            h0 = self.max_height()
            await asyncio.sleep(window_s)
            h1 = self.max_height()
            assert h1 <= h0 + slack, (
                f"expected a stall but the chain advanced "
                f"{h1 - h0} blocks in {window_s}s")
        elif kind == "expect_progress":
            # some subset must keep committing despite the fault
            idxs, blocks, timeout = args
            subset = [self.nodes[i] for i in idxs]
            target = max(n.height for n in subset) + blocks
            await self.wait_all_height(target, timeout, nodes=subset)
        else:
            raise ValueError(f"unknown nemesis step {kind!r}")


@dataclass(frozen=True)
class Scenario:
    """A named, seeded fault schedule.  After the steps run, the
    runner force-heals everything (links, crashed nodes), re-meshes,
    and asserts bounded-time recovery + full-history safety."""
    name: str
    seed: int = 0
    n: int = 4
    fuzz: Optional[dict] = None     # FuzzConfig kwargs for every link
    steps: tuple = ()
    recovery_blocks: int = 3
    recovery_timeout_s: float = 90.0
    # file-backed consensus WALs + full crash recovery (handshake +
    # catchup replay) on every restart — the pipelined-commit crash
    # window needs the real recovery path, not just durable stores
    use_wal: bool = False
    # register the mempool reactor on every node so have/want tx
    # gossip + compact-block proposals run under the fault schedule
    mempool_gossip: bool = False
    # validator key type ("ed25519" | "bls12_381") and optional
    # consensus-params override (the aggregate-commit scenario runs a
    # BLS valset with feature.aggregate_commit_enable_height set)
    key_type: str = "ed25519"
    consensus_params: object = None


def archive_dir() -> str:
    """Where failed-scenario flight records are archived.  Default is
    a repo-ignored ./nemesis-archive so CI can upload the directory as
    an artifact; override with COMETBFT_TPU_NEMESIS_ARCHIVE_DIR."""
    import os
    return os.environ.get("COMETBFT_TPU_NEMESIS_ARCHIVE_DIR",
                          "nemesis-archive")


def _archive_flight_record(s: Scenario, exc: BaseException,
                           net: "NemesisNet" = None) -> str:
    """A failing scenario (liveness miss, safety violation, runner
    crash) archives the whole flight recorder — liveness regressions
    in the slow sweeps come with per-height timelines attached
    (ROADMAP open item).  The archive name carries a run-unique
    suffix (pid + monotonic) so repeated runs of the same
    scenario/seed never overwrite each other's evidence.  Nemesis
    nodes are in-process and share the one module-global recorder, so
    this single dump IS the fleet-wide record; per-node state
    (height, running) rides in ``extra["nodes"]`` and the anchors in
    the dump let tools/fleet_report.py place it on a wall timeline
    next to out-of-process dumps.  Never raises; returns the path or
    ""."""
    import os
    import time as _time

    from cometbft_tpu.libs import tracing
    slug = "".join(c if c.isalnum() or c in "-_" else "-"
                   for c in s.name)[:64] or "scenario"
    run_id = f"{os.getpid():x}-{_time.monotonic_ns() & 0xFFFFFF:06x}"
    path = os.path.join(
        archive_dir(),
        f"nemesis-{slug}-seed{s.seed}-{run_id}.json")
    nodes = []
    if net is not None:
        try:
            nodes = [{"idx": n.idx, "running": n.running,
                      "height": n.block_store.height}
                     for n in net.nodes]
        except Exception:
            nodes = []
    return tracing.dump(
        reason=f"nemesis_scenario_failure_{slug}", path=path,
        extra={"scenario": s.name, "seed": s.seed, "n": s.n,
               "fuzz": s.fuzz, "steps": [list(map(str, st))
                                         for st in s.steps],
               "nodes": nodes,
               "error": repr(exc)[:500]})


async def run_scenario(s: Scenario) -> NemesisNet:
    import contextlib
    import tempfile
    wal_ctx = tempfile.TemporaryDirectory() if s.use_wal \
        else contextlib.nullcontext(None)
    with wal_ctx as wal_dir:
        return await _run_scenario_inner(s, wal_dir)


async def _run_scenario_inner(s: Scenario,
                              wal_dir: Optional[str]) -> NemesisNet:
    net = NemesisNet(s.n, seed=s.seed, fuzz_profile=s.fuzz,
                     wal_dir=wal_dir,
                     mempool_gossip=s.mempool_gossip,
                     key_type=s.key_type,
                     consensus_params=s.consensus_params)
    await net.start()
    try:
        try:
            for step in s.steps:
                await net.apply(step)
            # quiesce the load so the (single-core) recovery check
            # measures consensus catchup, not tx-throughput contention
            net._load_stop.set()
            # heal the world, then require recovery
            await net.heal_links()
            if s.fuzz is not None:
                # link noise "heals" too: new connections are clean,
                # and the old (noise-poisoned) ones are replaced
                net.fuzz_profile = None
                await net.reset_all_links()
            for node in net.nodes:
                if not node.running:
                    await node.start()
            await net.connect_full_mesh()
            h0 = net.max_height()
            await net.wait_all_height(h0 + s.recovery_blocks,
                                      s.recovery_timeout_s)
            net.assert_no_conflicting_commits()
        except BaseException as e:
            if not isinstance(e, asyncio.CancelledError):
                path = _archive_flight_record(s, e, net)
                if path and isinstance(e, AssertionError):
                    raise AssertionError(
                        f"{e}\nflight record archived: {path}") \
                        from e
            raise
    finally:
        await net.stop()
    return net
