"""Metrics v2 contract tests.

* A Prometheus-text-format parser validates the FULL /metrics output
  of a live multi-validator net: HELP/TYPE lines for every family,
  histogram bucket monotonicity, ``le="+Inf"`` == ``_count``, label
  escaping — so metrics v2 can never emit scrape-breaking text.
* The tier-1 cardinality/help guard: every registered family carries
  non-empty help, label names come from a bounded allowlist (no
  per-tx / unbounded label sets), and the per-family child cap
  collapses excess label values into one overflow series.
* Histogram exemplars link bucket observations to the flight-recorder
  height (``/metrics?exemplars=1``).
"""
import asyncio
import os
import tempfile

import pytest

from cometbft_tpu.libs import tracing
from cometbft_tpu.libs.metrics import (
    DEFAULT, Registry, _CHILDREN_MAX, render_merged,
)


# ---------------------------------------------------------------------
# Prometheus text-format parser (exposition format 0.0.4)

def _unescape(s: str, quotes: bool) -> str:
    out = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            if nxt == "\\":
                out.append("\\")
                i += 2
                continue
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if quotes and nxt == '"':
                out.append('"')
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def _parse_labels(s: str, line: str) -> tuple[dict, str]:
    """Parse '{k="v",...}rest' -> (labels, rest); raises AssertionError
    on malformed input (that IS the contract being tested)."""
    assert s[0] == "{", line
    labels = {}
    i = 1
    while True:
        if s[i] == "}":
            return labels, s[i + 1:]
        j = s.index("=", i)
        key = s[i:j]
        assert s[j + 1] == '"', f"unquoted label value: {line}"
        k = j + 2
        raw = []
        while True:
            c = s[k]
            if c == "\\":
                raw.append(s[k:k + 2])
                k += 2
                continue
            if c == '"':
                break
            assert c != "\n", f"raw newline inside label: {line}"
            raw.append(c)
            k += 1
        labels[key] = _unescape("".join(raw), quotes=True)
        i = k + 1
        if s[i] == ",":
            i += 1


def parse_exposition(text: str) -> dict:
    """-> {family: {"help": str, "type": str,
                    "samples": [(sample_name, labels, value)]}}"""
    families: dict[str, dict] = {}
    last_family = None
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_ = rest.partition(" ")
            fam = families.setdefault(
                name, {"help": "", "type": "", "samples": []})
            fam["help"] = _unescape(help_, quotes=False)
            last_family = name
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):]
            name, _, kind = rest.partition(" ")
            assert kind in ("counter", "gauge", "histogram",
                            "summary", "untyped"), line
            assert name in families, \
                f"TYPE before HELP for {name}: {line}"
            families[name]["type"] = kind
            last_family = name
            continue
        assert not line.startswith("#"), f"stray comment: {line}"
        # sample line: name[{labels}] value[ # exemplar]
        brace = line.find("{")
        space = line.find(" ")
        if brace != -1 and brace < space:
            sample_name = line[:brace]
            labels, rest = _parse_labels(line[brace:], line)
        else:
            sample_name = line[:space]
            labels, rest = {}, line[space:]
        rest = rest.strip()
        value_str = rest.split(" ", 1)[0]
        value = float(value_str)
        # attribute the sample to its family (histogram suffixes)
        fam_name = sample_name
        for suffix in ("_bucket", "_sum", "_count"):
            if sample_name.endswith(suffix) and \
                    sample_name[: -len(suffix)] in families and \
                    families[sample_name[: -len(suffix)]]["type"] \
                    == "histogram":
                fam_name = sample_name[: -len(suffix)]
                break
        assert fam_name in families, \
            f"sample with no HELP/TYPE: {line}"
        families[fam_name]["samples"].append(
            (sample_name, labels, value))
        last_family = fam_name
    return families


def assert_exposition_contract(text: str) -> dict:
    """The full scrape contract over an exposition page."""
    families = parse_exposition(text)
    assert families
    for name, fam in families.items():
        assert fam["type"], f"{name}: missing TYPE"
        assert fam["help"].strip(), f"{name}: empty HELP"
        if fam["type"] != "histogram":
            continue
        # group histogram samples per label set (minus le)
        series: dict[tuple, dict] = {}
        for sample_name, labels, value in fam["samples"]:
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            s = series.setdefault(
                key, {"buckets": [], "sum": None, "count": None})
            if sample_name == name + "_bucket":
                s["buckets"].append((labels["le"], value))
            elif sample_name == name + "_sum":
                s["sum"] = value
            elif sample_name == name + "_count":
                s["count"] = value
        for key, s in series.items():
            assert s["buckets"], f"{name}{key}: no buckets"
            assert s["sum"] is not None, f"{name}{key}: no _sum"
            assert s["count"] is not None, f"{name}{key}: no _count"
            inf = [v for le, v in s["buckets"] if le == "+Inf"]
            assert len(inf) == 1, f"{name}{key}: +Inf bucket count"
            assert inf[0] == s["count"], \
                f"{name}{key}: le=+Inf {inf[0]} != _count {s['count']}"
            finite = sorted(
                ((float(le), v) for le, v in s["buckets"]
                 if le != "+Inf"))
            counts = [v for _, v in finite] + inf
            assert counts == sorted(counts), \
                f"{name}{key}: buckets not monotonic: {counts}"
    return families


# ---------------------------------------------------------------------
# renderer unit contracts

class TestExpositionFormat:
    def test_label_escaping_roundtrip(self):
        reg = Registry()
        c = reg.counter("t", "esc", "escaping test", labels=("who",))
        hostile = 'mon"iker\\with\nnewline'
        c.with_labels(hostile).add(3)
        fams = assert_exposition_contract(reg.render())
        (_, labels, value), = fams["cometbft_t_esc"]["samples"]
        assert labels["who"] == hostile
        assert value == 3

    def test_help_escaping(self):
        reg = Registry()
        reg.gauge("t", "h", "line one\nline two")
        fams = parse_exposition(reg.render())
        assert fams["cometbft_t_h"]["help"] == "line one\nline two"

    def test_histogram_contract_and_exemplars(self):
        reg = Registry()
        h = reg.histogram("t", "lat", "latency", labels=("be",),
                          buckets=(0.1, 1.0))
        old = tracing.set_recorder(tracing.Recorder())
        try:
            tracing.set_height(42)
            h.with_labels("cpu").observe(0.05)
            h.with_labels("cpu").observe(3.0)
        finally:
            tracing.set_recorder(old)
        assert_exposition_contract(reg.render())
        # default render carries no exemplar syntax
        assert " # {" not in reg.render()
        out = reg.render(exemplars=True)
        assert 'trace_height="42"' in out
        # the exemplar rides the bucket the observation fell into
        line = [ln for ln in out.splitlines()
                if 'le="0.1"' in ln][0]
        assert "# {" in line and " 0.05 " in line

    def test_openmetrics_counter_total_suffix(self):
        """The exemplar page is OpenMetrics: counter samples carry
        the mandatory _total suffix and already-suffixed names don't
        double it."""
        reg = Registry()
        reg.counter("t", "ops", "plain counter").add(3)
        reg.counter("t", "bytes_total", "pre-suffixed").add(7)
        om = reg.render(exemplars=True)
        assert "cometbft_t_ops_total 3" in om
        assert "# TYPE cometbft_t_ops counter" in om
        assert "cometbft_t_bytes_total 7" in om
        assert "# TYPE cometbft_t_bytes counter" in om
        assert "bytes_total_total" not in om
        # default text-format render is unchanged
        plain = reg.render()
        assert "cometbft_t_ops 3" in plain
        assert "cometbft_t_ops_total" not in plain

    def test_render_merged_dedups_families(self):
        a, b = Registry(), Registry()
        a.counter("t", "x", "from a").add(1)
        b.counter("t", "x", "from b").add(5)
        b.counter("t", "y", "only b").add(2)
        out = render_merged(a, b)
        assert out.count("# TYPE cometbft_t_x counter") == 1
        assert "cometbft_t_x 1" in out       # first registry wins
        assert "cometbft_t_y 2" in out
        assert_exposition_contract(out)


# ---------------------------------------------------------------------
# cardinality / help guards (tier-1 CI satellite)

def _assemble_full_registry() -> Registry:
    """Every subsystem family a node registers, on one registry."""
    from cometbft_tpu.abci.metrics import Metrics as ProxyMetrics
    from cometbft_tpu.blocksync.metrics import (
        Metrics as BlocksyncMetrics,
    )
    from cometbft_tpu.consensus.metrics import (
        Metrics as ConsensusMetrics,
    )
    from cometbft_tpu.libs.supervisor import (
        Metrics as SupervisorMetrics,
    )
    from cometbft_tpu.lightserve.cache import (
        Metrics as LightserveMetrics,
    )
    from cometbft_tpu.mempool.metrics import Metrics as MempoolMetrics
    from cometbft_tpu.p2p.metrics import Metrics as P2PMetrics
    from cometbft_tpu.state.metrics import Metrics as StateMetrics
    from cometbft_tpu.statesync.metrics import (
        Metrics as StatesyncMetrics,
    )
    reg = Registry()
    for cls in (ConsensusMetrics, MempoolMetrics, P2PMetrics,
                BlocksyncMetrics, StatesyncMetrics, StateMetrics,
                ProxyMetrics, SupervisorMetrics, LightserveMetrics):
        cls(reg)
    return reg


# label names whose value sets are bounded by construction: protocol
# enums, claimed channel ids, config-capped peer slots, app-declared
# lanes (all further capped by the per-family child ceiling).
# Unbounded identifiers — tx hashes, heights, addresses-as-labels on
# histograms — must never appear here.
_ALLOWED_LABELS = {
    "step", "peer_id", "chID", "lane", "matches_current",
    "proposer_address", "status", "vote_type", "is_timely", "method",
    "conn", "type", "supervisor", "kind", "task", "backend",
    "pad_bucket", "phase", "kernel", "warm", "name", "le",
    "breaker",      # code-defined breaker names (crypto_tpu_kernel)
    "state",        # breaker state enum (closed/half-open/open/latched)
    "worker",       # verification workers: hard-coded names at the
                    # few SupervisedWorker construction sites
                    # (verify_stage / verify_kernel)
}


class TestCardinalityGuard:
    def test_every_family_has_help_and_bounded_labels(self):
        # also pull in the lazily-registered process-global families
        from cometbft_tpu.crypto import batch as crypto_batch
        from cometbft_tpu.types import signature_cache
        crypto_batch.verify_seconds_histogram()
        crypto_batch.tpu_breaker()
        signature_cache._metrics()
        reg = _assemble_full_registry()
        for fam in reg.collect() + DEFAULT.collect():
            assert fam["help"].strip(), \
                f"{fam['name']}: empty help text"
            for label in fam["labels"]:
                assert label in _ALLOWED_LABELS, (
                    f"{fam['name']}: label {label!r} not in the "
                    f"bounded-label allowlist — unbounded label sets "
                    f"blow up scrape size under churn")

    def test_child_cap_collapses_into_overflow_series(self):
        reg = Registry()
        c = reg.counter("t", "churn", "per-peer churn",
                        labels=("peer_id",))
        c.max_children = 8
        for i in range(100):
            c.with_labels(f"peer-{i}").add()
        fams = parse_exposition(reg.render())
        samples = fams["cometbft_t_churn"]["samples"]
        assert len(samples) == 9        # 8 distinct + 1 overflow
        overflow = [v for _, labels, v in samples
                    if labels["peer_id"] == "overflow"]
        assert overflow == [100 - 8]
        # total observations survive the collapse
        assert sum(v for _, _, v in samples) == 100

    def test_default_cap_is_sane(self):
        assert 512 <= _CHILDREN_MAX <= 16384

    def test_pad_bucket_matches_kernel_buckets(self):
        """crypto/batch.pad_bucket mirrors ops/ed25519_jax._bucket so
        CPU and TPU observations share label values."""
        from cometbft_tpu.crypto import batch as crypto_batch
        from cometbft_tpu.ops import ed25519_jax
        assert tuple(crypto_batch.PAD_BUCKETS) == \
            tuple(ed25519_jax._BUCKETS)
        for n in (1, 63, 64, 65, 1024, 5000, 10**6):
            assert crypto_batch.pad_bucket(n) == \
                ed25519_jax._bucket(n)


# ---------------------------------------------------------------------
# acceptance: the full exposition of a live multi-validator run

async def _fetch(addr: str, path: str) -> str:
    host, port = addr.rsplit(":", 1)
    reader, writer = await asyncio.open_connection(host, int(port))
    writer.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n"
                 f"Connection: close\r\n\r\n".encode())
    await writer.drain()
    raw = await reader.read(-1)
    writer.close()
    return raw.split(b"\r\n\r\n", 1)[1].decode()


def _mk_cfg(d, name):
    from cometbft_tpu.config import Config
    from cometbft_tpu.p2p.key import NodeKey
    from cometbft_tpu.privval import FilePV
    home = os.path.join(d, name)
    cfg = Config()
    cfg.base.home = home
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.p2p.allow_duplicate_ip = True
    cfg.consensus.timeout_commit_ns = 30_000_000
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    pv = FilePV.generate(
        cfg.base.path(cfg.base.priv_validator_key_file),
        cfg.base.path(cfg.base.priv_validator_state_file))
    NodeKey.load_or_gen(cfg.base.path(cfg.base.node_key_file))
    return cfg, pv


class TestLiveExpositionContract:
    def test_live_multi_validator_metrics_contract(self):
        """GET /metrics on a live 3-validator net passes the full
        exposition contract AND serves the metrics-v2 histogram
        families the perf analyses hang off: consensus step duration,
        quorum-prevote delay, batch-verify latency (by backend + pad
        bucket), ABCI call latency, p2p queue-stall duration."""
        from cometbft_tpu.node.node import Node
        from cometbft_tpu.rpc.client import HTTPClient
        from cometbft_tpu.types.genesis import (
            GenesisDoc, GenesisValidator,
        )
        from cometbft_tpu.types.timestamp import Timestamp

        async def run():
            with tempfile.TemporaryDirectory() as d:
                cfgs = [_mk_cfg(d, f"n{i}") for i in range(3)]
                gen = GenesisDoc(
                    chain_id="contract-chain",
                    genesis_time=Timestamp.now(),
                    validators=[GenesisValidator(
                        address=b"", pub_key=pv.get_pub_key(),
                        power=10) for _, pv in cfgs])
                for cfg, _ in cfgs:
                    gen.save_as(cfg.base.path(cfg.base.genesis_file))
                nodes = [Node(cfg) for cfg, _ in cfgs]
                for n in nodes:
                    await n.start()
                try:
                    for i, a in enumerate(nodes):
                        for b in nodes[i + 1:]:
                            await a.switch.dial_peer(
                                b.switch.listen_addr)
                    cli = HTTPClient(
                        f"http://{nodes[0]._rpc_server.listen_addr}",
                        timeout=30.0)
                    for i in range(4):
                        await cli.broadcast_tx_sync(
                            b"contract%d=v" % i)
                    for _ in range(600):
                        if all(n.height >= 4 for n in nodes):
                            break
                        await asyncio.sleep(0.02)
                    assert all(n.height >= 4 for n in nodes), \
                        "net did not progress"
                    addr = nodes[0]._rpc_server.listen_addr
                    body = await _fetch(addr, "/metrics")
                    fams = assert_exposition_contract(body)

                    def hist_observed(name, **want_labels):
                        fam = fams.get(name)
                        assert fam is not None, f"missing {name}"
                        assert fam["type"] == "histogram", name
                        for s_name, labels, v in fam["samples"]:
                            if not s_name.endswith("_count"):
                                continue
                            if all(labels.get(k) == v2 for k, v2
                                   in want_labels.items()) and v > 0:
                                return True
                        return False

                    assert hist_observed(
                        "cometbft_consensus_step_duration_seconds")
                    assert hist_observed(
                        "cometbft_consensus_"
                        "quorum_prevote_delay_seconds")
                    assert hist_observed(
                        "cometbft_consensus_block_interval_seconds")
                    assert hist_observed(
                        "cometbft_consensus_rounds_per_height")
                    assert hist_observed(
                        "cometbft_proxy_method_timing_seconds",
                        conn="consensus")
                    assert hist_observed(
                        "cometbft_mempool_checktx_duration_seconds")
                    assert hist_observed(
                        "cometbft_p2p_message_send_size_bytes")
                    # batch-verify rode the live commit-verification
                    # path, labeled by backend and pad bucket
                    assert hist_observed(
                        "cometbft_crypto_batch_verify_seconds",
                        backend="cpu", pad_bucket="64")
                    # the stall family serves its full bucket ladder
                    # even before any stall happened
                    stall = fams[
                        "cometbft_p2p_queue_stall_seconds"]
                    assert any(
                        s.endswith("_bucket")
                        for s, _, _ in stall["samples"])
                    # exemplar mode: OpenMetrics output, bucket
                    # observations link to a trace height
                    om = await _fetch(addr, "/metrics?exemplars=1")
                    assert 'trace_height="' in om
                finally:
                    for n in nodes:
                        await n.stop()
        asyncio.run(run())
