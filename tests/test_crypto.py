"""Crypto layer tests.

Modeled on the reference's crypto tests: crypto/ed25519/ed25519_test.go
(sign/verify round-trip, batch verify), crypto/merkle/tree_test.go
(root/proof construction + RFC-6962 vectors).
"""
import hashlib

import pytest

from cometbft_tpu.crypto import ed25519, merkle, tmhash, batch
from cometbft_tpu.crypto import _ed25519_ref as ref


class TestEd25519:
    def test_sign_verify_roundtrip(self):
        priv = ed25519.gen_priv_key()
        pub = priv.pub_key()
        msg = b"consensus is hard"
        sig = priv.sign(msg)
        assert len(sig) == 64
        assert pub.verify_signature(msg, sig)
        assert not pub.verify_signature(b"other", sig)
        assert not pub.verify_signature(msg, b"\x00" * 64)
        assert not pub.verify_signature(msg, sig[:-1])

    def test_pure_python_ref_matches_openssl(self):
        priv = ed25519.gen_priv_key()
        seed = priv.bytes()[:32]
        assert ref.public_key(seed) == priv.pub_key().bytes()
        msg = b"golden model agreement"
        assert ref.sign(seed, msg) == priv.sign(msg)
        assert ref.verify(priv.pub_key().bytes(), msg, priv.sign(msg))

    def test_deterministic_from_secret(self):
        a = ed25519.gen_priv_key_from_secret(b"hello")
        b = ed25519.gen_priv_key_from_secret(b"hello")
        assert a.bytes() == b.bytes()
        assert a.bytes()[:32] == tmhash.sum(b"hello")

    def test_address_is_truncated_sha256(self):
        priv = ed25519.gen_priv_key()
        pub = priv.pub_key()
        assert pub.address() == hashlib.sha256(pub.bytes()).digest()[:20]
        assert len(pub.address()) == 20

    def test_noncanonical_s_rejected(self):
        priv = ed25519.gen_priv_key()
        msg = b"m"
        sig = bytearray(priv.sign(msg))
        s = int.from_bytes(sig[32:], "little")
        bad = (s + ref.L).to_bytes(32, "little")
        sig[32:] = bad
        assert not priv.pub_key().verify_signature(msg, bytes(sig))
        assert not ref.verify(priv.pub_key().bytes(), msg, bytes(sig))

    def test_zip215_batch_equation(self):
        items = []
        for i in range(8):
            priv = ed25519.gen_priv_key()
            msg = f"vote {i}".encode()
            items.append((priv.pub_key().bytes(), msg, priv.sign(msg)))
        ok, per = ref.batch_verify(items)
        assert ok and all(per)
        # corrupt one signature -> batch fails, per-sig mask identifies it
        bad = bytearray(items[3][2])
        bad[0] ^= 0xFF
        items[3] = (items[3][0], items[3][1], bytes(bad))
        ok, per = ref.batch_verify(items)
        assert not ok
        assert per == [True, True, True, False, True, True, True, True]

    def test_cpu_batch_verifier(self):
        bv = ed25519.CpuBatchVerifier()
        privs = [ed25519.gen_priv_key() for _ in range(5)]
        for i, p in enumerate(privs):
            msg = f"height {i}".encode()
            bv.add(p.pub_key(), msg, p.sign(msg))
        ok, per = bv.verify()
        assert ok and list(per) == [True] * 5

    def test_batch_dispatch(self):
        pub = ed25519.gen_priv_key().pub_key()
        assert batch.supports_batch_verifier(pub)
        bv = batch.create_batch_verifier(pub)
        assert bv is not None


class TestMerkle:
    def test_empty_root(self):
        assert merkle.hash_from_byte_slices([]) == hashlib.sha256(b"").digest()

    def test_single_leaf(self):
        assert merkle.hash_from_byte_slices([b"x"]) == \
            hashlib.sha256(b"\x00x").digest()

    def test_two_leaves(self):
        l0 = hashlib.sha256(b"\x00a").digest()
        l1 = hashlib.sha256(b"\x00b").digest()
        assert merkle.hash_from_byte_slices([b"a", b"b"]) == \
            hashlib.sha256(b"\x01" + l0 + l1).digest()

    @pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 7, 8, 9, 33, 100])
    def test_proofs_verify(self, n):
        items = [f"item-{i}".encode() for i in range(n)]
        root, proofs = merkle.proofs_from_byte_slices(items)
        assert root == merkle.hash_from_byte_slices(items)
        for i, p in enumerate(proofs):
            p.verify(root, items[i])
            with pytest.raises(ValueError):
                p.verify(root, b"wrong")
        # proof for item i must not verify at root of modified set
        items2 = list(items)
        items2[0] = b"evil"
        root2 = merkle.hash_from_byte_slices(items2)
        if root2 != root:
            with pytest.raises(ValueError):
                proofs[0].verify(root2, items[0])

    def test_split_point(self):
        assert merkle._split_point(2) == 1
        assert merkle._split_point(3) == 2
        assert merkle._split_point(4) == 2
        assert merkle._split_point(5) == 4
        assert merkle._split_point(8) == 4
        assert merkle._split_point(9) == 8


class TestValueOp:
    def test_value_op_binds_key(self):
        """Leaf is leafHash(uvarint-len(key)+key + uvarint-len(vhash)+vhash)
        (reference: crypto/merkle/proof_value.go:89-102)."""
        from cometbft_tpu.crypto import merkle, tmhash
        kvs = [(b"k%d" % i, b"v%d" % i) for i in range(5)]
        leaves = []
        for k, v in kvs:
            vhash = tmhash.sum(v)
            leaves.append(merkle._uvarint(len(k)) + k +
                          merkle._uvarint(len(vhash)) + vhash)
        root, proofs = merkle.proofs_from_byte_slices(leaves)
        op = merkle.ValueOp(key=kvs[2][0], proof=proofs[2])
        ops = merkle.ProofOperators([op])
        ops.verify_value(root, [kvs[2][0]], kvs[2][1])  # succeeds
        import pytest
        with pytest.raises(ValueError):
            ops.verify_value(root, [kvs[2][0]], b"wrong-value")
        # a proof for k2 must not verify under a different claimed key
        op_bad = merkle.ValueOp(key=b"k3", proof=proofs[2])
        with pytest.raises(ValueError):
            merkle.ProofOperators([op_bad]).verify_value(root, [b"k3"], kvs[2][1])


class TestSSWUDerivation:
    def test_iso3_kernel_rederives_from_curve_params(self):
        """The 3-isogeny E' -> E is derived offline with Vélu's
        formulas; re-derive the kernel x-coordinate from the division
        polynomial psi3 = 3x^4 + 6A'x^2 + 12B'x - A'^2 via
        gcd(psi3, x^(p^2) - x) and assert the committed constant
        (RFC 9380 §8.8.2 cross-check: the composed x-numerator's
        leading coefficient equals the RFC's k_(1,3) = 1/9 mod p)."""
        from cometbft_tpu.crypto import _bls12381_math as M

        A_, B_ = M.SSWU_A, M.SSWU_B
        P = M.P

        def padd(a, b):
            n = max(len(a), len(b))
            a = a + [(0, 0)] * (n - len(a))
            b = b + [(0, 0)] * (n - len(b))
            return [M.f2_add(x, y) for x, y in zip(a, b)]

        def pneg(a):
            return [M.f2_neg(x) for x in a]

        def pmul(a, b):
            out = [(0, 0)] * (len(a) + len(b) - 1)
            for i, x in enumerate(a):
                for j, y in enumerate(b):
                    out[i + j] = M.f2_add(out[i + j], M.f2_mul(x, y))
            return out

        def ptrim(a):
            while len(a) > 1 and a[-1] == (0, 0):
                a = a[:-1]
            return a

        def pmod(a, m):
            a, m = ptrim(a[:]), ptrim(m)
            dm = len(m) - 1
            inv_lead = M.f2_inv(m[-1])
            while len(a) - 1 >= dm and a != [(0, 0)]:
                k = len(a) - 1 - dm
                c = M.f2_mul(a[-1], inv_lead)
                sub = [(0, 0)] * k + [M.f2_mul(c, t) for t in m]
                a = ptrim(padd(a, pneg(sub)))
            return a

        A2 = M.f2_mul(A_, A_)
        psi3 = [M.f2_neg(A2), M.f2_muls(B_, 12), M.f2_muls(A_, 6),
                (0, 0), (3, 0)]
        # x^(p^2) mod psi3
        result, base, e = [(1, 0)], [(0, 0), (1, 0)], P * P
        while e:
            if e & 1:
                result = pmod(pmul(result, base), psi3)
            base = pmod(pmul(base, base), psi3)
            e >>= 1
        g = padd(result, pneg([(0, 0), (1, 0)]))
        # gcd
        a, b = psi3, g
        a, b = ptrim(a), ptrim(b)
        while b != [(0, 0)]:
            a, b = b, pmod(a, b)
        inv = M.f2_inv(a[-1])
        a = [M.f2_mul(inv, t) for t in a]
        assert len(a) - 1 == 1, "kernel x-coord must be unique"
        x0 = M.f2_neg(a[0])
        assert x0 == M.ISO3_X0

        # Vélu lands on y^2 = x^3 + 3^6·4(1+i); scaled by (1/9, 1/27)
        tQ = M.f2_muls(M.f2_add(M.f2_muls(M.f2_sqr(x0), 3), A_), 2)
        uQ = M.f2_muls(M.f2_add(
            M.f2_mul(M.f2_sqr(x0), x0),
            M.f2_add(M.f2_mul(A_, x0), B_)), 4)
        w = M.f2_add(uQ, M.f2_mul(x0, tQ))
        A_E = M.f2_sub(A_, M.f2_muls(tQ, 5))
        B_E = M.f2_sub(B_, M.f2_muls(w, 7))
        assert A_E == (0, 0)
        assert B_E == M.f2_muls(M.G2_B, 729)     # 3^6 · 4(1+i)
        # RFC k_(1,3) confirmation
        assert pow(9, P - 2, P) == int(
            "171d6541fa38ccfaed6dea691f5fb614cb14b4e7f4e810aa22d6108f"
            "142b85757098e38d0f671c7188e2aaaaaaaa5ed1", 16)
        # h_eff against RFC 9380 §8.8.2's literal value (independent
        # of the module's own closed-form definition)
        assert M.H_EFF == int(
            "bc69f08f2ee75b3584c6a0ea91b352888e2a8e9145ad7689986ff0"
            "31508ffe1329c2f178731db956d82bf015d1212b02ec0ec69d7477c"
            "1ae954cbc06689f6a359894c0adebbf6b4e8020005aaa95551", 16)

    def test_sswu_map_and_hash_properties(self, monkeypatch):
        """SSWU output is on E', the isogeny image is on E, and the
        full hash is deterministic and lands in G2 — for the blst
        ciphersuite DST (reference: key_bls12381.go)."""
        monkeypatch.setenv("COMETBFT_TPU_NATIVE", "0")
        from cometbft_tpu.crypto import _bls12381_math as M
        dst = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_NUL_"
        for msg in (b"", b"abc", b"a" * 130):
            for u in M.hash_to_field_fq2(msg, dst, 2):
                x, y = M._sswu_g2(u)
                g = M.f2_add(M.f2_mul(M.f2_sqr(x), x), M.f2_add(
                    M.f2_mul(M.SSWU_A, x), M.SSWU_B))
                assert M.f2_sqr(y) == g
                assert M._sgn0_fq2(y) == M._sgn0_fq2(u)
                pt = M._iso3_g2((x, y))
                assert M.pt_on_curve(M.G2_OPS, pt)
            h = M.hash_to_g2(msg, dst)
            assert M.g2_in_subgroup(h)
            assert h == M.hash_to_g2(msg, dst)
