"""Crypto layer tests.

Modeled on the reference's crypto tests: crypto/ed25519/ed25519_test.go
(sign/verify round-trip, batch verify), crypto/merkle/tree_test.go
(root/proof construction + RFC-6962 vectors).
"""
import hashlib

import pytest

from cometbft_tpu.crypto import ed25519, merkle, tmhash, batch
from cometbft_tpu.crypto import _ed25519_ref as ref


class TestEd25519:
    def test_sign_verify_roundtrip(self):
        priv = ed25519.gen_priv_key()
        pub = priv.pub_key()
        msg = b"consensus is hard"
        sig = priv.sign(msg)
        assert len(sig) == 64
        assert pub.verify_signature(msg, sig)
        assert not pub.verify_signature(b"other", sig)
        assert not pub.verify_signature(msg, b"\x00" * 64)
        assert not pub.verify_signature(msg, sig[:-1])

    def test_pure_python_ref_matches_openssl(self):
        priv = ed25519.gen_priv_key()
        seed = priv.bytes()[:32]
        assert ref.public_key(seed) == priv.pub_key().bytes()
        msg = b"golden model agreement"
        assert ref.sign(seed, msg) == priv.sign(msg)
        assert ref.verify(priv.pub_key().bytes(), msg, priv.sign(msg))

    def test_deterministic_from_secret(self):
        a = ed25519.gen_priv_key_from_secret(b"hello")
        b = ed25519.gen_priv_key_from_secret(b"hello")
        assert a.bytes() == b.bytes()
        assert a.bytes()[:32] == tmhash.sum(b"hello")

    def test_address_is_truncated_sha256(self):
        priv = ed25519.gen_priv_key()
        pub = priv.pub_key()
        assert pub.address() == hashlib.sha256(pub.bytes()).digest()[:20]
        assert len(pub.address()) == 20

    def test_noncanonical_s_rejected(self):
        priv = ed25519.gen_priv_key()
        msg = b"m"
        sig = bytearray(priv.sign(msg))
        s = int.from_bytes(sig[32:], "little")
        bad = (s + ref.L).to_bytes(32, "little")
        sig[32:] = bad
        assert not priv.pub_key().verify_signature(msg, bytes(sig))
        assert not ref.verify(priv.pub_key().bytes(), msg, bytes(sig))

    def test_zip215_batch_equation(self):
        items = []
        for i in range(8):
            priv = ed25519.gen_priv_key()
            msg = f"vote {i}".encode()
            items.append((priv.pub_key().bytes(), msg, priv.sign(msg)))
        ok, per = ref.batch_verify(items)
        assert ok and all(per)
        # corrupt one signature -> batch fails, per-sig mask identifies it
        bad = bytearray(items[3][2])
        bad[0] ^= 0xFF
        items[3] = (items[3][0], items[3][1], bytes(bad))
        ok, per = ref.batch_verify(items)
        assert not ok
        assert per == [True, True, True, False, True, True, True, True]

    def test_cpu_batch_verifier(self):
        bv = ed25519.CpuBatchVerifier()
        privs = [ed25519.gen_priv_key() for _ in range(5)]
        for i, p in enumerate(privs):
            msg = f"height {i}".encode()
            bv.add(p.pub_key(), msg, p.sign(msg))
        ok, per = bv.verify()
        assert ok and list(per) == [True] * 5

    def test_batch_dispatch(self):
        pub = ed25519.gen_priv_key().pub_key()
        assert batch.supports_batch_verifier(pub)
        bv = batch.create_batch_verifier(pub)
        assert bv is not None


class TestMerkle:
    def test_empty_root(self):
        assert merkle.hash_from_byte_slices([]) == hashlib.sha256(b"").digest()

    def test_single_leaf(self):
        assert merkle.hash_from_byte_slices([b"x"]) == \
            hashlib.sha256(b"\x00x").digest()

    def test_two_leaves(self):
        l0 = hashlib.sha256(b"\x00a").digest()
        l1 = hashlib.sha256(b"\x00b").digest()
        assert merkle.hash_from_byte_slices([b"a", b"b"]) == \
            hashlib.sha256(b"\x01" + l0 + l1).digest()

    @pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 7, 8, 9, 33, 100])
    def test_proofs_verify(self, n):
        items = [f"item-{i}".encode() for i in range(n)]
        root, proofs = merkle.proofs_from_byte_slices(items)
        assert root == merkle.hash_from_byte_slices(items)
        for i, p in enumerate(proofs):
            p.verify(root, items[i])
            with pytest.raises(ValueError):
                p.verify(root, b"wrong")
        # proof for item i must not verify at root of modified set
        items2 = list(items)
        items2[0] = b"evil"
        root2 = merkle.hash_from_byte_slices(items2)
        if root2 != root:
            with pytest.raises(ValueError):
                proofs[0].verify(root2, items[0])

    def test_split_point(self):
        assert merkle._split_point(2) == 1
        assert merkle._split_point(3) == 2
        assert merkle._split_point(4) == 2
        assert merkle._split_point(5) == 4
        assert merkle._split_point(8) == 4
        assert merkle._split_point(9) == 8


class TestValueOp:
    def test_value_op_binds_key(self):
        """Leaf is leafHash(uvarint-len(key)+key + uvarint-len(vhash)+vhash)
        (reference: crypto/merkle/proof_value.go:89-102)."""
        from cometbft_tpu.crypto import merkle, tmhash
        kvs = [(b"k%d" % i, b"v%d" % i) for i in range(5)]
        leaves = []
        for k, v in kvs:
            vhash = tmhash.sum(v)
            leaves.append(merkle._uvarint(len(k)) + k +
                          merkle._uvarint(len(vhash)) + vhash)
        root, proofs = merkle.proofs_from_byte_slices(leaves)
        op = merkle.ValueOp(key=kvs[2][0], proof=proofs[2])
        ops = merkle.ProofOperators([op])
        ops.verify_value(root, [kvs[2][0]], kvs[2][1])  # succeeds
        import pytest
        with pytest.raises(ValueError):
            ops.verify_value(root, [kvs[2][0]], b"wrong-value")
        # a proof for k2 must not verify under a different claimed key
        op_bad = merkle.ValueOp(key=b"k3", proof=proofs[2])
        with pytest.raises(ValueError):
            merkle.ProofOperators([op_bad]).verify_value(root, [b"k3"], kvs[2][1])
