"""Crypto layer tests.

Modeled on the reference's crypto tests: crypto/ed25519/ed25519_test.go
(sign/verify round-trip, batch verify), crypto/merkle/tree_test.go
(root/proof construction + RFC-6962 vectors).
"""
import hashlib

import pytest

from cometbft_tpu.crypto import ed25519, merkle, tmhash, batch
from cometbft_tpu.crypto import _ed25519_ref as ref


class TestEd25519:
    def test_sign_verify_roundtrip(self):
        priv = ed25519.gen_priv_key()
        pub = priv.pub_key()
        msg = b"consensus is hard"
        sig = priv.sign(msg)
        assert len(sig) == 64
        assert pub.verify_signature(msg, sig)
        assert not pub.verify_signature(b"other", sig)
        assert not pub.verify_signature(msg, b"\x00" * 64)
        assert not pub.verify_signature(msg, sig[:-1])

    def test_pure_python_ref_matches_openssl(self):
        priv = ed25519.gen_priv_key()
        seed = priv.bytes()[:32]
        assert ref.public_key(seed) == priv.pub_key().bytes()
        msg = b"golden model agreement"
        assert ref.sign(seed, msg) == priv.sign(msg)
        assert ref.verify(priv.pub_key().bytes(), msg, priv.sign(msg))

    def test_deterministic_from_secret(self):
        a = ed25519.gen_priv_key_from_secret(b"hello")
        b = ed25519.gen_priv_key_from_secret(b"hello")
        assert a.bytes() == b.bytes()
        assert a.bytes()[:32] == tmhash.sum(b"hello")

    def test_address_is_truncated_sha256(self):
        priv = ed25519.gen_priv_key()
        pub = priv.pub_key()
        assert pub.address() == hashlib.sha256(pub.bytes()).digest()[:20]
        assert len(pub.address()) == 20

    def test_noncanonical_s_rejected(self):
        priv = ed25519.gen_priv_key()
        msg = b"m"
        sig = bytearray(priv.sign(msg))
        s = int.from_bytes(sig[32:], "little")
        bad = (s + ref.L).to_bytes(32, "little")
        sig[32:] = bad
        assert not priv.pub_key().verify_signature(msg, bytes(sig))
        assert not ref.verify(priv.pub_key().bytes(), msg, bytes(sig))

    def test_zip215_batch_equation(self):
        items = []
        for i in range(8):
            priv = ed25519.gen_priv_key()
            msg = f"vote {i}".encode()
            items.append((priv.pub_key().bytes(), msg, priv.sign(msg)))
        ok, per = ref.batch_verify(items)
        assert ok and all(per)
        # corrupt one signature -> batch fails, per-sig mask identifies it
        bad = bytearray(items[3][2])
        bad[0] ^= 0xFF
        items[3] = (items[3][0], items[3][1], bytes(bad))
        ok, per = ref.batch_verify(items)
        assert not ok
        assert per == [True, True, True, False, True, True, True, True]

    def test_cpu_batch_verifier(self):
        bv = ed25519.CpuBatchVerifier()
        privs = [ed25519.gen_priv_key() for _ in range(5)]
        for i, p in enumerate(privs):
            msg = f"height {i}".encode()
            bv.add(p.pub_key(), msg, p.sign(msg))
        ok, per = bv.verify()
        assert ok and list(per) == [True] * 5

    def test_batch_dispatch(self):
        pub = ed25519.gen_priv_key().pub_key()
        assert batch.supports_batch_verifier(pub)
        bv = batch.create_batch_verifier(pub)
        assert bv is not None


class TestMerkle:
    def test_empty_root(self):
        assert merkle.hash_from_byte_slices([]) == hashlib.sha256(b"").digest()

    def test_single_leaf(self):
        assert merkle.hash_from_byte_slices([b"x"]) == \
            hashlib.sha256(b"\x00x").digest()

    def test_two_leaves(self):
        l0 = hashlib.sha256(b"\x00a").digest()
        l1 = hashlib.sha256(b"\x00b").digest()
        assert merkle.hash_from_byte_slices([b"a", b"b"]) == \
            hashlib.sha256(b"\x01" + l0 + l1).digest()

    @pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 7, 8, 9, 33, 100])
    def test_proofs_verify(self, n):
        items = [f"item-{i}".encode() for i in range(n)]
        root, proofs = merkle.proofs_from_byte_slices(items)
        assert root == merkle.hash_from_byte_slices(items)
        for i, p in enumerate(proofs):
            p.verify(root, items[i])
            with pytest.raises(ValueError):
                p.verify(root, b"wrong")
        # proof for item i must not verify at root of modified set
        items2 = list(items)
        items2[0] = b"evil"
        root2 = merkle.hash_from_byte_slices(items2)
        if root2 != root:
            with pytest.raises(ValueError):
                proofs[0].verify(root2, items[0])

    def test_split_point(self):
        assert merkle._split_point(2) == 1
        assert merkle._split_point(3) == 2
        assert merkle._split_point(4) == 2
        assert merkle._split_point(5) == 4
        assert merkle._split_point(8) == 4
        assert merkle._split_point(9) == 8


class TestValueOp:
    def test_value_op_binds_key(self):
        """Leaf is leafHash(uvarint-len(key)+key + uvarint-len(vhash)+vhash)
        (reference: crypto/merkle/proof_value.go:89-102)."""
        from cometbft_tpu.crypto import merkle, tmhash
        kvs = [(b"k%d" % i, b"v%d" % i) for i in range(5)]
        leaves = []
        for k, v in kvs:
            vhash = tmhash.sum(v)
            leaves.append(merkle._uvarint(len(k)) + k +
                          merkle._uvarint(len(vhash)) + vhash)
        root, proofs = merkle.proofs_from_byte_slices(leaves)
        op = merkle.ValueOp(key=kvs[2][0], proof=proofs[2])
        ops = merkle.ProofOperators([op])
        ops.verify_value(root, [kvs[2][0]], kvs[2][1])  # succeeds
        import pytest
        with pytest.raises(ValueError):
            ops.verify_value(root, [kvs[2][0]], b"wrong-value")
        # a proof for k2 must not verify under a different claimed key
        op_bad = merkle.ValueOp(key=b"k3", proof=proofs[2])
        with pytest.raises(ValueError):
            merkle.ProofOperators([op_bad]).verify_value(root, [b"k3"], kvs[2][1])


class TestSSWUDerivation:
    def test_iso3_kernel_rederives_from_curve_params(self):
        """The 3-isogeny E' -> E is derived offline with Vélu's
        formulas; re-derive the kernel x-coordinate from the division
        polynomial psi3 = 3x^4 + 6A'x^2 + 12B'x - A'^2 via
        gcd(psi3, x^(p^2) - x) and assert the committed constant
        (RFC 9380 §8.8.2 cross-check: the composed x-numerator's
        leading coefficient equals the RFC's k_(1,3) = 1/9 mod p)."""
        from cometbft_tpu.crypto import _bls12381_math as M

        A_, B_ = M.SSWU_A, M.SSWU_B
        P = M.P

        def padd(a, b):
            n = max(len(a), len(b))
            a = a + [(0, 0)] * (n - len(a))
            b = b + [(0, 0)] * (n - len(b))
            return [M.f2_add(x, y) for x, y in zip(a, b)]

        def pneg(a):
            return [M.f2_neg(x) for x in a]

        def pmul(a, b):
            out = [(0, 0)] * (len(a) + len(b) - 1)
            for i, x in enumerate(a):
                for j, y in enumerate(b):
                    out[i + j] = M.f2_add(out[i + j], M.f2_mul(x, y))
            return out

        def ptrim(a):
            while len(a) > 1 and a[-1] == (0, 0):
                a = a[:-1]
            return a

        def pmod(a, m):
            a, m = ptrim(a[:]), ptrim(m)
            dm = len(m) - 1
            inv_lead = M.f2_inv(m[-1])
            while len(a) - 1 >= dm and a != [(0, 0)]:
                k = len(a) - 1 - dm
                c = M.f2_mul(a[-1], inv_lead)
                sub = [(0, 0)] * k + [M.f2_mul(c, t) for t in m]
                a = ptrim(padd(a, pneg(sub)))
            return a

        A2 = M.f2_mul(A_, A_)
        psi3 = [M.f2_neg(A2), M.f2_muls(B_, 12), M.f2_muls(A_, 6),
                (0, 0), (3, 0)]
        # x^(p^2) mod psi3
        result, base, e = [(1, 0)], [(0, 0), (1, 0)], P * P
        while e:
            if e & 1:
                result = pmod(pmul(result, base), psi3)
            base = pmod(pmul(base, base), psi3)
            e >>= 1
        g = padd(result, pneg([(0, 0), (1, 0)]))
        # gcd
        a, b = psi3, g
        a, b = ptrim(a), ptrim(b)
        while b != [(0, 0)]:
            a, b = b, pmod(a, b)
        inv = M.f2_inv(a[-1])
        a = [M.f2_mul(inv, t) for t in a]
        assert len(a) - 1 == 1, "kernel x-coord must be unique"
        x0 = M.f2_neg(a[0])
        assert x0 == M.ISO3_X0

        # Vélu lands on y^2 = x^3 + 3^6·4(1+i); scaled by (1/9, 1/27)
        tQ = M.f2_muls(M.f2_add(M.f2_muls(M.f2_sqr(x0), 3), A_), 2)
        uQ = M.f2_muls(M.f2_add(
            M.f2_mul(M.f2_sqr(x0), x0),
            M.f2_add(M.f2_mul(A_, x0), B_)), 4)
        w = M.f2_add(uQ, M.f2_mul(x0, tQ))
        A_E = M.f2_sub(A_, M.f2_muls(tQ, 5))
        B_E = M.f2_sub(B_, M.f2_muls(w, 7))
        assert A_E == (0, 0)
        assert B_E == M.f2_muls(M.G2_B, 729)     # 3^6 · 4(1+i)
        # RFC k_(1,3) confirmation
        assert pow(9, P - 2, P) == int(
            "171d6541fa38ccfaed6dea691f5fb614cb14b4e7f4e810aa22d6108f"
            "142b85757098e38d0f671c7188e2aaaaaaaa5ed1", 16)
        # h_eff against RFC 9380 §8.8.2's literal value (independent
        # of the module's own closed-form definition)
        assert M.H_EFF == int(
            "bc69f08f2ee75b3584c6a0ea91b352888e2a8e9145ad7689986ff0"
            "31508ffe1329c2f178731db956d82bf015d1212b02ec0ec69d7477c"
            "1ae954cbc06689f6a359894c0adebbf6b4e8020005aaa95551", 16)

    def test_sswu_map_and_hash_properties(self, monkeypatch):
        """SSWU output is on E', the isogeny image is on E, and the
        full hash is deterministic and lands in G2 — for the blst
        ciphersuite DST (reference: key_bls12381.go)."""
        monkeypatch.setenv("COMETBFT_TPU_NATIVE", "0")
        from cometbft_tpu.crypto import _bls12381_math as M
        dst = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_NUL_"
        for msg in (b"", b"abc", b"a" * 130):
            for u in M.hash_to_field_fq2(msg, dst, 2):
                x, y = M._sswu_g2(u)
                g = M.f2_add(M.f2_mul(M.f2_sqr(x), x), M.f2_add(
                    M.f2_mul(M.SSWU_A, x), M.SSWU_B))
                assert M.f2_sqr(y) == g
                assert M._sgn0_fq2(y) == M._sgn0_fq2(u)
                pt = M._iso3_g2((x, y))
                assert M.pt_on_curve(M.G2_OPS, pt)
            h = M.hash_to_g2(msg, dst)
            assert M.g2_in_subgroup(h)
            assert h == M.hash_to_g2(msg, dst)


class TestRFC9380Vectors:
    """Known-answer vectors from RFC 9380 appendices — the interop
    pin for the hash-to-curve pipeline (reference: blst's HashToG2
    behind crypto/bls12381/key_bls12381.go).  Property tests cannot
    catch a globally inverted y sign (negation commutes with point
    addition and cofactor clearing, so -P passes on-curve/subgroup/
    x-coordinate checks for every message); these vectors do.
    """

    # RFC 9380 K.1: expand_message_xmd(SHA-256), len_in_bytes=0x20
    K1_DST = b"QUUX-V01-CS02-with-expander-SHA256-128"
    K1 = [
        (b"", "68a985b87eb6b46952128911f2a4412bbc302a9d759667f87f"
              "7a21d803f07235"),
        (b"abc", "d8ccab23b5985ccea865c6c97b6e5b8350e794e603b4b979"
                 "02f53a8a0d605615"),
        (b"abcdef0123456789", "eff31487c770a893cfb36f912fbfcbff40d5"
                              "661771ca4b2cb4eafe524333f5c1"),
        (b"q128_" + b"q" * 128, "b23a1d2b4d97b2ef7785562a7e8bac7eed"
                                "54ed6e97e29aa51bfe3f12ddad1ff9"),
        (b"a512_" + b"a" * 512, "4623227bcc01293b8c130bf771da8c29"
                                "8dede7383243dc0993d2d94823958c4c"),
    ]

    def test_expand_message_xmd_k1(self):
        from cometbft_tpu.crypto import _bls12381_math as M
        for msg, want in self.K1:
            got = M.expand_message_xmd(msg, self.K1_DST, 0x20).hex()
            assert got == want, msg

    # RFC 9380 J.10.1: BLS12381G2_XMD:SHA-256_SSWU_RO_ — final output
    # point P = (x0 + i*x1, y0 + i*y1) for the five appendix messages.
    J101_DST = b"QUUX-V01-CS02-with-BLS12381G2_XMD:SHA-256_SSWU_RO_"
    J101 = [
        (b"",
         "0141ebfbdca40eb85b87142e130ab689c673cf60f1a3e98d69335266f30d"
         "9b8d4ac44c1038e9dcdd5393faf5c41fb78a",
         "05cb8437535e20ecffaef7752baddf98034139c38452458baeefab379ba1"
         "3dff5bf5dd71b72418717047f5b0f37da03d",
         "0503921d7f6a12805e72940b963c0cf3471c7b2a524950ca195d11062ee7"
         "5ec076daf2d4bc358c4b190c0c98064fdd92",
         "12424ac32561493f3fe3c260708a12b7c620e7be00099a974e259ddc7d1f"
         "6395c3c811cdd19f1e8dbf3e9ecfdcbab8d6"),
        (b"abc",
         "02c2d18e033b960562aae3cab37a27ce00d80ccd5ba4b7fe0e7a21024512"
         "9dbec7780ccc7954725f4168aff2787776e6",
         "139cddbccdc5e91b9623efd38c49f81a6f83f175e80b06fc374de9eb4b41"
         "dfe4ca3a230ed250fbe3a2acf73a41177fd8",
         "1787327b68159716a37440985269cf584bcb1e621d3a7202be6ea05c4cfe"
         "244aeb197642555a0645fb87bf7466b2ba48",
         "00aa65dae3c8d732d10ecd2c50f8a1baf3001578f71c694e03866e9f3d49"
         "ac1e1ce70dd94a733534f106d4cec0eddd16"),
        (b"abcdef0123456789",
         "121982811d2491fde9ba7ed31ef9ca474f0e1501297f68c298e9f4c0028"
         "add35aea8bb83d53c08cfc007c1e005723cd0",
         "190d119345b94fbd15497bcba94ecf7db2cbfd1e1fe7da034d26cbba169f"
         "b3968288b3fafb265f9ebd380512a71c3f2c",
         "05571a0f8d3c08d094576981f4a3b8eda0a8e771fcdcc8ecceaf1356a6ac"
         "f17574518acb506e435b639353c2e14827c8",
         "0bb5e7572275c567462d91807de765611490205a941a5a6af3b1691bfe59"
         "6c31225d3aabdf15faff860cb4ef17c7c3be"),
        (b"q128_" + b"q" * 128,
         "19a84dd7248a1066f737cc34502ee5555bd3c19f2ecdb3c7d9e24dc65d4e"
         "25e50d83f0f77105e955d78f4762d33c17da",
         "0934aba516a52d8ae479939a91998299c76d39cc0c035cd18813bec433f5"
         "87e2d7a4fef038260eef0cef4d02aae3eb91",
         "14f81cd421617428bc3b9fe25afbb751d934a00493524bc4e065635b0555"
         "084dd54679df1536101b2c979c0152d09192",
         "09bcccfa036b4847c9950780733633f13619994394c23ff0b32fa6b79584"
         "4f4a0673e20282d07bc69641cee04f5e5662"),
        (b"a512_" + b"a" * 512,
         "01a6ba2f9a11fa5598b2d8ace0fbe0a0eacb65deceb476fbbcb64fd24557"
         "c2f4b18ecfc5663e54ae16a84f5ab7f62534",
         "11fca2ff525572795a801eed17eb12785887c7b63fb77a42be46ce4a3413"
         "1d71f7a73e95fee3f812aea3de78b4d01569",
         "0b6798718c8aed24bc19cb27f866f1c9effcdbf92397ad6448b5c9db90d2"
         "b9da6cbabf48adc1adf59a1a28344e79d57e",
         "03a47f8e6d1763ba0cad63d6114c0accbef65707825a511b251a660a9b39"
         "94249ae4e63fac38b23da0c398689ee2ab52"),
    ]

    # hash_to_field intermediate for msg="" (same appendix): catches a
    # regression upstream of the curve maps with a precise finger.
    J101_U_EMPTY = (
        ("03dbc2cce174e91ba93cbb08f26b917f98194a2ea08d1cce75b2b9cc9f21"
         "689d80bd79b594a613d0a68eb807dfdc1cf8",
         "05a2acec64114845711a54199ea339abd125ba38253b70a92c876df10598"
         "bd1986b739cad67961eb94f7076511b3b39a"),
        ("02f99798e8a5acdeed60d7e18e9120521ba1f47ec090984662846bc825de"
         "191b5b7641148c0dbc237726a334473eee94",
         "145a81e418d4010cc027a68f14391b30074e89e60ee7a22f87217b2f6eb0"
         "c4b94c9115b436e6fa4607e95a98de30a435"),
    )

    def test_hash_to_field_j101(self, monkeypatch):
        from cometbft_tpu.crypto import _bls12381_math as M
        monkeypatch.setattr(M, "_native", lambda: None)
        u = M.hash_to_field_fq2(b"", self.J101_DST, 2)
        for got, want in zip(u, self.J101_U_EMPTY):
            assert got == (int(want[0], 16), int(want[1], 16))

    def _check_suite(self, M, hash_fn):
        for msg, x0, x1, y0, y1 in self.J101:
            (gx0, gx1), (gy0, gy1) = hash_fn(msg)
            assert gx0 == int(x0, 16) and gx1 == int(x1, 16), msg
            assert gy0 == int(y0, 16) and gy1 == int(y1, 16), msg

    def test_hash_to_g2_j101_python(self, monkeypatch):
        # monkeypatch the module's native hook, not the env var: the
        # loader caches the module after first load, so the env flag
        # cannot force the pure-python golden model mid-process
        from cometbft_tpu.crypto import _bls12381_math as M
        monkeypatch.setattr(M, "_native", lambda: None)
        self._check_suite(
            M, lambda msg: M.hash_to_g2(msg, self.J101_DST))

    def test_hash_to_g2_j101_native(self):
        from cometbft_tpu.crypto import _bls12381_math as M
        from cometbft_tpu.crypto import _native_loader
        import pytest
        if _native_loader.load() is None:
            pytest.skip("native module unavailable")
        self._check_suite(
            M, lambda msg: M._g2_unraw(
                _native_loader.load().bls_hash_to_g2(
                    msg, self.J101_DST)))

    def test_blst_interop_sign_triple(self, monkeypatch):
        """A fixed (sk, msg, signature) triple produced by a
        blst-based stack (the eth2 BLS sign suite,
        BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_): our sk*H(msg)
        must reproduce the blst signature BYTE-FOR-BYTE, pinning the
        full pipeline — expand, field hashing, SSWU, isogeny sign
        convention, cofactor, scalar mult, and compressed
        serialization — to blst's."""
        from cometbft_tpu.crypto import _bls12381_math as M
        monkeypatch.setattr(M, "_native", lambda: None)
        sk = int("328388aff0d4a5b7dc9205abd374e7e98f3cd9f3418edb4eaf"
                 "da5fb16473d216", 16)
        msg = bytes.fromhex("ab" * 32)
        dst = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"
        want_sig = bytes.fromhex(
            "ae82747ddeefe4fd64cf9cedb9b04ae3e8a43420cd255e3c7cd06a8d"
            "88b7c7f8638543719981c5d16fa3527c468c25f0026704a6951bde89"
            "1360c7e8d12ddee0559004ccdbe6046b55bae1b257ee97f7cdb95577"
            "3d7cf29adf3ccbb9975e4eb9")
        sig_pt = M.pt_mul(M.G2_OPS, M.hash_to_g2(msg, dst), sk)
        assert M.g2_compress(sig_pt) == want_sig
        # and the public verify equation holds for the triple
        pub = M.pt_mul(M.G1_OPS, M.G1_GEN, sk)
        neg_pub = (pub[0], M.P - pub[1])
        assert M.pairings_product_is_one(
            [(neg_pub, M.hash_to_g2(msg, dst)),
             (M.G1_GEN, M.g2_uncompress(want_sig))])
