"""C++ fast-path module (native/_native.cpp): build, correctness
against the pure-Python implementations, and fallback behavior."""
import hashlib
import secrets

import pytest

from cometbft_tpu.crypto import _native_loader, merkle


def _native():
    mod = _native_loader.load()
    if mod is None:
        pytest.skip("no compiler available")
    return mod


class TestNative:
    def test_sha256_parity(self):
        native = _native()
        for n in [0, 1, 55, 56, 63, 64, 65, 119, 120, 1000, 65537]:
            d = secrets.token_bytes(n)
            assert native.sha256(d) == hashlib.sha256(d).digest(), n

    def test_sha256_many(self):
        native = _native()
        items = [secrets.token_bytes(i * 13 % 300) for i in range(40)]
        cat = native.sha256_many(items)
        assert len(cat) == 40 * 32
        for i, m in enumerate(items):
            assert cat[i * 32:(i + 1) * 32] == \
                hashlib.sha256(m).digest()

    def test_merkle_root_parity(self):
        native = _native()
        for n in [0, 1, 2, 3, 5, 7, 8, 9, 64, 100, 257]:
            items = [secrets.token_bytes(30 + i % 70)
                     for i in range(n)]
            want = _py_root(items)
            assert native.merkle_root(items) == want, f"n={n}"
            assert merkle.hash_from_byte_slices(items) == want

    def test_leaf_hashes(self):
        native = _native()
        items = [b"a", b"bb", b"ccc"]
        cat = native.leaf_hashes(items)
        for i, it in enumerate(items):
            assert cat[i * 32:(i + 1) * 32] == merkle.leaf_hash(it)

    def test_proofs_still_verify_against_native_root(self):
        native = _native()
        items = [secrets.token_bytes(50) for _ in range(33)]
        root, proofs = merkle.proofs_from_byte_slices(items)
        assert root == native.merkle_root(items)
        for i, p in enumerate(proofs):
            p.verify(root, items[i])

    def test_disabled_fallback(self, monkeypatch):
        monkeypatch.setenv("COMETBFT_TPU_NATIVE", "0")
        monkeypatch.setattr(_native_loader, "_failed", False)
        monkeypatch.setattr(_native_loader, "_mod", None)
        assert _native_loader.load() is None
        items = [secrets.token_bytes(20) for _ in range(20)]
        assert merkle.hash_from_byte_slices(items) == _py_root(items)
        # restore for other tests
        monkeypatch.setenv("COMETBFT_TPU_NATIVE", "1")
        monkeypatch.setattr(_native_loader, "_failed", False)

    def test_no_build_on_hot_path(self, monkeypatch, tmp_path):
        """load(allow_build=False) must never shell out to g++."""
        import subprocess

        monkeypatch.setattr(_native_loader, "_failed", False)
        monkeypatch.setattr(_native_loader, "_mod", None)
        monkeypatch.setattr(_native_loader, "_target_path",
                            lambda: str(tmp_path / "absent.so"))

        def boom(*a, **kw):
            raise AssertionError("hot path invoked the compiler")
        monkeypatch.setattr(subprocess, "run", boom)
        assert _native_loader.load(allow_build=False) is None


def _py_root(items):
    n = len(items)
    if n == 0:
        return hashlib.sha256(b"").digest()
    if n == 1:
        return hashlib.sha256(b"\x00" + items[0]).digest()
    k = merkle._split_point(n)
    return hashlib.sha256(b"\x01" + _py_root(items[:k]) +
                          _py_root(items[k:])).digest()


class TestEd25519Prep:
    def test_malformed_items_marked_bad_no_error_state(self):
        """Non-tuple / wrong-length items become pre_bad lanes without
        leaving a live CPython error set (SystemError regression)."""
        native = _native()
        if not hasattr(native, "ed25519_prep"):
            pytest.skip("older native module")
        out = native.ed25519_prep(
            [None, 42, (b"x" * 32, b"m", b"s" * 64),
             (b"short", b"m", b"s" * 64)],
            8, b"b" * 32, b"i" * 32)
        a_b, r_b, s_win, k_win, bad = out
        assert bad[0] == 1 and bad[1] == 1 and bad[3] == 1
        # s_win is lane-major uint8 since the packed-wire rewrite
        assert len(a_b) == 8 * 32 and len(s_win) == 8 * 64


class TestSha512AndKScalars:
    def test_sha512_many_parity(self):
        native = _native()
        if not hasattr(native, "sha512_many"):
            pytest.skip("older native module")
        items = [secrets.token_bytes(n)
                 for n in (0, 1, 63, 64, 111, 112, 127, 128, 129,
                           500)]
        cat = native.sha512_many(items)
        for i, d in enumerate(items):
            assert cat[i * 64:(i + 1) * 64] == \
                hashlib.sha512(d).digest(), f"len {len(d)}"

    def test_kscalars_barrett_mod_l_parity(self):
        """The C Barrett reduction must match python big-int mod L —
        this backs ed25519_prep's k-scalar math."""
        native = _native()
        if not hasattr(native, "ed25519_kscalars"):
            pytest.skip("older native module")
        L = 2 ** 252 + 27742317777372353535851937790883648493
        items = [secrets.token_bytes(32 + i % 150)
                 for i in range(500)]
        cat = native.ed25519_kscalars(items)
        for i, d in enumerate(items):
            want = int.from_bytes(hashlib.sha512(d).digest(),
                                  "little") % L
            got = int.from_bytes(cat[i * 32:(i + 1) * 32], "little")
            assert got == want, f"trial {i}"


class TestNativeBLS:
    """The C++ BLS12-381 port is differentially tested against the
    pure-python golden model (cometbft_tpu/crypto/_bls12381_math.py);
    point wire format: raw affine big-endian coords, b'' = infinity."""

    def _mod(self):
        native = _native()
        if not hasattr(native, "bls_pairings_product_is_one"):
            pytest.skip("older native module")
        return native

    def test_scalar_mult_and_subgroup_parity(self):
        import random

        from cometbft_tpu.crypto import _bls12381_math as M

        native = self._mod()
        rng = random.Random(5)
        orig = M._native
        try:
            for _ in range(4):
                k = rng.getrandbits(180)
                kb = k.to_bytes(23, "big")
                M._native = lambda: None      # python reference
                want1 = M.pt_mul(M.G1_OPS, M.G1_GEN, k)
                want2 = M.pt_mul(M.G2_OPS, M.G2_GEN, k)
                got1 = M._g1_unraw(native.bls_g1_mul(
                    M._g1_raw(M.G1_GEN), kb))
                got2 = M._g2_unraw(native.bls_g2_mul(
                    M._g2_raw(M.G2_GEN), kb))
                assert got1 == want1 and got2 == want2
        finally:
            M._native = orig
        assert native.bls_g1_in_subgroup(M._g1_raw(M.G1_GEN))
        assert native.bls_g2_in_subgroup(M._g2_raw(M.G2_GEN))
        bad = (M.G1_GEN[0], (M.G1_GEN[1] + 1) % M.P)
        assert not native.bls_g1_in_subgroup(M._g1_raw(bad))

    def test_hash_to_g2_parity(self):
        from cometbft_tpu.crypto import _bls12381_math as M

        native = self._mod()
        orig = M._native
        try:
            for msg in (b"", b"abc", b"x" * 130):
                M._native = lambda: None
                want = M.hash_to_g2(msg, b"PARITY-DST")
                got = M._g2_unraw(
                    native.bls_hash_to_g2(msg, b"PARITY-DST"))
                assert got == want, msg
        finally:
            M._native = orig

    def test_pairing_bilinearity(self):
        import random

        from cometbft_tpu.crypto import _bls12381_math as M

        native = self._mod()
        P1, Q2 = M.G1_GEN, M.G2_GEN
        negP = M.pt_neg(M.G1_OPS, P1)
        pp = native.bls_pairings_product_is_one
        assert pp([(M._g1_raw(P1), M._g2_raw(Q2)),
                   (M._g1_raw(negP), M._g2_raw(Q2))])
        assert not pp([(M._g1_raw(P1), M._g2_raw(Q2))])
        rng = random.Random(9)
        x, y = rng.getrandbits(90), rng.getrandbits(90)
        xP = M.pt_mul(M.G1_OPS, P1, x)
        yQ = M.pt_mul(M.G2_OPS, Q2, y)
        xyP = M.pt_mul(M.G1_OPS, P1, x * y)
        # e(xP, yQ) * e(-xyP, Q) == 1
        assert pp([(M._g1_raw(xP), M._g2_raw(yQ)),
                   (M._g1_raw(M.pt_neg(M.G1_OPS, xyP)),
                    M._g2_raw(Q2))])
        # infinity pairs are skipped, matching the python model
        assert pp([(b"", M._g2_raw(Q2)), (M._g1_raw(P1), b"")])


class TestBLSFinalExp:
    def test_frobenius_and_fast_final_exp_selftest(self):
        """The C++ module's built-in algebra check: Frobenius equals a
        plain ^p pow, and the decomposed final exponentiation equals
        the naive one cubed (the ==1 verdict is unchanged since
        gcd(3, r) = 1)."""
        native = _native()
        if not hasattr(native, "bls_selftest"):
            pytest.skip("older native module")
        assert native.bls_selftest()


class TestPrepParityVariedLengths:
    def test_c_prep_matches_python_prep(self, monkeypatch):
        """The threaded C prep (incl. the 8-way AVX-512 SHA-512 path,
        its equal-block-count grouping, partial groups, and the scalar
        fallback) must produce bit-identical arrays to the pure-python
        prep across message lengths spanning 1..9 SHA-512 blocks,
        non-canonical S, and malformed lanes."""
        import numpy as np

        from cometbft_tpu.crypto import _ed25519_ref as ref
        from cometbft_tpu.ops import ed25519_jax as ej

        _native()   # skip when no compiler
        lengths = [0, 5, 47, 48, 63, 64, 111, 112, 120, 200, 300,
                   1000]
        items = []
        for i in range(200):
            seed = secrets.token_bytes(32)
            msg = secrets.token_bytes(lengths[i % len(lengths)])
            pub = ref.public_key(seed)
            sig = ref.sign(seed, msg)
            if i % 9 == 4:    # non-canonical S
                sig = sig[:32] + (ref.L + 5).to_bytes(32, "little")
            if i % 13 == 6:   # malformed
                pub = b"short"
            items.append((pub, msg, sig))
        native_out = ej.prep_arrays(items, 256)

        monkeypatch.setenv("COMETBFT_TPU_NATIVE", "0")
        saved_mod, saved_failed = (_native_loader._mod,
                                   _native_loader._failed)
        _native_loader._mod = None
        try:
            python_out = ej.prep_arrays(items, 256)
        finally:
            _native_loader._mod = saved_mod
            _native_loader._failed = saved_failed
        for name, a, b in zip(("a_b", "r_b", "s_win", "k_win",
                               "pre_bad"), native_out, python_out):
            assert np.array_equal(a, b), f"{name} differs"


class TestEd25519BatchMsm:
    """RLC batch verification (native/ed25519_msm.hpp) vs the golden
    model's batch_verify — the CPU analog of the reference's voi
    batch verifier (crypto/ed25519/ed25519.go:189-222)."""

    @staticmethod
    def _valid(i, msg=None):
        from cometbft_tpu.crypto import _ed25519_ref as ref
        seed = bytes([i % 256, i // 256 % 256]) + secrets.token_bytes(30)
        pub = ref.public_key(seed)
        m = msg if msg is not None else b"batch-msg-%d" % i
        return (pub, m, ref.sign(seed, m))

    def _check(self, items):
        from cometbft_tpu.crypto import _ed25519_ref as ref
        mod = _native()
        if not hasattr(mod, "ed25519_batch_verify"):
            pytest.skip("module predates ed25519_batch_verify")
        z = secrets.token_bytes(16 * len(items))
        got = bool(mod.ed25519_batch_verify(items, z))
        want_ok, want_mask = ref.batch_verify(items)
        assert got == want_ok, (got, want_ok, want_mask)
        return got

    @pytest.mark.parametrize("n", [2, 3, 7, 33, 200])
    def test_valid_batches_accept(self, n):
        assert self._check([self._valid(i) for i in range(n)])

    def test_corrupted_signature_rejects(self):
        items = [self._valid(i) for i in range(9)]
        pub, msg, sig = items[4]
        items[4] = (pub, msg, sig[:7] + bytes([sig[7] ^ 1]) + sig[8:])
        assert not self._check(items)

    def test_wrong_message_rejects(self):
        items = [self._valid(i) for i in range(5)]
        pub, _, sig = items[0]
        items[0] = (pub, b"forged", sig)
        assert not self._check(items)

    def test_non_canonical_s_rejects(self):
        from cometbft_tpu.crypto import _ed25519_ref as ref
        items = [self._valid(i) for i in range(3)]
        pub, msg, sig = items[1]
        s = int.from_bytes(sig[32:], "little") + ref.L
        items[1] = (pub, msg, sig[:32] + s.to_bytes(32, "little"))
        assert not self._check(items)

    def test_zip215_small_order_and_non_canonical_y(self):
        # A = order-4 point (y=0), R = non-canonical identity
        # encoding (y = p+1): S=0 signatures over any message verify
        # under ZIP-215 (cofactored) — the native path must agree
        # with the golden model on these
        from cometbft_tpu.crypto import _ed25519_ref as ref
        a_small = bytes(32)                      # y=0, sign 0
        r_nc = (ref.P + 1).to_bytes(32, "little")
        corner = (a_small, b"whatever", r_nc + bytes(32))
        assert ref.verify(*corner)               # golden ZIP-215 accept
        items = [self._valid(0), corner, self._valid(2)]
        assert self._check(items)

    def test_off_curve_pubkey_rejects_batch(self):
        # an encoding with no curve point: batch returns 0 and the
        # per-signature fallback produces the mask
        items = [self._valid(0), self._valid(1)]
        bad_pub = bytes([2]) + bytes(30) + bytes([0])
        from cometbft_tpu.crypto import _ed25519_ref as ref
        if ref.decompress(bad_pub) is not None:
            pytest.skip("encoding unexpectedly valid")
        items.append((bad_pub, b"m", items[0][2]))
        assert not self._check(items)

    def test_cpu_batch_verifier_uses_native_and_keeps_mask_contract(self):
        from cometbft_tpu.crypto import ed25519
        privs = [ed25519.gen_priv_key() for _ in range(6)]
        bv = ed25519.CpuBatchVerifier()
        for i, p in enumerate(privs):
            bv.add(p.pub_key(), b"m%d" % i, p.sign(b"m%d" % i))
        ok, mask = bv.verify()
        assert ok and mask == [True] * 6
        bv2 = ed25519.CpuBatchVerifier()
        for i, p in enumerate(privs):
            sig = p.sign(b"m%d" % i)
            if i == 2:
                sig = bytes([sig[0] ^ 4]) + sig[1:]
            bv2.add(p.pub_key(), b"m%d" % i, sig)
        ok, mask = bv2.verify()
        assert not ok
        assert mask == [True, True, False, True, True, True]

    def test_pub_decompress_cache_does_not_bypass_verification(self):
        # the A-point cache memoizes DECOMPRESSION only; a second
        # batch reusing a cached pubkey with a forged signature must
        # still reject, and a valid re-verify must still accept
        from cometbft_tpu.crypto import _ed25519_ref as ref
        mod = _native()
        if not hasattr(mod, "ed25519_batch_verify"):
            pytest.skip("module predates ed25519_batch_verify")
        seed = secrets.token_bytes(32)
        pub = ref.public_key(seed)
        items = [(pub, b"m-%d" % i, ref.sign(seed, b"m-%d" % i))
                 for i in range(4)]
        z = secrets.token_bytes(16 * 4)
        assert mod.ed25519_batch_verify(items, z)      # caches pub
        forged = list(items)
        forged[2] = (pub, b"forged", items[2][2])
        assert not mod.ed25519_batch_verify(forged, z)
        assert mod.ed25519_batch_verify(items, z)
