"""Subprocess driver for the fail-point crash-consistency test.

Runs a single-validator node at HOME until TARGET_HEIGHT, then exits 0.
With FAIL_TEST_INDEX set, the node hard-crashes (exit 99) at the indexed
commit-path boundary instead (see cometbft_tpu/libs/fail.py).
"""
import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


async def main(home: str, target: int) -> int:
    from cometbft_tpu.config import Config
    from cometbft_tpu.node.node import Node
    from cometbft_tpu.p2p.key import NodeKey
    from cometbft_tpu.privval import FilePV
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.timestamp import Timestamp

    cfg = Config()
    cfg.base.home = home
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = ""
    cfg.consensus.timeout_commit_ns = 20_000_000
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    key_file = cfg.base.path(cfg.base.priv_validator_key_file)
    pv = FilePV.load_or_generate(
        key_file, cfg.base.path(cfg.base.priv_validator_state_file))
    NodeKey.load_or_gen(cfg.base.path(cfg.base.node_key_file))
    gen_file = cfg.base.path(cfg.base.genesis_file)
    if not os.path.exists(gen_file):
        doc = GenesisDoc(
            chain_id="crash-chain", genesis_time=Timestamp(1700000000, 0),
            validators=[GenesisValidator(address=b"",
                                         pub_key=pv.get_pub_key(),
                                         power=10)])
        doc.save_as(gen_file)

    node = Node(cfg)
    await node.start()
    for _ in range(2000):
        if node.height >= target:
            await node.stop()
            return 0
        await asyncio.sleep(0.02)
    return 1


if __name__ == "__main__":
    sys.exit(asyncio.run(main(sys.argv[1], int(sys.argv[2]))))
