"""Pipelined commit + adaptive timeouts (docs/pipeline.md).

Covers the ISSUE-10 tentpole contracts:

  * two heights in flight: a wired multi-validator net with
    ``consensus.pipeline_commit`` (the default) commits identical
    chains, and the pipeline actually engages (apply-duration
    histogram observes);
  * WAL replay converges to the same app hash as the serial path:
    a chain produced WITH pipelining, replayed from its WAL through a
    fresh serial (pipeline-off) machine, reproduces the same blocks
    and app hashes byte-for-byte;
  * adaptive timeouts: EWMA-derived values respect floor/ceiling,
    never shrink below the measured p95 quorum delay, fall back to
    the static config while no delays have been measured (fresh node
    / replay), and commit padding only ever shrinks.
"""
import asyncio

import pytest

from cometbft_tpu.abci.client import AppConns
from cometbft_tpu.abci.kvstore import DEFAULT_LANES, KVStoreApplication
from cometbft_tpu.config import MempoolConfig
from cometbft_tpu.config import test_config as _test_config
from cometbft_tpu.consensus.adaptive import AdaptiveTimeouts
from cometbft_tpu.consensus.messages import (
    BlockPartMessage, ProposalMessage, VoteMessage,
)
from cometbft_tpu.consensus.round_state import RoundState
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.consensus.wal import WAL
from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.db import MemDB
from cometbft_tpu.mempool import CListMempool
from cometbft_tpu.state import make_genesis_state
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.store import Store
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.priv_validator import new_mock_pv
from cometbft_tpu.types.timestamp import Timestamp

_MS = 1_000_000
_S = 1_000_000_000


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _make_genesis(n_vals):
    pvs = [new_mock_pv() for _ in range(n_vals)]
    doc = GenesisDoc(
        chain_id="pipeline-test",
        genesis_time=Timestamp(1700000000, 0),
        validators=[GenesisValidator(address=b"",
                                     pub_key=pv.get_pub_key(),
                                     power=10)
                    for pv in pvs],
    )
    return doc, pvs


def _make_node(doc, pv, wal=None, pipeline=True, adaptive=False):
    state = make_genesis_state(doc)
    app = KVStoreApplication()
    conns = AppConns(app)
    state_store = Store(MemDB())
    block_store = BlockStore(MemDB())
    state_store.save(state)
    mp = CListMempool(MempoolConfig(), conns.mempool,
                      lanes=DEFAULT_LANES, default_lane="default")
    exec_ = BlockExecutor(state_store, conns.consensus, mempool=mp,
                          block_store=block_store)
    cfg = _test_config().consensus
    cfg.pipeline_commit = pipeline
    cfg.adaptive_timeouts = adaptive
    cs = ConsensusState(cfg, state, exec_, block_store,
                        priv_validator=pv, wal=wal)
    return cs, app, block_store, mp


GOSSIP_TYPES = (ProposalMessage, BlockPartMessage, VoteMessage)


def _wire(nodes):
    for i, cs in enumerate(nodes):
        def mk_hook(sender_idx):
            def hook(msg):
                if not isinstance(msg, GOSSIP_TYPES):
                    return
                for j, other in enumerate(nodes):
                    if j != sender_idx:
                        other.send_peer(msg, f"node{sender_idx}")
            return hook
        cs.broadcast_hooks.append(mk_hook(i))


async def _replay_all(cs, wal_path: str) -> int:
    """From-genesis serial replay of an ENTIRE WAL (catchup_replay's
    dispatch loop without the in-flight-tail scoping — the test wants
    every height re-executed through the serial path)."""
    from cometbft_tpu.consensus.messages import message_from_wal
    from cometbft_tpu.consensus.round_state import TimeoutInfo
    n = 0
    cs.replay_mode = True
    try:
        for record in WAL.iter_group(wal_path):
            t = record.get("type")
            if t in ("round_state", "end_height"):
                continue
            if t == "timeout":
                await cs._handle_timeout(TimeoutInfo(
                    duration_ns=0,
                    height=record.get("height", 0),
                    round=record.get("round", 0),
                    step=record.get("step", 0)))
            else:
                await cs._handle_msg(message_from_wal(record), "",
                                     internal=False)
            n += 1
    finally:
        cs.replay_mode = False
        cs.ticker.stop()   # round-0 timers scheduled during replay
    return n


async def _wait_for_height(nodes, height, timeout=30.0):
    async def waiter():
        while True:
            if all(cs.block_store.height >= height for cs in nodes):
                return
            await asyncio.sleep(0.01)
    await asyncio.wait_for(waiter(), timeout)


class TestPipelinedCommit:
    def test_pipelined_net_agrees_and_overlaps(self):
        """4 pipelined validators commit identical chains with real
        txs, and the background apply path actually engages."""
        async def go():
            doc, pvs = _make_genesis(4)
            made = [_make_node(doc, pv) for pv in pvs]
            nodes = [m[0] for m in made]
            pools = [m[3] for m in made]
            _wire(nodes)
            for cs in nodes:
                await cs.start()
            try:
                for i in range(24):
                    for mp in pools:
                        try:
                            await mp.check_tx(b"px%03d=v" % i)
                        except Exception:
                            pass
                await _wait_for_height(nodes, 4)
            finally:
                for cs in nodes:
                    await cs.stop()
            for h in range(1, 5):
                hashes = {cs.block_store.load_block(h).hash()
                          for cs in nodes}
                assert len(hashes) == 1, f"fork at {h}"
                app_hashes = {
                    cs.block_store.load_block_meta(h).header.app_hash
                    for cs in nodes}
                assert len(app_hashes) == 1, f"app fork at {h}"
            committed = sum(
                len(nodes[0].block_store.load_block(h).data.txs)
                for h in range(1, nodes[0].block_store.height + 1))
            assert committed > 0, "no txs committed"
            engaged = sum(
                cs.metrics.pipeline_apply_seconds._count
                for cs in nodes)
            assert engaged > 0, "pipelined apply never engaged"
        run(go())

    def test_wal_replay_matches_pipelined_execution(self, tmp_path):
        """A chain produced WITH pipelining, replayed from its WAL by
        a fresh SERIAL machine (pipeline off, replay mode), converges
        to the same blocks and app hashes — the WAL ordering the
        pipeline writes is replay-equivalent to serial execution."""
        async def go():
            doc, pvs = _make_genesis(4)
            wal_path = str(tmp_path / "wal0")
            made = [_make_node(doc, pv,
                               wal=WAL(wal_path) if i == 0 else None)
                    for i, pv in enumerate(pvs)]
            nodes = [m[0] for m in made]
            pools = [m[3] for m in made]
            _wire(nodes)
            for cs in nodes:
                await cs.start()
            try:
                for i in range(16):
                    for mp in pools:
                        try:
                            await mp.check_tx(b"wr%03d=v" % i)
                        except Exception:
                            pass
                await _wait_for_height(nodes, 4)
            finally:
                for cs in nodes:
                    await cs.stop()
            bs1 = nodes[0].block_store
            assert nodes[0].metrics.pipeline_apply_seconds._count > 0

            # fresh machine, same genesis + key, serial path
            cs2, app2, bs2, _ = _make_node(doc, pvs[0],
                                           pipeline=False)
            n = await _replay_all(cs2, wal_path)
            assert n > 0, "nothing replayed"
            assert bs2.height >= bs1.height - 1, \
                f"replay stalled at {bs2.height} (orig {bs1.height})"
            for h in range(1, bs2.height + 1):
                want = bs1.load_block_meta(h)
                got = bs2.load_block_meta(h)
                assert got.block_id.hash == want.block_id.hash, \
                    f"block hash diverged at {h}"
                assert got.header.app_hash == want.header.app_hash, \
                    f"app hash diverged at {h}"
            # the replayed app itself converged (serial execution of
            # the pipelined chain): its post-apply app hash matches
            # the one the pipelined run committed into height+1
            if bs1.height > bs2.height:
                nxt = bs1.load_block_meta(bs2.height + 1)
                assert cs2.sm_state.app_hash == nxt.header.app_hash
        run(go())

    def test_serial_mode_still_works(self):
        """pipeline_commit=False restores the fully serial path."""
        async def go():
            doc, pvs = _make_genesis(1)
            cs, app, bs, _ = _make_node(doc, pvs[0], pipeline=False)
            await cs.start()
            try:
                await _wait_for_height([cs], 3)
            finally:
                await cs.stop()
            assert bs.height >= 3
            assert cs.metrics.pipeline_apply_seconds._count == 0
        run(go())


class TestWaitForTxs:
    """create_empty_blocks gating: an empty pool holds round 0 of a
    fresh height (poll re-arm, no WAL records) until a tx arrives or
    the configured interval elapses — at pipelined sub-second
    intervals empty-block churn otherwise starves real work."""

    def test_waits_for_txs_then_commits(self):
        async def go():
            doc, pvs = _make_genesis(1)
            cs, app, bs, mp = _make_node(doc, pvs[0])
            cs.config.create_empty_blocks = False
            await cs.start()
            try:
                await asyncio.sleep(0.5)
                assert bs.height == 0, "proposed an empty block"
                await mp.check_tx(b"wt1=v")
                await _wait_for_height([cs], 1, timeout=10.0)
                assert bs.load_block(1).data.txs, "empty block"
            finally:
                await cs.stop()
        run(go())

    def test_interval_allows_periodic_empty_blocks(self):
        async def go():
            doc, pvs = _make_genesis(1)
            cs, app, bs, mp = _make_node(doc, pvs[0])
            cs.config.create_empty_blocks_interval_ns = 200 * _MS
            await cs.start()
            try:
                # no txs at all: heights still advance on the
                # interval cadence (liveness / BFT-time keeps moving)
                await _wait_for_height([cs], 2, timeout=10.0)
            finally:
                await cs.stop()
        run(go())


class TestRoundStateSeam:
    def test_advance_is_monotonic(self):
        rs = RoundState()
        rs.height = 5
        rs.advance(0, 3)
        rs.advance(0, 4)
        rs.advance(1, 2)       # new round resets the step forward
        with pytest.raises(RoundState.TransitionError):
            rs.advance(0, 8)   # earlier round
        with pytest.raises(RoundState.TransitionError):
            rs.advance(1, 1)   # earlier step, same round

    def test_relock_requires_live_lock(self):
        rs = RoundState()
        with pytest.raises(RoundState.TransitionError):
            rs.relock(2)
        rs.lock(1, object(), object())
        rs.relock(3)
        with pytest.raises(RoundState.TransitionError):
            rs.relock(2)       # backwards

    def test_set_valid_monotonic(self):
        rs = RoundState()
        rs.set_valid(2, object(), object())
        with pytest.raises(RoundState.TransitionError):
            rs.set_valid(1, object(), object())


class TestAdaptiveTimeouts:
    FLOOR = 200 * _MS
    CEIL = 10 * _S

    def test_empty_falls_back_to_static(self):
        a = AdaptiveTimeouts(self.FLOOR, self.CEIL)
        assert a.propose_timeout_ns() is None
        assert a.vote_timeout_ns() is None
        assert a.commit_padding_ns(1 * _S) == 1 * _S

    def test_cs_uses_static_until_measured(self):
        doc, pvs = _make_genesis(1)
        cs, _, _, _ = _make_node(doc, pvs[0], adaptive=True)
        static = cs.config.propose_timeout_ns(0)
        assert cs._adaptive is not None
        assert cs._propose_timeout_ns(0) == static
        assert cs._vote_wait_timeout_ns(1) == \
            cs.config.prevote_timeout_ns(1)
        # measurements flip it to the derived value
        for _ in range(8):
            cs._adaptive.observe(0.05)
        derived = cs._propose_timeout_ns(0)
        assert derived != static
        assert derived >= self.FLOOR

    def test_respects_floor_and_ceiling(self):
        a = AdaptiveTimeouts(self.FLOOR, self.CEIL)
        for _ in range(16):
            a.observe(0.001)           # 1 ms net: clamp up to floor
        assert a.propose_timeout_ns() == self.FLOOR
        assert a.vote_timeout_ns() == self.FLOOR
        b = AdaptiveTimeouts(self.FLOOR, self.CEIL)
        for _ in range(16):
            b.observe(60.0)            # awful net: clamp to ceiling
        assert b.propose_timeout_ns() == self.CEIL
        assert b.vote_timeout_ns() == self.CEIL

    def test_never_below_measured_p95(self):
        a = AdaptiveTimeouts(self.FLOOR, self.CEIL)
        # EWMA warmed on a fast net, then the net degrades: the
        # current window's p95 must floor the derived timeouts even
        # while the EWMA lags behind
        for _ in range(64):
            a.observe(0.01)
        for _ in range(60):
            a.observe(2.0)
        p95_ns = int(a.p95_s() * 1e9)
        assert a.p95_s() == 2.0
        assert a.propose_timeout_ns() >= p95_ns
        assert a.vote_timeout_ns() >= p95_ns

    def test_commit_padding_only_shrinks(self):
        a = AdaptiveTimeouts(self.FLOOR, self.CEIL)
        for _ in range(16):
            a.observe(0.01)            # 10 ms quorum delay
        # static 1 s padding shrinks toward the measured delay...
        assert a.commit_padding_ns(1 * _S) < 1 * _S
        assert a.commit_padding_ns(1 * _S) >= self.FLOOR
        # ...but a static padding BELOW the derived value is kept
        assert a.commit_padding_ns(50 * _MS) == 50 * _MS

    def test_ewma_rises_fast_decays_slow(self):
        a = AdaptiveTimeouts(self.FLOOR, self.CEIL, alpha=0.5,
                             window=4)
        a.observe(1.0)
        assert a.ewma_s() == 1.0
        # upward: snaps straight to the new p95 (under-deadlining
        # churns rounds, and churned rounds never produce a sample
        # to correct the estimator)
        a.observe(3.0)
        assert a.ewma_s() == 3.0
        # downward: geometric decay only (window drains the slow
        # samples, then the EWMA follows at rate alpha)
        for _ in range(4):
            a.observe(1.0)
        assert a.p95_s() == 1.0
        assert 1.0 < a.ewma_s() < 3.0

    def test_replay_does_not_feed_adaptive(self, tmp_path):
        """WAL replay must not poison the EWMA with historical
        delays: a replayed machine still reports None (static)."""
        async def go():
            doc, pvs = _make_genesis(1)
            wal_path = str(tmp_path / "wal")
            cs, _, _, _ = _make_node(doc, pvs[0], wal=WAL(wal_path))
            await cs.start()
            try:
                await _wait_for_height([cs], 3)
            finally:
                await cs.stop()
            cs2, _, _, _ = _make_node(doc, pvs[0], adaptive=True)
            await _replay_all(cs2, wal_path)
            assert cs2._adaptive.samples == 0
            assert cs2._adaptive.propose_timeout_ns() is None
        run(go())
