"""Nemesis scenarios: deterministic seeded fault schedules against
in-proc testnets (runner: tests/nemesis.py).

Every scenario asserts BOTH properties:
  * safety  — no two honest nodes commit conflicting blocks at any
              height (full-history check);
  * liveness — the chain commits `recovery_blocks` more blocks within
              a bounded time after the faults heal.

The default (not-slow) tier keeps three fast scenarios; the longer
partition sweeps are `slow`.
"""
import asyncio

import pytest

from cometbft_tpu.crypto import batch as crypto_batch

from nemesis import Scenario, run_scenario

pytestmark = pytest.mark.nemesis


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


class TestNemesisScenarios:
    def test_asymmetric_partition_stalls_then_heals(self):
        """One-way 2|2 cut: {0,1} frames never reach {2,3}, the
        reverse direction stays up.  Neither side can assemble a
        quorum (votes flow one way only), so the chain must STALL —
        committing through an asymmetric half-cut would be a safety
        smell — and after heal the vote-catchup gossip must revive
        the wedged round within the recovery budget."""
        run(run_scenario(Scenario(
            name="asym-partition-2x2",
            seed=7,
            steps=(
                ("wait_blocks", 2),
                ("partition", (0, 1), (2, 3)),
                ("expect_stall", 1.5, 1),
                ("heal",),
            ),
            recovery_blocks=3)))

    def test_crash_restart_mid_height(self):
        """Hard-kill a validator mid-height; the 3/4 supermajority
        keeps committing; the crashed node restarts on its durable
        stores and converges onto the same chain."""
        run(run_scenario(Scenario(
            name="crash-restart",
            seed=11,
            steps=(
                ("wait_blocks", 2),
                ("crash", 3),
                ("expect_progress", (0, 1, 2), 3, 60.0),
                ("restart", 3),
            ),
            recovery_blocks=2)))

    def test_reorder_duplicate_drop_links(self):
        """Every link reorders, duplicates, delays, and drops frames
        (seeded); the stack must keep committing through the noise
        and the histories must agree."""
        run(run_scenario(Scenario(
            name="faulty-links",
            seed=23,
            fuzz=dict(prob_reorder=0.05, prob_duplicate=0.05,
                      prob_drop_write=0.02, prob_delay=0.05,
                      max_delay_s=0.02),
            steps=(
                ("wait_blocks", 4),
            ),
            recovery_blocks=2)))

    def test_pipelined_commit_crash_reorder(self):
        """Two heights in flight (pipelined commit, the default) under
        reorder/duplicate link fuzz, plus a hard crash that ABORTS an
        in-flight background apply and restarts through the real
        recovery path (file WAL + ABCI handshake + catchup replay).
        Safety (identical chains) and bounded recovery must hold; the
        restarted node's replayed app hashes converge with the nodes
        that executed serially-in-order — the WAL-replay-equals-
        pipelined-execution half of the claim is pinned byte-exactly
        in test_pipeline.py."""
        run(run_scenario(Scenario(
            name="pipelined-commit",
            seed=17,
            use_wal=True,
            fuzz=dict(prob_reorder=0.06, prob_duplicate=0.06,
                      prob_delay=0.04, max_delay_s=0.01),
            steps=(
                ("wait_blocks", 3),
                ("crash", 2),
                ("expect_progress", (0, 1, 3), 2, 60.0),
                ("restart", 2),
                ("wait_blocks", 2),
            ),
            recovery_blocks=3)))

    def test_aggregate_commit_crash_restart_fuzz(self):
        """Aggregate-commit chain (BLS valset, one aggregate signature
        + signer bitmap per commit — docs/aggregate_commits.md) under
        reorder/duplicate link fuzz plus a hard crash/restart through
        the REAL recovery path (file WAL + ABCI handshake + catchup
        replay).  Restart recovery replays blocks whose LastCommit is
        the aggregate form from the store, and the node keeps
        proposing/validating aggregates afterwards.  Gated on zero
        safety violations and bounded recovery, like every tier-1
        scenario."""
        from cometbft_tpu.types.params import (
            ConsensusParams, FeatureParams, ValidatorParams,
        )
        run(run_scenario(Scenario(
            name="aggregate-commit",
            seed=29,
            use_wal=True,
            key_type="bls12_381",
            consensus_params=ConsensusParams(
                validator=ValidatorParams(
                    pub_key_types=["bls12_381"]),
                feature=FeatureParams(
                    pbts_enable_height=1,
                    aggregate_commit_enable_height=1)),
            fuzz=dict(prob_reorder=0.05, prob_duplicate=0.05,
                      prob_delay=0.03, max_delay_s=0.01),
            steps=(
                ("wait_blocks", 3),
                ("crash", 1),
                ("expect_progress", (0, 2, 3), 2, 90.0),
                ("restart", 1),
                ("wait_blocks", 2),
            ),
            recovery_blocks=3,
            recovery_timeout_s=120.0)))

    def test_statetree_crash_restart_fuzz(self):
        """ISSUE 17: the kvstore's storage engine is the committed
        state tree, so every header's app_hash IS a tree root.
        Hard-crash a node mid-height under reorder/duplicate link
        fuzz; the restart rebuilds the app from its durable db and
        ABCI handshake replay (plus WAL catchup) must converge on the
        exact roots the live nodes committed — checked
        header-by-header after the run, on top of the runner's
        zero-safety-violations and bounded-recovery gates."""
        net = run(run_scenario(Scenario(
            name="statetree-crash",
            seed=37,
            use_wal=True,
            fuzz=dict(prob_reorder=0.06, prob_duplicate=0.06,
                      prob_delay=0.03, max_delay_s=0.01),
            steps=(
                ("wait_blocks", 3),
                ("crash", 1),
                ("expect_progress", (0, 2, 3), 2, 60.0),
                ("restart", 1),
                ("wait_blocks", 2),
            ),
            recovery_blocks=3)))
        # every committed tree version must chain to the NEXT block's
        # header app_hash — i.e. handshake replay on the restarted
        # node reproduced byte-identical roots, not just "a" state
        checked = 0
        for n in net.nodes:
            for v in n.app.tree.versions():
                if v < 1:
                    continue
                meta = n.block_store.load_block_meta(v + 1)
                if meta is None:
                    continue
                assert meta.header.app_hash == \
                    n.app.tree.reported_hash(v), \
                    f"node {n.idx}: version {v} root diverges " \
                    f"from header {v + 1}"
                checked += 1
        assert checked >= 4, "app-hash chain check found no headers"

    def test_recon_gossip_under_fuzz_and_partition(self):
        """ISSUE 12: have/want tx gossip + compact-block proposals
        (the mempool reactor, negotiated by default) running under
        reorder/duplicate link fuzz and a transient asymmetric
        partition.  The load injector puts every tx in exactly ONE
        node's pool, so blocks only fill if reconciliation moves txs
        across the fuzzed links; liveness, bounded recovery, and
        zero conflicting commits (the runner's full-history hash
        check) must all hold."""
        run(run_scenario(Scenario(
            name="recon-gossip",
            seed=31,
            mempool_gossip=True,
            fuzz=dict(prob_reorder=0.05, prob_duplicate=0.05,
                      prob_delay=0.04, max_delay_s=0.015),
            steps=(
                ("wait_blocks", 3),
                ("partition", (0,), (2, 3)),
                ("sleep", 1.0),
                ("heal",),
                ("wait_blocks", 2),
            ),
            recovery_blocks=2)))

    def test_mute_validator_routes_around(self):
        """Asymmetric single-node mute: node 3's frames reach nobody,
        but it still hears the net.  The other three form a quorum and
        progress must CONTINUE during the fault (gossip routes around
        the mute), and node 3 still follows the chain passively."""
        run(run_scenario(Scenario(
            name="mute-one",
            seed=13,
            steps=(
                ("wait_blocks", 2),
                ("partition", (3,), (0, 1, 2)),
                ("expect_progress", (0, 1, 2), 3, 60.0),
                ("heal",),
            ),
            recovery_blocks=2)))


class TestFailureArchive:
    def test_failed_scenario_archives_flight_record(
            self, tmp_path, monkeypatch):
        """A scenario that misses its liveness budget must leave a
        flight-record archive named after the scenario+seed (ROADMAP
        open item: liveness regressions come with timelines
        attached)."""
        import json
        import os

        from cometbft_tpu.libs import tracing

        monkeypatch.setenv("COMETBFT_TPU_NEMESIS_ARCHIVE_DIR",
                           str(tmp_path))
        old = tracing.set_recorder(tracing.Recorder())
        try:
            with pytest.raises(AssertionError) as exc_info:
                run(run_scenario(Scenario(
                    name="archive-probe",
                    seed=41,
                    # unreachable liveness target: fail fast
                    recovery_blocks=10_000,
                    recovery_timeout_s=0.2)))
        finally:
            tracing.set_recorder(old)
        import glob
        matches = glob.glob(os.path.join(
            str(tmp_path), "nemesis-archive-probe-seed41-*.json"))
        assert len(matches) == 1, os.listdir(str(tmp_path))
        path = matches[0]
        assert str(path) in str(exc_info.value)
        with open(path) as f:
            record = json.load(f)
        assert record["extra"]["scenario"] == "archive-probe"
        assert record["extra"]["seed"] == 41
        assert "liveness" in record["extra"]["error"]
        # fleet observability: per-node state + clock anchors ride in
        # the archive so fleet_report can place it on a wall timeline
        assert record["extra"]["nodes"], "per-node state missing"
        assert all("height" in n for n in record["extra"]["nodes"])
        assert record["anchors"], "clock anchors missing"
        # the archive carries a real timeline, not an empty ring
        assert record["events"], "archived flight record is empty"

    def test_archive_names_are_run_unique(self, tmp_path,
                                          monkeypatch):
        """Re-running the same scenario+seed must never overwrite the
        previous run's archive (the old fixed naming silently lost
        the first failure's evidence)."""
        import glob
        import os

        from cometbft_tpu.libs import tracing
        from nemesis import Scenario, _archive_flight_record

        monkeypatch.setenv("COMETBFT_TPU_NEMESIS_ARCHIVE_DIR",
                           str(tmp_path))
        old = tracing.set_recorder(tracing.Recorder())
        try:
            s = Scenario(name="dup-probe", seed=7)
            p1 = _archive_flight_record(s, RuntimeError("first"))
            p2 = _archive_flight_record(s, RuntimeError("second"))
        finally:
            tracing.set_recorder(old)
        assert p1 and p2 and p1 != p2
        matches = glob.glob(os.path.join(
            str(tmp_path), "nemesis-dup-probe-seed7-*.json"))
        assert len(matches) == 2


@pytest.mark.slow
class TestNemesisSweeps:
    def test_partition_sweep_seeded(self):
        """Sweep cut patterns x seeds: every asymmetric cut must heal
        into a safe, live chain."""
        cuts = (
            ((0,), (1, 2, 3)),          # mute one
            ((0, 1), (2, 3)),           # half split
            ((0, 1, 2), (3,)),          # isolate one's inbound
        )
        for seed in (1, 2):
            for srcs, dsts in cuts:
                run(run_scenario(Scenario(
                    name=f"sweep-{srcs}-{dsts}-s{seed}",
                    seed=seed,
                    steps=(
                        ("wait_blocks", 2),
                        ("partition", srcs, dsts),
                        ("sleep", 1.0),
                        ("heal",),
                    ),
                    recovery_blocks=3)))

    def test_compound_fuzz_plus_crash(self):
        """Compose link noise with a crash/restart — the schedules
        must not mask each other."""
        run(run_scenario(Scenario(
            name="fuzz+crash",
            seed=29,
            fuzz=dict(prob_reorder=0.03, prob_duplicate=0.03,
                      prob_drop_write=0.01),
            steps=(
                ("wait_blocks", 2),
                ("crash", 1),
                ("expect_progress", (0, 2, 3), 2, 60.0),
                ("restart", 1),
                ("wait_blocks", 2),
            ),
            recovery_blocks=2)))
