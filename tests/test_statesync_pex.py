"""Statesync (snapshot bootstrap over sockets) and PEX tests."""
import asyncio

import pytest

from cometbft_tpu.abci.client import AppConns
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config import test_config as _test_config
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.db import MemDB
from cometbft_tpu.light.client import (
    SKIPPING, Client as LightClient, TrustOptions,
)
from cometbft_tpu.light.provider import NodeProvider
from cometbft_tpu.light.store import TrustedStore
from cometbft_tpu.p2p.key import NodeKey
from cometbft_tpu.p2p.pex import AddrBook, PexReactor
from cometbft_tpu.p2p.switch import Switch
from cometbft_tpu.state import make_genesis_state
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.store import Store
from cometbft_tpu.statesync import StateProvider, StatesyncReactor, Syncer
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.priv_validator import new_mock_pv
from cometbft_tpu.types.timestamp import Timestamp

_S = 1_000_000_000


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


class TestStatesync:
    def test_snapshot_bootstrap(self):
        async def go():
            # source: single validator with snapshots every 4 blocks
            pv = new_mock_pv()
            doc = GenesisDoc(
                chain_id="ss-chain",
                genesis_time=Timestamp(1700000000, 0),
                validators=[GenesisValidator(
                    address=b"", pub_key=pv.get_pub_key(), power=10)])
            # realistic block cadence: unthrottled, this in-memory
            # chain commits ~150 blocks/s and the app's bounded
            # snapshot window (5) turns over faster than a chunk
            # round-trip can complete
            src_app = KVStoreApplication(snapshot_interval=4)
            src_app.next_block_delay_ns = 100_000_000
            src_conns = AppConns(src_app)
            src_ss, src_bs = Store(MemDB()), BlockStore(MemDB())
            state = make_genesis_state(doc)
            src_ss.save(state)
            ex = BlockExecutor(src_ss, src_conns.consensus,
                               block_store=src_bs)
            cs = ConsensusState(_test_config().consensus, state, ex,
                                src_bs, priv_validator=pv)
            await cs.start()
            while src_bs.height < 10:
                await asyncio.sleep(0.01)
            # keep producing while the client syncs
            snaps = (await src_app.list_snapshots(None)).snapshots
            assert snaps, "source must have taken snapshots"

            src_switch = Switch(NodeKey.generate(), doc.chain_id,
                                listen_addr="127.0.0.1:0")
            src_reactor = StatesyncReactor(src_conns)
            src_switch.add_reactor(src_reactor)
            await src_switch.start()

            # destination: fresh app; trusted light client over the
            # source's stores
            dst_app = KVStoreApplication()
            dst_conns = AppConns(dst_app)
            provider = NodeProvider(src_bs, src_ss, doc.chain_id)
            root = await provider.light_block(1)
            lc = LightClient(
                doc.chain_id,
                TrustOptions(
                    period_ns=10 * 365 * 24 * 3600 * _S, height=1,
                    header_hash=root.signed_header.header.hash()),
                provider, [], TrustedStore(MemDB()),
                verification_mode=SKIPPING)
            await lc.initialize()
            sp = StateProvider(lc, doc.chain_id, doc)

            dst_switch = Switch(NodeKey.generate(), doc.chain_id,
                                listen_addr="127.0.0.1:0")
            syncer = Syncer(dst_conns, sp, request_chunk=None,
                            chunk_timeout_s=2.0)
            dst_reactor = StatesyncReactor(dst_conns, syncer=syncer)
            syncer.request_chunk = dst_reactor.request_chunk
            dst_switch.add_reactor(dst_reactor)
            await dst_switch.start()
            await dst_switch.dial_peer(src_switch.listen_addr)

            new_state, commit = await asyncio.wait_for(
                syncer.sync_any(discovery_time_s=0.3), 30)
            snap_h = new_state.last_block_height
            assert snap_h % 4 == 0 and snap_h >= 4
            assert commit.height == snap_h
            # the app restored to the snapshot state
            from cometbft_tpu.abci import types as abci
            info = await dst_conns.query.info(abci.InfoRequest())
            assert info.last_block_height == snap_h
            # bootstrap the state store like node startup would
            dst_ss = Store(MemDB())
            dst_ss.bootstrap(new_state)
            assert dst_ss.load().last_block_height == snap_h
            await cs.stop()
            await dst_switch.stop()
            await src_switch.stop()
        run(go())


class TestPex:
    def test_addrbook_roundtrip(self, tmp_path):
        p = str(tmp_path / "addrbook.json")
        book = AddrBook(p)
        assert book.add_address("a" * 40, "10.0.0.1", 26656)
        assert not book.add_address("a" * 40, "10.0.0.1", 26656)
        assert book.add_address("b" * 40, "10.0.0.2", 26656)
        book.save()
        book2 = AddrBook(p)
        assert book2.size() == 2
        picked = book2.pick_addresses(10)
        assert len(picked) == 2

    def test_pex_discovery(self):
        """C learns about A from B via PEX and dials it."""
        async def go():
            async def mk(name):
                nk = NodeKey.generate()
                sw = Switch(nk, "pexnet", listen_addr="127.0.0.1:0")
                pex = PexReactor(AddrBook())
                sw.add_reactor(pex)
                await sw.start()
                await pex.start()
                return sw, pex
            a, pex_a = await mk("a")
            b, pex_b = await mk("b")
            c, pex_c = await mk("c")
            # A ↔ B, then C → B; C should discover and dial A
            await a.dial_peer(b.listen_addr)
            await asyncio.sleep(0.1)
            await c.dial_peer(b.listen_addr)

            async def wait():
                while a.node_key.id not in c.peers:
                    await asyncio.sleep(0.05)
            await asyncio.wait_for(wait(), 15)
            assert c.num_peers() == 2
            for sw, pex in ((a, pex_a), (b, pex_b), (c, pex_c)):
                await pex.stop()
                await sw.stop()
        run(go())


class TestAddrBookBuckets:
    def test_new_bucket_eviction(self):
        from cometbft_tpu.p2p import pex as pexmod
        from cometbft_tpu.p2p.pex import AddrBook
        book = AddrBook(strict=False, key="k")
        # force tiny buckets so eviction triggers deterministically
        old_cap = pexmod._BUCKET_CAP
        pexmod._BUCKET_CAP = 4
        try:
            for i in range(2000):
                book.add_address(f"node{i:04d}", "10.0.0.1", 26656 + i)
            # every NEW bucket respects the cap
            from collections import Counter
            per_bucket = Counter(
                a.bucket for a in book._addrs.values() if not a.is_old)
            assert max(per_bucket.values()) <= 4
            assert book.size() < 2000       # evictions happened
        finally:
            pexmod._BUCKET_CAP = old_cap

    def test_mark_good_promotes_and_old_bucket_demotes(self):
        from cometbft_tpu.p2p import pex as pexmod
        from cometbft_tpu.p2p.pex import AddrBook
        book = AddrBook(strict=False, key="k2")
        old_cap = pexmod._BUCKET_CAP
        pexmod._BUCKET_CAP = 2
        try:
            for i in range(200):
                book.add_address(f"peer{i:03d}", "10.0.0.2", 1000 + i)
                book.mark_good(f"peer{i:03d}")
            olds = [a for a in book._addrs.values() if a.is_old]
            news = [a for a in book._addrs.values() if not a.is_old]
            assert olds, "promotion never happened"
            from collections import Counter
            per_old = Counter(a.bucket for a in olds)
            assert max(per_old.values()) <= 2
            assert news, "old-bucket overflow must demote back to new"
        finally:
            pexmod._BUCKET_CAP = old_cap

    def test_failed_new_addresses_age_out(self):
        from cometbft_tpu.p2p.pex import AddrBook, _MAX_ATTEMPTS_NEW
        book = AddrBook(strict=False)
        book.add_address("flaky", "10.1.1.1", 1)
        for _ in range(_MAX_ATTEMPTS_NEW + 1):
            book.mark_attempt("flaky")
        assert book.size() == 0
        # old addresses survive failures
        book.add_address("good", "10.1.1.2", 2)
        book.mark_good("good")
        for _ in range(_MAX_ATTEMPTS_NEW + 5):
            book.mark_attempt("good")
        assert book.size() == 1

    def test_pick_bias_and_persistence_roundtrip(self, tmp_path):
        from cometbft_tpu.p2p.pex import AddrBook
        path = str(tmp_path / "addrbook.json")
        book = AddrBook(path=path, strict=False)
        for i in range(30):
            book.add_address(f"n{i:02d}", "10.2.0.1", 100 + i)
        for i in range(10):
            book.mark_good(f"n{i:02d}")
        picked = book.pick_addresses(10)
        assert len(picked) == 10
        book.save()
        book2 = AddrBook(path=path, strict=False)
        assert book2.size() == 30
        assert book2.key == book.key
        assert sum(1 for a in book2._addrs.values() if a.is_old) == 10


class TestPrivatePeers:
    def test_private_peer_ids_not_gossiped(self):
        """Addresses of private peers are withheld from PEX responses
        (reference: p2p.private_peer_ids / UnsafeDialPeers private)."""
        async def go():
            async def mk():
                nk = NodeKey.generate()
                sw = Switch(nk, "pexnet", listen_addr="127.0.0.1:0")
                pex = PexReactor(AddrBook())
                sw.add_reactor(pex)
                await sw.start()
                await pex.start()
                return sw, pex
            a, pex_a = await mk()
            b, pex_b = await mk()
            c, pex_c = await mk()
            # B marks A private BEFORE learning its address
            b.private_ids.add(a.node_key.id)
            await a.dial_peer(b.listen_addr)
            await asyncio.sleep(0.1)
            await c.dial_peer(b.listen_addr)
            # give PEX time to exchange; C must never learn about A
            await asyncio.sleep(1.0)
            assert a.node_key.id not in c.peers
            assert all(ka.node_id != a.node_key.id
                       for ka in pex_c.book.pick_addresses(100))
            for sw, pex in ((a, pex_a), (b, pex_b), (c, pex_c)):
                await pex.stop()
                await sw.stop()
        run(go())
