"""Full statesync bootstrap: a fresh node restores a snapshot from a
peer, verifies it against light-client state fetched over real RPC, and
continues with blocksync + consensus.

Reference: statesync/syncer.go SyncAny, stateprovider.go:29 (light
client over rpc_servers), node/setup.go:569 startStateSync, and the
blocksync handoff.
"""
import asyncio
import os
import tempfile

import pytest

pytestmark = pytest.mark.slow

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config import Config
from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.node.node import Node
from cometbft_tpu.p2p.key import NodeKey
from cometbft_tpu.privval import FilePV
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.timestamp import Timestamp


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


def _mk_home(d, name, cfg):
    home = os.path.join(d, name)
    cfg.base.home = home
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    return home


class TestStatesyncE2E:
    def test_fresh_node_statesyncs_from_live_peer(self):
        async def run():
            with tempfile.TemporaryDirectory() as d:
                # --- validator A: produces blocks + snapshots ---------
                cfg_a = Config()
                _mk_home(d, "a", cfg_a)
                cfg_a.p2p.laddr = "tcp://127.0.0.1:0"
                cfg_a.rpc.laddr = "tcp://127.0.0.1:0"
                pv = FilePV.generate(
                    cfg_a.base.path(cfg_a.base.priv_validator_key_file),
                    cfg_a.base.path(
                        cfg_a.base.priv_validator_state_file))
                NodeKey.load_or_gen(
                    cfg_a.base.path(cfg_a.base.node_key_file))
                doc = GenesisDoc(
                    chain_id="ss-chain", genesis_time=Timestamp.now(),
                    validators=[GenesisValidator(
                        address=b"", pub_key=pv.get_pub_key(),
                        power=10)])
                doc.save_as(cfg_a.base.path(cfg_a.base.genesis_file))
                app_a = KVStoreApplication(snapshot_interval=5)
                # pace block production: with next_block_delay AND
                # timeout_commit both 0 (the reference's deprecated-
                # default semantics, config.go:1259) a solo validator
                # commits ~100 blocks/s flat out — the joiner then
                # chases a tip that advances faster than it can sync
                # and the test "hangs" (VERDICT r4 weak #8; measured:
                # height 19,354 after 5 min)
                app_a.next_block_delay_ns = 200_000_000
                node_a = Node(cfg_a, app=app_a)
                await node_a.start()
                node_b = None
                try:
                    # run past a snapshot height
                    for _ in range(600):
                        if node_a.height >= 12:
                            break
                        await asyncio.sleep(0.02)
                    assert node_a.height >= 12
                    assert app_a._snapshots, "no snapshots taken"

                    # trust root from A's RPC
                    from cometbft_tpu.rpc.client import HTTPClient
                    rpc_a = f"http://{node_a._rpc_server.listen_addr}"
                    sh, _ = await HTTPClient(rpc_a).commit(1)

                    # --- fresh node B: statesync enabled --------------
                    cfg_b = Config()
                    _mk_home(d, "b", cfg_b)
                    cfg_b.p2p.laddr = "tcp://127.0.0.1:0"
                    cfg_b.rpc.laddr = ""
                    cfg_b.statesync.enable = True
                    cfg_b.statesync.rpc_servers = [rpc_a]
                    cfg_b.statesync.trust_height = 1
                    cfg_b.statesync.trust_hash = \
                        sh.header.hash().hex()
                    cfg_b.statesync.discovery_time_ns = int(1e9)
                    cfg_b.p2p.persistent_peers = (
                        f"x@{node_a.switch.listen_addr}")
                    FilePV.generate(
                        cfg_b.base.path(
                            cfg_b.base.priv_validator_key_file),
                        cfg_b.base.path(
                            cfg_b.base.priv_validator_state_file))
                    NodeKey.load_or_gen(
                        cfg_b.base.path(cfg_b.base.node_key_file))
                    doc.save_as(
                        cfg_b.base.path(cfg_b.base.genesis_file))
                    app_b = KVStoreApplication()
                    app_b.next_block_delay_ns = 200_000_000
                    snap_h = max(app_a._snapshots)   # before B starts
                    node_b = Node(cfg_b, app=app_b)
                    await node_b.start()
                    # B restored the app state from the snapshot and
                    # kept up via blocksync
                    assert node_b.state_store.load() \
                        .last_block_height >= snap_h
                    assert app_b._height >= snap_h
                    for _ in range(600):
                        if node_b.height >= node_a.height - 1:
                            break
                        await asyncio.sleep(0.02)
                    assert node_b.height >= snap_h
                    # same chain: B's store only has blocks ABOVE its
                    # bootstrap height — compare the first one it holds
                    boot_h = node_b.state_store.load() \
                        .last_block_height
                    h = min(node_a.height, node_b.height)
                    while h > boot_h and \
                            node_b.block_store.load_block(h) is None:
                        h -= 1
                    b_block = node_b.block_store.load_block(h)
                    assert b_block is not None, \
                        "blocksync made no progress after statesync"
                    assert b_block.hash() == \
                        node_a.block_store.load_block(h).hash()
                finally:
                    if node_b is not None:
                        await node_b.stop()
                    await node_a.stop()
        asyncio.run(run())
