"""Fleet observatory: clock-anchor fits, cross-node merge math, and
the /trace anchor contract (tools/fleet_report.py; ISSUE 19).

The synthetic-fleet tests construct 3 nodes whose monotonic clocks
have known offsets and drift, inject known propagation latencies on
the shared wall timeline, and require the report to reconstruct them
within tolerance — the merge math is only trustworthy if injected
ground truth survives the round trip through anchors + fit.
"""
import asyncio
import importlib.util
import json
import os

import pytest

from cometbft_tpu.libs import tracing

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MS = 1_000_000  # ns


def _fr():
    spec = importlib.util.spec_from_file_location(
        "fleet_report", os.path.join(_ROOT, "tools",
                                     "fleet_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


class TestClockFit:
    def test_single_anchor_pins_offset_only(self):
        fr = _fr()
        off, drift = fr.fit_clock([(1_000, 5_000)])
        assert off == 4_000 and drift == 0.0
        assert fr.to_wall(1_000, (off, drift)) == 5_000

    def test_offset_and_drift_recovered_exactly(self):
        fr = _fr()
        true_off, true_drift = 7_000_000_000.0, 2e-6
        anchors = [(m, int(m * (1 + true_drift) + true_off))
                   for m in (0, 10**9, 3 * 10**9, 10 * 10**9)]
        off, drift = fr.fit_clock(anchors)
        assert drift == pytest.approx(true_drift, abs=1e-9)
        for m in (0, 5 * 10**9, 10 * 10**9):
            want = m * (1 + true_drift) + true_off
            assert abs(fr.to_wall(m, (off, drift)) - want) < 0.1 * MS

    def test_no_anchors_is_identity(self):
        fr = _fr()
        assert fr.fit_clock([]) == (0.0, 0.0)
        assert fr.to_wall(123, (0.0, 0.0)) == 123


# ---------------------------------------------------------------------
# synthetic 3-node fleet with known clock errors + latencies

T0 = 100 * 10**9  # the proposer's first-sent instant, wall ns


def _node(name, off_ns, events, drift=0.0):
    """Build a flight-dump record for a node whose monotonic clock
    satisfies wall = mono*(1+drift) + off_ns.  ``events`` is
    [(wall_ts_ns, name, attrs)] — converted to the node's monotonic
    domain, which is what the recorder would have written."""
    def mono(w):
        return int(round((w - off_ns) / (1 + drift)))
    evs = [{"ts_ns": mono(w), "dur_ns": 0, "category": "consensus",
            "name": n, "height": 5, "attrs": a}
           for w, n, a in events]
    anchors = [[m, int(m * (1 + drift) + off_ns)]
               for m in (0, 20 * 10**9, 200 * 10**9)]
    return {"node": name, "anchors": anchors, "events": evs}


def _fleet():
    pv, pc = 1, 2  # canonical PREVOTE_TYPE / PRECOMMIT_TYPE
    # proposer a (validator 0): first-sent at T0, commits at +90ms
    a = _node("a", off_ns=0, events=[
        (T0, "proposal_broadcast", {"round": 0, "parts": 2}),
        (T0 + 40 * MS, "vote_recv", {"type": pv, "index": 1}),
        (T0 + 50 * MS, "vote_recv", {"type": pv, "index": 2}),
        (T0 + 62 * MS, "vote_recv", {"type": pv, "index": 3}),
        (T0 + 75 * MS, "vote_recv", {"type": pc, "index": 1}),
        (T0 + 80 * MS, "vote_recv", {"type": pc, "index": 2}),
        (T0 + 85 * MS, "vote_recv", {"type": pc, "index": 3}),
        (T0 + 90 * MS, "commit", {}),
    ])
    # b: clock 5 s ahead + 1e-6 drift; sees the proposal 30 ms after
    # first-sent, reaches 2/3 prevote power (3rd distinct foreign
    # vote of 4 equal validators) at +70 ms, commits at +95 ms
    b = _node("b", off_ns=5 * 10**9, drift=1e-6, events=[
        (T0 + 30 * MS, "proposal_recv", {"peer": "a"}),
        (T0 + 40 * MS, "vote_recv", {"type": pv, "index": 0}),
        (T0 + 55 * MS, "vote_recv", {"type": pv, "index": 2}),
        (T0 + 70 * MS, "vote_recv", {"type": pv, "index": 3}),
        (T0 + 70 * MS, "vote_recv", {"type": pv, "index": 3}),
        (T0 + 95 * MS, "commit", {}),
    ])
    # c: clock 12 s behind; the straggler — sees the proposal at
    # +45 ms, commits last at +110 ms
    c = _node("c", off_ns=-12 * 10**9, events=[
        (T0 + 45 * MS, "proposal_recv", {"peer": "b"}),
        (T0 + 50 * MS, "vote_recv", {"type": pv, "index": 0}),
        (T0 + 60 * MS, "vote_recv", {"type": pv, "index": 1}),
        (T0 + 110 * MS, "commit", {}),
    ])
    return [a, b, c]


class TestFleetMerge:
    def test_injected_latencies_reconstructed(self):
        fr = _fr()
        report = fr.analyze([fr.node_record(r, r["node"])
                             for r in _fleet()])
        assert report["nodes"] == ["a", "b", "c"]
        h = report["heights"][5]
        assert h["proposer"] == "a"
        rows = h["nodes"]
        tol = 1.0  # ms: fit error must stay far below the latencies
        assert rows["b"]["proposal_seen_ms"] == \
            pytest.approx(30.0, abs=tol)
        assert rows["c"]["proposal_seen_ms"] == \
            pytest.approx(45.0, abs=tol)
        # 4 equal validators: 1/3 crossed at the 2nd distinct foreign
        # vote, 2/3 at the 3rd; duplicate deliveries carry no power
        assert rows["b"]["prevote_t13_ms"] == \
            pytest.approx(55.0, abs=tol)
        assert rows["b"]["prevote_t23_ms"] == \
            pytest.approx(70.0, abs=tol)
        assert rows["a"]["precommit_t23_ms"] == \
            pytest.approx(85.0, abs=tol)
        # c never collected 2/3 prevote power in these events
        assert rows["c"]["prevote_t23_ms"] is None
        assert h["commit_skew_ms"] == pytest.approx(20.0, abs=tol)
        # straggler table: c trails on both proposal and commit
        st = report["stragglers"]
        assert st["c"]["mean_proposal_delay_ms"] == \
            pytest.approx(45.0, abs=tol)
        assert st["c"]["mean_commit_delay_ms"] > \
            st["a"]["mean_commit_delay_ms"]
        # proposal hop latencies are the injected 30/45 ms deltas
        hops = report["hop_latency_ms"]["proposal"]
        assert hops["n"] == 2
        assert hops["max"] == pytest.approx(45.0, abs=tol)

    def test_clock_fits_reported(self):
        fr = _fr()
        report = fr.analyze([fr.node_record(r, r["node"])
                             for r in _fleet()])
        fits = report["clock_fits"]
        assert fits["b"]["offset_ns"] == \
            pytest.approx(5e9, rel=1e-3)
        assert fits["c"]["offset_ns"] == \
            pytest.approx(-12e9, rel=1e-3)

    def test_fleet_collection_file_and_text_render(self, tmp_path):
        fr = _fr()
        path = os.path.join(str(tmp_path), "fleet_test.json")
        with open(path, "w") as f:
            json.dump({"nodes": {r["node"]: r for r in _fleet()}}, f)
        nodes = fr.load_inputs([path])
        assert sorted(n["node"] for n in nodes) == ["a", "b", "c"]
        text = fr.render_report(fr.analyze(nodes))
        assert "proposer=a" in text
        assert "stragglers" in text
        # stringified-int64 events (a /trace body) parse identically
        stringified = []
        for r in _fleet():
            r2 = dict(r)
            r2["anchors"] = [[str(m), str(w)]
                             for m, w in r["anchors"]]
            r2["events"] = [{**e, "ts_ns": str(e["ts_ns"]),
                             "dur_ns": str(e["dur_ns"]),
                             "height": str(e["height"])}
                            for e in r["events"]]
            stringified.append(fr.node_record(r2, r2["node"]))
        rep2 = fr.analyze(stringified)
        assert rep2["heights"][5]["nodes"]["b"]["proposal_seen_ms"] \
            == pytest.approx(30.0, abs=1.0)


class TestTraceAnchorContract:
    def test_trace_serves_anchors_per_spec(self):
        """docs/rpc-spec.json requires the anchor field; the route
        must serve (monotonic_ns, wall_ns) string pairs."""
        with open(os.path.join(_ROOT, "docs", "rpc-spec.json")) as f:
            spec = json.load(f)
        required = spec["methods"]["trace"]["result_required"]
        assert "anchors" in required and "node" in required
        from cometbft_tpu.rpc import core
        old = tracing.set_recorder(
            tracing.Recorder(node_id="contract-probe"))
        try:
            tracing.instant(tracing.CONSENSUS, "commit", height=1)
            resp = run(core.routes(None)["trace"]())
        finally:
            tracing.set_recorder(old)
        for field in required:
            assert field in resp, field
        assert resp["node"] == "contract-probe"
        assert resp["anchors"], "at least the construction anchor"
        for pair in resp["anchors"]:
            assert len(pair) == 2
            mono, wall = int(pair[0]), int(pair[1])
            assert mono > 0 and wall > 0

    def test_dump_carries_anchors_and_node(self, tmp_path):
        r = tracing.Recorder(node_id="dump-probe",
                             dump_dir=str(tmp_path))
        r.record_instant("consensus", "commit", 3, None)
        path = r.dump("probe")
        with open(path) as f:
            rec = json.load(f)
        assert rec["node"] == "dump-probe"
        assert rec["anchors"]
        mono, wall = rec["anchors"][0]
        assert isinstance(mono, int) and isinstance(wall, int)

    def test_anchor_refresh_passive_and_bounded(self):
        r = tracing.Recorder(anchor_interval_s=1e-9)
        for _ in range(200):
            r.record_instant("p2p", "recv", 0, None)
        assert 2 <= len(r.anchors) <= r.ANCHORS_MAX
        first = r.anchors[0]
        r2 = tracing.Recorder(anchor_interval_s=3600.0)
        for _ in range(200):
            r2.record_instant("p2p", "recv", 0, None)
        assert len(r2.anchors) == 1  # interval not reached
        # the first anchor survives eviction (drift baseline)
        r3 = tracing.Recorder(anchor_interval_s=1e-9)
        f0 = r3.anchors[0]
        for _ in range(r3.ANCHORS_MAX * 3):
            r3.record_instant("p2p", "recv", 0, None)
        assert len(r3.anchors) <= r3.ANCHORS_MAX
        assert r3.anchors[0] == f0
        assert first  # silence unused warning
