"""Test config: select the CPU JAX platform *in process*.

Multi-chip hardware isn't available in CI; sharding correctness is validated
on a virtual 8-device CPU mesh (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).

Platform selection happens via jax.config.update rather than the
JAX_PLATFORMS environment variable: this environment registers a TPU
plugin ("axon") from sitecustomize at interpreter start, and overriding
the env var conflicts with that hook (it expects to manage platform
selection).  Post-import config.update only initializes the CPU client,
never dials the TPU pool, and works the same everywhere.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Host-protocol tests exercise the CPU crypto path; kernel tests import
# ops.ed25519_jax directly (and run it on the virtual CPU devices).
from cometbft_tpu.crypto import batch  # noqa: E402

if not os.environ.get("COMETBFT_TPU_CRYPTO_BACKEND"):
    batch.set_backend("cpu")
