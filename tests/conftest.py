"""Test config: select the CPU JAX platform *in process*.

Multi-chip hardware isn't available in CI; sharding correctness is validated
on a virtual 8-device CPU mesh (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).

Platform selection happens via jax.config.update rather than the
JAX_PLATFORMS environment variable: this environment registers a TPU
plugin ("axon") from sitecustomize at interpreter start, and overriding
the env var conflicts with that hook (it expects to manage platform
selection).  Post-import config.update only initializes the CPU client,
never dials the TPU pool, and works the same everywhere.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Host-protocol tests exercise the CPU crypto path; kernel tests import
# ops.ed25519_jax directly (and run it on the virtual CPU devices).
from cometbft_tpu.crypto import batch  # noqa: E402

if not os.environ.get("COMETBFT_TPU_CRYPTO_BACKEND"):
    batch.set_backend("cpu")


# ---------------------------------------------------------------------------
# Per-test wall-clock timeouts (VERDICT r4 #8: one hung net must not
# mask the whole tier).  SIGALRM raises inside the test — including
# inside asyncio.run — so a wedged event loop still fails fast with a
# traceback instead of eating the session.  Budgets are generous (the
# box has one CPU and kernel tests pay a 60-110 s cold compile);
# override per test with @pytest.mark.timeout_s(N).

import signal

import pytest

_DEFAULT_TIMEOUT_S = 300
_SLOW_TIMEOUT_S = 600
_KERNEL_TIMEOUT_S = 900


class _TestTimeout(Exception):
    pass


@pytest.fixture(autouse=True)
def _per_test_timeout(request):
    limit = _DEFAULT_TIMEOUT_S
    if request.node.get_closest_marker("slow"):
        limit = _SLOW_TIMEOUT_S
    if request.node.get_closest_marker("kernel"):
        limit = _KERNEL_TIMEOUT_S
    override = request.node.get_closest_marker("timeout_s")
    if override and override.args:
        limit = override.args[0]

    def on_alarm(signum, frame):
        raise _TestTimeout(
            f"test exceeded its {limit}s wall-clock budget")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.setitimer(signal.ITIMER_REAL, limit)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old)
