"""Drift guards for the observability surface (ISSUE 19).

Two classes of silent rot are pinned here:

* the metrics catalog embedded in docs/observability.md must be the
  byte-exact output of ``tools/metrics_catalog.py`` — adding a family
  without regenerating the docs fails tier-1;
* the span/marker tables in ``tools/trace_report.py`` (and the event
  names ``tools/fleet_report.py`` keys its critical path on) must
  match the names the instrumented modules actually emit — renaming
  an event without updating the report tables would silently drop it
  from every report.
"""
import importlib.util
import os
import re

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_ROOT, "cometbft_tpu")


def _load(mod_name):
    spec = importlib.util.spec_from_file_location(
        mod_name, os.path.join(_ROOT, "tools", f"{mod_name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestCatalogDocsDrift:
    def test_docs_catalog_matches_generator(self):
        cat = _load("metrics_catalog")
        generated = cat.to_markdown(cat.collect_catalog()).strip()
        with open(os.path.join(_ROOT, "docs",
                               "observability.md")) as f:
            doc = f.read()
        m = re.search(r"<!-- catalog:generated -->\n(.*?)\n"
                      r"<!-- /catalog:generated -->", doc, re.S)
        assert m, "catalog markers missing from docs/observability.md"
        assert m.group(1).strip() == generated, (
            "docs/observability.md catalog is stale — regenerate "
            "with: python tools/metrics_catalog.py")


def _emitted(category: str) -> tuple[set, set]:
    """(span_names, instant_names) for one category, by scanning the
    package source for tracing calls.  F-string names are truncated
    at the first placeholder (``step:{...}`` -> ``step:``)."""
    call = re.compile(
        r"tracing\.(instant|span|record_span)\(\s*"
        r"tracing\.([A-Z0-9_]+)\s*,\s*[fF]?\"([^\"]+)\"", re.S)
    spans: set = set()
    instants: set = set()
    for dirpath, _dirs, files in os.walk(_PKG):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn)) as f:
                src = f.read()
            for kind, cat_const, name in call.findall(src):
                if cat_const != category:
                    continue
                name = name.split("{")[0]
                (instants if kind == "instant" else spans).add(name)
    return spans, instants


class TestTraceReportNamePinning:
    def test_consensus_spans_all_bucketed(self):
        tr = _load("trace_report")
        spans, _ = _emitted("CONSENSUS")
        assert spans, "census found no consensus spans"
        for name in spans:
            if name.startswith("step:"):
                continue
            assert name in tr.CONSENSUS_SPAN_BUCKETS, (
                f"consensus span {name!r} is emitted but has no "
                f"bucket in trace_report.CONSENSUS_SPAN_BUCKETS")
        # and the reverse: no stale table entries for names nobody
        # emits any more ("step:Commit" is matched dynamically)
        for name in tr.CONSENSUS_SPAN_BUCKETS:
            assert name in spans or name.startswith("step:"), (
                f"trace_report buckets {name!r} but nothing emits it")

    def test_consensus_instants_all_marked(self):
        tr = _load("trace_report")
        _, instants = _emitted("CONSENSUS")
        assert instants, "census found no consensus instants"
        assert instants == set(tr.CONSENSUS_MARKERS), (
            "trace_report.CONSENSUS_MARKERS out of sync with the "
            f"emitted names: emitted-only="
            f"{sorted(instants - set(tr.CONSENSUS_MARKERS))} "
            f"table-only="
            f"{sorted(set(tr.CONSENSUS_MARKERS) - instants)}")

    def test_fleet_report_keys_on_emitted_names(self):
        """The cluster critical path is keyed on these instants; if
        one is renamed at the emit site the fleet report silently
        loses that column."""
        spans, instants = _emitted("CONSENSUS")
        for needed in ("proposal_broadcast", "proposal_recv",
                       "vote_recv", "commit"):
            assert needed in instants, needed
        assert "step:" in spans  # step:{...} spans incl. Propose

    def test_peer_attributed_mempool_instants_emitted(self):
        _, instants = _emitted("MEMPOOL")
        for needed in ("txs_recv", "have_recv", "want_recv"):
            assert needed in instants, needed
