"""SQL event sink (reference: state/indexer/sink/psql + schema.sql).

A node configured with tx_index.indexer = "psql" mirrors block and tx
events into a relational database with the reference's schema; the
sink is write-only from the node (searches unsupported), and operator
SQL runs against the tables/views directly.
"""
import asyncio
import os
import sqlite3
import tempfile

import pytest


class TestSQLEventSink:
    def test_unit_schema_and_rows(self):
        from cometbft_tpu.abci import types as abci
        from cometbft_tpu.indexer import SQLEventSink

        sink = SQLEventSink(":memory:", "sink-chain")
        sink.index_block_events(1, [
            abci.Event(type="rewards", attributes=[
                abci.EventAttribute(key="amount", value="12",
                                    index=True)])])
        sink.index_tx_events([abci.TxResult(
            height=1, index=0, tx=b"k=v",
            result=abci.ExecTxResult(code=0, events=[
                abci.Event(type="transfer", attributes=[
                    abci.EventAttribute(key="to", value="bob",
                                        index=True)])]))])
        cur = sink._conn.cursor()
        cur.execute("SELECT height, chain_id FROM blocks")
        assert cur.fetchall() == [(1, "sink-chain")]
        cur.execute("SELECT tx_hash FROM tx_results")
        (tx_hash_,), = cur.fetchall()
        assert len(tx_hash_) == 64          # hex sha256
        # the reference's views answer operator queries
        cur.execute(
            "SELECT value FROM block_events WHERE "
            "composite_key = 'rewards.amount'")
        assert cur.fetchall() == [("12",)]
        cur.execute(
            "SELECT value FROM tx_events WHERE "
            "composite_key = 'transfer.to'")
        assert cur.fetchall() == [("bob",)]
        # write-only: searches route operators to SQL
        with pytest.raises(NotImplementedError):
            sink.tx_indexer.search(None)
        # prune removes tx rows below the retain height
        assert sink.tx_indexer.prune(1, 2) > 0
        cur.execute("SELECT COUNT(*) FROM tx_results")
        assert cur.fetchone()[0] == 0
        sink.close()

    def test_live_node_psql_indexer(self):
        from cometbft_tpu.config import Config
        from cometbft_tpu.node.node import Node
        from cometbft_tpu.p2p.key import NodeKey
        from cometbft_tpu.privval import FilePV
        from cometbft_tpu.rpc.client import HTTPClient
        from cometbft_tpu.types.genesis import (
            GenesisDoc, GenesisValidator,
        )
        from cometbft_tpu.types.timestamp import Timestamp

        async def run():
            with tempfile.TemporaryDirectory() as d:
                home = os.path.join(d, "node")
                cfg = Config()
                cfg.base.home = home
                cfg.p2p.laddr = "tcp://127.0.0.1:0"
                cfg.rpc.laddr = "tcp://127.0.0.1:0"
                cfg.tx_index.indexer = "psql"
                cfg.consensus.timeout_commit_ns = 20_000_000
                os.makedirs(os.path.join(home, "config"),
                            exist_ok=True)
                os.makedirs(os.path.join(home, "data"), exist_ok=True)
                pv = FilePV.generate(
                    cfg.base.path(cfg.base.priv_validator_key_file),
                    cfg.base.path(cfg.base.priv_validator_state_file))
                NodeKey.load_or_gen(
                    cfg.base.path(cfg.base.node_key_file))
                GenesisDoc(
                    chain_id="psql-chain",
                    genesis_time=Timestamp.now(),
                    validators=[GenesisValidator(
                        address=b"", pub_key=pv.get_pub_key(),
                        power=10)],
                ).save_as(cfg.base.path(cfg.base.genesis_file))
                node = Node(cfg)
                await node.start()
                try:
                    cli = HTTPClient(
                        f"http://{node._rpc_server.listen_addr}",
                        timeout=30.0)
                    res = await cli.broadcast_tx_commit(b"psql=row")
                    assert res["tx_result"]["code"] == 0
                    tx_height = int(res["height"])
                    for _ in range(200):
                        if node.height > tx_height:
                            break
                        await asyncio.sleep(0.02)
                finally:
                    await node.stop()
                db_path = cfg.base.path(
                    os.path.join(cfg.base.db_dir, "events.sqlite"))
                assert os.path.exists(db_path)
                conn = sqlite3.connect(db_path)
                cur = conn.cursor()
                # NewBlockEvents fires only for blocks with app
                # events, so the tx block is the one guaranteed row
                cur.execute("SELECT height FROM blocks")
                assert (tx_height,) in cur.fetchall()
                cur.execute(
                    "SELECT height, \"index\" FROM tx_results "
                    "JOIN blocks ON tx_results.block_id = blocks.rowid")
                rows = cur.fetchall()
                assert (tx_height, 0) in rows
                # kvstore app emits app events for the tx
                cur.execute(
                    "SELECT DISTINCT type FROM events "
                    "WHERE tx_id IS NOT NULL")
                types = {t for (t,) in cur.fetchall()}
                assert "tx" in types
                conn.close()
        asyncio.run(run())


class TestSinkReindex:
    def test_reindexing_replaces_not_duplicates(self):
        """Re-delivery of a height/tx must not double event rows."""
        from cometbft_tpu.abci import types as abci
        from cometbft_tpu.indexer import SQLEventSink

        sink = SQLEventSink(":memory:", "c")
        ev = [abci.Event(type="t", attributes=[
            abci.EventAttribute(key="k", value="v", index=True)])]
        sink.index_block_events(1, ev)
        sink.index_block_events(1, ev)
        cur = sink._conn.cursor()
        cur.execute("SELECT COUNT(*) FROM events WHERE tx_id IS NULL")
        assert cur.fetchone()[0] == 2     # implicit block + t
        txr = abci.TxResult(height=1, index=0, tx=b"x",
                            result=abci.ExecTxResult(code=0,
                                                     events=ev))
        sink.index_tx_events([txr])
        sink.index_tx_events([txr])
        cur.execute(
            "SELECT COUNT(*) FROM events WHERE tx_id IS NOT NULL")
        assert cur.fetchone()[0] == 3     # 2 implicit + t
        sink.close()


class TestPsqlDSN:
    def test_dsn_without_driver_raises_clear_error(self):
        """A postgres:// DSN on a host without psycopg2 must fail
        loudly with guidance, not fall back to a sqlite file named
        'postgres://...' (reference: the sink targets a real psql)."""
        import pytest

        from cometbft_tpu.indexer.sink_sql import SQLEventSink
        with pytest.raises(RuntimeError, match="psycopg2"):
            SQLEventSink("postgres://u:p@localhost/db", "c")

    def test_psql_schema_dialect(self):
        from cometbft_tpu.indexer.sink_sql import _psql_schema
        s = _psql_schema()
        assert "BIGSERIAL PRIMARY KEY" in s
        assert "BYTEA" in s
        assert "AUTOINCREMENT" not in s and "BLOB" not in s
        assert "CREATE OR REPLACE VIEW" in s

    def test_cursor_paramstyle_rewrite(self):
        from cometbft_tpu.indexer.sink_sql import _Cursor

        captured = {}

        class FakeCur:
            def execute(self, sql, params=()):
                captured["sql"] = sql
        _Cursor(FakeCur(), "%s").execute(
            "SELECT rowid FROM blocks WHERE height = ?", (1,))
        assert captured["sql"] == \
            "SELECT rowid FROM blocks WHERE height = %s"
