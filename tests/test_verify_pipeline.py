"""Overlapped verification pipeline (ISSUE 14): tile kernel
correctness, tiled-vs-monolithic verdict parity, per-tile bisection
attribution, GIL-free worker overlap, the async verify seam, and the
committed perf-claim gates.

The tile kernel (native ed25519_batch_verify_tile: packed blobs,
staged pubkey decompression, signed-digit MSM, fe_sqr decompression)
must agree with the legacy monolithic entry and the golden model on
every verdict — including ZIP-215 corner encodings — and the python
pipeline (crypto/pipeline.py) must attribute bad signatures to exact
indices no matter where they fall relative to tile boundaries.
"""
import asyncio
import os
import secrets
import struct
import threading
import time

import pytest

from cometbft_tpu.crypto import _native_loader
from cometbft_tpu.crypto import ed25519
from cometbft_tpu.crypto import pipeline as cpipe
from cometbft_tpu.libs.workers import SupervisedWorker


def _native():
    mod = _native_loader.load()
    if mod is None:
        pytest.skip("no compiler available")
    if not hasattr(mod, "ed25519_batch_verify_tile"):
        pytest.skip("module predates the tile kernel")
    return mod


def _valid(i, msg=None):
    from cometbft_tpu.crypto import _ed25519_ref as ref
    seed = bytes([i % 256, i // 256 % 256]) + secrets.token_bytes(30)
    pub = ref.public_key(seed)
    m = msg if msg is not None else b"tile-msg-%d" % i
    return (pub, m, ref.sign(seed, m))


def _blobs(chunk):
    return (b"".join(p for p, _, _ in chunk),
            b"".join(m for _, m, _ in chunk),
            struct.pack(f"<{len(chunk)}I",
                        *(len(m) for _, m, _ in chunk)),
            b"".join(s for _, _, s in chunk))


def _tile_verdict(native, items, staged=False):
    z = secrets.token_bytes(16 * len(items))
    blobs = _blobs(items)
    if staged:
        pts = native.ed25519_stage_pubs(blobs[0])
        return bool(native.ed25519_batch_verify_tile(*blobs, z, pts))
    return bool(native.ed25519_batch_verify_tile(*blobs, z))


# ---------------------------------------------------------------------
# tile kernel vs golden model / legacy entry

class TestTileKernel:
    @pytest.mark.parametrize("staged", [False, True])
    @pytest.mark.parametrize("n", [1, 2, 7, 40])
    def test_valid_tiles_accept(self, n, staged):
        native = _native()
        items = [_valid(i) for i in range(n)]
        assert _tile_verdict(native, items, staged=staged)

    @pytest.mark.parametrize("staged", [False, True])
    def test_corrupted_signature_rejects(self, staged):
        native = _native()
        items = [_valid(i) for i in range(9)]
        pub, msg, sig = items[4]
        items[4] = (pub, msg, sig[:7] + bytes([sig[7] ^ 1]) + sig[8:])
        assert not _tile_verdict(native, items, staged=staged)

    def test_wrong_message_rejects(self):
        native = _native()
        items = [_valid(i) for i in range(5)]
        pub, _, sig = items[0]
        items[0] = (pub, b"forged", sig)
        assert not _tile_verdict(native, items)

    def test_non_canonical_s_rejects(self):
        from cometbft_tpu.crypto import _ed25519_ref as ref
        native = _native()
        items = [_valid(i) for i in range(3)]
        pub, msg, sig = items[1]
        s = int.from_bytes(sig[32:], "little") + ref.L
        items[1] = (pub, msg, sig[:32] + s.to_bytes(32, "little"))
        assert not _tile_verdict(native, items)

    @pytest.mark.parametrize("staged", [False, True])
    def test_zip215_corner_encodings_accept(self, staged):
        # A = order-4 point (y=0), R = non-canonical identity (y=p+1),
        # S=0: a ZIP-215 accept the golden model certifies — the
        # fe_sqr decompression chain must agree with the legacy one
        from cometbft_tpu.crypto import _ed25519_ref as ref
        native = _native()
        a_small = bytes(32)
        r_nc = (ref.P + 1).to_bytes(32, "little")
        corner = (a_small, b"whatever", r_nc + bytes(32))
        assert ref.verify(*corner)
        items = [_valid(0), corner, _valid(2)]
        assert _tile_verdict(native, items, staged=staged)

    def test_off_curve_pubkey_rejects(self):
        from cometbft_tpu.crypto import _ed25519_ref as ref
        native = _native()
        bad_pub = bytes([2]) + bytes(30) + bytes([0])
        if ref.decompress(bad_pub) is not None:
            pytest.skip("encoding unexpectedly valid")
        items = [_valid(0), (bad_pub, b"m", _valid(0)[2])]
        assert not _tile_verdict(native, items)
        assert not _tile_verdict(native, items, staged=True)

    def test_decompress_parity_fuzz_vs_legacy(self):
        """Random + structured encodings: the tile entry (fast
        decompression) and the legacy entry must return identical
        verdicts item-for-item (checked via singleton batches, where
        verdict == per-item acceptance)."""
        from cometbft_tpu.crypto import _ed25519_ref as ref
        native = _native()
        rng_cases = [secrets.token_bytes(32) for _ in range(24)]
        structured = [
            bytes(32),                                # y=0
            (ref.P - 1).to_bytes(32, "little"),       # y=p-1
            (ref.P).to_bytes(32, "little"),           # y=p (non-canon 0)
            (ref.P + 1).to_bytes(32, "little"),       # non-canon 1
            bytes([1] + [0] * 31),                    # identity
            bytes([0] * 31 + [0x80]),                 # y=0, sign=1
            bytes([0xFF] * 32),
        ]
        good = _valid(7)
        for enc in rng_cases + structured:
            item = (enc, b"m", good[2])
            z = secrets.token_bytes(16)
            legacy = bool(native.ed25519_batch_verify([item], z))
            tiled = bool(native.ed25519_batch_verify_tile(
                *_blobs([item]), z))
            assert legacy == tiled, enc.hex()

    def test_stage_pubs_blob_shape_and_invalid_marker(self):
        native = _native()
        good = _valid(1)[0]
        from cometbft_tpu.crypto import _ed25519_ref as ref
        bad = bytes([2]) + bytes(30) + bytes([0])
        if ref.decompress(bad) is not None:
            pytest.skip("encoding unexpectedly valid")
        blob = native.ed25519_stage_pubs(good + bad)
        rec = len(blob) // 2
        assert len(blob) % 2 == 0
        assert blob[rec - 1] == 1          # valid marker
        assert blob[2 * rec - 1] == 0      # invalid marker

    def test_mismatched_staged_blob_is_ignored_not_trusted(self):
        # a stale/mismatched staged blob must not corrupt verdicts
        native = _native()
        items = [_valid(i) for i in range(3)]
        z = secrets.token_bytes(16 * 3)
        assert native.ed25519_batch_verify_tile(
            *_blobs(items), z, b"\x00" * 7)


# ---------------------------------------------------------------------
# tiled pipeline: verdict parity + per-tile bisection attribution

class TestTiledParityFuzz:
    def _run_pair(self, items, tile):
        native = _native()
        raw = list(items)

        def verify_one(i):
            from cometbft_tpu.crypto import _ed25519_ref as ref
            pub, m, s = raw[i]
            return ref.verify(pub, m, s)

        ok_t, mask_t = cpipe.verify_items_pipelined(
            native, raw, verify_one, tile=tile)
        z = secrets.token_bytes(16 * len(raw))
        ok_m = bool(native.ed25519_batch_verify(raw, z))
        return (ok_t, mask_t), ok_m

    def test_all_valid_parity(self):
        items = [_valid(i) for i in range(150)]
        (ok_t, mask_t), ok_m = self._run_pair(items, tile=64)
        assert ok_t and ok_m and all(mask_t)

    @pytest.mark.parametrize("bad_idx", [
        [0],                      # first item of first tile
        [63], [64],               # tile boundary straddle
        [149],                    # last item of partial tile
        [127, 128],               # boundary pair
        [5, 70, 148],             # one per tile
    ])
    def test_bad_positions_attributed_exactly(self, bad_idx):
        items = [_valid(i) for i in range(150)]
        for i in bad_idx:
            pub, m, s = items[i]
            items[i] = (pub, m, s[:9] + bytes([s[9] ^ 0x40]) + s[10:])
        (ok_t, mask_t), ok_m = self._run_pair(items, tile=64)
        assert not ok_t and not ok_m
        assert [i for i, v in enumerate(mask_t) if not v] == bad_idx

    def test_random_fuzz_matches_monolithic_bisection(self):
        """Random bad positions: the per-tile bisection's mask must
        equal the monolithic path's mask (CpuBatchVerifier pipelined
        vs monolithic=True) — the attribution contract."""
        import random
        rng = random.Random(1400)
        for trial in range(3):
            n = rng.randrange(130, 200)
            items = [_valid(1000 * trial + i) for i in range(n)]
            bad = sorted(rng.sample(range(n), rng.randrange(1, 5)))
            for i in bad:
                pub, m, s = items[i]
                items[i] = (pub, m,
                            s[:3] + bytes([s[3] ^ 0x11]) + s[4:])

            def bv(monolithic):
                v = ed25519.CpuBatchVerifier(monolithic=monolithic)
                for pub, m, s in items:
                    v.add(ed25519.Ed25519PubKey(pub), m, s)
                return v

            old = os.environ.get("COMETBFT_TPU_VERIFY_TILE")
            os.environ["COMETBFT_TPU_VERIFY_TILE"] = "64"
            try:
                ok_t, mask_t = bv(False).verify()
            finally:
                if old is None:
                    os.environ.pop("COMETBFT_TPU_VERIFY_TILE", None)
                else:
                    os.environ["COMETBFT_TPU_VERIFY_TILE"] = old
            ok_m, mask_m = bv(True).verify()
            assert ok_t == ok_m is False
            assert mask_t == mask_m
            assert [i for i, v in enumerate(mask_t) if not v] == bad

    def test_tile_reject_counter_counts_rejecting_tiles(self):
        native = _native()
        ctr = cpipe._tile_reject_counter()
        before = ctr.value
        items = [_valid(i) for i in range(150)]
        pub, m, s = items[70]
        items[70] = (pub, m, s[:5] + bytes([s[5] ^ 2]) + s[6:])
        self._run_pair(items, tile=64)
        assert ctr.value == before + 1     # exactly one tile bisected


class TestTilePlan:
    def test_balanced_and_bounded(self):
        plan = cpipe.tile_plan(10000, 4096)
        sizes = [hi - lo for lo, hi in plan]
        assert sum(sizes) == 10000
        assert max(sizes) <= 4096
        # balanced: no degenerate tail tile (the naive plan's 1808)
        assert max(sizes) - min(sizes) <= len(sizes)
        assert plan[0][0] == 0 and plan[-1][1] == 10000

    def test_small_and_exact(self):
        assert cpipe.tile_plan(10, 64) == [(0, 10)]
        assert cpipe.tile_plan(128, 64) == [(0, 64), (64, 128)]
        assert cpipe.tile_plan(0, 64) == []


# ---------------------------------------------------------------------
# GIL release / two-thread overlap

class TestKernelGilRelease:
    N = 5000

    def _items(self, tag):
        sk = ed25519.gen_priv_key()
        pkb = sk.pub_key().bytes()
        out = []
        for i in range(self.N):
            m = b"%s-%05d" % (tag, i)
            out.append((pkb, m, sk.sign(m)))
        return out

    def test_python_progress_during_native_batch(self):
        """The 1-core-safe GIL proof: while a 5k batch runs on a
        worker thread, the main thread must keep executing python —
        with the GIL held through the kernel the counter would stay
        at ~0."""
        native = _native()
        items = self._items(b"gil")
        z = secrets.token_bytes(16 * self.N)
        native.ed25519_batch_verify(items, z)        # warm
        done = threading.Event()
        result = {}

        def run():
            result["ok"] = native.ed25519_batch_verify(items, z)
            done.set()

        t = threading.Thread(target=run)
        t.start()
        ticks = 0
        while not done.is_set():
            ticks += 1
        t.join()
        assert result["ok"] == 1
        # a held GIL yields only the handful of iterations before the
        # kernel grabs it; released, the loop runs millions — 1000 is
        # orders of magnitude above the held case on any host
        assert ticks > 1000, ticks

    def test_two_thread_overlap_wall_clock(self):
        """Two concurrent 5k batches: on a multi-core host the
        GIL-free kernels overlap (< 1.9x single-thread wall); on the
        1-vCPU QA rig they timeshare — the bound only proves no
        pathological serialization (< 2.6x)."""
        native = _native()
        a = self._items(b"ova")
        b = self._items(b"ovb")
        za = secrets.token_bytes(16 * self.N)
        zb = secrets.token_bytes(16 * self.N)
        native.ed25519_batch_verify(a, za)           # warm
        native.ed25519_batch_verify(b, zb)
        t0 = time.perf_counter()
        native.ed25519_batch_verify(a, za)
        single = time.perf_counter() - t0

        t0 = time.perf_counter()
        ts = [threading.Thread(target=native.ed25519_batch_verify,
                               args=(a, za)),
              threading.Thread(target=native.ed25519_batch_verify,
                               args=(b, zb))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        both = time.perf_counter() - t0
        limit = 1.9 if (os.cpu_count() or 1) >= 2 else 2.6
        assert both < limit * single, (both, single, limit)


# ---------------------------------------------------------------------
# the async seam + the supervised worker

class TestVerifyAsync:
    def test_verify_async_matches_verify(self):
        privs = [ed25519.gen_priv_key() for _ in range(6)]
        bv = ed25519.CpuBatchVerifier()
        for i, p in enumerate(privs):
            sig = p.sign(b"a%d" % i)
            if i == 3:
                sig = bytes([sig[0] ^ 1]) + sig[1:]
            bv.add(p.pub_key(), b"a%d" % i, sig)

        async def go():
            return await bv.verify_async()

        ok, mask = asyncio.run(go())
        assert not ok
        assert mask == [True, True, True, False, True, True]

    def test_traced_wrapper_keeps_async_seam(self):
        from cometbft_tpu.crypto import batch as crypto_batch
        p = ed25519.gen_priv_key()
        bv = crypto_batch.create_batch_verifier(p.pub_key())
        bv.add(p.pub_key(), b"w0", p.sign(b"w0"))
        bv.add(p.pub_key(), b"w1", p.sign(b"w1"))

        async def go():
            return await bv.verify_async()

        ok, mask = asyncio.run(go())
        assert ok and list(mask) == [True, True]

    def test_loop_stays_responsive_during_verify_async(self):
        """The event-loop-stall contract at test scale: a ticker's
        max gap while a 2k batch verifies off-loop must be far below
        the batch's own duration."""
        sk = ed25519.gen_priv_key()
        pkb = sk.pub_key()
        bv = ed25519.CpuBatchVerifier()
        for i in range(2000):
            m = b"stall-%04d" % i
            bv.add(pkb, m, sk.sign(m))

        async def go():
            t0 = time.perf_counter()
            ok, _ = bv.verify()              # sync: measures duration
            sync_s = time.perf_counter() - t0
            assert ok
            max_gap = 0.0
            done = asyncio.Event()

            async def ticker():
                nonlocal max_gap
                last = time.perf_counter()
                while not done.is_set():
                    await asyncio.sleep(0.001)
                    now = time.perf_counter()
                    max_gap = max(max_gap, now - last)
                    last = now

            t = asyncio.ensure_future(ticker())
            await asyncio.sleep(0.02)
            max_gap = 0.0
            ok, _ = await bv.verify_async()
            done.set()
            await t
            assert ok
            return sync_s, max_gap

        sync_s, gap = asyncio.run(go())
        assert gap < max(0.5 * sync_s, 0.02), (sync_s, gap)

    def test_preverify_signatures_async_fills_memo(self):
        from cometbft_tpu.types import vote as vote_mod
        privs = [ed25519.gen_priv_key() for _ in range(4)]
        entries = [(p.pub_key(), b"pv%d" % i, p.sign(b"pv%d" % i))
                   for i, p in enumerate(privs)]
        vote_mod._VERIFIED.clear()

        async def go():
            await asyncio.wrap_future(
                vote_mod.preverify_signatures_async(entries))

        asyncio.run(go())
        for pub, msg, sig in entries:
            assert vote_mod._memo_key(pub, msg, sig) in \
                vote_mod._VERIFIED


class TestSupervisedWorker:
    def test_submit_result_and_metrics(self):
        from cometbft_tpu.libs import metrics as libmetrics
        reg = libmetrics.Registry()
        w = SupervisedWorker("t_basic", registry=reg)
        try:
            assert w.submit(lambda a, b: a + b, 2, 3).result(5) == 5
            # queue-wait histogram observed at least once
            fam = reg.histogram(
                "crypto", "verify_queue_wait_seconds", "",
                labels=("worker",), buckets=(0.001, 1.0))
            assert fam.with_labels("t_basic")._count >= 1
        finally:
            w.stop()

    def test_exception_captured_and_worker_survives(self):
        w = SupervisedWorker("t_crash")
        try:
            fut = w.submit(lambda: 1 / 0)
            with pytest.raises(ZeroDivisionError):
                fut.result(5)
            # the worker thread survived the crash
            assert w.submit(lambda: 41 + 1).result(5) == 42
        finally:
            w.stop()

    def test_stop_drains_queued_tasks(self):
        w = SupervisedWorker("t_drain")
        futs = [w.submit(time.sleep, 0.01) for _ in range(3)]
        last = w.submit(lambda: "done")
        w.stop()
        assert last.result(5) == "done"
        for f in futs:
            assert f.done()
        with pytest.raises(RuntimeError):
            w.submit(lambda: None)

    def test_depth_gauge_returns_to_zero(self):
        w = SupervisedWorker("t_depth")
        try:
            w.submit(time.sleep, 0.02).result(5)
            deadline = time.time() + 2
            while w.depth() and time.time() < deadline:
                time.sleep(0.005)
            assert w.depth() == 0
        finally:
            w.stop()


@pytest.mark.slow
class TestPipelinePartitioner:
    def test_sharded_pipeline_parity_forced_devices(self):
        """4 forced host devices: verify_sharded (now routed through
        the once-per-pipeline PipelinePartitioner) and the tiled JAX
        pipeline must produce exact masks.  Subprocess because
        XLA_FLAGS must be set before jax initializes."""
        import subprocess
        import sys
        code = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["COMETBFT_TPU_SHARD_MIN"] = "32"
os.environ["COMETBFT_TPU_VERIFY_TILE"] = "64"
import secrets
from cometbft_tpu.crypto import _ed25519_ref as ref
from cometbft_tpu.ops import ed25519_jax as ej
from cometbft_tpu.parallel import mesh as pmesh
import jax
assert len(jax.devices()) == 4, jax.devices()
items = []
for i in range(130):
    seed = bytes([i]) + secrets.token_bytes(31)
    m = b"shard-%03d" % i
    items.append((ref.public_key(seed), m, ref.sign(seed, m)))
pub, m, s = items[65]
items[65] = (pub, m, s[:6] + bytes([s[6] ^ 1]) + s[7:])
a_b, r_b, s_w8, k_w8, pre_bad = ej.prep_arrays(items, 130)
ok = pmesh.verify_sharded(a_b, r_b, s_w8, k_w8, ndev=4)
assert not ok[65] and ok[:65].all() and ok[66:].all()
ok2, mask = ej.verify_batch(items)       # tiled pipeline, sharded
assert not ok2 and mask.count(False) == 1 and not mask[65]
print("PARITY_OK")
"""
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            " --xla_force_host_platform_device_count"
                            "=4").strip()
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=900, env=env)
        assert proc.returncode == 0, proc.stderr[-3000:]
        assert "PARITY_OK" in proc.stdout


# ---------------------------------------------------------------------
# committed perf-claim gates (static checks on the baseline, the
# test_lightserve pattern: the live regression gate is perf_lab
# `check --fast`; the CLAIM is pinned against the committed numbers)

class TestCommittedClaims:
    @pytest.fixture(scope="class")
    def baseline(self):
        import json
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "perf_baseline.json")
        with open(path) as f:
            return json.load(f)["benchmarks"]

    def test_pipelined_dispatch_claim(self, baseline):
        b = baseline["ed25519_pipelined_dispatch"]
        assert b["monolithic_min_ms"] / b["min_ms"] >= 1.25, b
        # the host_prep/kernel_execute split was live during the
        # committed measurement (both phases observed)
        assert b["host_prep_ms"] > 0 and b["kernel_execute_ms"] > 0

    def test_event_loop_stall_claim(self, baseline):
        b = baseline["verify_event_loop_stall"]
        assert b["sync_stall_ms"] / b["min_ms"] >= 5.0, b
