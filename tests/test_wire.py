"""Wire codec tests, pinned to the reference's own sign-bytes test vectors.

Golden vectors from types/vote_test.go TestVoteSignBytesTestVectors and
the CanonicalVoteExtension schema.
"""
import pytest

from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.vote import Vote
from cometbft_tpu.types import canonical
from cometbft_tpu.wire import pb, encode, decode, marshal_delimited


ZERO_TS = Timestamp.zero()


def _vote_sign_bytes(chain_id, **kw):
    v = Vote(**kw)
    return v.sign_bytes(chain_id)


class TestVoteSignBytesGoldenVectors:
    """Byte-exact vectors from reference types/vote_test.go:67-165."""

    def test_empty_vote(self):
        want = bytes([0xd, 0x2a, 0xb, 0x8, 0x80, 0x92, 0xb8, 0xc3, 0x98,
                      0xfe, 0xff, 0xff, 0xff, 0x1])
        assert _vote_sign_bytes("") == want

    def test_precommit(self):
        want = bytes([
            0x21,
            0x8, 0x2,
            0x11, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0,
            0x19, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0,
            0x2a, 0xb, 0x8, 0x80, 0x92, 0xb8, 0xc3, 0x98, 0xfe, 0xff,
            0xff, 0xff, 0x1,
        ])
        assert _vote_sign_bytes(
            "", height=1, round=1,
            type=canonical.PRECOMMIT_TYPE) == want

    def test_prevote(self):
        want = bytes([
            0x21,
            0x8, 0x1,
            0x11, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0,
            0x19, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0,
            0x2a, 0xb, 0x8, 0x80, 0x92, 0xb8, 0xc3, 0x98, 0xfe, 0xff,
            0xff, 0xff, 0x1,
        ])
        assert _vote_sign_bytes("", height=1, round=1,
                                type=canonical.PREVOTE_TYPE) == want

    def test_no_type(self):
        want = bytes([
            0x1f,
            0x11, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0,
            0x19, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0,
            0x2a, 0xb, 0x8, 0x80, 0x92, 0xb8, 0xc3, 0x98, 0xfe, 0xff,
            0xff, 0xff, 0x1,
        ])
        assert _vote_sign_bytes("", height=1, round=1) == want

    def test_with_chain_id(self):
        want = bytes([
            0x2e,
            0x11, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0,
            0x19, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0,
            0x2a, 0xb, 0x8, 0x80, 0x92, 0xb8, 0xc3, 0x98, 0xfe, 0xff,
            0xff, 0xff, 0x1,
            0x32, 0xd, 0x74, 0x65, 0x73, 0x74, 0x5f, 0x63, 0x68, 0x61,
            0x69, 0x6e, 0x5f, 0x69, 0x64,
        ])
        assert _vote_sign_bytes("test_chain_id", height=1, round=1) == want

    def test_extension_not_in_vote_sign_bytes(self):
        # vector 5: extension does not change vote sign-bytes
        a = _vote_sign_bytes("test_chain_id", height=1, round=1)
        b = _vote_sign_bytes("test_chain_id", height=1, round=1,
                             extension=b"extension")
        assert a == b


class TestRoundTrip:
    def test_vote_roundtrip(self):
        v = Vote(
            type=canonical.PRECOMMIT_TYPE, height=12345, round=2,
            block_id=BlockID(hash=b"\xab" * 32,
                             part_set_header=PartSetHeader(3, b"\xcd" * 32)),
            timestamp=Timestamp(1700000000, 123456789),
            validator_address=b"\x11" * 20, validator_index=7,
            signature=b"\x22" * 64, extension=b"ext",
            extension_signature=b"\x33" * 64,
        )
        raw = encode(pb.VOTE, v.to_proto())
        v2 = Vote.from_proto(decode(pb.VOTE, raw))
        assert v == v2

    def test_negative_int_roundtrip(self):
        d = {"pol_round": -1, "type": 32,
             "timestamp": ZERO_TS.to_proto()}
        raw = encode(pb.CANONICAL_PROPOSAL, d)
        back = decode(pb.CANONICAL_PROPOSAL, raw)
        assert back["pol_round"] == -1

    def test_unknown_field_skipped(self):
        # encode a Vote, decode as CommitSig-shaped desc missing most fields
        v = Vote(type=1, height=5, round=0, timestamp=ZERO_TS,
                 validator_address=b"\x01" * 20, signature=b"\x02" * 64)
        raw = encode(pb.VOTE, v.to_proto())
        got = decode(pb.COMMIT_SIG, raw)  # overlapping field numbers differ
        assert isinstance(got, dict)

    def test_timestamp_zero_value(self):
        assert Timestamp.zero().to_proto() == {"seconds": -62135596800}
        assert encode(pb.TIMESTAMP, Timestamp.zero().to_proto()) == bytes(
            [0x8, 0x80, 0x92, 0xb8, 0xc3, 0x98, 0xfe, 0xff, 0xff, 0xff,
             0x1])


class TestTimestamp:
    def test_rfc3339(self):
        ts = Timestamp(1700000000, 500000000)
        assert ts.rfc3339() == "2023-11-14T22:13:20.5Z"
        assert Timestamp.from_rfc3339(ts.rfc3339()) == ts

    def test_rfc3339_no_frac(self):
        ts = Timestamp(1700000000, 0)
        assert ts.rfc3339() == "2023-11-14T22:13:20Z"
        assert Timestamp.from_rfc3339(ts.rfc3339()) == ts


class TestVoteExtensionSignBytes:
    def test_shape(self):
        b = canonical.vote_extension_sign_bytes("chain", 3, 1, b"ext")
        # length-prefixed; decodable
        from cometbft_tpu.wire import unmarshal_delimited
        d, n = unmarshal_delimited(pb.CANONICAL_VOTE_EXTENSION, b)
        assert n == len(b)
        assert d == {"extension": b"ext", "height": 3, "round": 1,
                     "chain_id": "chain"}
