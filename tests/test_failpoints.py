"""Crash-consistency: iterate EVERY fail-point index through the commit
path, hard-crash a real node process there, restart, and require full
recovery.

Reference: internal/fail/fail.go:28 + the fail.Fail() crash points in
internal/consensus/state.go:1872-1941 and state/execution.go:267-322;
the replay tests iterate all indices the same way.
"""
import os
import subprocess
import pytest
import sys
import tempfile

_DRIVER = os.path.join(os.path.dirname(__file__), "crash_driver.py")

# 7 fail() calls fire per committed height: 4 in consensus/state.py
# _finalize_commit + 3 in state/execution.py _apply_block (order: 0 before
# block save, 1 before WAL barrier, 2 after barrier, 3 before response
# save, 4 after response save, 5 after app commit, 6 before
# update_to_state).
N_FAIL_POINTS = 7


def _run(home: str, target: int, fail_index: int = -1,
         timeout: int = 60) -> int:
    env = {**os.environ, "JAX_PLATFORMS": ""}
    if fail_index >= 0:
        env["FAIL_TEST_INDEX"] = str(fail_index)
    else:
        env.pop("FAIL_TEST_INDEX", None)
    p = subprocess.run(
        [sys.executable, _DRIVER, home, str(target)],
        env=env, timeout=timeout, capture_output=True, text=True)
    return p.returncode


class TestCrashConsistency:
    @pytest.mark.slow
    def test_recovery_at_every_commit_boundary(self):
        """For each index i: crash a node mid-commit at boundary i (the
        crash is index i of height 2's commit because height 1 commits
        before the WAL has settled... indices count from process start),
        then restart and require the chain to keep committing."""
        for i in range(N_FAIL_POINTS):
            with tempfile.TemporaryDirectory() as d:
                home = os.path.join(d, "node")
                rc = _run(home, target=50, fail_index=i)
                assert rc == 99, \
                    f"fail point {i} did not fire (rc={rc})"
                rc = _run(home, target=5)
                assert rc == 0, f"recovery after crash at {i} failed"

    @pytest.mark.slow
    def test_crash_at_later_height_boundaries(self):
        """Crash during the 3rd height's commit (index 2 heights in) and
        recover — catches bugs that only appear once LastCommit exists."""
        for boundary in (0, 2, 5, 6):
            i = 2 * N_FAIL_POINTS + boundary
            with tempfile.TemporaryDirectory() as d:
                home = os.path.join(d, "node")
                rc = _run(home, target=50, fail_index=i)
                assert rc == 99, \
                    f"fail point {i} did not fire (rc={rc})"
                rc = _run(home, target=6)
                assert rc == 0, f"recovery after crash at {i} failed"

    def test_no_failpoint_runs_clean(self):
        with tempfile.TemporaryDirectory() as d:
            home = os.path.join(d, "node")
            assert _run(home, target=3) == 0


class TestCorruptWALRecovery:
    def test_node_repairs_corrupt_wal_and_restarts(self):
        """Append garbage to the WAL tail (torn/corrupt write), restart:
        the node truncates the corrupt tail, keeps a forensics copy, and
        keeps committing (reference: state.go OnStart repair retry)."""
        import glob

        with tempfile.TemporaryDirectory() as d:
            home = os.path.join(d, "node")
            assert _run(home, target=5) == 0
            wal = os.path.join(home, "data", "cs.wal", "wal")
            if not os.path.exists(wal):
                cands = glob.glob(os.path.join(home, "data", "**",
                                               "wal*"),
                                  recursive=True)
                assert cands, "no WAL file found"
                wal = cands[0]
            with open(wal, "r+b") as f:
                f.seek(0, 2)
                size = f.tell()
                # corrupt the last frame's payload bytes
                f.seek(max(0, size - 20))
                f.write(b"\xde\xad\xbe\xef" * 5)
            assert _run(home, target=8) == 0, \
                "node failed to recover from corrupt WAL"
            assert os.path.exists(wal + ".corrupted")
