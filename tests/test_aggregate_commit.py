"""Aggregate-signature commits (docs/aggregate_commits.md): O(1) BLS
commit verification — the differential forgery matrix (aggregate and
per-signature verdicts must agree), wire/store roundtrips, the
aggregate-pubkey + verdict caches, light-client skipping parity with
the ed25519 path, and the batch-reject bisection fallback.
"""
import asyncio
import copy

import pytest

from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.crypto import bls12381 as bls
from cometbft_tpu.crypto import ed25519
from cometbft_tpu.db.db import MemDB
from cometbft_tpu.libs.bits import BitArray
from cometbft_tpu.light.client import Client, TrustOptions
from cometbft_tpu.light.store import TrustedStore
from cometbft_tpu.store.store import BlockStore
from cometbft_tpu.types import canonical, validation
from cometbft_tpu.types.block import (
    Block, Header, LightBlock, SignedHeader,
)
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.commit import (
    AggregateCommit, Commit, CommitError, CommitSig,
)
from cometbft_tpu.types.params import (
    ConsensusParams, FeatureParams, ParamsError, ValidatorParams,
)
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.signature_cache import SignatureCache
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validator_set import Validator, ValidatorSet
from cometbft_tpu.types.vote import BLOCK_ID_FLAG_COMMIT, Vote
from cometbft_tpu.types.vote_set import VoteSet
from cometbft_tpu.version import BLOCK_PROTOCOL
from cometbft_tpu.wire import pb, decode, encode

CHAIN_ID = "agg-chain"
T0 = 1_700_000_000
HOUR_NS = 3600 * 10**9


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


def _bls_keys(n, tag=b"k"):
    return [bls.gen_priv_key_from_secret(
        bytes([i % 256, i // 256]) + tag + b"\0" * (30 - len(tag)))
        for i in range(n)]


def _valset(sks) -> ValidatorSet:
    return ValidatorSet([
        Validator(address=sk.pub_key().address(),
                  pub_key=sk.pub_key(), voting_power=10)
        for sk in sks])


def _bid(tag: bytes = b"B") -> BlockID:
    return BlockID(hash=tag * 32,
                   part_set_header=PartSetHeader(1, b"P" * 32))


def _sign_bytes(height, round_, bid):
    return canonical.vote_sign_bytes(
        CHAIN_ID, canonical.PRECOMMIT_TYPE, height, round_, bid,
        Timestamp.zero())


def _aggregate_commit(sks, vals, height, round_, bid,
                      skip=()) -> AggregateCommit:
    """Build a valid aggregate from all validators except ``skip``
    (validator-set order, which may differ from key order)."""
    sb = _sign_bytes(height, round_, bid)
    by_addr = {sk.pub_key().address(): sk for sk in sks}
    signers = BitArray(vals.size())
    sigs = []
    for i, v in enumerate(vals.validators):
        if i in skip:
            continue
        signers.set_index(i, True)
        sigs.append(by_addr[v.address].sign(sb))
    return AggregateCommit(height=height, round=round_, block_id=bid,
                           signers=signers,
                           signature=bls.aggregate(sigs))


def _per_sig_commit(sks, vals, height, round_, bid,
                    skip=()) -> Commit:
    """The SAME signatures as the aggregate, in per-signature form
    (zero timestamps — what aggregate-mode validators actually sign),
    for differential verdict checks."""
    sb = _sign_bytes(height, round_, bid)
    by_addr = {sk.pub_key().address(): sk for sk in sks}
    sigs = []
    for i, v in enumerate(vals.validators):
        if i in skip:
            sigs.append(CommitSig.absent())
            continue
        sigs.append(CommitSig(block_id_flag=BLOCK_ID_FLAG_COMMIT,
                              validator_address=v.address,
                              timestamp=Timestamp.zero(),
                              signature=by_addr[v.address].sign(sb)))
    return Commit(height=height, round=round_, block_id=bid,
                  signatures=sigs)


def _verdict(fn, *args, **kw):
    """'ok' or the exception class name — the unit of differential
    comparison."""
    try:
        fn(*args, **kw)
        return "ok"
    except validation.NotEnoughVotingPowerError:
        return "power"
    except validation.VerificationError:
        return "invalid"


class TestForgeryMatrix:
    """Aggregate and serial per-signature verdicts must agree on every
    row of the forgery matrix (ISSUE 13 acceptance)."""

    def setup_method(self):
        self.sks = _bls_keys(7)
        self.vals = _valset(self.sks)
        self.bid = _bid()
        self.h = 5

    def _both(self, skip=(), mutate_agg=None, mutate_commit=None):
        agg = _aggregate_commit(self.sks, self.vals, self.h, 0,
                                self.bid, skip=skip)
        per = _per_sig_commit(self.sks, self.vals, self.h, 0,
                              self.bid, skip=skip)
        if mutate_agg:
            mutate_agg(agg)
        if mutate_commit:
            mutate_commit(per)
        va = _verdict(validation.verify_commit, CHAIN_ID, self.vals,
                      self.bid, self.h, agg)
        vp = _verdict(validation.verify_commit, CHAIN_ID, self.vals,
                      self.bid, self.h, per)
        return va, vp

    def test_honest_full_commit_agrees(self):
        assert self._both() == ("ok", "ok")

    def test_one_absent_still_quorum(self):
        assert self._both(skip=(3,)) == ("ok", "ok")

    def test_sub_quorum_bitmap_rejected_both(self):
        # 4 of 7 at equal power is 40 <= 46 (2/3 of 70): not enough
        va, vp = self._both(skip=(0, 1, 2))
        assert va == vp == "power"

    def test_non_signer_bit_set_rejected(self):
        # bitmap claims validator 3 signed, but its signature is not
        # in the aggregate: the per-sig analogue is a COMMIT flag with
        # the wrong (missing -> forged) signature
        def add_bit(agg):
            agg.signers.set_index(3, True)
        agg = _aggregate_commit(self.sks, self.vals, self.h, 0,
                                self.bid, skip=(3,))
        add_bit(agg)
        assert _verdict(validation.verify_commit, CHAIN_ID, self.vals,
                        self.bid, self.h, agg) == "invalid"

    def test_out_of_range_bitmap_bit_rejected(self):
        agg = _aggregate_commit(self.sks, self.vals, self.h, 0,
                                self.bid)
        wide = BitArray(self.vals.size() + 2)
        for i in agg.signers.true_indices():
            wide.set_index(i, True)
        wide.set_index(self.vals.size() + 1, True)
        agg.signers = wide
        # size mismatch against the valset is structural
        assert _verdict(validation.verify_commit, CHAIN_ID, self.vals,
                        self.bid, self.h, agg) == "invalid"

    def test_duplicate_bits_impossible_on_wire(self):
        """The wire form cannot express duplicate signer bits (one bit
        per index), and non-canonical padding bits are rejected at
        decode — the aggregate analogue of double-vote detection."""
        agg = _aggregate_commit(self.sks, self.vals, self.h, 0,
                                self.bid)
        d = agg.to_proto()
        raw = bytearray(d["signers"])
        raw[0] |= 0x80  # bit 7 of a 7-validator bitmap = padding
        d["signers"] = bytes(raw)
        with pytest.raises(CommitError, match="padding"):
            AggregateCommit.from_proto(d)
        d2 = agg.to_proto()
        d2["signers"] = d2["signers"] + b"\x00"
        with pytest.raises(CommitError, match="length"):
            AggregateCommit.from_proto(d2)

    def test_wrong_key_aggregate_rejected_both(self):
        other = _bls_keys(7, tag=b"x")
        bad_agg = _aggregate_commit(other, _valset(other), self.h, 0,
                                    self.bid)
        # graft the foreign aggregate signature onto our bitmap
        def swap(agg):
            agg.signature = bad_agg.signature
        va, _ = self._both(mutate_agg=swap)
        assert va == "invalid"
        # per-sig analogue: one foreign signature
        sb = _sign_bytes(self.h, 0, self.bid)
        def swap_sig(per):
            per.signatures[2] = CommitSig(
                block_id_flag=BLOCK_ID_FLAG_COMMIT,
                validator_address=per.signatures[2].validator_address,
                timestamp=Timestamp.zero(),
                signature=other[0].sign(sb))
        _, vp = self._both(mutate_commit=swap_sig)
        assert vp == "invalid"

    def test_wrong_block_id_rejected_both(self):
        other_bid = _bid(b"C")
        agg = _aggregate_commit(self.sks, self.vals, self.h, 0,
                                other_bid)
        per = _per_sig_commit(self.sks, self.vals, self.h, 0,
                              other_bid)
        va = _verdict(validation.verify_commit, CHAIN_ID, self.vals,
                      self.bid, self.h, agg)
        vp = _verdict(validation.verify_commit, CHAIN_ID, self.vals,
                      self.bid, self.h, per)
        assert va == vp == "invalid"

    def test_nil_vote_exclusion(self):
        """A nil precommit signs a DIFFERENT canonical message (block
        id omitted): summing it into the aggregate must fail even with
        its bit set — nil voters can only be excluded."""
        sb_nil = canonical.vote_sign_bytes(
            CHAIN_ID, canonical.PRECOMMIT_TYPE, self.h, 0, BlockID(),
            Timestamp.zero())
        by_addr = {sk.pub_key().address(): sk for sk in self.sks}
        signers = BitArray(self.vals.size())
        sigs = []
        sb = _sign_bytes(self.h, 0, self.bid)
        for i, v in enumerate(self.vals.validators):
            signers.set_index(i, True)
            sk = by_addr[v.address]
            sigs.append(sk.sign(sb_nil if i == 2 else sb))
        agg = AggregateCommit(height=self.h, round=0,
                              block_id=self.bid, signers=signers,
                              signature=bls.aggregate(sigs))
        assert _verdict(validation.verify_commit, CHAIN_ID, self.vals,
                        self.bid, self.h, agg) == "invalid"
        # excluded (bit unset, signature not summed): fine
        assert self._both(skip=(2,)) == ("ok", "ok")

    def test_rogue_key_substitution_caught_by_valset_hash(self):
        """Rogue-key-style pubkey substitution: an attacker crafts a
        substitute valset whose KEY SUM matches (pk_a' = pk_a + D,
        pk_b' = pk_b - D), so the pairing equation still holds — the
        defense is that the signer set is BOUND by valset hash: the
        forged set hashes differently, headers commit validators_hash,
        and the aggregate-pubkey cache keys on (valset_hash, bitmap),
        so the forged set can neither pass header checks nor poison
        the cache."""
        from cometbft_tpu.crypto import _bls12381_math as m
        agg = _aggregate_commit(self.sks, self.vals, self.h, 0,
                                self.bid)
        d_sk = 12345
        delta = m.pt_mul(m.G1_OPS, m.G1_GEN, d_sk)
        pk_a = self.vals.validators[0].pub_key.point()
        pk_b = self.vals.validators[1].pub_key.point()
        rogue_a = bls.Bls12381PubKey(m.g1_serialize(
            m.pt_add(m.G1_OPS, pk_a, delta)))
        rogue_b = bls.Bls12381PubKey(m.g1_serialize(
            m.pt_add(m.G1_OPS, pk_b, m.pt_neg(m.G1_OPS, delta))))
        forged = [Validator(address=v.address, pub_key=v.pub_key,
                            voting_power=v.voting_power)
                  for v in self.vals.validators]
        forged[0] = Validator(address=rogue_a.address(),
                              pub_key=rogue_a, voting_power=10)
        forged[1] = Validator(address=rogue_b.address(),
                              pub_key=rogue_b, voting_power=10)
        forged_vals = ValidatorSet(forged)
        # the pairing itself passes against the forged set (this is
        # exactly why the valset must be hash-bound)...
        validation.verify_commit(CHAIN_ID, forged_vals, self.bid,
                                 self.h, copy.deepcopy(agg))
        # ...but the binding holds: the forged set has a different
        # hash, so no header/light-client path will accept it
        assert forged_vals.hash() != self.vals.hash()

    def test_trusting_rogue_cancellation_key_rejected(self):
        """The skipping-hop forgery the trusting path must kill: the
        signer set rides the UNTRUSTED header (self-certified by its
        own validators_hash), so an attacker can fabricate one whose
        bitmap covers real trusted addresses for the power tally
        while a rogue key pk_r = [x]g1 - sum(trusted keys) cancels
        them in the pubkey sum — the set sums to [x]g1 and the
        attacker signs alone with x.  Sound verification resolves
        every signer's KEY from the trusted set by address; the rogue
        signer's address is unknown there, so the hop reports zero
        provable power (NotEnoughVotingPowerError -> the light client
        bisects) instead of accepting the forgery."""
        from cometbft_tpu.crypto import _bls12381_math as m
        x = 987654321
        trusted_pts = [v.pub_key.point()
                       for v in self.vals.validators[:5]]
        rogue_pt = m.pt_mul(m.G1_OPS, m.G1_GEN, x)
        for pt in trusted_pts:
            rogue_pt = m.pt_add(m.G1_OPS, rogue_pt,
                                m.pt_neg(m.G1_OPS, pt))
        rogue_pk = bls.Bls12381PubKey(m.g1_serialize(rogue_pt))
        fabricated = ValidatorSet(
            [Validator(address=v.address, pub_key=v.pub_key,
                       voting_power=v.voting_power)
             for v in self.vals.validators[:5]] +
            [Validator(address=rogue_pk.address(), pub_key=rogue_pk,
                       voting_power=1)])
        sb = _sign_bytes(self.h, 0, self.bid)
        sig = m.g2_compress(m.pt_mul(
            m.G2_OPS, m.hash_to_g2(sb, bls.DST), x))
        agg = AggregateCommit(
            height=self.h, round=0, block_id=self.bid,
            signers=BitArray.from_indices(6, range(6)), signature=sig)
        # the bare pairing over the fabricated set really does pass —
        # this is the attack, not a malformed input
        assert bls.verify_aggregate(
            bls.aggregate_pub_keys([v.pub_key
                                    for v in fabricated.validators]),
            sb, sig)
        with pytest.raises(validation.NotEnoughVotingPowerError):
            validation.verify_commit_light_trusting(
                CHAIN_ID, self.vals, agg, validation.Fraction(1, 3),
                signer_vals=fabricated)

    def test_trusting_substituted_keys_rejected(self):
        """Same hop, second shape: every signer address IS trusted but
        the fabricated set claims different KEYS for them (two keys
        shifted by +/-D so their sum — and the bare pairing — still
        matches).  The trusting path must verify against the TRUSTED
        set's keys for those addresses, which the real signatures do
        satisfy but a signature under the shifted keys does not."""
        from cometbft_tpu.crypto import _bls12381_math as m
        d_sk = 4242
        delta = m.pt_mul(m.G1_OPS, m.G1_GEN, d_sk)
        sub = [Validator(address=v.address, pub_key=v.pub_key,
                         voting_power=v.voting_power)
               for v in self.vals.validators]
        pk_a = bls.Bls12381PubKey(m.g1_serialize(m.pt_add(
            m.G1_OPS, sub[0].pub_key.point(), delta)))
        pk_b = bls.Bls12381PubKey(m.g1_serialize(m.pt_add(
            m.G1_OPS, sub[1].pub_key.point(),
            m.pt_neg(m.G1_OPS, delta))))
        sub[0] = Validator(address=sub[0].address, pub_key=pk_a,
                           voting_power=10)
        sub[1] = Validator(address=sub[1].address, pub_key=pk_b,
                           voting_power=10)
        fabricated = ValidatorSet(sub)
        agg = _aggregate_commit(self.sks, self.vals, self.h, 0,
                                self.bid)
        # honest aggregate, honest addresses, shifted claimed keys:
        # resolution by address from the TRUSTED set makes the claimed
        # keys irrelevant — verification still passes...
        validation.verify_commit_light_trusting(
            CHAIN_ID, self.vals, agg, validation.Fraction(1, 3),
            signer_vals=fabricated)
        # ...and a signature valid only under the shifted key sum
        # (attacker knows neither real secret) cannot exist; simulate
        # the closest forgery — reusing the honest signature after
        # swapping ONE real signer's contribution for the shifted
        # keys' — by checking a wrong-message signature still fails
        bad = copy.deepcopy(agg)
        bad.signature = bls.aggregate(
            [sk.sign(_sign_bytes(self.h, 1, self.bid))
             for sk in self.sks])
        with pytest.raises(validation.VerificationError):
            validation.verify_commit_light_trusting(
                CHAIN_ID, self.vals, bad, validation.Fraction(1, 3),
                signer_vals=fabricated)

    def test_trusting_unknown_signer_bisects_not_fatal(self):
        """Honest rotation: a genuinely valid aggregate whose signer
        set contains a validator the light client does not trust yet.
        Its key cannot be authenticated on this hop, so the verdict
        must be the BISECT signal (NotEnoughVotingPowerError), never
        acceptance and never the fatal InvalidHeaderError shape."""
        new_sks = self.sks + _bls_keys(1, tag=b"new")
        new_vals = _valset(new_sks)
        agg = _aggregate_commit(new_sks, new_vals, self.h, 0,
                                self.bid)
        validation.verify_commit_light(CHAIN_ID, new_vals, self.bid,
                                       self.h, copy.deepcopy(agg))
        with pytest.raises(validation.NotEnoughVotingPowerError):
            validation.verify_commit_light_trusting(
                CHAIN_ID, self.vals, agg, validation.Fraction(1, 3),
                signer_vals=new_vals)

    def test_light_and_trusting_variants_agree(self):
        agg = _aggregate_commit(self.sks, self.vals, self.h, 0,
                                self.bid)
        validation.verify_commit_light(CHAIN_ID, self.vals, self.bid,
                                       self.h, agg)
        validation.verify_commit_light_trusting(
            CHAIN_ID, self.vals, agg, validation.Fraction(1, 3),
            signer_vals=self.vals)
        with pytest.raises(validation.VerificationError,
                           match="signing validator set"):
            validation.verify_commit_light_trusting(
                CHAIN_ID, self.vals, agg, validation.Fraction(1, 3))

    def test_verdict_memo_skips_pairing(self, monkeypatch):
        agg = _aggregate_commit(self.sks, self.vals, self.h, 0,
                                self.bid)
        cache = SignatureCache()
        validation.verify_commit(CHAIN_ID, self.vals, self.bid,
                                 self.h, agg, cache=cache)
        calls = []
        orig = bls.verify_aggregate
        monkeypatch.setattr(bls, "verify_aggregate",
                            lambda *a: calls.append(1) or orig(*a))
        validation.verify_commit(CHAIN_ID, self.vals, self.bid,
                                 self.h, agg, cache=cache)
        assert calls == []   # memo hit: no pairing at all

    def test_agg_pubkey_cache_skips_point_sum(self, monkeypatch):
        agg = _aggregate_commit(self.sks, self.vals, self.h, 0,
                                self.bid)
        validation.verify_commit(CHAIN_ID, self.vals, self.bid,
                                 self.h, agg)
        calls = []
        orig = bls.aggregate_pub_keys_raw
        monkeypatch.setattr(
            bls, "aggregate_pub_keys_raw",
            lambda blob: calls.append(1) or orig(blob))
        # no verdict cache -> the pairing runs, but the G1 sum is
        # served by the aggregate-pubkey cache
        validation.verify_commit(CHAIN_ID, self.vals, self.bid,
                                 self.h, agg)
        assert calls == []


class TestPeerRefusalActivation:
    """aggcommit/1 refusal keys on REACHING the enable height, not on
    the param merely being set: a far-future enable height (scheduled
    by param update) must not partition old-build peers that can
    still parse every existing block (docs/gossip.md)."""

    @staticmethod
    def _reactor_at(last_block_height, enable_height):
        from types import SimpleNamespace
        from cometbft_tpu.consensus.reactor import ConsensusReactor
        sm = SimpleNamespace(
            last_block_height=last_block_height,
            consensus_params=SimpleNamespace(feature=SimpleNamespace(
                aggregate_commit_enable_height=enable_height)))
        fake = SimpleNamespace(cs=SimpleNamespace(sm_state=sm))
        return ConsensusReactor._chain_uses_aggregate_commits(fake)

    def test_inactive_before_enable_height(self):
        assert self._reactor_at(10, 500_000) is False
        assert self._reactor_at(0, 0) is False      # ed25519 chain
        assert self._reactor_at(10**6, 0) is False  # never enabled

    def test_active_at_and_past_enable_height(self):
        # next height == enable height: the very next commit
        # aggregates, so a new peer must be capable
        assert self._reactor_at(99, 100) is True
        assert self._reactor_at(100, 100) is True
        assert self._reactor_at(10**6, 100) is True
        # genesis-enabled chain is active from the start
        assert self._reactor_at(0, 1) is True


class TestWireAndStore:
    def setup_method(self):
        self.sks = _bls_keys(4)
        self.vals = _valset(self.sks)

    def _agg(self, h=3):
        return _aggregate_commit(self.sks, self.vals, h, 0, _bid())

    def test_proto_roundtrip(self):
        agg = self._agg()
        d = decode(pb.AGGREGATE_COMMIT,
                   encode(pb.AGGREGATE_COMMIT, agg.to_proto()))
        agg2 = AggregateCommit.from_proto(d)
        assert agg2 == agg and agg2.hash() == agg.hash()

    def test_block_roundtrip_and_kind_exclusivity(self):
        from cometbft_tpu.types.block import Data
        agg = self._agg(h=2)
        blk = Block(header=Header(chain_id=CHAIN_ID, height=3,
                                  time=Timestamp(T0, 0),
                                  proposer_address=b"\x01" * 20),
                    data=Data(txs=[b"tx1"]), last_commit=agg)
        blk.fill_header()
        raw = encode(pb.BLOCK, blk.to_proto())
        blk2 = Block.from_proto(decode(pb.BLOCK, raw))
        assert isinstance(blk2.last_commit, AggregateCommit)
        assert blk2.last_commit == agg
        blk2.validate_basic()
        d = blk.to_proto()
        d["last_commit"] = Commit(height=2, block_id=_bid(),
                                  signatures=[CommitSig.absent()]
                                  ).to_proto()
        from cometbft_tpu.types.block import BlockError
        with pytest.raises(BlockError, match="both"):
            Block.from_proto(d)

    def test_ed25519_wire_unchanged(self):
        """A per-signature block encodes byte-identically with the
        aggregate arms in the schema (old peers see the old bytes)."""
        from cometbft_tpu.types.block import Data
        per = Commit(height=2, round=0, block_id=_bid(),
                     signatures=[CommitSig.absent()])
        blk = Block(header=Header(chain_id=CHAIN_ID, height=3,
                                  time=Timestamp(T0, 0)),
                    data=Data(txs=[]), last_commit=per)
        blk.fill_header()
        d = blk.to_proto()
        assert "last_aggregate_commit" not in d
        raw = encode(pb.BLOCK, d)
        # field 5 (the aggregate arm) never appears in the bytes
        assert b"\x2a" != raw[:1]
        blk2 = Block.from_proto(decode(pb.BLOCK, raw))
        assert isinstance(blk2.last_commit, Commit)

    def test_signed_header_roundtrip(self):
        agg = self._agg()
        hdr = Header(chain_id=CHAIN_ID, height=3,
                     time=Timestamp(T0, 0))
        sh = SignedHeader(header=hdr, commit=agg)
        raw = encode(pb.SIGNED_HEADER, sh.to_proto())
        sh2 = SignedHeader.from_proto(decode(pb.SIGNED_HEADER, raw))
        assert isinstance(sh2.commit, AggregateCommit)
        assert sh2.commit == agg

    def test_store_seen_commit_roundtrip(self):
        store = BlockStore(MemDB())
        agg = self._agg(h=7)
        store.save_seen_commit_standalone(agg)
        loaded = store.load_seen_commit(7)
        assert isinstance(loaded, AggregateCommit) and loaded == agg

    def test_feature_params_validation(self):
        with pytest.raises(ParamsError, match="PBTS"):
            ConsensusParams(feature=FeatureParams(
                aggregate_commit_enable_height=1)).validate_basic()
        # an ed25519 key type with aggregates enabled would halt the
        # chain at the enable height — rejected at genesis instead
        with pytest.raises(ParamsError, match="PubKeyTypes"):
            ConsensusParams(feature=FeatureParams(
                pbts_enable_height=1,
                aggregate_commit_enable_height=1)).validate_basic()
        with pytest.raises(ParamsError, match="vote extensions"):
            ConsensusParams(feature=FeatureParams(
                pbts_enable_height=1,
                vote_extensions_enable_height=1,
                aggregate_commit_enable_height=1)).validate_basic()
        ConsensusParams(
            validator=ValidatorParams(pub_key_types=["bls12_381"]),
            feature=FeatureParams(
                pbts_enable_height=1,
                aggregate_commit_enable_height=5)).validate_basic()

    def test_params_proto_roundtrip(self):
        p = ConsensusParams(feature=FeatureParams(
            pbts_enable_height=1, aggregate_commit_enable_height=9))
        p2 = ConsensusParams.from_proto(
            decode(pb.CONSENSUS_PARAMS,
                   encode(pb.CONSENSUS_PARAMS, p.to_proto())))
        assert p2.feature.aggregate_commit_enable_height == 9

    def test_from_commit_aggregates_for_block_only(self):
        per = _per_sig_commit(self.sks, self.vals, 3, 0, _bid(),
                              skip=(1,))
        agg = AggregateCommit.from_commit(per)
        assert agg.signed_indices() == [0, 2, 3]
        validation.verify_commit(CHAIN_ID, self.vals, _bid(), 3, agg)

    def test_vote_set_from_aggregate_commit(self):
        agg = self._agg(h=3)
        vs = VoteSet.from_aggregate_commit(CHAIN_ID, agg, self.vals)
        assert vs.has_two_thirds_majority()
        assert not vs.has_two_thirds_votes_for_maj23()
        assert vs.stored_aggregate_commit is agg
        ec = vs.make_extended_commit()
        assert all(s.absent_flag() for s in ec.extended_signatures)

    def test_inject_aggregate_majority(self):
        agg = self._agg(h=3)
        vs = VoteSet(CHAIN_ID, 3, 0, canonical.PRECOMMIT_TYPE,
                     self.vals)
        assert vs.inject_aggregate_majority(agg)
        assert vs.has_two_thirds_majority()
        conflicting = copy.deepcopy(agg)
        conflicting.block_id = _bid(b"Z")
        assert not vs.inject_aggregate_majority(conflicting)
        assert vs.maj23 == agg.block_id

    def test_catchup_round_beyond_local_tracking(self):
        """The chain can decide at a round a lagging node never
        reached: ensure_round_tracked materializes the vote set so a
        verified aggregate for round 3 injects while the node still
        sits at round 0 (the restart-wedge regression)."""
        from cometbft_tpu.consensus.height_vote_set import (
            HeightVoteSet,
        )
        hvs = HeightVoteSet(CHAIN_ID, 3, self.vals)
        agg = _aggregate_commit(self.sks, self.vals, 3, 3, _bid())
        assert hvs.precommits(3) is None   # rounds 0..1 tracked
        hvs.ensure_round_tracked(agg.round)
        pc = hvs.precommits(3)
        assert pc is not None and pc.inject_aggregate_majority(agg)
        assert pc.two_thirds_majority() == (agg.block_id, True)


def _make_agg_chain(n_heights: int, pvs_by_height):
    """Synthetic aggregate-commit header chain (the BLS analogue of
    test_light_skipping.make_chain)."""
    blocks = {}
    prev_id = BlockID()
    for h in range(1, n_heights + 1):
        sks = pvs_by_height(h)
        vals = _valset(sks)
        next_vals = _valset(pvs_by_height(h + 1))
        header = Header(
            chain_id=CHAIN_ID, height=h,
            time=Timestamp(T0 + h, 0),
            last_block_id=prev_id,
            validators_hash=vals.hash(),
            next_validators_hash=next_vals.hash(),
            proposer_address=vals.validators[0].address)
        assert header.version.block == BLOCK_PROTOCOL
        bid = BlockID(hash=header.hash(),
                      part_set_header=PartSetHeader(1, b"\xAA" * 32))
        agg = _aggregate_commit(sks, vals, h, 0, bid)
        blocks[h] = LightBlock(
            signed_header=SignedHeader(header=header, commit=agg),
            validator_set=vals)
        blocks[h].validate_basic(CHAIN_ID)
        prev_id = bid
    return blocks


class TestLightSkippingParity:
    """A light client skipping-syncs an aggregate-commit chain with
    the same outcomes as the ed25519 path (ISSUE 13 acceptance)."""

    def _client(self, blocks, chain_id=CHAIN_ID):
        from test_light_skipping import DictProvider
        primary = DictProvider(blocks)
        c = Client(chain_id,
                   TrustOptions(period_ns=24 * HOUR_NS, height=1,
                                header_hash=blocks[1].hash()),
                   primary, [], TrustedStore(MemDB()))
        return c, primary

    def _now(self):
        return Timestamp(T0 + 1000, 0)

    def test_skipping_hop_verdict_parity(self):
        """Same chain shape, BLS-aggregate vs ed25519: both sync to
        the tip through a skipping hop, and both reject a tampered
        tip the same way."""
        from test_light_skipping import make_chain
        n = 8
        bls_keys = _bls_keys(4)
        agg_blocks = _make_agg_chain(n, lambda h: bls_keys)
        ed_pvs = [__import__(
            "cometbft_tpu.types.priv_validator",
            fromlist=["new_mock_pv"]).new_mock_pv()
            for _ in range(4)]
        ed_blocks = make_chain(n, lambda h: ed_pvs)

        import test_light_skipping
        for blocks, cid in ((agg_blocks, CHAIN_ID),
                            (ed_blocks, test_light_skipping.CHAIN_ID)):
            c, primary = self._client(blocks, chain_id=cid)

            async def run(c=c):
                await c.initialize(now=self._now())
                return await c.verify_to_height(n, now=self._now())

            lb = asyncio.run(run())
            assert lb.height == n
            # skipping actually skipped: not every height fetched
            assert len(set(primary.requests)) < n

    def test_tampered_aggregate_tip_rejected(self):
        from cometbft_tpu.light.verifier import LightClientError
        n = 6
        keys = _bls_keys(4)
        blocks = _make_agg_chain(n, lambda h: keys)
        # tamper: swap in a sub-quorum aggregate at the tip
        tip = blocks[n]
        vals = tip.validator_set
        bad = _aggregate_commit(
            keys, vals, n, 0, tip.signed_header.commit.block_id,
            skip=(0, 1, 2))
        blocks[n] = LightBlock(
            signed_header=SignedHeader(header=tip.signed_header.header,
                                       commit=bad),
            validator_set=vals)
        c, _ = self._client(blocks)

        async def run():
            await c.initialize(now=self._now())
            return await c.verify_to_height(n, now=self._now())

        with pytest.raises(LightClientError):
            asyncio.run(run())

    def test_valset_rotation_skipping(self):
        """Aggregate chain with per-height valset rotation: bisection
        falls back to shorter hops exactly as on ed25519 chains."""
        n = 6
        windows = [_bls_keys(4, tag=bytes([65 + w])) for w in range(3)]

        def pvs_by_height(h):
            # rotate one validator every 2 heights
            w = min((h - 1) // 2, 2)
            return windows[0][:3] + [windows[w][3]]

        blocks = _make_agg_chain(n, pvs_by_height)
        c, _ = self._client(blocks)

        async def run():
            await c.initialize(now=self._now())
            return await c.verify_to_height(n, now=self._now())

        lb = asyncio.run(run())
        assert lb.height == n


class TestBisectionFallback:
    """Satellite: batch-reject fallback bisects instead of
    re-verifying the whole group per signature."""

    def test_bls_mask_exact_multi_bad(self):
        sks = _bls_keys(9)
        bv = bls.Bls12381BatchVerifier()
        msgs = [f"m{i}".encode() for i in range(9)]
        sigs = [sk.sign(m) for sk, m in zip(sks, msgs)]
        for i in (1, 4, 8):
            sigs[i] = sks[i].sign(b"forged")
        for sk, m, s in zip(sks, msgs, sigs):
            bv.add(sk.pub_key(), m, s)
        ok, mask = bv.verify()
        assert not ok
        assert [i for i, good in enumerate(mask) if not good] == \
            [1, 4, 8]

    def test_bls_bisection_skips_good_subtrees(self, monkeypatch):
        sks = _bls_keys(8)
        bv = bls.Bls12381BatchVerifier()
        msgs = [f"m{i}".encode() for i in range(8)]
        for i, (sk, m) in enumerate(zip(sks, msgs)):
            sig = sk.sign(b"bad") if i == 5 else sk.sign(m)
            bv.add(sk.pub_key(), m, sig)
        singles = []
        orig = bls.Bls12381PubKey.verify_signature
        monkeypatch.setattr(
            bls.Bls12381PubKey, "verify_signature",
            lambda self, m, s: singles.append(1) or
            orig(self, m, s))
        ok, mask = bv.verify()
        assert not ok and mask == [True] * 5 + [False] + [True] * 2
        # one bad signature: exactly TWO per-signature verifications
        # — the failing leaf and its pair sibling (the singleton
        # short-circuit goes straight to exact verification instead
        # of paying a full-cost RLC product on one item first; see
        # keys.bisect_bad) — not the whole group of 8
        assert len(singles) == 2

    def test_ed25519_mask_exact(self):
        sks = [ed25519.gen_priv_key_from_secret(bytes([i]) + b"e" * 31)
               for i in range(10)]
        cv = ed25519.CpuBatchVerifier()
        msgs = [f"e{i}".encode() for i in range(10)]
        for i, (sk, m) in enumerate(zip(sks, msgs)):
            sig = sk.sign(b"zzz") if i in (0, 7) else sk.sign(m)
            cv.add(sk.pub_key(), m, sig)
        ok, mask = cv.verify()
        assert not ok
        assert [i for i, good in enumerate(mask) if not good] == [0, 7]


class TestBucketTuning:
    """Satellite: pad-bucket sizing steered by the measured
    host_prep vs kernel_execute split."""

    def setup_method(self):
        from cometbft_tpu.ops import ed25519_jax as oj
        oj.reset_bucket_tuning()

    teardown_method = setup_method

    def test_kernel_dominated_low_occupancy_refines(self):
        from cometbft_tpu.ops import ed25519_jax as oj
        for _ in range(oj._TUNE_MIN_SAMPLES):
            oj._tune_record(100, 1024, 0.001, 0.010)
        assert 128 in oj._BUCKETS
        assert crypto_batch.pad_bucket(100) == oj._bucket(100) == 128

    def test_host_prep_dominated_never_refines(self):
        from cometbft_tpu.ops import ed25519_jax as oj
        for _ in range(4 * oj._TUNE_MIN_SAMPLES):
            oj._tune_record(100, 1024, 0.010, 0.001)
        assert oj._BUCKETS == list(oj._BASE_BUCKETS)

    def test_high_occupancy_never_refines(self):
        from cometbft_tpu.ops import ed25519_jax as oj
        for _ in range(4 * oj._TUNE_MIN_SAMPLES):
            oj._tune_record(900, 1024, 0.001, 0.010)
        assert oj._BUCKETS == list(oj._BASE_BUCKETS)

    def test_refined_bucket_covers_observed_sizes(self):
        from cometbft_tpu.ops import ed25519_jax as oj
        for _ in range(oj._TUNE_MIN_SAMPLES):
            oj._tune_record(200, 1024, 0.001, 0.010)
        # 128 < 200: candidate must cover the observed max
        assert 128 not in oj._BUCKETS and 256 in oj._BUCKETS
