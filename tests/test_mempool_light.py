"""Mempool (lanes, cache, recheck) and light-client verifier tests."""
import asyncio

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import AppConns
from cometbft_tpu.abci.kvstore import DEFAULT_LANES, KVStoreApplication
from cometbft_tpu.config import MempoolConfig
from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.light import (
    verify, verify_adjacent, verify_backwards, verify_non_adjacent,
)
from cometbft_tpu.light.verifier import (
    InvalidHeaderError, LightClientError, NewValSetCantBeTrustedError,
    OldHeaderExpiredError,
)
from cometbft_tpu.mempool import (
    CListMempool, MempoolError, NopMempool, TxCache,
)
from cometbft_tpu.mempool.mempool import InvalidTxError, TxInCacheError
from cometbft_tpu.state import make_genesis_state
from cometbft_tpu.types import canonical
from cometbft_tpu.types.block import SignedHeader
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.commit import Commit, CommitSig
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.priv_validator import new_mock_pv
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validation import Fraction
from cometbft_tpu.types.validator import Validator
from cometbft_tpu.types.validator_set import ValidatorSet
from cometbft_tpu.types.vote import BLOCK_ID_FLAG_COMMIT, Vote

_S = 1_000_000_000


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _mk_mempool(**cfg_kw):
    app = KVStoreApplication()
    conns = AppConns(app)
    cfg = MempoolConfig(**cfg_kw)
    mp = CListMempool(cfg, conns.mempool, lanes=DEFAULT_LANES,
                      default_lane="default")
    return mp, app, conns


class TestMempool:
    def test_check_tx_and_reap(self):
        async def go():
            mp, app, conns = _mk_mempool()
            await mp.check_tx(b"a=1")
            await mp.check_tx(b"b=2")
            assert mp.size() == 2
            txs = mp.reap_max_bytes_max_gas(-1, -1)
            assert sorted(txs) == [b"a=1", b"b=2"]
        run(go())

    def test_duplicate_rejected_via_cache(self):
        async def go():
            mp, app, conns = _mk_mempool()
            await mp.check_tx(b"a=1")
            with pytest.raises(TxInCacheError):
                await mp.check_tx(b"a=1")
            assert mp.size() == 1
        run(go())

    def test_invalid_tx_rejected(self):
        async def go():
            mp, app, conns = _mk_mempool()
            with pytest.raises(InvalidTxError):
                await mp.check_tx(b"garbage-no-sep")
            assert mp.size() == 0
        run(go())

    def test_lane_assignment_and_priority_order(self):
        async def go():
            mp, app, conns = _mk_mempool()
            # key 22 -> lane foo (prio 7); key 9 -> bar (1); key 5 -> default (3)
            await mp.check_tx(b"9=x")
            await mp.check_tx(b"5=x")
            await mp.check_tx(b"22=x")
            assert mp.lane_sizes("foo") == (1, 4)
            assert mp.lane_sizes("bar") == (1, 3)
            order = mp.reap_max_bytes_max_gas(-1, -1)
            # highest priority lane first in the IWRR order
            assert order[0] == b"22=x"
            assert order.index(b"22=x") < order.index(b"5=x") < \
                order.index(b"9=x")
        run(go())

    def test_reap_respects_max_bytes(self):
        async def go():
            mp, app, conns = _mk_mempool()
            for i in range(10):
                await mp.check_tx(f"k{i}=v{i}".encode())
            txs = mp.reap_max_bytes_max_gas(12, -1)
            assert sum(len(t) for t in txs) <= 12
            assert len(txs) >= 1
        run(go())

    def test_full_rejected(self):
        async def go():
            mp, app, conns = _mk_mempool(size=2)
            await mp.check_tx(b"a=1")
            await mp.check_tx(b"b=2")
            with pytest.raises(MempoolError, match="full"):
                await mp.check_tx(b"c=3")
        run(go())

    def test_update_removes_committed_and_rechecks(self):
        async def go():
            mp, app, conns = _mk_mempool()
            await mp.check_tx(b"a=1")
            await mp.check_tx(b"b=2")
            ok = abci.ExecTxResult(code=0)
            await mp.update(5, [b"a=1"], [ok])
            assert mp.size() == 1
            assert mp.get_tx_by_hash(
                __import__("cometbft_tpu.types.tx",
                           fromlist=["tx_key"]).tx_key(b"b=2")) == b"b=2"
            # committed tx stays cached: re-submission rejected
            with pytest.raises(TxInCacheError):
                await mp.check_tx(b"a=1")
        run(go())

    def test_txs_available_notification(self):
        async def go():
            mp, app, conns = _mk_mempool()
            mp.enable_txs_available()
            ev = mp.txs_available()
            assert not ev.is_set()
            await mp.check_tx(b"a=1")
            assert ev.is_set()
            await mp.update(1, [b"a=1"], [abci.ExecTxResult(code=0)])
            assert not ev.is_set()
        run(go())

    def test_nop_mempool(self):
        async def go():
            mp = NopMempool()
            assert mp.reap_max_bytes_max_gas(-1, -1) == []
            with pytest.raises(MempoolError):
                await mp.check_tx(b"a=1")
        run(go())


class TestTxCache:
    def test_lru_eviction(self):
        c = TxCache(2)
        assert c.push(b"a")
        assert c.push(b"b")
        assert not c.push(b"a")     # refreshes a
        assert c.push(b"c")         # evicts b
        assert c.has(b"a")
        assert not c.has(b"b")
        assert c.has(b"c")


# ---------------------------------------------------------------------------


def _light_fixture(n=4, power=10, chain_id="light-test"):
    pvs = [new_mock_pv() for _ in range(n)]
    vals = [Validator.new(pv.get_pub_key(), power) for pv in pvs]
    pairs = sorted(zip(vals, pvs),
                   key=lambda vp: (-vp[0].voting_power, vp[0].address))
    vset = ValidatorSet([p[0] for p in pairs])
    pv_by_addr = {p[1].get_pub_key().address(): p[1] for p in pairs}
    return vset, pv_by_addr


def _signed_header(chain_id, height, time_s, vset, pv_by_addr,
                   next_vset=None, signers=None):
    doc = GenesisDoc(chain_id=chain_id,
                     genesis_time=Timestamp(1700000000, 0),
                     validators=[])
    state = make_genesis_state(doc)
    from cometbft_tpu.types.block import Header
    header = Header(
        chain_id=chain_id, height=height,
        time=Timestamp(time_s, 0),
        last_block_id=BlockID(hash=b"\x01" * 32,
                              part_set_header=PartSetHeader(1,
                                                            b"\x02" * 32)),
        validators_hash=vset.hash(),
        next_validators_hash=(next_vset or vset).hash(),
        consensus_hash=b"\x03" * 32,
        proposer_address=vset.validators[0].address,
        last_commit_hash=b"\x04" * 32,
        data_hash=b"\x05" * 32,
    )
    bid = BlockID(hash=header.hash(),
                  part_set_header=PartSetHeader(1, b"\x06" * 32))
    sigs = []
    for i, v in enumerate(vset.validators):
        if signers is not None and i not in signers:
            sigs.append(CommitSig.absent())
            continue
        ts = Timestamp(time_s, 0)
        vote = Vote(type=canonical.PRECOMMIT_TYPE, height=height,
                    round=0, block_id=bid, timestamp=ts,
                    validator_address=v.address, validator_index=i)
        pv_by_addr[v.address].sign_vote(chain_id, vote,
                                        sign_extension=False)
        sigs.append(CommitSig(block_id_flag=BLOCK_ID_FLAG_COMMIT,
                              validator_address=v.address, timestamp=ts,
                              signature=vote.signature))
    commit = Commit(height=height, round=0, block_id=bid,
                    signatures=sigs)
    return SignedHeader(header=header, commit=commit)


class TestLightVerifier:
    def test_verify_adjacent_ok(self):
        vset, pvs = _light_fixture()
        h1 = _signed_header("light-test", 1, 1700000100, vset, pvs)
        h2 = _signed_header("light-test", 2, 1700000200, vset, pvs)
        verify_adjacent(h1, h2, vset, trusting_period_ns=3600 * _S,
                        now=Timestamp(1700000300, 0),
                        max_clock_drift_ns=10 * _S)

    def test_verify_non_adjacent_ok(self):
        vset, pvs = _light_fixture()
        h1 = _signed_header("light-test", 1, 1700000100, vset, pvs)
        h5 = _signed_header("light-test", 5, 1700000500, vset, pvs)
        verify_non_adjacent(h1, vset, h5, vset,
                            trusting_period_ns=3600 * _S,
                            now=Timestamp(1700000600, 0),
                            max_clock_drift_ns=10 * _S)

    def test_expired_trusted_header(self):
        vset, pvs = _light_fixture()
        h1 = _signed_header("light-test", 1, 1700000100, vset, pvs)
        h5 = _signed_header("light-test", 5, 1700000500, vset, pvs)
        with pytest.raises(OldHeaderExpiredError):
            verify_non_adjacent(h1, vset, h5, vset,
                                trusting_period_ns=100 * _S,
                                now=Timestamp(1700010000, 0),
                                max_clock_drift_ns=10 * _S)

    def test_insufficient_trust(self):
        # new valset disjoint from trusted: 1/3 trust check must fail
        vset, pvs = _light_fixture()
        new_vset, new_pvs = _light_fixture()
        h1 = _signed_header("light-test", 1, 1700000100, vset, pvs)
        h5 = _signed_header("light-test", 5, 1700000500, new_vset,
                            new_pvs)
        with pytest.raises(NewValSetCantBeTrustedError):
            verify_non_adjacent(h1, vset, h5, new_vset,
                                trusting_period_ns=3600 * _S,
                                now=Timestamp(1700000600, 0),
                                max_clock_drift_ns=10 * _S)

    def test_insufficient_new_signatures(self):
        vset, pvs = _light_fixture(4)
        h1 = _signed_header("light-test", 1, 1700000100, vset, pvs)
        # only 2 of 4 sign height 5 (50% < 2/3)
        h5 = _signed_header("light-test", 5, 1700000500, vset, pvs,
                            signers={0, 1})
        with pytest.raises(InvalidHeaderError):
            verify_non_adjacent(h1, vset, h5, vset,
                                trusting_period_ns=3600 * _S,
                                now=Timestamp(1700000600, 0),
                                max_clock_drift_ns=10 * _S)

    def test_adjacent_requires_valhash_continuity(self):
        vset, pvs = _light_fixture()
        other_vset, other_pvs = _light_fixture()
        h1 = _signed_header("light-test", 1, 1700000100, vset, pvs)
        h2 = _signed_header("light-test", 2, 1700000200, other_vset,
                            other_pvs)
        with pytest.raises(InvalidHeaderError):
            verify_adjacent(h1, h2, other_vset,
                            trusting_period_ns=3600 * _S,
                            now=Timestamp(1700000300, 0),
                            max_clock_drift_ns=10 * _S)

    def test_verify_dispatches(self):
        vset, pvs = _light_fixture()
        h1 = _signed_header("light-test", 1, 1700000100, vset, pvs)
        h2 = _signed_header("light-test", 2, 1700000200, vset, pvs)
        verify(h1, vset, h2, vset, 3600 * _S,
               Timestamp(1700000300, 0), 10 * _S, Fraction(1, 3))

    def test_verify_backwards(self):
        vset, pvs = _light_fixture()
        h1 = _signed_header("light-test", 1, 1700000100, vset, pvs)
        h2 = _signed_header("light-test", 2, 1700000200, vset, pvs)
        h2.header.last_block_id = BlockID(
            hash=h1.header.hash(),
            part_set_header=PartSetHeader(1, b"\x06" * 32))
        verify_backwards(h1.header, h2.header)
        h1.header.time = Timestamp(1800000000, 0)
        with pytest.raises(InvalidHeaderError):
            verify_backwards(h1.header, h2.header)


class TestLaneBytesBookkeeping:
    """lane_sizes byte totals are maintained incrementally (the rescan
    form measured ~19% of a saturated node's CPU — QA_r05 profile);
    the counter must agree with a recount through every mutation."""

    def test_counter_matches_recount_through_lifecycle(self):
        async def go():
            mp, app, conns = _mk_mempool()

            def recount(lane):
                d = mp._lane_txs[lane]
                return len(d), sum(len(e.tx) for e in d.values())

            txs = [b"k%03d=v%d" % (i, i) for i in range(12)]
            for tx in txs:
                await mp.check_tx(tx)
            for lane in mp.lanes:
                assert mp.lane_sizes(lane) == recount(lane)
            # commit-style removal of a third of them
            from cometbft_tpu.mempool.mempool import tx_key
            for tx in txs[::3]:
                mp.remove_tx_by_key(tx_key(tx))
            for lane in mp.lanes:
                assert mp.lane_sizes(lane) == recount(lane)
            mp.flush()
            for lane in mp.lanes:
                assert mp.lane_sizes(lane) == (0, 0) == recount(lane)
        run(go())
