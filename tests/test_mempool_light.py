"""Mempool (lanes, cache, recheck) and light-client verifier tests."""
import asyncio

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import AppConns
from cometbft_tpu.abci.kvstore import DEFAULT_LANES, KVStoreApplication
from cometbft_tpu.config import MempoolConfig
from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.light import (
    verify, verify_adjacent, verify_backwards, verify_non_adjacent,
)
from cometbft_tpu.light.verifier import (
    InvalidHeaderError, LightClientError, NewValSetCantBeTrustedError,
    OldHeaderExpiredError,
)
from cometbft_tpu.mempool import (
    CListMempool, MempoolError, NopMempool, TxCache,
)
from cometbft_tpu.mempool.mempool import InvalidTxError, TxInCacheError
from cometbft_tpu.state import make_genesis_state
from cometbft_tpu.types import canonical
from cometbft_tpu.types.block import SignedHeader
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.commit import Commit, CommitSig
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.priv_validator import new_mock_pv
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validation import Fraction
from cometbft_tpu.types.validator import Validator
from cometbft_tpu.types.validator_set import ValidatorSet
from cometbft_tpu.types.vote import BLOCK_ID_FLAG_COMMIT, Vote

_S = 1_000_000_000


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _mk_mempool(**cfg_kw):
    app = KVStoreApplication()
    conns = AppConns(app)
    cfg = MempoolConfig(**cfg_kw)
    mp = CListMempool(cfg, conns.mempool, lanes=DEFAULT_LANES,
                      default_lane="default")
    return mp, app, conns


class TestMempool:
    def test_check_tx_and_reap(self):
        async def go():
            mp, app, conns = _mk_mempool()
            await mp.check_tx(b"a=1")
            await mp.check_tx(b"b=2")
            assert mp.size() == 2
            txs = mp.reap_max_bytes_max_gas(-1, -1)
            assert sorted(txs) == [b"a=1", b"b=2"]
        run(go())

    def test_duplicate_rejected_via_cache(self):
        async def go():
            mp, app, conns = _mk_mempool()
            await mp.check_tx(b"a=1")
            with pytest.raises(TxInCacheError):
                await mp.check_tx(b"a=1")
            assert mp.size() == 1
        run(go())

    def test_invalid_tx_rejected(self):
        async def go():
            mp, app, conns = _mk_mempool()
            with pytest.raises(InvalidTxError):
                await mp.check_tx(b"garbage-no-sep")
            assert mp.size() == 0
        run(go())

    def test_lane_assignment_and_priority_order(self):
        async def go():
            mp, app, conns = _mk_mempool()
            # key 22 -> lane foo (prio 7); key 9 -> bar (1); key 5 -> default (3)
            await mp.check_tx(b"9=x")
            await mp.check_tx(b"5=x")
            await mp.check_tx(b"22=x")
            assert mp.lane_sizes("foo") == (1, 4)
            assert mp.lane_sizes("bar") == (1, 3)
            order = mp.reap_max_bytes_max_gas(-1, -1)
            # highest priority lane first in the IWRR order
            assert order[0] == b"22=x"
            assert order.index(b"22=x") < order.index(b"5=x") < \
                order.index(b"9=x")
        run(go())

    def test_reap_respects_max_bytes(self):
        async def go():
            mp, app, conns = _mk_mempool()
            for i in range(10):
                await mp.check_tx(f"k{i}=v{i}".encode())
            txs = mp.reap_max_bytes_max_gas(12, -1)
            assert sum(len(t) for t in txs) <= 12
            assert len(txs) >= 1
        run(go())

    def test_full_rejected(self):
        async def go():
            mp, app, conns = _mk_mempool(size=2)
            await mp.check_tx(b"a=1")
            await mp.check_tx(b"b=2")
            with pytest.raises(MempoolError, match="full"):
                await mp.check_tx(b"c=3")
        run(go())

    def test_update_removes_committed_and_rechecks(self):
        async def go():
            mp, app, conns = _mk_mempool()
            await mp.check_tx(b"a=1")
            await mp.check_tx(b"b=2")
            ok = abci.ExecTxResult(code=0)
            await mp.update(5, [b"a=1"], [ok])
            assert mp.size() == 1
            assert mp.get_tx_by_hash(
                __import__("cometbft_tpu.types.tx",
                           fromlist=["tx_key"]).tx_key(b"b=2")) == b"b=2"
            # committed tx stays cached: re-submission rejected
            with pytest.raises(TxInCacheError):
                await mp.check_tx(b"a=1")
        run(go())

    def test_txs_available_notification(self):
        async def go():
            mp, app, conns = _mk_mempool()
            mp.enable_txs_available()
            ev = mp.txs_available()
            assert not ev.is_set()
            await mp.check_tx(b"a=1")
            assert ev.is_set()
            await mp.update(1, [b"a=1"], [abci.ExecTxResult(code=0)])
            assert not ev.is_set()
        run(go())

    def test_nop_mempool(self):
        async def go():
            mp = NopMempool()
            assert mp.reap_max_bytes_max_gas(-1, -1) == []
            with pytest.raises(MempoolError):
                await mp.check_tx(b"a=1")
        run(go())


class _CountingKV(KVStoreApplication):
    """KVStore that counts CheckTx calls by type and can reject
    rechecks of chosen txs."""

    def __init__(self):
        super().__init__()
        self.checks = 0
        self.rechecks = 0
        self.reject_on_recheck: set = set()

    async def check_tx(self, req):
        if req.type == abci.CHECK_TX_TYPE_RECHECK:
            self.rechecks += 1
            if bytes(req.tx) in self.reject_on_recheck:
                return abci.CheckTxResponse(code=9)
        else:
            self.checks += 1
        return await super().check_tx(req)


def _mk_incremental(app=None, **cfg_kw):
    app = app if app is not None else _CountingKV()
    conns = AppConns(app)
    cfg = MempoolConfig(**cfg_kw)
    mp = CListMempool(cfg, conns.mempool, lanes=DEFAULT_LANES,
                      default_lane="default")
    return mp, app


def _committed(tx: bytes) -> abci.ExecTxResult:
    from cometbft_tpu.abci.kvstore import tx_recheck_keys
    return abci.ExecTxResult(code=abci.CODE_TYPE_OK,
                             recheck_keys=tx_recheck_keys(tx))


class TestIncrementalRecheck:
    """Incremental recheck (docs/pipeline.md): a commit re-runs
    CheckTx only for pooled txs whose app-reported keys overlap the
    committed block's, plus the bounded-age watermark."""

    def test_targets_only_touched_keys(self):
        async def go():
            mp, app = _mk_incremental()
            for tx in (b"aa=1", b"bb=2", b"cc=3"):
                await mp.check_tx(tx)
            assert mp.size() == 3
            await mp.update(1, [b"aa=9"], [_committed(b"aa=9")])
            # only the pooled tx sharing key "aa" was rechecked
            assert app.rechecks == 1
            assert mp.size() == 3
            # the rechecked entry's watermark clock was reset
            from cometbft_tpu.types.tx import tx_key
            for d in mp._lane_txs.values():
                e = d.get(tx_key(b"aa=1"))
                if e is not None:
                    assert e.height == 1
        run(go())

    def test_watermark_bounds_staleness(self):
        async def go():
            mp, app = _mk_incremental(recheck_max_age_blocks=2)
            await mp.check_tx(b"bb=2")          # validated at h 0
            await mp.update(1, [b"zz=1"], [_committed(b"zz=1")])
            assert app.rechecks == 0            # age 1 < 2, no overlap
            await mp.update(2, [b"zz=2"], [_committed(b"zz=2")])
            assert app.rechecks == 1            # age 2 hit the watermark
            await mp.update(3, [b"zz=3"], [_committed(b"zz=3")])
            assert app.rechecks == 1            # clock was reset to 2
        run(go())

    def test_unattributed_commit_rechecks_keyed_entries(self):
        async def go():
            mp, app = _mk_incremental()
            for tx in (b"aa=1", b"bb=2"):
                await mp.check_tx(tx)
            # a state-changing result the app did not attribute: key
            # targeting is unsound, every keyed entry gets rechecked
            await mp.update(1, [b"zz=9"],
                            [abci.ExecTxResult(code=abci.CODE_TYPE_OK)])
            assert app.rechecks == 2
        run(go())

    def test_incremental_off_restores_full_recheck(self):
        async def go():
            mp, app = _mk_incremental(recheck_incremental=False)
            for tx in (b"aa=1", b"bb=2", b"cc=3"):
                await mp.check_tx(tx)
            await mp.update(1, [b"zz=9"], [_committed(b"zz=9")])
            assert app.rechecks == 3
        run(go())

    def test_recheck_evicts_invalidated_tx(self):
        async def go():
            mp, app = _mk_incremental()
            await mp.check_tx(b"aa=1")
            await mp.check_tx(b"bb=2")
            app.reject_on_recheck.add(b"aa=1")
            await mp.update(1, [b"aa=9"], [_committed(b"aa=9")])
            assert mp.size() == 1
            from cometbft_tpu.types.tx import tx_key
            assert not mp.contains(tx_key(b"aa=1"))
            # byte accounting stayed consistent
            assert mp.size_bytes() == len(b"bb=2")
            # evicted = resubmittable (not kept in cache)
            await mp.check_tx(b"aa=1")
            assert mp.size() == 2
        run(go())

    def test_batched_recheck_full_pass_parity(self):
        """A large pool rechecked in gather-batches evicts exactly
        what per-tx serial recheck would."""
        async def go():
            mp, app = _mk_incremental(recheck_incremental=False,
                                      recheck_batch_size=8)
            txs = [b"k%02dx=v" % i for i in range(30)]
            for tx in txs:
                await mp.check_tx(tx)
            app.reject_on_recheck = {txs[3], txs[17], txs[29]}
            await mp.update(1, [b"zz=9"], [_committed(b"zz=9")])
            assert app.rechecks == 30
            assert mp.size() == 27
            assert mp.size_bytes() == sum(
                len(t) for t in txs
                if t not in app.reject_on_recheck)
        run(go())


class TestCheckTxCommitRace:
    """Regression for the FinalizeBlock→recheck admission gap (the
    mempool.py:150 note): a tx whose CheckTx was in flight when a
    commit cycle started must be revalidated at the post-commit
    height, never admitted on pre-block validation."""

    class _GatedKV(_CountingKV):
        def __init__(self):
            super().__init__()
            self.gate = asyncio.Event()
            self.entered = asyncio.Event()

        async def check_tx(self, req):
            first = not self.gate.is_set()
            if req.type == abci.CHECK_TX_TYPE_CHECK and first:
                self.entered.set()
                await self.gate.wait()
            return await super().check_tx(req)

    def test_in_flight_checktx_revalidated_by_next_update(self):
        async def go():
            app = self._GatedKV()
            mp, _ = _mk_incremental(app=app)
            task = asyncio.get_running_loop().create_task(
                mp.check_tx(b"aa=1"))
            await app.entered.wait()
            # a commit cycle runs while the CheckTx is in flight
            # (BlockExecutor.commit: lock → app commit → update) —
            # its recheck pass cannot see the not-yet-admitted tx
            mp.lock()
            await mp.update(5, [b"zz=9"], [_committed(b"zz=9")])
            mp.unlock()
            app.gate.set()
            await task
            assert mp.size() == 1
            from cometbft_tpu.types.tx import tx_key
            # the raced admission is flagged for unconditional
            # revalidation (no validate-retry loop: under sub-second
            # block intervals that could chase the tip forever)
            assert tx_key(b"aa=1") in mp._pending_recheck
            assert mp.metrics.checktx_revalidations.value >= 1
            # the NEXT update rechecks it even though neither key
            # overlap nor the age watermark selects it
            assert app.rechecks == 0
            await mp.update(6, [b"zz=8"], [_committed(b"zz=8")])
            assert app.rechecks == 1
            assert not mp._pending_recheck
            # and only once — the entry rejoins the normal schedule
            await mp.update(7, [b"zz=7"], [_committed(b"zz=7")])
            assert app.rechecks == 1
        run(go())

    def test_checktx_after_unlock_no_extra_roundtrip(self):
        async def go():
            mp, app = _mk_incremental()
            await mp.check_tx(b"aa=1")
            assert app.checks == 1
        run(go())


class TestTxCache:
    def test_lru_eviction(self):
        c = TxCache(2)
        assert c.push(b"a")
        assert c.push(b"b")
        assert not c.push(b"a")     # refreshes a
        assert c.push(b"c")         # evicts b
        assert c.has(b"a")
        assert not c.has(b"b")
        assert c.has(b"c")


# ---------------------------------------------------------------------------


def _light_fixture(n=4, power=10, chain_id="light-test"):
    pvs = [new_mock_pv() for _ in range(n)]
    vals = [Validator.new(pv.get_pub_key(), power) for pv in pvs]
    pairs = sorted(zip(vals, pvs),
                   key=lambda vp: (-vp[0].voting_power, vp[0].address))
    vset = ValidatorSet([p[0] for p in pairs])
    pv_by_addr = {p[1].get_pub_key().address(): p[1] for p in pairs}
    return vset, pv_by_addr


def _signed_header(chain_id, height, time_s, vset, pv_by_addr,
                   next_vset=None, signers=None):
    doc = GenesisDoc(chain_id=chain_id,
                     genesis_time=Timestamp(1700000000, 0),
                     validators=[])
    state = make_genesis_state(doc)
    from cometbft_tpu.types.block import Header
    header = Header(
        chain_id=chain_id, height=height,
        time=Timestamp(time_s, 0),
        last_block_id=BlockID(hash=b"\x01" * 32,
                              part_set_header=PartSetHeader(1,
                                                            b"\x02" * 32)),
        validators_hash=vset.hash(),
        next_validators_hash=(next_vset or vset).hash(),
        consensus_hash=b"\x03" * 32,
        proposer_address=vset.validators[0].address,
        last_commit_hash=b"\x04" * 32,
        data_hash=b"\x05" * 32,
    )
    bid = BlockID(hash=header.hash(),
                  part_set_header=PartSetHeader(1, b"\x06" * 32))
    sigs = []
    for i, v in enumerate(vset.validators):
        if signers is not None and i not in signers:
            sigs.append(CommitSig.absent())
            continue
        ts = Timestamp(time_s, 0)
        vote = Vote(type=canonical.PRECOMMIT_TYPE, height=height,
                    round=0, block_id=bid, timestamp=ts,
                    validator_address=v.address, validator_index=i)
        pv_by_addr[v.address].sign_vote(chain_id, vote,
                                        sign_extension=False)
        sigs.append(CommitSig(block_id_flag=BLOCK_ID_FLAG_COMMIT,
                              validator_address=v.address, timestamp=ts,
                              signature=vote.signature))
    commit = Commit(height=height, round=0, block_id=bid,
                    signatures=sigs)
    return SignedHeader(header=header, commit=commit)


class TestLightVerifier:
    def test_verify_adjacent_ok(self):
        vset, pvs = _light_fixture()
        h1 = _signed_header("light-test", 1, 1700000100, vset, pvs)
        h2 = _signed_header("light-test", 2, 1700000200, vset, pvs)
        verify_adjacent(h1, h2, vset, trusting_period_ns=3600 * _S,
                        now=Timestamp(1700000300, 0),
                        max_clock_drift_ns=10 * _S)

    def test_verify_non_adjacent_ok(self):
        vset, pvs = _light_fixture()
        h1 = _signed_header("light-test", 1, 1700000100, vset, pvs)
        h5 = _signed_header("light-test", 5, 1700000500, vset, pvs)
        verify_non_adjacent(h1, vset, h5, vset,
                            trusting_period_ns=3600 * _S,
                            now=Timestamp(1700000600, 0),
                            max_clock_drift_ns=10 * _S)

    def test_expired_trusted_header(self):
        vset, pvs = _light_fixture()
        h1 = _signed_header("light-test", 1, 1700000100, vset, pvs)
        h5 = _signed_header("light-test", 5, 1700000500, vset, pvs)
        with pytest.raises(OldHeaderExpiredError):
            verify_non_adjacent(h1, vset, h5, vset,
                                trusting_period_ns=100 * _S,
                                now=Timestamp(1700010000, 0),
                                max_clock_drift_ns=10 * _S)

    def test_insufficient_trust(self):
        # new valset disjoint from trusted: 1/3 trust check must fail
        vset, pvs = _light_fixture()
        new_vset, new_pvs = _light_fixture()
        h1 = _signed_header("light-test", 1, 1700000100, vset, pvs)
        h5 = _signed_header("light-test", 5, 1700000500, new_vset,
                            new_pvs)
        with pytest.raises(NewValSetCantBeTrustedError):
            verify_non_adjacent(h1, vset, h5, new_vset,
                                trusting_period_ns=3600 * _S,
                                now=Timestamp(1700000600, 0),
                                max_clock_drift_ns=10 * _S)

    def test_insufficient_new_signatures(self):
        vset, pvs = _light_fixture(4)
        h1 = _signed_header("light-test", 1, 1700000100, vset, pvs)
        # only 2 of 4 sign height 5 (50% < 2/3)
        h5 = _signed_header("light-test", 5, 1700000500, vset, pvs,
                            signers={0, 1})
        with pytest.raises(InvalidHeaderError):
            verify_non_adjacent(h1, vset, h5, vset,
                                trusting_period_ns=3600 * _S,
                                now=Timestamp(1700000600, 0),
                                max_clock_drift_ns=10 * _S)

    def test_adjacent_requires_valhash_continuity(self):
        vset, pvs = _light_fixture()
        other_vset, other_pvs = _light_fixture()
        h1 = _signed_header("light-test", 1, 1700000100, vset, pvs)
        h2 = _signed_header("light-test", 2, 1700000200, other_vset,
                            other_pvs)
        with pytest.raises(InvalidHeaderError):
            verify_adjacent(h1, h2, other_vset,
                            trusting_period_ns=3600 * _S,
                            now=Timestamp(1700000300, 0),
                            max_clock_drift_ns=10 * _S)

    def test_verify_dispatches(self):
        vset, pvs = _light_fixture()
        h1 = _signed_header("light-test", 1, 1700000100, vset, pvs)
        h2 = _signed_header("light-test", 2, 1700000200, vset, pvs)
        verify(h1, vset, h2, vset, 3600 * _S,
               Timestamp(1700000300, 0), 10 * _S, Fraction(1, 3))

    def test_verify_backwards(self):
        vset, pvs = _light_fixture()
        h1 = _signed_header("light-test", 1, 1700000100, vset, pvs)
        h2 = _signed_header("light-test", 2, 1700000200, vset, pvs)
        h2.header.last_block_id = BlockID(
            hash=h1.header.hash(),
            part_set_header=PartSetHeader(1, b"\x06" * 32))
        verify_backwards(h1.header, h2.header)
        h1.header.time = Timestamp(1800000000, 0)
        with pytest.raises(InvalidHeaderError):
            verify_backwards(h1.header, h2.header)


class TestLaneBytesBookkeeping:
    """lane_sizes byte totals are maintained incrementally (the rescan
    form measured ~19% of a saturated node's CPU — QA_r05 profile);
    the counter must agree with a recount through every mutation."""

    def test_counter_matches_recount_through_lifecycle(self):
        async def go():
            mp, app, conns = _mk_mempool()

            def recount(lane):
                d = mp._lane_txs[lane]
                return len(d), sum(len(e.tx) for e in d.values())

            txs = [b"k%03d=v%d" % (i, i) for i in range(12)]
            for tx in txs:
                await mp.check_tx(tx)
            for lane in mp.lanes:
                assert mp.lane_sizes(lane) == recount(lane)
            # commit-style removal of a third of them
            from cometbft_tpu.mempool.mempool import tx_key
            for tx in txs[::3]:
                mp.remove_tx_by_key(tx_key(tx))
            for lane in mp.lanes:
                assert mp.lane_sizes(lane) == recount(lane)
            mp.flush()
            for lane in mp.lanes:
                assert mp.lane_sizes(lane) == (0, 0) == recount(lane)
        run(go())
