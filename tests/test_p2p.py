"""P2P tests: secret connection, mconnection multiplexing, switch."""
import asyncio

import pytest

from cometbft_tpu.crypto import ed25519
from cometbft_tpu.p2p.conn import ChannelDescriptor, MConnection
from cometbft_tpu.p2p.key import NodeKey
from cometbft_tpu.p2p.secret_connection import (
    AuthFailureError, SecretConnection,
)
from cometbft_tpu.p2p.switch import NodeInfo, Reactor, Switch


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


async def _pipe_pair():
    """Two connected (reader, writer) pairs over a localhost socket."""
    server_side = asyncio.Queue()

    async def on_conn(r, w):
        await server_side.put((r, w))

    server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    cr, cw = await asyncio.open_connection("127.0.0.1", port)
    sr, sw = await server_side.get()
    return (cr, cw), (sr, sw), server


class TestSecretConnection:
    def test_handshake_and_roundtrip(self):
        async def go():
            (cr, cw), (sr, sw), server = await _pipe_pair()
            k1, k2 = ed25519.gen_priv_key(), ed25519.gen_priv_key()
            sc1, sc2 = await asyncio.gather(
                SecretConnection.make(cr, cw, k1),
                SecretConnection.make(sr, sw, k2))
            # mutual authentication
            assert sc1.remote_pub_key == k2.pub_key()
            assert sc2.remote_pub_key == k1.pub_key()
            # small message
            await sc1.write_msg(b"hello")
            assert await sc2.read_msg() == b"hello"
            # exact-multiple-of-frame message
            big = b"\xab" * 2048
            await sc2.write_msg(big)
            assert await sc1.read_msg() == big
            # large multi-frame message
            big2 = bytes(range(256)) * 40
            await sc1.write_msg(big2)
            assert await sc2.read_msg() == big2
            server.close()
        run(go())

    def test_tampered_ciphertext_rejected(self):
        async def go():
            (cr, cw), (sr, sw), server = await _pipe_pair()
            k1, k2 = ed25519.gen_priv_key(), ed25519.gen_priv_key()
            sc1, sc2 = await asyncio.gather(
                SecretConnection.make(cr, cw, k1),
                SecretConnection.make(sr, sw, k2))
            # write garbage straight to the transport
            sw.write(b"\x00" * 1044)
            await sw.drain()
            with pytest.raises(Exception):
                await sc1.read_msg()
            server.close()
        run(go())


class TestMConnection:
    def test_multiplexed_channels(self):
        async def go():
            (cr, cw), (sr, sw), server = await _pipe_pair()
            k1, k2 = ed25519.gen_priv_key(), ed25519.gen_priv_key()
            sc1, sc2 = await asyncio.gather(
                SecretConnection.make(cr, cw, k1),
                SecretConnection.make(sr, sw, k2))
            chans = [ChannelDescriptor(id=0x20, priority=5),
                     ChannelDescriptor(id=0x21, priority=1)]
            got = asyncio.Queue()

            async def recv2(cid, msg):
                await got.put((cid, msg))

            async def recv1(cid, msg):
                pass

            m1 = MConnection(sc1, chans, recv1, lambda e: None)
            m2 = MConnection(sc2, chans, recv2, lambda e: None)
            m1.start()
            m2.start()
            assert m1.send(0x20, b"on-chan-20")
            assert m1.send(0x21, b"x" * 5000)   # multi-packet
            out = {}
            for _ in range(2):
                cid, msg = await asyncio.wait_for(got.get(), 5)
                out[cid] = msg
            assert out[0x20] == b"on-chan-20"
            assert out[0x21] == b"x" * 5000
            m1.close()
            m2.close()
            server.close()
        run(go())


class EchoReactor(Reactor):
    CHAN = 0x77

    def __init__(self, name="echo"):
        super().__init__(name)
        self.received = asyncio.Queue()
        self.peers = []

    def get_channels(self):
        return [ChannelDescriptor(id=self.CHAN, priority=1)]

    async def add_peer(self, peer):
        self.peers.append(peer)

    async def receive(self, chan_id, peer, msg_bytes):
        await self.received.put((peer.id, msg_bytes))


class TestSwitch:
    def test_two_switches_exchange(self):
        async def go():
            nk1, nk2 = NodeKey.generate(), NodeKey.generate()
            s1 = Switch(nk1, "testnet", listen_addr="127.0.0.1:0")
            s2 = Switch(nk2, "testnet", listen_addr="127.0.0.1:0")
            r1, r2 = EchoReactor("echo"), EchoReactor("echo")
            s1.add_reactor(r1)
            s2.add_reactor(r2)
            await s1.start()
            await s2.start()
            await s2.dial_peer(s1.listen_addr)
            await asyncio.sleep(0.05)
            assert s1.num_peers() == 1
            assert s2.num_peers() == 1
            # authenticated identity matches node keys
            assert list(s1.peers)[0] == nk2.id
            assert list(s2.peers)[0] == nk1.id
            # message flows through the reactor
            s2.broadcast(EchoReactor.CHAN, b"hello-from-2")
            pid, msg = await asyncio.wait_for(r1.received.get(), 5)
            assert pid == nk2.id
            assert msg == b"hello-from-2"
            await s1.stop()
            await s2.stop()
        run(go())

    def test_network_mismatch_rejected(self):
        async def go():
            nk1, nk2 = NodeKey.generate(), NodeKey.generate()
            s1 = Switch(nk1, "chain-A", listen_addr="127.0.0.1:0")
            s2 = Switch(nk2, "chain-B", listen_addr="127.0.0.1:0")
            s1.add_reactor(EchoReactor())
            s2.add_reactor(EchoReactor())
            await s1.start()
            await s2.start()
            with pytest.raises(Exception, match="network|incompatible"):
                await s2.dial_peer(s1.listen_addr)
            await asyncio.sleep(0.05)
            assert s1.num_peers() == 0
            await s1.stop()
            await s2.stop()
        run(go())

    def test_self_dial_rejected(self):
        async def go():
            nk = NodeKey.generate()
            s = Switch(nk, "net", listen_addr="127.0.0.1:0")
            s.add_reactor(EchoReactor())
            await s.start()
            with pytest.raises(Exception, match="self"):
                await s.dial_peer(s.listen_addr)
            await s.stop()
        run(go())


class TestNodeKey:
    def test_save_load(self, tmp_path):
        p = str(tmp_path / "node_key.json")
        nk = NodeKey.load_or_gen(p)
        nk2 = NodeKey.load_or_gen(p)
        assert nk.id == nk2.id
        assert len(nk.id) == 40
