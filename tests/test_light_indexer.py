"""Light client (bisection/sequential/backwards/detector) and indexer
tests."""
import asyncio

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import AppConns
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config import test_config as _test_config
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.db import MemDB
from cometbft_tpu.indexer import BlockIndexer, TxIndexer
from cometbft_tpu.libs.pubsub import Query
from cometbft_tpu.light.client import (
    SEQUENTIAL, SKIPPING, Client, DivergenceError, TrustOptions,
)
from cometbft_tpu.light.provider import NodeProvider
from cometbft_tpu.light.store import TrustedStore
from cometbft_tpu.state import make_genesis_state
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.store import Store
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.priv_validator import new_mock_pv
from cometbft_tpu.types.timestamp import Timestamp

_S = 1_000_000_000


@pytest.fixture(autouse=True)
def _cpu_backend():
    crypto_batch.set_backend("cpu")
    yield
    crypto_batch.set_backend("auto")


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


async def _grow_chain(n_blocks, n_vals=3):
    pvs = [new_mock_pv() for _ in range(n_vals)]
    doc = GenesisDoc(
        chain_id="light-chain",
        genesis_time=Timestamp(1700000000, 0),
        validators=[GenesisValidator(address=b"",
                                     pub_key=pv.get_pub_key(),
                                     power=10) for pv in pvs])
    # single in-process multi-validator chain (wire via broadcast hooks)
    from cometbft_tpu.consensus.messages import (
        BlockPartMessage, ProposalMessage, VoteMessage,
    )
    nodes = []
    for pv in pvs:
        app = KVStoreApplication()
        conns = AppConns(app)
        ss, bs = Store(MemDB()), BlockStore(MemDB())
        state = make_genesis_state(doc)
        ss.save(state)
        ex = BlockExecutor(ss, conns.consensus, block_store=bs)
        cs = ConsensusState(_test_config().consensus, state, ex, bs,
                            priv_validator=pv)
        nodes.append((cs, ss, bs))
    gossip = (ProposalMessage, BlockPartMessage, VoteMessage)
    for i, (cs, _, _) in enumerate(nodes):
        def mk(sender):
            def hook(msg):
                if isinstance(msg, gossip):
                    for j, (other, _, _) in enumerate(nodes):
                        if j != sender:
                            other.send_peer(msg, f"n{sender}")
            return hook
        cs.broadcast_hooks.append(mk(i))
    for cs, _, _ in nodes:
        await cs.start()
    while nodes[0][2].height < n_blocks:
        await asyncio.sleep(0.01)
    for cs, _, _ in nodes:
        await cs.stop()
    return doc, nodes[0][1], nodes[0][2]


async def _make_client(doc, ss, bs, mode, witnesses=()):
    provider = NodeProvider(bs, ss, doc.chain_id)
    root = await provider.light_block(1)
    client = Client(
        doc.chain_id,
        TrustOptions(period_ns=10 * 365 * 24 * 3600 * _S, height=1,
                     header_hash=root.signed_header.header.hash()),
        provider, list(witnesses), TrustedStore(MemDB()),
        verification_mode=mode)
    await client.initialize()
    return client


class TestLightClient:
    def test_skipping_verification(self):
        async def go():
            doc, ss, bs = await _grow_chain(8)
            client = await _make_client(doc, ss, bs, SKIPPING)
            lb = await client.verify_light_block_at_height(bs.height)
            assert lb.height == bs.height
            assert client.trusted_light_block(bs.height) is not None
        run(go())

    def test_sequential_verification(self):
        async def go():
            doc, ss, bs = await _grow_chain(5)
            client = await _make_client(doc, ss, bs, SEQUENTIAL)
            lb = await client.verify_light_block_at_height(4)
            assert lb.height == 4
            # every intermediate header is now trusted
            for h in range(1, 5):
                assert client.trusted_light_block(h) is not None
        run(go())

    def test_update_to_latest(self):
        async def go():
            doc, ss, bs = await _grow_chain(6)
            client = await _make_client(doc, ss, bs, SKIPPING)
            lb = await client.update(Timestamp.now())
            assert lb is not None and lb.height == bs.height
        run(go())

    def test_honest_witness_ok(self):
        async def go():
            doc, ss, bs = await _grow_chain(5)
            witness = NodeProvider(bs, ss, doc.chain_id)
            client = await _make_client(doc, ss, bs, SKIPPING,
                                        witnesses=[witness])
            lb = await client.verify_light_block_at_height(4)
            assert lb.height == 4
        run(go())

    def test_diverging_witness_detected(self):
        async def go():
            doc, ss, bs = await _grow_chain(5)
            # witness serving a DIFFERENT chain with same heights
            doc2, ss2, bs2 = await _grow_chain(5)
            witness = NodeProvider(bs2, ss2, doc.chain_id)
            client = await _make_client(doc, ss, bs, SKIPPING,
                                        witnesses=[witness])
            with pytest.raises(DivergenceError):
                await client.verify_light_block_at_height(4)
            # evidence was reported to the witness + primary
            assert witness.evidence or client.primary.evidence
        run(go())


class TestIndexer:
    def test_tx_index_and_search(self):
        db = MemDB()
        txi = TxIndexer(db)
        res = abci.ExecTxResult(code=0, events=[abci.Event(
            type="app", attributes=[
                abci.EventAttribute("key", "alice", True),
                abci.EventAttribute("noindex", "x", False)])])
        tr = abci.TxResult(height=7, index=0, tx=b"alice=1",
                           result=res)
        txi.index(tr)
        from cometbft_tpu.types.tx import tx_hash
        got = txi.get(tx_hash(b"alice=1"))
        assert got is not None
        assert got.height == 7
        assert got.result.events[0].attributes[0].value == "alice"
        # search by event attr
        hits = txi.search(Query("app.key = 'alice'"))
        assert hits == [tx_hash(b"alice=1")]
        # unindexed attribute is not searchable
        assert txi.search(Query("app.noindex = 'x'")) == []
        # search by height
        assert txi.search(Query("tx.height = 7")) == \
            [tx_hash(b"alice=1")]
        assert txi.search(Query("tx.height > 7")) == []

    def test_block_index_and_search(self):
        db = MemDB()
        bi = BlockIndexer(db)
        bi.index(5, [abci.Event(type="begin_event", attributes=[
            abci.EventAttribute("foo", "100", True)])])
        bi.index(6, [abci.Event(type="begin_event", attributes=[
            abci.EventAttribute("foo", "200", True)])])
        assert bi.search(Query("begin_event.foo = '100'")) == [5]
        assert bi.search(Query("block.height > 5")) == [6]
        assert bi.search(Query(
            "begin_event.foo = '200' AND block.height = 6")) == [6]
