"""Unit tests for the 24-limb balanced radix (ops/field24.py) — the
second-generation Pallas kernel's field arithmetic.  The golden model
here mirrors the kernel's slab/variant structure exactly, so these
tests pin the schedule, the separable doubling pattern, the int32
accumulator bound, and the carry/fold semantics without paying a
Mosaic interpret run (the kernel itself is covered by the -m kernel
suite in test_ops_ed25519.py).
"""
import random

import numpy as np
import pytest

from cometbft_tpu.ops import field24 as f24


class TestSchedule:
    def test_offsets_and_sizes(self):
        assert f24.OFFSETS[0] == 0
        assert f24.OFFSETS[f24.LIMBS] == 256
        assert set(f24.SIZES) == {10, 11}
        # (11, 11, 10) cycle
        for i, t in enumerate(f24.SIZES):
            assert t == (11, 11, 10)[i % 3]

    def test_p_digit_rows_are_raw_not_reduced(self):
        # regression: to_limbs reduces mod p, which silently turned
        # P_DIGITS into zeros and disarmed canonical's subtract-p
        assert f24.P_DIGITS.sum() > 0
        assert f24.from_limbs(f24.P_DIGITS) == 0          # ≡ 0 mod p
        val = sum(int(v) << f24.OFFSETS[i]
                  for i, v in enumerate(f24.P_DIGITS))
        assert val == f24.P
        val2 = sum(int(v) << f24.OFFSETS[i]
                   for i, v in enumerate(f24.TWO_P_DIGITS))
        assert val2 == 2 * f24.P

    def test_doubling_pattern_matches_offset_identity(self):
        # 2^(s_i + s_j - s_{(i+j) mod 24} [- 256 if wrapped]) must be
        # exactly the residue rule the kernel uses
        for i in range(f24.LIMBS):
            for j in range(f24.LIMBS):
                k = i + j
                e = f24.OFFSETS[i] + f24.OFFSETS[j]
                if k >= f24.LIMBS:
                    e -= 256
                e -= f24.OFFSETS[k % f24.LIMBS]
                want = 2 if (i % 3) + (j % 3) >= 3 else 1
                assert 2**e == want, (i, j)


class TestArithmetic:
    def test_roundtrip(self):
        random.seed(0)
        for _ in range(100):
            x = random.randrange(f24.P)
            assert f24.from_limbs(f24.to_limbs(x)) == x

    def test_carry_preserves_value_and_bounds(self):
        rng = np.random.default_rng(1)
        for _ in range(100):
            a = rng.integers(-2**28, 2**28, size=24)
            v = f24.from_limbs(a)
            c = f24.carry(a)
            assert f24.from_limbs(c) == v
        # post-mul-sized input settles to resting bounds in 2 passes
        a = rng.integers(-9 * 10**8, 9 * 10**8, size=24)
        c = f24.carry(f24.carry(a))
        assert f24.from_limbs(c) == f24.from_limbs(a)

    @pytest.mark.parametrize("redundant", [False, True])
    def test_mul_matches_int_math(self, redundant):
        random.seed(2)
        for _ in range(50):
            x = random.randrange(f24.P)
            y = random.randrange(f24.P)
            a = f24.to_limbs(x).astype(np.int64)
            b = f24.to_limbs(y).astype(np.int64)
            if redundant:
                # lazy two-term sums, like the kernel's ext-add inputs
                z = random.randrange(f24.P)
                a = a + f24.to_limbs(z) - f24.to_limbs(z)
                b = b - f24.to_limbs(0)
            r = f24.mul(a, b)       # asserts the int32 bound inside
            assert f24.from_limbs(r) == x * y % f24.P

    def test_mul_worst_case_magnitude_stays_int32(self):
        # all limbs at the lazy-sum maximum: the in-model assertion
        # (|acc| < 2^31) is the kernel's overflow-safety proof
        worst = np.full(24, 2**11 - 1, np.int64) * 2
        r = f24.mul(worst, -worst)
        assert f24.from_limbs(r) == \
            f24.from_limbs(worst) * f24.from_limbs(-worst) % f24.P

    def test_bytes_to_limbs_exact(self):
        random.seed(3)
        for _ in range(100):
            x = random.randrange(2**256)
            b = np.frombuffer(x.to_bytes(32, "little"), np.uint8)
            digits = f24.bytes_to_limbs(b)
            val = sum(int(v) << f24.OFFSETS[i]
                      for i, v in enumerate(digits))
            assert val == x
