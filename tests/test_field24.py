"""Unit tests for the 24-limb balanced radix (ops/field24.py) — the
second-generation Pallas kernel's field arithmetic.  The golden model
here mirrors the kernel's slab/variant structure exactly, so these
tests pin the schedule, the separable doubling pattern, the int32
accumulator bound, and the carry/fold semantics without paying a
Mosaic interpret run (the kernel itself is covered by the -m kernel
suite in test_ops_ed25519.py).
"""
import random

import numpy as np
import pytest

from cometbft_tpu.ops import field24 as f24


class TestSchedule:
    def test_offsets_and_sizes(self):
        assert f24.OFFSETS[0] == 0
        assert f24.OFFSETS[f24.LIMBS] == 256
        assert set(f24.SIZES) == {10, 11}
        # (11, 11, 10) cycle
        for i, t in enumerate(f24.SIZES):
            assert t == (11, 11, 10)[i % 3]

    def test_p_digit_rows_are_raw_not_reduced(self):
        # regression: to_limbs reduces mod p, which silently turned
        # P_DIGITS into zeros and disarmed canonical's subtract-p
        assert f24.P_DIGITS.sum() > 0
        assert f24.from_limbs(f24.P_DIGITS) == 0          # ≡ 0 mod p
        val = sum(int(v) << f24.OFFSETS[i]
                  for i, v in enumerate(f24.P_DIGITS))
        assert val == f24.P
        val2 = sum(int(v) << f24.OFFSETS[i]
                   for i, v in enumerate(f24.TWO_P_DIGITS))
        assert val2 == 2 * f24.P

    def test_doubling_pattern_matches_offset_identity(self):
        # 2^(s_i + s_j - s_{(i+j) mod 24} [- 256 if wrapped]) must be
        # exactly the residue rule the kernel uses
        for i in range(f24.LIMBS):
            for j in range(f24.LIMBS):
                k = i + j
                e = f24.OFFSETS[i] + f24.OFFSETS[j]
                if k >= f24.LIMBS:
                    e -= 256
                e -= f24.OFFSETS[k % f24.LIMBS]
                want = 2 if (i % 3) + (j % 3) >= 3 else 1
                assert 2**e == want, (i, j)


class TestArithmetic:
    def test_roundtrip(self):
        random.seed(0)
        for _ in range(100):
            x = random.randrange(f24.P)
            assert f24.from_limbs(f24.to_limbs(x)) == x

    def test_carry_preserves_value_and_bounds(self):
        rng = np.random.default_rng(1)
        for _ in range(100):
            a = rng.integers(-2**28, 2**28, size=24)
            v = f24.from_limbs(a)
            c = f24.carry(a)
            assert f24.from_limbs(c) == v
        # post-mul-sized input settles to resting bounds in 2 passes
        a = rng.integers(-9 * 10**8, 9 * 10**8, size=24)
        c = f24.carry(f24.carry(a))
        assert f24.from_limbs(c) == f24.from_limbs(a)

    @pytest.mark.parametrize("redundant", [False, True])
    def test_mul_matches_int_math(self, redundant):
        random.seed(2)
        for _ in range(50):
            x = random.randrange(f24.P)
            y = random.randrange(f24.P)
            a = f24.to_limbs(x).astype(np.int64)
            b = f24.to_limbs(y).astype(np.int64)
            if redundant:
                # lazy two-term sums, like the kernel's ext-add inputs
                z = random.randrange(f24.P)
                a = a + f24.to_limbs(z) - f24.to_limbs(z)
                b = b - f24.to_limbs(0)
            r = f24.mul(a, b)       # asserts the int32 bound inside
            assert f24.from_limbs(r) == x * y % f24.P

    def test_mul_worst_case_magnitude_stays_int32(self):
        # all limbs at the lazy-sum maximum: the in-model assertion
        # (|acc| < 2^31) is the kernel's overflow-safety proof
        worst = np.full(24, 2**11 - 1, np.int64) * 2
        r = f24.mul(worst, -worst)
        assert f24.from_limbs(r) == \
            f24.from_limbs(worst) * f24.from_limbs(-worst) % f24.P

    def test_balance_preserves_value_and_bounds(self):
        random.seed(4)
        for _ in range(50):
            x = random.randrange(f24.P)
            b = f24.balance(f24.to_limbs(x))
            assert b.dtype == np.int32
            assert f24.from_limbs(b) == x
            # balanced: inside the one-pass bound the kernel assumes
            # for pre-balanced constants
            assert np.abs(b).max() <= 1062

    def test_bytes_to_limbs_exact(self):
        random.seed(3)
        for _ in range(100):
            x = random.randrange(2**256)
            b = np.frombuffer(x.to_bytes(32, "little"), np.uint8)
            digits = f24.bytes_to_limbs(b)
            val = sum(int(v) << f24.OFFSETS[i]
                      for i, v in enumerate(digits))
            assert val == x


class TestCarryDiscipline:
    """Re-derive the relaxed carry discipline's overflow proof (the
    kernel's round-4 claim that resting conv operands need no input
    pass).  Everything here is exact integer worst-case propagation —
    if a kernel change moves a bound past int32, this fails."""

    INT32 = 2**31

    def test_resting_fixed_point_exists(self):
        r = f24.resting_bound()
        # applying another conv+2-carry round must not grow the bound
        nxt = f24.carry_bound(f24.carry_bound(f24.conv_bound(r, r)))
        assert all(n <= b for n, b in zip(nxt, r))
        assert max(r) == 2048          # limb 0, fold landing slot

    def test_resting_conv_and_carry_stay_int32(self):
        r = f24.resting_bound()
        cb = f24.conv_bound(r, r)
        assert max(cb) < self.INT32                    # accumulator
        assert f24.prescaled_max(cb) < self.INT32      # carry pre-scale
        # the headroom the kernel docstring quotes
        assert f24.prescaled_max(cb) < 1.75e9

    def test_sum_operands_need_exactly_one_pass(self):
        r = f24.resting_bound()
        for k in (2, 3, 4):
            lazy = [k * v for v in r]
            # unpassed: over int32 — the pass is NOT optional
            assert f24.prescaled_max(f24.conv_bound(lazy, r)) >= self.INT32
            # one balanced pass: safe, even against another carried sum
            once = f24.carry_bound(lazy)
            assert f24.prescaled_max(f24.conv_bound(once, r)) < self.INT32
            assert f24.prescaled_max(f24.conv_bound(once, once)) < self.INT32

    def test_once_carried_products_settle_to_resting(self):
        # closure: every ca=0 annotation downstream of a mul of
        # once-carried sums relies on the product re-entering the
        # resting class after the standard two output passes —
        # elementwise, not just max-wise
        r = f24.resting_bound()
        for j in (2, 3, 4):
            for k in (2, 3, 4):
                oj = f24.carry_bound([j * v for v in r])
                ok = f24.carry_bound([k * v for v in r])
                out = f24.carry_bound(f24.carry_bound(
                    f24.conv_bound(oj, ok)))
                assert all(o <= b for o, b in zip(out, r)), (j, k)

    def test_constant_tables_must_be_balanced(self):
        r = f24.resting_bound()
        raw = [(1 << t) - 1 for t in f24.SIZES]        # canonical digits
        assert f24.prescaled_max(f24.conv_bound(r, raw)) >= self.INT32
        bal = f24.carry_bound(raw)
        assert f24.prescaled_max(f24.conv_bound(r, bal)) < self.INT32

    def test_carry_bound_is_sound_on_samples(self):
        rng = np.random.default_rng(7)
        for _ in range(200):
            bx = rng.integers(1, 2**28, size=24)
            worst = f24.carry_bound(bx)
            for sign in (1, -1):
                got = f24.carry(sign * bx)
                assert (np.abs(got) <= np.array(
                    [int(v) for v in worst])).all()
